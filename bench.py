"""Headline benchmark — prints ONE JSON line, always.

Metric: AmoebaNet-D training throughput (images/sec) on one chip at the
reference's flagship 1024x1024 resolution, batch size 1 — the configuration of
the reference's published charts (BASELINE.md: best bs1 result at 1024² is
≈2.1 img/s for SP square + halo-D2 across FIVE GPUs, i.e. ≈0.42 img/s/GPU).

``vs_baseline`` is our single-chip img/s divided by the 2.1 img/s cluster bar
(the headline comparison, chip-count mismatch stated in the metric name);
``vs_baseline_per_device`` divides by 2.1/5.  Both are null when the run had
to fall back to an incomparable configuration (CPU smoke / reduced size).

Robustness: the measurement runs in a SUBPROCESS so a broken TPU plugin (the
round-1 failure: axon init raised at jax.devices()) cannot kill the benchmark
before it prints.  Ladder: TPU@1024² → TPU@512² → CPU smoke.  The outer
process re-prints the first inner JSON line that parses; if every rung fails
it still prints a JSON line with value 0 and the failure tail.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_CLUSTER = 2.1   # reference: AmoebaNet-D 1024² bs1, SP square + D2, 5 GPUs
BASELINE_DEVICES = 5

# (name, platform, image_size, num_layers, num_filters, warmup, iters, timeout_s, comparable)
LADDER = [
    ("tpu_1024", "tpu", 1024, 18, 416, 2, 8, 1500, True),
    ("tpu_512", "tpu", 512, 18, 416, 2, 8, 900, False),
    ("cpu_smoke", "cpu", 128, 3, 64, 1, 3, 600, False),
]


def _inner(platform: str, image_size: int, num_layers: int, num_filters: int,
           warmup: int, iters: int, comparable: bool) -> None:
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.models.amoebanet import amoebanetd
    from mpi4dl_tpu.train import Optimizer, TrainState, make_train_step

    dev = jax.devices()[0]
    print(f"[bench] platform={dev.platform} device={dev}", file=sys.stderr)
    # The axon TPU plugin may report its platform as 'tpu' or 'axon'; the only
    # disqualifying case is a TPU rung landing on the CPU fallback (it would
    # grind the huge config on the host) and vice versa.
    is_cpu = dev.platform == "cpu"
    if (platform == "tpu") == is_cpu:
        print(f"[bench] wanted {platform!r}, got {dev.platform!r} — bail",
              file=sys.stderr)
        sys.exit(3)
    batch = 1

    model = amoebanetd(
        (batch, image_size, image_size, 3),
        num_classes=1000,
        num_layers=num_layers,
        num_filters=num_filters,
    )
    params, _ = model.init(jax.random.key(0))
    opt = Optimizer("sgd", lr=0.001)
    # bf16 compute + per-cell remat: the memory configuration that fits
    # 1024² bs1 on one chip (the reference needs 5 GPUs for this workload).
    step = make_train_step(model, opt, compute_dtype=jnp.bfloat16, remat=True)
    state = TrainState.create(params, opt)

    x = jax.random.normal(jax.random.key(1), (batch, image_size, image_size, 3))
    y = jnp.zeros((batch,), jnp.int32)

    t_c = time.perf_counter()
    for _ in range(warmup):
        state, metrics = step(state, x, y)
    jax.block_until_ready(metrics["loss"])
    print(f"[bench] compile+warmup {time.perf_counter() - t_c:.1f}s", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, x, y)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    img_per_sec = batch * iters / dt
    out = {
        "metric": f"amoebanetd_{image_size}px_bs{batch}_train_img_per_sec"
                  "_single_chip_vs_5gpu_cluster_baseline",
        "value": round(img_per_sec, 4),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_CLUSTER, 4) if comparable else None,
        "vs_baseline_per_device": (
            round(img_per_sec / (BASELINE_CLUSTER / BASELINE_DEVICES), 4)
            if comparable else None
        ),
        "baseline_img_per_sec_cluster": BASELINE_CLUSTER,
        "baseline_devices": BASELINE_DEVICES,
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))


def _try_rung(name, platform, image_size, num_layers, num_filters,
              warmup, iters, timeout_s, comparable):
    env = dict(os.environ)
    if platform == "cpu":
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
    argv = [sys.executable, os.path.abspath(__file__), "--inner",
            platform, str(image_size), str(num_layers), str(num_filters),
            str(warmup), str(iters), "1" if comparable else "0"]
    try:
        proc = subprocess.run(
            argv, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        return None, f"{name}: timeout after {timeout_s}s; stderr tail: " \
                     f"{(e.stderr or '')[-300:] if isinstance(e.stderr, str) else ''}"
    sys.stderr.write(proc.stderr[-2000:] if proc.stderr else "")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"{name}: rc={proc.returncode}; stderr tail: {(proc.stderr or '')[-300:]}"


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--inner":
        platform, image_size, num_layers, num_filters, warmup, iters, comp = sys.argv[2:9]
        _inner(platform, int(image_size), int(num_layers), int(num_filters),
               int(warmup), int(iters), comp == "1")
        return 0

    failures = []
    for rung in LADDER:
        print(f"[bench] trying rung {rung[0]}", file=sys.stderr)
        result, err = _try_rung(*rung)
        if result is not None:
            print(json.dumps(result))
            return 0
        failures.append(err)
        print(f"[bench] rung failed: {err}", file=sys.stderr)

    print(json.dumps({
        "metric": "amoebanetd_train_img_per_sec_single_chip",
        "value": 0,
        "unit": "images/sec",
        "vs_baseline": None,
        "error": "; ".join(f for f in failures if f)[-500:],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
