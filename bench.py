"""Headline benchmark — prints ONE JSON line, always.

Metric: AmoebaNet-D training throughput (images/sec) on one chip at the
reference's flagship 1024x1024 resolution, batch size 1 — the configuration of
the reference's published charts (BASELINE.md: best bs1 result at 1024² is
≈2.1 img/s for SP square + halo-D2 across FIVE GPUs, i.e. ≈0.42 img/s/GPU).

Honesty instrumentation (round 3): the step's FLOPs are taken from XLA's own
``compiled.cost_analysis()`` and the JSON carries ``flops_per_step``,
``achieved_tflops`` and ``mfu`` against the chip's bf16 peak.  A measurement
with mfu > 1 is *physically impossible* and is treated as a failed
measurement: the run falls back to per-step ``jax.block_until_ready`` on the
FULL state (which cannot overcount — every step's outputs are materialized
between timestamps) with more iterations and fresh inputs each step.  If even
the blocked measurement lands above peak, ``vs_baseline`` is null and an
``error`` explains.

Memory-capability rungs (round 3): in addition to the 1024² headline (the
no-remat rung, with a per-cell-remat fallback on OOM), the JSON's ``rungs``
carry a 2048² bs1 measurement (the reference's OOM frontier — ResNet 2048²
bs2 OOMs on its GPUs, BASELINE.md) and a 1024² bs2 measurement (the
reference's best bs2 chart point), plus ``max_trainable_px`` — the largest
square resolution that completes a bs1 training step on one chip with
fine remat+bf16, found by doubling + one midpoint refinement (each attempt
in a subprocess so OOM cannot kill the benchmark).

Robustness: every measurement runs in a SUBPROCESS so a broken TPU plugin
(the round-1 failure: axon init raised at jax.devices()) cannot kill the
benchmark before it prints.  Ladder: TPU@1024² → TPU@512² → CPU smoke.  The
outer process re-prints the first inner JSON line that parses; if every rung
fails it still prints a JSON line with value 0 and the failure tail.
"""

from __future__ import annotations

import json
import os
import re as _re
import subprocess
import sys
import time

BASELINE_CLUSTER = 2.1   # reference: AmoebaNet-D 1024² bs1, SP square + D2, 5 GPUs
BASELINE_DEVICES = 5
BASELINE_2048 = 2.85     # reference: AmoebaNet-D 2048² bs1, SP vertical + D2, 5 GPUs
BASELINE_2048_BS2 = 5.0  # reference: AmoebaNet-D 2048² bs2 — its best chart point
BASELINE_1024_BS2 = 2.95  # reference: AmoebaNet-D 1024² bs2, SP square + D2, 5 GPUs
BASELINE_RESNET_1024 = 2.55  # reference: ResNet-110-v2 1024² bs1, SP best, 5 GPUs
BASELINE_RESNET_2048 = 0.99  # reference: ResNet-110-v2 2048² bs1, SP, 5 GPUs

# (name, platform, image_size, num_layers, num_filters, warmup, iters,
#  timeout_s, comparable, remat, batch, scan)
# The 1024² headline fits WITHOUT remat on a 16 GB chip and runs ~21%
# faster (no recompute forward); the remat rung is the OOM fallback and
# the configuration of the memory rungs.  scan=6 packs 6 optimizer steps
# per dispatch (axon RPC dispatch costs ~28 ms/step unamortized —
# PERF_NOTES r4); warmup counts CALLS.
LADDER = [
    ("tpu_1024_noremat", "tpu", 1024, 18, 416, 1, 18, 1800, True, "none", 1, 6),
    ("tpu_1024", "tpu", 1024, 18, 416, 1, 18, 1800, True, "cell", 1, 6),
    ("tpu_512", "tpu", 512, 18, 416, 1, 12, 900, False, "cell", 1, 6),
    ("cpu_smoke", "cpu", 128, 3, 64, 1, 3, 600, False, "cell", 1, 1),
]

_REMAT = {"none": False, "cell": True, "fine": "fine", "sqrt": "sqrt"}

# 1800 not 1200: the 3072px fine-remat first compile outran 1200 s in r5
# (probe budget is still clamped to the remaining bench deadline).
PROBE_TIMEOUT_S = 1800
# Global wall-clock budget: the memory rungs/probe stop (and the headline
# JSON still prints) once exceeded — a slow tunnel must not starve the
# driver of the one JSON line it records.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "3600"))
_T0 = time.monotonic()

# Incremental hardware-evidence file (VERDICT r4 task 1): every successful
# rung is merged into this JSON the moment it lands, so a tunnel outage at
# the END of a round can never zero the round's hardware record again.
# bench.py also folds its contents into the final headline JSON.  The
# default carries the CURRENT round's number (bump alongside VERDICT.md;
# mid-round sessions can override via BENCH_MEASURED_PATH).  The .lock and
# .tmp sidecars it creates are gitignored.
MEASURED_PATH = os.environ.get(
    "BENCH_MEASURED_PATH",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "MEASURED_r5.json"),
)


def _time_left() -> float:
    return DEADLINE_S - (time.monotonic() - _T0)


def _record_measured(name: str, entry: dict) -> None:
    """Atomically merge one successful rung into MEASURED_PATH.

    Never raises: evidence recording must not break the benchmark.  Each
    entry keeps its full rung_config so round-over-round numbers are
    comparable without PERF_NOTES archaeology (VERDICT r4 weak-9).
    """
    try:
        import fcntl

        # flock around the read-modify-write: mid-round sessions and the
        # bench ladder share this file, and last-writer-wins would silently
        # drop rungs.
        with open(MEASURED_PATH + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            data = {}
            if os.path.exists(MEASURED_PATH):
                with open(MEASURED_PATH) as f:
                    data = json.load(f)
            rungs = data.setdefault("rungs", {})
            entry = dict(entry)
            entry["captured_unix"] = int(time.time())
            rungs[name] = entry
            data["updated_unix"] = int(time.time())
            tmp = MEASURED_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, MEASURED_PATH)
    except Exception as e:  # noqa: BLE001
        print(f"[bench] measured-record failed: {e}", file=sys.stderr)


def _load_measured() -> dict | None:
    try:
        with open(MEASURED_PATH) as f:
            return json.load(f)
    except Exception:  # noqa: BLE001
        return None


def _peak_flops(device) -> float | None:
    """bf16 peak FLOP/s for the mfu sanity check — the table and matching
    policy (cpu -> None, substring table, assume-FASTEST for unknown kinds
    so mfu>1 stays a sound impossibility test) live in the obs subsystem.
    Imported lazily: the orchestrator process must stay stdlib-only so a
    broken install still prints its one JSON line."""
    from mpi4dl_tpu.obs.costs import peak_flops

    peak, _source = peak_flops(device)
    return peak


def _build_step(image_size: int, num_layers: int, num_filters: int,
                batch: int = 1, remat=True, scan: int = 1,
                arch: str = "amoeba"):
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.train import Optimizer, TrainState, make_train_step

    if arch == "resnet":
        # Memory-tuned remat grouping for the deep-thin model (PERF_NOTES
        # r4: 16 groups beat sqrt(38)≈6 by ~2.2 GB at 2048²).
        os.environ.setdefault("MPI4DL_SQRT_GROUPS", "16")
        from mpi4dl_tpu.models.resnet import get_resnet_v2

        # num_layers carries the depth for the ResNet rungs (110 = the
        # reference's charted model, BASELINE.md).
        model = get_resnet_v2(
            (batch, image_size, image_size, 3),
            depth=num_layers, num_classes=1000,
        )
    else:
        from mpi4dl_tpu.models.amoebanet import amoebanetd

        model = amoebanetd(
            (batch, image_size, image_size, 3),
            num_classes=1000,
            num_layers=num_layers,
            num_filters=num_filters,
        )
    params, _ = model.init(jax.random.key(0))
    opt = Optimizer("sgd", lr=0.001)
    # bf16 compute + remat: per-cell (remat=True) for the throughput rungs;
    # per-op ("fine") for the max-resolution probes — backward temps bound
    # to one op at a time.  scan>1 packs k optimizer steps per dispatch
    # (the dispatch-overhead amortization, PERF_NOTES r4).
    step = make_train_step(
        model, opt, compute_dtype=jnp.bfloat16, remat=remat, donate=True,
        scan_steps=scan,
        # A/B escape hatch (same pattern as MPI4DL_SQRT_GROUPS): route
        # [ReLU, Conv2d, BatchNorm] windows through the fused Pallas
        # relu→conv→BN-stats kernel (single-device dispatch, ops/d2.py
        # maybe_run_fused_unsharded).
        pallas_conv=os.environ.get("MPI4DL_PALLAS_CONV") == "1",
    )
    state = TrainState.create(params, opt)
    return step, state


def build_probe_setup(image_size, num_layers, num_filters, batch,
                      remat="none", scan=1, arch="amoeba"):
    """(step, state, x, y) for a rung config — shared by the diagnostic
    probes (benchmarks/layout_probe.py, benchmarks/mem_probe.py) so their
    input conventions (bf16 inputs, scan-stacked leading dim) cannot drift
    from the bench's own rungs."""
    import jax
    import jax.numpy as jnp

    step, state = _build_step(
        image_size, num_layers, num_filters, batch, remat=_REMAT[remat],
        scan=scan, arch=arch,
    )
    shp = (batch, image_size, image_size, 3)
    if scan > 1:
        shp = (scan,) + shp
    x = jax.random.normal(jax.random.key(0), shp, jnp.bfloat16)
    y = jnp.zeros((scan, batch) if scan > 1 else (batch,), jnp.int32)
    return step, state, x, y


def _step_flops(step, state, x, y) -> float | None:
    """FLOPs of one compiled training step from XLA's own cost model."""
    try:
        ca = step.lower(state, x, y).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception as e:  # noqa: BLE001 — any backend may lack cost_analysis
        print(f"[bench] cost_analysis unavailable: {e}", file=sys.stderr)
        return None


def _measure(step, state, xs, ys, iters: int, blocked: bool):
    """Time `iters` steps cycling through fresh inputs.

    blocked=False: steps chain through state; one block_until_ready on the
    full final (state, metrics) plus a device-to-host fetch of the final loss
    — standard async JAX timing.
    blocked=True: fetch the loss scalar to the HOST every step.  A D2H copy
    cannot complete before the value exists, so this is immune to any
    dispatch/readiness artifact of the experimental axon RPC backend (whose
    block_until_ready has been observed returning early — the round-2
    275 img/s fiction); it is a strict upper bound on step time.
    """
    import jax

    n = len(xs)
    t0 = time.perf_counter()
    metrics = None
    for i in range(iters):
        state, metrics = step(state, xs[i % n], ys[i % n])
        if blocked:
            float(metrics["loss"])
    float(metrics["loss"])
    jax.block_until_ready(state)
    return time.perf_counter() - t0, state


def _inner(platform: str, image_size: int, num_layers: int, num_filters: int,
           warmup: int, iters: int, comparable: bool,
           remat="cell", batch: int = 1, scan: int = 1,
           arch: str = "amoeba") -> None:
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"[bench] platform={dev.platform} device={dev} "
          f"kind={getattr(dev, 'device_kind', '?')}", file=sys.stderr)
    # The axon TPU plugin may report its platform as 'tpu' or 'axon'; the only
    # disqualifying case is a TPU rung landing on the CPU fallback (it would
    # grind the huge config on the host) and vice versa.
    is_cpu = dev.platform == "cpu"
    if (platform == "tpu") == is_cpu:
        print(f"[bench] wanted {platform!r}, got {dev.platform!r} — bail",
              file=sys.stderr)
        sys.exit(3)

    step, state = _build_step(
        image_size, num_layers, num_filters, batch, remat=_REMAT[remat],
        scan=scan, arch=arch,
    )
    # One timed "call" = `scan` optimizer steps compiled into one program
    # (scan=1: the plain per-step dispatch).  iters counts optimizer steps.
    calls = max(1, iters // scan)
    iters = calls * scan

    # Fresh inputs: a small pool of distinct images cycled through the loop so
    # no iteration can be satisfied by a cached/constant-folded result.
    n_inputs = min(4, max(2, calls))
    shp = (batch, image_size, image_size, 3)
    if scan > 1:
        shp = (scan,) + shp
    # bf16 input pool: the step casts to compute_dtype anyway, and fp32
    # scan-stacked pools cost real HBM at the memory-frontier rungs
    # (~300 MB at 2048² scan=3 — on rungs that miss fitting by ~250 MB).
    xs = [
        jax.random.normal(jax.random.key(100 + i), shp, jnp.bfloat16)
        for i in range(n_inputs)
    ]
    ys = [
        jnp.full(shp[:-3], i % 1000, jnp.int32).reshape(
            (scan, batch) if scan > 1 else (batch,)
        )
        for i in range(n_inputs)
    ]

    # XLA's HLO cost analysis counts a while/scan body ONCE (trip counts are
    # not folded in) — verified empirically: the scanned program reports the
    # same flops as the unscanned step (5.061e12 at 1024², r4).  So the
    # reported number IS per-step; a call executes `scan` times that.
    flops = _step_flops(step, state, xs[0], ys[0])
    peak = _peak_flops(dev)

    t_c = time.perf_counter()
    for i in range(warmup):
        state, metrics = step(state, xs[i % n_inputs], ys[i % n_inputs])
    float(metrics["loss"])  # D2H: warmup really finished (see _measure)
    jax.block_until_ready(state)
    print(f"[bench] compile+warmup {time.perf_counter() - t_c:.1f}s; "
          f"flops/step={flops}", file=sys.stderr)

    def mfu_of(dt: float, n_calls: int):
        if flops is None or peak is None:
            return None
        return (flops * scan * n_calls / dt) / peak

    mode = "async_chain" if scan == 1 else f"scan{scan}_chain"
    dt, state = _measure(step, state, xs, ys, calls, blocked=False)
    mfu = mfu_of(dt, calls)
    error = None
    if mfu is not None and mfu > 1.0:
        # Physically impossible — the async timing did not capture the real
        # work.  Re-measure with per-call blocking on the full state and more
        # iterations; this cannot overcount.
        print(f"[bench] mfu={mfu:.2f} > 1 under async timing — "
              f"falling back to per-step blocking", file=sys.stderr)
        mode = "per_step_blocked"
        calls = calls * 2
        iters = calls * scan
        dt, state = _measure(step, state, xs, ys, calls, blocked=True)
        mfu = mfu_of(dt, calls)
        if mfu is not None and mfu > 1.0:
            error = (f"measurement failed: mfu={mfu:.2f} > 1 even with "
                     f"per-step block_until_ready on the full state")

    img_per_sec = batch * iters / dt
    achieved = (flops * scan * calls / dt) if flops else None
    ok = error is None
    model_tag = "resnet110v2" if arch == "resnet" else "amoebanetd"
    out = {
        "metric": f"{model_tag}_{image_size}px_bs{batch}_train_img_per_sec"
                  "_single_chip_vs_5gpu_cluster_baseline",
        "value": round(img_per_sec, 4),
        "unit": "images/sec",
        "vs_baseline": (
            round(img_per_sec / BASELINE_CLUSTER, 4) if (comparable and ok) else None
        ),
        "vs_baseline_per_device": (
            round(img_per_sec / (BASELINE_CLUSTER / BASELINE_DEVICES), 4)
            if (comparable and ok) else None
        ),
        "baseline_img_per_sec_cluster": BASELINE_CLUSTER,
        "baseline_devices": BASELINE_DEVICES,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", None),
        "timing_mode": mode,
        "iters": iters,
        "scan_steps_per_dispatch": scan,
        "flops_per_step": flops,
        "achieved_tflops": round(achieved / 1e12, 2) if achieved else None,
        "peak_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    if error:
        out["error"] = error
    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if tdir:
        # --telemetry-dir: mirror the rung result into a RunLog so bench
        # evidence and training-loop telemetry share one format/reader.
        try:
            from mpi4dl_tpu.obs import RunLog

            with RunLog.create(tdir, prefix=f"bench-{model_tag}") as rl:
                rl.write_meta(
                    config={
                        "image_size": image_size, "num_layers": num_layers,
                        "num_filters": num_filters, "batch": batch,
                        "remat": remat, "scan": scan, "arch": arch,
                        "platform": platform,
                    },
                    family="bench",
                )
                rl.write("summary", **out)
            from mpi4dl_tpu.obs.metrics import write_metrics_file
            from mpi4dl_tpu.obs.runlog import read_runlog

            prom = os.path.splitext(rl.path)[0] + ".prom"
            write_metrics_file(read_runlog(rl.path), prom)
            print(f"[bench] telemetry -> {rl.path} (+ {prom})",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — telemetry must not kill bench
            print(f"[bench] telemetry failed: {e}", file=sys.stderr)
    print(json.dumps(out))


def _inner_probe(image_size: int) -> None:
    """Train ONE bs1 step at image_size; print a tiny JSON on success.

    OOM aborts the process — the outer driver interprets death as 'does not
    fit'.  Exits 3 if not actually on an accelerator.
    """
    import jax

    dev = jax.devices()[0]
    if dev.platform == "cpu" and os.environ.get("BENCH_PROBE_CPU_OK") != "1":
        sys.exit(3)
    step, state = _build_step(image_size, 18, 416, 1, remat="fine")
    import jax.numpy as jnp

    x = jax.random.normal(jax.random.key(1), (1, image_size, image_size, 3))
    y = jnp.zeros((1,), jnp.int32)
    t0 = time.perf_counter()
    state, metrics = step(state, x, y)
    jax.block_until_ready((state, metrics))
    dt = time.perf_counter() - t0
    loss = float(metrics["loss"])
    print(json.dumps({"ok": bool(loss == loss), "image_size": image_size,
                      "first_step_s": round(dt, 1)}))


def _stderr_gist(stderr: str) -> str:
    """The most informative failure line (OOM/compile errors name themselves
    mid-log; a raw tail often lands on a useless traceback fragment)."""
    import re

    for line in reversed((stderr or "").splitlines()):
        if re.search(
            r"Ran out of memory|RESOURCE_EXHAUSTED|Out of memory|"
            r"XLA:TPU compile|UNAVAILABLE|\w*Error\b|error:", line,
        ):
            return line.strip()[-300:]
    return (stderr or "")[-300:]


def _run_sub(argv_tail, timeout_s, platform="tpu"):
    env = dict(os.environ)
    # Persistent compilation cache shared by every rung/probe subprocess:
    # a re-probe of a config this round already compiled (e.g. the 3072px
    # fine-remat attempt, whose first compile outran the r5 probe budget)
    # hits the cache instead of re-paying a multi-minute compile.
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/mpi4dl_tpu_bench_cache")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "10")
    if platform == "cpu":
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
    argv = [sys.executable, os.path.abspath(__file__)] + argv_tail
    try:
        proc = subprocess.run(
            argv, env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired as e:
        # A hang has no failure line — the raw tail (last progress output)
        # says WHERE it hung; the gist scan could misattribute it to some
        # earlier benign warning line.
        tail = (e.stderr or "")[-300:] if isinstance(e.stderr, str) else ""
        return None, f"timeout after {timeout_s}s; stderr tail: {tail}"
    sys.stderr.write(proc.stderr[-2000:] if proc.stderr else "")
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, f"rc={proc.returncode}; stderr: {_stderr_gist(proc.stderr)}"


def _try_rung(name, platform, image_size, num_layers, num_filters,
              warmup, iters, timeout_s, comparable, remat="cell",
              batch=1, scan=1, arch="amoeba"):
    tail = ["--inner", platform, str(image_size), str(num_layers),
            str(num_filters), str(warmup), str(iters),
            "1" if comparable else "0", remat, str(batch), str(scan), arch]
    result, err = _run_sub(tail, timeout_s, platform)
    if err:
        err = f"{name}: {err}"
    if result is not None:
        result["remat"] = remat
        # Frozen rung configuration (VERDICT r4 weak-9): everything needed to
        # reproduce this number travels with it.
        result["rung_config"] = {
            "arch": arch, "image_size": image_size, "num_layers": num_layers,
            "num_filters": num_filters, "batch": batch, "scan_steps": scan,
            "remat": remat, "iters": iters, "input_dtype": "bfloat16",
            "compute_dtype": "bfloat16", "optimizer": "sgd", "donate": True,
        }
        # Trace-time env hatches that change the compiled program travel
        # with the number too (comparability).  Boolean hatches are active
        # only at the exact value "1" (matching their readers) — recording
        # any other value would label a number with an inactive hatch.
        for hatch in ("MPI4DL_REMAT_OPS", "MPI4DL_LANE_PAD",
                      "MPI4DL_PALLAS_CONV"):
            if os.environ.get(hatch) == "1":
                result["rung_config"][hatch] = "1"
        if os.environ.get("MPI4DL_SQRT_GROUPS"):
            result["rung_config"]["MPI4DL_SQRT_GROUPS"] = (
                os.environ["MPI4DL_SQRT_GROUPS"]
            )
        if result.get("platform") not in (None, "cpu"):
            _record_measured(name, {
                "img_per_sec": result.get("value"),
                "mfu": result.get("mfu"),
                "achieved_tflops": result.get("achieved_tflops"),
                "timing_mode": result.get("timing_mode"),
                "platform": result.get("platform"),
                "device_kind": result.get("device_kind"),
                "rung_config": result["rung_config"],
                "error": result.get("error"),
            })
    return result, err


def _rung_summary(result, err, baseline, baseline_key):
    """Uniform per-rung summary dict for the `rungs` section."""
    if result is None:
        return {"error": (err or "")[-200:]}
    out = {
        "img_per_sec": result["value"],
        "mfu": result.get("mfu"),
        "timing_mode": result.get("timing_mode"),
        "remat": result.get("remat"),
        "rung_config": result.get("rung_config"),
        baseline_key: (
            round(result["value"] / baseline, 4)
            if (baseline and not result.get("error")) else None
        ),
    }
    return out


def _max_trainable_px(start: int = 2048, cap: int = 8192,
                      known_fit: int = 0, gate=None,
                      note_ok=None) -> tuple[int, dict]:
    """Largest square resolution whose bs1 step completes on the chip.

    Doubling ladder from `start`, then one midpoint refinement between the
    last success and first failure.  Every attempt is a subprocess; any
    death (OOM, crash, timeout) counts as 'does not fit'.  ``known_fit``
    seeds the ladder with a resolution another rung already proved (avoids
    re-paying its multi-minute compile+step).  ``gate`` (if given) is a
    health predicate checked before each probe: a dead tunnel costs one
    short probe, not a PROBE_TIMEOUT_S hang per resolution.
    """
    attempts = {}

    def fits(px: int) -> bool:
        if gate is not None and not gate():
            attempts[str(px)] = {"ok": False,
                                 "error": "skipped (tpu probe negative)"}
            return False
        # Budget computed AFTER the gate: its preflight may have spent
        # minutes, and a stale budget would let a hung probe overrun
        # DEADLINE_S.
        budget = min(PROBE_TIMEOUT_S, max(0, _time_left() - 60))
        if budget < 120:
            attempts[str(px)] = {"ok": False, "error": "bench deadline reached"}
            return False
        result, err = _run_sub(["--probe", str(px)], budget)
        ok = bool(result and result.get("ok"))
        if note_ok is not None and (ok or _re.search(_OOM_RE, err or "")):
            # A parsed result OR an OOM death both prove live TPU contact —
            # refresh the health cache so the next gate() call doesn't burn
            # a redundant preflight subprocess (probes outlast FRESH_S).
            note_ok()
        attempts[str(px)] = (
            {"ok": True, "first_step_s": result.get("first_step_s")} if ok
            else {"ok": False, "error": (err or "no output")[-300:]}
        )
        if ok:
            # Bank the proven resolution: a later bench run (mid-round or
            # the driver's final one) seeds its ladder from it instead of
            # re-paying the multi-minute fine-remat compile.
            _record_measured(f"probe_{px}", {
                "ok": True, "first_step_s": result.get("first_step_s"),
                "platform": "tpu",
                "rung_config": {"image_size": px, "remat": "fine",
                                "batch": 1, "scan_steps": 1},
            })
        print(f"[bench] probe {px}px: {'fits' if ok else 'FAILS'}", file=sys.stderr)
        return ok

    best, px, fail_at = known_fit, max(start, known_fit * 2), None
    while px <= cap:
        if not fits(px):
            fail_at = px
            break
        best, px = px, px * 2
    if fail_at is None and best < cap and px > cap:
        # Non-power-of-2 seeds (banked mid-round probes like 3072) make
        # the doubling ladder overshoot the cap without ever probing it —
        # probe the cap itself so 8192 stays discoverable.
        if fits(cap):
            best = cap
        else:
            fail_at = cap
    if best and (fail_at or best < cap):
        # Bounded bisection of [best, first-failure) on /64-aligned values —
        # a single midpoint stops at 3072 and never reaches the 3328-class
        # frontier the r4 manual probes charted (VERDICT r4 task 6).
        lo, hi = best, (fail_at or cap)
        while hi - lo >= 512:
            mid = ((lo + hi) // 2) - (((lo + hi) // 2) % 64)
            if mid <= lo or mid >= hi:
                break
            if fits(mid):
                lo = mid
            else:
                hi = mid
        best = lo
    return best, attempts


def _tpu_preflight(timeout_s: int = 240) -> bool:
    """Can a subprocess reach the TPU at all?  When the axon tunnel is down
    the backend init HANGS (measured >25 min) rather than failing — without
    this check each TPU rung burns its full timeout and the ladder can
    exhaust the deadline before ever reaching the CPU smoke rung."""
    argv = [sys.executable, "-c",
            "import jax; print(jax.devices()[0].platform)"]
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False
    # Last stdout line only: init banners/warnings must not mask a healthy
    # tunnel (a false negative caps every TPU rung below its compile time).
    lines = (proc.stdout or "").strip().splitlines()
    return proc.returncode == 0 and bool(lines) and lines[-1] in ("tpu", "axon")


_OOM_RE = r"Ran out of memory|RESOURCE_EXHAUSTED|Out of memory"


def _remat_ladder(name, px, tries, iters, batch, timeout_cap, health):
    """OOM remat/scan ladder shared by the batch-scaling rungs: walk
    ``tries`` = [(remat, scan), ...] until one fits; only OOM justifies
    the next attempt (any other failure invalidates TPU health and stops).
    Returns (result_or_None, joined_errors)."""
    r, errs = None, []
    for rm, t_scan in tries:
        if _time_left() < 300:
            errs.append(f"{rm}/scan{t_scan}: skipped (bench deadline reached)")
            break
        r, e = _try_rung(
            name, "tpu", px, 18, 416, 1, iters,
            min(timeout_cap, max(300, _time_left() - 300)), False, rm,
            batch, t_scan,
        )
        if r is not None:
            health.note_success()
            break
        errs.append(f"{rm}/scan{t_scan}: {e}")
        _note_health(health, r, e)
        if not _re.search(_OOM_RE, e or ""):
            break
    return r, "; ".join(errs)


def _note_health(health, result, err) -> None:
    """Update the health cache from a rung outcome.  An OOM death proves
    live TPU contact just as a parsed result does — memory-frontier rungs
    (tpu_2048, resnet_2048) OOM by DESIGN, and invalidating on them would
    burn a redundant preflight before every subsequent gate."""
    if result is not None or _re.search(_OOM_RE, err or ""):
        health.note_success()
    else:
        health.note_rung_failure()


class _TpuHealth:
    """Per-rung TPU reachability tracking (VERDICT r4 weak-1 fix).

    The r4 design probed ONCE up front and a negative stuck for the whole
    run — a tunnel that recovered mid-bench still yielded a CPU-only round.
    This tracker re-probes before each TPU rung group: a recent success
    (a passed probe OR a rung that actually produced a TPU number) is
    trusted for ``FRESH_S``; after a failure the next TPU rung triggers a
    fresh probe instead of inheriting the stale verdict.
    """

    FRESH_S = 300.0

    def __init__(self):
        self._last_ok = None  # monotonic timestamp of last proven contact
        self.consec_fail = 0

    def note_success(self) -> None:
        self._last_ok = time.monotonic()
        self.consec_fail = 0

    def note_rung_failure(self) -> None:
        # A timed-out/failed TPU rung invalidates the cached health — the
        # next rung must re-probe rather than burn its full budget.
        self._last_ok = None

    def check(self) -> bool:
        if self._last_ok is not None and (
            time.monotonic() - self._last_ok < self.FRESH_S
        ):
            return True
        if _time_left() <= 90:
            return False
        budget = min(240, max(60, int(_time_left() / 4)))
        ok = _tpu_preflight(budget)
        if not ok and self.consec_fail == 0 and _time_left() > 240:
            # One immediate retry on the FIRST failure only: a transient
            # blip must not forfeit a TPU rung, but a dead tunnel must not
            # eat two probes before every rung.
            ok = _tpu_preflight(budget)
        if ok:
            self.note_success()
        else:
            self.consec_fail += 1
        return ok


def main() -> int:
    # --telemetry-dir DIR: rung subprocesses mirror their JSON result into
    # RunLog files there (env-carried so the positional --inner protocol is
    # untouched; _run_sub's env inherits it).
    if "--telemetry-dir" in sys.argv:
        i = sys.argv.index("--telemetry-dir")
        try:
            os.environ["BENCH_TELEMETRY_DIR"] = sys.argv[i + 1]
        except IndexError:
            print("[bench] --telemetry-dir needs a directory", file=sys.stderr)
            return 2
        del sys.argv[i:i + 2]
    if len(sys.argv) > 1 and sys.argv[1] == "--inner":
        platform, image_size, num_layers, num_filters, warmup, iters, comp = sys.argv[2:9]
        remat = sys.argv[9] if len(sys.argv) > 9 else "cell"
        batch = int(sys.argv[10]) if len(sys.argv) > 10 else 1
        scan = int(sys.argv[11]) if len(sys.argv) > 11 else 1
        arch = sys.argv[12] if len(sys.argv) > 12 else "amoeba"
        _inner(platform, int(image_size), int(num_layers), int(num_filters),
               int(warmup), int(iters), comp == "1", remat, batch, scan, arch)
        return 0
    if len(sys.argv) > 1 and sys.argv[1] == "--probe":
        _inner_probe(int(sys.argv[2]))
        return 0

    failures = []
    headline = None
    health = _TpuHealth()

    def try_ladder():
        nonlocal headline
        for rung in LADDER:
            # Clamp every rung to the remaining global budget (two 1800 s
            # rungs would otherwise overrun DEADLINE_S when the tunnel
            # hangs).  TPU rungs are gated on a fresh health probe — a rung
            # that runs gets a FULL compile-sized budget (≥300 s when the
            # deadline allows; the r4 design's 120 s cheap-shot cap sat
            # below the 155 s compile and could never succeed).
            left = _time_left()
            if left < 120:
                failures.append(f"{rung[0]}: skipped (bench deadline reached)")
                continue
            if rung[1] == "tpu":
                # A TPU rung needs a full compile-sized budget (≥300 s) AND
                # must stay inside the deadline — if the remaining time
                # can't grant both, skip to the CPU smoke rung rather than
                # either fire a doomed short rung (the r4 cheap-shot
                # failure) or overrun DEADLINE_S.
                if left < 390:
                    failures.append(
                        f"{rung[0]}: skipped (deadline leaves <300s budget)")
                    continue
                if not health.check():
                    failures.append(f"{rung[0]}: skipped (tpu probe negative)")
                    print(f"[bench] skipping {rung[0]} — probe negative",
                          file=sys.stderr)
                    continue
                # Re-check after the probe spent its share of the budget.
                if _time_left() - 60 < 300:
                    failures.append(
                        f"{rung[0]}: skipped (deadline leaves <300s budget)")
                    continue
                cap = min(rung[7], int(_time_left() - 60))
            else:
                cap = min(rung[7], max(60, int(left - 60)))
            rung = (*rung[:7], cap, *rung[8:])
            print(f"[bench] trying rung {rung[0]}", file=sys.stderr)
            result, err = _try_rung(*rung)
            if result is not None:
                headline = result
                headline["rung"] = rung[0]
                if result.get("platform") != "cpu":
                    health.note_success()
                return
            if rung[1] == "tpu":
                health.note_rung_failure()
            failures.append(err)
            print(f"[bench] rung failed: {err}", file=sys.stderr)

    try_ladder()
    if (headline is not None and headline.get("platform") == "cpu"
            and _time_left() > 900 and health.check()):
        # The tunnel recovered after the TPU rungs failed (the r4 fatal
        # pattern, inverted): spend the remaining budget on a real retry.
        print("[bench] tunnel recovered — retrying TPU headline",
              file=sys.stderr)
        cpu_headline = headline
        headline = None
        try_ladder()
        if headline is None or headline.get("platform") == "cpu":
            headline = cpu_headline

    if headline is None:
        # Even a fully-failed ladder must fall through to the evidence
        # fold below: the banked mid-round TPU headline (if any) is
        # promoted there instead of the round's record reading zero.
        headline = {
            "metric": "amoebanetd_train_img_per_sec_single_chip",
            "value": 0,
            "unit": "images/sec",
            "vs_baseline": None,
            "platform": "none",
            "error": "; ".join(f for f in failures if f)[-500:],
        }

    on_tpu = headline.get("platform") not in ("cpu", "none")
    skip_extra = (
        os.environ.get("BENCH_SKIP_MEMORY_RUNGS") == "1" or _time_left() < 300
    )

    def tpu_gate(rname: str) -> bool:
        """Health-gated admission for each extra TPU rung: a mid-bench
        tunnel death costs one short probe per rung, not a full timeout."""
        if health.check():
            return True
        headline.setdefault("rungs", {})[rname] = {
            "error": "skipped (tpu probe negative)"}
        return False

    if on_tpu and not skip_extra:
        # Memory-capability rung: the reference's OOM frontier (2048², bs1 —
        # its GPUs OOM at bs2 across all schemes, BASELINE.md).
        headline["rungs"] = {}
        r2048, err = None, "skipped"
        if tpu_gate("2048"):
            print("[bench] 2048px memory rung", file=sys.stderr)
            # scan=1 on memory-frontier rungs: the scan-of-steps wrapper
            # costs ~3.7 GB peak at 2048² (measured r4 — likely carry
            # double-buffering), which a frontier rung cannot afford.
            r2048, err = _try_rung(
                "tpu_2048", "tpu", 2048, 18, 416, 1, 4,
                min(1800, max(300, _time_left() - 300)), False, "cell", 1, 1,
            )
            _note_health(health, r2048, err)
            headline["rungs"]["2048"] = _rung_summary(
                r2048, err, BASELINE_2048, "vs_baseline_cluster_2048")
        # 2048² bs2 — the reference's single best chart point (≈5.0 img/s
        # across 5 GPUs, AmeobaNet_img_size_2048.png); never measured here
        # before r5.  Honest attempt: cell remat, then fine on OOM.
        if tpu_gate("2048_bs2"):
            print("[bench] 2048px bs2 rung", file=sys.stderr)
            r_b2, b2_errs = _remat_ladder(
                "tpu_2048_bs2", 2048, [("cell", 1), ("fine", 1)], 4, 2,
                1500, health,
            )
            headline["rungs"]["2048_bs2"] = _rung_summary(
                r_b2, b2_errs, BASELINE_2048_BS2,
                "vs_baseline_cluster_2048_bs2")
        # Batch-scaling rungs at the flagship resolution (VERDICT r3 task 2:
        # the reference scales positively bs1→bs2; bs4/bs8 chart the curve).
        # no-remat first, remat fallback on OOM.
        for bname, bs, rung_scan in (
            ("1024_bs2", 2, 4), ("1024_bs4", 4, 2), ("1024_bs8", 8, 1),
        ):
            if not tpu_gate(bname):
                continue
            print(f"[bench] 1024px bs{bs} rung", file=sys.stderr)
            # OOM ladder: prefer no-remat (backward reads stored
            # activations, ~21% faster); before surrendering to cell
            # remat, drop the scan wrapper — its loop-carry
            # double-buffering costs real GBs (measured ~3.7 GB at 2048²),
            # which is exactly what pushed r5's bs4 rung into the cell
            # fallback (3.75 img/s vs bs2's 4.49 at none).  iters is the
            # RUNG's step count regardless of which scan wins (it only
            # needs to be a multiple of the active scan, and rung_scan
            # is): a scan-drop retry must not shrink the sample.
            tries = [("none", rung_scan), ("none", 1),
                     ("cell", rung_scan), ("cell", 1)]
            if rung_scan == 1:
                tries = [("none", 1), ("cell", 1)]
            r_b, b_errs = _remat_ladder(
                f"tpu_{bname}", 1024, tries, 2 * bs * rung_scan, bs,
                1200, health,
            )
            headline["rungs"][bname] = _rung_summary(
                r_b, b_errs,
                BASELINE_1024_BS2 if bs == 2 else None,
                "vs_baseline_cluster_1024_bs2" if bs == 2 else "vs_baseline",
            )
        # ResNet-110-v2 rungs — the reference's second charted model family
        # (VERDICT r3 task 3).  1024² fits on the chip; the 2048² attempt is
        # recorded honestly either way (as of r4 it misses the 16 GB HBM by
        # ~250 MB after striping/packing/group-tuning — PERF_NOTES r4).
        for rname, rpx, rscan, rbase in (
            ("resnet_1024", 1024, 6, BASELINE_RESNET_1024),
            ("resnet_2048", 2048, 1, BASELINE_RESNET_2048),  # frontier: scan=1
        ):
            if _time_left() < 300:
                headline["rungs"][rname] = {"error": "bench deadline reached"}
                continue
            if not tpu_gate(rname):
                continue
            print(f"[bench] {rname} rung", file=sys.stderr)
            r_rn, e_rn = _try_rung(
                f"tpu_{rname}", "tpu", rpx, 110, 0, 1, 2 * rscan,
                min(1200, max(300, _time_left() - 300)), False, "sqrt", 1,
                rscan, "resnet",
            )
            if (r_rn is None and rname == "resnet_2048"
                    and _re.search(_OOM_RE, e_rn or "")
                    and os.environ.get("MPI4DL_REMAT_OPS") != "1"
                    and _time_left() >= 300):
                # Frontier OOM retry with per-op branch checkpoints: the r5
                # OOM top-list is a pile of recomputed stage-2 BN-stat
                # temps during group backward (one cell-level remat
                # re-executes whole branches); MPI4DL_REMAT_OPS=1 bounds
                # the live set to one sub-cell plus packed boundaries.
                print("[bench] resnet_2048 OOM — retrying with "
                      "MPI4DL_REMAT_OPS=1", file=sys.stderr)
                prev_ro = os.environ.get("MPI4DL_REMAT_OPS")
                os.environ["MPI4DL_REMAT_OPS"] = "1"
                try:
                    r2, e2 = _try_rung(
                        f"tpu_{rname}", "tpu", rpx, 110, 0, 1, 2 * rscan,
                        min(1200, max(300, _time_left() - 300)), False,
                        "sqrt", 1, rscan, "resnet",
                    )
                finally:
                    if prev_ro is None:
                        os.environ.pop("MPI4DL_REMAT_OPS", None)
                    else:
                        os.environ["MPI4DL_REMAT_OPS"] = prev_ro
                if r2 is not None:
                    r_rn, e_rn = r2, None
                else:
                    e_rn = f"{e_rn}; remat_ops retry: {e2}"
            _note_health(health, r_rn, e_rn)
            headline["rungs"][rname] = _rung_summary(
                r_rn, e_rn, rbase, f"vs_baseline_cluster_{rname}"
            )
        # Max trainable resolution per chip (driver north-star metric).  The
        # 2048 rung above already proved (or failed) that resolution — seed
        # the ladder instead of re-compiling it.
        print("[bench] max-resolution probe", file=sys.stderr)
        rung_ok = bool(r2048 is not None and not r2048.get("error"))
        known = 2048 if rung_ok else 0
        # Seed from resolutions PROVEN earlier in the round (probe_<px>
        # entries in MEASURED) — the driver's final run must not re-pay
        # compiles a mid-round session already banked.
        prior = _load_measured() or {}
        for k, v in (prior.get("rungs") or {}).items():
            if k.startswith("probe_") and v.get("ok"):
                try:
                    known = max(known, int(k.split("_", 1)[1]))
                except ValueError:
                    pass
        best, attempts = _max_trainable_px(
            start=1024 if not known else 2048,
            known_fit=known,
            gate=health.check, note_ok=health.note_success,
        )
        headline["max_trainable_px"] = best
        headline["max_trainable_px_attempts"] = attempts
        if (best and best == known and not rung_ok
                and not any(a.get("ok") for a in attempts.values())):
            # The reported resolution rests entirely on banked mid-round
            # evidence (no probe succeeded THIS run) — say so, like the
            # headline promotion does.
            headline["max_trainable_px_source"] = (
                "midround_measured (probe_%d; no successful probe this run)"
                % best)

    # Fold the incrementally-captured hardware evidence into the driver's
    # record: even if THIS run landed on the CPU smoke rung, any hardware
    # numbers measured earlier in the round (mid-round sessions write the
    # same file) still reach BENCH_r*.json (VERDICT r4 fatal-gap fix).
    measured = _load_measured()
    if measured and measured.get("rungs"):
        headline["midround_measured"] = measured["rungs"]
        if headline.get("platform") in ("cpu", "none"):
            # The live run could not reach the TPU — promote the banked
            # mid-round TPU headline (same rung configs, explicit
            # provenance) so a dead tunnel at round end cannot zero the
            # round's primary metric again (the r4 fatal gap).
            for mname in ("tpu_1024_noremat", "tpu_1024"):
                m = measured["rungs"].get(mname)
                if not m or m.get("error"):
                    continue
                live_cpu = {k: headline.get(k) for k in (
                    "metric", "value", "unit", "platform", "rung", "error")
                    if k in headline}
                # Per-run measurement metadata of the failed/smoke run must
                # not masquerade as the promoted TPU rung's.
                for stale in ("iters", "scan_steps_per_dispatch",
                              "flops_per_step", "peak_tflops", "error"):
                    headline.pop(stale, None)
                v = m["img_per_sec"]
                headline.update({
                    "metric": "amoebanetd_1024px_bs1_train_img_per_sec"
                              "_single_chip_vs_5gpu_cluster_baseline",
                    "value": v,
                    "unit": "images/sec",
                    "vs_baseline": round(v / BASELINE_CLUSTER, 4),
                    "vs_baseline_per_device": round(
                        v / (BASELINE_CLUSTER / BASELINE_DEVICES), 4),
                    "platform": m.get("platform", "tpu"),
                    "device_kind": m.get("device_kind"),
                    "mfu": m.get("mfu"),
                    "achieved_tflops": m.get("achieved_tflops"),
                    "timing_mode": m.get("timing_mode"),
                    "rung": mname,
                    "rung_config": m.get("rung_config"),
                    "headline_source": (
                        f"midround_measured (captured_unix="
                        f"{m.get('captured_unix')}; live TPU attempt failed "
                        f"this run)"),
                    "live_fallback": live_cpu,
                })
                break
    if failures:
        headline["ladder_failures"] = [f for f in failures if f][-6:]

    print(json.dumps(headline))
    return 0


if __name__ == "__main__":
    sys.exit(main())
