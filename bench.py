"""Headline benchmark — prints ONE JSON line.

Metric: AmoebaNet-D training throughput (images/sec) on one chip at the
reference's flagship 1024x1024 resolution, batch size 1 (the configuration of
the reference's published charts, BASELINE.md: best bs1 result at 1024^2 is
~2.1 img/s for SP square + halo-D2 across 5 GPUs).  ``vs_baseline`` is
images/sec divided by that 2.1 img/s reference number.

On a CPU host (no TPU attached) the benchmark downsizes so it still completes;
the driver runs it on real TPU hardware.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

from mpi4dl_tpu.models.amoebanet import amoebanetd
from mpi4dl_tpu.train import Optimizer, TrainState, make_train_step

BASELINE_IMG_PER_SEC = 2.1  # reference: AmoebaNet-D 1024^2 bs1, SP square + D2, 5 GPUs


def main() -> None:
    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"

    if on_tpu:
        image_size, num_layers, num_filters, batch = 1024, 18, 416, 1
        warmup, iters = 2, 8
    else:  # smoke mode for CPU-only environments
        image_size, num_layers, num_filters, batch = 128, 3, 64, 1
        warmup, iters = 1, 3

    model = amoebanetd(
        (batch, image_size, image_size, 3),
        num_classes=1000,
        num_layers=num_layers,
        num_filters=num_filters,
    )
    params, _ = model.init(jax.random.key(0))
    opt = Optimizer("sgd", lr=0.001)
    step = make_train_step(model, opt, compute_dtype=jnp.bfloat16)
    state = TrainState.create(params, opt)

    x = jax.random.normal(jax.random.key(1), (batch, image_size, image_size, 3))
    y = jnp.zeros((batch,), jnp.int32)

    for _ in range(warmup):
        state, metrics = step(state, x, y)
    jax.block_until_ready(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, x, y)
    jax.block_until_ready(metrics["loss"])
    dt = time.perf_counter() - t0

    img_per_sec = batch * iters / dt
    out = {
        "metric": f"amoebanetd_{image_size}px_bs{batch}_train_img_per_sec_per_chip",
        "value": round(img_per_sec, 4),
        "unit": "images/sec",
        # Only the TPU run at the reference resolution is comparable to the
        # reference's 2.1 img/s; the CPU smoke config reports 0.
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 4) if on_tpu else 0.0,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
