"""8K x 8K readiness artifact (VERDICT r3 task 7).

The driver's north-star (`BASELINE.json`) is AmoebaNet-D at 8192x8192 under
SP+PP on a v5p-32.  That hardware is unreachable from this environment, but
two of the three questions it poses are answerable today:

1. **Does the flagship program COMPILE at the real shapes?**  This tool
   builds the SP(4x4) x PP(2) training step for AmoebaNet-D(18,416) at
   8192² bs1 on a 32-virtual-device CPU mesh and compiles it — XLA
   partitions, inserts the collectives, and assigns buffers exactly as it
   would for a real 32-device slice (CPU layouts, i.e. no TPU tile
   padding — stated with the numbers).
2. **What moves per step?**  Collective counts/bytes are read from the
   compiled HLO at the REAL shapes (the existing comm_volume_report runs at
   64² toy shapes), via the same parser.
3. **Does it fit?**  Per-device HBM demand = the compiled module's
   temp+argument+output sizes (SPMD: the module IS the per-device program)
   plus an analytic eval_shape activation ledger as a cross-check, compared
   against per-chip HBM of v5p (95 GB) and v5e (16 GB).

Usage (self-provisions the virtual mesh):
    python benchmarks/readiness_8k.py [--image-size 8192] [--tiles 4]
        [--stages 2] [--parts 1] [--out /tmp/readiness.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from mpi4dl_tpu.config import _spatial_until_arg  # noqa: E402

V5P_HBM_GB = 95.0
V5E_HBM_GB = 16.0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=8192)
    p.add_argument("--tiles", type=int, default=4, help="spatial grid per dim")
    p.add_argument("--stages", type=int, default=2)
    p.add_argument("--parts", type=int, default=1)
    p.add_argument("--schedule", choices=["gpipe", "1f1b"], default="gpipe",
                   help="pipeline-tail schedule (1f1b bounds live tail "
                        "micro-batches to O(stages); docs/pipeline.md)")
    p.add_argument("--num-layers", type=int, default=18)
    p.add_argument("--num-filters", type=int, default=416)
    p.add_argument("--spatial-until", default="9",
                   type=_spatial_until_arg,
                   help="cells in the spatial region (stems + first normal "
                        "group by default — the high-resolution cells), or "
                        "'auto' to resolve the junction placement from the "
                        "analytical frontier "
                        "(parallel/spatial.choose_spatial_until)")
    p.add_argument("--spatial-parts", default=None, metavar="N[,N...]",
                   help="multi-level spatial chain (square grids), e.g. "
                        "'64,16' = SP(8x8) head levels coarsening to 4x4 "
                        "via the gather-free respatial fast paths; "
                        "overrides --tiles (level-0 grid = sqrt(N0))")
    p.add_argument("--stripe-bwd", action="store_true",
                   help="sets MPI4DL_STRIPE_BWD=1: stripe-wise backward "
                        "through the SP-region blocks (the O(parts) "
                        "buy-back; docs/pipeline.md)")
    p.add_argument("--require-gb", type=float, default=None,
                   help="exit 1 if the compiled per-device HBM demand "
                        "exceeds this many GB (the spatial-stripe-memory "
                        "CI gate: < 95 GB at 8192² parts=8 with "
                        "--stripe-bwd on)")
    p.add_argument("--attribute", action="store_true",
                   help="add the per-obs.scope HBM breakdown + analytical "
                        "timeline + exposed-wire overlap ledger (obs/hbm.py,"
                        " obs/timeline.py, obs/overlap.py) to the artifact "
                        "— names which phase owns the per-device GB this "
                        "tool reports and how much of the per-step wire "
                        "volume is structurally hidden vs exposed")
    p.add_argument("--telemetry-dir", default=None,
                   help="mirror the artifact into a RunLog JSONL "
                        "(readiness + hbm + timeline records; render with "
                        "`python -m mpi4dl_tpu.obs report`)")
    p.add_argument("--quant", default="off", metavar="SPEC",
                   help="quantized-collective policy (off | int8|fp8|int4 | "
                        "per-class spec; docs/quantization.md) — the "
                        "tentpole's wire-shrink measured at the real shapes")
    p.add_argument("--require-wire-gb", type=float, default=None,
                   help="with --attribute: exit 1 if the overlap ledger's "
                        "total wire exceeds this many GB/step (the "
                        "quant-contract CI gate: <= 18 GB at 8192² with "
                        "quantization on, vs the 31.0 GB raw baseline)")
    p.add_argument("--out", default=None)
    args = p.parse_args(argv)
    if args.require_wire_gb is not None and not args.attribute:
        # The gate reads the overlap ledger, which only exists under
        # --attribute; a silent no-op here would pass the CI gate vacuously.
        p.error("--require-wire-gb needs --attribute (the gate reads the "
                "overlap ledger)")

    if args.stripe_bwd:
        os.environ["MPI4DL_STRIPE_BWD"] = "1"
    spatial_parts = (
        [int(s) for s in args.spatial_parts.split(",")]
        if args.spatial_parts else None
    )
    if spatial_parts:
        import math

        g0 = math.isqrt(spatial_parts[0])
        assert g0 * g0 == spatial_parts[0], (
            f"--spatial-parts levels must be perfect squares, got "
            f"{spatial_parts[0]}"
        )
        args.tiles = g0
    n_dev = args.tiles * args.tiles * args.stages
    import jax

    from benchmarks.common import _ensure_devices

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — backend already up; checked below
        pass
    _ensure_devices(n_dev)
    if len(jax.devices()) < n_dev:
        raise SystemExit(f"needs {n_dev} devices (got {len(jax.devices())})")

    import jax.numpy as jnp

    from benchmarks.communication.comm_volume_report import hlo_collective_stats
    from mpi4dl_tpu.layer_ctx import SpatialCtx
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh
    from mpi4dl_tpu.models.amoebanet import amoebanetd
    from mpi4dl_tpu.parallel.sp_pipeline import (
        SPPipeline, init_sp_pipeline_state, make_sp_pipeline_train_step,
    )
    from mpi4dl_tpu.train import Optimizer

    from mpi4dl_tpu.quant import QuantPolicy

    quant = QuantPolicy.resolve(args.quant)
    px, t, S = args.image_size, args.tiles, args.stages
    model = amoebanetd(
        (1, px, px, 3), num_classes=1000,
        num_layers=args.num_layers, num_filters=args.num_filters,
    )
    params, shapes = model.init(jax.random.key(0))
    if args.spatial_until == "auto":
        from mpi4dl_tpu.parallel.spatial import choose_spatial_until

        # With --spatial-parts the proxy assumes the LEVEL-0 grid for the
        # whole region; coarser levels hold a larger share, so the chosen
        # placement is conservative (never deeper than the true optimum).
        su = choose_spatial_until(shapes, t * t, itemsize=2)
        print(f"[readiness] --spatial-until auto -> {su} "
              f"(analytical placement frontier, {t}x{t} tiles)",
              file=sys.stderr)
    else:
        su = int(args.spatial_until)
    model.spatial_until = min(su, len(model.cells) - 1)
    su = model.spatial_until

    # --- spatial level chain (built before the ledger: per-cell tile
    # counts depend on which level a cell lands in) ----------------------
    sp = SpatialCtx(axis_h="sph", axis_w="spw", grid_h=t, grid_w=t)
    levels = None
    if spatial_parts:
        # Multi-level spatial chain (e.g. SP(8x8) head coarsening to 4x4):
        # square grids per level, level transitions via the gather-free
        # respatial fast paths (PR 10), level stops splitting the spatial
        # region evenly.
        from mpi4dl_tpu.cells import split_even
        from mpi4dl_tpu.layer_ctx import spatial_levels_for

        ctxs = spatial_levels_for("square", spatial_parts)
        sp = ctxs[0]
        stops = [hi for _, hi in split_even(su, len(ctxs))]
        levels = []
        # Unlike benchmarks/common._spatial_levels there is no
        # identical-grid merge case here: a square chain's grids shrink
        # strictly level to level, so a stop collision (su < levels) just
        # drops the coarser level.
        for stop, c in zip(stops, ctxs):
            if stop > (levels[-1][0] if levels else 0):
                levels.append((stop, c))
        levels[-1] = (su, levels[-1][1])

    # --- analytic ledger: per-device activation bytes from eval_shape ----
    # A spatial cell carries its LEVEL's tile fraction (multi-level chains
    # coarsen the grid, so later cells hold a larger per-device share);
    # tail cells live on one stage.
    from mpi4dl_tpu.parallel.spatial import _cell_bytes

    def _tiles_for(i: int) -> int:
        if levels:
            for stop, c in levels:
                if i < stop:
                    return c.grid_h * c.grid_w
        return t * t

    ledger = {"spatial_cells": [], "tail_cells": []}
    for i, shp in enumerate(shapes):
        bytes_dev = _cell_bytes(shp, 2)  # bf16
        if i < su:
            bytes_dev //= _tiles_for(i)
        (ledger["spatial_cells"] if i < su else ledger["tail_cells"]).append(
            {"cell": i, "per_device_mb": round(bytes_dev / 2**20, 1)}
        )
    sp_sum = sum(c["per_device_mb"] for c in ledger["spatial_cells"])
    tail_sum = sum(c["per_device_mb"] for c in ledger["tail_cells"])

    # --- build + compile the flagship program at real shapes -------------
    mesh = build_mesh(
        MeshSpec(data=1, stage=S, sph=t, spw=t), jax.devices()[:n_dev]
    )
    opt = Optimizer("sgd", lr=0.001)
    t0 = time.time()
    # gather junction: batch_split needs microbatch % tiles² == 0, which
    # bs1 (the north-star config) cannot satisfy.
    spp = SPPipeline.build(model, params, S, sp, microbatch=1,
                           junction="gather", levels=levels)
    step = make_sp_pipeline_train_step(
        spp, opt, mesh, parts=args.parts, compute_dtype=jnp.bfloat16,
        remat=True, donate=True, schedule=args.schedule, quant=quant,
    )
    state = init_sp_pipeline_state(spp, params, opt, mesh)
    x = jnp.zeros((args.parts * 1, px, px, 3), jnp.bfloat16)
    y = jnp.zeros((args.parts * 1,), jnp.int32)
    lowered = step.lower(state, x, y)
    print(f"[readiness] lowered in {time.time()-t0:.0f}s; compiling...",
          file=sys.stderr)
    t0 = time.time()
    compiled = lowered.compile()
    compile_s = time.time() - t0
    print(f"[readiness] compiled in {compile_s:.0f}s", file=sys.stderr)

    ma = compiled.memory_analysis()
    mem = {
        "temp_gb": round(ma.temp_size_in_bytes / 2**30, 2),
        "arg_gb": round(ma.argument_size_in_bytes / 2**30, 2),
        "out_gb": round(ma.output_size_in_bytes / 2**30, 2),
        "alias_gb": round(ma.alias_size_in_bytes / 2**30, 2),
        "note": "per-device (SPMD module) on CPU layouts — no TPU tile "
                "padding; TPU adds up to 2x on non-128-multiple channels",
    }
    per_dev_gb = (
        ma.temp_size_in_bytes
        + ma.argument_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
    ) / 2**30
    # Serialize the module once — as_text() on the 8K flagship program is
    # the dominant non-compile cost; the attribution block reuses it.
    hlo_text = compiled.as_text()
    comm = hlo_collective_stats(hlo_text)

    out = {
        "metric": "readiness_8k_per_device_gb",
        "value": round(per_dev_gb, 2),
        "unit": "GB/device",
        "config": {
            "image_size": px, "grid": f"{t}x{t}", "stages": S,
            "parts": args.parts, "schedule": args.schedule,
            "devices": n_dev,
            "spatial_until": model.spatial_until,
            "spatial_parts": spatial_parts,
            "stripe_bwd": bool(args.stripe_bwd
                               or os.environ.get("MPI4DL_STRIPE_BWD") == "1"),
            "model": f"amoebanetd({args.num_layers},{args.num_filters})",
            "quant": quant.spec() if quant else "off",
        },
        "compile_seconds": round(compile_s, 1),
        "memory_analysis": mem,
        "fits_v5p_95gb": per_dev_gb < V5P_HBM_GB,
        "fits_v5e_16gb": per_dev_gb < V5E_HBM_GB,
        "headroom_v5p_gb": round(V5P_HBM_GB - per_dev_gb, 1),
        "collectives_per_step": {
            k: v for k, v in comm.items() if isinstance(v, dict) and v["count"]
        },
        "collective_total_gb": round(comm["total_bytes"] / 2**30, 3),
        "activation_ledger": {
            "spatial_cells_sum_per_device_mb": round(sp_sum, 1),
            "tail_cells_sum_total_mb": round(tail_sum, 1),
            "largest_spatial_cell_mb": max(
                (c["per_device_mb"] for c in ledger["spatial_cells"]),
                default=0,
            ),
        },
    }
    if args.require_gb is not None:
        ok = per_dev_gb < args.require_gb
        out["hbm_gate"] = {"limit_gb": args.require_gb, "ok": ok}
        print(
            f"[readiness] HBM gate {'ok' if ok else 'FAILED'}: "
            f"{per_dev_gb:.2f} GB/device "
            f"{'<' if ok else '>='} --require-gb {args.require_gb}",
            file=sys.stderr,
        )
    breakdown = timeline = ledger = None
    if args.attribute:
        from mpi4dl_tpu.obs import (
            analytical_timeline,
            attribute_compiled,
            overlap_ledger,
        )
        from mpi4dl_tpu.obs.hbm import format_breakdown, scope_group_bytes
        from mpi4dl_tpu.obs.overlap import format_ledger

        breakdown = attribute_compiled(compiled, hlo_text=hlo_text)
        timeline = analytical_timeline(
            hlo_text, device=jax.devices()[0],
            schedule=args.schedule, stages=S, parts=args.parts,
        )
        ledger = overlap_ledger(hlo_text, device=jax.devices()[0])
        out["hbm"] = breakdown
        out["timeline"] = timeline
        out["overlap"] = ledger
        out["hbm_phase_groups_gb"] = {
            k: round(v / 2**30, 3)
            for k, v in scope_group_bytes(breakdown).items()
        }
        # The overlap rollup: how much of the wire volume this tool reports
        # under "what moves per step" is structurally hidden vs exposed in
        # the compiled schedule (ROADMAP item 2's measurement half; on the
        # CPU backend every collective compiles sync, so exposed == all —
        # the baseline the halo-RDMA overlap work must move).
        t_led = ledger["totals"]
        out["overlap_rollup"] = {
            "wire_gb": round(t_led["bytes"] / 2**30, 3),
            "quantized_gb": round(
                t_led.get("quantized_bytes", 0) / 2**30, 3
            ),
            "exposed_ms": t_led["exposed_ms"],
            "hidden_ms": t_led["hidden_ms"],
            "hidden_frac": ledger["hidden_frac"],
            "async_pairs": t_led["async_pairs"],
            "sync_collectives": t_led["sync"],
            "attributed_bytes_frac": ledger["attributed_bytes_frac"],
            "by_class": {
                cls: {"exposed_ms": b["exposed_ms"],
                      "hidden_ms": b["hidden_ms"],
                      "gb": round(b["bytes"] / 2**30, 3),
                      "quantized_gb": round(
                          b.get("quantized_bytes", 0) / 2**30, 3)}
                for cls, b in ledger["by_class"].items()
            },
        }
        print(format_breakdown(breakdown), file=sys.stderr)
        print(format_ledger(ledger), file=sys.stderr)
        if args.require_wire_gb is not None:
            wire_gb = out["overlap_rollup"]["wire_gb"]
            if wire_gb > args.require_wire_gb:
                print(
                    f"[readiness] WIRE GATE FAILED: {wire_gb} GB/step > "
                    f"--require-wire-gb {args.require_wire_gb}",
                    file=sys.stderr,
                )
                out["wire_gate"] = {"limit_gb": args.require_wire_gb,
                                    "ok": False}
            else:
                print(
                    f"[readiness] wire gate ok: {wire_gb} GB/step <= "
                    f"{args.require_wire_gb}", file=sys.stderr,
                )
                out["wire_gate"] = {"limit_gb": args.require_wire_gb,
                                    "ok": True}

    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)
    if args.telemetry_dir:
        from mpi4dl_tpu.obs import RunLog

        runlog = RunLog.create(args.telemetry_dir, prefix="readiness")
        runlog.write_meta(config=out["config"], family="sp",
                          argv=list(argv) if argv is not None else sys.argv[1:])
        runlog.write("readiness", **{k: v for k, v in out.items()
                                     if k not in ("hbm", "timeline",
                                                  "overlap")})
        if breakdown is not None:
            runlog.write("hbm", label="readiness", breakdown=breakdown)
            runlog.write("timeline", label="readiness", **timeline)
            runlog.write("overlap", label="readiness", **ledger)
        runlog.close()
        print(f"[readiness] telemetry written to {runlog.path}",
              file=sys.stderr)
    if not out.get("wire_gate", {}).get("ok", True):
        return 1
    if not out.get("hbm_gate", {}).get("ok", True):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
