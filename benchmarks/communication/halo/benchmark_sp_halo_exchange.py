"""Halo-exchange latency microbenchmark + validation.

Reference: benchmarks/communication/halo/benchmark_sp_halo_exchange.py —
arange-image construction (:417-557), exact compare vs the globally
zero-padded image (:568-578), warmup + CUDA-event timed loop (:581-615).
Its published sample: ≈0.334 ms/iter at 1024², 4-way vertical, halo 3,
batch 1 on 4 GPUs (halo README:29-43).

``--with-compute`` adds the reference's `_with_compute` / `_conv` variants
(benchmark_sp_halo_exchange_with_compute.py:600-666): time exchange+conv
across the tile grid AGAINST the same convolution over the full image on one
device, and validate the gathered distributed conv output against the
single-device result (the `_with_compute_val` check).

This version runs the experiment as ONE jitted shard_map program whose
distributed body is the halo exchange (4 ppermutes max) [+ a VALID conv
consuming the margin], on whatever platform JAX offers: a TPU mesh when
multiple chips are attached, else a forced-host CPU mesh (functional
validation; CPU timing is not comparable).

Example:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \\
  python benchmark_sp_halo_exchange.py --image-size 256 --halo-len 3 \\
      --num-spatial-parts 4 --slice-method vertical --with-compute
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--channels", type=int, default=3)
    p.add_argument("--halo-len", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", default="vertical",
                   help="square | vertical | horizontal")
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--with-compute", action="store_true",
                   help="also time halo-exchange+conv vs a single-device conv "
                        "(reference _with_compute variant) and validate")
    p.add_argument("--num-filters", type=int, default=32,
                   help="conv output channels for --with-compute")
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of the timed loop here")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from mpi4dl_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from mpi4dl_tpu.layer_ctx import spatial_ctx_for
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh
    from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_2d

    sp = spatial_ctx_for(args.slice_method, args.num_spatial_parts)
    from benchmarks.common import _ensure_devices

    _ensure_devices(sp.grid_h * sp.grid_w)
    mesh = build_mesh(MeshSpec(sph=sp.grid_h, spw=sp.grid_w), jax.devices())
    h = args.halo_len
    size, b, c = args.image_size, args.batch_size, args.channels
    spec = P(None, sp.axis_h, sp.axis_w, None)
    halo_h = HaloSpec.symmetric(h if sp.grid_h > 1 else 0)
    halo_w = HaloSpec.symmetric(h if sp.grid_w > 1 else 0)

    fn = jax.jit(
        shard_map(
            lambda t: halo_exchange_2d(
                t, halo_h, halo_w, sp.axis_h, sp.axis_w, sp.grid_h, sp.grid_w
            ),
            mesh=mesh, in_specs=spec, out_specs=spec,
        )
    )

    # --- validation: arange image, exact compare against the matching window
    # of the globally zero-padded image (reference :417-461 per slice method).
    x = jnp.arange(b * size * size * c, dtype=jnp.float32).reshape(b, size, size, c)
    out = np.asarray(jax.block_until_ready(fn(x)))
    padded = np.pad(
        np.asarray(x), ((0, 0), (halo_h.lo, halo_h.hi), (halo_w.lo, halo_w.hi), (0, 0))
    )
    th, tw = size // sp.grid_h, size // sp.grid_w
    eth, etw = th + 2 * halo_h.lo, tw + 2 * halo_w.lo
    ok = True
    # shard_map concatenates per-tile outputs along the sharded dims.
    for r in range(sp.grid_h):
        for cc in range(sp.grid_w):
            got = out[:, r * eth : (r + 1) * eth, cc * etw : (cc + 1) * etw]
            want = padded[:, r * th : r * th + eth, cc * tw : cc * tw + etw]
            if not np.array_equal(got, want):
                ok = False
    print(f"validation: {'PASSED' if ok else 'FAILED'}")

    def timed_loop(f, arg):
        """warmup + per-iter timing (reference :598-613)."""
        out_d = f(arg)  # ensure compiled even with --warmup 0
        for _ in range(args.warmup):
            out_d = f(arg)
        jax.block_until_ready(out_d)
        ts = []
        for _ in range(args.iterations):
            t0 = time.perf_counter()
            jax.block_until_ready(f(arg))
            ts.append((time.perf_counter() - t0) * 1e3)
        return np.asarray(ts)

    # try/finally: a crash mid-measurement must still flush the trace
    # (start_trace only buffers; stop_trace writes the files).
    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    try:
        times_np = timed_loop(fn, x)
        result = {
            "metric": "halo_exchange_ms_per_iter",
            "value": round(float(np.mean(times_np)), 4),
            "median_ms": round(float(np.median(times_np)), 4),
            "min_ms": round(float(np.min(times_np)), 4),
            "platform": jax.devices()[0].platform,
            "config": {
                "image_size": size, "batch": b, "channels": c, "halo_len": h,
                "parts": args.num_spatial_parts, "slice_method": args.slice_method,
            },
            "validation": "pass" if ok else "FAIL",
            "reference_ms": 0.334,  # 4xGPU MVAPICH2-GDR sample, halo README:29-43
        }

        if args.with_compute:
            # Reference _with_compute/_conv: a conv whose receptive field matches
            # the halo (k = 2*halo+1), run (a) distributed as exchange + VALID
            # conv consuming the margin, (b) on the full image on one device;
            # the gathered outputs must agree (_with_compute_val, ref
            # benchmark_sp_halo_exchange_conv.py:759-843) and both get timed
            # (ref benchmark_sp_halo_exchange_with_compute.py:600-666).
            kh = 2 * h + 1
            kernel = jax.random.normal(
                jax.random.key(0), (kh, kh, c, args.num_filters), jnp.float32
            ) / (kh * kh * c)
            sharded_h = sp.grid_h > 1
            sharded_w = sp.grid_w > 1

            def conv(t, pad_h, pad_w):
                return lax.conv_general_dilated(
                    t, kernel, (1, 1), (pad_h, pad_w),
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )

            def dist_body(t):
                t = halo_exchange_2d(
                    t, halo_h, halo_w, sp.axis_h, sp.axis_w, sp.grid_h, sp.grid_w
                )
                return conv(
                    t,
                    (0, 0) if sharded_h else (h, h),
                    (0, 0) if sharded_w else (h, h),
                )

            dist_fn = jax.jit(
                shard_map(dist_body, mesh=mesh, in_specs=spec, out_specs=spec)
            )
            single_fn = jax.jit(lambda t: conv(t, (h, h), (h, h)))

            got = np.asarray(jax.block_until_ready(dist_fn(x)))
            want = np.asarray(jax.block_until_ready(single_fn(x)))
            cok = np.allclose(got, want, atol=1e-4)
            print(f"conv validation: {'PASSED' if cok else 'FAILED'}")
            ok = ok and cok

            t_dist = timed_loop(dist_fn, x)
            t_single = timed_loop(single_fn, x)
            result["with_compute"] = {
                "dist_exchange_conv_ms": round(float(np.mean(t_dist)), 4),
                "single_device_conv_ms": round(float(np.mean(t_single)), 4),
                "speedup_vs_single": round(
                    float(np.mean(t_single) / np.mean(t_dist)), 3
                ),
                "num_filters": args.num_filters,
                "kernel": kh,
                "conv_validation": "pass" if cok else "FAIL",
            }
            result["validation"] = "pass" if ok else "FAIL"

    finally:
        if args.profile_dir:
            jax.profiler.stop_trace()
    if args.profile_dir:
        result["profile_dir"] = args.profile_dir
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
