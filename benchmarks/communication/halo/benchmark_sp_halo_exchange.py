"""Halo-exchange latency microbenchmark + validation.

Reference: benchmarks/communication/halo/benchmark_sp_halo_exchange.py —
arange-image construction (:417-557), exact compare vs the globally
zero-padded image (:568-578), warmup + CUDA-event timed loop (:581-615).
Its published sample: ≈0.334 ms/iter at 1024², 4-way vertical, halo 3,
batch 1 on 4 GPUs (halo README:29-43).

This version runs the same experiment as ONE jitted shard_map program whose
only body is the halo exchange (4 ppermutes max), on whatever platform JAX
offers: a TPU mesh when multiple chips are attached, else the forced-host
8-device CPU mesh (functional validation; CPU timing is not comparable).

Example:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
  python benchmark_sp_halo_exchange.py --image-size 256 --halo-len 3 \\
      --num-spatial-parts 4 --slice-method vertical
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--channels", type=int, default=3)
    p.add_argument("--halo-len", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", default="vertical",
                   help="square | vertical | horizontal")
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument("--iterations", type=int, default=100)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from mpi4dl_tpu.layer_ctx import spatial_ctx_for
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh
    from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_2d

    sp = spatial_ctx_for(args.slice_method, args.num_spatial_parts)
    mesh = build_mesh(MeshSpec(sph=sp.grid_h, spw=sp.grid_w), jax.devices())
    h = args.halo_len
    size, b, c = args.image_size, args.batch_size, args.channels
    spec = P(None, sp.axis_h, sp.axis_w, None)
    halo_h = HaloSpec.symmetric(h if sp.grid_h > 1 else 0)
    halo_w = HaloSpec.symmetric(h if sp.grid_w > 1 else 0)

    fn = jax.jit(
        shard_map(
            lambda t: halo_exchange_2d(
                t, halo_h, halo_w, sp.axis_h, sp.axis_w, sp.grid_h, sp.grid_w
            ),
            mesh=mesh, in_specs=spec, out_specs=spec,
        )
    )

    # --- validation: arange image, exact compare against the matching window
    # of the globally zero-padded image (reference :417-461 per slice method).
    x = jnp.arange(b * size * size * c, dtype=jnp.float32).reshape(b, size, size, c)
    out = np.asarray(jax.block_until_ready(fn(x)))
    padded = np.pad(
        np.asarray(x), ((0, 0), (halo_h.lo, halo_h.hi), (halo_w.lo, halo_w.hi), (0, 0))
    )
    th, tw = size // sp.grid_h, size // sp.grid_w
    eth, etw = th + 2 * halo_h.lo, tw + 2 * halo_w.lo
    ok = True
    # shard_map concatenates per-tile outputs along the sharded dims.
    for r in range(sp.grid_h):
        for cc in range(sp.grid_w):
            got = out[:, r * eth : (r + 1) * eth, cc * etw : (cc + 1) * etw]
            want = padded[:, r * th : r * th + eth, cc * tw : cc * tw + etw]
            if not np.array_equal(got, want):
                ok = False
    print(f"validation: {'PASSED' if ok else 'FAILED'}")

    # --- timed loop (reference :598-613: warmup then per-iter timing) ---
    for _ in range(args.warmup):
        out_d = fn(x)
    jax.block_until_ready(out_d)
    times = []
    for _ in range(args.iterations):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x))
        times.append((time.perf_counter() - t0) * 1e3)
    times_np = np.asarray(times)
    result = {
        "metric": "halo_exchange_ms_per_iter",
        "value": round(float(np.mean(times_np)), 4),
        "median_ms": round(float(np.median(times_np)), 4),
        "min_ms": round(float(np.min(times_np)), 4),
        "platform": jax.devices()[0].platform,
        "config": {
            "image_size": size, "batch": b, "channels": c, "halo_len": h,
            "parts": args.num_spatial_parts, "slice_method": args.slice_method,
        },
        "validation": "pass" if ok else "FAIL",
        "reference_ms": 0.334,  # 4xGPU MVAPICH2-GDR sample, halo README:29-43
    }
    print(json.dumps(result))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
