"""Pallas halo-consuming conv vs XLA conv — the SURVEY §7 D2-endgame
measurement (VERDICT r3 task 9: measure, then decide).

Times the margin-consuming VALID conv (the hot op of fused halo-D2 runs,
ops/d2.py) three ways at D2-representative shapes:

  xla_valid   — lax.conv_general_dilated VALID on the margin-carrying input
                (the production path inside a fused run today)
  pallas      — ops/pallas_conv.halo_conv2d (implicit-GEMM Pallas kernel)
  xla_same    — lax.conv SAME on the unpadded input (the D1 cost for scale)

Prints one JSON line with ms + achieved TFLOPs per variant and the
pallas/xla speedup.  Run on real TPU hardware; on CPU it still runs (with
--interpret for the Pallas path) but timings are not meaningful.

Example:
  python benchmark_pallas_conv.py --height 512 --width 512 --cin 256 \\
      --cout 256 --kernel 3 --dtype bf16
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--height", type=int, default=512)
    p.add_argument("--width", type=int, default=512)
    p.add_argument("--cin", type=int, default=256)
    p.add_argument("--cout", type=int, default=256)
    p.add_argument("--kernel", type=int, default=3)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--tile-h", type=int, default=64)
    p.add_argument("--tile-w", type=int, default=128)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iterations", type=int, default=30)
    p.add_argument("--interpret", action="store_true",
                   help="run the Pallas kernel in interpreter mode (CPU)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.ops.pallas_conv import conv_flops, halo_conv2d

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    k, h, w = args.kernel, args.height, args.width
    m = k - 1
    kx, kw_ = jax.random.split(jax.random.key(0))
    x_pad = jax.random.normal(kx, (args.batch, h + m, w + m, args.cin), dtype)
    x_raw = x_pad[:, m // 2 : m // 2 + h, m // 2 : m // 2 + w, :]
    wk = (jax.random.normal(kw_, (k, k, args.cin, args.cout), dtype)
          / (k * k))

    def xla_valid(t):
        return jax.lax.conv_general_dilated(
            t, wk, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )

    def xla_same(t):
        return jax.lax.conv_general_dilated(
            t, wk, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
        )

    def pallas_fn(t):
        return halo_conv2d(
            t, wk, th=args.tile_h, tw=args.tile_w, interpret=args.interpret
        )

    variants = {
        "xla_valid": (jax.jit(xla_valid), x_pad),
        "pallas": (pallas_fn, x_pad),
        "xla_same": (jax.jit(xla_same), x_raw),
    }
    flops = conv_flops(args.batch, h, w, args.cin, args.cout, k, k)

    results = {}
    for name, (fn, arg) in variants.items():
        out = fn(arg)
        # D2H fetch of a scalar — honest sync under the axon RPC backend
        # (block_until_ready has been observed returning early; bench.py).
        float(jnp.sum(out[..., 0].astype(jnp.float32)))
        for _ in range(args.warmup):
            out = fn(arg)
        float(jnp.sum(out[..., 0].astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            out = fn(arg)
        float(jnp.sum(out[..., 0].astype(jnp.float32)))
        dt = (time.perf_counter() - t0) / args.iterations
        results[name] = {
            "ms": round(dt * 1e3, 4),
            "tflops": round(flops / dt / 1e12, 2),
        }

    # Correctness cross-check at benchmark shapes.
    a = np.asarray(variants["pallas"][0](x_pad), np.float32)
    b = np.asarray(variants["xla_valid"][0](x_pad), np.float32)
    ok = bool(np.allclose(a, b, rtol=0.05, atol=0.05))

    out = {
        "metric": "halo_valid_conv_ms",
        "value": results["pallas"]["ms"],
        "unit": "ms",
        "config": {
            "h": h, "w": w, "cin": args.cin, "cout": args.cout, "k": k,
            "batch": args.batch, "dtype": args.dtype,
            "tile": [args.tile_h, args.tile_w],
        },
        "variants": results,
        "pallas_speedup_vs_xla": round(
            results["xla_valid"]["ms"] / results["pallas"]["ms"], 3
        ),
        "flops_per_call": flops,
        "validation": "pass" if ok else "FAIL",
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
