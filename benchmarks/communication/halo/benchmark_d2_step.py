"""Hardware A/B of the Pallas-conv default in a D2-shaped END-TO-END step.

VERDICT r3 task 5: the kernel wins every op microbenchmark at D2 depths
(benchmark_pallas_conv.py), yet the same kernel measured 35% SLOWER in the
whole single-device SAME-conv program — XLA's conv+bias+BN+ReLU fusion died
at the pallas_call boundary.  The margin-consuming D2 path keeps it ON
based on op numbers only; this tool closes the gap with STEP-level timing.

Construction: the single-chip pad-once emulation of a fused margin-
consuming run (exactly what tests/test_d2.py uses for numerics) — the tile
carries the run's accumulated margin, and ``apply_layers_premargin`` drives
the SAME dispatch the distributed D2 path takes (SpatialCtx with
halo_pre_exchanged margins; bn_cross_tile=False so no collectives).  One
"step" = forward + grads + SGD update of a run of ``--fused`` relu-conv-bn
ops (the AmoebaNet op body, models/amoebanet.py _relu_conv_bn), timed with
a device-to-host scalar fetch.  A/B = SpatialCtx.use_pallas_conv.

Example (real chip):
  python benchmark_d2_step.py --tile 512 --channels 208 --fused 3
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--tile", type=int, default=512,
                   help="local tile extent (e.g. 512 = a 1024² image on a "
                        "2x2 grid)")
    p.add_argument("--channels", type=int, default=208)
    p.add_argument("--fused", type=int, default=3,
                   help="number of relu-conv-bn ops in the fused run")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iterations", type=int, default=20)
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx
    from mpi4dl_tpu.layers import BatchNorm, Conv2d, ReLU
    from mpi4dl_tpu.ops.d2 import accumulated_halo, apply_layers_premargin

    c, t, bs = args.channels, args.tile, args.batch
    layers = []
    for _ in range(args.fused):
        layers += [ReLU(), Conv2d(c, c, 3, bias=False), BatchNorm(c)]
    hh, hw = accumulated_halo(layers)

    key = jax.random.key(0)
    params = []
    shape = (bs, t, t, c)
    for i, l in enumerate(layers):
        pp, shape = l.init(jax.random.fold_in(key, i), shape)
        params.append(pp)

    # Margin-carrying tile (zero margin = a global-border tile of the
    # pad-once semantics — identical compute to any interior tile).
    x = jax.random.normal(
        jax.random.key(1), (bs, t + 2 * hh, t + 2 * hw, c), jnp.bfloat16
    )

    def make_step(use_pallas: bool):
        sp = SpatialCtx(
            axis_h="sph", axis_w="spw", grid_h=2, grid_w=2,
            bn_cross_tile=False, use_pallas_conv=use_pallas,
        )
        ctx = ApplyCtx(train=True, spatial=sp)

        def loss_fn(ps, x):
            y, mh, mw = apply_layers_premargin(layers, ps, x, ctx, hh, hw)
            assert mh == 0 and mw == 0, (mh, mw)
            return jnp.mean(jnp.square(y.astype(jnp.float32)))

        @jax.jit
        def step(ps, x):
            loss, grads = jax.value_and_grad(loss_fn)(ps, x)
            new = jax.tree.map(
                lambda p, g: (
                    p.astype(jnp.float32) - 0.001 * g.astype(jnp.float32)
                ).astype(p.dtype),
                ps, grads,
            )
            return new, loss

        return step

    def time_step(use_pallas: bool):
        # (the Pallas path auto-selects interpret mode on CPU hosts)
        step = make_step(use_pallas)
        ps = params
        t0 = time.perf_counter()
        for _ in range(args.warmup):
            ps, loss = step(ps, x)
        lval = float(loss)  # D2H sync (honest under the axon backend)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            ps, loss = step(ps, x)
        lval = float(loss)
        dt = (time.perf_counter() - t0) / args.iterations
        return dt, lval, compile_s

    dt_off, loss_off, c_off = time_step(False)
    dt_on, loss_on, c_on = time_step(True)
    rel = abs(loss_on - loss_off) / max(abs(loss_off), 1e-9)
    out = {
        "metric": "d2_step_pallas_speedup",
        "value": round(dt_off / dt_on, 4),
        "unit": "x (xla_step_ms / pallas_step_ms)",
        "config": {
            "tile": t, "channels": c, "fused_convs": args.fused,
            "batch": bs, "margin": [hh, hw],
        },
        "xla_step_ms": round(dt_off * 1e3, 3),
        "pallas_step_ms": round(dt_on * 1e3, 3),
        "validation": "pass" if rel < 0.05 else f"FAIL rel={rel:.3g}",
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
