"""Long-context attention microbenchmark — the 1-D (sequence) twin of the
halo tools (SURVEY §5 "long-context analog").

Times exact attention three ways at a given sequence length:

  einsum      — the materialized-scores reference (ops/ring.py einsum path)
  flash       — the Pallas blockwise kernel (ops/pallas_attention.py)
  ring        — ring_attention over an n-device mesh (CPU: validates the
                sharded schedule; real multi-chip: measures the ICI overlap)

and exact-validates flash and ring against the reference.  Beyond the
einsum path's memory wall (T² scores: 34 GB at T=32k, H=8) only flash
runs — pass --flash-only.

Examples:
  python benchmark_ring_attention.py --seq-len 8192 --heads 8 --dim 128
  python benchmark_ring_attention.py --seq-len 32768 --flash-only
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
      python benchmark_ring_attention.py --seq-len 1024 --ring-devices 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--causal", action="store_true", default=True)
    p.add_argument("--no-causal", dest="causal", action="store_false")
    p.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--iterations", type=int, default=20)
    p.add_argument("--flash-only", action="store_true",
                   help="skip the einsum reference (OOM territory)")
    p.add_argument("--ring-devices", type=int, default=0,
                   help="also run ring_attention over this many devices "
                        "(0 = skip; needs that many JAX devices)")
    p.add_argument("--interpret", action="store_true",
                   help="Pallas interpreter mode (CPU)")
    args = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.ops.pallas_attention import flash_attention_local
    from mpi4dl_tpu.ops.ring import ring_attention

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    b, t, h, d = args.batch, args.seq_len, args.heads, args.dim
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (b, t, h, d), dtype) for kk in ks)
    # attention flops: QK^T + PV, 2 matmuls x 2 flops/MAC
    flops = 4 * b * h * t * t * d
    if args.causal:
        flops //= 2

    def timed(fn, *xs):
        out = fn(*xs)
        float(jnp.sum(out[..., 0].astype(jnp.float32)))  # honest D2H sync
        for _ in range(args.warmup):
            out = fn(*xs)
        float(jnp.sum(out[..., 0].astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            out = fn(*xs)
        float(jnp.sum(out[..., 0].astype(jnp.float32)))
        dt = (time.perf_counter() - t0) / args.iterations
        return out, dt

    variants = {}
    flash = jax.jit(
        lambda q, k, v: flash_attention_local(
            q, k, v, causal=args.causal, interpret=args.interpret
        )
    )
    out_f, dt = timed(flash, q, k, v)
    variants["flash"] = {"ms": round(dt * 1e3, 3),
                         "tflops": round(flops / dt / 1e12, 2)}

    validation = None
    if not args.flash_only:
        ref = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, None, 1, causal=args.causal, use_flash=False
            )
        )
        out_r, dt = timed(ref, q, k, v)
        variants["einsum"] = {"ms": round(dt * 1e3, 3),
                              "tflops": round(flops / dt / 1e12, 2)}
        validation = bool(np.allclose(
            np.asarray(out_f, np.float32), np.asarray(out_r, np.float32),
            rtol=0.05, atol=0.05,
        ))

    if args.ring_devices > 1:
        from mpi4dl_tpu.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from mpi4dl_tpu.mesh import MeshSpec, build_mesh

        n = args.ring_devices
        mesh = build_mesh(MeshSpec(spw=n), jax.devices()[:n])
        spec = P(None, "spw", None, None)
        ring = jax.jit(
            shard_map(
                lambda a, bb, c: ring_attention(
                    a, bb, c, "spw", n, causal=args.causal,
                    interpret=args.interpret,
                ),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            )
        )
        out_ring, dt = timed(ring, q, k, v)
        variants["ring"] = {"ms": round(dt * 1e3, 3),
                            "tflops": round(flops / dt / 1e12, 2),
                            "devices": n}
        if not args.flash_only:
            validation = validation and bool(np.allclose(
                np.asarray(out_ring, np.float32),
                np.asarray(out_r, np.float32), rtol=0.05, atol=0.05,
            ))

    out = {
        "metric": "exact_attention_ms",
        "value": variants["flash"]["ms"],
        "unit": "ms",
        "config": {"seq_len": t, "heads": h, "dim": d, "batch": b,
                   "causal": args.causal, "dtype": args.dtype},
        "variants": variants,
        "flops_per_call": flops,
        "validation": (
            "skipped" if validation is None
            else ("pass" if validation else "FAIL")
        ),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    return 0 if validation in (None, True) else 1


if __name__ == "__main__":
    raise SystemExit(main())
