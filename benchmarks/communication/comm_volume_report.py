"""Per-step communication volume of the compiled SPMD programs.

Multi-chip hardware is not reachable from this environment, but the
collectives XLA actually schedules are: this tool compiles a training step
for each engine on a virtual mesh and reports, from the compiled HLO, the
number of collective ops and the bytes they move per step — the
compiler-derived counterpart of the reference's MPI message accounting
(SURVEY §2a "comm backend" row; the reference exchanges per-conv halos via
9-neighbour tagged p2p, per-stage activations via send/recv, and whole
flat parameter buffers for GEMS MASTER-OPT).

Collective classes counted: collective-permute (halo exchange, pipeline
handoffs, GEMS mirror), all-reduce (DP gradients, cross-tile BN),
all-gather / reduce-scatter / all-to-all (junctions, GSPMD resharding).

Example (8 virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \\
      python benchmarks/communication/comm_volume_report.py --image-size 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0,
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
)

# The parsing lives in the obs library now (ISSUE 2: collective accounting
# as a reusable capability, not a script); re-exported here so existing
# imports of this tool keep working.
from mpi4dl_tpu.obs.hlo_stats import hlo_collective_stats  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--halo-d2", action="store_true")
    args = p.parse_args(argv)

    import jax

    # Pure host-side HLO analysis — always run on a deterministic 8-virtual-
    # device CPU backend.  Must precede the first backend query (after
    # jax.devices() these config updates no longer take effect).
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception as e:  # already initialized (e.g. under pytest)
        if len(jax.devices()) < 8:
            raise SystemExit(
                "needs 8 devices: run with XLA_FLAGS="
                "--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu"
            ) from e

    import jax.numpy as jnp

    devices = jax.devices()[:8]

    from mpi4dl_tpu.layer_ctx import SpatialCtx
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import StagePartition
    from mpi4dl_tpu.parallel.pipeline import (
        init_pipeline_state, make_pipeline_train_step,
    )
    from mpi4dl_tpu.parallel.gems import make_gems_train_step
    from mpi4dl_tpu.train import Optimizer, TrainState, make_spatial_train_step

    px = args.image_size
    bs = args.batch_size
    model = get_resnet_v2((bs, px, px, 3), depth=11, num_classes=10)
    params, _ = model.init(jax.random.key(0))
    opt = Optimizer("sgd", lr=0.01)
    report = {}

    def compiled_text(step, *step_args):
        return jax.jit(step).lower(*step_args).compile().as_text()

    # SP: 4-tile vertical spatial step (per-conv D1 halos or fused D2)
    sp = SpatialCtx(axis_w="spw", grid_w=4, d2_mode=args.halo_d2)
    mesh_sp = build_mesh(MeshSpec(spw=4), devices[:4])
    sstep = make_spatial_train_step(
        model, opt, mesh_sp, sp, spatial_until=len(model.cells) - 1
    )
    state = TrainState.create(params, opt)
    x = jnp.zeros((bs, px, px, 3), jnp.float32)
    y = jnp.zeros((bs,), jnp.int32)
    report["sp_4tile" + ("_d2" if args.halo_d2 else "")] = hlo_collective_stats(
        compiled_text(sstep, state, x, y)
    )

    # PP: 4-stage GPipe pipeline, parts=2
    mesh_pp = build_mesh(MeshSpec(stage=4), devices[:4])
    part = StagePartition.build(model, params, 4, (1, px, px, 3))
    pstep = make_pipeline_train_step(part, opt, mesh_pp, parts=2)
    pstate = init_pipeline_state(part, params, opt, mesh_pp)
    xp = jnp.zeros((2, px, px, 3), jnp.float32)
    yp = jnp.zeros((2,), jnp.int32)
    report["pp_4stage"] = hlo_collective_stats(
        compiled_text(pstep, pstate, xp, yp)
    )

    # GEMS: bidirectional dual scan on the same 4-stage mesh
    gstep = make_gems_train_step(part, opt, mesh_pp, parts=2, times=1)
    gstate = init_pipeline_state(part, params, opt, mesh_pp)
    xg = jnp.zeros((4, px, px, 3), jnp.float32)
    yg = jnp.zeros((4,), jnp.int32)
    report["gems_4stage"] = hlo_collective_stats(
        compiled_text(gstep, gstate, xg, yg)
    )

    out = {
        "metric": "per_step_collective_bytes",
        "value": report[next(iter(report))]["total_bytes"],
        "unit": "bytes",
        "config": {"image_size": px, "batch_size": bs,
                   "halo_d2": args.halo_d2},
        "programs": report,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
