"""Compile-only peak-HBM probe: single-chip rung configs AND the PP engine
families.

Asks XLA (via ``compiled.memory_analysis()``) what a training step's peak
device memory is WITHOUT running it — the fast way to chart the memory
frontier (ResNet-110-v2 2048², AmoebaNet 3328²+) against the ~15.75 GB
usable HBM of a 16 GB chip, and to A/B memory levers (boundary packing,
remat grouping, pipeline schedules) without burning a full rung timeout per
point.

Single-chip rung (the original mode):

    python benchmarks/mem_probe.py --arch resnet --image-size 2048 \
        --num-layers 110 --remat sqrt --scan 1

PP engine families (``--family lp|gems|sp|gems_sp``) build the same train
step the benchmark runner would (benchmarks/common.build_train) on a
self-provisioned virtual mesh and emit one row per schedule —
``--schedule both`` is the gpipe-vs-1f1b peak-HBM table the 1F1B work is
judged by (docs/pipeline.md):

    python benchmarks/mem_probe.py --family lp --schedule both \
        --image-size 256 --num-layers 11 --split-size 2 --parts 8 --batch 8

``--telemetry-dir`` mirrors the table into a RunLog JSONL as a ``mem_probe``
record (rendered by ``python -m mpi4dl_tpu.obs report``); ``--require-1f1b-win``
exits 1 unless the 1f1b row's peak is strictly below gpipe's — the CI gate.

``--attribute`` adds the per-``obs.scope`` HBM breakdown (obs/hbm.py: which
scope owns the peak bytes, coverage metric, top buffers) and the analytical
timeline (obs/timeline.py) to every probed row — the microscope over the
aggregate number.  Gates: ``--min-coverage 0.9`` fails the run when less
than 90% of peak bytes attribute to named scopes; ``--require-attrib-top
sp_region,junction`` fails unless one of the named phase groups owns the
plurality of scoped peak bytes (the PR-5 "the memory lives in the spatial
phase + junction" finding, machine-checked in CI).

``--delta-parts N`` (family mode) probes the SAME config a second time at
``parts=N`` (micro-batch size held fixed, so the batch scales with parts)
and emits the per-scope growth between the two — the "+19.5 GB/device per
part" PR-5 finding as a first-class artifact: *which scope grows when parts
grow*.  ``--require-delta-top sp_region,junction`` exits 1 unless the
phase group with the largest positive growth matches one of the prefixes
(the CI gate: the O(parts) memory lives in the spatial phase + junction,
not the tail).

``--overlap`` adds the per-``obs.scope`` exposed-wire ledger (obs/overlap.py:
which collectives ride async start/done pairs and hide under scheduled
compute, which are sync/structurally exposed, wire-ms per scope and wire
class) to every probed row and mirrors it as an ``overlap`` RunLog record.
``--require-hidden-frac 0.5`` exits 1 when less than half the wire time is
hidden on any probed row — the CI gate the T3-style halo-RDMA work
(ROADMAP item 2) is judged by.  On the CPU backend every collective
compiles sync, so the virtual mesh honestly reports hidden 0% — the
baseline the overlap work must move.

``--sweep-junction`` sweeps the SP->LP junction placement (``spatial_until``)
for the sp family and emits the placement frontier — per-placement compiled
peak HBM plus the analytic spatial-activation ledger — as a BENCH-style JSON
artifact and a ``junction_sweep`` RunLog record (rendered by ``obs report``;
ROADMAP item 1's 370-vs-116.7 GB/device placement finding as a reproducible
artifact):

    python benchmarks/mem_probe.py --sweep-junction --image-size 64 \
        --num-layers 11 --split-size 2 --parts 2 --batch 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mem_row(compiled, compile_s: float) -> dict:
    ma = compiled.memory_analysis()
    row = {"compile_s": round(compile_s, 1)}
    for k in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            row[k] = int(v)
    temp = row.get("temp_size_in_bytes", 0)
    arg = row.get("argument_size_in_bytes", 0)
    alias = row.get("alias_size_in_bytes", 0)
    # Peak live ≈ args + temps (donated args counted once via alias).
    row["peak_gb_est"] = round((temp + arg - alias) / 2**30, 3)
    return row


def _attribution(compiled, args, schedule=None, hlo_text=None) -> dict:
    """The per-scope breakdown + analytical timeline of one compiled row
    (``--attribute``); printed to stderr, embedded in the JSON artifact."""
    import jax

    from mpi4dl_tpu.obs import analytical_timeline, attribute_compiled
    from mpi4dl_tpu.obs.hbm import format_breakdown

    if hlo_text is None:
        hlo_text = compiled.as_text()
    breakdown = attribute_compiled(compiled, hlo_text=hlo_text)
    timeline = analytical_timeline(
        hlo_text, device=jax.devices()[0],
        schedule=schedule, stages=getattr(args, "split_size", None),
        parts=getattr(args, "parts", None),
    )
    print(format_breakdown(breakdown), file=sys.stderr)
    return {"hbm": breakdown, "timeline": timeline}


def _overlap_row(compiled, hlo_text=None) -> dict:
    """The per-scope exposed-wire ledger of one compiled row
    (``--overlap``); printed to stderr, embedded in the JSON artifact and
    mirrored as an ``overlap`` RunLog record."""
    import jax

    from mpi4dl_tpu.obs import overlap_ledger
    from mpi4dl_tpu.obs.overlap import format_ledger

    ledger = overlap_ledger(
        hlo_text if hlo_text is not None else compiled.as_text(),
        device=jax.devices()[0],
    )
    print(format_ledger(ledger), file=sys.stderr)
    return ledger


def _probe_single(args) -> dict:
    from bench import build_probe_setup

    step, state, x, y = build_probe_setup(
        args.image_size, args.num_layers, args.num_filters, args.batch,
        remat=args.remat, scan=args.scan, arch=args.arch,
    )
    t0 = time.perf_counter()
    compiled = step.lower(state, x, y).compile()
    out = {
        "config": vars(args),
        **_mem_row(compiled, time.perf_counter() - t0),
    }
    # One serialization shared by both instruments: as_text() is the
    # dominant non-compile cost on large modules.
    hlo_text = compiled.as_text() if (args.attribute or args.overlap) \
        else None
    if args.attribute:
        out.update(_attribution(compiled, args, hlo_text=hlo_text))
    if args.overlap:
        out["overlap"] = _overlap_row(compiled, hlo_text)
    return out


def _probe_family(args) -> dict:
    """One row per schedule for a PP engine family, built exactly as the
    benchmark runner builds it (same cfg vocabulary, same mesh math)."""
    import jax

    from benchmarks.common import _ensure_devices, build_train
    from mpi4dl_tpu.config import ParallelConfig, _spatial_until_arg
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh

    schedules = (
        ["gpipe", "1f1b"] if args.schedule == "both" else [args.schedule]
    )
    rows = {}
    spec = None
    for schedule in schedules:
        cfg = ParallelConfig(
            model=args.arch if args.arch != "amoeba" else "amoebanet",
            batch_size=args.batch,
            parts=args.parts,
            split_size=args.split_size,
            schedule=schedule,
            # The engines checkpoint whole stages, so the single-chip remat
            # vocabulary collapses to on/off here; --scan is a single-chip
            # rung knob with no family equivalent (both recorded effective
            # below so the table says what was actually probed).
            remat=args.remat != "none",
            times=args.times,
            spatial_size=args.spatial_size,
            num_spatial_parts=(args.num_spatial_parts,),
            image_size=args.image_size,
            num_layers=args.num_layers,
            num_filters=args.num_filters,
            num_classes=args.num_classes,
            quant_collectives=args.quant,
            spatial_until=_spatial_until_arg(
                getattr(args, "spatial_until", None)
            ),
            slice_method=getattr(args, "slice_method", "square"),
        )
        spec = (
            MeshSpec.from_config(cfg)
            if args.family in ("sp", "gems_sp")
            else MeshSpec(stage=max(cfg.split_size, 1))
        )
        _ensure_devices(spec.size)
        mesh = build_mesh(spec, jax.devices()[:spec.size])
        step, state, _, global_batch = build_train(cfg, args.family, mesh)
        import jax.numpy as jnp

        x = jnp.zeros(
            (global_batch, args.image_size, args.image_size, 3), jnp.float32
        )
        y = jnp.zeros((global_batch,), jnp.int32)
        t0 = time.perf_counter()
        compiled = step.lower(state, x, y).compile()
        rows[schedule] = _mem_row(compiled, time.perf_counter() - t0)
        hlo_text = compiled.as_text() if (args.attribute or args.overlap) \
            else None
        if args.attribute:
            rows[schedule].update(
                _attribution(compiled, args, schedule, hlo_text=hlo_text)
            )
        if args.overlap:
            rows[schedule]["overlap"] = _overlap_row(compiled, hlo_text)
        print(
            f"[mem_probe] {args.family}/{schedule}: "
            f"{rows[schedule]['peak_gb_est']} GB peak "
            f"({rows[schedule]['compile_s']}s compile)",
            file=sys.stderr,
        )
    out = {
        "metric": "mem_probe_peak_gb",
        "family": args.family,
        "mesh": str(spec),
        "config": {**vars(args), "remat": args.remat != "none", "scan": None},
        "schedules": rows,
    }
    if len(rows) == 2:
        g, f = rows["gpipe"]["peak_gb_est"], rows["1f1b"]["peak_gb_est"]
        out["win_1f1b_gb"] = round(g - f, 3)
        out["table"] = (
            f"schedule  peak_gb\ngpipe     {g}\n1f1b      {f}\n"
            f"1f1b win  {round(g - f, 3)} GB"
        )
    return out


def _sweep_junction(args) -> dict:
    """Junction-placement frontier: compile the sp engine at each candidate
    ``spatial_until`` and record peak HBM per placement (ROADMAP item 1's
    placement search — naive placement measured 370 vs 116.7 GB/device at
    8K; this makes the frontier a reproducible artifact at any size)."""
    import jax
    import jax.numpy as jnp

    from benchmarks.common import _ensure_devices
    from mpi4dl_tpu.layer_ctx import SpatialCtx
    from mpi4dl_tpu.mesh import AXIS_SPW, MeshSpec, build_mesh
    from mpi4dl_tpu.models.amoebanet import amoebanetd
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.sp_pipeline import (
        SPPipeline, init_sp_pipeline_state, make_sp_pipeline_train_step,
    )
    from mpi4dl_tpu.train import Optimizer

    S, g, px = args.split_size, args.num_spatial_parts, args.image_size
    micro = args.batch // args.parts
    assert micro >= 1, "batch must cover parts"
    # Before any jax op (model.init below) initializes the backend.
    _ensure_devices(S * g)
    shape = (micro, px, px, 3)
    if args.arch == "resnet":
        model = get_resnet_v2(shape, depth=args.num_layers,
                              num_classes=args.num_classes)
    else:
        model = amoebanetd(shape, num_classes=args.num_classes,
                           num_layers=args.num_layers,
                           num_filters=args.num_filters)
    params, shapes = model.init(jax.random.key(0))
    n_cells = len(model.cells)

    if args.junction_levels:
        asked = [int(s) for s in args.junction_levels.split(",")]
        # At least one spatial cell, at least one tail cell (the head can
        # never run tiled) — out-of-range candidates are dropped, not
        # crashed on (the fixed CI list must survive model-size changes).
        levels = [su for su in asked if 1 <= su <= n_cells - 1]
        if levels != asked:
            print(
                f"[mem_probe] note: junction levels {asked} clamped to "
                f"legal placements {levels} ({n_cells}-cell model)",
                file=sys.stderr,
            )
        assert levels, f"no legal junction level in {asked}"
    else:
        # Every legal placement: at least one spatial cell, at least one
        # tail cell (the head can never run tiled).
        levels = list(range(1, n_cells - 1))
    mesh = build_mesh(MeshSpec(stage=S, spw=g), jax.devices()[:S * g])
    sp = SpatialCtx(axis_w=AXIS_SPW, grid_w=g)
    opt = Optimizer("sgd", lr=0.01)
    x = jnp.zeros((args.parts * micro, px, px, 3), jnp.float32)
    y = jnp.zeros((args.parts * micro,), jnp.int32)

    placements = []
    for su in levels:
        model.spatial_until = su
        # Analytic spatial-activation ledger (eval_shape bytes, tiled by
        # the grid) — monotone in placement by construction; the compiled
        # peak is the measured counterpart.
        spatial_mb = 0.0
        for i, shp in enumerate(shapes[:su]):
            shps = shp if isinstance(shp[0], tuple) else (shp,)
            for s in shps:
                n = 1
                for d in s:
                    n *= d
                spatial_mb += n * 4 / g / 2**20
        from mpi4dl_tpu.quant import QuantPolicy

        spp = SPPipeline.build(model, params, S, sp, microbatch=micro,
                               junction="gather")
        step = make_sp_pipeline_train_step(
            spp, opt, mesh, parts=args.parts,
            remat=args.remat != "none", schedule=(
                args.schedule if args.schedule != "both" else "gpipe"
            ),
            quant=QuantPolicy.resolve(args.quant),
        )
        state = init_sp_pipeline_state(spp, params, opt, mesh)
        t0 = time.perf_counter()
        compiled = step.lower(state, x, y).compile()
        row = _mem_row(compiled, time.perf_counter() - t0)
        entry = {
            "spatial_until": su,
            "spatial_cells": su,
            "tail_cells": n_cells - su,
            "spatial_ledger_mb": round(spatial_mb, 2),
            **row,
        }
        hlo_text = compiled.as_text() if (args.attribute or args.overlap) \
            else None
        if args.attribute:
            entry.update(_attribution(compiled, args, hlo_text=hlo_text))
        if args.overlap:
            entry["overlap"] = _overlap_row(compiled, hlo_text)
        placements.append(entry)
        print(
            f"[mem_probe] sweep spatial_until={su}: "
            f"{row['peak_gb_est']} GB peak ({row['compile_s']}s compile)",
            file=sys.stderr,
        )
    best = min(placements, key=lambda p: p["peak_gb_est"])
    for p in placements:
        p["best"] = p is best
    # "Naive" = the deepest spatial region probed (ROADMAP item 1's config
    # A), regardless of the order --junction-levels listed the candidates.
    naive = max(placements, key=lambda p: p["spatial_until"])
    # The analytical chooser's pick, recorded next to the compiled frontier
    # so the --spatial-until auto default stays validated by the sweep.
    from mpi4dl_tpu.parallel.spatial import choose_spatial_until

    auto_su = choose_spatial_until(shapes, g, itemsize=4)
    auto_row = next(
        (p for p in placements if p["spatial_until"] == auto_su), None
    )
    auto_choice = {
        "spatial_until": auto_su,
        "in_probed_frontier": auto_row is not None,
        "peak_gb_est": auto_row["peak_gb_est"] if auto_row else None,
        "over_best": (
            round(auto_row["peak_gb_est"] / best["peak_gb_est"], 3)
            if auto_row and best["peak_gb_est"] else None
        ),
    }
    return {
        "metric": "junction_frontier_peak_gb",
        "value": best["peak_gb_est"],
        "unit": "GB/device",
        "family": "sp",
        "mesh": str(MeshSpec(stage=S, spw=g)),
        "config": {**vars(args), "remat": args.remat != "none"},
        "placements": placements,
        "best": {k: best[k] for k in ("spatial_until", "peak_gb_est")},
        "naive": {k: naive[k] for k in ("spatial_until", "peak_gb_est")},
        "auto_choice": auto_choice,
        "naive_over_best": (
            round(naive["peak_gb_est"] / best["peak_gb_est"], 3)
            if best["peak_gb_est"] else None
        ),
    }


def growth_groups(bd_a: dict, bd_b: dict, parts_a: int, parts_b: int) -> dict:
    """Per-phase-group byte growth between two breakdowns of the same config
    at different part counts, normalized per part: ``{group: bytes/part}``
    sorted by growth.  Pure (unit-tested in tests/test_hbm.py)."""
    from mpi4dl_tpu.obs.hbm import scope_group_bytes

    ga, gb = scope_group_bytes(bd_a), scope_group_bytes(bd_b)
    dparts = parts_b - parts_a
    if dparts <= 0:
        raise ValueError(f"need parts_b > parts_a, got {parts_a}->{parts_b}")
    out = {
        k: (gb.get(k, 0) - ga.get(k, 0)) / dparts
        for k in set(ga) | set(gb)
        if gb.get(k, 0) != ga.get(k, 0)
    }
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def top_growth_group(growth: dict) -> "str | None":
    """The phase group with the largest positive per-part growth (arguments
    and unattributed excluded — the question is which *phase* owns the
    O(parts) bytes)."""
    from mpi4dl_tpu.obs.hbm import ARGS_SCOPE, UNATTRIBUTED

    for k, v in growth.items():  # sorted descending
        if k == UNATTRIBUTED or k.startswith(ARGS_SCOPE):
            continue
        return k if v > 0 else None
    return None


def _parts_delta(args, out) -> dict:
    """Probe the family again at ``--delta-parts`` (same micro-batch size)
    and attach the per-scope growth ledger to the artifact."""
    import argparse as _ap

    from mpi4dl_tpu.obs.hbm import compare_breakdowns

    micro = max(args.batch // args.parts, 1)
    args_b = _ap.Namespace(**{
        **vars(args),
        "parts": args.delta_parts,
        "batch": micro * args.delta_parts,
        "delta_parts": None,
        "telemetry_dir": None,
    })
    out_b = _probe_family(args_b)
    delta = {
        "parts_a": args.parts, "parts_b": args.delta_parts,
        "micro_batch": micro, "per_schedule": {},
    }
    for sched, row in out["schedules"].items():
        row_b = (out_b["schedules"] or {}).get(sched)
        if not (row.get("hbm") and row_b and row_b.get("hbm")):
            continue
        growth = growth_groups(
            row["hbm"], row_b["hbm"], args.parts, args.delta_parts
        )
        dparts = args.delta_parts - args.parts
        delta["per_schedule"][sched] = {
            "growth_bytes_per_part": growth,
            "top_growth_group": top_growth_group(growth),
            "peak_delta_bytes": compare_breakdowns(
                row["hbm"], row_b["hbm"]
            )["peak_delta_bytes"],
            # The compiled (memory_analysis) per-part slope — the number the
            # --require-delta-slope ceiling gates; the growth ledger above
            # rides the attribution ESTIMATE and only names the owner.
            "peak_slope_gb_per_part": round(
                (row_b["peak_gb_est"] - row["peak_gb_est"]) / dparts, 3
            ),
        }
        print(
            f"[mem_probe] {args.family}/{sched} growth "
            f"parts {args.parts}->{args.delta_parts} (bytes/part):",
            file=sys.stderr,
        )
        for k, v in list(growth.items())[:8]:
            print(f"  {v / 2**20:>10.1f} MB/part  {k}", file=sys.stderr)
    return delta


def _check_gates(args, rows) -> int:
    """--min-coverage / --require-attrib-top over every attributed row;
    returns the number of gate failures (each reported on stderr)."""
    from mpi4dl_tpu.obs.hbm import scope_group_bytes, ARGS_SCOPE, UNATTRIBUTED

    failures = 0
    for label, row in rows:
        bd = row.get("hbm")
        if bd is None:
            continue
        if args.min_coverage is not None and bd["coverage"] < args.min_coverage:
            print(
                f"[mem_probe] FAIL {label}: coverage {bd['coverage']:.3f} "
                f"< --min-coverage {args.min_coverage}",
                file=sys.stderr,
            )
            failures += 1
        if args.require_attrib_top:
            prefixes = tuple(
                s.strip() for s in args.require_attrib_top.split(",") if s.strip()
            )
            groups = scope_group_bytes(bd)
            phase = next(
                (k for k in groups
                 if k != UNATTRIBUTED and not k.startswith(ARGS_SCOPE)),
                None,
            )
            if phase is None or not any(phase.startswith(p) for p in prefixes):
                print(
                    f"[mem_probe] FAIL {label}: plurality scope group "
                    f"{phase!r} does not match --require-attrib-top "
                    f"{prefixes} (groups: "
                    f"{ {k: v for k, v in list(groups.items())[:4]} })",
                    file=sys.stderr,
                )
                failures += 1
            else:
                print(
                    f"[mem_probe] OK {label}: plurality scope group {phase!r}"
                    f" owns {groups[phase]} bytes at peak",
                    file=sys.stderr,
                )
    return failures


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=2048)
    p.add_argument("--num-layers", type=int, default=110)
    p.add_argument("--num-filters", type=int, default=416)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--remat", default="sqrt",
                   choices=["none", "cell", "fine", "sqrt"])
    p.add_argument("--arch", default="resnet", choices=["amoeba", "resnet"])
    p.add_argument("--scan", type=int, default=1)
    p.add_argument("--family", default="single",
                   choices=["single", "lp", "gems", "sp", "sp_pipeline",
                            "gems_sp"],
                   help="'single' probes a one-chip rung (bench.py path); "
                        "the engine families probe the PP train step on a "
                        "virtual mesh ('sp_pipeline' is an alias for 'sp')")
    p.add_argument("--schedule", default="both",
                   choices=["gpipe", "1f1b", "both"],
                   help="pipeline schedule(s) to probe (family mode)")
    p.add_argument("--split-size", type=int, default=2)
    p.add_argument("--parts", type=int, default=4)
    p.add_argument("--times", type=int, default=1)
    p.add_argument("--spatial-size", type=int, default=1)
    p.add_argument("--num-spatial-parts", type=int, default=2)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--spatial-until", default=None, metavar="N|auto",
                   help="SP->LP junction placement for the probed engines "
                        "(an explicit cell index or 'auto' — the flag the "
                        "supervisor's degrade planner probes through; "
                        "family mode only)")
    p.add_argument("--slice-method", default="square",
                   choices=["square", "vertical", "horizontal"],
                   help="spatial slicing of the probed engines (the probe "
                        "must build the SAME tile grid the relaunch would)")
    p.add_argument("--quant", default="off", metavar="SPEC",
                   help="quantized-collective policy for the probed engines "
                        "(off | int8|fp8|int4 | per-class spec; "
                        "docs/quantization.md) — pair with --overlap to "
                        "read the quantized wire per rung")
    p.add_argument("--telemetry-dir", default=None,
                   help="mirror the result into a RunLog JSONL as a "
                        "mem_probe record (docs/observability.md)")
    p.add_argument("--require-1f1b-win", action="store_true",
                   help="exit 1 unless 1f1b peak < gpipe peak (needs "
                        "--schedule both)")
    p.add_argument("--attribute", action="store_true",
                   help="add the per-obs.scope HBM breakdown + analytical "
                        "timeline to every probed row (obs/hbm.py, "
                        "obs/timeline.py; docs/observability.md)")
    p.add_argument("--overlap", action="store_true",
                   help="add the per-obs.scope exposed-wire ledger to every "
                        "probed row (obs/overlap.py: async start/done "
                        "windows vs sync collectives in the compiled "
                        "schedule; docs/observability.md)")
    p.add_argument("--require-hidden-frac", type=float, default=None,
                   metavar="FRAC",
                   help="with --overlap: exit 1 when less than this "
                        "fraction of wire time is hidden under compute on "
                        "any probed row (rows that move no collective "
                        "bytes pass)")
    p.add_argument("--min-coverage", type=float, default=None,
                   help="with --attribute: exit 1 when less than this "
                        "fraction of peak bytes attributes to named scopes")
    p.add_argument("--require-attrib-top", default=None,
                   help="with --attribute: exit 1 unless the plurality "
                        "scope group at peak starts with one of these "
                        "comma-separated prefixes (e.g. 'sp_region,junction')")
    p.add_argument("--delta-parts", type=int, default=None,
                   help="with --attribute in family mode: probe the same "
                        "config again at this part count (micro-batch held "
                        "fixed) and emit the per-scope O(parts) growth "
                        "ledger — the PR-5 '+GB/device per part' finding "
                        "as an artifact")
    p.add_argument("--require-delta-top", default=None,
                   help="with --delta-parts: exit 1 unless the phase group "
                        "with the largest positive per-part growth starts "
                        "with one of these comma-separated prefixes "
                        "(e.g. 'sp_region,junction,stage_lineup')")
    p.add_argument("--require-delta-slope", type=float, default=None,
                   metavar="GB",
                   help="with --delta-parts: exit 1 when the TOTAL per-part "
                        "peak-HBM slope exceeds this many GB/device/part on "
                        "any probed schedule — the stripe-backward O(parts) "
                        "buy-back's regression ceiling (docs/pipeline.md)")
    p.add_argument("--stripe-bwd", action="store_true",
                   help="sets MPI4DL_STRIPE_BWD=1 for the probed engines: "
                        "stripe-wise backward through eligible blocks "
                        "(ops/stripe_bwd.py)")
    p.add_argument("--sweep-junction", action="store_true",
                   help="sweep the SP->LP junction placement (spatial_until)"
                        " and emit the placement frontier artifact")
    p.add_argument("--junction-levels", default=None,
                   help="comma-separated spatial_until candidates for "
                        "--sweep-junction (default: every legal placement)")
    p.add_argument("--out", default=None, help="also write the JSON here")
    args = p.parse_args(argv)
    if args.family == "sp_pipeline":
        args.family = "sp"
    if args.delta_parts is not None and (
        args.sweep_junction or args.family == "single"
    ):
        print("[mem_probe] --delta-parts needs an engine family "
              "(--family lp|gems|sp|gems_sp, no --sweep-junction)",
              file=sys.stderr)
        return 2
    # Attribution gates without --attribute would silently check nothing;
    # fail at parse time, before any compile is paid for.
    if not args.attribute and (
        args.min_coverage is not None or args.require_attrib_top
        or args.delta_parts is not None or args.require_delta_top
        or args.require_delta_slope is not None
    ):
        print("[mem_probe] --min-coverage/--require-attrib-top/"
              "--delta-parts/--require-delta-top/--require-delta-slope "
              "need --attribute", file=sys.stderr)
        return 2
    if args.require_delta_slope is not None and args.delta_parts is None:
        print("[mem_probe] --require-delta-slope needs --delta-parts "
              "(the slope is measured between the two part counts)",
              file=sys.stderr)
        return 2
    if args.stripe_bwd:
        os.environ["MPI4DL_STRIPE_BWD"] = "1"
    if args.require_hidden_frac is not None and not args.overlap:
        print("[mem_probe] --require-hidden-frac needs --overlap",
              file=sys.stderr)
        return 2

    import jax

    if args.attribute or args.overlap:
        # The persistent compilation cache keys on the program MINUS debug
        # metadata; a scope-less executable compiled elsewhere (e.g. an
        # MPI4DL_NO_SCOPES A/B run) would alias this build and return HLO
        # text without op_name paths — attribution and the overlap ledger
        # both require a fresh compile.
        jax.config.update("jax_compilation_cache_dir", None)

    # Careful not to touch jax.devices() before a mesh mode self-provisions
    # the virtual CPU platform (backend init is one-shot).
    single = args.family == "single" and not args.sweep_junction
    print(f"[mem_probe] device={jax.devices()[0] if single else 'virtual mesh'}",
          file=sys.stderr)
    if args.sweep_junction:
        out = _sweep_junction(args)
        gate_rows = [(f"su={p_['spatial_until']}", p_)
                     for p_ in out["placements"]]
    elif args.family == "single":
        out = _probe_single(args)
        gate_rows = [("single", out)]
    else:
        out = _probe_family(args)
        gate_rows = [(f"{args.family}/{s}", r)
                     for s, r in out["schedules"].items()]
        if args.delta_parts is not None:
            out["parts_delta"] = _parts_delta(args, out)

    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)
    if args.telemetry_dir:
        from mpi4dl_tpu.obs import RunLog

        runlog = RunLog.create(args.telemetry_dir, prefix="mem_probe")
        runlog.write_meta(config=out.get("config") or vars(args),
                          family=args.family,
                          argv=list(argv) if argv is not None else sys.argv[1:])
        if args.sweep_junction:
            runlog.write("junction_sweep", placements=out["placements"],
                         best=out["best"], naive=out["naive"],
                         naive_over_best=out["naive_over_best"])
        else:
            runlog.write("mem_probe", **out)
        for label, row in gate_rows:
            if row.get("hbm") is not None:
                runlog.write("hbm", label=label, breakdown=row["hbm"])
            if row.get("timeline") is not None:
                runlog.write("timeline", label=label, **row["timeline"])
            if row.get("overlap") is not None:
                runlog.write("overlap", label=label, **row["overlap"])
        runlog.close()
        print(f"[mem_probe] telemetry written to {runlog.path}",
              file=sys.stderr)
    if args.attribute and (args.min_coverage is not None
                           or args.require_attrib_top):
        if _check_gates(args, gate_rows):
            return 1
    if args.require_hidden_frac is not None:
        fails = 0
        for label, row in gate_rows:
            led = row.get("overlap")
            if led is None:
                continue
            # Rows that move no collective bytes have nothing to hide.
            hf = led.get("hidden_frac")
            if hf is None:
                continue
            if hf < args.require_hidden_frac:
                t = led["totals"]
                print(
                    f"[mem_probe] FAIL {label}: hidden wire fraction "
                    f"{hf:.3f} < --require-hidden-frac "
                    f"{args.require_hidden_frac} (exposed "
                    f"{t['exposed_ms']} ms of {t['wire_ms']} ms wire; "
                    f"sync collectives {t['sync']})",
                    file=sys.stderr,
                )
                fails += 1
            else:
                print(
                    f"[mem_probe] OK {label}: hidden wire fraction {hf:.3f}",
                    file=sys.stderr,
                )
        if fails:
            return 1
    if args.require_delta_top:
        prefixes = tuple(s.strip() for s in args.require_delta_top.split(",")
                         if s.strip())
        fails = 0
        for sched, d in (out.get("parts_delta") or {}).get(
            "per_schedule", {}
        ).items():
            topg = d.get("top_growth_group")
            if topg is None or not any(topg.startswith(p_) for p_ in prefixes):
                print(
                    f"[mem_probe] FAIL {args.family}/{sched}: top O(parts) "
                    f"growth group {topg!r} does not match "
                    f"--require-delta-top {prefixes}",
                    file=sys.stderr,
                )
                fails += 1
            else:
                gbp = d["growth_bytes_per_part"][topg] / 2**30
                print(
                    f"[mem_probe] OK {args.family}/{sched}: O(parts) memory "
                    f"lives in {topg!r} ({gbp:.3f} GB/device/part)",
                    file=sys.stderr,
                )
        if fails or not (out.get("parts_delta") or {}).get("per_schedule"):
            if not fails:
                print("[mem_probe] FAIL: --require-delta-top with no "
                      "parts-delta rows (need --delta-parts + --attribute "
                      "in family mode)", file=sys.stderr)
            return 1
    if args.require_delta_slope is not None:
        rows_d = (out.get("parts_delta") or {}).get("per_schedule") or {}
        fails = 0
        for sched, d in rows_d.items():
            slope = d.get("peak_slope_gb_per_part")
            if slope is None or slope > args.require_delta_slope:
                print(
                    f"[mem_probe] FAIL {args.family}/{sched}: per-part "
                    f"peak-HBM slope {slope} GB/part exceeds "
                    f"--require-delta-slope {args.require_delta_slope}",
                    file=sys.stderr,
                )
                fails += 1
            else:
                print(
                    f"[mem_probe] OK {args.family}/{sched}: per-part "
                    f"peak-HBM slope {slope} GB/part <= "
                    f"{args.require_delta_slope}",
                    file=sys.stderr,
                )
        if fails or not rows_d:
            if not rows_d:
                print("[mem_probe] FAIL: --require-delta-slope with no "
                      "parts-delta rows", file=sys.stderr)
            return 1
    if args.require_1f1b_win:
        win = out.get("win_1f1b_gb")
        if win is None or win <= 0:
            print(
                f"[mem_probe] FAIL: 1f1b does not win (win_1f1b_gb={win})",
                file=sys.stderr,
            )
            return 1
        print(f"[mem_probe] OK: 1f1b wins by {win} GB", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
