"""Compile-only peak-HBM probe: single-chip rung configs AND the PP engine
families.

Asks XLA (via ``compiled.memory_analysis()``) what a training step's peak
device memory is WITHOUT running it — the fast way to chart the memory
frontier (ResNet-110-v2 2048², AmoebaNet 3328²+) against the ~15.75 GB
usable HBM of a 16 GB chip, and to A/B memory levers (boundary packing,
remat grouping, pipeline schedules) without burning a full rung timeout per
point.

Single-chip rung (the original mode):

    python benchmarks/mem_probe.py --arch resnet --image-size 2048 \
        --num-layers 110 --remat sqrt --scan 1

PP engine families (``--family lp|gems|sp|gems_sp``) build the same train
step the benchmark runner would (benchmarks/common.build_train) on a
self-provisioned virtual mesh and emit one row per schedule —
``--schedule both`` is the gpipe-vs-1f1b peak-HBM table the 1F1B work is
judged by (docs/pipeline.md):

    python benchmarks/mem_probe.py --family lp --schedule both \
        --image-size 256 --num-layers 11 --split-size 2 --parts 8 --batch 8

``--telemetry-dir`` mirrors the table into a RunLog JSONL as a ``mem_probe``
record (rendered by ``python -m mpi4dl_tpu.obs report``); ``--require-1f1b-win``
exits 1 unless the 1f1b row's peak is strictly below gpipe's — the CI gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mem_row(compiled, compile_s: float) -> dict:
    ma = compiled.memory_analysis()
    row = {"compile_s": round(compile_s, 1)}
    for k in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            row[k] = int(v)
    temp = row.get("temp_size_in_bytes", 0)
    arg = row.get("argument_size_in_bytes", 0)
    alias = row.get("alias_size_in_bytes", 0)
    # Peak live ≈ args + temps (donated args counted once via alias).
    row["peak_gb_est"] = round((temp + arg - alias) / 2**30, 3)
    return row


def _probe_single(args) -> dict:
    from bench import build_probe_setup

    step, state, x, y = build_probe_setup(
        args.image_size, args.num_layers, args.num_filters, args.batch,
        remat=args.remat, scan=args.scan, arch=args.arch,
    )
    t0 = time.perf_counter()
    compiled = step.lower(state, x, y).compile()
    return {
        "config": vars(args),
        **_mem_row(compiled, time.perf_counter() - t0),
    }


def _probe_family(args) -> dict:
    """One row per schedule for a PP engine family, built exactly as the
    benchmark runner builds it (same cfg vocabulary, same mesh math)."""
    import jax

    from benchmarks.common import _ensure_devices, build_train
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh

    schedules = (
        ["gpipe", "1f1b"] if args.schedule == "both" else [args.schedule]
    )
    rows = {}
    spec = None
    for schedule in schedules:
        cfg = ParallelConfig(
            model=args.arch if args.arch != "amoeba" else "amoebanet",
            batch_size=args.batch,
            parts=args.parts,
            split_size=args.split_size,
            schedule=schedule,
            # The engines checkpoint whole stages, so the single-chip remat
            # vocabulary collapses to on/off here; --scan is a single-chip
            # rung knob with no family equivalent (both recorded effective
            # below so the table says what was actually probed).
            remat=args.remat != "none",
            times=args.times,
            spatial_size=args.spatial_size,
            num_spatial_parts=(args.num_spatial_parts,),
            image_size=args.image_size,
            num_layers=args.num_layers,
            num_filters=args.num_filters,
            num_classes=args.num_classes,
        )
        spec = (
            MeshSpec.from_config(cfg)
            if args.family in ("sp", "gems_sp")
            else MeshSpec(stage=max(cfg.split_size, 1))
        )
        _ensure_devices(spec.size)
        mesh = build_mesh(spec, jax.devices()[:spec.size])
        step, state, _, global_batch = build_train(cfg, args.family, mesh)
        import jax.numpy as jnp

        x = jnp.zeros(
            (global_batch, args.image_size, args.image_size, 3), jnp.float32
        )
        y = jnp.zeros((global_batch,), jnp.int32)
        t0 = time.perf_counter()
        compiled = step.lower(state, x, y).compile()
        rows[schedule] = _mem_row(compiled, time.perf_counter() - t0)
        print(
            f"[mem_probe] {args.family}/{schedule}: "
            f"{rows[schedule]['peak_gb_est']} GB peak "
            f"({rows[schedule]['compile_s']}s compile)",
            file=sys.stderr,
        )
    out = {
        "metric": "mem_probe_peak_gb",
        "family": args.family,
        "mesh": str(spec),
        "config": {**vars(args), "remat": args.remat != "none", "scan": None},
        "schedules": rows,
    }
    if len(rows) == 2:
        g, f = rows["gpipe"]["peak_gb_est"], rows["1f1b"]["peak_gb_est"]
        out["win_1f1b_gb"] = round(g - f, 3)
        out["table"] = (
            f"schedule  peak_gb\ngpipe     {g}\n1f1b      {f}\n"
            f"1f1b win  {round(g - f, 3)} GB"
        )
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=2048)
    p.add_argument("--num-layers", type=int, default=110)
    p.add_argument("--num-filters", type=int, default=416)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--remat", default="sqrt",
                   choices=["none", "cell", "fine", "sqrt"])
    p.add_argument("--arch", default="resnet", choices=["amoeba", "resnet"])
    p.add_argument("--scan", type=int, default=1)
    p.add_argument("--family", default="single",
                   choices=["single", "lp", "gems", "sp", "gems_sp"],
                   help="'single' probes a one-chip rung (bench.py path); "
                        "the engine families probe the PP train step on a "
                        "virtual mesh")
    p.add_argument("--schedule", default="both",
                   choices=["gpipe", "1f1b", "both"],
                   help="pipeline schedule(s) to probe (family mode)")
    p.add_argument("--split-size", type=int, default=2)
    p.add_argument("--parts", type=int, default=4)
    p.add_argument("--times", type=int, default=1)
    p.add_argument("--spatial-size", type=int, default=1)
    p.add_argument("--num-spatial-parts", type=int, default=2)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--telemetry-dir", default=None,
                   help="mirror the result into a RunLog JSONL as a "
                        "mem_probe record (docs/observability.md)")
    p.add_argument("--require-1f1b-win", action="store_true",
                   help="exit 1 unless 1f1b peak < gpipe peak (needs "
                        "--schedule both)")
    p.add_argument("--out", default=None, help="also write the JSON here")
    args = p.parse_args(argv)

    import jax

    print(f"[mem_probe] device={jax.devices()[0] if args.family == 'single' else 'virtual mesh'}",
          file=sys.stderr)
    if args.family == "single":
        out = _probe_single(args)
    else:
        out = _probe_family(args)

    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line)
    if args.telemetry_dir:
        from mpi4dl_tpu.obs import RunLog

        runlog = RunLog.create(args.telemetry_dir, prefix="mem_probe")
        runlog.write_meta(config=out.get("config") or vars(args),
                          family=args.family,
                          argv=list(argv) if argv is not None else sys.argv[1:])
        runlog.write("mem_probe", **out)
        runlog.close()
        print(f"[mem_probe] telemetry written to {runlog.path}",
              file=sys.stderr)
    if args.require_1f1b_win:
        win = out.get("win_1f1b_gb")
        if win is None or win <= 0:
            print(
                f"[mem_probe] FAIL: 1f1b does not win (win_1f1b_gb={win})",
                file=sys.stderr,
            )
            return 1
        print(f"[mem_probe] OK: 1f1b wins by {win} GB", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
