"""Compile-only peak-HBM probe for a bench rung configuration.

Asks XLA (via ``compiled.memory_analysis()``) what a training step's peak
device memory is WITHOUT running it — the fast way to chart the memory
frontier (ResNet-110-v2 2048², AmoebaNet 3328²+) against the ~15.75 GB
usable HBM of a 16 GB chip, and to A/B memory levers (boundary packing,
remat grouping) without burning a full rung timeout per point.

    python benchmarks/mem_probe.py --arch resnet --image-size 2048 \
        --num-layers 110 --remat sqrt --scan 1
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=2048)
    p.add_argument("--num-layers", type=int, default=110)
    p.add_argument("--num-filters", type=int, default=416)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--remat", default="sqrt",
                   choices=["none", "cell", "fine", "sqrt"])
    p.add_argument("--arch", default="resnet", choices=["amoeba", "resnet"])
    p.add_argument("--scan", type=int, default=1)
    args = p.parse_args()

    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_probe_setup

    dev = jax.devices()[0]
    print(f"[mem_probe] device={dev}", file=sys.stderr)
    step, state, x, y = build_probe_setup(
        args.image_size, args.num_layers, args.num_filters, args.batch,
        remat=args.remat, scan=args.scan, arch=args.arch,
    )
    t0 = time.perf_counter()
    compiled = step.lower(state, x, y).compile()
    ma = compiled.memory_analysis()
    out = {
        "config": vars(args),
        "compile_s": round(time.perf_counter() - t0, 1),
    }
    for k in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    temp = out.get("temp_size_in_bytes", 0)
    arg = out.get("argument_size_in_bytes", 0)
    alias = out.get("alias_size_in_bytes", 0)
    # Peak live ≈ args + temps (donated args counted once via alias).
    out["peak_gb_est"] = round((temp + arg - alias) / 2**30, 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
