"""Capture + analyze an XProf trace of the headline training step — the
measured-time member of the obs stack (docs/observability.md, "Composing
with the profilers").

Builds the exact bench.py headline step (AmoebaNet-D(18,416), bf16, donate,
configurable remat/batch/res), captures a ``jax.profiler`` trace of a few
hot steps, then parses the xplane protobuf with xprof's own converter and
prints the top-N ops by self time.  Because the hot paths are threaded with
``obs.scope`` names, the op rows read ``stage1/cell03/halo_exchange_spw``
instead of ``fusion.1234`` — this is the measured counterpart of the
*analytical* per-scope timeline (``mpi4dl_tpu/obs/timeline.py``) and the
per-scope HBM breakdown (``mpi4dl_tpu/obs/hbm.py``).

``--telemetry-dir`` writes the capture as a RunLog JSONL (meta + per-step
wall records + an ``xprof_ops`` record with the top-op table), so profiler
evidence shares the artifact format every other tool emits and renders via
``python -m mpi4dl_tpu.obs report``.

Usage:
    python benchmarks/profile_step.py --image-size 1024 --batch 1 \
        --remat none --steps 5 --out /tmp/xprof_1024 --telemetry-dir /tmp/t

The analysis step also runs standalone on an existing trace dir:
    python benchmarks/profile_step.py --analyze /tmp/xprof_1024
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time

# Make `mpi4dl_tpu` importable when run by path (the benchmarks/common.py
# recipe; capture() needs it for bench imports, _open_runlog for obs).
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _open_runlog(args):
    """RunLog sink for ``--telemetry-dir`` (None when the flag is off)."""
    if not getattr(args, "telemetry_dir", None):
        return None
    from mpi4dl_tpu.obs import RunLog

    runlog = RunLog.create(args.telemetry_dir, prefix="profile")
    runlog.write_meta(config=vars(args), family="single",
                      argv=sys.argv[1:])
    return runlog


def capture(args, runlog=None) -> str:
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import _build_step, _REMAT

    dev = jax.devices()[0]
    print(f"[profile] device={dev} kind={getattr(dev, 'device_kind', '?')}",
          file=sys.stderr)

    if args.sqrt_groups:
        # bench._build_step sets this for its ResNet rungs; the profiler
        # must be able to reproduce the exact frontier configuration.
        os.environ["MPI4DL_SQRT_GROUPS"] = str(args.sqrt_groups)
    step, state = _build_step(
        args.image_size, args.num_layers, args.num_filters, args.batch,
        remat=_REMAT[args.remat], arch=args.arch,
    )
    xs = [
        jax.random.normal(jax.random.key(100 + i),
                          (args.batch, args.image_size, args.image_size, 3),
                          jnp.bfloat16)
        for i in range(2)
    ]
    ys = [jnp.full((args.batch,), i % 1000, jnp.int32) for i in range(2)]

    t0 = time.perf_counter()
    for i in range(2):
        state, metrics = step(state, xs[i % 2], ys[i % 2])
    float(metrics["loss"])
    jax.block_until_ready(state)
    print(f"[profile] compile+warmup {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    from mpi4dl_tpu.obs import step_annotation

    os.makedirs(args.out, exist_ok=True)
    jax.profiler.start_trace(args.out)
    t0 = time.perf_counter()
    try:
        for i in range(args.steps):
            # Scope-named trace: the step ops carry obs.scope paths; the
            # host-side annotation lines the trace's step view up with the
            # RunLog step records (match on step number).
            with step_annotation(i):
                ts = time.perf_counter()
                state, metrics = step(state, xs[i % 2], ys[i % 2])
                if runlog is not None:
                    # Per-step wall records need a per-step sync.  Without
                    # the sink, keep the original free-running dispatch so
                    # the aggregate img/s figure stays comparable with
                    # pre-telemetry captures.
                    jax.block_until_ready(state)
            if runlog is not None:
                step_s = time.perf_counter() - ts
                runlog.write_step(
                    epoch=0, step=i, ms=step_s * 1e3,
                    images_per_sec=args.batch / step_s,
                    loss=float(metrics["loss"]),
                    accuracy=float(metrics.get("accuracy", 0.0)),
                )
        float(metrics["loss"])
        jax.block_until_ready(state)
    finally:
        dt = time.perf_counter() - t0
        jax.profiler.stop_trace()
    print(f"[profile] {args.steps} steps in {dt:.2f}s "
          f"({args.steps * args.batch / dt:.2f} img/s); trace -> {args.out}",
          file=sys.stderr)
    if runlog is not None:
        _record_overlap(step, (state, xs[0], ys[0]), runlog)
    return args.out


def _record_overlap(step, step_args, runlog) -> None:
    """The analytical exposed-wire ledger of the profiled step, written as
    an ``overlap`` RunLog record next to the measured ``xprof_ops`` table —
    the analytical and measured views of the same step land in the same
    JSONL for side-by-side reading (docs/observability.md).  Costs one AOT
    compile (the jit call cache doesn't expose the compiled module's text,
    and the persistent compilation cache is bypassed so the HLO keeps its
    obs.scope metadata)."""
    import time as _time

    import jax

    from mpi4dl_tpu.obs import overlap_ledger

    t0 = _time.perf_counter()
    try:
        cache_dir = jax.config.jax_compilation_cache_dir
        jax.config.update("jax_compilation_cache_dir", None)
        try:
            compiled = step.lower(*step_args).compile()
        finally:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
        ledger = overlap_ledger(compiled.as_text(),
                                device=jax.devices()[0])
    except Exception as e:  # noqa: BLE001 — telemetry never kills a capture
        print(f"[profile] overlap ledger unavailable ({e})", file=sys.stderr)
        return
    runlog.write("overlap", label="profile_step", **ledger)
    t = ledger["totals"]
    hf = ledger.get("hidden_frac")
    print(
        f"[profile] overlap ledger ({_time.perf_counter() - t0:.1f}s AOT "
        f"compile): wire {t['wire_ms']} ms, exposed {t['exposed_ms']} ms"
        + (f" (hidden {hf:.1%})" if hf is not None else ""),
        file=sys.stderr,
    )


def _find_xplane(trace_dir: str) -> str | None:
    pats = os.path.join(trace_dir, "**", "*.xplane.pb")
    files = sorted(glob.glob(pats, recursive=True), key=os.path.getmtime)
    return files[-1] if files else None


def analyze(trace_dir: str, top: int = 30, runlog=None) -> None:
    """Print per-op totals from the device plane of the xplane trace; with
    ``runlog``, also record them as an ``xprof_ops`` RunLog record."""
    xplane = _find_xplane(trace_dir)
    if xplane is None:
        print(f"[profile] no .xplane.pb under {trace_dir}", file=sys.stderr)
        return
    print(f"[profile] parsing {xplane}", file=sys.stderr)
    try:
        from xprof.convert import raw_to_tool_data as rtd
    except ImportError as e:
        # The capture (trace dir + RunLog records) is still useful without
        # the converter; say what is missing instead of dying on it.
        print(f"[profile] xprof converter unavailable ({e}); trace kept at "
              f"{trace_dir} — open it in TensorBoard/XProf instead",
              file=sys.stderr)
        return

    params = {"use_saved_result": False}
    data, _ = rtd.xspace_to_tool_data([xplane], "hlo_stats", params)
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    obj = json.loads(data) if isinstance(data, str) else data
    # hlo_stats: list-of-dicts table ({p: columns, rows} varies by version).
    rows = obj.get("rows") if isinstance(obj, dict) else obj
    cols = [c.get("label") for c in obj.get("cols", [])] if isinstance(obj, dict) else None
    if not rows or not cols:
        out = os.path.join(trace_dir, "hlo_stats.json")
        with open(out, "w") as f:
            f.write(data if isinstance(data, str) else json.dumps(obj))
        print(f"[profile] unrecognized hlo_stats layout; raw dump -> {out}",
              file=sys.stderr)
        return
    idx = {c: i for i, c in enumerate(cols)}

    def val(r, c):
        return r["c"][idx[c]].get("v")

    key = "Total self time (us)"
    rows = sorted(rows, key=lambda r: -(val(r, key) or 0))
    total = sum(val(r, key) or 0 for r in rows)
    print(f"total device self time: {total / 1e3:.1f} ms")
    for r in rows[:top]:
        t = val(r, key) or 0
        print(
            f"{t / 1e3:8.2f} ms {100 * t / total:5.2f}% "
            f"x{int(val(r, '#Occurrences') or 0):<3d} "
            f"{val(r, 'HLO op category')}: {val(r, 'HLO op name')} "
            f"bound={val(r, 'Bound by')}"
        )
        print("          ", (val(r, "HLO op text") or "")[:160].replace("\n", " "))
    if runlog is not None:
        runlog.write(
            "xprof_ops",
            total_self_ms=round(total / 1e3, 3),
            ops=[
                {
                    "self_ms": round((val(r, key) or 0) / 1e3, 3),
                    "occurrences": int(val(r, "#Occurrences") or 0),
                    "category": val(r, "HLO op category"),
                    "name": val(r, "HLO op name"),
                    "bound_by": val(r, "Bound by"),
                }
                for r in rows[:top]
            ],
        )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--image-size", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--num-layers", type=int, default=18)
    ap.add_argument("--num-filters", type=int, default=416)
    ap.add_argument("--arch", default="amoeba", choices=["amoeba", "resnet"],
                    help="resnet: --num-layers carries the depth (110)")
    ap.add_argument("--remat", default="none",
                    choices=["none", "cell", "fine", "sqrt"])
    ap.add_argument("--sqrt-groups", type=int, default=0,
                    help="MPI4DL_SQRT_GROUPS for --remat sqrt (bench.py's "
                         "ResNet rungs use 16)")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default="/tmp/xprof_step")
    ap.add_argument("--analyze", default=None,
                    help="skip capture; analyze this existing trace dir")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--telemetry-dir", default=None,
                    help="write the capture as a RunLog JSONL (meta + "
                         "per-step records + xprof_ops top-op table); "
                         "render with `python -m mpi4dl_tpu.obs report` "
                         "(docs/observability.md)")
    args = ap.parse_args()

    runlog = _open_runlog(args)
    try:
        if args.analyze:
            analyze(args.analyze, args.top, runlog=runlog)
            return 0
        out = capture(args, runlog=runlog)
        analyze(out, args.top, runlog=runlog)
        return 0
    finally:
        if runlog is not None:
            runlog.close()
            print(f"[profile] telemetry written to {runlog.path}",
                  file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
