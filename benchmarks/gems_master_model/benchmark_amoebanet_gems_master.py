"""amoebanet gems benchmark (reference: benchmarks/gems_master_model/benchmark_amoebanet_gems_master.py).

Example (CPU smoke run; the runner provisions the virtual CPU mesh itself):
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python gems_master_model/benchmark_amoebanet_gems_master.py --image-size 32 --num-layers 1 --batch-size 8 --steps-per-epoch 3
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from benchmarks.common import run

if __name__ == "__main__":
    run("gems", "amoebanet")
