"""Attribute XLA layout-conversion copies in the headline step to model ops.

The r4 hardware profile (PERF_NOTES.md) shows ~51 ms/step of pure
layout-conversion copies (`T(8,128)` <-> narrow `T(2,128)` flips around convs
at C in {208,416,624}) plus loop fusions running well under HBM speed —
together the bulk of the 0.18-mfu gap.  XProf names the copy ops but not
*which model op* forces each flip; this tool does: it compiles the exact
bench.py headline step for the live backend, walks the optimized HLO, and for
every explicit `copy`/`transpose`/`bitcast-convert` instruction — at module
scope or inside fusion bodies (the line scan does not care about scope) —
prints result bytes, the operand/result layouts, and the `op_name` metadata
XLA preserves from the JAX trace (the model-source attribution).  Layout
flips absorbed entirely into a fusion's output layout (no copy instruction
anywhere) are NOT visible here; cross-check class totals against XProf
(benchmarks/profile_step.py).

Usage (TPU; compile-only, no timed steps):
    python benchmarks/layout_probe.py --image-size 1024 --remat none --top 30
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from collections import defaultdict

_DTYPE_BYTES = {
    "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
    "s8": 1, "u8": 1, "f64": 8, "s64": 8, "u64": 8, "s16": 2, "u16": 2,
}

# e.g. bf16[1,256,256,208]{3,2,1,0:T(8,128)(2,1)}
_SHAPE_RE = re.compile(
    r"(?P<dt>\w+)\[(?P<dims>[\d,]*)\]\{(?P<minor>[\d,]+)"
    r"(?::(?P<tiles>[^}]*))?\}"
)
_TILE_RE = re.compile(r"T\(([\d,]+)\)")


def parse_shape(text: str):
    m = _SHAPE_RE.search(text)
    if not m:
        return None
    dims = [int(d) for d in m.group("dims").split(",") if d] or [1]
    order = [int(d) for d in m.group("minor").split(",")]
    tiles = _TILE_RE.findall(m.group("tiles") or "")
    return m.group("dt"), dims, order, tiles


def padded_bytes(dt: str, dims, order, tiles) -> int:
    """Physical bytes including tile padding (first T(...) tile only)."""
    esz = _DTYPE_BYTES.get(dt, 4)
    logical = 1
    for d in dims:
        logical *= d
    if not tiles:
        return logical * esz
    tile = [int(t) for t in tiles[0].split(",")]
    # Layout order lists dims minor-to-major? No: HLO {3,2,1,0} lists
    # minor_to_major, first entry = minor-most dim index.
    phys = list(dims)
    for i, tdim in enumerate(reversed(tile)):
        if i < len(order):
            di = order[i]
            phys[di] = -(-dims[di] // tdim) * tdim
    total = 1
    for d in phys:
        total *= d
    return total * esz


def layout_str(dt: str, dims, order, tiles) -> str:
    t = "".join(f"T({x})" for x in tiles)
    return f"{dt}[{','.join(map(str, dims))}]{{{','.join(map(str, order))}:{t}}}"


def probe(args) -> None:
    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench import build_probe_setup

    dev = jax.devices()[0]
    print(f"[layout_probe] device={dev}", file=sys.stderr)
    step, state, x, y = build_probe_setup(
        args.image_size, args.num_layers, args.num_filters, args.batch,
        remat=args.remat, scan=1, arch=args.arch,
    )
    compiled = step.lower(state, x, y).compile()
    hlo = compiled.as_text()
    if args.dump:
        with open(args.dump, "w") as f:
            f.write(hlo)
        print(f"[layout_probe] HLO -> {args.dump} ({len(hlo)} bytes)",
              file=sys.stderr)
    analyze_text(hlo, args.top)


def analyze_text(hlo: str, top: int) -> None:
    # Map instruction name -> result-shape text, SCOPED per computation:
    # HLO instruction names (param_0, copy.1, ...) repeat across fusion
    # computations, so a module-wide map would misattribute operand
    # layouts.  A computation starts at "<name> {" (possibly prefixed by
    # ENTRY/%) and ends at its closing "}" line.
    inst_re = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (.+)$")
    comp_re = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s.*\{\s*$")
    lines = hlo.splitlines()
    scopes = {None: {}}
    comp_of_line = []
    cur = None
    for ln in lines:
        cm = comp_re.match(ln)
        if cm and " = " not in ln:
            cur = cm.group(1)
            scopes.setdefault(cur, {})
        elif ln.strip() == "}":
            cur = None
        comp_of_line.append(cur)
        m = inst_re.match(ln)
        if m:
            scopes.setdefault(cur, {})[m.group(1)] = m.group(2)

    convert_bytes = defaultdict(int)
    convert_count = defaultdict(int)
    op_names = defaultdict(set)
    copy_re = re.compile(
        r"^\s*(?:ROOT\s+)?%?([\w.\-]+) = (.*?)\s(copy|transpose|bitcast-convert)"
        r"\(%?([\w.\-]+)", )
    meta_re = re.compile(r'op_name="([^"]*)"')
    total = 0
    for ln_idx, ln in enumerate(lines):
        m = copy_re.match(ln)
        if not m:
            continue
        name, res_text, kind, operand = m.groups()
        res = parse_shape(res_text)
        scope = scopes.get(comp_of_line[ln_idx], {})
        src_text = scope.get(operand) or scopes[None].get(operand, "")
        src = parse_shape(src_text)
        if res is None:
            continue
        rb = padded_bytes(*res)
        key_src = layout_str(*src) if src else "?"
        key = (kind, key_src, layout_str(*res))
        convert_bytes[key] += rb  # result (dst) bytes, padded
        convert_count[key] += 1
        total += rb
        mm = meta_re.search(ln)
        if mm:
            op_names[key].add(mm.group(1)[-110:])

    print(f"\n== layout/format conversions (copy/transpose/bitcast), "
          f"{sum(convert_count.values())} ops ==")
    ranked = sorted(convert_bytes.items(), key=lambda kv: -kv[1])[:top]
    for key, b in ranked:
        kind, src, dst = key
        print(f"\n{b / 1e6:9.1f} MB x{convert_count[key]:<4} {kind}")
        print(f"    from {src}")
        print(f"    to   {dst}")
        for n in sorted(op_names[key])[:4]:
            print(f"    op: …{n}")
    print(f"\ntotal dst bytes across conversions: {total / 1e6:.1f} MB "
          f"(src-side read traffic adds ~1x on top)")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--image-size", type=int, default=1024)
    p.add_argument("--num-layers", type=int, default=18)
    p.add_argument("--num-filters", type=int, default=416)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--remat", default="none",
                   choices=["none", "cell", "fine", "sqrt"])
    p.add_argument("--arch", default="amoeba", choices=["amoeba", "resnet"])
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--dump", default="",
                   help="also write the optimized HLO text here")
    p.add_argument("--analyze", default="",
                   help="skip compile; analyze an existing HLO text file")
    args = p.parse_args()
    if args.analyze:
        with open(args.analyze) as f:
            analyze_text(f.read(), args.top)
        return
    probe(args)


if __name__ == "__main__":
    main()
