"""amoebanet sp benchmark (reference: benchmarks/spatial_parallelism/benchmark_amoebanet_sp.py:116-371).

Example (8-device CPU mesh smoke run):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
  python spatial_parallelism/benchmark_amoebanet_sp.py --image-size 32 --num-layers 1 --batch-size 8 --steps-per-epoch 3
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from benchmarks.common import run

if __name__ == "__main__":
    run("sp", "amoebanet")
