"""resnet sp benchmark (reference: benchmarks/spatial_parallelism/benchmark_resnet_sp.py:116-370).

Example (CPU smoke run; the runner provisions the virtual CPU mesh itself):
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
  python spatial_parallelism/benchmark_resnet_sp.py --image-size 32 --num-layers 1 --batch-size 8 --steps-per-epoch 3
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from benchmarks.common import run

if __name__ == "__main__":
    run("sp", "resnet")
