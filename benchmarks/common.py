"""Shared benchmark runner — the L6 entry-point layer.

The reference implements twelve near-identical script bodies (parse → MPIComm
→ shape probe → model → runtime → dataset → epoch loop with CUDA-event img/s
timing; flagship flow `benchmark_amoebanet_sp.py:116-371`).  Here the flow is
one function parameterized by (family, model):

    parse flags (config.get_parser, reference parser.py vocabulary)
    → MeshSpec.from_config / build_mesh     (replaces MPIComm rank math)
    → build_model + spatial_until placement (replaces the two-phase shape
      probe: shapes come from jax.eval_shape inside the builders)
    → the family's train-step builder       (replaces train_model* runtimes)
    → make_dataset APP dispatch             (reference APP 1/2/3)
    → epoch loop printing per-step images/sec + mean/median via StepMeter
      (reference output format, benchmark_amoebanet_sp.py:322-367)

Families:
  lp       — LP/PP pipeline (reference benchmarks/layer_parallelism)
  sp       — spatial(+pipeline tail) (reference benchmarks/spatial_parallelism)
  gems     — GEMS bidirectional (reference benchmarks/gems_master_model)
  gems_sp  — GEMS x SP x PP (reference gems_master_with_spatial_parallelism)

Every script runs on any JAX platform; on a CPU host pass small flags, e.g.
  python benchmark_resnet_sp.py --image-size 32 --num-layers 1 --batch-size 4
The runner SELF-PROVISIONS a virtual CPU mesh when the mesh needs more
devices than the environment provides (VERDICT r2: the classic
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` recipe silently yields
one device when a sitecustomize imports jax at interpreter startup — env vars
are baked before user code runs; ``jax.config.update`` still works until the
first backend initialization, so the runner applies it just in time).
"""

from __future__ import annotations

import os
import sys

# Make `mpi4dl_tpu` importable when a benchmark script is run by path.
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from mpi4dl_tpu.config import (
    ParallelConfig, config_from_args, get_parser, resolve_pallas_conv,
)
from mpi4dl_tpu.utils import StepMeter


def _resolve_spatial_until(cfg: ParallelConfig, n_cells: int, shapes):
    """Resolve cfg.spatial_until to a concrete junction cell (or None when
    unset): an explicit int is clamped to the legal [1, n_cells-1] range;
    ``"auto"`` asks the analytical placement frontier
    (parallel/spatial.choose_spatial_until) — the ``mem_probe
    --sweep-junction`` chooser running as default config."""
    su = cfg.spatial_until
    if su is None:
        return None
    if su == "auto":
        import jax.numpy as jnp

        from mpi4dl_tpu.parallel.spatial import choose_spatial_until

        assert shapes is not None, "--spatial-until auto needs cell shapes"
        tiles = cfg.spatial_part_size
        itemsize = jnp.dtype(cfg.compute_dtype).itemsize
        su = choose_spatial_until(shapes, tiles, itemsize=itemsize)
        print(f"note: --spatial-until auto resolved to {su} "
              f"(analytical placement frontier, {tiles} tiles)")
    clamped = max(1, min(int(su), n_cells - 1))
    if clamped != int(su):
        # Placement is the dominant memory lever (PERF_NOTES: su=18 vs 22
        # is 123.9 vs 59.4 GB) — never re-place a pinned junction silently.
        print(f"note: --spatial-until {su} clamped to {clamped} "
              f"({n_cells}-cell model)")
    return clamped


def _spatial_levels(cfg: ParallelConfig, n_cells: int, shapes=None):
    """[(stop_cell, SpatialCtx)] for the spatial region.

    Level i covers the cells of pipeline split i (reference: the first
    `spatial_size` splits run conv_spatial, resnet_spatial.py:272-296) with
    `num_spatial_parts[i]` tiles (multi-level SP, train_spatial.py:453-504);
    a short parts list repeats its last element, and consecutive levels with
    identical grids merge (no respatial between them).  ``cfg.spatial_until``
    (int or "auto") overrides the junction placement derived from the
    splits."""
    from mpi4dl_tpu.cells import split_even
    from mpi4dl_tpu.layer_ctx import spatial_levels_for

    ranges = split_even(n_cells, max(cfg.split_size, 1), cfg.balance)
    k = min(max(cfg.spatial_size, 1), len(ranges))
    if cfg.split_size > 1 and k >= cfg.split_size:
        # The SPxPP engine needs a non-spatial pipeline tail (the reference's
        # models likewise keep non-spatial layers past end_layer — the head
        # cannot run tiled).  Clamp and say so.
        k = cfg.split_size - 1
        print(
            f"note: spatial_size clamped to {k} (split_size {cfg.split_size} "
            "needs at least one non-spatial tail split)"
        )
    parts = list(cfg.num_spatial_parts)
    if len(parts) > k:
        print(
            f"note: num_spatial_parts {parts} has more levels than the "
            f"{k} spatial split(s); using {parts[:k]} (raise --spatial-size "
            "and --split-size to use the full chain)"
        )
    parts = (parts + [parts[-1]] * k)[:k]
    ctxs = spatial_levels_for(
        cfg.slice_method,
        parts,
        bn_cross_tile=cfg.bn_cross_tile,
        d2_mode=cfg.halo_d2,
        # --fused-layers caps margin-consuming layers per fused exchange
        # (reference resnet_spatial_d2.py get_balance); <=0 → maximal fusion.
        d2_max_fused=cfg.fused_layers if cfg.fused_layers > 0 else None,
        use_pallas_conv=resolve_pallas_conv(cfg.pallas_conv),
    )
    levels = []
    for i in range(k):
        # The head cell can never run tiled (its global pooling kernel
        # exceeds any tile), so the junction comes before it — same reason
        # apply_spatial_model's default spatial_until is len(cells)-1.
        stop = min(ranges[i][1], n_cells - 1)
        if levels and ctxs[i] == levels[-1][1]:
            levels[-1] = (stop, ctxs[i])
        elif stop > (levels[-1][0] if levels else 0):
            levels.append((stop, ctxs[i]))
    su = _resolve_spatial_until(cfg, n_cells, shapes)
    if su is not None:
        # Re-place the junction: clamp the level chain at the new stop
        # (dropping levels that now start past it) or extend the last level
        # to reach it — interior level boundaries keep their positions.
        clamped = []
        for stop, c in levels:
            prev = clamped[-1][0] if clamped else 0
            if prev >= su:
                break
            clamped.append((min(stop, su), c))
        clamped[-1] = (su, clamped[-1][1])
        levels = clamped
    return levels


def build_train(cfg: ParallelConfig, family: str, mesh):
    """Return (step, state, eval_params_fn, global_batch).

    ``eval_params_fn(state) -> params_list`` reassembles full parameters for
    the eval step / checkpointing regardless of the family's state layout.
    """
    import jax

    from mpi4dl_tpu.models import build_model
    from mpi4dl_tpu.train import Optimizer, TrainState

    from mpi4dl_tpu.quant import QuantPolicy

    if cfg.stripe_bwd:
        # The stripe-wise backward is dispatched at trace time off the
        # MPI4DL_STRIPE_BWD hatch (like the other layer-dispatch hatches);
        # the config flag sets it for this process before any step builds.
        # Deliberately NOT cleared when cfg.stripe_bwd is false: tracing
        # happens after build_train returns, and the env-var hatch is a
        # documented interface of its own (HATCHES) — an in-process
        # striped-vs-plain A/B must manage the variable itself (as the
        # tests do via monkeypatch).
        os.environ["MPI4DL_STRIPE_BWD"] = "1"
    model = build_model(cfg)
    params, shapes = model.init(jax.random.key(cfg.seed))
    opt = Optimizer(cfg.optimizer, lr=cfg.lr, momentum=cfg.momentum)
    dp = cfg.data_parallel
    dtype = cfg.compute_dtype
    pdtype = cfg.param_dtype
    # Quantized-collective policy (None = off = bit-identical engines);
    # the MPI4DL_QUANT_COLLECTIVES hatch overrides the --quant flag.
    quant = QuantPolicy.resolve(cfg.quant_collectives)
    if quant is not None:
        print(f"note: quantized collectives on: {quant.spec()}",
              file=sys.stderr)
    if cfg.precision == "bf_16_all":
        # bf_16_all: parameters stored bf16 as well (reference parser.py
        # precision vocabulary); fp32 update arithmetic lives in Optimizer.
        params = jax.tree.map(lambda p: p.astype(pdtype), params)
    from_probs = cfg.softmax_in_model

    if cfg.schedule != "gpipe" and cfg.split_size <= 1:
        print(
            f"note: --schedule {cfg.schedule} needs a pipeline "
            "(--split-size >= 2); single-chip path ignores it",
            file=sys.stderr,
        )

    if family == "lp":
        if cfg.split_size <= 1:
            from mpi4dl_tpu.train import make_train_step

            step = make_train_step(
                model, opt, mesh if dp > 1 else None, parts=cfg.parts,
                compute_dtype=dtype, from_probs=from_probs, remat=cfg.remat,
                donate=True,
            )
            state = TrainState.create(params, opt)
            return step, state, (lambda s: s.params), cfg.batch_size * dp
        from mpi4dl_tpu.parallel.partition import StagePartition
        from mpi4dl_tpu.parallel.pipeline import (
            init_pipeline_state,
            make_pipeline_train_step,
        )

        mb = cfg.batch_size // cfg.parts
        part = StagePartition.build(
            model, params, cfg.split_size,
            (mb, cfg.image_size, cfg.image_size, 3),
            balance=cfg.balance, compute_dtype=dtype, param_dtype=pdtype,
        )
        step = make_pipeline_train_step(
            part, opt, mesh, cfg.parts, compute_dtype=dtype, remat=cfg.remat,
            from_probs=from_probs, with_data_axis=dp > 1, donate=True,
            schedule=cfg.schedule, quant=quant,
        )
        state = init_pipeline_state(part, params, opt, mesh)
        return (
            step, state,
            (lambda s: part.unpack_params(jax.device_get(s.param_buf))),
            cfg.batch_size * dp,
        )

    if family == "gems":
        from mpi4dl_tpu.parallel.gems import make_gems_train_step
        from mpi4dl_tpu.parallel.partition import StagePartition
        from mpi4dl_tpu.parallel.pipeline import init_pipeline_state

        groups = 2 * cfg.times * cfg.parts
        assert cfg.batch_size % groups == 0, (
            f"GEMS needs batch_size divisible by 2*times*parts={groups}"
        )
        mb = cfg.batch_size // groups
        part = StagePartition.build(
            model, params, cfg.split_size,
            (mb, cfg.image_size, cfg.image_size, 3),
            balance=cfg.balance, compute_dtype=dtype, param_dtype=pdtype,
        )
        step = make_gems_train_step(
            part, opt, mesh, cfg.parts, times=cfg.times, compute_dtype=dtype,
            remat=cfg.remat, from_probs=from_probs, with_data_axis=dp > 1,
            donate=True, schedule=cfg.schedule, quant=quant,
        )
        state = init_pipeline_state(part, params, opt, mesh)
        return (
            step, state,
            (lambda s: part.unpack_params(jax.device_get(s.param_buf))),
            cfg.batch_size * dp,
        )

    # Spatial families
    levels = _spatial_levels(cfg, len(model.cells), shapes=shapes)
    sp = levels[0][1]
    model.spatial_until = levels[-1][0]
    junction = "batch_split" if cfg.local_dp_lp > 1 else "gather"
    local_dp = cfg.local_dp_lp if cfg.local_dp_lp > 1 else None

    if family == "sp" and cfg.split_size <= 1:
        from mpi4dl_tpu.train import make_spatial_train_step

        step = make_spatial_train_step(
            model, opt, mesh, sp, parts=cfg.parts, with_data_axis=dp > 1,
            compute_dtype=dtype, from_probs=from_probs,
            spatial_until=model.spatial_until, junction=junction,
            levels=levels, local_dp=local_dp, donate=True, quant=quant,
        )
        state = TrainState.create(params, opt)
        return step, state, (lambda s: s.params), cfg.batch_size * dp

    from mpi4dl_tpu.parallel.sp_pipeline import (
        SPPipeline,
        init_sp_pipeline_state,
        make_sp_gems_train_step,
        make_sp_pipeline_train_step,
    )

    groups = (2 * cfg.times * cfg.parts) if family == "gems_sp" else cfg.parts
    assert cfg.batch_size % groups == 0, (cfg.batch_size, groups)
    micro = cfg.batch_size // groups
    spp = SPPipeline.build(
        model, params, max(cfg.split_size, 2), sp, microbatch=micro,
        junction=junction, balance=cfg.balance, compute_dtype=dtype,
        levels=levels, local_dp=local_dp, param_dtype=pdtype,
    )
    if family == "gems_sp":
        step = make_sp_gems_train_step(
            spp, opt, mesh, cfg.parts, times=cfg.times, compute_dtype=dtype,
            remat=cfg.remat, from_probs=from_probs, with_data_axis=dp > 1,
            donate=True, schedule=cfg.schedule, quant=quant,
        )
    else:
        step = make_sp_pipeline_train_step(
            spp, opt, mesh, cfg.parts, compute_dtype=dtype, remat=cfg.remat,
            from_probs=from_probs, with_data_axis=dp > 1, donate=True,
            schedule=cfg.schedule, quant=quant,
        )
    state = init_sp_pipeline_state(spp, params, opt, mesh)
    return (
        step, state,
        (lambda s: spp.unpack_all(
            jax.device_get(s.sp_buf), jax.device_get(s.tail_buf))),
        cfg.batch_size * dp,
    )


def _ensure_devices(need: int) -> None:
    """Self-provision an `need`-device CPU platform when the process is headed
    for CPU anyway and no backend is initialized yet (the conftest.py
    fallback, applied just in time for script users).

    A fleet leg is pinned to its slice: when the scheduler set
    ``MPI4DL_FLEET_SLICE_DEVICES`` the process provisions EXACTLY that many
    devices — the slice IS the job's world, and over-provisioning would let
    a 4-device tenant silently compile onto its neighbor's devices."""
    cap = os.environ.get("MPI4DL_FLEET_SLICE_DEVICES", "")
    pinned = int(cap) if cap.isdigit() and int(cap) > 0 else None
    if pinned is None and need <= 1:
        return
    import jax

    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return
    except Exception:
        return
    # Inert unless the CPU platform actually gets selected (explicitly or
    # by auto-fallback), so a live GPU/TPU is never hijacked.
    from mpi4dl_tpu.compat import ensure_host_device_count

    ensure_host_device_count(pinned if pinned is not None else max(need, 8))


def _open_telemetry(directory, family, cfg, spec, step, state, dataset,
                    global_batch, argv):
    """Open a RunLog and write the meta + compiled-step cost records.

    The cost record lowers and compiles the step once more through the AOT
    path (``step.lower(...).compile()``) to reach ``cost_analysis()`` and
    the collective-bearing HLO text — an extra compile the flag opts into
    (the persistent compilation cache absorbs it where enabled).  Failures
    degrade to a ``cost_error`` record: telemetry must never kill a run."""
    from mpi4dl_tpu.obs import RunLog

    runlog = RunLog.create(directory, prefix=f"{family}-{cfg.model}")
    runlog.write_meta(
        config=cfg, mesh_spec=spec, family=family,
        argv=list(argv) if argv is not None else sys.argv[1:],
    )
    try:
        import jax

        from mpi4dl_tpu.obs import (
            arithmetic_intensity, compiled_cost, hlo_collective_stats,
            peak_flops,
        )

        x, y = dataset.batch(0, global_batch)
        compiled = step.lower(state, x, y).compile()
        cost = compiled_cost(compiled)
        hlo_text = compiled.as_text()
        coll = hlo_collective_stats(hlo_text)
        # Schedule fingerprint: which per-tick scopes the compiled program
        # carries (obs/report.py renders them on the `pipeline:` line).
        tick_scopes = sorted(
            s for s in ("gpipe_scan", "pp_1f1b_scan", "gems_dual_scan",
                        "gems_1f1b_scan", "tail_scan", "fwd_tick", "bwd_tick")
            if s in hlo_text
        )
        # Cost-model flops are PER DEVICE (the one SPMD module every device
        # executes), so the report's MFU divides by one device's peak.
        peak, src = peak_flops(jax.devices()[0], allow_cpu_nominal=True)
        runlog.write(
            "cost",
            flops=cost["flops"],
            bytes_accessed=cost["bytes_accessed"],
            arithmetic_intensity=arithmetic_intensity(
                cost["flops"], cost["bytes_accessed"]
            ),
            collectives=coll,
            tick_scopes=tick_scopes,
            peak_flops=peak,
            peak_source=src,
            device_count=len(jax.devices()),
        )
    except Exception as e:  # noqa: BLE001 — telemetry must never kill a run
        runlog.write("cost_error", error=repr(e))
        print(f"note: telemetry cost analysis unavailable ({e})")
    return runlog


def run(family: str, model: str, argv=None) -> dict:
    """Parse flags and run the benchmark; returns the final summary dict."""
    import jax
    import numpy as np

    parser = get_parser()
    parser.set_defaults(model=model)
    parser.add_argument("--steps-per-epoch", type=int, default=10)
    parser.add_argument(
        "--profile-dir", default=None,
        help="write a jax.profiler trace of the epoch loop (TensorBoard/XProf"
             " format) — the TPU analog of the reference's CUDA-event phase "
             "timing (benchmark_resnet_gems_master_with_sp.py:417-440)",
    )
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="write a RunLog JSONL (run metadata + per-step records + "
             "compiled-step cost/collective accounting) under this "
             "directory; render with `python -m mpi4dl_tpu.obs report` "
             "(docs/observability.md)",
    )
    parser.add_argument(
        "--watchdog-secs", type=float, default=None,
        help="step wall-clock budget: a step (batch fetch + device step) "
             "exceeding it dumps live Python stacks + the last RunLog "
             "record to stderr (default: MPI4DL_WATCHDOG_SECS, else off; "
             "docs/resilience.md)",
    )
    parser.add_argument(
        "--watchdog-compile-secs", type=float, default=None,
        help="watchdog budget for the FIRST step (the one that pays the "
             "XLA compile; default: MPI4DL_WATCHDOG_COMPILE_SECS, else 10x "
             "the step budget; docs/resilience.md)",
    )
    args = parser.parse_args(argv)
    cfg = config_from_args(args)
    if cfg.verbose:
        # Reference --verbose enables stdlib logging (benchmark scripts,
        # e.g. benchmark_amoebanet_sp.py:41-42); force=True because jax/absl
        # may already have attached root handlers.
        import logging

        logging.basicConfig(level=logging.DEBUG, force=True)
    if cfg.enable_master_comm_opt:
        print(
            "note: --enable-master-comm-opt is a no-op here — the one-weight-"
            "set GEMS redesign cannot diverge, so the reference's MASTER-OPT "
            "param/grad exchange (train_spatial_master.py:229-455) has "
            "nothing to synchronize."
        )

    from mpi4dl_tpu.data import make_dataset
    from mpi4dl_tpu.mesh import MeshSpec, build_mesh

    spec = MeshSpec.from_config(cfg) if family != "lp" and family != "gems" else (
        MeshSpec(data=cfg.data_parallel, stage=max(cfg.split_size, 1))
    )
    _ensure_devices(spec.size)
    devices = jax.devices()
    print(f"devices: {len(devices)} x {devices[0].platform}; mesh {spec}")
    try:
        mesh = build_mesh(spec, devices)
    except ValueError as e:
        raise SystemExit(
            f"{e}\nOn a CPU host, run exactly:\n  env -u PALLAS_AXON_POOL_IPS "
            f"JAX_PLATFORMS=cpu python {sys.argv[0]} "
            f"{' '.join(sys.argv[1:])}\n(the runner then provisions "
            f"{spec.size} virtual CPU devices itself)"
        )

    step, state, eval_params_fn, global_batch = build_train(cfg, family, mesh)

    # Optional checkpoint resume (reference has no checkpointing; SURVEY §5
    # plans it as a new capability).  restore_latest returns the step id the
    # checkpoint was taken at, so a resumed run continues the global step
    # count and batch sequence instead of restarting at 0.
    ckpt_mgr = None
    start_step = 0
    if cfg.checkpoint_dir:
        from mpi4dl_tpu.checkpoint import (
            CheckpointManager, config_fingerprint, split_config_fingerprint,
        )
        from mpi4dl_tpu.quant import QuantPolicy

        # steps_per_epoch is fingerprinted as model IDENTITY: it defines the
        # global-step → batch-index mapping and the checkpoint cadence, so
        # resuming with a different value would replay different data while
        # claiming the bit-identical-resume contract.  The LAYOUT side
        # (mesh, parts, schedule, spatial placement, quant/stripe policy —
        # RESOLVED, so a hatch override is a recorded layout change, not
        # silent drift) may differ between save and restore: elastic restore
        # re-places every leaf under this run's mesh (docs/resilience.md).
        quant_resolved = QuantPolicy.resolve(cfg.quant_collectives)
        identity_fp, layout_fp, layout_desc = split_config_fingerprint(
            cfg, spec,
            extra_identity={"steps_per_epoch": args.steps_per_epoch},
            extra_layout={
                "quant_resolved": (
                    quant_resolved.spec() if quant_resolved else "off"
                ),
                "stripe_bwd_resolved": os.environ.get(
                    "MPI4DL_STRIPE_BWD", "0"
                ),
            },
        )
        ckpt_mgr = CheckpointManager(
            cfg.checkpoint_dir,
            fingerprint=config_fingerprint(
                cfg, spec, {"steps_per_epoch": args.steps_per_epoch}
            ),
            identity=identity_fp, layout=layout_fp, layout_desc=layout_desc,
        )
        state, start_step = ckpt_mgr.restore_latest(state)
        if start_step:
            print(f"resuming from checkpoint step {start_step}")
        if ckpt_mgr.last_restore is not None and ckpt_mgr.last_restore.elastic:
            print(
                "note: ELASTIC restore — checkpoint was saved under a "
                f"different layout ({ckpt_mgr.last_restore.saved_layout}); "
                "leaves re-placed under this run's mesh"
            )

    dataset = make_dataset(cfg)
    steps = args.steps_per_epoch
    # warmup_steps=1: the first step pays compilation; StepMeter drops it
    # explicitly (and reports the drop count) instead of the old implicit
    # `epoch > 0 or i > 0` skip.
    meter = StepMeter(global_batch, warmup_steps=1)

    runlog = None
    if args.telemetry_dir:
        runlog = _open_telemetry(
            args.telemetry_dir, family, cfg, spec, step, state, dataset,
            global_batch, argv,
        )
        if ckpt_mgr is not None and ckpt_mgr.last_restore is not None:
            runlog.write("restore", **ckpt_mgr.last_restore.record())

    # The supervised loop (mpi4dl_tpu/resilience/loop.py) owns the epoch
    # structure: anomaly guard + rollback, preemption-safe checkpointing
    # through the background writer, fault injection, step watchdog.
    from mpi4dl_tpu.resilience import AnomalyGuard, FaultInjector, run_supervised
    from mpi4dl_tpu.resilience.watchdog import watchdog_budget_from_env

    if start_step >= cfg.num_epochs * steps:
        print(
            f"note: checkpoint step {start_step} already covers "
            f"{cfg.num_epochs} epoch(s) x {steps} steps — nothing to run"
        )

    # try/finally: a crash mid-epoch must still flush the profiler trace
    # (start_trace only buffers; stop_trace writes the files — the crash you
    # wanted to profile would otherwise leave an empty trace dir) and close
    # the telemetry sink.
    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    try:
        result = run_supervised(
            step, state, dataset,
            global_batch=global_batch,
            steps_per_epoch=steps,
            num_epochs=cfg.num_epochs,
            num_workers=cfg.num_workers,
            start_step=start_step,
            ckpt=ckpt_mgr,
            runlog=runlog,
            meter=meter,
            print_fn=print,
            profile=bool(args.profile_dir),
            guard=AnomalyGuard.from_env(),
            faults=FaultInjector.from_env(),
            watchdog_secs=watchdog_budget_from_env(args.watchdog_secs),
            watchdog_compile_secs=args.watchdog_compile_secs,
        )
    finally:
        if args.profile_dir:
            jax.profiler.stop_trace()
            print(f"profile trace written to {args.profile_dir}")
        if runlog is not None:
            runlog.write("summary", **meter.stats())
            runlog.close()
            print(f"telemetry written to {runlog.path} "
                  f"(render: python -m mpi4dl_tpu.obs report {runlog.path})")
            try:
                from mpi4dl_tpu.obs.metrics import write_metrics_file
                from mpi4dl_tpu.obs.runlog import read_runlog

                prom = os.path.splitext(runlog.path)[0] + ".prom"
                write_metrics_file(read_runlog(runlog.path), prom)
                print(f"metrics snapshot written to {prom}")
            except Exception as e:  # noqa: BLE001  # analysis: ok(swallow-except)
                # deliberate: telemetry must never kill a run
                print(f"note: metrics snapshot unavailable ({e})")
    print(meter.summary())
    return {
        "images_per_sec": meter.images_per_sec(),
        "loss": result.metrics.get("loss", float("nan")),
        "steps": len(meter.times_ms),
        "final_step": result.final_step,
        "start_step": start_step,
        "preempted": result.preempted,
        "anomalies": result.anomalies,
        "elastic": bool(
            ckpt_mgr is not None and ckpt_mgr.last_restore is not None
            and ckpt_mgr.last_restore.elastic
        ),
        "telemetry_path": runlog.path if runlog is not None else None,
    }
