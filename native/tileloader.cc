// Native tile loader for the image-folder data path (APP=1).
//
// The reference has no native code in-repo (its native layer is external
// MVAPICH2-GDR + a patched ProcessGroupMPI, SURVEY §2 bottom rows); its data
// loading rides torchvision/PIL on worker processes.  Here the hot host-side
// work — decoding raw u8 images, normalizing to float32, center-crop/tiling
// to the target resolution, and cutting per-device spatial tiles for SP input
// splitting (the reference's split_input, train_spatial.py:241-290, done on
// GPU there) — is a small C++ library driven from Python via ctypes
// (mpi4dl_tpu/data_native.py).  For multi-thousand-pixel pathology/satellite
// frames this is the difference between the input pipeline keeping up with
// the TPU step or not.
//
// Build:  g++ -O3 -shared -fPIC -o libtileloader.so tileloader.cc
// (data_native.py builds it on demand and caches the .so.)

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>

extern "C" {

// Read a raw interleaved-RGB u8 file and produce a float32 HWC image of
// side `image_size`, values in [0, 1].  The stored side is inferred as
// isqrt(bytes/3).  Larger images are center-cropped; smaller ones tiled.
// Returns 0 on success, negative errno-style codes otherwise.
int tl_load_rgb(const char* path, int image_size, float* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long bytes = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (bytes < 3) {
    std::fclose(f);
    return -2;
  }
  long side = (long)std::sqrt((double)(bytes / 3));
  while ((side + 1) * (side + 1) * 3 <= bytes) side++;
  while (side > 0 && side * side * 3 > bytes) side--;
  if (side <= 0) {
    std::fclose(f);
    return -2;
  }
  long need = side * side * 3;
  uint8_t* buf = new uint8_t[need];
  size_t got = std::fread(buf, 1, (size_t)need, f);
  std::fclose(f);
  if ((long)got != need) {
    delete[] buf;
    return -3;
  }
  const float inv = 1.0f / 255.0f;
  if (side >= image_size) {
    long o = (side - image_size) / 2;  // center crop
    for (int y = 0; y < image_size; y++) {
      const uint8_t* row = buf + ((o + y) * side + o) * 3;
      float* orow = out + (long)y * image_size * 3;
      for (int i = 0; i < image_size * 3; i++) orow[i] = row[i] * inv;
    }
  } else {  // tile up to target
    for (int y = 0; y < image_size; y++) {
      const uint8_t* row = buf + (long)(y % side) * side * 3;
      float* orow = out + (long)y * image_size * 3;
      for (int x = 0; x < image_size; x++) {
        const uint8_t* px = row + (long)(x % side) * 3;
        orow[x * 3 + 0] = px[0] * inv;
        orow[x * 3 + 1] = px[1] * inv;
        orow[x * 3 + 2] = px[2] * inv;
      }
    }
  }
  delete[] buf;
  return 0;
}

// Load a batch: `paths` is n C-strings; out is [n, image_size, image_size, 3]
// contiguous float32.  Returns the index of the first failing file, or -1 if
// all succeeded.
int tl_load_batch(const char** paths, int n, int image_size, float* out) {
  const long stride = (long)image_size * image_size * 3;
  for (int i = 0; i < n; i++) {
    if (tl_load_rgb(paths[i], image_size, out + (long)i * stride) != 0) return i;
  }
  return -1;
}

// Cut the (row, col) tile of a tile_h x tile_w grid out of a contiguous
// float32 NHWC batch — the host-side form of the reference's split_input
// slicing (train_spatial.py:241-290).  out is [n, th, tw, c].
void tl_crop_tiles(const float* batch, int n, int h, int w, int c, int row,
                   int col, int grid_h, int grid_w, float* out) {
  const int th = h / grid_h, tw = w / grid_w;
  const long img = (long)h * w * c, timg = (long)th * tw * c;
  const int y0 = row * th, x0 = col * tw;
  for (int i = 0; i < n; i++) {
    const float* src = batch + i * img;
    float* dst = out + i * timg;
    for (int y = 0; y < th; y++) {
      std::memcpy(dst + (long)y * tw * c,
                  src + ((long)(y0 + y) * w + x0) * c,
                  sizeof(float) * (size_t)tw * c);
    }
  }
}

}  // extern "C"
