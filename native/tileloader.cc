// Native tile loader for the image-folder data path (APP=1).
//
// The reference has no native code in-repo (its native layer is external
// MVAPICH2-GDR + a patched ProcessGroupMPI, SURVEY §2 bottom rows); its data
// loading rides torchvision/PIL on worker processes.  Here the hot host-side
// work — decoding raw u8 images, normalizing to float32, center-crop/tiling
// to the target resolution, and cutting per-device spatial tiles for SP input
// splitting (the reference's split_input, train_spatial.py:241-290, done on
// GPU there) — is a small C++ library driven from Python via ctypes
// (mpi4dl_tpu/data_native.py).  For multi-thousand-pixel pathology/satellite
// frames this is the difference between the input pipeline keeping up with
// the TPU step or not.
//
// Build:  g++ -O3 -shared -fPIC -o libtileloader.so tileloader.cc
//         [-DHAVE_LIBJPEG -ljpeg] [-DHAVE_LIBPNG -lpng]
// (data_native.py builds it on demand, probing for libjpeg/libpng, and
// caches the .so.)
//
// Codecs (VERDICT r2 item 7 — the reference's APP=1 benchmarks read real
// encoded images via torchvision ImageFolder,
// /root/reference/benchmarks/spatial_parallelism/benchmark_amoebanet_sp.py:264-283):
//   - PPM (P6) and BMP (24/32-bit uncompressed): self-contained decoders.
//   - JPEG / PNG: thin wrappers over the system libjpeg / libpng when the
//     dev headers were present at build time (compile-gated).
// Python keeps a PIL/numpy fallback for anything the native layer lacks.

#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <new>

#ifdef HAVE_LIBJPEG
#include <jpeglib.h>
#endif
#ifdef HAVE_LIBPNG
#include <png.h>
#endif

namespace {

// 1 GiB decoded-pixel cap: headers are file-controlled, so dimension products
// must not drive unbounded allocation (a crafted 65500x65500 JPEG header
// would otherwise ask for ~12.8 GB).
const long kMaxPixels = (1L << 30) / 3;

// Fit a decoded W x H interleaved-RGB u8 image into a float32 [S, S, 3]
// output in [0, 1]: center-crop when larger, tile when smaller (the same
// semantics as the raw-RGB path below, generalized to rectangles).
void fit_rgb(const uint8_t* img, long w, long h, int image_size, float* out) {
  const float inv = 1.0f / 255.0f;
  const long ox = w > image_size ? (w - image_size) / 2 : 0;
  const long oy = h > image_size ? (h - image_size) / 2 : 0;
  for (int y = 0; y < image_size; y++) {
    const long sy = h > image_size ? oy + y : y % h;
    const uint8_t* row = img + (sy * w) * 3;
    float* orow = out + (long)y * image_size * 3;
    if (w >= image_size) {
      const uint8_t* px = row + ox * 3;
      for (int i = 0; i < image_size * 3; i++) orow[i] = px[i] * inv;
    } else {
      for (int x = 0; x < image_size; x++) {
        const uint8_t* px = row + (long)(x % w) * 3;
        orow[x * 3 + 0] = px[0] * inv;
        orow[x * 3 + 1] = px[1] * inv;
        orow[x * 3 + 2] = px[2] * inv;
      }
    }
  }
}

uint8_t* read_file(const char* path, long* n_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (n <= 0) {
    std::fclose(f);
    return nullptr;
  }
  uint8_t* buf = new uint8_t[n];
  size_t got = std::fread(buf, 1, (size_t)n, f);
  std::fclose(f);
  if ((long)got != n) {
    delete[] buf;
    return nullptr;
  }
  *n_out = n;
  return buf;
}

// --- PPM (P6, 8-bit) ---
int skip_ppm_ws(const uint8_t* b, long n, long p) {
  while (p < n) {
    if (b[p] == '#') {
      while (p < n && b[p] != '\n') p++;
    } else if (b[p] == ' ' || b[p] == '\t' || b[p] == '\r' || b[p] == '\n') {
      p++;
    } else {
      break;
    }
  }
  return (int)p;
}

long ppm_int(const uint8_t* b, long n, long* p) {
  *p = skip_ppm_ws(b, n, *p);
  long v = 0;
  bool any = false;
  while (*p < n && b[*p] >= '0' && b[*p] <= '9') {
    v = v * 10 + (b[*p] - '0');
    (*p)++;
    any = true;
  }
  return any ? v : -1;
}

int decode_ppm(const uint8_t* b, long n, int image_size, float* out) {
  if (n < 2 || b[0] != 'P' || b[1] != '6') return -10;
  long p = 2;
  long w = ppm_int(b, n, &p);
  long h = ppm_int(b, n, &p);
  long maxv = ppm_int(b, n, &p);
  if (w <= 0 || h <= 0 || maxv != 255 || p >= n) return -11;
  // Exactly one whitespace byte follows maxval — but tolerate CRLF (a "\r\n"
  // pair counts as the one separator, else pixels shift by a byte).
  if (b[p] != ' ' && b[p] != '\t' && b[p] != '\r' && b[p] != '\n') return -13;
  if (b[p] == '\r' && p + 1 < n && b[p + 1] == '\n') p++;
  p++;
  if (n - p < w * h * 3) return -12;
  fit_rgb(b + p, w, h, image_size, out);
  return 0;
}

// --- BMP (BITMAPINFOHEADER, 24/32bpp, uncompressed, bottom-up or top-down) ---
uint32_t le32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

int decode_bmp(const uint8_t* b, long n, int image_size, float* out) {
  if (n < 54 || b[0] != 'B' || b[1] != 'M') return -20;
  uint32_t data_off = le32(b + 10);
  uint32_t hdr = le32(b + 14);
  if (hdr < 40) return -21;
  int32_t w = (int32_t)le32(b + 18);
  int32_t h_raw = (int32_t)le32(b + 22);
  uint16_t bpp = (uint16_t)(b[28] | (b[29] << 8));
  uint32_t comp = le32(b + 30);
  bool top_down = h_raw < 0;
  long h = top_down ? -(long)h_raw : (long)h_raw;
  if (w <= 0 || h <= 0 || comp != 0 || (bpp != 24 && bpp != 32)) return -22;
  const long bytespp = bpp / 8;
  const long stride = ((w * bytespp + 3) / 4) * 4;
  if ((long)data_off + stride * h > n) return -23;
  uint8_t* rgb = new uint8_t[(long)w * h * 3];
  for (long y = 0; y < h; y++) {
    const long sy = top_down ? y : h - 1 - y;
    const uint8_t* row = b + data_off + sy * stride;
    for (long x = 0; x < w; x++) {
      const uint8_t* px = row + x * bytespp;  // BGR(A)
      uint8_t* o = rgb + (y * w + x) * 3;
      o[0] = px[2];
      o[1] = px[1];
      o[2] = px[0];
    }
  }
  fit_rgb(rgb, w, h, image_size, out);
  delete[] rgb;
  return 0;
}

#ifdef HAVE_LIBJPEG
struct tl_jpeg_err {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void tl_jpeg_abort(j_common_ptr cinfo) {
  std::longjmp(((tl_jpeg_err*)cinfo->err)->jb, 1);
}

int decode_jpeg(const uint8_t* b, long n, int image_size, float* out) {
  jpeg_decompress_struct cinfo;
  tl_jpeg_err jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = tl_jpeg_abort;
  // volatile: modified after setjmp and read in the longjmp error path
  // (non-volatile locals are indeterminate there per the setjmp rules).
  uint8_t* volatile rgb = nullptr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    delete[] rgb;
    return -30;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, (unsigned char*)b, (unsigned long)n);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -31;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const long w = cinfo.output_width, h = cinfo.output_height;
  if (w <= 0 || h <= 0 || w * h > kMaxPixels) {
    jpeg_destroy_decompress(&cinfo);
    return -32;
  }
  rgb = new (std::nothrow) uint8_t[w * h * 3];
  if (!rgb) {
    jpeg_destroy_decompress(&cinfo);
    return -33;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = rgb + (long)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  fit_rgb(rgb, w, h, image_size, out);
  delete[] rgb;
  return 0;
}
#endif  // HAVE_LIBJPEG

#ifdef HAVE_LIBPNG
struct tl_png_reader {
  const uint8_t* data;
  long size;
  long pos;
};

void tl_png_read(png_structp png, png_bytep out, png_size_t n) {
  tl_png_reader* r = (tl_png_reader*)png_get_io_ptr(png);
  if (r->pos + (long)n > r->size) png_error(png, "eof");
  std::memcpy(out, r->data + r->pos, n);
  r->pos += (long)n;
}

int decode_png(const uint8_t* b, long n, int image_size, float* out) {
  if (png_sig_cmp((png_const_bytep)b, 0, 8)) return -40;
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                           nullptr, nullptr);
  if (!png) return -41;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return -41;
  }
  // volatile: see decode_jpeg — read in the longjmp error path.
  uint8_t* volatile rgb = nullptr;
  png_bytep* volatile rows = nullptr;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    delete[] rgb;
    delete[] rows;
    return -42;
  }
  tl_png_reader reader = {b, n, 0};
  png_set_read_fn(png, &reader, tl_png_read);
  png_read_info(png, info);
  png_uint_32 w = png_get_image_width(png, info);
  png_uint_32 h = png_get_image_height(png, info);
  int color = png_get_color_type(png, info);
  int depth = png_get_bit_depth(png, info);
  // Normalize everything to 8-bit RGB.
  if (depth == 16) png_set_strip_16(png);
  if (color == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color == PNG_COLOR_TYPE_GRAY && depth < 8) png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  if (color == PNG_COLOR_TYPE_GRAY || color == PNG_COLOR_TYPE_GRAY_ALPHA)
    png_set_gray_to_rgb(png);
  png_set_strip_alpha(png);
  png_read_update_info(png, info);
  if (w == 0 || h == 0 || (long)w * h > kMaxPixels) {
    png_destroy_read_struct(&png, &info, nullptr);
    return -43;
  }
  rgb = new (std::nothrow) uint8_t[(long)w * h * 3];
  rows = new (std::nothrow) png_bytep[h];
  if (!rgb || !rows) {
    png_destroy_read_struct(&png, &info, nullptr);
    delete[] rgb;
    delete[] rows;
    return -44;
  }
  for (png_uint_32 y = 0; y < h; y++) rows[y] = rgb + (long)y * w * 3;
  png_read_image(png, rows);
  png_destroy_read_struct(&png, &info, nullptr);
  delete[] rows;
  fit_rgb(rgb, w, h, image_size, out);
  delete[] rgb;
  return 0;
}
#endif  // HAVE_LIBPNG

}  // namespace

extern "C" {

// Decode an ENCODED image file (PPM P6 / BMP / JPEG / PNG, dispatched on
// magic bytes) into float32 [image_size, image_size, 3] in [0, 1], center-
// cropped or tiled to fit.  Returns 0 on success; -4 for an unsupported or
// unrecognized format (caller falls back to Python-side decoding); negative
// codec-specific codes for corrupt files.
int tl_load_image(const char* path, int image_size, float* out) {
  long n = 0;
  uint8_t* b = read_file(path, &n);
  if (!b) return -1;
  int rc = -4;
  if (n >= 2 && b[0] == 'P' && b[1] == '6') {
    rc = decode_ppm(b, n, image_size, out);
  } else if (n >= 2 && b[0] == 'B' && b[1] == 'M') {
    rc = decode_bmp(b, n, image_size, out);
  }
#ifdef HAVE_LIBJPEG
  else if (n >= 3 && b[0] == 0xFF && b[1] == 0xD8 && b[2] == 0xFF) {
    rc = decode_jpeg(b, n, image_size, out);
  }
#endif
#ifdef HAVE_LIBPNG
  else if (n >= 8 && b[0] == 0x89 && b[1] == 'P' && b[2] == 'N' && b[3] == 'G') {
    rc = decode_png(b, n, image_size, out);
  }
#endif
  delete[] b;
  return rc;
}

// Which optional codecs this build carries: bit 0 = JPEG, bit 1 = PNG.
int tl_codecs(void) {
  int c = 0;
#ifdef HAVE_LIBJPEG
  c |= 1;
#endif
#ifdef HAVE_LIBPNG
  c |= 2;
#endif
  return c;
}

// Read a raw interleaved-RGB u8 file and produce a float32 HWC image of
// side `image_size`, values in [0, 1].  The stored side is inferred as
// isqrt(bytes/3).  Larger images are center-cropped; smaller ones tiled.
// Returns 0 on success, negative errno-style codes otherwise.
int tl_load_rgb(const char* path, int image_size, float* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  std::fseek(f, 0, SEEK_END);
  long bytes = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (bytes < 3) {
    std::fclose(f);
    return -2;
  }
  long side = (long)std::sqrt((double)(bytes / 3));
  while ((side + 1) * (side + 1) * 3 <= bytes) side++;
  while (side > 0 && side * side * 3 > bytes) side--;
  if (side <= 0) {
    std::fclose(f);
    return -2;
  }
  long need = side * side * 3;
  uint8_t* buf = new uint8_t[need];
  size_t got = std::fread(buf, 1, (size_t)need, f);
  std::fclose(f);
  if ((long)got != need) {
    delete[] buf;
    return -3;
  }
  const float inv = 1.0f / 255.0f;
  if (side >= image_size) {
    long o = (side - image_size) / 2;  // center crop
    for (int y = 0; y < image_size; y++) {
      const uint8_t* row = buf + ((o + y) * side + o) * 3;
      float* orow = out + (long)y * image_size * 3;
      for (int i = 0; i < image_size * 3; i++) orow[i] = row[i] * inv;
    }
  } else {  // tile up to target
    for (int y = 0; y < image_size; y++) {
      const uint8_t* row = buf + (long)(y % side) * side * 3;
      float* orow = out + (long)y * image_size * 3;
      for (int x = 0; x < image_size; x++) {
        const uint8_t* px = row + (long)(x % side) * 3;
        orow[x * 3 + 0] = px[0] * inv;
        orow[x * 3 + 1] = px[1] * inv;
        orow[x * 3 + 2] = px[2] * inv;
      }
    }
  }
  delete[] buf;
  return 0;
}

// Load a batch: `paths` is n C-strings; out is [n, image_size, image_size, 3]
// contiguous float32.  Returns the index of the first failing file, or -1 if
// all succeeded.
int tl_load_batch(const char** paths, int n, int image_size, float* out) {
  const long stride = (long)image_size * image_size * 3;
  for (int i = 0; i < n; i++) {
    if (tl_load_rgb(paths[i], image_size, out + (long)i * stride) != 0) return i;
  }
  return -1;
}

// Cut the (row, col) tile of a tile_h x tile_w grid out of a contiguous
// float32 NHWC batch — the host-side form of the reference's split_input
// slicing (train_spatial.py:241-290).  out is [n, th, tw, c].
void tl_crop_tiles(const float* batch, int n, int h, int w, int c, int row,
                   int col, int grid_h, int grid_w, float* out) {
  const int th = h / grid_h, tw = w / grid_w;
  const long img = (long)h * w * c, timg = (long)th * tw * c;
  const int y0 = row * th, x0 = col * tw;
  for (int i = 0; i < n; i++) {
    const float* src = batch + i * img;
    float* dst = out + i * timg;
    for (int y = 0; y < th; y++) {
      std::memcpy(dst + (long)y * tw * c,
                  src + ((long)(y0 + y) * w + x0) * c,
                  sizeof(float) * (size_t)tw * c);
    }
  }
}

}  // extern "C"
