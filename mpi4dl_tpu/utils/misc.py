"""Small utilities (reference: src/torchgems/utils.py, timing in benchmarks)."""

from __future__ import annotations

import statistics
import time
from typing import List


def is_power_two(n: int) -> bool:
    """True iff n is a power of two (reference utils.py:20-21)."""
    return n > 0 and (n & (n - 1)) == 0


def get_depth(version: int, n: int) -> int:
    """ResNet depth formula (reference utils.py:26-30): v1 → 6n+2, v2 → 9n+2."""
    if version == 1:
        return n * 6 + 2
    elif version == 2:
        return n * 9 + 2
    raise ValueError(f"unknown resnet version {version}")


class Timer:
    """Wall-clock timer for a single region; call start/stop, read .ms."""

    def __init__(self) -> None:
        self._t0 = 0.0
        self.ms = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        self.ms = (time.perf_counter() - self._t0) * 1e3
        return self.ms


class StepMeter:
    """Collects per-step times and prints images/sec the way the reference
    benchmarks do (mean/median over steps, reference
    benchmark_amoebanet_sp.py:322-367)."""

    def __init__(self, batch_size: int) -> None:
        self.batch_size = batch_size
        self.times_ms: List[float] = []

    def add(self, ms: float) -> None:
        self.times_ms.append(ms)

    def images_per_sec(self) -> float:
        if not self.times_ms:
            return 0.0
        return self.batch_size / (statistics.mean(self.times_ms) / 1e3)

    def summary(self) -> str:
        if not self.times_ms:
            return "no steps recorded"
        mean = statistics.mean(self.times_ms)
        med = statistics.median(self.times_ms)
        return (
            f"steps={len(self.times_ms)} mean={mean:.2f}ms median={med:.2f}ms "
            f"images/sec={self.images_per_sec():.3f}"
        )
