"""Small utilities (reference: src/torchgems/utils.py, timing in benchmarks)."""

from __future__ import annotations

import statistics
import time
from typing import List


def is_power_two(n: int) -> bool:
    """True iff n is a power of two (reference utils.py:20-21)."""
    return n > 0 and (n & (n - 1)) == 0


def get_depth(version: int, n: int) -> int:
    """ResNet depth formula (reference utils.py:26-30): v1 → 6n+2, v2 → 9n+2."""
    if version == 1:
        return n * 6 + 2
    elif version == 2:
        return n * 9 + 2
    raise ValueError(f"unknown resnet version {version}")


class Timer:
    """Wall-clock timer for a single region; call start/stop, read .ms."""

    def __init__(self) -> None:
        self._t0 = 0.0
        self.ms = 0.0

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        self.ms = (time.perf_counter() - self._t0) * 1e3
        return self.ms


def _percentile(sorted_ms: List[float], q: float) -> float:
    """Linear-interpolated percentile of an already-sorted sample."""
    if len(sorted_ms) == 1:
        return sorted_ms[0]
    pos = q * (len(sorted_ms) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_ms) - 1)
    frac = pos - lo
    return sorted_ms[lo] * (1 - frac) + sorted_ms[hi] * frac


class StepMeter:
    """Collects per-step times and prints images/sec the way the reference
    benchmarks do (mean/median over steps, reference
    benchmark_amoebanet_sp.py:322-367).

    ``warmup_steps`` makes the compile-step exclusion explicit: the first
    `warmup_steps` ``add()`` calls are counted (``warmup_dropped``) but
    excluded from the statistics — replacing the epoch-loop's implicit
    ``epoch > 0 or i > 0`` skip.  ``add`` returns whether the sample was
    measured, so telemetry can tag records."""

    def __init__(self, batch_size: int, warmup_steps: int = 0) -> None:
        self.batch_size = batch_size
        self.warmup_steps = warmup_steps
        self.warmup_dropped = 0
        self.times_ms: List[float] = []

    def add(self, ms: float) -> bool:
        if self.warmup_dropped < self.warmup_steps:
            self.warmup_dropped += 1
            return False
        self.times_ms.append(ms)
        return True

    def images_per_sec(self) -> float:
        if not self.times_ms:
            return 0.0
        return self.batch_size / (statistics.mean(self.times_ms) / 1e3)

    def stats(self) -> dict:
        """mean/median/p10/p90/min over the measured (post-warmup) steps."""
        if not self.times_ms:
            return {"steps": 0, "warmup_dropped": self.warmup_dropped}
        s = sorted(self.times_ms)
        return {
            "steps": len(s),
            "warmup_dropped": self.warmup_dropped,
            "mean_ms": statistics.mean(s),
            "median_ms": statistics.median(s),
            "p10_ms": _percentile(s, 0.10),
            "p90_ms": _percentile(s, 0.90),
            "min_ms": s[0],
            "images_per_sec": self.images_per_sec(),
        }

    def summary(self) -> str:
        if not self.times_ms:
            return "no steps recorded"
        st = self.stats()
        return (
            f"steps={st['steps']} mean={st['mean_ms']:.2f}ms "
            f"median={st['median_ms']:.2f}ms p10={st['p10_ms']:.2f}ms "
            f"p90={st['p90_ms']:.2f}ms min={st['min_ms']:.2f}ms "
            f"warmup_dropped={st['warmup_dropped']} "
            f"images/sec={st['images_per_sec']:.3f}"
        )
