"""Bounded retry with exponential backoff for transient I/O (ISSUE 15
satellite).

Extracted from ``mpi4dl_tpu.data.fetch_batch_with_retry`` so the data
pipeline and the checkpoint layer share ONE retry discipline: NFS blips,
GCS-fuse eviction races, and stale-handle errors are transient and worth a
couple of bounded retries; everything else (bad shapes, logic bugs) must
propagate immediately — retrying those only delays the crash.  On
exhaustion the ORIGINAL exception is re-raised, not the last one: the first
failure is the honest evidence, later ones are usually the same fault
echoing.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple, Type, TypeVar

T = TypeVar("T")


def retry_io(
    fn: Callable[[], T],
    *,
    retries: int = 2,
    backoff: float = 0.05,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    no_retry: Tuple[Type[BaseException], ...] = (),
    _sleep: Callable[[float], None] = time.sleep,
) -> T:
    """Call ``fn`` with up to ``retries`` retries around ``exceptions``,
    sleeping ``backoff`` seconds (doubling each time) between attempts;
    re-raises the ORIGINAL exception when the budget is exhausted.

    ``no_retry`` carves deterministic subclasses out of ``exceptions``
    (e.g. ``FileNotFoundError`` out of ``OSError``): those raise
    immediately — a vanished file is not an NFS blip and will never
    succeed on retry."""
    delay = backoff
    first = None
    for remaining in range(retries, -1, -1):
        try:
            return fn()
        except exceptions as e:
            if no_retry and isinstance(e, no_retry):
                raise
            if first is None:
                first = e
            if remaining == 0:
                raise first
            _sleep(delay)
            delay *= 2.0
    raise AssertionError("unreachable")  # loop always returns or raises
