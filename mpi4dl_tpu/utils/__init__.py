from mpi4dl_tpu.utils.misc import is_power_two, get_depth, Timer, StepMeter
from mpi4dl_tpu.utils.retry import retry_io

__all__ = ["is_power_two", "get_depth", "Timer", "StepMeter", "retry_io"]
