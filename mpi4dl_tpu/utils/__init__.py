from mpi4dl_tpu.utils.misc import is_power_two, get_depth, Timer, StepMeter

__all__ = ["is_power_two", "get_depth", "Timer", "StepMeter"]
