"""ctypes bridge to the native C++ tile loader (native/tileloader.cc).

Builds libtileloader.so with g++ on first use (cached next to the source, or
under $MPI4DL_TPU_NATIVE_DIR) and exposes numpy-facing wrappers; every entry
point degrades gracefully to None/False when no compiler is available, and
data.py keeps a pure-numpy fallback, so the native path is an accelerator,
never a hard dependency (pybind11 is not available in this environment —
ctypes over an extern-C ABI is the binding layer)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native",
        "tileloader.cc",
    )


def _build(src: str, out: str) -> bool:
    """Compile the loader, probing for optional system codecs: full build
    (libjpeg + libpng) first, then degrading — the .so always exists if g++
    does; codecs are compile-gated (tl_codecs() reports what's in)."""
    base = ["g++", "-O3", "-shared", "-fPIC", "-o", out, src]
    variants = [
        base + ["-DHAVE_LIBJPEG", "-DHAVE_LIBPNG", "-ljpeg", "-lpng"],
        base + ["-DHAVE_LIBJPEG", "-ljpeg"],
        base + ["-DHAVE_LIBPNG", "-lpng"],
        base,
    ]
    for cmd in variants:
        try:
            r = subprocess.run(cmd, capture_output=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            return False
        if r.returncode == 0 and os.path.exists(out):
            return True
    return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it if needed; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src = _source_path()
        if not os.path.exists(src):
            return None
        cache_dir = os.environ.get(
            "MPI4DL_TPU_NATIVE_DIR", os.path.dirname(src)
        )
        so = os.path.join(cache_dir, "libtileloader.so")
        if not (
            os.path.exists(so)
            and os.path.getmtime(so) >= os.path.getmtime(src)
        ):
            os.makedirs(cache_dir, exist_ok=True)
            if not _build(src, so):
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        # A stale cached .so from an older source (e.g. timestamp-preserving
        # installs defeating the mtime guard) may lack newer symbols; rebuild
        # once, then degrade to None rather than raising AttributeError.
        if not hasattr(lib, "tl_load_image"):
            if not _build(src, so):
                return None
            try:
                lib = ctypes.CDLL(so)
            except OSError:
                return None
            if not hasattr(lib, "tl_load_image"):
                return None
        lib.tl_load_rgb.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ]
        lib.tl_load_rgb.restype = ctypes.c_int
        lib.tl_load_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ]
        lib.tl_load_batch.restype = ctypes.c_int
        lib.tl_crop_tiles.argtypes = [
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ]
        lib.tl_crop_tiles.restype = None
        lib.tl_load_image.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ]
        lib.tl_load_image.restype = ctypes.c_int
        lib.tl_codecs.argtypes = []
        lib.tl_codecs.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def codecs() -> dict:
    """Which optional codecs the native build carries."""
    lib = get_lib()
    bits = lib.tl_codecs() if lib is not None else 0
    return {"jpeg": bool(bits & 1), "png": bool(bits & 2)}


def load_image(path: str, image_size: int) -> Optional[np.ndarray]:
    """Native decode of an ENCODED image (PPM/BMP always; JPEG/PNG when the
    build found the system codecs) → [S, S, 3] float32 in [0,1]; None when
    unavailable or the format is not supported by this build."""
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty((image_size, image_size, 3), np.float32)
    if lib.tl_load_image(path.encode(), image_size, out) != 0:
        return None
    return out


def load_rgb(path: str, image_size: int) -> Optional[np.ndarray]:
    """Native load of one raw-RGB file → [S, S, 3] float32 in [0,1]."""
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty((image_size, image_size, 3), np.float32)
    if lib.tl_load_rgb(path.encode(), image_size, out) != 0:
        return None
    return out


def load_batch(paths: Sequence[str], image_size: int) -> Optional[np.ndarray]:
    """Native load of a batch of raw-RGB files → [N, S, S, 3] float32."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(paths)
    out = np.empty((n, image_size, image_size, 3), np.float32)
    arr = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    if lib.tl_load_batch(arr, n, image_size, out) != -1:
        return None
    return out


def crop_tiles(
    batch: np.ndarray, row: int, col: int, grid_h: int, grid_w: int
) -> Optional[np.ndarray]:
    """Native tile crop (host-side split_input analog): [N,H,W,C] → tile
    (row, col) of a grid_h x grid_w grid."""
    lib = get_lib()
    if lib is None:
        return None
    batch = np.ascontiguousarray(batch, np.float32)
    n, h, w, c = batch.shape
    out = np.empty((n, h // grid_h, w // grid_w, c), np.float32)
    lib.tl_crop_tiles(batch, n, h, w, c, row, col, grid_h, grid_w, out)
    return out
