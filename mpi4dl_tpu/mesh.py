"""Device-mesh construction.

The reference derives a rank topology by hand from MPI world size
(``src/torchgems/comm.py:44-137``: split_rank math, spatial groups, GEMS rank
inversion).  On TPU all of that becomes a named :class:`jax.sharding.Mesh`:

- ``data``  — outer data parallelism (reference allreduce groups)
- ``stage`` — pipeline/layer-parallel stages (reference split_rank)
- ``sph``/``spw`` — spatial tile grid over image H/W (reference spatial ranks)

GEMS needs no axis: the mirror placement is a compile-time permutation of the
``stage`` axis (see parallel/gems.py), not a second set of processes.

Axis order is (data, stage, sph, spw) so that the *innermost* (fastest-moving,
most-bandwidth-coupled on ICI) axes are the spatial tile axes that exchange
halos every conv, and stage neighbours are contiguous blocks — the topological
analog of the reference pinning spatial ranks to one node's 4 GPUs
(``comm.py:34-41``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical mesh-axis names.  All collective calls and PartitionSpecs in the
# package reference these constants (not raw strings) so the static analyzer
# (mpi4dl_tpu/analysis, rule `collective-axis`) can verify every axis name
# against this single source of truth.
AXIS_DATA = "data"
AXIS_STAGE = "stage"
AXIS_SPH = "sph"
AXIS_SPW = "spw"

AXES = (AXIS_DATA, AXIS_STAGE, AXIS_SPH, AXIS_SPW)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    data: int = 1
    stage: int = 1
    sph: int = 1
    spw: int = 1

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.stage, self.sph, self.spw)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape))

    @classmethod
    def from_config(cls, cfg) -> "MeshSpec":
        """Derive the mesh from a ParallelConfig, mirroring the reference's
        mp_size math (comm.py:59-67): the spatial region occupies
        num_spatial_parts devices which double as the first `spatial_size`
        pipeline stage(s)."""
        if cfg.spatial_size > 0 and cfg.spatial_part_size > 1:
            if cfg.slice_method == "square":
                g = int(np.sqrt(cfg.spatial_part_size))
                sph, spw = g, g
            elif cfg.slice_method == "vertical":
                sph, spw = 1, cfg.spatial_part_size
            else:  # horizontal
                sph, spw = cfg.spatial_part_size, 1
        else:
            sph, spw = 1, 1
        return cls(data=cfg.data_parallel, stage=cfg.split_size, sph=sph, spw=spw)


def build_mesh(
    spec: MeshSpec,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named Mesh of shape (data, stage, sph, spw).

    With fewer physical devices than ``spec.size`` this raises — tests use the
    8-device CPU fixture; the driver validates multi-chip via
    ``__graft_entry__.dryrun_multichip``.
    """
    devices = list(devices if devices is not None else jax.devices())
    need = spec.size
    if len(devices) < need:
        raise ValueError(
            f"mesh {spec} needs {need} devices, have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(spec.shape)
    return Mesh(arr, AXES)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshSpec())


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Multi-host initialization — the TPU-native analog of the reference's
    ``dist.init_process_group("mpi")`` world init (``comm.py:154-159``).

    On TPU pods ``jax.distributed.initialize()`` auto-discovers the
    coordinator and peers from the TPU environment; elsewhere pass the
    coordinator address + process count/id (or set the standard
    ``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``).
    After this, ``jax.devices()`` spans every host and :func:`build_mesh`
    builds pod-wide meshes — with the default (data, stage, sph, spw) axis
    order, the outermost ``data`` axis lands across hosts (DCN) and the
    innermost spatial tile axes stay within a host's ICI domain, which is
    the right network mapping for gradient-allreduce-over-DCN /
    halo-exchange-over-ICI.  Returns the process index.  Idempotent: a
    second call is a no-op.
    """
    import os

    import jax

    # Probe WITHOUT touching the backend: jax.process_count() would
    # initialize XLA, after which distributed.initialize() always raises.
    try:
        from jax._src.distributed import global_state

        already = global_state.client is not None
    except Exception:  # noqa: BLE001 — internals moved; assume fresh
        already = False
    if not already:
        kwargs = {}
        if coordinator_address:
            kwargs = dict(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        # A failure is only benign when NO distributed environment was
        # configured — via args, the standard env vars, or a TPU pod
        # environment; swallowing it there would silently train N
        # unsynchronized single-process replicas.
        configured = bool(coordinator_address) or any(
            os.environ.get(v)
            for v in (
                "JAX_COORDINATOR_ADDRESS",
                "COORDINATOR_ADDRESS",
                "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS",
            )
        )
        try:
            jax.distributed.initialize(**kwargs)
        except (RuntimeError, ValueError) as e:
            if configured:
                raise
            import logging

            logging.getLogger(__name__).warning(
                "single-process mode (%s)", e
            )
    return jax.process_index()
