"""Chrome/Perfetto trace-event export of the analytic schedule + RunLogs.

Everything the analytic stack knows about one compiled step — the overlap
ledger's start/done wire windows (obs/overlap.py, now with simulated-clock
timestamps on every :class:`~mpi4dl_tpu.obs.overlap.WireEvent`), the
per-scope analytical timeline (obs/timeline.py), and the pipeline
tick/bubble arithmetic — rendered as Trace Event Format JSON that loads
directly in ``chrome://tracing`` / https://ui.perfetto.dev.  Plus
:func:`trace_from_runlog`: the MEASURED step walls and resilience events of
any RunLog file on the same timeline format, so a simulated schedule and a
real run are inspectable side by side in the same viewer.

Lanes (one Perfetto "process" per view, named via ``M`` metadata events):

- ``schedule sim``: the simulated wire — one complete (``ph: X``) span per
  collective transfer over its ``begin..end`` wire window, a ``device
  stall`` lane for the exposed portion ending at the done, and ``s``/``f``
  flow arrows tying each async start's issue to its done-side stall;
- ``analytical``: per-scope serialized compute and wire spans (the
  obs/timeline.py ranking, laid end to end);
- ``pipeline``: per-stage tick lanes — busy ticks plus fill/drain bubble
  spans from :func:`~mpi4dl_tpu.obs.timeline.pipeline_ticks` (a
  *visualization* of the schedule arithmetic: stage ``s`` is drawn active
  over ticks ``[s, ticks - (S-1-s))`` — exactly ``parts`` busy ticks under
  GPipe; under 1F1B the window includes the steady-state fwd/bwd
  alternation);
- ``measured``: RunLog step records as wall-clock spans, with checkpoint
  saves and anomaly/preempt/quarantine instants on an event lane.

Timestamps are microseconds (the format's unit); simulated lanes sit on the
walker's local clock, measured lanes on seconds-since-first-record.  CLI:
``python -m mpi4dl_tpu.obs trace [--families lp,... | --runlog F] --out
trace.json``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from mpi4dl_tpu.obs.costs import (
    DEFAULT_ICI_BYTES_PER_S,
    ici_bytes_per_s,
    peak_flops,
)
from mpi4dl_tpu.obs.overlap import UNSCOPED, _events, wire_class
from mpi4dl_tpu.obs.timeline import (
    bubble_fraction,
    hlo_scope_costs,
    pipeline_ticks,
)

#: The trace-event container's display unit hint.
DISPLAY_TIME_UNIT = "ms"


def _us(ms: float) -> float:
    """Walker/report milliseconds -> trace-event microseconds."""
    return round(ms * 1000.0, 3)


def _span(name: str, pid: int, tid: int, ts_ms: float, dur_ms: float,
          cat: str, args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    ev: Dict[str, Any] = {
        "name": name, "ph": "X", "cat": cat, "pid": pid, "tid": tid,
        "ts": _us(ts_ms), "dur": max(_us(dur_ms), 0.0),
    }
    if args:
        ev["args"] = args
    return ev


def _instant(name: str, pid: int, tid: int, ts_ms: float, cat: str,
             args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    ev: Dict[str, Any] = {
        "name": name, "ph": "i", "s": "t", "cat": cat, "pid": pid,
        "tid": tid, "ts": _us(ts_ms),
    }
    if args:
        ev["args"] = args
    return ev


def _meta(pid: int, process: Optional[str] = None, tid: int = 0,
          thread: Optional[str] = None) -> Dict[str, Any]:
    if process is not None:
        return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": process}}
    return {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": thread or ""}}


def _resolve_rates(peak: Optional[float], ici_bw: Optional[float],
                   device) -> Tuple[Optional[float], float]:
    """Same device-derived defaulting as overlap_ledger /
    analytical_timeline (CPU hosts get the labeled nominal constants)."""
    if peak is None and device is not None:
        peak, _ = peak_flops(device, allow_cpu_nominal=True)
    if ici_bw is None:
        if device is not None:
            ici_bw, _ = ici_bytes_per_s(device)
        else:
            ici_bw = DEFAULT_ICI_BYTES_PER_S
    return peak, float(ici_bw or 0.0)


def hlo_trace_events(
    hlo_text: str,
    *,
    label: str = "step",
    peak: Optional[float] = None,
    ici_bw: Optional[float] = None,
    device=None,
    schedule: Optional[str] = None,
    stages: Optional[int] = None,
    parts: Optional[int] = None,
    pid_base: int = 1,
) -> List[Dict[str, Any]]:
    """Trace events for one compiled module: simulated wire lane, analytical
    per-scope lanes, and (with ``schedule``/``stages``/``parts``) per-stage
    pipeline tick lanes.  ``pid_base`` spaces multiple modules — each module
    occupies pids ``pid_base .. pid_base+2``."""
    peak, ici_bw = _resolve_rates(peak, ici_bw, device)
    events, sim = _events(hlo_text, peak, ici_bw)
    sim_pid, ana_pid, pipe_pid = pid_base, pid_base + 1, pid_base + 2

    out: List[Dict[str, Any]] = [
        _meta(sim_pid, process=f"schedule sim [{label}]"),
        _meta(sim_pid, tid=0, thread="wire"),
        _meta(sim_pid, tid=1, thread="device stalls"),
    ]
    flow_id = 0
    for e in events:
        scope = e.scope or UNSCOPED
        out.append(_span(
            f"{e.cls} {scope}", sim_pid, 0, e.begin_ms, e.wire_ms, "wire",
            args={
                "bytes": e.bytes, "wire_ms": round(e.wire_ms, 4),
                "hidden_ms": round(e.hidden_ms, 4),
                "exposed_ms": round(e.exposed_ms, 4),
                "sync": e.sync, "quantized": e.quantized,
                "wire_class": wire_class(e.scope, e.cls), "comp": e.comp,
            },
        ))
        if e.exposed_ms > 0:
            out.append(_span(
                f"stall {e.cls} {scope}", sim_pid, 1,
                e.done_ms - e.exposed_ms, e.exposed_ms, "stall",
                args={"bytes": e.bytes, "sync": e.sync},
            ))
        if not e.sync:
            # Flow arrow: the async start's issue point to its done-side
            # landing — the visual "this window hides that transfer".
            flow_id += 1
            common = {"cat": "wire-flow", "name": f"{e.cls} {scope}",
                      "id": flow_id, "pid": sim_pid}
            out.append({**common, "ph": "s", "tid": 0,
                        "ts": _us(e.begin_ms)})
            out.append({**common, "ph": "f", "bp": "e", "tid": 1,
                        "ts": _us(e.done_ms)})

    # -- analytical per-scope lanes (serialized, laid end to end) ----------
    out.append(_meta(ana_pid, process=f"analytical [{label}]"))
    out.append(_meta(ana_pid, tid=0, thread="compute (serialized)"))
    out.append(_meta(ana_pid, tid=1, thread="wire (serialized)"))
    costs = hlo_scope_costs(hlo_text)
    rows = sorted(
        costs.items(),
        key=lambda kv: -(kv[1]["flops"] + kv[1]["collective_bytes"]),
    )
    comp_t = wire_t = 0.0
    for scope, c in rows:
        name = scope or UNSCOPED
        if c["flops"] and peak:
            dur = c["flops"] / peak * 1e3
            out.append(_span(name, ana_pid, 0, comp_t, dur, "compute",
                             args={"flops": c["flops"]}))
            comp_t += dur
        if c["collective_bytes"] and ici_bw:
            dur = c["collective_bytes"] / ici_bw * 1e3
            out.append(_span(
                name, ana_pid, 1, wire_t, dur, "wire",
                args={"bytes": int(c["collective_bytes"]),
                      "count": int(c["collective_count"])},
            ))
            wire_t += dur

    # -- pipeline tick lanes -----------------------------------------------
    ticks = (pipeline_ticks(schedule, stages, parts)
             if schedule and stages and parts else None)
    if ticks is not None and stages and parts:
        bubble = bubble_fraction(schedule or "", stages, parts)
        # Share the simulated step's time scale so the lanes line up with
        # the wire lane; an all-zero-cost module still gets unit ticks.
        tick_ms = (sim.duration_ms / parts) if sim.duration_ms > 0 else 1.0
        out.append(_meta(pipe_pid, process=f"pipeline [{label}]"))
        for s in range(stages):
            out.append(_meta(pipe_pid, tid=s, thread=f"stage {s}"))
            head, tail = s, stages - 1 - s
            if head:
                out.append(_span("bubble (fill)", pipe_pid, s, 0.0,
                                 head * tick_ms, "bubble"))
            for t in range(head, ticks - tail):
                name = (f"mb{t - head}" if schedule == "gpipe"
                        else f"tick {t}")
                out.append(_span(
                    name, pipe_pid, s, t * tick_ms, tick_ms, "tick",
                    args={"schedule": schedule, "tick": t,
                          "bubble_fraction": bubble},
                ))
            if tail:
                out.append(_span("bubble (drain)", pipe_pid, s,
                                 (ticks - tail) * tick_ms, tail * tick_ms,
                                 "bubble"))
    return out


#: RunLog record kinds rendered as instants on the measured event lane.
_RUNLOG_INSTANTS = (
    "anomaly", "recovery", "preempt", "quarantine", "restore", "drill",
    "supervisor",
)


def trace_from_runlog(
    records: List[Dict[str, Any]],
    *,
    label: str = "run",
    pid_base: int = 90,
) -> List[Dict[str, Any]]:
    """Measured lanes from RunLog records: step walls as spans (ended at
    the record's write time, so the span is the step's real wall window),
    checkpoint saves as gather+write spans, resilience/supervisor events as
    instants.  Timeline zero is the file's first record."""
    ts = [float(r["t"]) for r in records if r.get("t") is not None]
    if not ts:
        return []
    t0 = min(ts)
    pid = pid_base
    out: List[Dict[str, Any]] = [
        _meta(pid, process=f"measured [{label}]"),
        _meta(pid, tid=0, thread="steps"),
        _meta(pid, tid=1, thread="checkpoints"),
        _meta(pid, tid=2, thread="events"),
    ]
    for r in records:
        kind, t = r.get("kind"), r.get("t")
        if t is None:
            continue
        end_ms = (float(t) - t0) * 1e3
        if kind == "step" and r.get("ms") is not None:
            dur = float(r["ms"])
            out.append(_span(
                f"step e{r.get('epoch', '?')}:{r.get('step', '?')}",
                pid, 0, max(end_ms - dur, 0.0), dur, "step",
                args={k: r.get(k) for k in (
                    "loss", "images_per_sec", "measured",
                    "memory_peak_bytes", "hbm_skew", "jit_cache_size",
                ) if r.get(k) is not None},
            ))
        elif kind == "checkpoint":
            dur = (float(r.get("gather_ms") or 0.0)
                   + float(r.get("write_ms") or 0.0))
            out.append(_span(
                f"checkpoint {r.get('step_id', '?')}", pid, 1,
                max(end_ms - dur, 0.0), dur, "checkpoint",
                args={k: r.get(k) for k in (
                    "bytes", "gather_ms", "write_ms", "peak_pending_bytes",
                ) if r.get(k) is not None},
            ))
        elif kind in _RUNLOG_INSTANTS:
            detail = (r.get("reason") or r.get("failure_class")
                      or r.get("scenario") or "")
            name = f"{kind} {detail}".strip()
            out.append(_instant(name, pid, 2, end_ms, "event",
                                args={"gstep": r.get("gstep")}))
    return out


def chrome_trace(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Wrap event lists into the JSON-object trace container the viewers
    load (``displayTimeUnit`` is a hint; timestamps stay microseconds)."""
    return {"traceEvents": events, "displayTimeUnit": DISPLAY_TIME_UNIT}
