"""RunLog: per-run JSONL telemetry sink.

One run = one ``.jsonl`` file; one line = one record, every record carrying
``kind`` (meta | cost | step | summary | hbm | timeline | overlap |
mem_probe | junction_sweep | xprof_ops | readiness | anomaly | recovery |
preempt | checkpoint | restore | quarantine | drill | drill_summary |
supervisor | supervisor_summary | fleet | fleet_summary | <custom> — field
reference in docs/observability.md), ``t`` (unix
seconds) and ``schema``.  The first record is the run's metadata — full config, mesh spec,
device kind, jax version, active ``MPI4DL_*`` hatches — so a step file is
self-describing: no PERF_NOTES archaeology to learn what produced it
(VERDICT r4 weak-9, the bench ladder's rung_config lesson applied to every
training loop).

The sink is line-buffered and flushes per record, so a crash mid-epoch keeps
everything logged so far — same rationale as the try/finally around
``jax.profiler.stop_trace`` in benchmarks/common.py.

``python -m mpi4dl_tpu.obs report run.jsonl`` renders a file (obs/report.py);
:func:`read_runlog` is the programmatic reader.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1


def _jsonable(obj: Any) -> Any:
    """Best-effort conversion to JSON-serializable data (dataclasses, dtypes,
    numpy scalars, tuples); falls back to repr so telemetry never raises."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _jsonable(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "item"):  # numpy / jax scalars
        try:
            return obj.item()
        except Exception:  # noqa: BLE001  # analysis: ok(swallow-except)
            pass  # deliberate: falls through to the repr() fallback below
    return repr(obj)


def active_hatches() -> Dict[str, str]:
    """Environment values of every declared ``MPI4DL_*`` hatch that is SET
    (config.HATCHES is the registry; unset hatches are omitted — their
    defaults are documented there)."""
    from mpi4dl_tpu.config import HATCHES

    out: Dict[str, str] = {}
    for name in HATCHES:
        val = os.environ.get(name)
        if val is not None:
            out[name] = val
    return out


def device_memory_watermark(device=None) -> Optional[int]:
    """``peak_bytes_in_use`` from ``device.memory_stats()``; None where the
    backend has no allocator stats (CPU)."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if not stats:
        return None
    return stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")


def device_memory_watermarks(devices=None) -> Optional[Dict[str, Any]]:
    """Watermarks across ALL local devices — device 0 alone hides SP
    imbalance (an unevenly sliced grid OOMs on the hot tile while device 0
    reads healthy).  ``max``/``min``/``hbm_skew`` (max − min) plus the raw
    ``per_device`` list; None where no device reports allocator stats."""
    import jax

    devs = devices if devices is not None else jax.local_devices()
    peaks: List[int] = []
    for dev in devs:
        stats = getattr(dev, "memory_stats", lambda: None)()
        if not stats:
            continue
        peak = stats.get("peak_bytes_in_use") or stats.get("bytes_in_use")
        if peak:
            peaks.append(int(peak))
    if not peaks:
        return None
    return {
        "max": max(peaks),
        "min": min(peaks),
        "hbm_skew": max(peaks) - min(peaks),
        "devices": len(peaks),
        "per_device": peaks,
    }


def host_rss_peak_bytes() -> Optional[int]:
    """Process peak RSS — the memory watermark that exists on every host,
    including CPU backends whose devices report no allocator stats."""
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KB on Linux, bytes on macOS.
        return int(peak if sys.platform == "darwin" else peak * 1024)
    except Exception:  # noqa: BLE001 — non-POSIX host
        return None


def jit_cache_size(fn) -> Optional[int]:
    """Compiled-variant count of a jitted callable — the retrace probe.  A
    per-step record sequence where this GROWS past 1 is a retrace hazard
    (shape/dtype churn in the loop; analysis rule ``retrace`` finds the
    static cases, this catches the dynamic ones)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001
        return None


class RunLog:
    """Append-only JSONL writer for one run."""

    def __init__(self, path: str):
        self.path = path
        # Most recent record written (any kind) — the step watchdog dumps it
        # to stderr alongside live stacks when a step blows its budget.
        self.last_record: Optional[Dict[str, Any]] = None
        # Most recent record PER KIND: the watchdog pairs the last record
        # with the last `checkpoint` record so a stall inside a shard-
        # gather is distinguishable from a data stall.
        self.last_by_kind: Dict[str, Dict[str, Any]] = {}
        # The async checkpoint writer emits `checkpoint` records from its
        # worker thread while the training thread writes `step` records.
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")

    @classmethod
    def create(cls, directory: str, prefix: str = "run") -> "RunLog":
        """New uniquely-named run file under ``directory``."""
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = f"{prefix}-{stamp}-p{os.getpid()}"
        path = os.path.join(directory, base + ".jsonl")
        n = 0
        while os.path.exists(path):  # same second, same pid: suffix
            n += 1
            path = os.path.join(directory, f"{base}-{n}.jsonl")
        return cls(path)

    # -- records -----------------------------------------------------------

    def write(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec = {"kind": kind, "schema": SCHEMA_VERSION, "t": time.time()}
        rec.update({k: _jsonable(v) for k, v in fields.items()})
        with self._lock:
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
            self.last_record = rec
            self.last_by_kind[kind] = rec
        return rec

    def write_meta(self, config: Any = None, mesh_spec: Any = None,
                   argv: Optional[List[str]] = None, **extra: Any) -> Dict[str, Any]:
        """The run's self-description record (always the file's first line)."""
        import jax

        devices = jax.devices()
        return self.write(
            "meta",
            config=config,
            mesh=mesh_spec,
            argv=argv,
            jax_version=jax.__version__,
            backend=jax.default_backend(),
            device_count=len(devices),
            device_kind=getattr(devices[0], "device_kind", None),
            platform=devices[0].platform,
            hatches=active_hatches(),
            **extra,
        )

    def write_step(self, *, epoch: int, step: int, ms: float,
                   images_per_sec: float, loss: float, accuracy: float,
                   step_fn=None, measured: bool = True,
                   **extra: Any) -> Dict[str, Any]:
        """One optimizer step.  ``measured=False`` marks warmup/compile steps
        (excluded from summary stats, kept in the record stream)."""
        wm = device_memory_watermarks()
        return self.write(
            "step",
            epoch=epoch,
            step=step,
            ms=round(float(ms), 3),
            images_per_sec=round(float(images_per_sec), 3),
            loss=float(loss),
            accuracy=float(accuracy),
            measured=bool(measured),
            memory_peak_bytes=None if wm is None else wm["max"],
            memory_peak_bytes_min=None if wm is None else wm["min"],
            hbm_skew=None if wm is None else wm["hbm_skew"],
            host_rss_peak_bytes=host_rss_peak_bytes(),
            jit_cache_size=jit_cache_size(step_fn) if step_fn is not None else None,
            **extra,
        )

    def close(self) -> None:
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_runlog(path: str) -> List[Dict[str, Any]]:
    """Parse one run file back into records, skipping malformed lines with a
    stderr note — a crashed leg truncates its last line mid-write, and the
    report/trend tooling promises to render crashed-run files."""
    import sys

    out: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                sys.stderr.write(
                    f"[obs] {path}:{lineno}: skipping torn record "
                    f"({len(line)} bytes) — truncated mid-write?\n")
                continue
    return out
