"""Derived cost metrics: FLOPs, bytes, MFU, arithmetic intensity.

The FLOP source is XLA's own cost model (``compiled.cost_analysis()``), the
same number bench.py's honesty instrumentation uses: a while/scan body is
counted ONCE (trip counts are not folded in — verified empirically in r4),
so for the scan-stacked step builders the reported figure is per optimizer
step.  MFU is achieved FLOP/s over the chip's published bf16 peak
(:data:`PEAK_BF16_FLOPS` — the single source of truth, imported by bench.py).

On CPU hosts there is no defensible peak, so :func:`peak_flops` returns
``(None, None)`` by default (bench.py's rule: never fake an MFU on the
host).  The report surface (obs/report.py) instead passes
``allow_cpu_nominal=True`` to get :data:`CPU_NOMINAL_PEAK_FLOPS` labeled
``"nominal-cpu"`` — a fixed reference point that makes CPU smoke-run MFU
lines comparable run-over-run while being explicit that it is NOT a
hardware utilization claim.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

# bf16 peak FLOP/s by TPU generation (public numbers); matched by substring
# of jax.devices()[0].device_kind.  Order matters: first match wins, so the
# more specific v5 spellings precede the bare "v5".
PEAK_BF16_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]

# Labeled reference peak for CPU smoke runs (see module docstring) — a
# nominal 100 GFLOP/s core, not a measured host capability.
CPU_NOMINAL_PEAK_FLOPS = 1e11

# Aggregate per-chip ICI bandwidth (public numbers, bytes/s): total
# inter-chip interconnect bandwidth per chip — the denominator of the
# analytical collective-time estimates in obs/timeline.py.  Matched like
# PEAK_BF16_FLOPS (first substring wins, specific v5 spellings first).
ICI_BYTES_PER_S = [
    ("v6", 4.48e11),      # 3,584 Gbps
    ("v5p", 6.0e11),      # 4,800 Gbps
    ("v5 lite", 2.0e11), ("v5e", 2.0e11), ("v5litepod", 2.0e11),  # 1,600 Gbps
    ("v5", 6.0e11),
    ("v4", 3.0e11),       # 2,400 Gbps
    ("v3", 8.2e10),
    ("v2", 6.2e10),
]

# Labeled nominal interconnect for CPU smoke runs — a fixed 10 GB/s
# reference so analytical timelines are comparable run-over-run on the
# virtual mesh (NOT a host measurement; same contract as the nominal peak).
DEFAULT_ICI_BYTES_PER_S = 1e10


def ici_bytes_per_s(device) -> Tuple[float, str]:
    """(aggregate ICI bytes/s, source) for a jax device; source mirrors
    :func:`peak_flops`: ``"table"``, ``"assumed-max"``, ``"nominal-cpu"``."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    if device is None or device.platform == "cpu":
        return DEFAULT_ICI_BYTES_PER_S, "nominal-cpu"
    for sub, bw in ICI_BYTES_PER_S:
        if sub in kind:
            return bw, "table"
    return max(b for _, b in ICI_BYTES_PER_S), "assumed-max"


def peak_flops(device, allow_cpu_nominal: bool = False
               ) -> Tuple[Optional[float], Optional[str]]:
    """(peak FLOP/s, source) for a jax device.

    source: ``"table"`` (known kind), ``"assumed-max"`` (unknown accelerator
    — over-estimate so an mfu>1 impossibility check stays sound, bench.py's
    rule), ``"nominal-cpu"`` (only with ``allow_cpu_nominal``), or None.
    """
    kind = (getattr(device, "device_kind", "") or "").lower()
    if device.platform == "cpu":
        if allow_cpu_nominal:
            return CPU_NOMINAL_PEAK_FLOPS, "nominal-cpu"
        return None, None
    for sub, peak in PEAK_BF16_FLOPS:
        if sub in kind:
            return peak, "table"
    return max(p for _, p in PEAK_BF16_FLOPS), "assumed-max"


def compiled_cost(compiled) -> Dict[str, Optional[float]]:
    """{'flops', 'bytes_accessed'} from a jax.stages.Compiled's cost
    analysis (None where the backend reports nothing useful)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — any backend may lack cost_analysis
        return {"flops": None, "bytes_accessed": None}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0)) or None
    nbytes = float(ca.get("bytes accessed", 0.0)) or None
    return {"flops": flops, "bytes_accessed": nbytes}


def step_cost(step_fn, *args) -> Dict[str, Optional[float]]:
    """Lower + compile ``step_fn(*args)`` and return :func:`compiled_cost`.
    Prefer :func:`compiled_cost` on an existing Compiled to avoid a second
    compilation of the same program."""
    return compiled_cost(step_fn.lower(*args).compile())


def mfu(flops_per_step: Optional[float], step_ms: Optional[float],
        peak: Optional[float], n_devices: int = 1) -> Optional[float]:
    """Model FLOP utilization: (flops/step) / (step seconds) / (peak x N).

    ``cost_analysis`` on an SPMD program reports the PER-DEVICE module's
    FLOPs, so the usual call passes per-device flops with ``n_devices=1``;
    pass aggregate flops with the device count only when you summed shards
    yourself."""
    if not flops_per_step or not step_ms or not peak or step_ms <= 0:
        return None
    return (flops_per_step / (step_ms / 1e3)) / (peak * max(n_devices, 1))


def arithmetic_intensity(flops: Optional[float],
                         bytes_accessed: Optional[float]) -> Optional[float]:
    """FLOPs per HBM byte — the roofline abscissa; low values say the step
    is bandwidth-bound and more MFU needs fusion/layout work, not schedule
    work (PERF_NOTES r5's 0.10-0.18 MFU diagnosis made quantitative)."""
    if not flops or not bytes_accessed:
        return None
    return flops / bytes_accessed
