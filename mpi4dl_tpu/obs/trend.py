"""Fleet-trend rollup: trajectory + regression gate over a telemetry dir.

``obs report --trend DIR`` scans one directory for every RunLog
(``*.jsonl``) and every bench-ladder artifact (``BENCH_*.json``), renders
the per-metric trajectory over time, and gates the NEWEST run of each
RunLog series against its predecessor with the same extractors and
threshold semantics as ``obs report --compare`` — exit 1 on a breach, so a
CI lane pointed at its telemetry artifacts becomes a perf-regression gate
with zero extra plumbing.

Two deliberate scoping rules keep the gate honest:

- **series-scoped**: RunLog files group by their ``RunLog.create`` prefix
  (``bench-resnet56-<stamp>-p<pid>.jsonl`` -> series ``bench-resnet56``),
  and only newest-vs-previous WITHIN a series gates — a supervisor drill
  log is never "a regression against" a bench log that happens to sort
  next to it;
- **bench artifacts are informational**: ``BENCH_*.json`` rung rows
  (img/s, MFU) render in the trajectory but never gate.  Half the
  historical artifacts are crash tails whose outer JSON is front-truncated
  (``parsed: null``); the reader prefers the ``parsed`` block, attempts a
  bounded brace-scan recovery of the tail, and skips with a note — a
  missing rung must not turn the trend lane permanently red.

:func:`trend_report` is the programmatic product (the ``--trend-out`` JSON
artifact); :func:`format_trend` the rendered table.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from mpi4dl_tpu.obs.report import _COMPARE_METRICS
from mpi4dl_tpu.obs.runlog import read_runlog

TREND_SCHEMA = 1

#: ``RunLog.create`` filename shape: ``<prefix>-<stamp>-p<pid>[-n].jsonl``.
_SERIES_RE = re.compile(r"^(?P<series>.+)-\d{8}-\d{6}-p\d+(?:-\d+)?$")


def runlog_series(path: str) -> str:
    """The series key of one RunLog file — its ``RunLog.create`` prefix,
    or the whole basename for hand-named files."""
    base = os.path.basename(path)
    if base.endswith(".jsonl"):
        base = base[: -len(".jsonl")]
    m = _SERIES_RE.match(base)
    return m.group("series") if m else base


def _recover_truncated_json(text: str,
                            scan_limit: int = 200) -> Optional[dict]:
    """Bounded brace-scan recovery of a front-truncated JSON document: try
    ``raw_decode`` at each ``{`` (first ``scan_limit`` of them) and keep
    the best complete dict — preferring one that carries bench ``rungs``.
    Returns None when nothing decodes."""
    dec = json.JSONDecoder()
    best: Optional[dict] = None
    tried = 0
    for m in re.finditer(r"\{", text):
        if tried >= scan_limit:
            break
        tried += 1
        try:
            val, _ = dec.raw_decode(text, m.start())
        except ValueError:
            continue
        if not isinstance(val, dict):
            continue
        if "rungs" in val:
            return val
        if best is None or len(val) > len(best):
            best = val
    return best


def _bench_rungs(doc: dict) -> Dict[str, Any]:
    """Normalize the two bench-artifact shapes to ``{rung: row}``:
    ladder crash-capture files nest under ``parsed.rungs``; the
    BENCH_stripe/BENCH_ci refresh files carry ``rungs`` at top level."""
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("rungs"), dict):
        return parsed["rungs"]
    if isinstance(doc.get("rungs"), dict):
        return doc["rungs"]
    return {}


def read_bench_artifact(path: str) -> Dict[str, Any]:
    """One BENCH_*.json as a trend row: ``rungs`` (possibly recovered from
    a truncated tail), ``recovered`` flag, and a ``note`` when the
    artifact yields nothing usable.  Never raises on artifact content."""
    out: Dict[str, Any] = {"path": path, "rungs": {}, "recovered": False}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        out["note"] = f"unreadable: {e}"
        return out
    if not isinstance(doc, dict):
        out["note"] = "not a JSON object"
        return out
    rungs = _bench_rungs(doc)
    if not rungs and isinstance(doc.get("tail"), str):
        # Crash-captured ladder run: the outer doc is {n, cmd, rc, tail,
        # parsed: null} with the result JSON front-truncated inside tail.
        rec = _recover_truncated_json(doc["tail"])
        if rec is not None:
            rungs = _bench_rungs({"parsed": rec, **rec})
            out["recovered"] = bool(rungs)
    if not rungs:
        out["note"] = "no rung rows (crash tail beyond recovery)"
        return out
    out["rungs"] = {
        str(k): {
            f: v.get(f) for f in ("img_per_sec", "mfu", "timing_mode")
            if isinstance(v, dict) and v.get(f) is not None
        }
        for k, v in rungs.items()
    }
    out["source"] = doc.get("source")
    return out


def _run_row(path: str) -> Dict[str, Any]:
    records = read_runlog(path)
    ts = [float(r["t"]) for r in records if r.get("t") is not None]
    metrics = {}
    for name, good, fn in _COMPARE_METRICS:
        v = fn(records)
        if v is not None:
            metrics[name] = v
    return {
        "path": path,
        "series": runlog_series(path),
        "t": min(ts) if ts else os.path.getmtime(path),
        "records": len(records),
        "metrics": metrics,
    }


def _gate(prev: Dict[str, Any], new: Dict[str, Any],
          threshold_pct: float) -> Dict[str, Any]:
    """Newest-vs-previous breach check with --compare semantics."""
    rows = []
    breaches = 0
    for name, good, _fn in _COMPARE_METRICS:
        va = prev["metrics"].get(name)
        vb = new["metrics"].get(name)
        if va is None or vb is None:
            continue
        if va == 0:
            delta = 0.0 if vb == 0 else float("inf")
        else:
            delta = (vb - va) / abs(va) * 100.0
        regressed = (delta > threshold_pct if good == "lower"
                     else delta < -threshold_pct)
        breaches += int(regressed)
        rows.append({"metric": name, "baseline": va, "candidate": vb,
                     "delta_pct": round(delta, 4), "regressed": regressed})
    return {
        "series": new["series"],
        "baseline": prev["path"],
        "candidate": new["path"],
        "metrics": rows,
        "breaches": breaches,
    }


def trend_report(directory: str,
                 threshold_pct: float = 5.0) -> Dict[str, Any]:
    """Scan ``directory`` (non-recursive) and build the trend artifact:
    per-RunLog trajectory rows (time-ordered), bench rung rows, and the
    per-series newest-vs-previous gates.  ``breaches`` > 0 means the
    newest run of some series regressed past the threshold."""
    names = sorted(os.listdir(directory))
    runs = [
        _run_row(os.path.join(directory, n))
        for n in names if n.endswith(".jsonl")
    ]
    runs.sort(key=lambda r: (r["series"], r["t"], r["path"]))
    bench = [
        read_bench_artifact(os.path.join(directory, n))
        for n in names
        if n.startswith("BENCH_") and n.endswith(".json")
    ]

    gates: List[Dict[str, Any]] = []
    by_series: Dict[str, List[Dict[str, Any]]] = {}
    for r in runs:
        by_series.setdefault(r["series"], []).append(r)
    for series, rows in sorted(by_series.items()):
        if len(rows) >= 2:
            gates.append(_gate(rows[-2], rows[-1], threshold_pct))
    return {
        "schema": TREND_SCHEMA,
        "directory": directory,
        "threshold_pct": threshold_pct,
        "runs": runs,
        "bench": bench,
        "gates": gates,
        "breaches": sum(g["breaches"] for g in gates),
    }


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def format_trend(trend: Dict[str, Any]) -> str:
    """Rendered trajectory + gate table of one :func:`trend_report`."""
    lines = [
        f"== trend  {trend['directory']}  "
        f"(threshold {trend['threshold_pct']:g}%)"
    ]
    shown = [m for m, _g, _f in _COMPARE_METRICS[:4]]
    for series, rows in _group(trend["runs"]).items():
        lines.append(f"series {series}: {len(rows)} run(s)")
        for r in rows:
            vals = "  ".join(
                f"{m}={_fmt(r['metrics'][m])}" for m in shown
                if m in r["metrics"]
            ) or "(no comparable metrics)"
            lines.append(f"  {os.path.basename(r['path'])}  {vals}")
    for b in trend["bench"]:
        base = os.path.basename(b["path"])
        if b.get("note"):
            lines.append(f"bench {base}: skipped — {b['note']}")
            continue
        mark = " [recovered from crash tail]" if b.get("recovered") else ""
        lines.append(f"bench {base}{mark}:")
        for rung, row in sorted(b["rungs"].items()):
            vals = "  ".join(f"{k}={_fmt(v)}" for k, v in row.items())
            lines.append(f"  rung {rung}: {vals}")
    for g in trend["gates"]:
        verdict = (f"{g['breaches']} REGRESSION(S)" if g["breaches"]
                   else "ok")
        lines.append(
            f"gate [{g['series']}] {os.path.basename(g['baseline'])} -> "
            f"{os.path.basename(g['candidate'])}: {verdict}"
        )
        for m in g["metrics"]:
            flag = "  REGRESSION" if m["regressed"] else ""
            lines.append(
                f"  {m['metric']:<24} {_fmt(m['baseline']):>12} -> "
                f"{_fmt(m['candidate']):>12}  "
                f"({m['delta_pct']:+.2f}%){flag}"
            )
    if not trend["gates"]:
        lines.append("gate: n/a (no series with two or more runs)")
    lines.append(
        f"{trend['breaches']} regression(s) beyond threshold"
        if trend["breaches"] else "no regressions beyond threshold"
    )
    return "\n".join(lines)


def _group(runs: List[Dict[str, Any]]) -> Dict[str, List[Dict[str, Any]]]:
    out: Dict[str, List[Dict[str, Any]]] = {}
    for r in runs:
        out.setdefault(r["series"], []).append(r)
    return out
