"""Structured telemetry: the library that replaces print-pile observability.

Every training step becomes self-describing through four pieces (ISSUE 2;
the reference's CUDA-event phase timing + MPI message accounting, SURVEY
§2a, re-expressed as compiler artifacts):

- **Trace scopes** (:mod:`~mpi4dl_tpu.obs.scopes`): ``obs.scope(name)``
  threads semantic names (``cell03``, ``halo_exchange_w``, ``stage1``)
  through the hot paths so XProf traces and compiled HLO carry phase
  attribution.  Disable with ``MPI4DL_NO_SCOPES=1``.
- **Run telemetry** (:mod:`~mpi4dl_tpu.obs.runlog`): :class:`RunLog` JSONL
  sink — run metadata (config, mesh, device, jax version, active hatches)
  plus per-step records (wall ms, images/sec, loss/acc, memory watermark,
  jit-cache retrace probe).
- **Derived metrics** (:mod:`~mpi4dl_tpu.obs.costs`,
  :mod:`~mpi4dl_tpu.obs.hlo_stats`): FLOPs/bytes from
  ``compiled.cost_analysis()`` → MFU + arithmetic intensity; per-class
  collective count/bytes parsed from compiled HLO.
- **Surfaces**: ``python -m mpi4dl_tpu.obs report run.jsonl``
  (:mod:`~mpi4dl_tpu.obs.report`), and ``--telemetry-dir`` on every
  benchmark entry point (benchmarks/common.py) and bench.py.

Forensics + fleet telemetry (ISSUE 17) ride on the same records:

- **Flight recorder** (:mod:`~mpi4dl_tpu.obs.flight`): bounded in-memory
  ring of the last N step records + checkpoint/anomaly/preempt events,
  dumped as ``flight.json`` on anomaly/escalation/preemption/crash — the
  supervisor's fourth evidence source.  ``MPI4DL_NO_FLIGHT=1`` disables.
- **Trace export** (:mod:`~mpi4dl_tpu.obs.trace`): Chrome/Perfetto
  trace-event JSON of the simulated wire schedule, analytical timeline,
  pipeline tick lanes, and measured RunLog walls.
- **Metrics** (:mod:`~mpi4dl_tpu.obs.metrics`): OpenMetrics/Prometheus
  text exposition (file snapshot + stdlib HTTP endpoint).
- **Trend** (:mod:`~mpi4dl_tpu.obs.trend`): directory-wide trajectory +
  newest-vs-previous regression gate (``obs report --trend DIR``).
"""

from __future__ import annotations

from mpi4dl_tpu.obs.scopes import scope, scopes_enabled, step_annotation
from mpi4dl_tpu.obs.runlog import (
    RunLog,
    active_hatches,
    device_memory_watermark,
    device_memory_watermarks,
    host_rss_peak_bytes,
    jit_cache_size,
    read_runlog,
)
from mpi4dl_tpu.obs.flight import (
    FlightRecorder,
    flight_summary,
    read_flight,
    watermark_growth,
)
from mpi4dl_tpu.obs.trace import (
    chrome_trace,
    hlo_trace_events,
    trace_from_runlog,
)
from mpi4dl_tpu.obs.metrics import (
    metrics_from_records,
    metrics_from_runlog,
    metrics_from_runlogs,
    serve_metrics,
    write_metrics_file,
)
from mpi4dl_tpu.obs.trend import (
    format_trend,
    read_bench_artifact,
    trend_report,
)
from mpi4dl_tpu.obs.costs import (
    arithmetic_intensity,
    compiled_cost,
    ici_bytes_per_s,
    mfu,
    peak_flops,
    step_cost,
)
from mpi4dl_tpu.obs.hbm import (
    attribute_compiled,
    attribute_hlo,
    compare_breakdowns,
    format_breakdown,
    format_delta,
    scope_group_bytes,
    top_scope,
)
from mpi4dl_tpu.obs.timeline import (
    analytical_timeline,
    bubble_fraction,
    collective_base,
    format_timeline,
    hlo_scope_costs,
    pipeline_ticks,
)
from mpi4dl_tpu.obs.overlap import (
    format_ledger,
    overlap_ledger,
    structural_overlap,
    wire_class,
)
from mpi4dl_tpu.obs.hlo_stats import (
    clean_scope_path,
    compiled_collective_stats,
    hlo_collective_stats,
    scope_coverage,
    scope_names,
    stablehlo_collectives,
    stablehlo_debug_text,
    stablehlo_sharding_annotations,
)

__all__ = [
    "FlightRecorder",
    "RunLog",
    "active_hatches",
    "analytical_timeline",
    "arithmetic_intensity",
    "attribute_compiled",
    "attribute_hlo",
    "bubble_fraction",
    "chrome_trace",
    "clean_scope_path",
    "collective_base",
    "compare_breakdowns",
    "compiled_collective_stats",
    "compiled_cost",
    "device_memory_watermark",
    "device_memory_watermarks",
    "flight_summary",
    "format_breakdown",
    "format_delta",
    "format_ledger",
    "format_timeline",
    "format_trend",
    "hlo_collective_stats",
    "hlo_scope_costs",
    "hlo_trace_events",
    "host_rss_peak_bytes",
    "ici_bytes_per_s",
    "jit_cache_size",
    "metrics_from_records",
    "metrics_from_runlog",
    "metrics_from_runlogs",
    "mfu",
    "overlap_ledger",
    "peak_flops",
    "pipeline_ticks",
    "read_bench_artifact",
    "read_flight",
    "read_runlog",
    "scope",
    "scope_coverage",
    "scope_group_bytes",
    "scope_names",
    "scopes_enabled",
    "serve_metrics",
    "stablehlo_collectives",
    "stablehlo_debug_text",
    "stablehlo_sharding_annotations",
    "step_annotation",
    "step_cost",
    "structural_overlap",
    "top_scope",
    "trace_from_runlog",
    "trend_report",
    "watermark_growth",
    "wire_class",
]
