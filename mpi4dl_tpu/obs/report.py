"""Render a RunLog JSONL file as a human-readable summary.

Pure string construction (printing happens in obs/__main__.py — the CLI
surface; library modules never print, analysis rule ``print-call``).  The
summary is computed from the step records themselves, so it works on files
from crashed runs that never wrote a summary record.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from mpi4dl_tpu.obs.costs import mfu
from mpi4dl_tpu.obs.hlo_stats import COLLECTIVE_CLASSES
from mpi4dl_tpu.obs.runlog import read_runlog
# Same interpolation as StepMeter.stats(), so report percentiles of the raw
# step records always match a run's own summary record.
from mpi4dl_tpu.utils.misc import _percentile as _pct


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _first(records: List[dict], kind: str) -> Optional[dict]:
    for r in records:
        if r.get("kind") == kind:
            return r
    return None


def render_run(path: str) -> str:
    """The report for one run file."""
    records = read_runlog(path)
    lines: List[str] = [f"== {path}"]
    meta = _first(records, "meta")
    cost = _first(records, "cost")
    steps = [r for r in records if r.get("kind") == "step"]
    measured = [r for r in steps if r.get("measured", True)]
    warmup = len(steps) - len(measured)

    if meta is not None:
        cfg = meta.get("config") or {}
        desc = " ".join(
            f"{k}={cfg[k]}" for k in (
                "model", "image_size", "batch_size", "split_size",
                "spatial_size", "parts", "precision",
            ) if k in cfg
        )
        lines.append(
            f"run: family={meta.get('family', '?')} {desc}".rstrip()
        )
        lines.append(
            f"devices: {meta.get('device_count')} x {meta.get('platform')} "
            f"({meta.get('device_kind')})  mesh={meta.get('mesh')}  "
            f"jax {meta.get('jax_version')}"
        )
        if meta.get("hatches"):
            lines.append(
                "hatches: " + " ".join(
                    f"{k}={v}" for k, v in sorted(meta["hatches"].items())
                )
            )

    # -- step timings ------------------------------------------------------
    if measured:
        ms = sorted(float(r["ms"]) for r in measured)
        mean = sum(ms) / len(ms)
        med = _pct(ms, 0.5)
        lines.append(
            f"steps: {len(measured)} measured, {warmup} warmup dropped"
        )
        lines.append(
            f"step time ms: mean {mean:.2f}  median {med:.2f}  "
            f"p10 {_pct(ms, 0.10):.2f}  p90 {_pct(ms, 0.90):.2f}  "
            f"min {ms[0]:.2f}"
        )
        ips = [float(r["images_per_sec"]) for r in measured]
        last_loss = measured[-1].get("loss")
        lines.append(
            f"images/sec: mean {sum(ips) / len(ips):.3f}  last-loss "
            + (f"{last_loss:.4f}" if last_loss is not None else "n/a")
        )
    else:
        med = None
        lines.append(f"steps: 0 measured, {warmup} warmup dropped")

    # -- resilience events (docs/resilience.md) ----------------------------
    events = [r for r in records
              if r.get("kind") in ("anomaly", "recovery", "preempt")]
    if events:
        parts = []
        for r in events:
            at = r.get("gstep", r.get("skipped_step"))
            extra = ""
            if r["kind"] == "anomaly":
                extra = f" ({r.get('reason')})"
            elif r["kind"] == "recovery":
                extra = f" (resumed from {r.get('resumed_from')})"
            parts.append(f"{r['kind']}@{at}{extra}")
        lines.append("resilience events: " + "; ".join(parts))

    # -- memory watermark --------------------------------------------------
    dev_peaks = [r.get("memory_peak_bytes") for r in steps
                 if r.get("memory_peak_bytes") is not None]
    rss_peaks = [r.get("host_rss_peak_bytes") for r in steps
                 if r.get("host_rss_peak_bytes") is not None]
    if dev_peaks:
        lines.append(f"memory watermark: {_fmt_bytes(max(dev_peaks))} "
                     "(device peak_bytes_in_use)")
    elif rss_peaks:
        lines.append(f"memory watermark: {_fmt_bytes(max(rss_peaks))} "
                     "(host peak RSS; backend reports no device stats)")
    else:
        lines.append("memory watermark: n/a")

    # -- pipeline schedule -------------------------------------------------
    # Keyed on the meta family, not just split_size: tools that record raw
    # argparse defaults (mem_probe's single-chip mode carries
    # --split-size 2) must not render a pipeline line for a run without one.
    cfg = (meta.get("config") or {}) if meta is not None else {}
    split = int(cfg.get("split_size") or 1)
    if split > 1 and (meta or {}).get("family") != "single":
        parts_n = int(cfg.get("parts") or 1)
        schedule = cfg.get("schedule") or "gpipe"
        if schedule == "1f1b":
            # One fwd AND one bwd micro-batch per tick; fill+drain covers
            # both directions.
            ticks = parts_n + 2 * (split - 1)
            bubble = 2 * (split - 1) / (parts_n + 2 * (split - 1))
        elif schedule == "gpipe":
            ticks = parts_n + split - 1
            bubble = (split - 1) / ticks
        else:
            # Not a schedule the tick arithmetic knows (e.g. mem_probe's
            # multi-schedule sweeps record schedule="both") — don't render
            # one schedule's numbers under another's name.
            ticks = None
            bubble = None
        line = f"pipeline: schedule={schedule}  stages={split}  parts={parts_n}"
        if ticks is not None:
            line += f"  ticks/step={ticks}  bubble={bubble:.3f}"
        # Corroborate from the compiled program when the cost record saw it:
        # tick scopes are the schedule's fingerprint in the HLO op names.
        scopes_seen = (cost or {}).get("tick_scopes")
        if scopes_seen:
            line += "  scopes: " + ",".join(scopes_seen)
        lines.append(line)

    # -- retraces ----------------------------------------------------------
    sizes = [r.get("jit_cache_size") for r in steps
             if r.get("jit_cache_size") is not None]
    if sizes:
        if max(sizes) <= 2:
            # 2 variants is the normal donate+reshard pattern: the first call
            # sees unsharded inputs, every later call the mesh-sharded state.
            note = ""
        else:
            note = "  RETRACE HAZARD (shape/dtype/sharding churn in the loop)"
        lines.append(f"compiled step variants (jit cache): {max(sizes)}{note}")

    # -- derived cost metrics ----------------------------------------------
    if cost is not None:
        flops = cost.get("flops")
        nbytes = cost.get("bytes_accessed")
        ai = cost.get("arithmetic_intensity")
        if flops:
            lines.append(
                f"cost model: flops/step {flops:.4g}  bytes/step "
                f"{_fmt_bytes(nbytes)}  arithmetic intensity "
                + (f"{ai:.2f} flops/byte" if ai else "n/a")
            )
        else:
            lines.append("cost model: n/a (backend lacks cost_analysis)")
        peak = cost.get("peak_flops")
        ndev = cost.get("device_count") or 1
        # flops is per-device (the one SPMD module each device runs), so
        # utilization is against ONE device's peak.
        util = mfu(flops, med, peak)
        if util is not None:
            lines.append(
                f"mfu estimate: {util:.4f} "
                f"(median step, per-device peak {peak:.3g} FLOP/s, "
                f"{ndev} devices, peak source: {cost.get('peak_source')})"
            )
        else:
            lines.append("mfu estimate: n/a (missing flops, steps, or peak)")
        coll = cost.get("collectives") or {}
        if coll:
            lines.append("collectives per step (compiled HLO):")
            for cls in COLLECTIVE_CLASSES:
                c = coll.get(cls) or {}
                lines.append(
                    f"  {cls:<19} count {c.get('count', 0):>4}  "
                    f"bytes {_fmt_bytes(c.get('bytes', 0))}"
                )
            lines.append(
                f"  {'total':<19} count {coll.get('total_count', 0):>4}  "
                f"bytes {_fmt_bytes(coll.get('total_bytes', 0))}"
            )
    return "\n".join(lines)


def render(paths: Sequence[str]) -> str:
    return "\n\n".join(render_run(p) for p in paths)
