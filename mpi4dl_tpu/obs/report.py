"""Render a RunLog JSONL file as a human-readable summary.

Pure string construction (printing happens in obs/__main__.py — the CLI
surface; library modules never print, analysis rule ``print-call``).  The
summary is computed from the step records themselves, so it works on files
from crashed runs that never wrote a summary record.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from mpi4dl_tpu.obs.costs import mfu
from mpi4dl_tpu.obs.hlo_stats import COLLECTIVE_CLASSES
from mpi4dl_tpu.obs.runlog import read_runlog
from mpi4dl_tpu.obs.timeline import bubble_fraction, pipeline_ticks
# Same interpolation as StepMeter.stats(), so report percentiles of the raw
# step records always match a run's own summary record.
from mpi4dl_tpu.utils.misc import _percentile as _pct


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _first(records: List[dict], kind: str) -> Optional[dict]:
    for r in records:
        if r.get("kind") == kind:
            return r
    return None


def render_run(path: str) -> str:
    """The report for one run file."""
    records = read_runlog(path)
    lines: List[str] = [f"== {path}"]
    meta = _first(records, "meta")
    cost = _first(records, "cost")
    steps = [r for r in records if r.get("kind") == "step"]
    measured = [r for r in steps if r.get("measured", True)]
    warmup = len(steps) - len(measured)

    if meta is not None:
        cfg = meta.get("config") or {}
        desc = " ".join(
            f"{k}={cfg[k]}" for k in (
                "model", "image_size", "batch_size", "split_size",
                "spatial_size", "parts", "precision",
            ) if k in cfg
        )
        lines.append(
            f"run: family={meta.get('family', '?')} {desc}".rstrip()
        )
        lines.append(
            f"devices: {meta.get('device_count')} x {meta.get('platform')} "
            f"({meta.get('device_kind')})  mesh={meta.get('mesh')}  "
            f"jax {meta.get('jax_version')}"
        )
        if meta.get("hatches"):
            lines.append(
                "hatches: " + " ".join(
                    f"{k}={v}" for k, v in sorted(meta["hatches"].items())
                )
            )

    # -- step timings ------------------------------------------------------
    if measured:
        ms = sorted(float(r["ms"]) for r in measured)
        mean = sum(ms) / len(ms)
        med = _pct(ms, 0.5)
        lines.append(
            f"steps: {len(measured)} measured, {warmup} warmup dropped"
        )
        lines.append(
            f"step time ms: mean {mean:.2f}  median {med:.2f}  "
            f"p10 {_pct(ms, 0.10):.2f}  p90 {_pct(ms, 0.90):.2f}  "
            f"min {ms[0]:.2f}"
        )
        ips = [float(r["images_per_sec"]) for r in measured]
        last_loss = measured[-1].get("loss")
        lines.append(
            f"images/sec: mean {sum(ips) / len(ips):.3f}  last-loss "
            + (f"{last_loss:.4f}" if last_loss is not None else "n/a")
        )
    else:
        med = None
        lines.append(f"steps: 0 measured, {warmup} warmup dropped")

    # -- resilience events (docs/resilience.md) ----------------------------
    events = [r for r in records
              if r.get("kind") in ("anomaly", "recovery", "preempt",
                                   "quarantine")]
    if events:
        parts = []
        for r in events:
            at = r.get("gstep", r.get("skipped_step"))
            extra = ""
            if r["kind"] == "anomaly":
                extra = f" ({r.get('reason')})"
            elif r["kind"] == "recovery":
                extra = f" (resumed from {r.get('resumed_from')})"
            parts.append(f"{r['kind']}@{at}{extra}")
        lines.append("resilience events: " + "; ".join(parts))

    # -- supervisor incident timeline (ISSUE 15) ---------------------------
    incidents = [r for r in records if r.get("kind") == "supervisor"]
    if incidents:
        lines.append(f"supervisor incidents: {len(incidents)}")
        for r in incidents:
            bits = [f"  attempt {r.get('attempt')}: "
                    f"{r.get('failure_class')} -> {r.get('policy')}"]
            delta = r.get("config_delta")
            if delta:
                bits.append("delta " + ",".join(
                    f"{k}={v}" for k, v in delta.items()
                ))
            probe = r.get("probe") or {}
            if probe.get("probe_peak_gb") is not None:
                gauge = probe.get("budget_gb")
                bits.append(
                    f"probed {probe['probe_peak_gb']} GB"
                    + (f" <= {gauge} GB" if gauge is not None else "")
                )
            if r.get("backoff_s") is not None:
                bits.append(f"backoff {r['backoff_s']} s")
            if r.get("quarantined"):
                bits.append(f"quarantined {r['quarantined']}")
            lines.append("  ".join(bits))
    sup_sum = _first(records, "supervisor_summary")
    if sup_sum is not None:
        lines.append(
            f"supervisor: {'completed' if sup_sum.get('ok') else 'FAILED'} "
            f"after {sup_sum.get('attempts')} leg(s), "
            f"{sup_sum.get('incidents')} incident(s)"
            + (f" — {sup_sum.get('reason')}" if sup_sum.get("reason") else "")
        )

    # -- checkpoint ledger (ISSUE 13: save cost + elastic restores) --------
    ckpts = [r for r in records if r.get("kind") == "checkpoint"]
    if ckpts:
        total_b = sum(int(r.get("bytes") or 0) for r in ckpts)
        gather = sum(float(r.get("gather_ms") or 0) for r in ckpts)
        write = sum(float(r.get("write_ms") or 0) for r in ckpts)
        peak = max(int(r.get("peak_pending_bytes") or 0) for r in ckpts)
        lines.append(
            f"checkpoints: {len(ckpts)} saves  {_fmt_bytes(total_b)}  "
            f"gather {gather:.1f} ms  write {write:.1f} ms  "
            f"peak pending {_fmt_bytes(peak)}"
        )
    restores = [r for r in records if r.get("kind") == "restore"]
    for r in restores:
        lines.append(
            f"restore: step {r.get('step_id')} from {r.get('path')}"
            + (" [ELASTIC — saved under a different layout]"
               if r.get("elastic") else "")
        )

    # -- drill verdicts (python -m mpi4dl_tpu.resilience drill) ------------
    drills = [r for r in records if r.get("kind") == "drill"]
    if drills:
        ok = sum(1 for r in drills if r.get("passed"))
        lines.append(f"drills: {ok}/{len(drills)} verified recoveries")
        for r in drills:
            mark = "PASS" if r.get("passed") else "FAIL"
            extra = "" if r.get("passed") else f" — {r.get('reason', '')}"
            lines.append(
                f"  {mark} {r.get('scenario')}: {r.get('verdict')}{extra}"
            )

    # -- fleet timeline (ISSUE 18: the scheduler's decision ledger) --------
    fleet = [r for r in records if r.get("kind") == "fleet"]
    if fleet:
        lines.append(f"fleet timeline: {len(fleet)} events")
        for r in fleet:
            bits = [f"  t={r.get('t'):>8} {r.get('event')}"]
            if r.get("job"):
                bits.append(str(r["job"]))
            if r.get("state"):
                bits.append(f"-> {r['state']}")
            if r.get("slice"):
                bits.append(str(r["slice"]))
            if r.get("victim"):
                bits.append(f"victim={r['victim']}")
            if r.get("reason"):
                bits.append(f"({r['reason']})")
            lines.append("  ".join(bits))
    fleet_sum = _first(records, "fleet_summary")
    if fleet_sum is not None:
        jobs = fleet_sum.get("jobs") or {}
        lines.append(
            f"fleet: {'OK' if fleet_sum.get('ok') else 'FAILED'} — "
            + ", ".join(f"{j}={st}" for j, st in sorted(jobs.items()))
            + (f"  (pool {fleet_sum.get('pool')}, "
               f"{fleet_sum.get('events')} events)")
        )

    # -- memory watermark --------------------------------------------------
    dev_peaks = [r.get("memory_peak_bytes") for r in steps
                 if r.get("memory_peak_bytes") is not None]
    rss_peaks = [r.get("host_rss_peak_bytes") for r in steps
                 if r.get("host_rss_peak_bytes") is not None]
    if dev_peaks:
        lines.append(f"memory watermark: {_fmt_bytes(max(dev_peaks))} "
                     "(device peak_bytes_in_use)")
        skews = [r.get("hbm_skew") for r in steps
                 if r.get("hbm_skew") is not None]
        if skews:
            # Hot-vs-cold device spread: SP imbalance shows here while the
            # device-0 watermark still reads healthy.
            lines.append(
                f"hbm skew: {_fmt_bytes(max(skews))} max spread across "
                "local devices (hot tile vs coldest)"
            )
    elif rss_peaks:
        lines.append(f"memory watermark: {_fmt_bytes(max(rss_peaks))} "
                     "(host peak RSS; backend reports no device stats)")
    else:
        lines.append("memory watermark: n/a")

    # -- pipeline schedule -------------------------------------------------
    # Keyed on the meta family, not just split_size: tools that record raw
    # argparse defaults (mem_probe's single-chip mode carries
    # --split-size 2) must not render a pipeline line for a run without one.
    cfg = (meta.get("config") or {}) if meta is not None else {}
    split = int(cfg.get("split_size") or 1)
    if split > 1 and (meta or {}).get("family") != "single":
        parts_n = int(cfg.get("parts") or 1)
        schedule = cfg.get("schedule") or "gpipe"
        # Canonical tick/bubble arithmetic lives in obs/timeline.py; unknown
        # schedules (e.g. mem_probe's multi-schedule sweeps record
        # schedule="both") yield None — don't render one schedule's numbers
        # under another's name.
        ticks = pipeline_ticks(schedule, split, parts_n)
        bubble = bubble_fraction(schedule, split, parts_n)
        line = f"pipeline: schedule={schedule}  stages={split}  parts={parts_n}"
        if ticks is not None:
            line += f"  ticks/step={ticks}  bubble={bubble:.3f}"
        # Corroborate from the compiled program when the cost record saw it:
        # tick scopes are the schedule's fingerprint in the HLO op names.
        scopes_seen = (cost or {}).get("tick_scopes")
        if scopes_seen:
            line += "  scopes: " + ",".join(scopes_seen)
        lines.append(line)

    # -- exposed wire (overlap ledger, next to the pipeline line) ----------
    for rec in records:
        if rec.get("kind") != "overlap":
            continue
        t = rec.get("totals") or {}
        label = rec.get("label")
        hf = rec.get("hidden_frac")
        qb = t.get("quantized_bytes") or 0
        lines.append(
            "wire" + (f" [{label}]" if label else "") + ": "
            f"{_fmt_bytes(t.get('bytes'))}/step"
            + (f" ({_fmt_bytes(qb)} quantized)" if qb else "")
            + " — exposed "
            f"{t.get('exposed_ms')} ms, hidden {t.get('hidden_ms')} ms"
            + (f" ({hf:.1%} hidden)" if hf is not None else "")
            + f"; async pairs {t.get('async_pairs', 0)}, "
              f"sync {t.get('sync', 0)}; sim step "
              f"{rec.get('simulated_step_ms')} ms"
        )
        exposed_rows = [r for r in (rec.get("rows") or [])
                        if r.get("exposed_ms")]
        for r in exposed_rows[:4]:
            lines.append(
                f"  {r['exposed_ms']:>10.3f} ms exposed  "
                f"{_fmt_bytes(r.get('bytes')):>10}  {r['scope']}"
            )

    # -- retraces ----------------------------------------------------------
    sizes = [r.get("jit_cache_size") for r in steps
             if r.get("jit_cache_size") is not None]
    if sizes:
        if max(sizes) <= 2:
            # 2 variants is the normal donate+reshard pattern: the first call
            # sees unsharded inputs, every later call the mesh-sharded state.
            note = ""
        else:
            note = "  RETRACE HAZARD (shape/dtype/sharding churn in the loop)"
        lines.append(f"compiled step variants (jit cache): {max(sizes)}{note}")

    # -- derived cost metrics ----------------------------------------------
    if cost is not None:
        flops = cost.get("flops")
        nbytes = cost.get("bytes_accessed")
        ai = cost.get("arithmetic_intensity")
        if flops:
            lines.append(
                f"cost model: flops/step {flops:.4g}  bytes/step "
                f"{_fmt_bytes(nbytes)}  arithmetic intensity "
                + (f"{ai:.2f} flops/byte" if ai else "n/a")
            )
        else:
            lines.append("cost model: n/a (backend lacks cost_analysis)")
        peak = cost.get("peak_flops")
        ndev = cost.get("device_count") or 1
        # flops is per-device (the one SPMD module each device runs), so
        # utilization is against ONE device's peak.
        util = mfu(flops, med, peak)
        if util is not None:
            lines.append(
                f"mfu estimate: {util:.4f} "
                f"(median step, per-device peak {peak:.3g} FLOP/s, "
                f"{ndev} devices, peak source: {cost.get('peak_source')})"
            )
        else:
            lines.append("mfu estimate: n/a (missing flops, steps, or peak)")
        coll = cost.get("collectives") or {}
        if coll:
            lines.append("collectives per step (compiled HLO):")
            for cls in COLLECTIVE_CLASSES:
                c = coll.get(cls) or {}
                lines.append(
                    f"  {cls:<19} count {c.get('count', 0):>4}  "
                    f"bytes {_fmt_bytes(c.get('bytes', 0))}"
                )
            lines.append(
                f"  {'total':<19} count {coll.get('total_count', 0):>4}  "
                f"bytes {_fmt_bytes(coll.get('total_bytes', 0))}"
            )

    # -- mem_probe / HBM attribution / timeline / junction sweep -----------
    probe = _first(records, "mem_probe")
    if probe is not None and probe.get("table"):
        lines.append("mem_probe (compile-only peak HBM):")
        lines.extend("  " + ln for ln in str(probe["table"]).splitlines())
    if probe is not None and probe.get("parts_delta"):
        pd = probe["parts_delta"]
        for sched, d in (pd.get("per_schedule") or {}).items():
            lines.append(
                f"O(parts) growth [{sched}] parts {pd.get('parts_a')} -> "
                f"{pd.get('parts_b')} (top group: "
                f"{d.get('top_growth_group')}):"
            )
            for k, v in list(
                (d.get("growth_bytes_per_part") or {}).items()
            )[:6]:
                lines.append(f"  {_fmt_bytes(v):>10}/part  {k}")
    for rec in records:
        if rec.get("kind") != "hbm":
            continue
        bd = rec.get("breakdown") or {}
        label = rec.get("label")
        lines.append(
            "hbm attribution" + (f" [{label}]" if label else "") + ": peak "
            f"{_fmt_bytes(bd.get('peak_bytes_est'))} (analytical), coverage "
            f"{bd.get('coverage', 0):.1%}"
        )
        for k, v in list((bd.get("by_scope") or {}).items())[:6]:
            lines.append(f"  {_fmt_bytes(v):>10}  {k}")
    tl = _first(records, "timeline")
    if tl is not None:
        lines.append(
            f"analytical timeline: serialized {tl.get('serialized_ms')} ms "
            f"(compute {tl.get('compute_ms')} + collectives "
            f"{tl.get('collective_ms')}), perfect overlap "
            f"{tl.get('overlapped_ms')} ms — headroom "
            f"{tl.get('overlap_headroom_ms')} ms"
        )
    sweep = _first(records, "junction_sweep")
    if sweep is not None:
        lines.append(
            "junction placement frontier (spatial_until -> peak GB/device):"
        )
        for p in sweep.get("placements") or []:
            mark = " <-- best" if p.get("best") else ""
            lines.append(
                f"  spatial_until={p.get('spatial_until'):>3}  "
                f"{p.get('peak_gb_est')} GB{mark}"
            )
    return "\n".join(lines)


def render(paths: Sequence[str]) -> str:
    return "\n\n".join(render_run(p) for p in paths)


# ---------------------------------------------------------------------------
# A/B regression compare (the perf gate over RunLog artifacts)
# ---------------------------------------------------------------------------

# metric name -> (direction, extractor).  Direction "lower"/"higher" is the
# GOOD direction; a move in the other direction beyond the threshold is a
# regression breach.
def _median_ms(records: List[dict]) -> Optional[float]:
    ms = sorted(
        float(r["ms"]) for r in records
        if r.get("kind") == "step" and r.get("measured", True)
    )
    return _pct(ms, 0.5) if ms else None


def _mean_ips(records: List[dict]) -> Optional[float]:
    ips = [
        float(r["images_per_sec"]) for r in records
        if r.get("kind") == "step" and r.get("measured", True)
    ]
    return sum(ips) / len(ips) if ips else None


def _peak_hbm(records: List[dict]) -> Optional[float]:
    peaks = [
        r["memory_peak_bytes"] for r in records
        if r.get("kind") == "step" and r.get("memory_peak_bytes") is not None
    ]
    if peaks:
        return max(peaks)
    # Compile-only artifacts fall back to the analytical liveness estimate.
    # Never mixed with measured watermarks: the estimate over-counts by a
    # documented 1.1-2.4x (obs/hbm.py), so max() across the two kinds would
    # compare incomparable quantities between an instrumented and a plain
    # run.
    est = [
        r["breakdown"]["peak_bytes_est"] for r in records
        if r.get("kind") == "hbm"
        and (r.get("breakdown") or {}).get("peak_bytes_est")
    ]
    return max(est) if est else None


def _coll_bytes(records: List[dict]) -> Optional[float]:
    for r in records:
        if r.get("kind") == "cost" and (r.get("collectives") or {}).get(
            "total_bytes"
        ) is not None:
            return float(r["collectives"]["total_bytes"])
    return None


def _probe_peak_gb(records: List[dict]) -> Optional[float]:
    for r in records:
        if r.get("kind") == "mem_probe":
            rows = r.get("schedules") or {}
            vals = [
                v.get("peak_gb_est") for v in rows.values()
                if isinstance(v, dict) and v.get("peak_gb_est") is not None
            ]
            if vals:
                return min(vals)
            if r.get("peak_gb_est") is not None:
                return float(r["peak_gb_est"])
    return None


def _overlap_byte_pairs(records: List[dict]) -> List[Tuple[float, float]]:
    """(total, quantized) wire bytes of every ``overlap`` record — the one
    scan both wire-byte compare metrics min-reduce over."""
    return [
        (float(t["bytes"]), float(t.get("quantized_bytes") or 0))
        for r in records if r.get("kind") == "overlap"
        for t in [r.get("totals") or {}] if t.get("bytes") is not None
    ]


def _wire_bytes(records: List[dict]) -> Optional[float]:
    """Total wire bytes/step from ``overlap`` records (best probed row)."""
    pairs = _overlap_byte_pairs(records)
    return min(b for b, _ in pairs) if pairs else None


def _raw_wire_bytes(records: List[dict]) -> Optional[float]:
    """UNQUANTIZED wire bytes/step (total - quantized) — the quantized-vs-
    raw split as a first-class compare metric: a run that loses its
    quantized payloads (the quant layer silently off) regresses here even
    if total bytes barely move.  Records predating the quantized_bytes
    column report their total (all-raw)."""
    pairs = _overlap_byte_pairs(records)
    return min(b - q for b, q in pairs) if pairs else None


def _exposed_wire_ms(records: List[dict]) -> Optional[float]:
    """Exposed-wire time from ``overlap`` records (best probed row, like
    the mem_probe peak metric), falling back to the timeline record's
    schedule-aware block for older artifacts."""
    vals = [
        float(r["totals"]["exposed_ms"]) for r in records
        if r.get("kind") == "overlap"
        and (r.get("totals") or {}).get("exposed_ms") is not None
    ]
    if vals:
        return min(vals)
    for r in records:
        sa = (r.get("schedule_aware") or {}) if r.get("kind") == "timeline" \
            else {}
        if sa.get("exposed_wire_ms") is not None:
            return float(sa["exposed_wire_ms"])
    return None


_COMPARE_METRICS = [
    ("step ms (median)", "lower", _median_ms),
    ("images/sec (mean)", "higher", _mean_ips),
    ("peak HBM bytes", "lower", _peak_hbm),
    ("collective bytes/step", "lower", _coll_bytes),
    ("mem_probe peak GB", "lower", _probe_peak_gb),
    ("exposed wire ms", "lower", _exposed_wire_ms),
    ("wire bytes/step", "lower", _wire_bytes),
    ("raw (unquantized) wire bytes", "lower", _raw_wire_bytes),
]


def compare_runs(path_a: str, path_b: str,
                 threshold_pct: float = 5.0) -> Tuple[str, int]:
    """Per-metric regression diff of two RunLog files (A = baseline,
    B = candidate).  Returns ``(report text, breach count)`` — a breach is a
    metric that moved against its good direction by more than
    ``threshold_pct`` percent.  Metrics absent from either file are skipped
    (reported as such), so a compile-only probe artifact and a full
    benchmark run can still be compared on their shared metrics."""
    ra, rb = read_runlog(path_a), read_runlog(path_b)
    lines = [f"== compare  A: {path_a}  ->  B: {path_b}  "
             f"(threshold {threshold_pct:g}%)"]
    breaches = 0
    for name, good, fn in _COMPARE_METRICS:
        va, vb = fn(ra), fn(rb)
        if va is None or vb is None:
            lines.append(f"  {name:<24} n/a (missing in "
                         f"{'A' if va is None else 'B'})")
            continue
        if va == 0:
            delta_pct = 0.0 if vb == 0 else float("inf")
        else:
            delta_pct = (vb - va) / abs(va) * 100.0
        regressed = (
            delta_pct > threshold_pct if good == "lower"
            else delta_pct < -threshold_pct
        )
        flag = "  REGRESSION" if regressed else ""
        breaches += int(regressed)
        lines.append(
            f"  {name:<24} {va:>14.4g} -> {vb:>14.4g}  "
            f"({delta_pct:+.2f}%){flag}"
        )
    lines.append(
        f"{breaches} regression(s) beyond threshold" if breaches
        else "no regressions beyond threshold"
    )
    return "\n".join(lines), breaches
