"""Analytical per-scope step timeline from the compiled artifact.

The companion of obs/hbm.py (same parsed module, the time axis instead of
the byte axis): for every ``obs.scope`` in the compiled HLO, an analytical
**compute-time** estimate (conv/dot FLOPs at the instruction's shapes over
the chip's bf16 peak, :data:`~mpi4dl_tpu.obs.costs.PEAK_BF16_FLOPS`) and a
**collective-time** estimate (collective payload bytes over the chip's ICI
bandwidth, :func:`~mpi4dl_tpu.obs.costs.ici_bytes_per_s`), rolled into a
serialized-vs-overlappable report: the serialized total assumes no
compute/communication overlap, the overlapped bound assumes perfect overlap
— the gap is the budget the T3-style halo-RDMA work (ROADMAP item 2, arXiv
2401.16677) can win, now measurable per scope before any silicon run.
Between the two brackets, the ``schedule_aware`` block (obs/overlap.py's
ledger) reports where the compiled schedule actually lands: which wire
milliseconds hide under async start/done windows and which are exposed.

Also the canonical home of the pipeline-schedule tick/bubble arithmetic
(:func:`pipeline_ticks` / :func:`bubble_fraction`, docs/pipeline.md):
obs/report.py renders from these, and the readiness/probing tools reuse them
for bubble accounting instead of re-deriving the formulas.

Estimates are *analytical*: XLA fusion, layout, and memory-bound ops are not
modeled (a scope with zero conv/dot FLOPs can still burn wall-clock on
element-wise work).  Use them for ranking scopes and for overlap headroom,
not as wall-clock predictions — the RunLog's measured step records stay the
ground truth.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from mpi4dl_tpu.obs.costs import (
    DEFAULT_ICI_BYTES_PER_S,
    ici_bytes_per_s,
    peak_flops,
)
from mpi4dl_tpu.obs.hbm import Instr, parse_hlo_module, shape_bytes

#: HLO collective opcodes with a payload on the inter-chip wire.  The bare
#: opcode is the sync form; ``<base>-start``/``<base>-done`` are the async
#: halves; generic ``async-start``/``async-update``/``async-done`` wrap any
#: of them with the real collective inside the wrapped computation.
COLLECTIVE_BASES = (
    "collective-permute", "all-reduce", "all-gather", "reduce-scatter",
    "all-to-all",
)

#: Generic async plumbing opcodes.  Never counted as collectives of their
#: own: the payload is accounted exactly once — at the named ``*-start``
#: (async pairs) or at the collective inside the wrapped computation
#: (generic wrappers), with ``*-done`` always skipped — so per-scope
#: collective costs can't double-count a start/done pair.
ASYNC_GLUE_OPS = ("async-start", "async-update", "async-done")


def collective_base(opcode: str) -> Optional[str]:
    """Async-opcode normalization: ``all-gather-start`` -> ``all-gather``,
    ``collective-permute-done`` -> ``collective-permute``; None for
    non-collective opcodes, including the generic ``async-*`` glue (their
    wire class lives in the wrapped computation)."""
    for suffix in ("-start", "-done"):
        if opcode.endswith(suffix):
            opcode = opcode[: -len(suffix)]
    return opcode if opcode in COLLECTIVE_BASES else None

_DIMS = re.compile(r"\[([0-9,]*)\]")


def _dims(shape: str) -> List[int]:
    m = _DIMS.search(shape)
    if not m:
        return []
    return [int(d) for d in m.group(1).split(",") if d]


def _prod(xs) -> int:
    n = 1
    for x in xs:
        n *= x
    return n


def instr_flops(ins: Instr, line_attrs: Optional[str] = None) -> float:
    """Analytical FLOPs of one HLO instruction (0 for non-conv/dot ops).

    conv: 2 x out_elems x (kernel elements / out_features) — the per-output
    MAC count; kernel shape already folds in ``feature_group_count`` (its
    input-feature dim is per-group), so grouped/depthwise convs are right.
    dot: 2 x out_elems x contracted extent (from ``lhs_contracting_dims``).

    ``line_attrs`` defaults to the instruction's own raw line (the parser
    keeps it on :class:`~mpi4dl_tpu.obs.hbm.Instr`).
    """
    if line_attrs is None:
        line_attrs = ins.raw
    # Operand shapes live after the opcode's '(' — slicing there keeps the
    # defined (output) shape out of the operand-shape scan.
    cut = line_attrs.find(ins.opcode + "(")
    operand_text = line_attrs[cut:] if cut >= 0 else line_attrs
    if ins.opcode == "convolution":
        out = _dims(ins.shape)
        # The kernel is the second operand.
        shapes = re.findall(r"\w+\[[0-9,]*\]", operand_text)
        if len(shapes) < 2 or not out:
            return 0.0
        kernel = _dims(shapes[1])
        m = re.search(r"->([b01-9f]+)", line_attrs)
        # Output feature dim position from dim_labels ("->b01f": f last).
        out_features = out[-1]
        if m and "f" in m.group(1):
            out_features = out[m.group(1).index("f")]
        if not kernel or not out_features:
            return 0.0
        return 2.0 * _prod(out) * _prod(kernel) / out_features
    if ins.opcode == "dot":
        out = _dims(ins.shape)
        shapes = re.findall(r"\w+\[[0-9,]*\]", operand_text)
        if not shapes:
            return 0.0
        lhs = _dims(shapes[0])
        m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", line_attrs)
        contract = [int(d) for d in m.group(1).split(",") if d] if m else []
        k = _prod(lhs[c] for c in contract if c < len(lhs)) if contract else 1
        return 2.0 * _prod(out) * k
    return 0.0


def hlo_scope_costs(hlo_text: str) -> Dict[str, Dict[str, float]]:
    """Per-scope ``{flops, collective_bytes, collective_count}`` from one
    compiled HLO module's text.  Scope keys are the obs.scope vocabulary
    (:func:`~mpi4dl_tpu.obs.hlo_stats.clean_scope_path`); ops without a
    scope path aggregate under ``""``.  Walks every computation (fusion
    bodies carry the conv/dot instructions' metadata).

    Async normalization (:func:`collective_base`): a start/done pair counts
    exactly once — at the ``*-start`` with the result payload's bytes, with
    every ``*-done`` and the generic ``async-*`` glue skipped; a collective
    inside a generic async wrapper's computation counts once via the flat
    computation walk (its wrapper is glue, not a second collective)."""
    comps, _ = parse_hlo_module(hlo_text)
    out: Dict[str, Dict[str, float]] = {}

    def bucket(scope: str) -> Dict[str, float]:
        return out.setdefault(scope, {
            "flops": 0.0, "collective_bytes": 0, "collective_count": 0,
        })

    for instrs in comps.values():
        for ins in instrs:
            if ins.opcode in ("convolution", "dot"):
                fl = instr_flops(ins)
                if fl:
                    bucket(ins.scope)["flops"] += fl
                continue
            if ins.opcode in ASYNC_GLUE_OPS or ins.opcode.endswith("-done"):
                continue  # counted at the start / in the wrapped body
            if collective_base(ins.opcode) is None:
                continue
            b = bucket(ins.scope)
            nbytes = ins.bytes
            if ins.opcode.endswith("-start"):
                # Start tuples are (operand, result[, ctx]); count the
                # result payload, matching hlo_collective_stats.
                shapes = re.findall(r"\w+\[[0-9,]*\]", ins.shape)
                if len(shapes) > 1:
                    nbytes = shape_bytes(shapes[1])
            b["collective_bytes"] += nbytes
            b["collective_count"] += 1
    return out


# ---------------------------------------------------------------------------
# Pipeline schedule arithmetic (canonical home; docs/pipeline.md derivations)
# ---------------------------------------------------------------------------


def pipeline_ticks(schedule: str, stages: int, parts: int) -> Optional[int]:
    """Scan ticks per optimizer step.  GPipe: ``parts + S - 1`` forward-ish
    ticks (each tick one micro-batch through one stage).  1F1B: each tick
    runs one fwd AND one bwd micro-batch, and fill+drain cover both
    directions: ``parts + 2(S - 1)``.  None for unknown schedules."""
    if schedule == "gpipe":
        return parts + stages - 1
    if schedule == "1f1b":
        return parts + 2 * (stages - 1)
    return None


def bubble_fraction(schedule: str, stages: int, parts: int) -> Optional[float]:
    """Idle-tick fraction of the schedule: ``(ticks - parts) / ticks`` —
    GPipe ``(S-1)/(parts+S-1)``, 1F1B ``2(S-1)/(parts+2(S-1))`` (the
    docs/pipeline.md crossover arithmetic)."""
    ticks = pipeline_ticks(schedule, stages, parts)
    if ticks is None or ticks <= 0:
        return None
    return (ticks - parts) / ticks


# ---------------------------------------------------------------------------
# The timeline report
# ---------------------------------------------------------------------------


def analytical_timeline(
    hlo_text: str,
    *,
    peak: Optional[float] = None,
    ici_bw: Optional[float] = None,
    device=None,
    schedule: Optional[str] = None,
    stages: Optional[int] = None,
    parts: Optional[int] = None,
) -> dict:
    """Serialized-vs-overlappable analytical timeline of one compiled step.

    ``peak``/``ici_bw`` default from ``device`` (CPU hosts get the labeled
    nominal constants — comparable run-over-run, explicitly not a hardware
    claim).  With ``schedule``/``stages``/``parts``, adds the pipeline
    bubble accounting.  Returns a JSON-ready dict (the ``timeline`` RunLog
    record; render with :func:`format_timeline`)."""
    peak_src = ici_src = "given"
    if peak is None:
        peak, peak_src = peak_flops(device, allow_cpu_nominal=True) \
            if device is not None else (None, None)
    if ici_bw is None:
        if device is not None:
            ici_bw, ici_src = ici_bytes_per_s(device)
        else:
            ici_bw, ici_src = DEFAULT_ICI_BYTES_PER_S, "default"

    # Late import: obs/overlap.py imports this module's cost primitives.
    from mpi4dl_tpu.obs.overlap import overlap_ledger

    costs = hlo_scope_costs(hlo_text)
    rows = []
    tot_compute_ms = tot_coll_ms = 0.0
    tot_flops = 0.0
    tot_bytes = 0
    for scope, c in costs.items():
        compute_ms = (c["flops"] / peak * 1e3) if peak else None
        coll_ms = (
            c["collective_bytes"] / ici_bw * 1e3 if ici_bw else None
        )
        tot_flops += c["flops"]
        tot_bytes += int(c["collective_bytes"])
        tot_compute_ms += compute_ms or 0.0
        tot_coll_ms += coll_ms or 0.0
        rows.append({
            "scope": scope or "(unattributed)",
            "flops": c["flops"],
            "compute_ms": round(compute_ms, 4) if compute_ms is not None else None,
            "collective_bytes": int(c["collective_bytes"]),
            "collective_count": int(c["collective_count"]),
            "collective_ms": round(coll_ms, 4) if coll_ms is not None else None,
        })
    rows.sort(key=lambda r: -((r["compute_ms"] or 0) + (r["collective_ms"] or 0)))

    serialized = tot_compute_ms + tot_coll_ms
    overlapped = max(tot_compute_ms, tot_coll_ms)
    # Schedule-aware refinement of the serialized/perfect-overlap brackets:
    # the compiled module's own schedule says which wire time is actually
    # hidden under compute (obs/overlap.py; async start/done windows vs
    # structurally-sync collectives).
    ledger = overlap_ledger(hlo_text, peak=peak, ici_bw=ici_bw)
    out = {
        "rows": rows,
        "total_flops": tot_flops,
        "total_collective_bytes": tot_bytes,
        "compute_ms": round(tot_compute_ms, 4),
        "collective_ms": round(tot_coll_ms, 4),
        "serialized_ms": round(serialized, 4),
        "overlapped_ms": round(overlapped, 4),
        "overlap_headroom_ms": round(serialized - overlapped, 4),
        "schedule_aware": {
            "simulated_step_ms": ledger["simulated_step_ms"],
            "exposed_wire_ms": ledger["totals"]["exposed_ms"],
            "hidden_wire_ms": ledger["totals"]["hidden_ms"],
            "hidden_frac": ledger["hidden_frac"],
            "async_pairs": ledger["totals"]["async_pairs"],
            "sync_collectives": ledger["totals"]["sync"],
        },
        "peak_flops": peak,
        "peak_source": peak_src,
        "ici_bytes_per_s": ici_bw,
        "ici_source": ici_src,
    }
    if schedule and stages and parts:
        ticks = pipeline_ticks(schedule, stages, parts)
        bubble = bubble_fraction(schedule, stages, parts)
        out["pipeline"] = {
            "schedule": schedule, "stages": stages, "parts": parts,
            "ticks": ticks, "bubble_fraction": bubble,
            # Bubble-adjusted wall estimate: the serialized estimate is
            # per-step work; idle ticks stretch it by 1/(1-bubble).
            "bubble_adjusted_serialized_ms": (
                round(serialized / (1 - bubble), 4)
                if bubble is not None and bubble < 1 else None
            ),
        }
    return out


def format_timeline(tl: dict, top: int = 12) -> str:
    lines = [
        f"analytical timeline (peak {tl['peak_flops']:.3g} FLOP/s "
        f"[{tl['peak_source']}], ICI {tl['ici_bytes_per_s']:.3g} B/s "
        f"[{tl['ici_source']}])"
        if tl.get("peak_flops") else
        "analytical timeline (no peak FLOPs — collective times only)",
        f"serialized {tl['serialized_ms']:.3f} ms = compute "
        f"{tl['compute_ms']:.3f} + collectives {tl['collective_ms']:.3f}; "
        f"perfect overlap {tl['overlapped_ms']:.3f} ms "
        f"(headroom {tl['overlap_headroom_ms']:.3f} ms)",
    ]
    sa = tl.get("schedule_aware")
    if sa:
        hf = sa.get("hidden_frac")
        lines.append(
            f"schedule-aware: simulated step {sa['simulated_step_ms']:.3f} "
            f"ms — exposed wire {sa['exposed_wire_ms']:.3f} ms, hidden "
            f"{sa['hidden_wire_ms']:.3f} ms"
            + (f" ({hf:.1%} hidden)" if hf is not None else "")
            + f"; async pairs {sa['async_pairs']}, "
              f"sync {sa['sync_collectives']}"
        )
    pipe = tl.get("pipeline")
    if pipe:
        lines.append(
            f"pipeline: {pipe['schedule']} stages={pipe['stages']} "
            f"parts={pipe['parts']} ticks={pipe['ticks']} "
            f"bubble={pipe['bubble_fraction']:.3f}"
            + (
                f"  bubble-adjusted {pipe['bubble_adjusted_serialized_ms']:.3f} ms"
                if pipe.get("bubble_adjusted_serialized_ms") is not None else ""
            )
        )
    lines.append(
        f"{'scope':<44} {'compute_ms':>10} {'coll_ms':>8} {'coll_bytes':>12}"
    )
    for r in tl["rows"][:top]:
        lines.append(
            f"{r['scope'][:44]:<44} "
            f"{(r['compute_ms'] if r['compute_ms'] is not None else 0):>10.4f} "
            f"{(r['collective_ms'] if r['collective_ms'] is not None else 0):>8.4f} "
            f"{r['collective_bytes']:>12}"
        )
    return "\n".join(lines)
