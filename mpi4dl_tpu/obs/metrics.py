"""OpenMetrics/Prometheus text exposition over RunLog records.

The fleet-telemetry half of the observability stack: any RunLog file (a
live training leg's, a bench rung's, a supervisor's) renders to the
OpenMetrics text format — step-latency quantiles (the same
``_percentile`` interpolation as ``StepMeter.stats()`` and ``obs
report``, so the scrape never disagrees with the report), throughput,
per-device HBM watermark and skew, wire bytes per step split
quantized/raw, and supervisor incident counters by failure class.

Two sinks:

- **file** — :func:`write_metrics_file` drops a ``metrics.prom`` snapshot
  atomically next to the RunLog (benchmarks/common.py and bench.py write
  one per run/rung; a node-exporter textfile collector or CI artifact
  picks it up);
- **endpoint** — :func:`serve_metrics` is a stdlib-only HTTP server whose
  ``/metrics`` re-reads the RunLog per scrape (no new dependencies; the
  ``MPI4DL_METRICS_PORT`` hatch is the CLI's default port).

CLI: ``python -m mpi4dl_tpu.obs metrics run.jsonl [--out F] [--serve
[PORT]]``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from mpi4dl_tpu.obs.runlog import read_runlog
from mpi4dl_tpu.utils.misc import _percentile

#: Default snapshot basename (next to the RunLog it summarizes).
METRICS_BASENAME = "metrics.prom"

#: Exposition content type (OpenMetrics; Prometheus scrapes it natively).
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_QUANTILES = (0.5, 0.9, 0.99)


def metrics_port_from_env() -> Optional[int]:
    """The ``MPI4DL_METRICS_PORT`` hatch as an int port, or None (unset or
    unparsable — file-sink only)."""
    raw = os.environ.get("MPI4DL_METRICS_PORT", "")
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def _num(v: float) -> str:
    """Float rendering that round-trips and never uses locale separators."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _esc(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Exposition:
    """Ordered OpenMetrics text builder (families declared once, samples
    appended under them, ``# EOF`` terminator)."""

    def __init__(self) -> None:
        self.lines: List[str] = []

    def family(self, name: str, mtype: str, help_text: str) -> None:
        self.lines.append(f"# TYPE {name} {mtype}")
        self.lines.append(f"# HELP {name} {help_text}")

    def sample(self, name: str, value: float,
               labels: Optional[Dict[str, Any]] = None) -> None:
        self.lines.append(f"{name}{_labels(labels)} {_num(float(value))}")

    def text(self) -> str:
        return "\n".join(self.lines + ["# EOF"]) + "\n"


def _measured_steps(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r for r in records
            if r.get("kind") == "step" and r.get("measured", True)]


def _wire_totals(
    records: List[Dict[str, Any]],
) -> Optional[Tuple[float, float]]:
    """(total, quantized) wire bytes/step from ``overlap`` records — the
    min-bytes row, matching the ``obs report --compare`` extractors."""
    pairs = [
        (float(t["bytes"]), float(t.get("quantized_bytes") or 0))
        for r in records if r.get("kind") == "overlap"
        for t in [r.get("totals") or {}] if t.get("bytes") is not None
    ]
    return min(pairs) if pairs else None


def metrics_from_records(records: List[Dict[str, Any]],
                         *, prefix: str = "mpi4dl") -> str:
    """The OpenMetrics exposition of one record stream.  Families with no
    source records are omitted (absent metric > lying zero), so the output
    of a supervisor log and a bench log differ in families, not in junk."""
    exp = _Exposition()
    steps = _measured_steps(records)

    if steps:
        ms = sorted(float(r["ms"]) for r in steps)
        name = f"{prefix}_step_latency_ms"
        exp.family(name, "summary", "Measured optimizer-step wall time.")
        for q in _QUANTILES:
            exp.sample(name, _percentile(ms, q), {"quantile": _num(q)})
        exp.sample(name + "_sum", sum(ms))
        exp.sample(name + "_count", len(ms))

        ips = [float(r["images_per_sec"]) for r in steps
               if r.get("images_per_sec") is not None]
        if ips:
            name = f"{prefix}_images_per_sec"
            exp.family(name, "gauge", "Mean measured throughput.")
            exp.sample(name, sum(ips) / len(ips))

        peaks = [int(r["memory_peak_bytes"]) for r in steps
                 if r.get("memory_peak_bytes") is not None]
        if peaks:
            name = f"{prefix}_device_hbm_peak_bytes"
            exp.family(name, "gauge",
                       "Max per-device allocator watermark over the run.")
            exp.sample(name, max(peaks))
        skews = [int(r["hbm_skew"]) for r in steps
                 if r.get("hbm_skew") is not None]
        if skews:
            name = f"{prefix}_device_hbm_skew_bytes"
            exp.family(name, "gauge",
                       "Max hot-vs-cold device watermark spread (SP "
                       "imbalance shows here before the hot tile OOMs).")
            exp.sample(name, max(skews))
        rss = [int(r["host_rss_peak_bytes"]) for r in steps
               if r.get("host_rss_peak_bytes") is not None]
        if rss:
            name = f"{prefix}_host_rss_peak_bytes"
            exp.family(name, "gauge", "Peak host RSS over the run.")
            exp.sample(name, max(rss))

    wire = _wire_totals(records)
    if wire is not None:
        total, quant = wire
        name = f"{prefix}_wire_bytes_per_step"
        exp.family(name, "gauge",
                   "Collective wire payload per step (overlap ledger; "
                   "quantized = sub-f32 dtypes on the wire).")
        exp.sample(name, total, {"kind": "total"})
        exp.sample(name, quant, {"kind": "quantized"})
        exp.sample(name, total - quant, {"kind": "raw"})

    counts: Dict[str, int] = {}
    for r in records:
        if r.get("kind") in ("anomaly", "recovery", "preempt",
                             "quarantine", "restore"):
            counts[str(r["kind"])] = counts.get(str(r["kind"]), 0) + 1
    if counts:
        name = f"{prefix}_resilience_events"
        exp.family(name, "counter",
                   "Resilience events recorded by the supervised loop.")
        for kind, n in sorted(counts.items()):
            exp.sample(name + "_total", n, {"event": kind})

    incidents: Dict[str, int] = {}
    for r in records:
        if r.get("kind") == "supervisor" and r.get("failure_class"):
            cls = str(r["failure_class"])
            incidents[cls] = incidents.get(cls, 0) + 1
    if incidents:
        name = f"{prefix}_supervisor_incidents"
        exp.family(name, "counter",
                   "Supervisor incidents by typed failure class.")
        for cls, n in sorted(incidents.items()):
            exp.sample(name + "_total", n, {"class": cls})
    for r in records:
        if r.get("kind") == "supervisor_summary":
            name = f"{prefix}_supervisor_ok"
            exp.family(name, "gauge",
                       "1 = the supervised run completed, 0 = gave up.")
            exp.sample(name, 1 if r.get("ok") else 0)
            break

    if steps:
        name = f"{prefix}_steps"
        exp.family(name, "counter", "Measured optimizer steps.")
        exp.sample(name + "_total", len(steps))
    return exp.text()


def metrics_from_runlog(path: str, *, prefix: str = "mpi4dl") -> str:
    return metrics_from_records(read_runlog(path), prefix=prefix)


def write_metrics_file(records: List[Dict[str, Any]], path: str,
                       *, prefix: str = "mpi4dl") -> str:
    """Atomic snapshot write (tmp + replace — a concurrent textfile
    collector never reads a half exposition).  Returns ``path``."""
    text = metrics_from_records(records, prefix=prefix)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
    os.replace(tmp, path)
    return path


def serve_metrics(runlog_path: str, port: int, *, host: str = "127.0.0.1",
                  prefix: str = "mpi4dl"):
    """A stdlib HTTP server whose ``/metrics`` re-reads ``runlog_path`` per
    scrape.  Returns the server (caller owns ``serve_forever`` /
    ``shutdown``; ``server_address[1]`` is the bound port — pass ``port=0``
    for an ephemeral one in tests)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 — stdlib API name
            if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            try:
                body = metrics_from_runlog(
                    runlog_path, prefix=prefix).encode("utf-8")
            except OSError as e:
                self.send_error(500, explain=str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, format: str, *args: Any) -> None:
            pass  # scrape traffic must not spam the training job's stderr

    return ThreadingHTTPServer((host, port), _Handler)
