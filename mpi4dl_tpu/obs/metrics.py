"""OpenMetrics/Prometheus text exposition over RunLog records.

The fleet-telemetry half of the observability stack: any RunLog file (a
live training leg's, a bench rung's, a supervisor's) renders to the
OpenMetrics text format — step-latency quantiles (the same
``_percentile`` interpolation as ``StepMeter.stats()`` and ``obs
report``, so the scrape never disagrees with the report), throughput,
per-device HBM watermark and skew, wire bytes per step split
quantized/raw, and supervisor incident counters by failure class.

Two sinks:

- **file** — :func:`write_metrics_file` drops a ``metrics.prom`` snapshot
  atomically next to the RunLog (benchmarks/common.py and bench.py write
  one per run/rung; a node-exporter textfile collector or CI artifact
  picks it up);
- **endpoint** — :func:`serve_metrics` is a stdlib-only HTTP server whose
  ``/metrics`` re-reads the RunLog(s) per scrape (no new dependencies; the
  ``MPI4DL_METRICS_PORT`` hatch is the CLI's default port).

Fleet aggregation (ISSUE 18): :func:`metrics_from_runlogs` merges many
RunLogs — a whole fleet's per-job supervisor logs plus the fleet log —
into ONE exposition, every sample labeled ``job="<id>"``, each metric
family declared exactly once.  ``serve_metrics`` accepts the same
multi-source forms, so one ``MPI4DL_METRICS_PORT`` endpoint serves the
whole fleet.

CLI: ``python -m mpi4dl_tpu.obs metrics run.jsonl [more.jsonl | DIR ...]
[--out F] [--serve [PORT]]`` (a DIR argument expands to every
``*.jsonl`` under it, recursively).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from mpi4dl_tpu.obs.runlog import read_runlog
from mpi4dl_tpu.utils.misc import _percentile

#: Default snapshot basename (next to the RunLog it summarizes).
METRICS_BASENAME = "metrics.prom"

#: Exposition content type (OpenMetrics; Prometheus scrapes it natively).
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

_QUANTILES = (0.5, 0.9, 0.99)


def metrics_port_from_env() -> Optional[int]:
    """The ``MPI4DL_METRICS_PORT`` hatch as an int port, or None (unset or
    unparsable — file-sink only)."""
    raw = os.environ.get("MPI4DL_METRICS_PORT", "")
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def _num(v: float) -> str:
    """Float rendering that round-trips and never uses locale separators."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _esc(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Exposition:
    """Ordered OpenMetrics text builder (families declared once, samples
    appended under them, ``# EOF`` terminator)."""

    def __init__(self) -> None:
        self.lines: List[str] = []

    def family(self, name: str, mtype: str, help_text: str) -> None:
        self.lines.append(f"# TYPE {name} {mtype}")
        self.lines.append(f"# HELP {name} {help_text}")

    def sample(self, name: str, value: float,
               labels: Optional[Dict[str, Any]] = None) -> None:
        self.lines.append(f"{name}{_labels(labels)} {_num(float(value))}")

    def text(self) -> str:
        return "\n".join(self.lines + ["# EOF"]) + "\n"


def _measured_steps(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r for r in records
            if r.get("kind") == "step" and r.get("measured", True)]


def _wire_totals(
    records: List[Dict[str, Any]],
) -> Optional[Tuple[float, float]]:
    """(total, quantized) wire bytes/step from ``overlap`` records — the
    min-bytes row, matching the ``obs report --compare`` extractors."""
    pairs = [
        (float(t["bytes"]), float(t.get("quantized_bytes") or 0))
        for r in records if r.get("kind") == "overlap"
        for t in [r.get("totals") or {}] if t.get("bytes") is not None
    ]
    return min(pairs) if pairs else None


#: One family's worth of samples: (name, type, help, [(sample_name,
#: value, labels), ...]).  The collect/emit split is what lets
#: :func:`metrics_from_runlogs` merge many record streams under ONE
#: family declaration per metric (OpenMetrics forbids repeating # TYPE).
_Family = Tuple[str, str, str, List[Tuple[str, float, Optional[Dict[str, Any]]]]]


def _collect(records: List[Dict[str, Any]], *, prefix: str,
             labels: Optional[Dict[str, Any]] = None) -> List[_Family]:
    """Per-family samples of one record stream.  Families with no source
    records are omitted (absent metric > lying zero), so the output of a
    supervisor log and a bench log differ in families, not in junk.
    ``labels`` (e.g. ``{"job": "alpha"}``) is stamped onto every sample."""
    base = dict(labels or {})

    def lab(extra: Optional[Dict[str, Any]] = None) -> Optional[Dict[str, Any]]:
        merged = {**base, **(extra or {})}
        return merged or None

    fams: List[_Family] = []
    steps = _measured_steps(records)

    if steps:
        ms = sorted(float(r["ms"]) for r in steps)
        name = f"{prefix}_step_latency_ms"
        samples = [(name, _percentile(ms, q), lab({"quantile": _num(q)}))
                   for q in _QUANTILES]
        samples += [(name + "_sum", sum(ms), lab()),
                    (name + "_count", float(len(ms)), lab())]
        fams.append((name, "summary",
                     "Measured optimizer-step wall time.", samples))

        ips = [float(r["images_per_sec"]) for r in steps
               if r.get("images_per_sec") is not None]
        if ips:
            name = f"{prefix}_images_per_sec"
            fams.append((name, "gauge", "Mean measured throughput.",
                         [(name, sum(ips) / len(ips), lab())]))

        peaks = [int(r["memory_peak_bytes"]) for r in steps
                 if r.get("memory_peak_bytes") is not None]
        if peaks:
            name = f"{prefix}_device_hbm_peak_bytes"
            fams.append((name, "gauge",
                         "Max per-device allocator watermark over the run.",
                         [(name, float(max(peaks)), lab())]))
        skews = [int(r["hbm_skew"]) for r in steps
                 if r.get("hbm_skew") is not None]
        if skews:
            name = f"{prefix}_device_hbm_skew_bytes"
            fams.append((name, "gauge",
                         "Max hot-vs-cold device watermark spread (SP "
                         "imbalance shows here before the hot tile OOMs).",
                         [(name, float(max(skews)), lab())]))
        rss = [int(r["host_rss_peak_bytes"]) for r in steps
               if r.get("host_rss_peak_bytes") is not None]
        if rss:
            name = f"{prefix}_host_rss_peak_bytes"
            fams.append((name, "gauge", "Peak host RSS over the run.",
                         [(name, float(max(rss)), lab())]))

    wire = _wire_totals(records)
    if wire is not None:
        total, quant = wire
        name = f"{prefix}_wire_bytes_per_step"
        fams.append((name, "gauge",
                     "Collective wire payload per step (overlap ledger; "
                     "quantized = sub-f32 dtypes on the wire).",
                     [(name, total, lab({"kind": "total"})),
                      (name, quant, lab({"kind": "quantized"})),
                      (name, total - quant, lab({"kind": "raw"}))]))

    counts: Dict[str, int] = {}
    for r in records:
        if r.get("kind") in ("anomaly", "recovery", "preempt",
                             "quarantine", "restore"):
            counts[str(r["kind"])] = counts.get(str(r["kind"]), 0) + 1
    if counts:
        name = f"{prefix}_resilience_events"
        fams.append((name, "counter",
                     "Resilience events recorded by the supervised loop.",
                     [(name + "_total", float(n), lab({"event": kind}))
                      for kind, n in sorted(counts.items())]))

    incidents: Dict[str, int] = {}
    for r in records:
        if r.get("kind") == "supervisor" and r.get("failure_class"):
            cls = str(r["failure_class"])
            incidents[cls] = incidents.get(cls, 0) + 1
    if incidents:
        name = f"{prefix}_supervisor_incidents"
        fams.append((name, "counter",
                     "Supervisor incidents by typed failure class.",
                     [(name + "_total", float(n), lab({"class": cls}))
                      for cls, n in sorted(incidents.items())]))
    for r in records:
        if r.get("kind") == "supervisor_summary":
            name = f"{prefix}_supervisor_ok"
            fams.append((name, "gauge",
                         "1 = the supervised run completed, 0 = gave up.",
                         [(name, 1.0 if r.get("ok") else 0.0, lab())]))
            break

    fleet_events: Dict[str, int] = {}
    for r in records:
        if r.get("kind") == "fleet" and r.get("event"):
            ev = str(r["event"])
            fleet_events[ev] = fleet_events.get(ev, 0) + 1
    if fleet_events:
        name = f"{prefix}_fleet_events"
        fams.append((name, "counter",
                     "Fleet scheduler decisions by event type.",
                     [(name + "_total", float(n), lab({"event": ev}))
                      for ev, n in sorted(fleet_events.items())]))
    for r in records:
        if r.get("kind") == "fleet_summary":
            name = f"{prefix}_fleet_ok"
            fams.append((name, "gauge",
                         "1 = every fleet job reached a non-failed "
                         "terminal state.",
                         [(name, 1.0 if r.get("ok") else 0.0, lab())]))
            states: Dict[str, int] = {}
            for st in (r.get("jobs") or {}).values():
                states[str(st)] = states.get(str(st), 0) + 1
            if states:
                name = f"{prefix}_fleet_jobs"
                fams.append((name, "gauge",
                             "Fleet jobs by final lifecycle state.",
                             [(name, float(n), lab({"state": st}))
                              for st, n in sorted(states.items())]))
            break

    if steps:
        name = f"{prefix}_steps"
        fams.append((name, "counter", "Measured optimizer steps.",
                     [(name + "_total", float(len(steps)), lab())]))
    return fams


def _emit(families: List[_Family]) -> str:
    exp = _Exposition()
    for name, mtype, help_text, samples in families:
        exp.family(name, mtype, help_text)
        for sname, value, slabels in samples:
            exp.sample(sname, value, slabels)
    return exp.text()


def metrics_from_records(records: List[Dict[str, Any]],
                         *, prefix: str = "mpi4dl",
                         labels: Optional[Dict[str, Any]] = None) -> str:
    """The OpenMetrics exposition of one record stream."""
    return _emit(_collect(records, prefix=prefix, labels=labels))


def metrics_from_runlog(path: str, *, prefix: str = "mpi4dl") -> str:
    return metrics_from_records(read_runlog(path), prefix=prefix)


def _job_paths(source) -> List[Tuple[str, str]]:
    """Normalize a metrics source into ``[(job, path), ...]``.

    A mapping is taken verbatim (sorted by job for a stable exposition).
    For a sequence of paths the job id is inferred: the file stem, except
    when stems collide (the fleet layout is ``jobs/<id>/supervisor00.jsonl``
    — every job's log shares a stem), in which case the parent directory
    name is used; any survivors of both rules are uniquified with ``~N``."""
    if isinstance(source, str):
        source = [source]
    if hasattr(source, "items"):
        return sorted((str(j), str(p)) for j, p in source.items())
    paths = [str(p) for p in source]
    stems = [os.path.splitext(os.path.basename(p))[0] for p in paths]
    jobs = []
    for p, stem in zip(paths, stems):
        if stems.count(stem) > 1:
            parent = os.path.basename(os.path.dirname(os.path.abspath(p)))
            jobs.append(parent or stem)
        else:
            jobs.append(stem)
    seen: Dict[str, int] = {}
    out: List[Tuple[str, str]] = []
    for job, p in zip(jobs, paths):
        seen[job] = seen.get(job, 0) + 1
        out.append((job if seen[job] == 1 else f"{job}~{seen[job]}", p))
    return out


def metrics_from_runlogs(source, *, prefix: str = "mpi4dl") -> str:
    """ONE exposition over many RunLogs, every sample labeled
    ``job="<id>"`` (ISSUE 18: the fleet's jobs scrape from a single
    ``MPI4DL_METRICS_PORT`` endpoint, not one port per job).

    ``source``: a mapping ``{job: path}``, a sequence of paths (job ids
    inferred — see :func:`_job_paths`), or a single path string.  Each
    metric family is declared once with every job's samples under it."""
    merged: Dict[str, _Family] = {}
    order: List[str] = []
    for job, path in _job_paths(source):
        for name, mtype, help_text, samples in _collect(
                read_runlog(path), prefix=prefix, labels={"job": job}):
            if name not in merged:
                merged[name] = (name, mtype, help_text, [])
                order.append(name)
            merged[name][3].extend(samples)
    return _emit([merged[name] for name in order])


def _atomic_write(text: str, path: str) -> str:
    """Tmp + replace — a concurrent textfile collector never reads a half
    exposition.  Returns ``path``."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(text)
    os.replace(tmp, path)
    return path


def write_metrics_file(records: List[Dict[str, Any]], path: str,
                       *, prefix: str = "mpi4dl") -> str:
    """Atomic exposition snapshot of one record stream."""
    return _atomic_write(metrics_from_records(records, prefix=prefix), path)


def serve_metrics(source, port: int, *, host: str = "127.0.0.1",
                  prefix: str = "mpi4dl"):
    """A stdlib HTTP server whose ``/metrics`` re-reads ``source`` per
    scrape.  ``source`` is one RunLog path (unlabeled exposition, the
    pre-fleet behavior) or a mapping / sequence of paths (one aggregated
    ``job``-labeled exposition — the fleet's single-endpoint scrape).
    Returns the server (caller owns ``serve_forever`` / ``shutdown``;
    ``server_address[1]`` is the bound port — pass ``port=0`` for an
    ephemeral one in tests)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self) -> None:  # noqa: N802 — stdlib API name
            if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            try:
                if isinstance(source, str):
                    text = metrics_from_runlog(source, prefix=prefix)
                else:
                    text = metrics_from_runlogs(source, prefix=prefix)
                body = text.encode("utf-8")
            except OSError as e:
                self.send_error(500, explain=str(e))
                return
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, format: str, *args: Any) -> None:
            pass  # scrape traffic must not spam the training job's stderr

    return ThreadingHTTPServer((host, port), _Handler)
