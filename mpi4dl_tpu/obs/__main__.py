"""CLI: ``python -m mpi4dl_tpu.obs report run.jsonl [more.jsonl ...]``.

Renders the summary table of one or more RunLog files (docs/observability.md
documents every field).  Exit status: 0 on success, 2 on usage errors or
unreadable files.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.obs",
        description="Telemetry surfaces (see docs/observability.md).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="render RunLog JSONL file(s)")
    rep.add_argument("paths", nargs="+", help="run .jsonl file(s)")
    args = ap.parse_args(argv)

    if args.cmd == "report":
        from mpi4dl_tpu.obs.report import render_run

        for i, path in enumerate(args.paths):
            try:
                text = render_run(path)
            except OSError as e:
                print(f"obs report: cannot read {path}: {e}", file=sys.stderr)
                return 2
            if i:
                print()
            print(text)
        return 0
    return 2  # pragma: no cover — argparse enforces the subcommand


if __name__ == "__main__":
    raise SystemExit(main())
