"""CLI: ``python -m mpi4dl_tpu.obs report run.jsonl [more.jsonl ...]``
and ``... report --compare A.jsonl B.jsonl [--threshold PCT]``.

Renders the summary table of one or more RunLog files, or the per-metric
regression diff of two (docs/observability.md documents every field and the
compare metrics).  Exit status: 0 on success, 1 when --compare finds a
regression past the threshold, 2 on usage errors or unreadable files.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.obs",
        description="Telemetry surfaces (see docs/observability.md).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report",
        help="render RunLog JSONL file(s), or A/B-diff two with --compare",
    )
    rep.add_argument("paths", nargs="*", help="run .jsonl file(s)")
    rep.add_argument(
        "--compare", nargs=2, metavar=("A", "B"), default=None,
        help="per-metric regression diff (A = baseline, B = candidate): "
             "step ms, images/sec, peak HBM, collective bytes, mem_probe "
             "peak; exit 1 when a metric regresses past --threshold",
    )
    rep.add_argument(
        "--threshold", type=float, default=5.0,
        help="regression threshold in percent for --compare (default 5)",
    )
    args = ap.parse_args(argv)

    if args.cmd == "report":
        if args.compare and args.paths:
            print("obs report: --compare takes exactly two files; drop the "
                  "positional run file(s) or the flag", file=sys.stderr)
            return 2
        if args.compare:
            from mpi4dl_tpu.obs.report import compare_runs

            try:
                text, breaches = compare_runs(
                    args.compare[0], args.compare[1], args.threshold
                )
            except OSError as e:
                print(f"obs report: cannot read compare input: {e}",
                      file=sys.stderr)
                return 2
            print(text)
            return 1 if breaches else 0
        if not args.paths:
            print("obs report: need run file(s) or --compare A B",
                  file=sys.stderr)
            return 2
        from mpi4dl_tpu.obs.report import render_run

        for i, path in enumerate(args.paths):
            try:
                text = render_run(path)
            except OSError as e:
                print(f"obs report: cannot read {path}: {e}", file=sys.stderr)
                return 2
            if i:
                print()
            print(text)
        return 0
    return 2  # pragma: no cover — argparse enforces the subcommand


if __name__ == "__main__":
    raise SystemExit(main())
