"""CLI: ``python -m mpi4dl_tpu.obs report run.jsonl [more.jsonl ...]``,
``... report --compare A.jsonl B.jsonl [--threshold PCT]``, and
``... overlap --families lp,sp|all [--json] [--out F]``.

``report`` renders the summary table of one or more RunLog files, or the
per-metric regression diff of two (docs/observability.md documents every
field and the compare metrics).  ``overlap`` builds + compiles engine
families on the virtual mesh (or reads an HLO text dump via ``--hlo``) and
prints their exposed-wire ledgers (obs/overlap.py) — the CI
``overlap-contract`` job's ledger artifact.  Exit status: 0 on success, 1
when --compare finds a regression past the threshold, 2 on usage errors or
unreadable files.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.obs",
        description="Telemetry surfaces (see docs/observability.md).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report",
        help="render RunLog JSONL file(s), or A/B-diff two with --compare",
    )
    rep.add_argument("paths", nargs="*", help="run .jsonl file(s)")
    rep.add_argument(
        "--compare", nargs=2, metavar=("A", "B"), default=None,
        help="per-metric regression diff (A = baseline, B = candidate): "
             "step ms, images/sec, peak HBM, collective bytes, mem_probe "
             "peak, exposed wire ms; exit 1 when a metric regresses past "
             "--threshold",
    )
    rep.add_argument(
        "--threshold", type=float, default=5.0,
        help="regression threshold in percent for --compare (default 5)",
    )
    ovl = sub.add_parser(
        "overlap",
        help="exposed-wire ledger of engine families (compiled on the "
             "virtual mesh) or of an HLO text dump",
    )
    ovl.add_argument(
        "--families", default=None,
        help="comma-separated engine families to compile and ledger "
             "('all' = every contract family)",
    )
    ovl.add_argument("--hlo", default=None, metavar="F",
                     help="ledger an existing compiled-HLO text dump "
                          "instead of building engines")
    ovl.add_argument("--json", action="store_true",
                     help="machine-readable ledgers on stdout")
    ovl.add_argument("--out", default=None, metavar="F",
                     help="also write the JSON ledgers to this file")
    args = ap.parse_args(argv)

    if args.cmd == "overlap":
        return _overlap_cmd(args)

    if args.cmd == "report":
        if args.compare and args.paths:
            print("obs report: --compare takes exactly two files; drop the "
                  "positional run file(s) or the flag", file=sys.stderr)
            return 2
        if args.compare:
            from mpi4dl_tpu.obs.report import compare_runs

            try:
                text, breaches = compare_runs(
                    args.compare[0], args.compare[1], args.threshold
                )
            except OSError as e:
                print(f"obs report: cannot read compare input: {e}",
                      file=sys.stderr)
                return 2
            print(text)
            return 1 if breaches else 0
        if not args.paths:
            print("obs report: need run file(s) or --compare A B",
                  file=sys.stderr)
            return 2
        from mpi4dl_tpu.obs.report import render_run

        for i, path in enumerate(args.paths):
            try:
                text = render_run(path)
            except OSError as e:
                print(f"obs report: cannot read {path}: {e}", file=sys.stderr)
                return 2
            if i:
                print()
            print(text)
        return 0
    return 2  # pragma: no cover — argparse enforces the subcommand


def _overlap_cmd(args) -> int:
    """``obs overlap``: per-family (or per-HLO-dump) exposed-wire ledgers."""
    from mpi4dl_tpu.obs.overlap import format_ledger, overlap_ledger

    if bool(args.hlo) == bool(args.families):
        print("obs overlap: need exactly one of --families or --hlo",
              file=sys.stderr)
        return 2

    ledgers = {}
    if args.hlo:
        try:
            with open(args.hlo, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            print(f"obs overlap: cannot read {args.hlo}: {e}",
                  file=sys.stderr)
            return 2
        # Same cost rates as the --families branch (device-derived, nominal
        # on CPU hosts): without a peak the compute windows would cost 0 ms
        # and every async pair would read as fully exposed.
        import jax

        ledgers[args.hlo] = overlap_ledger(text, device=jax.devices()[0])
    else:
        from mpi4dl_tpu.analysis.contracts.engines import (
            ENGINE_FAMILIES,
            build_engine,
        )
        from mpi4dl_tpu.analysis.contracts.extract import ensure_virtual_mesh

        families = (
            list(ENGINE_FAMILIES) if args.families == "all"
            else [f.strip() for f in args.families.split(",") if f.strip()]
        )
        unknown = [f for f in families if f not in ENGINE_FAMILIES]
        if unknown:
            print(f"obs overlap: unknown engine(s) {unknown}; "
                  f"have {list(ENGINE_FAMILIES)}", file=sys.stderr)
            return 2
        err = ensure_virtual_mesh(families)
        if err:
            print(f"obs overlap: {err}", file=sys.stderr)
            return 2
        import jax

        # Bypass the persistent compilation cache: it keys on the program
        # minus debug metadata, and the ledger needs the op_name scopes
        # (the obs/hbm.py attribution caveat).
        jax.config.update("jax_compilation_cache_dir", None)
        for family in families:
            step, fargs = build_engine(family)
            compiled = step.lower(*fargs).compile()
            ledgers[family] = overlap_ledger(compiled.as_text(),
                                             device=jax.devices()[0])

    payload = json.dumps(ledgers, indent=1, sort_keys=True)
    # Write the artifact before stdout: a consumer truncating the pipe
    # (e.g. `| head`) must not cost the CI job its ledger file.
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    if args.json:
        print(payload)
    else:
        for i, (name, ledger) in enumerate(ledgers.items()):
            if i:
                print()
            print(f"== {name}")
            print(format_ledger(ledger))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
