"""CLI: ``python -m mpi4dl_tpu.obs report run.jsonl [more.jsonl ...]``,
``... report --compare A.jsonl B.jsonl [--threshold PCT]``,
``... report --trend DIR [--trend-out F]``,
``... overlap --families lp,sp|all [--json] [--out F]``,
``... trace [--families lp,...|--hlo F|--runlog F] --out trace.json``, and
``... metrics run.jsonl [--out F] [--serve [PORT]]``.

``report`` renders the summary table of one or more RunLog files, the
per-metric regression diff of two (docs/observability.md documents every
field and the compare metrics), or the directory-wide trajectory + gate
(obs/trend.py).  ``overlap`` builds + compiles engine families on the
virtual mesh (or reads an HLO text dump via ``--hlo``) and prints their
exposed-wire ledgers (obs/overlap.py) — the CI ``overlap-contract`` job's
ledger artifact.  ``trace`` exports the same compiled artifacts (and/or a
RunLog's measured walls) as Chrome/Perfetto trace-event JSON
(obs/trace.py).  ``metrics`` renders a RunLog as OpenMetrics text
(obs/metrics.py).  Exit status: 0 on success, 1 when --compare/--trend
finds a regression past the threshold, 2 on usage errors or unreadable
files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.obs",
        description="Telemetry surfaces (see docs/observability.md).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser(
        "report",
        help="render RunLog JSONL file(s), or A/B-diff two with --compare",
    )
    rep.add_argument("paths", nargs="*", help="run .jsonl file(s)")
    rep.add_argument(
        "--compare", nargs=2, metavar=("A", "B"), default=None,
        help="per-metric regression diff (A = baseline, B = candidate): "
             "step ms, images/sec, peak HBM, collective bytes, mem_probe "
             "peak, exposed wire ms; exit 1 when a metric regresses past "
             "--threshold",
    )
    rep.add_argument(
        "--threshold", type=float, default=5.0,
        help="regression threshold in percent for --compare/--trend "
             "(default 5)",
    )
    rep.add_argument(
        "--trend", default=None, metavar="DIR",
        help="trajectory + newest-vs-previous regression gate over every "
             "RunLog (*.jsonl) and bench artifact (BENCH_*.json) in DIR; "
             "exit 1 when the newest run of a series regresses past "
             "--threshold",
    )
    rep.add_argument(
        "--trend-out", default=None, metavar="F",
        help="also write the --trend JSON artifact to this file",
    )
    ovl = sub.add_parser(
        "overlap",
        help="exposed-wire ledger of engine families (compiled on the "
             "virtual mesh) or of an HLO text dump",
    )
    ovl.add_argument(
        "--families", default=None,
        help="comma-separated engine families to compile and ledger "
             "('all' = every contract family)",
    )
    ovl.add_argument("--hlo", default=None, metavar="F",
                     help="ledger an existing compiled-HLO text dump "
                          "instead of building engines")
    ovl.add_argument("--json", action="store_true",
                     help="machine-readable ledgers on stdout")
    ovl.add_argument("--out", default=None, metavar="F",
                     help="also write the JSON ledgers to this file")
    trc = sub.add_parser(
        "trace",
        help="Chrome/Perfetto trace-event export: compiled engine families "
             "(simulated wire + analytical + pipeline-tick lanes) and/or a "
             "RunLog's measured step walls",
    )
    trc.add_argument(
        "--families", default=None,
        help="comma-separated engine families to compile and trace "
             "('all' = every contract family)",
    )
    trc.add_argument("--hlo", default=None, metavar="F",
                     help="trace an existing compiled-HLO text dump "
                          "instead of building engines")
    trc.add_argument("--runlog", default=None, metavar="F",
                     help="add measured lanes from this RunLog .jsonl")
    trc.add_argument("--out", default=None, metavar="F", required=True,
                     help="write the trace-event JSON here "
                          "(load in ui.perfetto.dev / chrome://tracing)")
    met = sub.add_parser(
        "metrics",
        help="OpenMetrics/Prometheus text exposition of one or more "
             "RunLogs (many → one job-labeled exposition)",
    )
    met.add_argument("paths", nargs="+", metavar="PATH",
                     help="run .jsonl file(s) and/or directories (a "
                          "directory expands to every *.jsonl under it, "
                          "recursively — e.g. a fleet drill's workdir)")
    met.add_argument("--out", default=None, metavar="F",
                     help="write the exposition here (atomic) instead of "
                          "stdout")
    met.add_argument(
        "--serve", nargs="?", type=int, const=-1, default=None,
        metavar="PORT",
        help="serve /metrics over stdlib HTTP, re-reading the RunLog per "
             "scrape (PORT defaults to the MPI4DL_METRICS_PORT hatch)",
    )
    args = ap.parse_args(argv)

    if args.cmd == "overlap":
        return _overlap_cmd(args)
    if args.cmd == "trace":
        return _trace_cmd(args)
    if args.cmd == "metrics":
        return _metrics_cmd(args)

    if args.cmd == "report":
        if args.trend:
            if args.compare or args.paths:
                print("obs report: --trend stands alone; drop --compare "
                      "and positional files", file=sys.stderr)
                return 2
            return _trend_cmd(args)
        if args.compare and args.paths:
            print("obs report: --compare takes exactly two files; drop the "
                  "positional run file(s) or the flag", file=sys.stderr)
            return 2
        if args.compare:
            from mpi4dl_tpu.obs.report import compare_runs

            try:
                text, breaches = compare_runs(
                    args.compare[0], args.compare[1], args.threshold
                )
            except OSError as e:
                print(f"obs report: cannot read compare input: {e}",
                      file=sys.stderr)
                return 2
            print(text)
            return 1 if breaches else 0
        if not args.paths:
            print("obs report: need run file(s) or --compare A B",
                  file=sys.stderr)
            return 2
        from mpi4dl_tpu.obs.report import render_run

        for i, path in enumerate(args.paths):
            try:
                text = render_run(path)
            except OSError as e:
                print(f"obs report: cannot read {path}: {e}", file=sys.stderr)
                return 2
            if i:
                print()
            print(text)
        return 0
    return 2  # pragma: no cover — argparse enforces the subcommand


def _overlap_cmd(args) -> int:
    """``obs overlap``: per-family (or per-HLO-dump) exposed-wire ledgers."""
    from mpi4dl_tpu.obs.overlap import format_ledger, overlap_ledger

    if bool(args.hlo) == bool(args.families):
        print("obs overlap: need exactly one of --families or --hlo",
              file=sys.stderr)
        return 2

    ledgers = {}
    if args.hlo:
        try:
            with open(args.hlo, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            print(f"obs overlap: cannot read {args.hlo}: {e}",
                  file=sys.stderr)
            return 2
        # Same cost rates as the --families branch (device-derived, nominal
        # on CPU hosts): without a peak the compute windows would cost 0 ms
        # and every async pair would read as fully exposed.
        import jax

        ledgers[args.hlo] = overlap_ledger(text, device=jax.devices()[0])
    else:
        from mpi4dl_tpu.analysis.contracts.engines import (
            ENGINE_FAMILIES,
            build_engine,
        )
        from mpi4dl_tpu.analysis.contracts.extract import ensure_virtual_mesh

        families = (
            list(ENGINE_FAMILIES) if args.families == "all"
            else [f.strip() for f in args.families.split(",") if f.strip()]
        )
        unknown = [f for f in families if f not in ENGINE_FAMILIES]
        if unknown:
            print(f"obs overlap: unknown engine(s) {unknown}; "
                  f"have {list(ENGINE_FAMILIES)}", file=sys.stderr)
            return 2
        err = ensure_virtual_mesh(families)
        if err:
            print(f"obs overlap: {err}", file=sys.stderr)
            return 2
        import jax

        # Bypass the persistent compilation cache: it keys on the program
        # minus debug metadata, and the ledger needs the op_name scopes
        # (the obs/hbm.py attribution caveat).
        jax.config.update("jax_compilation_cache_dir", None)
        for family in families:
            step, fargs = build_engine(family)
            compiled = step.lower(*fargs).compile()
            ledgers[family] = overlap_ledger(compiled.as_text(),
                                             device=jax.devices()[0])

    payload = json.dumps(ledgers, indent=1, sort_keys=True)
    # Write the artifact before stdout: a consumer truncating the pipe
    # (e.g. `| head`) must not cost the CI job its ledger file.
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    if args.json:
        print(payload)
    else:
        for i, (name, ledger) in enumerate(ledgers.items()):
            if i:
                print()
            print(f"== {name}")
            print(format_ledger(ledger))
    return 0


def _trace_cmd(args) -> int:
    """``obs trace``: Chrome/Perfetto trace-event JSON of compiled engine
    families (simulated wire, analytical, pipeline-tick lanes) and/or a
    RunLog's measured lanes.  Same compile pattern as ``obs overlap``."""
    from mpi4dl_tpu.obs.trace import (
        chrome_trace,
        hlo_trace_events,
        trace_from_runlog,
    )

    if bool(args.hlo) and bool(args.families):
        print("obs trace: --families and --hlo are mutually exclusive",
              file=sys.stderr)
        return 2
    if not (args.hlo or args.families or args.runlog):
        print("obs trace: need --families, --hlo, or --runlog",
              file=sys.stderr)
        return 2

    events = []
    if args.hlo:
        try:
            with open(args.hlo, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError as e:
            print(f"obs trace: cannot read {args.hlo}: {e}",
                  file=sys.stderr)
            return 2
        import jax

        events += hlo_trace_events(text, label=args.hlo,
                                   device=jax.devices()[0])
    elif args.families:
        from mpi4dl_tpu.analysis.contracts.engines import (
            _PARTS,
            _STAGES,
            ENGINE_FAMILIES,
            build_engine,
        )
        from mpi4dl_tpu.analysis.contracts.extract import ensure_virtual_mesh

        families = (
            list(ENGINE_FAMILIES) if args.families == "all"
            else [f.strip() for f in args.families.split(",") if f.strip()]
        )
        unknown = [f for f in families if f not in ENGINE_FAMILIES]
        if unknown:
            print(f"obs trace: unknown engine(s) {unknown}; "
                  f"have {list(ENGINE_FAMILIES)}", file=sys.stderr)
            return 2
        err = ensure_virtual_mesh(families)
        if err:
            print(f"obs trace: {err}", file=sys.stderr)
            return 2
        import jax

        # Bypass the persistent compilation cache: the trace lanes need the
        # op_name scopes that cache hits strip (the obs/hbm.py caveat).
        jax.config.update("jax_compilation_cache_dir", None)
        for i, family in enumerate(families):
            step, fargs = build_engine(family)
            compiled = step.lower(*fargs).compile()
            events += hlo_trace_events(
                compiled.as_text(),
                label=family,
                device=jax.devices()[0],
                schedule="1f1b" if family.endswith("_1f1b") else "gpipe",
                stages=_STAGES,
                parts=_PARTS,
                pid_base=1 + i * 10,
            )
    if args.runlog:
        from mpi4dl_tpu.obs.runlog import read_runlog

        try:
            records = read_runlog(args.runlog)
        except OSError as e:
            print(f"obs trace: cannot read {args.runlog}: {e}",
                  file=sys.stderr)
            return 2
        events += trace_from_runlog(records, label=args.runlog)

    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(chrome_trace(events), fh)
        fh.write("\n")
    print(f"obs trace: wrote {len(events)} events to {args.out}")
    return 0


def _metrics_paths(raw: List[str]) -> List[str]:
    """Expand the metrics CLI's positional args: files pass through,
    directories expand to every ``*.jsonl`` under them (recursive, sorted
    — a fleet drill workdir becomes its fleet log + every job's
    supervisor logs)."""
    import glob

    out: List[str] = []
    for p in raw:
        if os.path.isdir(p):
            out.extend(sorted(
                glob.glob(os.path.join(p, "**", "*.jsonl"), recursive=True)
            ))
        else:
            out.append(p)
    return out


def _metrics_cmd(args) -> int:
    """``obs metrics``: OpenMetrics exposition of RunLog(s) — stdout,
    atomic file sink, and/or the stdlib HTTP endpoint.  Multiple inputs
    aggregate into ONE ``job``-labeled exposition (ISSUE 18)."""
    from mpi4dl_tpu.obs.metrics import (
        metrics_from_runlog,
        metrics_from_runlogs,
        metrics_port_from_env,
        serve_metrics,
        write_metrics_file,
    )
    from mpi4dl_tpu.obs.runlog import read_runlog

    paths = _metrics_paths(args.paths)
    if not paths:
        print(f"obs metrics: no .jsonl files in {args.paths}",
              file=sys.stderr)
        return 2
    single = paths[0] if len(paths) == 1 else None
    try:
        if single is not None and args.out:
            write_metrics_file(read_runlog(single), args.out)
            print(f"obs metrics: wrote {args.out}")
        elif args.out:
            from mpi4dl_tpu.obs.metrics import _atomic_write

            _atomic_write(metrics_from_runlogs(paths), args.out)
            print(f"obs metrics: wrote {args.out} "
                  f"({len(paths)} runlogs, job-labeled)")
        elif args.serve is None:
            sys.stdout.write(metrics_from_runlog(single) if single
                             else metrics_from_runlogs(paths))
    except OSError as e:
        print(f"obs metrics: cannot read input: {e}", file=sys.stderr)
        return 2
    if args.serve is not None:
        port = args.serve if args.serve >= 0 else metrics_port_from_env()
        if port is None:
            print("obs metrics: --serve needs a PORT (or set "
                  "MPI4DL_METRICS_PORT)", file=sys.stderr)
            return 2
        srv = serve_metrics(single if single is not None else paths, port)
        host, bound = srv.server_address[0], srv.server_address[1]
        print(f"obs metrics: serving http://{host}:{bound}/metrics "
              "(Ctrl-C to stop)", file=sys.stderr)
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            srv.server_close()
    return 0


def _trend_cmd(args) -> int:
    """``obs report --trend DIR``: trajectory + per-series regression gate
    (obs/trend.py).  Exit 1 on a gated breach."""
    from mpi4dl_tpu.obs.trend import format_trend, trend_report

    if not os.path.isdir(args.trend):
        print(f"obs report: --trend {args.trend}: not a directory",
              file=sys.stderr)
        return 2
    trend = trend_report(args.trend, threshold_pct=args.threshold)
    # Artifact before stdout — a truncated pipe must not cost CI the JSON.
    if args.trend_out:
        with open(args.trend_out, "w", encoding="utf-8") as fh:
            json.dump(trend, fh, indent=1, sort_keys=True)
            fh.write("\n")
    print(format_trend(trend))
    return 1 if trend["breaches"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
