"""Collective accounting from compiled HLO — the reusable library form of
``benchmarks/communication/comm_volume_report.py`` (which now imports from
here).

Any jitted step can report, at runtime and on any host, how many collectives
XLA actually scheduled per step and the bytes each class moves — the
compiler-derived counterpart of the reference's MPI message accounting
(SURVEY §2a): collective-permute (halo exchange, pipeline handoffs, GEMS
mirror), all-reduce (DP gradients, cross-tile BN), all-gather /
reduce-scatter / all-to-all (junctions, GSPMD resharding).

Also home to :func:`stablehlo_debug_text`, the scope-name view of a lowered
(not yet compiled) program: StableHLO printed with debug locations carries
the ``jax.named_scope`` stack (``loc("jit(step)/.../cell03/halo_exchange_w/
ppermute")``), which is how tests assert the obs scopes survive lowering
without paying for a compile.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

COLLECTIVE_CLASSES = (
    "collective-permute", "all-reduce", "all-gather", "reduce-scatter",
    "all-to-all",
)

# StableHLO op names of the same five classes (the *lowered*, pre-compile
# artifact — what the contract gate in analysis/contracts reads).
STABLEHLO_COLLECTIVES = (
    "stablehlo.collective_permute", "stablehlo.all_reduce",
    "stablehlo.all_gather", "stablehlo.reduce_scatter",
    "stablehlo.all_to_all",
)

_DTYPE_BYTES = {
    "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
    "s8": 1, "u8": 1, "f64": 8, "s64": 8, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1,  # quantized fp8 payloads (quant layer)
}


def _tensor_bytes(shape_str: str) -> int:
    """bytes of one HLO shape literal like 'bf16[2,16,16,8]{...}'."""
    m = re.match(r"(\w+)\[([\d,]*)\]", shape_str)
    if not m:
        return 0
    dt, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


def hlo_collective_stats(hlo_text: str) -> dict:
    """Count collectives + bytes moved per class from compiled HLO text.

    Counts each op once with its OUTPUT shape (for permutes/all-gathers the
    received bytes; start/done pairs are deduplicated by counting only the
    -start form when present)."""
    stats = {k: {"count": 0, "bytes": 0} for k in COLLECTIVE_CLASSES}
    for line in hlo_text.splitlines():
        s = line.strip()
        m = re.match(
            r"(?:ROOT\s+)?\S+\s*=\s*((?:\([^)]*\))|(?:\w+\[[^\]]*\]\S*))\s*"
            r"(collective-permute|all-reduce|all-gather|reduce-scatter|"
            r"all-to-all)(-start|-done)?\(", s)
        if not m:
            continue
        shape_str, kind, phase = m.groups()
        if phase == "-done":
            continue  # counted at -start
        if shape_str.startswith("("):
            # Array entries of the tuple (split(',') would break multi-dim
            # shapes like bf16[2,16,16,8]).
            parts = re.findall(r"\w+\[[\d,]*\]", shape_str)
            if phase == "-start":
                # Async start tuples are (operand, result[, contexts]) —
                # one transfer; count the RESULT so async and sync forms of
                # the same program report identical bytes (all-gather's
                # result carries the group factor, reduce-scatter's the
                # scattered shard — both matching their sync outputs).
                nbytes = (
                    _tensor_bytes(parts[1]) if len(parts) > 1
                    else (_tensor_bytes(parts[0]) if parts else 0)
                )
            else:
                nbytes = sum(_tensor_bytes(t) for t in parts)
        else:
            nbytes = _tensor_bytes(shape_str)
        stats[kind]["count"] += 1
        stats[kind]["bytes"] += nbytes
    stats["total_bytes"] = sum(
        v["bytes"] for k, v in stats.items() if isinstance(v, dict)
    )
    stats["total_count"] = sum(
        v["count"] for k, v in stats.items() if isinstance(v, dict)
    )
    return stats


def compiled_collective_stats(compiled) -> dict:
    """:func:`hlo_collective_stats` of a jax.stages.Compiled."""
    return hlo_collective_stats(compiled.as_text())


def stablehlo_debug_text(lowered) -> str:
    """StableHLO asm WITH debug locations for a jax.stages.Lowered — the
    cheapest artifact in which ``obs.scope`` names are visible (no compile).
    Falls back to the compiled HLO's op_name metadata if the MLIR handle
    does not expose debug printing on this jax version."""
    try:
        mod = lowered.compiler_ir("stablehlo")
        return mod.operation.get_asm(enable_debug_info=True)
    except Exception:  # noqa: BLE001 — jaxlib API drift
        return lowered.compile().as_text()


def scope_names(debug_text: str) -> Dict[str, int]:
    """Histogram of named-scope path components found in a debug-located
    StableHLO / metadata-bearing HLO text.  Component = one level of the
    ``a/b/c`` op-name path, with jit/shard_map framing stripped."""
    out: Dict[str, int] = {}
    for m in re.finditer(r'"((?:jit|shmap)[^"]*)"', debug_text):
        for comp in m.group(1).split("/"):
            if comp.startswith(("jit(", "shmap", "transpose(", "vmap(")):
                continue
            out[comp] = out.get(comp, 0) + 1
    return out


# ---------------------------------------------------------------------------
# Lowered-StableHLO structural extraction (the compiled-artifact contract
# gate's raw material: analysis/contracts reads collectives, scope coverage
# and sharding annotations from a jax.stages.Lowered WITHOUT compiling).
# ---------------------------------------------------------------------------

# Transform wrappers jax threads into the op-name path; unwrapped so the
# forward op and its AD transpose land under the SAME semantic scope.
_WRAPPER_RE = re.compile(
    r"^(?:jvp|vjp|transpose|vmap|pmap|custom_jvp|custom_vjp|checkpoint|"
    r"remat|rematted_computation)\((.*)\)$"
)

# Bare framing components jax control-flow/remat lowering inserts into the
# path; dropped so scope keys stay the ``obs.scope`` vocabulary (a remat
# policy change moves collectives BETWEEN these frames without changing the
# semantic region they belong to).
_FRAMING_COMPONENTS = re.compile(
    r"^(?:checkpoint|rematted_computation|remat|while|body|cond|"
    r"branch_\d+(?:_fun)?|None)$"
)

_MLIR_TENSOR_RE = re.compile(
    # element type may carry uppercase (f8E4M3FN — the quant layer's fp8)
    r"tensor<(?:([0-9x]+)x)?([a-z][a-zA-Z0-9]+)>"
)

_MLIR_DTYPE_BYTES = {
    "f32": 4, "bf16": 2, "f16": 2, "f64": 8, "i1": 1, "i8": 1, "ui8": 1,
    "i16": 2, "ui16": 2, "i32": 4, "ui32": 4, "i64": 8, "ui64": 8,
    "f8E4M3FN": 1, "f8E5M2": 1,  # quantized fp8 payloads (quant layer)
}


def clean_scope_component(comp: str) -> Optional[str]:
    """One op-name path component reduced to its semantic scope name:
    ``jvp(sp_level0)`` -> ``sp_level0``; jit/shmap framing -> None."""
    while True:
        m = _WRAPPER_RE.match(comp)
        if m is None:
            break
        comp = m.group(1)
    if not comp or comp.startswith(("jit(", "shmap", "pjit(")):
        return None
    if _FRAMING_COMPONENTS.match(comp):
        return None
    return comp


def clean_scope_path(op_name_path: str) -> str:
    """Scope key for one op-name path: wrapper/framing components cleaned,
    the trailing primitive name dropped (it is the op, not a scope) —
    ``jit(step)/jit(main)/jit(shmap_body)/jvp(sp_level0)/cell00/
    halo_exchange_spw/ppermute`` -> ``sp_level0/cell00/halo_exchange_spw``."""
    comps = [clean_scope_component(c) for c in op_name_path.split("/")[:-1]]
    return "/".join(c for c in comps if c)


def _mlir_type_bytes(type_str: str) -> int:
    """Total payload bytes of an MLIR type string; tuples sum members."""
    total = 0
    for dims, dt in _MLIR_TENSOR_RE.findall(type_str):
        n = 1
        for d in dims.split("x"):
            if d:
                n *= int(d)
        total += n * _MLIR_DTYPE_BYTES.get(dt, 4)
    return total


def _named_loc_path(loc_str: str) -> Optional[str]:
    """The op-name path inside an MLIR location string, if any:
    ``loc("jit(step)/.../ppermute"(callsite(...)))`` -> the quoted path."""
    m = re.search(r'"((?:jit|shmap|pjit)[^"]*)"', loc_str)
    return m.group(1) if m else None


def _walk_mlir_ops(op):
    yield op
    for region in op.regions:
        for block in region:
            for inner in block:
                yield from _walk_mlir_ops(inner)


def stablehlo_collectives(lowered) -> List[dict]:
    """Every collective op in a Lowered's StableHLO module, as
    ``{"kind", "scope", "bytes"}`` dicts — kind is the bare StableHLO op name
    (``all_reduce``...), scope the :func:`clean_scope_path` of its location,
    bytes the op's total result payload.  Walks the MLIR module directly (no
    text round-trip, no compile)."""
    mod = lowered.compiler_ir("stablehlo")
    out: List[dict] = []
    for func in mod.body:
        for op in _walk_mlir_ops(func):
            name = op.operation.name if hasattr(op, "operation") else op.name
            if name not in STABLEHLO_COLLECTIVES:
                continue
            path = _named_loc_path(str(op.location))
            nbytes = sum(_mlir_type_bytes(str(r.type)) for r in op.results)
            out.append({
                "kind": name.split(".", 1)[1],
                "scope": clean_scope_path(path) if path else "",
                "bytes": nbytes,
            })
    return out


def stablehlo_sharding_annotations(lowered) -> Dict[str, int]:
    """Histogram of GSPMD sharding annotations (``mhlo.sharding`` on
    ``Sharding``/``SPMDFullToShardShape``/``SPMDShardToFullShape`` custom
    calls) in a Lowered's StableHLO — the pre-partitioning record of every
    sharding constraint and shard_map boundary.  A junction that starts
    resharding differently shows up here before any benchmark regresses."""
    mod = lowered.compiler_ir("stablehlo")
    out: Dict[str, int] = {}
    for func in mod.body:
        for op in _walk_mlir_ops(func):
            name = op.operation.name if hasattr(op, "operation") else op.name
            if name != "stablehlo.custom_call":
                continue
            attrs = op.attributes
            try:
                target = str(attrs["call_target_name"]).strip('"')
            except KeyError:
                continue
            if target not in (
                "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
            ):
                continue
            try:
                sharding = str(attrs["mhlo.sharding"]).strip('"')
            except KeyError:
                sharding = "<unannotated>"
            key = f"{target}:{sharding}"
            out[key] = out.get(key, 0) + 1
    return out


def scope_coverage(lowered) -> List[str]:
    """Sorted set of semantic scope names reachable in a Lowered's StableHLO
    locations — the contract gate's drift check for *instrumentation* (an
    ``obs.scope`` that stops covering its region disappears from here)."""
    mod = lowered.compiler_ir("stablehlo")
    names = set()
    for func in mod.body:
        for op in _walk_mlir_ops(func):
            path = _named_loc_path(str(op.location))
            if not path:
                continue
            for comp in path.split("/")[:-1]:
                cleaned = clean_scope_component(comp)
                if cleaned:
                    names.add(cleaned)
    return sorted(names)
