"""Collective accounting from compiled HLO — the reusable library form of
``benchmarks/communication/comm_volume_report.py`` (which now imports from
here).

Any jitted step can report, at runtime and on any host, how many collectives
XLA actually scheduled per step and the bytes each class moves — the
compiler-derived counterpart of the reference's MPI message accounting
(SURVEY §2a): collective-permute (halo exchange, pipeline handoffs, GEMS
mirror), all-reduce (DP gradients, cross-tile BN), all-gather /
reduce-scatter / all-to-all (junctions, GSPMD resharding).

Also home to :func:`stablehlo_debug_text`, the scope-name view of a lowered
(not yet compiled) program: StableHLO printed with debug locations carries
the ``jax.named_scope`` stack (``loc("jit(step)/.../cell03/halo_exchange_w/
ppermute")``), which is how tests assert the obs scopes survive lowering
without paying for a compile.
"""

from __future__ import annotations

import re
from typing import Dict

COLLECTIVE_CLASSES = (
    "collective-permute", "all-reduce", "all-gather", "reduce-scatter",
    "all-to-all",
)

_DTYPE_BYTES = {
    "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
    "s8": 1, "u8": 1, "f64": 8, "s64": 8, "u64": 8,
}


def _tensor_bytes(shape_str: str) -> int:
    """bytes of one HLO shape literal like 'bf16[2,16,16,8]{...}'."""
    m = re.match(r"(\w+)\[([\d,]*)\]", shape_str)
    if not m:
        return 0
    dt, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dt, 4)


def hlo_collective_stats(hlo_text: str) -> dict:
    """Count collectives + bytes moved per class from compiled HLO text.

    Counts each op once with its OUTPUT shape (for permutes/all-gathers the
    received bytes; start/done pairs are deduplicated by counting only the
    -start form when present)."""
    stats = {k: {"count": 0, "bytes": 0} for k in COLLECTIVE_CLASSES}
    for line in hlo_text.splitlines():
        s = line.strip()
        m = re.match(
            r"(?:ROOT\s+)?\S+\s*=\s*((?:\([^)]*\))|(?:\w+\[[^\]]*\]\S*))\s*"
            r"(collective-permute|all-reduce|all-gather|reduce-scatter|"
            r"all-to-all)(-start|-done)?\(", s)
        if not m:
            continue
        shape_str, kind, phase = m.groups()
        if phase == "-done":
            continue  # counted at -start
        if shape_str.startswith("("):
            # Array entries of the tuple (split(',') would break multi-dim
            # shapes like bf16[2,16,16,8]).
            parts = re.findall(r"\w+\[[\d,]*\]", shape_str)
            if phase == "-start":
                # Async start tuples are (operand, result[, contexts]) —
                # one transfer; count the RESULT so async and sync forms of
                # the same program report identical bytes (all-gather's
                # result carries the group factor, reduce-scatter's the
                # scattered shard — both matching their sync outputs).
                nbytes = (
                    _tensor_bytes(parts[1]) if len(parts) > 1
                    else (_tensor_bytes(parts[0]) if parts else 0)
                )
            else:
                nbytes = sum(_tensor_bytes(t) for t in parts)
        else:
            nbytes = _tensor_bytes(shape_str)
        stats[kind]["count"] += 1
        stats[kind]["bytes"] += nbytes
    stats["total_bytes"] = sum(
        v["bytes"] for k, v in stats.items() if isinstance(v, dict)
    )
    stats["total_count"] = sum(
        v["count"] for k, v in stats.items() if isinstance(v, dict)
    )
    return stats


def compiled_collective_stats(compiled) -> dict:
    """:func:`hlo_collective_stats` of a jax.stages.Compiled."""
    return hlo_collective_stats(compiled.as_text())


def stablehlo_debug_text(lowered) -> str:
    """StableHLO asm WITH debug locations for a jax.stages.Lowered — the
    cheapest artifact in which ``obs.scope`` names are visible (no compile).
    Falls back to the compiled HLO's op_name metadata if the MLIR handle
    does not expose debug printing on this jax version."""
    try:
        mod = lowered.compiler_ir("stablehlo")
        return mod.operation.get_asm(enable_debug_info=True)
    except Exception:  # noqa: BLE001 — jaxlib API drift
        return lowered.compile().as_text()


def scope_names(debug_text: str) -> Dict[str, int]:
    """Histogram of named-scope path components found in a debug-located
    StableHLO / metadata-bearing HLO text.  Component = one level of the
    ``a/b/c`` op-name path, with jit/shard_map framing stripped."""
    out: Dict[str, int] = {}
    for m in re.finditer(r'"((?:jit|shmap)[^"]*)"', debug_text):
        for comp in m.group(1).split("/"):
            if comp.startswith(("jit(", "shmap", "transpose(", "vmap(")):
                continue
            out[comp] = out.get(comp, 0) + 1
    return out
