"""Semantic trace scopes: the naming layer of the telemetry subsystem.

The reference instruments phases with CUDA events around named code regions
(``benchmark_resnet_gems_master_with_sp.py:417-440``); on TPU the analog is
the XLA op-name stack: :func:`scope` pushes a name onto ``jax.named_scope``
so every op traced inside carries it — in XProf traces (``--profile-dir``),
in compiled-HLO ``op_name`` metadata, and in StableHLO debug locations.
Threaded through the hot paths (cells, halo exchange, D2 runs, ring steps,
pipeline stages), a trace reads ``stage1/cell03/halo_exchange_w/...`` instead
of anonymous fusions — the per-phase attribution T3-style overlap work needs
(PAPERS.md, arXiv:2401.16677).

Scopes are trace-time only (zero steady-state runtime cost: the context
manager runs while JAX builds the jaxpr, never per step on device) and can be
disabled outright with ``MPI4DL_NO_SCOPES=1`` for pristine A/B compiles.

:func:`step_annotation` is the host-side counterpart: a
``jax.profiler.StepTraceAnnotation`` marking one optimizer step so XProf's
step view can attribute device time to steps.  Benchmark loops use it only
while a profiler trace is active (it costs a TraceMe per step).
"""

from __future__ import annotations

import contextlib
import os
from typing import ContextManager, Optional

_ENABLED: Optional[bool] = None


def scopes_enabled() -> bool:
    """Cached check of the ``MPI4DL_NO_SCOPES`` hatch (config.HATCHES)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("MPI4DL_NO_SCOPES", "0") != "1"
    return _ENABLED


def _reset_enabled_cache() -> None:
    """Test hook: re-read MPI4DL_NO_SCOPES on the next scopes_enabled()."""
    global _ENABLED
    _ENABLED = None


def scope(name: str) -> ContextManager[None]:
    """Named trace scope for ops created inside the ``with`` block.

    Inside jit/shard_map tracing this is ``jax.named_scope``; disabled it is
    a nullcontext (zero cost, zero graph difference)."""
    if not scopes_enabled():
        return contextlib.nullcontext()
    import jax

    return jax.named_scope(name)


def step_annotation(step_num: int, name: str = "train") -> ContextManager[None]:
    """Host-side step marker for XProf's step view (wrap ONE step dispatch).

    Only meaningful while a profiler trace is active; disabled along with
    scopes."""
    if not scopes_enabled():
        return contextlib.nullcontext()
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=step_num)
