"""Flight recorder: bounded in-memory forensics for training legs (ISSUE 17).

The RunLog is the durable record; the flight recorder is the *crash-scoped*
one — a ring buffer of the last N step records (per-device memory
watermarks, jit-cache probe) plus the last checkpoint / anomaly /
quarantine / preempt events, held in memory at ~zero per-step cost and
dumped as a typed ``flight.json`` artifact exactly when a leg goes down:
anomaly, watchdog escalation, preemption, and crash-marker writes.  The
elastic supervisor then reads the dump as a fourth evidence source next to
the crash marker, RunLog tail, and exit status
(:func:`mpi4dl_tpu.resilience.classify_failure`): the recorder's ``phase``
disambiguates a hang-in-collective from a data stall from a
checkpoint-gather stall, and the ring's watermark trajectory localizes an
``oom_step`` to the device whose high-water mark was growing.

Every supervised leg runs one by default (``MPI4DL_NO_FLIGHT=1`` disables;
``MPI4DL_FLIGHT_STEPS`` sizes the ring).  The dump lands next to the crash
marker when ``MPI4DL_CRASH_MARKER`` is set (so the supervisor's per-attempt
directory picks it up) and next to the RunLog otherwise.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from mpi4dl_tpu.obs.runlog import (
    _jsonable,
    device_memory_watermarks,
    host_rss_peak_bytes,
    jit_cache_size,
)

FLIGHT_SCHEMA = 1
FLIGHT_BASENAME = "flight.json"
DEFAULT_FLIGHT_STEPS = 64


def flight_steps_from_env() -> int:
    """Ring capacity from ``MPI4DL_FLIGHT_STEPS`` (default 64)."""
    raw = os.environ.get("MPI4DL_FLIGHT_STEPS")
    try:
        n = int(raw) if raw else DEFAULT_FLIGHT_STEPS
    except ValueError:
        n = DEFAULT_FLIGHT_STEPS
    return max(1, n)


def default_flight_path() -> Optional[str]:
    """Where a dump lands with no explicit path: next to the crash marker
    (the supervisor's per-attempt directory) when that hatch is set."""
    marker = os.environ.get("MPI4DL_CRASH_MARKER")
    if marker:
        return os.path.join(os.path.dirname(os.path.abspath(marker)),
                            FLIGHT_BASENAME)
    return None


def read_flight(path: str) -> Optional[Dict[str, Any]]:
    """Parse a ``flight.json`` dump; None on missing/torn/invalid files (a
    crashed leg may die mid-write — evidence readers must not)."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


class FlightRecorder:
    """Bounded ring of recent step/event records + last-event index."""

    def __init__(self, capacity: int = DEFAULT_FLIGHT_STEPS,
                 path: Optional[str] = None):
        self.capacity = max(1, int(capacity))
        self.path = path
        self.steps_seen = 0
        self.phase: Optional[str] = None
        self.gstep = -1
        # The watchdog monitor thread reads tail()/snapshot() while the
        # training thread notes records.
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._last_events: Dict[str, Dict[str, Any]] = {}
        self._dumps: List[str] = []

    @classmethod
    def from_env(cls, path: Optional[str] = None) -> Optional["FlightRecorder"]:
        """The default-on constructor: None when ``MPI4DL_NO_FLIGHT=1``."""
        if os.environ.get("MPI4DL_NO_FLIGHT") == "1":
            return None
        return cls(capacity=flight_steps_from_env(),
                   path=path or default_flight_path())

    # -- recording ---------------------------------------------------------

    def set_phase(self, phase: str, gstep: Optional[int] = None) -> None:
        with self._lock:
            self.phase = phase
            if gstep is not None:
                self.gstep = int(gstep)

    def note(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """One ring entry; non-step kinds also update the last-event index
        (checkpoint / anomaly / quarantine / preempt / ...)."""
        rec = {"kind": kind, "t": time.time()}
        rec.update({k: _jsonable(v) for k, v in fields.items()})
        with self._lock:
            self._ring.append(rec)
            if kind != "step":
                self._last_events[kind] = rec
        return rec

    def note_step(self, *, gstep: int, phase: str = "step", step_fn=None,
                  **fields: Any) -> Dict[str, Any]:
        """One completed step: per-device memory watermarks + retrace probe."""
        wm = device_memory_watermarks()
        rec = self.note(
            "step",
            gstep=int(gstep),
            memory_peak_bytes=None if wm is None else wm["max"],
            memory_peak_bytes_min=None if wm is None else wm["min"],
            hbm_skew=None if wm is None else wm["hbm_skew"],
            per_device_peak_bytes=None if wm is None else wm["per_device"],
            host_rss_peak_bytes=host_rss_peak_bytes(),
            jit_cache_size=(jit_cache_size(step_fn)
                            if step_fn is not None else None),
            **fields,
        )
        with self._lock:
            self.steps_seen += 1
            self.gstep = int(gstep)
            self.phase = phase
        return rec

    # -- reading -----------------------------------------------------------

    def tail(self, n: int = 5) -> List[Dict[str, Any]]:
        """The last ``n`` ring entries, oldest first (the watchdog appends
        these to its stall dump)."""
        with self._lock:
            return list(self._ring)[-max(0, int(n)):]

    def snapshot(self, reason: Optional[str] = None,
                 phase: Optional[str] = None,
                 gstep: Optional[int] = None) -> Dict[str, Any]:
        """The typed dump payload (``flight.json`` schema)."""
        with self._lock:
            snap: Dict[str, Any] = {
                "schema": FLIGHT_SCHEMA,
                "t": time.time(),
                "reason": reason,
                "phase": phase if phase is not None else self.phase,
                "gstep": int(gstep) if gstep is not None else self.gstep,
                "capacity": self.capacity,
                "steps_seen": self.steps_seen,
                "ring": list(self._ring),
                "last_events": dict(self._last_events),
                "dumps": list(self._dumps),
            }
        snap["device_memory"] = device_memory_watermarks()
        snap["host_rss_peak_bytes"] = host_rss_peak_bytes()
        return snap

    # -- dumping -----------------------------------------------------------

    def dump(self, reason: str, *, phase: Optional[str] = None,
             gstep: Optional[int] = None,
             path: Optional[str] = None) -> Optional[str]:
        """Atomically write ``flight.json``; never raises (forensics must not
        mask the original failure).  Returns the path written, or None when
        no destination resolves / the write fails."""
        dest = path or self.path or default_flight_path()
        if not dest:
            return None
        try:
            snap = self.snapshot(reason, phase=phase, gstep=gstep)
            with self._lock:
                self._dumps.append(reason)
            snap["dumps"] = list(self._dumps)
            os.makedirs(os.path.dirname(os.path.abspath(dest)) or ".",
                        exist_ok=True)
            tmp = dest + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(snap, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, dest)
            return dest
        except Exception:  # noqa: BLE001
            return None  # deliberate: a failed dump must not kill the leg


def flight_summary(flight: Optional[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The compact evidence block the supervisor attaches to incidents."""
    if not flight or not isinstance(flight, dict):
        return None
    out: Dict[str, Any] = {
        "reason": flight.get("reason"),
        "phase": flight.get("phase"),
        "gstep": flight.get("gstep"),
        "steps_seen": flight.get("steps_seen"),
    }
    growth = watermark_growth(flight)
    if growth is not None:
        out["watermark_growth_bytes"] = growth[0]
        if growth[1] is not None:
            out["watermark_growth_device"] = growth[1]
    return out


def watermark_growth(flight: Dict[str, Any]):
    """(total growth bytes, fastest-growing device index) over the dump's
    ring of step records; None when the ring carries no watermarks (CPU
    backends report no allocator stats)."""
    steps = [r for r in flight.get("ring", ())
             if isinstance(r, dict) and r.get("kind") == "step"]
    marks = [r["memory_peak_bytes"] for r in steps
             if isinstance(r.get("memory_peak_bytes"), int)]
    if len(marks) < 2:
        return None
    total = marks[-1] - marks[0]
    per_dev_first = steps[0].get("per_device_peak_bytes")
    per_dev_last = steps[-1].get("per_device_peak_bytes")
    device = None
    if (isinstance(per_dev_first, list) and isinstance(per_dev_last, list)
            and len(per_dev_first) == len(per_dev_last) and per_dev_first):
        deltas = [b - a for a, b in zip(per_dev_first, per_dev_last)]
        best = max(deltas)
        if best > 0:
            device = deltas.index(best)
    return total, device
