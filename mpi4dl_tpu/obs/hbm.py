"""Per-scope HBM attribution from the compiled HLO module.

``compiled.memory_analysis()`` reports *totals* (temp/argument/output bytes);
this module answers the question those totals cannot: **which ``obs.scope``
owns the bytes**.  PR 5 measured that the 8K flagship grows ~19.5 GB/device
per micro-batch part in the spatial phase + junction — a number read off
aggregate counters.  Here the compiled module itself is the ledger:

1. The compiled HLO is **scheduled** (``is_scheduled=true``): instruction
   order per computation is execution order, so classic interval liveness
   over instruction indices reconstructs the peak live set analytically.
2. Every instruction carries ``metadata={op_name="jit(step)/.../sp_region/
   sp_level0/cell03/conv"}`` — the ``obs.scope`` stack — so each live buffer
   maps to a semantic scope via the same :func:`clean_scope_path` the
   contract gate uses.
3. Entry parameters carry their argument names (``state.param_buf``, ``x``),
   so the argument portion of peak memory is attributed by name too.

The model (documented limits, tested tolerances in tests/test_hbm.py):

- view-like ops (``get-tuple-element``/``bitcast``/``tuple``/``*-done``)
  allocate nothing and forward liveness to their operands;
- call-like ops (``while``/``conditional``/``call``/reducers) contribute the
  callee's own internal peak at the call point, with callee parameters
  excluded (they alias caller operands) and operands dying into the call
  subtracted (they alias callee parameters / the while carry);
- fusion bodies allocate nothing (one output buffer, owned by the caller op).

Against XLA's real buffer assignment this over-estimates (no buffer reuse
across disjoint-lifetime same-shape values, while carries double-buffered at
the boundary) — but the *attribution shares* are what the memory campaigns
need, and the absolute estimate reconciles with ``memory_analysis()`` within
the tested tolerance on the engine families.

Surfaces: ``benchmarks/mem_probe.py --attribute`` (per-rung breakdown +
coverage gates), the ``hbm`` RunLog record (rendered by ``obs report``), and
:func:`compare_breakdowns` for A/B config deltas.  obs/timeline.py reuses
:func:`parse_hlo_module` for its per-scope FLOP/collective estimates.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from mpi4dl_tpu.obs.hlo_stats import clean_scope_path

ARGS_SCOPE = "(args)"
UNATTRIBUTED = "(unattributed)"

# Ops whose result is a view of an operand (no allocation; liveness forwards
# to the underlying buffer).  ``*-done`` async halves alias their start tuple.
_VIEW_OPS = ("get-tuple-element", "bitcast", "tuple", "parameter")

# Call-like ops that execute a non-fusion sub-computation whose internal
# temps are live while the op runs.
_CALL_ATTRS = ("body", "condition", "to_apply", "branch_computations",
               "called_computations")

_SHAPE_TOKEN = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_OP_NAME = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
_COMP_REF = re.compile(
    r"(?:body|condition|to_apply|calls)=(%[\w.\-]+)"
    r"|(?:branch_computations|called_computations)=\{([^}]*)\}"
)
_DTYPE_BYTES = {
    "f32": 4, "bf16": 2, "f16": 2, "f64": 8, "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}


def shape_bytes(shape_str: str) -> int:
    """Total payload bytes of an HLO shape literal (tuples sum members):
    ``'(f32[65536]{0}, bf16[2,8,8,4])'`` -> 262144 + 1024."""
    total = 0
    for dt, dims in _SHAPE_TOKEN.findall(shape_str):
        if dt == "token":
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


@dataclasses.dataclass
class Instr:
    """One parsed HLO instruction (scheduled position = list index)."""
    name: str
    shape: str
    opcode: str
    bytes: int
    operands: Tuple[str, ...]
    callees: Tuple[str, ...]
    op_name: str  # raw metadata op_name ("" when absent)
    scope: str    # clean_scope_path(op_name)
    raw: str = ""  # the full instruction line (attribute strings the fields
    #              above do not keep: window/dim_labels/contracting dims —
    #              obs/timeline.py's FLOP model reads them from here)

    @property
    def is_view(self) -> bool:
        return self.opcode in _VIEW_OPS or self.opcode.endswith("-done")


def _balanced(text: str, start: int) -> int:
    """Index one past the ')' matching the '(' at ``start``."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "(":
            depth += 1
        elif text[i] == ")":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(text)


_INSTR_HEAD = re.compile(r"^\s*(?:ROOT\s+)?(%[\w.\-]+)\s*=\s*")


def _parse_instruction(line: str) -> Optional[Instr]:
    m = _INSTR_HEAD.match(line)
    if m is None:
        return None
    name = m.group(1)
    rest = line[m.end():]
    # Defined shape: a parenthesized tuple or one token (layout included).
    if rest.startswith("("):
        end = _balanced(rest, 0)
    else:
        end = rest.find(" ")
        if end < 0:
            return None
    shape = rest[:end]
    rest = rest[end:].lstrip()
    om = re.match(r"([\w\-]+)\(", rest)
    if om is None:
        return None
    opcode = om.group(1)
    op_end = _balanced(rest, om.end() - 1)
    operand_str = rest[om.end():op_end - 1]
    attrs = rest[op_end:]

    callees: List[str] = []
    for single, multi in _COMP_REF.findall(attrs):
        if single:
            callees.append(single)
        else:
            callees.extend(t.strip() for t in multi.split(",") if t.strip())
    operands = tuple(re.findall(r"(%[\w.\-]+)", operand_str))
    op_name = ""
    mm = _OP_NAME.search(attrs) or _OP_NAME.search(line)
    if mm:
        op_name = mm.group(1)
    return Instr(
        name=name, shape=shape, opcode=opcode, bytes=shape_bytes(shape),
        operands=operands, callees=tuple(callees), op_name=op_name,
        scope=clean_scope_path(op_name) if "/" in op_name else "",
        raw=line,
    )


def parse_hlo_module(hlo_text: str) -> Tuple[Dict[str, List[Instr]], str]:
    """``(computations, entry_name)`` for a compiled HLO module's text.
    Computation keys keep their ``%`` sigil; instruction order is the
    module's schedule order (``is_scheduled=true``)."""
    comps: Dict[str, List[Instr]] = {}
    entry = ""
    cur: Optional[List[Instr]] = None
    for line in hlo_text.splitlines():
        if not line:
            continue
        if not line.startswith(" "):
            m = re.match(r"(ENTRY\s+)?(%[\w.\-]+)\s*\(.*\{\s*$", line)
            if m:
                cur = comps.setdefault(m.group(2), [])
                if m.group(1):
                    entry = m.group(2)
                continue
            if line.startswith("}"):
                cur = None
            continue
        if cur is None:
            continue
        ins = _parse_instruction(line)
        if ins is not None:
            cur.append(ins)
    return comps, entry


# ---------------------------------------------------------------------------
# Liveness simulation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LiveBuffer:
    name: str
    bytes: int
    shape: str
    scope: str
    category: str  # "temp" | "argument" | "constant"
    op_name: str


class _ModulePeak:
    """Per-computation analytical peak with memoization over the call graph."""

    def __init__(self, comps: Dict[str, List[Instr]]):
        self.comps = comps
        self._cache: Dict[str, Tuple[int, List[LiveBuffer]]] = {}
        self._scope_cache: Dict[str, str] = {}

    def scope_of(self, ins: Instr) -> str:
        """The instruction's scope; ``while``/``conditional`` ops lowered
        without their own op_name metadata (jax emits none for the loop op
        itself) inherit the longest common scope prefix of their callee
        bodies — the scan *carry* is thereby attributed to the scan's scope
        (``gpipe_scan``, ``tail_scan``…), which is exactly the O(parts)
        state the memory campaigns chase."""
        if ins.scope or not ins.callees or ins.opcode == "fusion":
            return ins.scope
        if ins.name in self._scope_cache:
            return self._scope_cache[ins.name]
        self._scope_cache[ins.name] = ""  # cycle guard
        paths = []
        for callee in ins.callees:
            for sub in self.comps.get(callee, ()):
                s = sub.scope or self.scope_of(sub)
                if s:
                    paths.append(s.split("/"))
        scope = ""
        if paths:
            lcp: List[str] = []
            for comps_at in zip(*paths):
                if all(c == comps_at[0] for c in comps_at):
                    lcp.append(comps_at[0])
                else:
                    break
            if not lcp:
                # Mixed bodies: fall back to the dominant first component.
                heads: Dict[str, int] = {}
                for p in paths:
                    heads[p[0]] = heads.get(p[0], 0) + 1
                lcp = [max(heads, key=lambda h: heads[h])]
            scope = "/".join(lcp)
        self._scope_cache[ins.name] = scope
        return scope

    def peak(self, comp: str, entry: bool = False
             ) -> Tuple[int, List[LiveBuffer]]:
        key = comp + ("#entry" if entry else "")
        if key in self._cache:
            return self._cache[key]
        # Break cycles defensively (real HLO call graphs are acyclic).
        self._cache[key] = (0, [])
        result = self._peak_uncached(comp, entry)
        self._cache[key] = result
        return result

    def _callee_peak(self, ins: Instr) -> Tuple[int, List[LiveBuffer]]:
        if ins.opcode == "fusion" or not ins.callees:
            return 0, []
        best: Tuple[int, List[LiveBuffer]] = (0, [])
        for callee in ins.callees:
            if callee in self.comps:
                p = self.peak(callee)
                if p[0] > best[0]:
                    best = p
        return best

    def _peak_uncached(self, comp: str, entry: bool
                       ) -> Tuple[int, List[LiveBuffer]]:
        instrs = self.comps.get(comp, [])
        by_name = {i.name: i for i in instrs}
        index = {i.name: k for k, i in enumerate(instrs)}

        def underlying(name: str, seen=None) -> List[str]:
            """Real (allocating) buffers a value aliases, through views."""
            ins = by_name.get(name)
            if ins is None:
                return []
            if not ins.is_view:
                return [name]
            if seen is None:
                seen = set()
            if name in seen:
                return []
            seen.add(name)
            out: List[str] = []
            for op in ins.operands:
                out.extend(underlying(op, seen))
            return out

        # Live intervals for allocating instructions.  Parameters allocate
        # only at the entry (category "argument", pinned live throughout);
        # in callees they alias caller operands.  ``last_direct_use`` tracks
        # every name (views included) for the dies-into scope fallback.
        last_use: Dict[str, int] = {}
        last_direct_use: Dict[str, int] = {}
        for k, ins in enumerate(instrs):
            for op in ins.operands:
                last_direct_use[op] = k
                for real in underlying(op):
                    last_use[real] = k

        def dying_scope(name: str, seen=None) -> str:
            """Scope of the instruction a scope-less value dies into,
            transitively through views and other scope-less consumers.
            XLA-synthesized values (hoisted zero inits, mirror-param copies,
            metadata-stripped constants) carry no op_name at all — but they
            flow somewhere scoped (the scan while, a stage conditional), and
            "the phase that consumes it" is the attribution the memory
            campaigns need."""
            if seen is None:
                seen = set()
            if name in seen:
                return ""
            seen.add(name)
            k = last_direct_use.get(name)
            if k is None:
                return ""
            consumer = instrs[k]
            s = self.scope_of(consumer)
            if s:
                return s
            return dying_scope(consumer.name, seen)

        def buf_of(ins: Instr, category: str) -> LiveBuffer:
            if category == "argument":
                label = ins.op_name or ins.name.lstrip("%")
                return LiveBuffer(
                    name=ins.name, bytes=ins.bytes, shape=ins.shape,
                    scope=f"{ARGS_SCOPE} {label}", category=category,
                    op_name=ins.op_name,
                )
            return LiveBuffer(
                name=ins.name, bytes=ins.bytes, shape=ins.shape,
                scope=self.scope_of(ins) or dying_scope(ins.name),
                category=category, op_name=ins.op_name,
            )

        def while_carry_bufs(ins: Instr) -> Optional[List[LiveBuffer]]:
            """A ``while`` carry decomposed per element, each attributed to
            the scope that PRODUCED its initial value.  The scan-carried
            junction activations of the SPxPP engines thereby attribute to
            ``junction_gather``/``stage_lineup`` — the phase that owns those
            bytes — instead of lumping into the scan's own scope."""
            if ins.opcode != "while" or len(ins.operands) != 1:
                return None
            init = by_name.get(ins.operands[0])
            if init is None or init.opcode != "tuple":
                return None
            elem_shapes = re.findall(r"\w+\[[0-9,]*\](?:\{[0-9,]*\})?",
                                     ins.shape)
            if len(elem_shapes) != len(init.operands):
                return None
            fallback = self.scope_of(ins)
            out = []
            for shp, opnd in zip(elem_shapes, init.operands):
                reals = underlying(opnd)
                scope = ""
                for r in reals:
                    scope = self.scope_of(by_name[r])
                    if scope:
                        break
                out.append(LiveBuffer(
                    name=f"{ins.name}:{opnd}", bytes=shape_bytes(shp),
                    shape=shp, scope=scope or fallback, category="temp",
                    op_name=ins.op_name,
                ))
            return out

        allocs: Dict[str, Tuple[int, str]] = {}  # name -> (def idx, category)
        arg_bufs: List[LiveBuffer] = []
        for k, ins in enumerate(instrs):
            if ins.opcode == "parameter":
                if entry:
                    arg_bufs.append(buf_of(ins, "argument"))
                continue
            if ins.is_view or ins.bytes == 0:
                continue
            cat = "constant" if ins.opcode == "constant" else "temp"
            allocs[ins.name] = (k, cat)

        arg_total = sum(b.bytes for b in arg_bufs)
        best_bytes, best_at = -1, -1
        best_callee: List[LiveBuffer] = []
        live_now = 0
        # Sweep program points; maintain the running live-byte sum
        # incrementally (O(n + uses)) instead of resumming per point.
        starts: Dict[int, List[str]] = {}
        ends: Dict[int, List[str]] = {}
        for name, (d, _) in allocs.items():
            starts.setdefault(d, []).append(name)
            ends.setdefault(max(last_use.get(name, d), d), []).append(name)
        for k, ins in enumerate(instrs):
            for name in starts.get(k, ()):
                live_now += by_name[name].bytes
            point = live_now
            callee_bytes, callee_set = self._callee_peak(ins)
            if callee_bytes:
                point += callee_bytes
                # Operands dying into the call alias callee parameters /
                # the while carry — don't count them twice.
                dying = set()
                for op in ins.operands:
                    for real in underlying(op):
                        if real in allocs and last_use.get(real) == k:
                            dying.add(real)
                point -= sum(by_name[r].bytes for r in dying)
            else:
                dying = set()
            if point > best_bytes:
                best_bytes, best_at = point, k
                best_callee = callee_set
                best_dying = dying
            for name in ends.get(k, ()):
                live_now -= by_name[name].bytes
        if best_at < 0:  # empty computation
            return arg_total, list(arg_bufs)

        live_set: List[LiveBuffer] = list(arg_bufs)
        for name, (d, cat) in allocs.items():
            if name in best_dying and self._callee_peak(instrs[best_at])[0]:
                continue
            if d <= best_at <= max(last_use.get(name, d), d):
                ins = by_name[name]
                carry = while_carry_bufs(ins)
                if carry is not None:
                    live_set.extend(carry)
                else:
                    live_set.append(buf_of(ins, cat))
        # Callee-internal buffers without a scope of their own belong to the
        # call site: rebadge them with the calling instruction's (inherited)
        # scope.  Copies are cheap and keep the per-callee cache intact.
        call_ins = instrs[best_at]
        call_scope = (self.scope_of(call_ins)
                      or dying_scope(call_ins.name)) if best_callee else ""
        for b in best_callee:
            if not b.scope and call_scope:
                b = dataclasses.replace(b, scope=call_scope)
            live_set.append(b)
        return best_bytes + arg_total, live_set


# ---------------------------------------------------------------------------
# Public surface
# ---------------------------------------------------------------------------


def attribute_hlo(hlo_text: str, top: int = 20) -> dict:
    """Per-scope peak-HBM breakdown of one compiled HLO module's text.

    Returns a JSON-ready dict::

        peak_bytes_est      analytical peak (liveness over the schedule)
        by_scope            {scope: bytes at peak} — "(args) <name>" entries
                            for entry arguments, "(unattributed)" for buffers
                            whose metadata carries no obs.scope path
        top_buffers         largest-N live-at-peak buffers (name/shape/
                            scope/category/bytes)
        coverage            attributed bytes / peak bytes  (arguments and
                            scoped temps both count as attributed)
        scoped_temp_coverage  scoped temp bytes / all temp bytes at peak
    """
    comps, entry = parse_hlo_module(hlo_text)
    if not entry:
        raise ValueError("no ENTRY computation found in HLO text")
    peak, live = _ModulePeak(comps).peak(entry, entry=True)

    by_scope: Dict[str, int] = {}
    temp_total = temp_scoped = attributed = 0
    for b in live:
        key = b.scope or UNATTRIBUTED
        by_scope[key] = by_scope.get(key, 0) + b.bytes
        if b.category == "temp":
            temp_total += b.bytes
            if b.scope:
                temp_scoped += b.bytes
        if b.scope:
            attributed += b.bytes
    live_sorted = sorted(live, key=lambda b: -b.bytes)
    return {
        "peak_bytes_est": peak,
        "by_scope": dict(sorted(by_scope.items(), key=lambda kv: -kv[1])),
        "top_buffers": [
            {"name": b.name, "bytes": b.bytes, "shape": b.shape,
             "scope": b.scope or UNATTRIBUTED, "category": b.category}
            for b in live_sorted[:top]
        ],
        "coverage": round(attributed / peak, 4) if peak else 1.0,
        "scoped_temp_coverage": (
            round(temp_scoped / temp_total, 4) if temp_total else 1.0
        ),
        "live_buffers": len(live),
    }


def attribute_compiled(compiled, top: int = 20,
                       hlo_text: Optional[str] = None) -> dict:
    """:func:`attribute_hlo` of a ``jax.stages.Compiled``, reconciled against
    its ``memory_analysis()`` (the ``reconcile`` sub-dict: XLA's own totals
    and the estimate/actual ratio the tests bound).  Pass ``hlo_text`` when
    the caller already has ``compiled.as_text()`` — serializing the module
    is the dominant non-compile cost on flagship-sized programs."""
    out = attribute_hlo(hlo_text if hlo_text is not None
                        else compiled.as_text(), top=top)
    try:
        ma = compiled.memory_analysis()
        actual = (
            int(ma.temp_size_in_bytes) + int(ma.argument_size_in_bytes)
            - int(ma.alias_size_in_bytes)
        )
        out["reconcile"] = {
            "memory_analysis_peak_bytes": actual,
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "ratio_est_over_actual": (
                round(out["peak_bytes_est"] / actual, 3) if actual else None
            ),
        }
    except Exception:  # noqa: BLE001 — backends without memory_analysis
        out["reconcile"] = None
    return out


def top_scope(breakdown: dict, prefixes: Optional[Tuple[str, ...]] = None
              ) -> Optional[str]:
    """The scope owning the most peak bytes (arguments and unattributed
    excluded — the question is which *phase* owns the working set).  With
    ``prefixes``, restricted to scopes starting with one of them."""
    best_key, best_val = None, -1
    for k, v in breakdown.get("by_scope", {}).items():
        if k == UNATTRIBUTED or k.startswith(ARGS_SCOPE):
            continue
        if prefixes and not any(k.startswith(p) for p in prefixes):
            continue
        if v > best_val:
            best_key, best_val = k, v
    return best_key


def scope_group_bytes(breakdown: dict, depth: int = 1) -> Dict[str, int]:
    """``by_scope`` rolled up to the first ``depth`` path components
    (``sp_region/sp_level0/cell03`` -> ``sp_region``) — the phase-level view
    the CI plurality gate reads."""
    out: Dict[str, int] = {}
    for k, v in breakdown.get("by_scope", {}).items():
        if k == UNATTRIBUTED or k.startswith(ARGS_SCOPE):
            key = k
        else:
            key = "/".join(k.split("/")[:depth])
        out[key] = out.get(key, 0) + v
    return dict(sorted(out.items(), key=lambda kv: -kv[1]))


def compare_breakdowns(a: dict, b: dict) -> dict:
    """A/B delta of two breakdowns: per-scope byte deltas (B minus A),
    sorted by absolute delta, plus the peak delta."""
    sa, sb = a.get("by_scope", {}), b.get("by_scope", {})
    deltas = {
        k: sb.get(k, 0) - sa.get(k, 0)
        for k in set(sa) | set(sb)
        if sb.get(k, 0) != sa.get(k, 0)
    }
    return {
        "peak_delta_bytes": a and b and (
            b.get("peak_bytes_est", 0) - a.get("peak_bytes_est", 0)
        ),
        "by_scope_delta": dict(
            sorted(deltas.items(), key=lambda kv: -abs(kv[1]))
        ),
    }


def _gb(n: int) -> str:
    if abs(n) >= 2**30:
        return f"{n / 2**30:.2f} GB"
    if abs(n) >= 2**20:
        return f"{n / 2**20:.1f} MB"
    return f"{n / 2**10:.1f} KB"


def format_breakdown(breakdown: dict, top: int = 12) -> str:
    """Human-readable table of one breakdown (the mem_probe --attribute and
    ``obs report`` rendering)."""
    peak = breakdown["peak_bytes_est"]
    lines = [
        f"peak (analytical liveness over the schedule): {_gb(peak)}  "
        f"coverage {breakdown['coverage']:.1%} "
        f"(scoped temps {breakdown['scoped_temp_coverage']:.1%})"
    ]
    rec = breakdown.get("reconcile")
    if rec:
        lines.append(
            f"memory_analysis peak: {_gb(rec['memory_analysis_peak_bytes'])} "
            f"(est/actual {rec['ratio_est_over_actual']})"
        )
    lines.append("per-scope peak bytes:")
    for k, v in list(breakdown["by_scope"].items())[:top]:
        lines.append(f"  {_gb(v):>10}  {100 * v / peak:5.1f}%  {k}")
    lines.append("largest live buffers at peak:")
    for b in breakdown["top_buffers"][:top]:
        lines.append(
            f"  {_gb(b['bytes']):>10}  {b['category']:<8} "
            f"{b['shape'][:40]:<40} {b['scope']}"
        )
    return "\n".join(lines)


def format_delta(delta: dict, top: int = 12) -> str:
    lines = [f"peak delta: {_gb(delta.get('peak_delta_bytes') or 0)} (B - A)"]
    for k, v in list(delta["by_scope_delta"].items())[:top]:
        lines.append(f"  {'+' if v >= 0 else ''}{_gb(v):>10}  {k}")
    return "\n".join(lines)
