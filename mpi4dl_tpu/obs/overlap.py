"""Exposed-wire ledger from the scheduled compiled HLO.

obs/timeline.py brackets a step between two extremes — serialized (no
compute/collective overlap) and perfect overlap — but the compiled module
already says where between them the schedule actually lands: the HLO is
**scheduled** (instruction order per computation is execution order), and a
collective XLA intends to hide is split into ``*-start``/``*-done`` async
halves with the hiding compute scheduled *between* them.  This module walks
that schedule and produces the **overlap ledger**:

- for every async collective pair, the **overlap window** — the compute
  instructions (FLOP-time from obs/timeline.py's cost model) scheduled
  between start and done.  Wire time covered by the window is **hidden**;
  the remainder is **exposed** (the device stalls at the done);
- a collective compiled *without* a start/done split is **sync** —
  structurally unhideable, its full wire time exposed no matter what the
  cost model says.  (The CPU backend compiles every collective sync, so on
  the virtual mesh the ledger reports 100% exposed — which is the honest
  baseline measurement ROADMAP item 2's halo-RDMA work must beat);
- everything attributed to ``obs.scope`` via the contract gate's
  :func:`clean_scope_path`, rolled up per scope and per semantic wire class
  (halo / junction / respatial / pipeline handoff / grad+stats reduce).

The simulation model (documented limits, hand-computed cases in
tests/test_overlap.py):

- compute time = conv/dot FLOPs over the bf16 peak (element-wise and
  memory-bound work costs zero — same caveat as the analytical timeline);
- one shared wire: in-flight transfers serialize among themselves
  (``wire_free`` clock), so a done can stall on queueing behind an earlier
  transfer as well as on its own payload; that queueing delay counts as
  exposed;
- each computation simulates with its own local clock; call-like ops
  (while/conditional/call) contribute their callee bodies ONCE at the call
  site (trip counts are not folded in — the structural per-step convention
  the whole analytic stack uses), and fusion bodies contribute their FLOPs;
- start/done pairs match within one computation (HLO guarantees this); a
  start whose done never appears is closed at the end of its computation.

:func:`overlap_ledger` is the time-domain product (ms, fractions — the
``overlap`` RunLog record, ``mem_probe --overlap``, the readiness rollup).
:func:`structural_overlap` is the integer-only projection the contract gate
pins as a golden: per-scope async-pair/sync counts, payload bytes, and
**structurally exposed bytes** (sync payloads plus async pairs whose window
contains zero FLOPs — no cost model, no floats, stable under a pinned jax).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Set, Tuple

from mpi4dl_tpu.obs.costs import (
    DEFAULT_ICI_BYTES_PER_S,
    ici_bytes_per_s,
    peak_flops,
)
from mpi4dl_tpu.obs.hbm import Instr, parse_hlo_module, shape_bytes
from mpi4dl_tpu.obs.timeline import (
    ASYNC_GLUE_OPS,
    collective_base,
    instr_flops,
)

UNSCOPED = "<unscoped>"

_CALL_OPS = ("while", "conditional", "call")


def _tuple_elements(shape: str) -> List[str]:
    """Top-level elements of an HLO tuple shape literal (depth-1 commas);
    a non-tuple shape is its own single element."""
    shape = shape.strip()
    if not shape.startswith("("):
        return [shape]
    inner = shape[1:-1] if shape.endswith(")") else shape[1:]
    out, depth, cur = [], 0, []
    for ch in inner:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return out


def start_payload_bytes(ins: Instr) -> int:
    """Wire payload of an async ``*-start``: the RESULT element of the
    start tuple ``(operand, result[, contexts])`` — matching
    ``hlo_collective_stats`` so sync and async forms of the same program
    report identical bytes.  Falls back to the full shape."""
    elems = _tuple_elements(ins.shape)
    if len(elems) > 1:
        return shape_bytes(elems[1])
    return ins.bytes


# Sub-f32 payload element types the quant layer puts on the wire
# (mpi4dl_tpu/quant: int8 / packed int4 ride s8, fp8 rides f8e4m3fn).
# The f32 scale tensors ride separate collectives and are — honestly —
# counted as unquantized bytes.
_QUANT_DTYPES = ("s8", "u8", "s4", "u4", "s2", "u2", "f8")
_SHAPE_DTYPE = re.compile(r"([a-z][a-z0-9]*)\[")


def payload_quantized(ins: Instr) -> bool:
    """True when every tensor element type of the collective's wire payload
    is a quantized dtype (the ``quantized_bytes`` ledger column)."""
    elems = _tuple_elements(ins.shape)
    shape = elems[1] if len(elems) > 1 else ins.shape
    dts = [d for d in _SHAPE_DTYPE.findall(shape) if d != "token"]
    return bool(dts) and all(
        d in _QUANT_DTYPES or d.startswith("f8") for d in dts
    )


@dataclasses.dataclass
class WireEvent:
    """One collective's wire accounting in the simulated schedule."""
    scope: str          # clean obs.scope path ("" = unscoped)
    cls: str            # HLO base opcode (collective-permute, all-gather…)
    bytes: int          # wire payload
    wire_ms: float      # bytes / ICI bandwidth
    hidden_ms: float    # wire time covered by compute in the window
    exposed_ms: float   # stall at the done (includes wire-queueing delay)
    sync: bool          # compiled without a start/done split
    window_flops: float  # FLOPs scheduled inside the start..done window
    comp: str           # computation the collective was scheduled in
    quantized: bool = False  # sub-f32 wire payload (quant layer)
    # Simulated-schedule timestamps (ms on the owning computation's local
    # clock — obs/trace.py shifts call-site COPIES onto the caller's clock;
    # the cached originals must never be mutated).  Defaulted so the
    # structural goldens, which never read them, stay byte-identical.
    issue_ms: float = 0.0   # device clock when the start issued
    begin_ms: float = 0.0   # wire clock when the payload began moving
    end_ms: float = 0.0     # wire clock when the payload finished
    done_ms: float = 0.0    # device clock after the done's stall


@dataclasses.dataclass
class _CompSim:
    duration_ms: float
    flops: float
    events: List[WireEvent]


@dataclasses.dataclass
class _Pending:
    issue_ms: float
    flops_at_issue: float
    bytes: int
    cls: str
    scope: str
    quantized: bool = False


class _ScheduleWalker:
    """Per-computation schedule simulation with memoization (a computation
    called from two sites contributes its body once per call site, computed
    once)."""

    def __init__(self, comps: Dict[str, List[Instr]],
                 peak: Optional[float], ici_bw: Optional[float]):
        self.comps = comps
        self.peak = peak or 0.0
        self.ici_bw = ici_bw or 0.0
        self._sim_cache: Dict[str, _CompSim] = {}
        self._flops_cache: Dict[str, float] = {}

    # -- cost primitives ---------------------------------------------------

    def _wire_ms(self, nbytes: int) -> float:
        return nbytes / self.ici_bw * 1e3 if self.ici_bw else 0.0

    def _compute_ms(self, flops: float) -> float:
        return flops / self.peak * 1e3 if self.peak else 0.0

    def comp_flops(self, comp: str) -> float:
        """Total conv/dot FLOPs of a computation including nested callees
        (fusion bodies carry the conv metadata)."""
        if comp in self._flops_cache:
            return self._flops_cache[comp]
        self._flops_cache[comp] = 0.0  # cycle guard
        total = 0.0
        for ins in self.comps.get(comp, ()):
            if ins.opcode in ("convolution", "dot"):
                total += instr_flops(ins, ins.raw)
            for callee in ins.callees:
                total += self.comp_flops(callee)
        self._flops_cache[comp] = total
        return total

    # -- async bookkeeping -------------------------------------------------

    def _wrapped_collective(self, ins: Instr) -> Optional[Instr]:
        """The collective op inside a generic ``async-start``'s wrapped
        computation, if any."""
        for callee in ins.callees:
            for sub in self.comps.get(callee, ()):
                if collective_base(sub.opcode):
                    return sub
        return None

    def _resolve_start(self, name: str, by_name: Dict[str, Instr],
                       pending: Dict[str, _Pending],
                       seen: Optional[Set[str]] = None) -> Optional[str]:
        """Follow a done's operand chain (through ``async-update`` and
        views) back to a pending start's name."""
        if name in pending:
            return name
        if seen is None:
            seen = set()
        if name in seen:
            return None
        seen.add(name)
        ins = by_name.get(name)
        if ins is None:
            return None
        if ins.opcode in ASYNC_GLUE_OPS or ins.is_view:
            for op in ins.operands:
                found = self._resolve_start(op, by_name, pending, seen)
                if found:
                    return found
        return None

    # -- the walk ----------------------------------------------------------

    def sim(self, comp: str) -> _CompSim:
        if comp in self._sim_cache:
            return self._sim_cache[comp]
        self._sim_cache[comp] = _CompSim(0.0, 0.0, [])  # cycle guard
        result = self._sim_uncached(comp)
        self._sim_cache[comp] = result
        return result

    def _sim_uncached(self, comp: str) -> _CompSim:
        instrs = self.comps.get(comp, [])
        by_name = {i.name: i for i in instrs}
        clock = 0.0        # device timeline (compute + stalls)
        wire_free = 0.0    # when the shared wire finishes its current queue
        flops_acc = 0.0
        events: List[WireEvent] = []
        pending: Dict[str, _Pending] = {}

        def finish(
            p: _Pending, now: float
        ) -> Tuple[float, float, float, float, float]:
            """(wire_ms, hidden_ms, exposed_ms, begin, end) of a pending
            transfer whose done executes at device time ``now``; advances
            the wire clock."""
            nonlocal wire_free
            wire_ms = self._wire_ms(p.bytes)
            begin = max(p.issue_ms, wire_free)
            end = begin + wire_ms
            wire_free = end
            exposed = max(0.0, end - now)          # stall incl. queueing
            hidden = max(0.0, wire_ms - exposed)   # covered by the window
            return wire_ms, hidden, exposed, begin, end

        for ins in instrs:
            base = collective_base(ins.opcode)
            if ins.opcode.endswith("-start") and (
                base or ins.opcode == "async-start"
            ):
                cls, scope, nbytes = base, ins.scope, start_payload_bytes(ins)
                quantized = payload_quantized(ins)
                if ins.opcode == "async-start":
                    inner = self._wrapped_collective(ins)
                    if inner is None:
                        continue  # copy-start etc.: not wire traffic
                    cls = collective_base(inner.opcode)
                    scope = ins.scope or inner.scope
                    nbytes = (start_payload_bytes(inner)
                              if inner.opcode.endswith("-start")
                              else inner.bytes)
                    quantized = payload_quantized(inner)
                pending[ins.name] = _Pending(clock, flops_acc, nbytes,
                                             cls or "collective", scope,
                                             quantized)
            elif ins.opcode.endswith("-done") and (
                base or ins.opcode == "async-done"
            ):
                start = self._resolve_start(
                    ins.operands[0], by_name, pending
                ) if ins.operands else None
                if start is None:
                    continue
                p = pending.pop(start)
                wire_ms, hidden, exposed, begin, end = finish(p, clock)
                clock += exposed
                events.append(WireEvent(
                    scope=p.scope, cls=p.cls, bytes=p.bytes,
                    wire_ms=wire_ms, hidden_ms=hidden, exposed_ms=exposed,
                    sync=False, window_flops=flops_acc - p.flops_at_issue,
                    comp=comp, quantized=p.quantized,
                    issue_ms=p.issue_ms, begin_ms=begin, end_ms=end,
                    done_ms=clock,
                ))
            elif base:
                # Sync collective: no split, the device sits on the whole
                # transfer — structurally unhideable.
                wire_ms = self._wire_ms(ins.bytes)
                issue = clock
                begin = max(clock, wire_free)
                wire_free = begin + wire_ms
                stall = wire_free - clock
                clock = wire_free
                events.append(WireEvent(
                    scope=ins.scope, cls=base, bytes=ins.bytes,
                    wire_ms=wire_ms, hidden_ms=0.0, exposed_ms=stall,
                    sync=True, window_flops=0.0, comp=comp,
                    quantized=payload_quantized(ins),
                    issue_ms=issue, begin_ms=begin, end_ms=wire_free,
                    done_ms=clock,
                ))
            elif ins.opcode in ("convolution", "dot"):
                fl = instr_flops(ins, ins.raw)
                flops_acc += fl
                clock += self._compute_ms(fl)
            elif ins.opcode == "fusion":
                fl = sum(self.comp_flops(c) for c in ins.callees)
                flops_acc += fl
                clock += self._compute_ms(fl)
            elif ins.callees and ins.opcode in _CALL_OPS:
                # Body contributes once at the call site (structural, trip
                # counts not folded); conditional branches sum — the same
                # all-computations-once convention as hlo_scope_costs.
                for callee in ins.callees:
                    sub = self.sim(callee)
                    off = clock
                    clock += sub.duration_ms
                    flops_acc += sub.flops
                    # Sub-sims are memoized and SHARED across call sites:
                    # shift copies onto this caller's clock, never the
                    # cached events themselves.
                    events.extend(
                        dataclasses.replace(
                            e,
                            issue_ms=e.issue_ms + off,
                            begin_ms=e.begin_ms + off,
                            end_ms=e.end_ms + off,
                            done_ms=e.done_ms + off,
                        )
                        for e in sub.events
                    )
            elif ins.callees and ins.opcode not in ASYNC_GLUE_OPS:
                # reduce/sort/map bodies: FLOPs only (no collectives there).
                # Async glue is excluded: an async-update's wrapped
                # computation belongs to its start/done pair, not to the
                # caller's compute time.
                fl = sum(self.comp_flops(c) for c in ins.callees)
                flops_acc += fl
                clock += self._compute_ms(fl)

        # Starts whose done never appeared: close them at the end of the
        # computation (the value must be ready before the computation ends).
        for name, p in pending.items():
            wire_ms, hidden, exposed, begin, end = finish(p, clock)
            clock += exposed
            events.append(WireEvent(
                scope=p.scope, cls=p.cls, bytes=p.bytes, wire_ms=wire_ms,
                hidden_ms=hidden, exposed_ms=exposed, sync=False,
                window_flops=flops_acc - p.flops_at_issue, comp=comp,
                quantized=p.quantized,
                issue_ms=p.issue_ms, begin_ms=begin, end_ms=end,
                done_ms=clock,
            ))
        return _CompSim(duration_ms=clock, flops=flops_acc, events=events)


def wire_class(scope: str, cls: str) -> str:
    """Semantic wire class of a collective from its obs.scope vocabulary —
    the per-class rollup PERF_NOTES' "what moves per step" table uses
    (halo ppermutes / junction gathers / respatial / pipeline handoffs /
    grad+stats reduces); falls back to the HLO opcode class."""
    s = scope or ""
    if "halo" in s or "d2_run" in s or "ring_step_hop" in s:
        return "halo"
    if "junction" in s or "stage_lineup" in s:
        return "junction"
    if "respatial" in s:
        return "respatial"
    if "handoff" in s or "mb_inject" in s or "mirror" in s:
        return "pipeline_handoff"
    if ("grad_reduce" in s or "loss_reduce" in s or "stats" in s
            or "bn_" in s or "optimizer" in s):
        return "grad_stats_reduce"
    return cls


def _events(hlo_text: str, peak: Optional[float], ici_bw: Optional[float]
            ) -> Tuple[List[WireEvent], _CompSim]:
    comps, entry = parse_hlo_module(hlo_text)
    if not entry:
        raise ValueError("no ENTRY computation found in HLO text")
    walker = _ScheduleWalker(comps, peak, ici_bw)
    sim = walker.sim(entry)
    return sim.events, sim


def overlap_ledger(
    hlo_text: str,
    *,
    peak: Optional[float] = None,
    ici_bw: Optional[float] = None,
    device=None,
    top: int = 24,
) -> dict:
    """The per-scope exposed/hidden wire ledger of one compiled module.

    ``peak``/``ici_bw`` default from ``device`` exactly like
    :func:`~mpi4dl_tpu.obs.timeline.analytical_timeline` (CPU hosts get the
    labeled nominal constants).  Returns a JSON-ready dict (the ``overlap``
    RunLog record; render with :func:`format_ledger`)::

        rows                per-scope {bytes, quantized_bytes, wire_ms,
                            hidden_ms, exposed_ms, async_pairs, sync,
                            classes} sorted by exposed_ms
                            (quantized_bytes = payload riding sub-f32
                            dtypes, the quant layer's wire; scale tensors
                            count as raw)
        by_class            the same, rolled up by semantic wire class
        totals              step-level sums + async_pairs/sync counts
        hidden_frac         hidden / wire (None when nothing moves)
        attributed_bytes_frac  collective bytes landing in named scopes
        simulated_step_ms   the schedule-aware wall estimate (compute +
                            exposed wire) that replaces the coarse
                            serialized/perfect-overlap brackets
    """
    peak_src = ici_src = "given"
    if peak is None:
        peak, peak_src = peak_flops(device, allow_cpu_nominal=True) \
            if device is not None else (None, None)
    if ici_bw is None:
        if device is not None:
            ici_bw, ici_src = ici_bytes_per_s(device)
        else:
            ici_bw, ici_src = DEFAULT_ICI_BYTES_PER_S, "default"

    events, sim = _events(hlo_text, peak, ici_bw)

    def bucket() -> dict:
        return {"bytes": 0, "quantized_bytes": 0, "wire_ms": 0.0,
                "hidden_ms": 0.0, "exposed_ms": 0.0, "async_pairs": 0,
                "sync": 0}

    def add(b: dict, e: WireEvent) -> None:
        b["bytes"] += e.bytes
        b["quantized_bytes"] += e.bytes if e.quantized else 0
        b["wire_ms"] += e.wire_ms
        b["hidden_ms"] += e.hidden_ms
        b["exposed_ms"] += e.exposed_ms
        b["async_pairs"] += 0 if e.sync else 1
        b["sync"] += 1 if e.sync else 0

    by_scope: Dict[str, dict] = {}
    by_class: Dict[str, dict] = {}
    totals = bucket()
    attributed = 0
    for e in events:
        key = e.scope or UNSCOPED
        row = by_scope.setdefault(key, {**bucket(), "classes": {}})
        add(row, e)
        add(row["classes"].setdefault(e.cls, bucket()), e)
        add(by_class.setdefault(wire_class(e.scope, e.cls), bucket()), e)
        add(totals, e)
        if e.scope:
            attributed += e.bytes

    def rounded(d: dict) -> dict:
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in d.items()}

    rows = [
        {"scope": k, **rounded({kk: vv for kk, vv in v.items()
                                if kk != "classes"}),
         "classes": {c: rounded(b) for c, b in v["classes"].items()}}
        for k, v in sorted(
            by_scope.items(),
            key=lambda kv: (-kv[1]["exposed_ms"], -kv[1]["bytes"]),
        )
    ]
    wire = totals["wire_ms"]
    return {
        "rows": rows[:top] if top else rows,
        "row_count": len(rows),
        "by_class": {c: rounded(b) for c, b in sorted(
            by_class.items(), key=lambda kv: -kv[1]["exposed_ms"])},
        "totals": rounded(totals),
        "hidden_frac": (
            round(totals["hidden_ms"] / wire, 4) if wire else None
        ),
        "quantized_frac": (
            round(totals["quantized_bytes"] / totals["bytes"], 4)
            if totals["bytes"] else None
        ),
        "attributed_bytes_frac": (
            round(attributed / totals["bytes"], 4) if totals["bytes"]
            else 1.0
        ),
        "compute_ms": round(
            sim.flops / peak * 1e3 if peak else 0.0, 4
        ),
        "simulated_step_ms": round(sim.duration_ms, 4),
        "peak_flops": peak,
        "peak_source": peak_src,
        "ici_bytes_per_s": ici_bw,
        "ici_source": ici_src,
    }


def structural_overlap(hlo_text: str) -> dict:
    """The integer-only overlap contract of one compiled module: per-scope
    per-class async-pair/sync counts, payload bytes, and **structurally
    exposed bytes** — sync payloads (no start/done split exists) plus async
    pairs whose window schedules zero FLOPs (nothing to hide under).  No
    cost model, so the projection is stable golden material under a pinned
    jax (the contract gate's ``overlap`` section)."""
    # Cost rates don't matter for the structural projection; the nominal
    # constants keep the walker's arithmetic well-defined.
    events, _ = _events(hlo_text, 1.0, 1.0)
    per_scope: Dict[str, Dict[str, dict]] = {}
    totals = {"async_pairs": 0, "sync": 0, "bytes": 0,
              "exposed_bytes": 0}
    for e in events:
        scope = e.scope or UNSCOPED
        entry = per_scope.setdefault(scope, {}).setdefault(
            e.cls, {"async_pairs": 0, "sync": 0, "bytes": 0,
                    "exposed_bytes": 0}
        )
        exposed = e.sync or e.window_flops <= 0.0
        for b in (entry, totals):
            b["async_pairs"] += 0 if e.sync else 1
            b["sync"] += 1 if e.sync else 0
            b["bytes"] += e.bytes
            b["exposed_bytes"] += e.bytes if exposed else 0
    return {
        "per_scope": {
            s: dict(sorted(ops.items()))
            for s, ops in sorted(per_scope.items())
        },
        "totals": totals,
    }


def _ms(v: float) -> str:
    return f"{v:.3f}"


def format_ledger(ledger: dict, top: int = 12) -> str:
    """Human-readable rendering of one overlap ledger (the
    ``mem_probe --overlap`` stderr table and ``obs report`` wire line)."""
    t = ledger["totals"]
    hidden_frac = ledger.get("hidden_frac")
    lines = [
        f"exposed-wire ledger (ICI {ledger['ici_bytes_per_s']:.3g} B/s "
        f"[{ledger['ici_source']}], peak "
        + (f"{ledger['peak_flops']:.3g} FLOP/s [{ledger['peak_source']}])"
           if ledger.get("peak_flops") else "n/a)"),
        f"wire {_ms(t['wire_ms'])} ms over {t['bytes']} bytes"
        + (f" ({t['quantized_bytes']} quantized)"
           if t.get("quantized_bytes") else "")
        + f" — hidden {_ms(t['hidden_ms'])} ms, exposed "
        f"{_ms(t['exposed_ms'])} ms"
        + (f" (hidden {hidden_frac:.1%})" if hidden_frac is not None else "")
        + f"; async pairs {t['async_pairs']}, sync {t['sync']}",
        f"simulated step {_ms(ledger['simulated_step_ms'])} ms "
        f"(compute {_ms(ledger['compute_ms'])} ms + exposed wire); "
        f"{ledger['attributed_bytes_frac']:.1%} of collective bytes "
        "scope-attributed",
    ]
    if ledger.get("by_class"):
        lines.append("per wire class (exposed/hidden ms, bytes):")
        for cls, b in ledger["by_class"].items():
            lines.append(
                f"  {cls:<18} exposed {_ms(b['exposed_ms']):>9}  hidden "
                f"{_ms(b['hidden_ms']):>9}  {b['bytes']:>12} B  "
                f"(async {b['async_pairs']}, sync {b['sync']})"
            )
    lines.append(
        f"{'scope':<44} {'exposed_ms':>10} {'hidden_ms':>10} "
        f"{'bytes':>12} {'async':>5} {'sync':>5}"
    )
    for r in ledger["rows"][:top]:
        lines.append(
            f"{r['scope'][:44]:<44} {r['exposed_ms']:>10.3f} "
            f"{r['hidden_ms']:>10.3f} {r['bytes']:>12} "
            f"{r['async_pairs']:>5} {r['sync']:>5}"
        )
    return "\n".join(lines)
