"""mpi4dl_tpu — a TPU-native framework for hybrid five-dimensional parallel
training of CNNs on very-high-resolution images.

Re-designed from scratch for TPU (JAX / XLA / pjit / shard_map / Pallas) with the
capabilities of OSU-Nowlab/MPI4DL (reference survey in SURVEY.md):

- **DP**    data parallelism over a ``data`` mesh axis (``psum`` gradients).
- **LP/PP** layer + GPipe pipeline parallelism over a ``stage`` mesh axis: one
  SPMD program where each device runs its stage via ``lax.switch`` on flat,
  stage-sharded parameter buffers and hands activations to its neighbour with
  ``lax.ppermute`` (reference: src/torchgems/mp_pipeline.py — tagged MPI
  send/recv between per-rank processes).
- **SP**    spatial parallelism: image H/W sharded over ``sph``/``spw`` mesh
  axes, halo (ghost-region) exchange expressed as non-wrapping ``ppermute``
  (reference: src/torchgems/spatial.py — 9-neighbour MPI isend/irecv).
- **GEMS**  bidirectional memory-aware model parallelism: a second activation
  stream flowing through the stage chain in the opposite direction inside the
  same compiled step (reference: src/torchgems/gems_master.py).

Unlike the reference there are no ranks, tags, recv buffers, or stream/MPI race
workarounds: everything is a single jitted dataflow program per step, and XLA
orders the collectives.
"""

__version__ = "0.1.0"

from mpi4dl_tpu.config import ParallelConfig, get_parser, config_from_args
from mpi4dl_tpu.mesh import build_mesh, MeshSpec
from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx

__all__ = [
    "ParallelConfig",
    "get_parser",
    "config_from_args",
    "build_mesh",
    "MeshSpec",
    "ApplyCtx",
    "SpatialCtx",
]
