"""Training steps: loss, optimizer, and jitted step builders.

The reference's training loops live in benchmark scripts + runtime classes
(`train_model.run_step/update`, mp_pipeline.py:509-538).  Here each regime is
a *builder* returning one jitted function `(state, batch) -> (state, metrics)`:

- :func:`make_train_step` — single device or pure DP (pjit over ``data``).
- :func:`make_spatial_train_step` — SP(+DP): shard_map over sph/spw(+data),
  halo convs inside, psum'd grads (the tile group doubles as a DP group for
  gradients, exactly the reference's create_allreduce_comm_spatial,
  comm.py:197-248).
- Pipeline/GEMS steps live in parallel/pipeline.py and parallel/gems.py.

Loss: softmax cross-entropy on logits (the reference's CrossEntropyLoss after
an in-model softmax is a double-softmax quirk, reproduced only when the model
was built with ``softmax_in_model=True``; then we take log of the model's
probabilities instead).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.compat import pcast

from mpi4dl_tpu.cells import CellModel
from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx
from mpi4dl_tpu.mesh import AXIS_DATA


def cross_entropy(logits_or_probs: jax.Array, labels: jax.Array,
                  from_probs: bool = False) -> jax.Array:
    """Mean softmax cross-entropy with integer labels."""
    x = logits_or_probs.astype(jnp.float32)
    if from_probs:
        logp = jnp.log(jnp.clip(x, 1e-20, 1.0))
    else:
        logp = jax.nn.log_softmax(x, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)
    return jnp.mean(nll)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))


# ---------------------------------------------------------------------------
# Optimizer — minimal SGD(+momentum) and Adam over arbitrary pytrees.
# (The reference uses torch.optim.SGD(lr=0.001); optax is available but the
# pipeline engine works on flat stage buffers where a hand-rolled update is
# clearer and allocation-free.)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """SGD(+momentum) / Adam with fp32 update arithmetic.

    Optimizer state (velocity, moments) is always fp32 and the update is
    computed in fp32 regardless of the parameter storage dtype, then rounded
    back — so ``--precision bf_16_all`` (params stored bf16, config.py) keeps
    fp32 math in the update path.  No persistent fp32 master copy is kept: a
    master would cost 4 extra bytes/param (6 vs 4 B — *negating* the memory
    capability the mode exists for) and would desynchronize from the BN
    running-stat write-back, which targets the live parameter buffer."""

    kind: str = "sgd"
    lr: float = 0.001
    momentum: float = 0.0
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8

    @staticmethod
    def _zeros32(params):
        return jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def init(self, params):
        if self.kind == "sgd" and self.momentum == 0.0:
            return ()
        if self.kind == "sgd":
            return (self._zeros32(params),)
        if self.kind == "adam":
            return (
                self._zeros32(params),
                self._zeros32(params),
                jnp.zeros((), jnp.int32),
            )
        raise ValueError(self.kind)

    def update(self, params, grads, opt_state):
        f32 = jnp.float32
        if self.kind == "sgd" and self.momentum == 0.0:
            new = jax.tree.map(
                lambda p, g: (p.astype(f32) - self.lr * g.astype(f32)).astype(p.dtype),
                params, grads,
            )
            return new, ()
        if self.kind == "sgd":
            (vel,) = opt_state
            vel = jax.tree.map(
                lambda v, g: self.momentum * v + g.astype(f32), vel, grads
            )
            new = jax.tree.map(
                lambda p, v: (p.astype(f32) - self.lr * v).astype(p.dtype),
                params, vel,
            )
            return new, (vel,)
        if self.kind == "adam":
            m, v, t = opt_state
            t = t + 1
            m = jax.tree.map(lambda a, g: self.b1 * a + (1 - self.b1) * g.astype(f32), m, grads)
            v = jax.tree.map(lambda a, g: self.b2 * a + (1 - self.b2) * jnp.square(g.astype(f32)), v, grads)
            bc1 = 1 - self.b1 ** t.astype(f32)
            bc2 = 1 - self.b2 ** t.astype(f32)
            new = jax.tree.map(
                lambda p, mm, vv: (
                    p.astype(f32) - self.lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + self.eps)
                ).astype(p.dtype),
                params, m, v,
            )
            return new, (m, v, t)
        raise ValueError(self.kind)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @staticmethod
    def create(params, optimizer: Optimizer) -> "TrainState":
        return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# Single-device / DP train step
# ---------------------------------------------------------------------------


def stat_updates_from_sink(sink: Optional[dict], params) -> Optional[list]:
    """Collect a bn_sink into a list aligned with the flattened param leaves
    (None where a leaf has no running-stat update — None is an empty pytree
    node, so the list is a valid jit/scan-carry aux with static structure)."""
    if sink is None:
        return None
    return [sink.get(id(leaf)) for leaf in jax.tree.leaves(params)]


def merge_stat_updates(params, updates: Optional[list]):
    """Write collected running-stat updates back into a params tree (typically
    the post-optimizer one — the functional analog of torch BN's in-place
    running-buffer mutation)."""
    if updates is None or all(u is None for u in updates):
        return params
    leaves, treedef = jax.tree.flatten(params)
    merged = [l if u is None else u.astype(l.dtype) for l, u in zip(leaves, updates)]
    return jax.tree.unflatten(treedef, merged)


def make_loss_fn(model: CellModel, ctx: ApplyCtx, from_probs: bool = False,
                 remat=False, with_stats: bool = False):
    """Loss fn returning ``(loss, (logits, stat_updates))``; stat_updates is
    None unless with_stats (then a leaf-aligned BN running-stat update list).
    ``remat`` is forwarded to ``CellModel.apply`` (False/True/"sqrt")."""

    def loss_fn(params_list, x, labels):
        c = dataclasses.replace(ctx, bn_sink={}) if with_stats else ctx
        logits = model.apply(params_list, x, c, remat=remat)
        if isinstance(logits, tuple):
            logits = logits[0]
        stats = stat_updates_from_sink(c.bn_sink, params_list) if with_stats else None
        return cross_entropy(logits, labels, from_probs), (logits, stats)

    return loss_fn


def make_train_step(
    model: CellModel,
    optimizer: Optimizer,
    mesh: Optional[Mesh] = None,
    parts: int = 1,
    compute_dtype=jnp.float32,
    from_probs: bool = False,
    remat: bool = False,
    bn_stats: bool = True,
    donate: bool = False,
    pallas_conv: bool = False,
    scan_steps: int = 1,
):
    """Single-device or DP (batch sharded over 'data') training step.

    ``scan_steps=k`` returns a MULTI-step function ``(state, xs, ys) ->
    (state, metrics)`` with ``xs: [k, B, H, W, C]`` running k optimizer
    steps in ONE compiled program (lax.scan; metrics averaged over the
    scan).  Under the axon RPC tunnel each dispatch costs ~28 ms of
    non-device time (PERF_NOTES r4) — k steps per dispatch amortizes it
    to ~0, which is also how a real training loop would drive the chip.
    Single-device only (the stacked-batch shardings are not plumbed).

    `parts` > 1 runs the micro-batch gradient-accumulation loop via lax.scan —
    the degenerate (split_size=1) form of the reference's GPipe parts loop.
    `remat=True` checkpoints per cell (memory for FLOPs — required for the
    reference's high-resolution configs at batch 1 on one chip);
    `remat="sqrt"` runs cells in ~√n two-level checkpoint groups (O(√n)
    live cell boundaries); `remat="fine"` keeps per-cell checkpoints and
    adds per-op checkpoints inside composite cells (ctx.remat_ops) — the
    max-trainable-resolution configuration for AmoebaNet (measured:
    boundary mass, not within-op temps, is what "fine" removes there;
    PERF_NOTES.md).
    `bn_stats=True` (default) updates BN running statistics each step (torch
    nn.BatchNorm2d semantics; with parts>1 the update uses the batch stats
    averaged over microbatches, which the momentum rule makes equivalent to
    averaging the per-microbatch updated values).
    """
    if pallas_conv and mesh is not None:
        raise ValueError(
            "pallas_conv=True is a single-device dispatch (pallas_call has "
            "no GSPMD partitioning rule under a pjit mesh); for sharded "
            "runs set use_pallas_conv on the SpatialCtx inside shard_map"
        )
    sp_knobs = (
        SpatialCtx(use_pallas_conv=True) if pallas_conv else None
    )
    import os as _os

    # MPI4DL_REMAT_OPS=1 combines per-op checkpoints with ANY outer remat
    # level (e.g. sqrt grouping + per-op bounding for the ResNet-2048
    # memory frontier) — "fine" remains per-cell + per-op.
    ctx = ApplyCtx(
        train=True,
        remat_ops=(remat == "fine"
                   or _os.environ.get("MPI4DL_REMAT_OPS") == "1"),
        spatial=sp_knobs,
    )
    model_remat = "sqrt" if remat == "sqrt" else bool(remat)
    loss_fn = make_loss_fn(
        model, ctx, from_probs, remat=model_remat, with_stats=bn_stats
    )

    def grads_for(params, x, labels):
        (loss, (logits, stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, x.astype(compute_dtype), labels
        )
        return loss, logits, stats, grads

    def step(state: TrainState, x, labels):
        if parts == 1:
            loss, logits, stats, grads = grads_for(state.params, x, labels)
            acc = accuracy(logits, labels)
        else:
            mb_x = x.reshape(parts, x.shape[0] // parts, *x.shape[1:])
            mb_y = labels.reshape(parts, labels.shape[0] // parts)
            zero = jax.tree.map(jnp.zeros_like, state.params)
            # Abstract probe for the (static) stat-update structure.
            stats_struct = jax.eval_shape(
                grads_for, state.params, mb_x[0], mb_y[0]
            )[2]
            stats_zero = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), stats_struct
            )

            def body(carry, mb):
                g_acc, loss_acc, acc_acc, st_acc = carry
                loss, logits, stats, grads = grads_for(state.params, mb[0], mb[1])
                g_acc = jax.tree.map(jnp.add, g_acc, grads)
                st_acc = jax.tree.map(jnp.add, st_acc, stats)
                return (
                    g_acc, loss_acc + loss, acc_acc + accuracy(logits, mb[1]), st_acc
                ), None

            (grads, loss, acc, stats), _ = lax.scan(
                body, (zero, jnp.zeros(()), jnp.zeros(()), stats_zero), (mb_x, mb_y)
            )
            grads = jax.tree.map(lambda g: g / parts, grads)
            stats = jax.tree.map(lambda s: s / parts, stats)
            loss, acc = loss / parts, acc / parts
        params, opt_state = optimizer.update(state.params, grads, state.opt_state)
        params = merge_stat_updates(params, stats)
        return (
            TrainState(params, opt_state, state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    if scan_steps > 1 and mesh is not None:
        raise ValueError("scan_steps>1 is single-device only")
    if scan_steps > 1:
        def multi(state: TrainState, xs, ys):
            state, ms = lax.scan(
                lambda s, xy: step(s, xy[0], xy[1]), state, (xs, ys)
            )
            return state, jax.tree.map(lambda a: jnp.mean(a), ms)

        return jax.jit(multi, donate_argnums=(0,) if donate else ())
    if mesh is None:
        # donate=True consumes the caller's state (params/opt buffers update
        # in place), removing a full extra copy of params+opt from peak
        # memory — part of the max-trainable-resolution story.  Off by
        # default: exact-match tests alias param arrays across states.
        return jax.jit(step, donate_argnums=(0,) if donate else ())

    # DP: batch sharded over 'data'; params replicated.  XLA inserts the
    # gradient all-reduce (the reference's SyncAllreduce, comm.py:440-514).
    data_spec = NamedSharding(mesh, P(AXIS_DATA))
    repl = NamedSharding(mesh, P())
    jstep = jax.jit(
        step,
        in_shardings=(None, data_spec, data_spec),
        out_shardings=(None, None),
        donate_argnums=(0,) if donate else (),
    )
    return jstep


# ---------------------------------------------------------------------------
# Spatial-parallel (SP [+DP]) train step via shard_map
# ---------------------------------------------------------------------------


def spatial_partition_spec(sp: SpatialCtx, data: bool = False) -> P:
    """PartitionSpec for an NHWC batch under a SpatialCtx (the analog of the
    reference's split_input slicing, train_spatial.py:241-290)."""
    return P(AXIS_DATA if data else None, sp.axis_h, sp.axis_w, None)


def make_spatial_train_step(
    model: CellModel,
    optimizer: Optimizer,
    mesh: Mesh,
    sp: SpatialCtx,
    parts: int = 1,
    with_data_axis: bool = False,
    compute_dtype=jnp.float32,
    from_probs: bool = False,
    spatial_until: Optional[int] = None,
    junction: str = "gather",
    bn_stats: bool = True,
    levels=None,
    local_dp: Optional[int] = None,
    donate: bool = False,
    remat=False,
    quant=None,
):
    """SP(+DP) training step: one shard_map over the whole step.
    ``remat`` threads per-cell checkpointing through the spatial region and
    tail (False/True/"sqrt" — see CellModel.apply).

    Inside, convs/pools halo-exchange over sph/spw; after `spatial_until`
    cells the activation is gathered (SP→LP junction; 'batch_split' = the
    LOCAL_DP_LP variant, degree `local_dp`); gradients are psum'd over the
    spatial axes (+ data axis when present) — the spatial tile group being a
    gradient DP group is exactly reference comm.py:197-248.

    ``levels`` is a list of (stop_cell, SpatialCtx) for multi-level spatial
    parallelism (reference num_spatial_parts="4,2"); ``sp`` must be the
    level-0 ctx (it defines the mesh axes and the input sharding).

    ``quant`` (Optional[QuantPolicy], docs/quantization.md): junction/
    respatial payload quantization inside ``apply_spatial_model`` and the
    EQuARX-style quantized gradient pmean (the whole gradient pytree
    reduced as ONE flattened vector); ``None`` is bit-identical.
    """
    from mpi4dl_tpu.parallel.spatial import (
        apply_spatial_model,
        junction_shard_index,
    )

    ctx = ApplyCtx(train=True, spatial=sp, data_axis=AXIS_DATA if with_data_axis else None)
    sp_last = levels[-1][1] if levels else sp
    degree = local_dp if local_dp else sp_last.grid_h * sp_last.grid_w

    def loss_fn(params_list, x, labels):
        c = dataclasses.replace(ctx, bn_sink={}) if bn_stats else ctx
        logits = apply_spatial_model(
            model, params_list, x, c, spatial_until=spatial_until,
            junction=junction, levels=levels, local_dp=local_dp, remat=remat,
            quant=quant,
        )
        if isinstance(logits, tuple):
            logits = logits[0]
        if junction == "batch_split":
            shard = labels.shape[0] // degree
            labels = lax.dynamic_slice_in_dim(
                labels, junction_shard_index(sp_last, degree) * shard, shard, axis=0
            )
        stats = stat_updates_from_sink(c.bn_sink, params_list) if bn_stats else None
        return cross_entropy(logits, labels, from_probs), (logits, labels, stats)
    grad_axes = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
    if with_data_axis:
        grad_axes = (AXIS_DATA,) + grad_axes

    x_spec = spatial_partition_spec(sp, data=with_data_axis)
    y_spec = P(AXIS_DATA) if with_data_axis else P()

    def global_loss_fn(p, xx, yy):
        # pmean over the tile axes makes the differentiated scalar the GLOBAL
        # loss; with shard_map's varying-axes tracking, each device's gradient
        # of it is then the complete gradient (the all_gather junction's
        # adjoint performs the cross-tile summation).  See tests/test_spatial.
        loss, aux = loss_fn(p, xx, yy)
        return lax.pmean(loss, grad_axes), aux

    def sharded_step(params, opt_state, x, labels):
        def grads_for(p, xx, yy):
            (loss, (logits, yy_used, stats)), grads = jax.value_and_grad(
                global_loss_fn, has_aux=True
            )(p, xx.astype(compute_dtype), yy)
            return loss, accuracy(logits, yy_used), stats, grads

        if parts == 1:
            loss, acc, stats, grads = grads_for(params, x, labels)
        else:
            mb_x = x.reshape(parts, x.shape[0] // parts, *x.shape[1:])
            mb_y = labels.reshape(parts, labels.shape[0] // parts)
            # Mark accumulators varying over the tile axes (see pipeline.py —
            # required for correct collective transposes under shard_map AD).
            v = lambda t: pcast(t, grad_axes, to="varying")
            zero = jax.tree.map(lambda p: v(jnp.zeros_like(p)), params)
            stats_struct = jax.eval_shape(grads_for, params, mb_x[0], mb_y[0])[2]
            stats_zero = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), stats_struct
            )

            def body(carry, mb):
                g_acc, l_acc, a_acc, st_acc = carry
                loss, acc, stats, grads = grads_for(params, mb[0], mb[1])
                return (
                    jax.tree.map(jnp.add, g_acc, grads),
                    l_acc + loss,
                    a_acc + acc,
                    jax.tree.map(jnp.add, st_acc, stats),
                ), None

            (grads, loss, acc, stats), _ = lax.scan(
                body,
                (zero, v(jnp.zeros(())), v(jnp.zeros(())), stats_zero),
                (mb_x, mb_y),
            )
            grads = jax.tree.map(lambda g: g / parts, grads)
            stats = jax.tree.map(lambda s: s / parts, stats)
            loss, acc = loss / parts, acc / parts

        grad_mode = quant.mode("grad") if quant is not None else None
        if grad_mode:
            from mpi4dl_tpu.quant.collectives import quantized_pmean_tree

            grads = quantized_pmean_tree(
                grads, grad_axes, grad_mode, quant.block
            )
        else:
            grads = jax.tree.map(lambda g: lax.pmean(g, grad_axes), grads)
        new_params, new_opt = optimizer.update(params, grads, opt_state)
        new_params = merge_stat_updates(new_params, stats)
        metrics = {
            "loss": lax.pmean(loss, grad_axes),
            "accuracy": lax.pmean(acc, grad_axes),
        }
        return new_params, new_opt, metrics

    from mpi4dl_tpu.compat import shard_map

    smapped = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(P(), P(), x_spec, y_spec),
        out_specs=(P(), P(), P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state: TrainState, x, labels):
        params, opt_state, metrics = smapped(state.params, state.opt_state, x, labels)
        return TrainState(params, opt_state, state.step + 1), metrics

    return step


# ---------------------------------------------------------------------------
# Eval / inference steps (train=False: BN normalizes with running stats)
# ---------------------------------------------------------------------------


def make_eval_step(
    model: CellModel,
    mesh: Optional[Mesh] = None,
    compute_dtype=jnp.float32,
    from_probs: bool = False,
):
    """Inference step `(params_list, x, labels) -> metrics` (train=False, so
    BN uses running statistics — the path the reference exercises implicitly
    through nn.BatchNorm2d.eval(), which round 1 lacked entirely)."""
    ctx = ApplyCtx(train=False)

    def estep(params_list, x, labels):
        logits = model.apply(params_list, x.astype(compute_dtype), ctx)
        if isinstance(logits, tuple):
            logits = logits[0]
        return {
            "loss": cross_entropy(logits, labels, from_probs),
            "accuracy": accuracy(logits, labels),
            "logits": logits,
        }

    if mesh is None:
        return jax.jit(estep)
    data_spec = NamedSharding(mesh, P(AXIS_DATA))
    return jax.jit(estep, in_shardings=(None, data_spec, data_spec))


def make_spatial_eval_step(
    model: CellModel,
    mesh: Mesh,
    sp: SpatialCtx,
    with_data_axis: bool = False,
    compute_dtype=jnp.float32,
    from_probs: bool = False,
    spatial_until: Optional[int] = None,
    junction: str = "gather",
    levels=None,
    local_dp: Optional[int] = None,
):
    """SP(+DP) inference step: tiles in, metrics out (train=False)."""
    from mpi4dl_tpu.compat import shard_map

    from mpi4dl_tpu.parallel.spatial import (
        apply_spatial_model,
        junction_shard_index,
    )

    ctx = ApplyCtx(
        train=False, spatial=sp, data_axis=AXIS_DATA if with_data_axis else None
    )
    red_axes = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
    if with_data_axis:
        red_axes = (AXIS_DATA,) + red_axes
    x_spec = spatial_partition_spec(sp, data=with_data_axis)
    y_spec = P(AXIS_DATA) if with_data_axis else P()
    sp_last = levels[-1][1] if levels else sp
    degree = local_dp if local_dp else sp_last.grid_h * sp_last.grid_w

    def sharded_eval(params_list, x, labels):
        logits = apply_spatial_model(
            model, params_list, x.astype(compute_dtype), ctx,
            spatial_until=spatial_until, junction=junction,
            levels=levels, local_dp=local_dp,
        )
        if isinstance(logits, tuple):
            logits = logits[0]
        if junction == "batch_split":
            shard = labels.shape[0] // degree
            labels = lax.dynamic_slice_in_dim(
                labels, junction_shard_index(sp_last, degree) * shard, shard, axis=0
            )
        return {
            "loss": lax.pmean(cross_entropy(logits, labels, from_probs), red_axes),
            "accuracy": lax.pmean(accuracy(logits, labels), red_axes),
        }

    smapped = shard_map(
        sharded_eval, mesh=mesh, in_specs=(P(), x_spec, y_spec), out_specs=P()
    )
    return jax.jit(smapped)
