"""Cells: the unit of layer-parallel splitting.

The reference splits a top-level ``nn.Sequential`` of coarse "cells" by index
range (``src/torchgems/mp_pipeline.py:41-83``) and discovers inter-split
shapes by a two-phase dummy forward (``:126-168``).  Here a model *is* a list
of :class:`Cell` objects; shapes come from ``jax.eval_shape`` over the global
(unsharded) shapes — no probe forward, no `image_size_seq` rescaling
(reference benchmark_amoebanet_sp.py:120-125 exists only because probing at
full resolution OOMs; eval_shape is abstract so it cannot).

A cell's activation may be a single array or a tuple of arrays — AmoebaNet
cells carry ``(x, skip)`` tuple state (reference amoebanet.py:500-532,
the reason the reference pipeline supports MULTIPLE_INPUT/OUTPUT).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from mpi4dl_tpu.layer_ctx import ApplyCtx, EVAL_CTX
from mpi4dl_tpu.layers import Layer
from mpi4dl_tpu.obs.scopes import scope

Act = Union[jax.Array, Tuple[jax.Array, ...]]
ShapeLike = Union[Tuple[int, ...], Tuple[Tuple[int, ...], ...]]


class Cell:
    """One pipeline-splittable unit: init/apply plus a human name."""

    name: str = "cell"

    def init(self, key, in_shape: ShapeLike):
        raise NotImplementedError

    def apply(self, params, x: Act, ctx: ApplyCtx) -> Act:
        raise NotImplementedError


@dataclasses.dataclass
class LayerCell(Cell):
    """A cell made of a plain sequence of layers (single-tensor state)."""

    layers: Sequence[Layer]
    name: str = "seq"

    def init(self, key, in_shape):
        keys = jax.random.split(key, max(len(self.layers), 1))
        params = []
        shape = in_shape
        for k, layer in zip(keys, self.layers):
            p, shape = layer.init(k, shape)
            params.append(p)
        return params, shape

    def apply(self, params, x, ctx):
        from mpi4dl_tpu.ops.d2 import maybe_run_d2, maybe_run_fused_unsharded
        from mpi4dl_tpu.ops.stripe_bwd import maybe_stripe_run

        y = maybe_run_d2(self.layers, params, x, ctx)
        if y is not None:
            return y
        # Stripe-wise execution (MPI4DL_STRIPE_BWD=1): the whole cell runs —
        # forward and backward — one H-stripe at a time under pad-once
        # margins (ops/stripe_bwd.py; the flagship's O(parts) buy-back).
        y = maybe_stripe_run(self.layers, params, x, ctx)
        if y is not None:
            return y
        y = maybe_run_fused_unsharded(self.layers, params, x, ctx)
        if y is not None:
            return y
        for p, layer in zip(params, self.layers):
            x = layer.apply(p, x, ctx)
        return x


@dataclasses.dataclass
class FnCell(Cell):
    """A cell defined by explicit init/apply callables (for residual blocks,
    NAS cells, heads...)."""

    init_fn: Callable[[Any, ShapeLike], Tuple[Any, ShapeLike]]
    apply_fn: Callable[[Any, Act, ApplyCtx], Act]
    name: str = "fn"

    def init(self, key, in_shape):
        return self.init_fn(key, in_shape)

    def apply(self, params, x, ctx):
        return self.apply_fn(params, x, ctx)


@dataclasses.dataclass
class CellModel:
    """A model: ordered cells + metadata.

    ``spatial_until``: number of leading cells that run under spatial sharding
    (the analog of the reference's `spatial_size` splits running conv_spatial;
    the junction gather happens after cell index spatial_until-1).
    """

    cells: List[Cell]
    in_shape: Tuple[int, ...]
    num_classes: int
    spatial_until: int = 0
    name: str = "model"

    def init(self, key) -> Tuple[List[Any], List[ShapeLike]]:
        """Init all cells; returns (params_list, shape_list) where
        shape_list[i] is the *output* shape of cell i (global shapes).
        shape_list mirrors the reference's get_output_shapes result
        (mp_pipeline.py:126-168)."""
        keys = jax.random.split(key, len(self.cells))
        params_list, shapes = [], []
        shape: ShapeLike = self.in_shape
        for k, cell in zip(keys, self.cells):
            p, shape = cell.init(k, shape)
            params_list.append(p)
            shapes.append(shape)
        return params_list, shapes

    def apply(self, params_list, x: Act, ctx: ApplyCtx, *,
              start: int = 0, stop: Optional[int] = None,
              remat=False) -> Act:
        """Run cells [start, stop) — the per-stage sub-model.

        ``remat=True`` wraps each cell in :func:`jax.checkpoint` so backward
        recomputes activations per cell instead of storing them — the memory
        lever that lets high-resolution configs (the reference's 1024²-2048²
        charts, BASELINE.md) fit on a single chip.

        ``remat="sqrt"`` adds a second checkpoint level: cells run in ~√n
        groups, the OUTER checkpoint saves only group-boundary activations
        and the inner per-cell checkpoints exist transiently during one
        group's backward — O(√n) live boundaries instead of O(n), the
        classic two-level recursive schedule (deep ResNets hold 55 block
        boundaries at high resolution; this is what lets them fit).
        """
        stop = len(self.cells) if stop is None else stop
        if remat == "sqrt" and stop - start > 3:
            import math as _m
            import os as _os

            n = stop - start
            # Group count: ~sqrt(n) balances outer boundaries against live
            # inner boundaries; MPI4DL_SQRT_GROUPS overrides for memory
            # tuning (bigger = smaller groups = fewer inner boundaries live
            # during one group's backward).
            g = int(_os.environ.get("MPI4DL_SQRT_GROUPS", "0")) or max(
                2, _m.isqrt(n)
            )
            meta = None
            for lo, hi in split_even(n, min(n, g)):
                grp = tuple(range(start + lo, start + hi))

                def grp_fn(ps, x, c, _grp=grp):
                    m = None
                    for k, i in enumerate(_grp):
                        with scope(f"cell{i:02d}"):
                            x, m = checkpointed_apply(
                                self.cells[i].apply, ps[k], x, c,
                                in_meta=m, pack=True,
                            )
                    return _unpack_act(x, m)

                x, meta = checkpointed_apply(
                    grp_fn, [params_list[i] for i in grp], x, ctx,
                    in_meta=meta, pack=True,
                )
            return _unpack_act(x, meta)
        meta = None
        for i in range(start, stop):
            with scope(f"cell{i:02d}"):
                if remat:
                    x, meta = checkpointed_apply(
                        self.cells[i].apply, params_list[i], x, ctx,
                        in_meta=meta, pack=True,
                    )
                else:
                    x = self.cells[i].apply(params_list[i], x, ctx)
        return _unpack_act(x, meta) if remat else x

    def out_shapes(self, params_list) -> List[ShapeLike]:
        """Abstract shape inference via eval_shape (no FLOPs, no memory)."""
        shapes: List[ShapeLike] = []
        x = jax.ShapeDtypeStruct(self.in_shape, jnp.float32)
        for cell, p in zip(self.cells, params_list):
            x = jax.eval_shape(lambda p, x, c=cell: c.apply(p, x, EVAL_CTX), p, x)
            shapes.append(
                tuple(t.shape for t in x) if isinstance(x, tuple) else x.shape
            )
        return shapes


# ---------------------------------------------------------------------------
# Boundary lane-packing: large checkpoint residuals stored exactly-128-lane.
#
# A [1, 2048, 2048, 64] bf16 boundary costs 1 GB on TPU — 2x its real size —
# because any channels-minor layout pads C=64 to the 128-lane tile (and XLA's
# backward temps for such shapes showed up in T(2,128) layouts padded 4-16x,
# the measured ResNet-110 2048² OOM driver after conv temps were fixed,
# PERF_NOTES r4).  Re-splitting the flattened (W, C) trailing dims as
# (W*C/128, 128) makes every saved residual (and its cotangent) an
# exactly-128-lane tensor with no padding at all — and a shape whose natural
# layout XLA stores densely packed (the r4 AmoebaNet frontier's binding mass,
# [1,416,416,1664] bf16, measured ~2x its 553 MB logical size: an unpacked
# narrow-tile layout this reshape makes impossible).  The pack/unpack
# reshapes live INSIDE the checkpoint, so only the packed form is ever
# stored.  Gated to large boundaries (and C not already exactly 128):
# W*C a multiple of 128 takes the W-fold form [N,H,W*C/128,128]; otherwise
# (margined SP tiles) H*W*C a multiple of 128 takes the full-flatten form
# [N,H*W*C/128,128]; packs nothing else — zero graph change.
# ---------------------------------------------------------------------------

_PACK_MIN_ELEMS = 1 << 24  # 16.7M elements = 32 MB bf16 per saved boundary


def _pack_meta(shape):
    """(w, c) for the W-fold form [N,H,W*C/128,128], or (h, w, c) for the
    full-flatten form [N,H*W*C/128,128] (margined SP tiles, whose halo
    rows/cols break the per-row divisibility), or None (no packing)."""
    import os

    if os.environ.get("MPI4DL_NO_PACK") == "1" or len(shape) != 4:
        return None
    n, h, w, c = shape
    if c == 128 or h * w * c < _PACK_MIN_ELEMS:
        return None
    if (w * c) % 128 == 0:
        return (w, c)
    if (h * w * c) % 128 == 0:
        return (h, w, c)
    return None


def _pack_one(x):
    m = _pack_meta(getattr(x, "shape", ()))
    if m is None:
        return x, None
    n, h, w, c = x.shape
    if len(m) == 2:
        return x.reshape(n, h, (w * c) // 128, 128), m
    return x.reshape(n, (h * w * c) // 128, 128), m


def _unpack_one(x, m):
    if m is None:
        return x
    n = x.shape[0]
    if len(m) == 2:
        w, c = m
        return x.reshape(n, x.shape[1], w, c)
    h, w, c = m
    return x.reshape(n, h, w, c)


def _pack_act(y: Act):
    if isinstance(y, tuple):
        pairs = [_pack_one(t) for t in y]
        return tuple(p[0] for p in pairs), tuple(p[1] for p in pairs)
    return _pack_one(y)


def _unpack_act(y: Act, meta) -> Act:
    if meta is None:
        return y
    if isinstance(y, tuple):
        return tuple(_unpack_one(t, m) for t, m in zip(y, meta))
    return _unpack_one(y, meta)


def checkpointed_apply(apply_fn, params, x: Act, ctx: ApplyCtx,
                       in_meta=None, pack: bool = False):
    """Run ``apply_fn(params, x, ctx)`` under jax.checkpoint.

    When a BN stats sink is active it must cross the checkpoint boundary
    explicitly: the sink captures tracers of the INNER (rematerialized) trace,
    which would escape if consumed outside.  The checkpointed fn therefore
    returns the stat updates aligned to the flattened param leaves, and they
    are re-deposited into the outer sink under the OUTER leaves' ids.

    ``pack=True`` threads boundary channel-packing through the checkpoint:
    ``x`` arrives in the packed form described by ``in_meta`` (unpacked
    INSIDE the checkpointed fn) and the returned value is ``(y_packed,
    out_meta)``.  The metas are static Python data captured at trace time.

    Serves the per-cell remat (model.apply remat=True) and the finer per-op
    remat inside AmoebaNet cells (ctx.remat_ops — the 'fine' level that
    bounds backward temps to one op's internals at a time; the
    max-trainable-resolution lever, PERF_NOTES.md)."""
    import dataclasses as _dc

    out_meta = [None]

    def body(p, x, c):
        y = apply_fn(p, _unpack_act(x, in_meta) if pack else x, c)
        if pack:
            y, out_meta[0] = _pack_act(y)
        return y

    if ctx.bn_sink is None:
        y = jax.checkpoint(lambda p, x: body(p, x, ctx))(params, x)
        return (y, out_meta[0]) if pack else y

    def fn(p, x):
        inner: dict = {}
        y = body(p, x, _dc.replace(ctx, bn_sink=inner))
        stats = [inner.get(id(leaf)) for leaf in jax.tree.leaves(p)]
        return y, stats

    y, stats = jax.checkpoint(fn)(params, x)
    for leaf, s in zip(jax.tree.leaves(params), stats):
        if s is not None:
            ctx.bn_sink[id(leaf)] = s
    return (y, out_meta[0]) if pack else y


def split_even(n_cells: int, split_size: int, balance: Optional[Sequence[int]] = None
               ) -> List[Tuple[int, int]]:
    """Partition cell indices into `split_size` contiguous ranges.

    Even split puts the remainder on the earliest stages, matching the
    reference's get_start_end_layer_index (mp_pipeline.py:41-69); an explicit
    `balance` list of per-stage cell counts overrides (must sum to n_cells,
    reference asserts mp_pipeline.py:55-58).
    """
    if balance is not None:
        assert sum(balance) == n_cells, (balance, n_cells)
        out, start = [], 0
        for b in balance:
            out.append((start, start + b))
            start += b
        return out
    base = n_cells // split_size
    rem = n_cells % split_size
    out, start = [], 0
    for s in range(split_size):
        size = base + (1 if s < rem else 0)
        out.append((start, start + size))
        start += size
    return out
