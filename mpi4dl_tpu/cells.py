"""Cells: the unit of layer-parallel splitting.

The reference splits a top-level ``nn.Sequential`` of coarse "cells" by index
range (``src/torchgems/mp_pipeline.py:41-83``) and discovers inter-split
shapes by a two-phase dummy forward (``:126-168``).  Here a model *is* a list
of :class:`Cell` objects; shapes come from ``jax.eval_shape`` over the global
(unsharded) shapes — no probe forward, no `image_size_seq` rescaling
(reference benchmark_amoebanet_sp.py:120-125 exists only because probing at
full resolution OOMs; eval_shape is abstract so it cannot).

A cell's activation may be a single array or a tuple of arrays — AmoebaNet
cells carry ``(x, skip)`` tuple state (reference amoebanet.py:500-532,
the reason the reference pipeline supports MULTIPLE_INPUT/OUTPUT).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from mpi4dl_tpu.layer_ctx import ApplyCtx, EVAL_CTX
from mpi4dl_tpu.layers import Layer

Act = Union[jax.Array, Tuple[jax.Array, ...]]
ShapeLike = Union[Tuple[int, ...], Tuple[Tuple[int, ...], ...]]


class Cell:
    """One pipeline-splittable unit: init/apply plus a human name."""

    name: str = "cell"

    def init(self, key, in_shape: ShapeLike):
        raise NotImplementedError

    def apply(self, params, x: Act, ctx: ApplyCtx) -> Act:
        raise NotImplementedError


@dataclasses.dataclass
class LayerCell(Cell):
    """A cell made of a plain sequence of layers (single-tensor state)."""

    layers: Sequence[Layer]
    name: str = "seq"

    def init(self, key, in_shape):
        keys = jax.random.split(key, max(len(self.layers), 1))
        params = []
        shape = in_shape
        for k, layer in zip(keys, self.layers):
            p, shape = layer.init(k, shape)
            params.append(p)
        return params, shape

    def apply(self, params, x, ctx):
        from mpi4dl_tpu.ops.d2 import maybe_run_d2

        y = maybe_run_d2(self.layers, params, x, ctx)
        if y is not None:
            return y
        for p, layer in zip(params, self.layers):
            x = layer.apply(p, x, ctx)
        return x


@dataclasses.dataclass
class FnCell(Cell):
    """A cell defined by explicit init/apply callables (for residual blocks,
    NAS cells, heads...)."""

    init_fn: Callable[[Any, ShapeLike], Tuple[Any, ShapeLike]]
    apply_fn: Callable[[Any, Act, ApplyCtx], Act]
    name: str = "fn"

    def init(self, key, in_shape):
        return self.init_fn(key, in_shape)

    def apply(self, params, x, ctx):
        return self.apply_fn(params, x, ctx)


@dataclasses.dataclass
class CellModel:
    """A model: ordered cells + metadata.

    ``spatial_until``: number of leading cells that run under spatial sharding
    (the analog of the reference's `spatial_size` splits running conv_spatial;
    the junction gather happens after cell index spatial_until-1).
    """

    cells: List[Cell]
    in_shape: Tuple[int, ...]
    num_classes: int
    spatial_until: int = 0
    name: str = "model"

    def init(self, key) -> Tuple[List[Any], List[ShapeLike]]:
        """Init all cells; returns (params_list, shape_list) where
        shape_list[i] is the *output* shape of cell i (global shapes).
        shape_list mirrors the reference's get_output_shapes result
        (mp_pipeline.py:126-168)."""
        keys = jax.random.split(key, len(self.cells))
        params_list, shapes = [], []
        shape: ShapeLike = self.in_shape
        for k, cell in zip(keys, self.cells):
            p, shape = cell.init(k, shape)
            params_list.append(p)
            shapes.append(shape)
        return params_list, shapes

    def apply(self, params_list, x: Act, ctx: ApplyCtx, *,
              start: int = 0, stop: Optional[int] = None,
              remat=False) -> Act:
        """Run cells [start, stop) — the per-stage sub-model.

        ``remat=True`` wraps each cell in :func:`jax.checkpoint` so backward
        recomputes activations per cell instead of storing them — the memory
        lever that lets high-resolution configs (the reference's 1024²-2048²
        charts, BASELINE.md) fit on a single chip.

        ``remat="sqrt"`` adds a second checkpoint level: cells run in ~√n
        groups, the OUTER checkpoint saves only group-boundary activations
        and the inner per-cell checkpoints exist transiently during one
        group's backward — O(√n) live boundaries instead of O(n), the
        classic two-level recursive schedule (deep ResNets hold 55 block
        boundaries at high resolution; this is what lets them fit).
        """
        stop = len(self.cells) if stop is None else stop
        if remat == "sqrt" and stop - start > 3:
            import math as _m

            n = stop - start
            for lo, hi in split_even(n, max(2, _m.isqrt(n))):
                grp = tuple(range(start + lo, start + hi))

                def grp_fn(ps, x, c, _grp=grp):
                    for k, i in enumerate(_grp):
                        x = _apply_cell_remat(self.cells[i], ps[k], x, c)
                    return x

                x = checkpointed_apply(
                    grp_fn, [params_list[i] for i in grp], x, ctx
                )
            return x
        for i in range(start, stop):
            if remat:
                x = _apply_cell_remat(self.cells[i], params_list[i], x, ctx)
            else:
                x = self.cells[i].apply(params_list[i], x, ctx)
        return x

    def out_shapes(self, params_list) -> List[ShapeLike]:
        """Abstract shape inference via eval_shape (no FLOPs, no memory)."""
        shapes: List[ShapeLike] = []
        x = jax.ShapeDtypeStruct(self.in_shape, jnp.float32)
        for cell, p in zip(self.cells, params_list):
            x = jax.eval_shape(lambda p, x, c=cell: c.apply(p, x, EVAL_CTX), p, x)
            shapes.append(
                tuple(t.shape for t in x) if isinstance(x, tuple) else x.shape
            )
        return shapes


def checkpointed_apply(apply_fn, params, x: Act, ctx: ApplyCtx) -> Act:
    """Run ``apply_fn(params, x, ctx)`` under jax.checkpoint.

    When a BN stats sink is active it must cross the checkpoint boundary
    explicitly: the sink captures tracers of the INNER (rematerialized) trace,
    which would escape if consumed outside.  The checkpointed fn therefore
    returns the stat updates aligned to the flattened param leaves, and they
    are re-deposited into the outer sink under the OUTER leaves' ids.

    Serves the per-cell remat (model.apply remat=True) and the finer per-op
    remat inside AmoebaNet cells (ctx.remat_ops — the 'fine' level that
    bounds backward temps to one op's internals at a time; the
    max-trainable-resolution lever, PERF_NOTES.md)."""
    import dataclasses as _dc

    if ctx.bn_sink is None:
        return jax.checkpoint(lambda p, x: apply_fn(p, x, ctx))(params, x)

    def fn(p, x):
        inner: dict = {}
        y = apply_fn(p, x, _dc.replace(ctx, bn_sink=inner))
        stats = [inner.get(id(leaf)) for leaf in jax.tree.leaves(p)]
        return y, stats

    y, stats = jax.checkpoint(fn)(params, x)
    for leaf, s in zip(jax.tree.leaves(params), stats):
        if s is not None:
            ctx.bn_sink[id(leaf)] = s
    return y


def _apply_cell_remat(cell: Cell, params, x: Act, ctx: ApplyCtx) -> Act:
    return checkpointed_apply(cell.apply, params, x, ctx)


def split_even(n_cells: int, split_size: int, balance: Optional[Sequence[int]] = None
               ) -> List[Tuple[int, int]]:
    """Partition cell indices into `split_size` contiguous ranges.

    Even split puts the remainder on the earliest stages, matching the
    reference's get_start_end_layer_index (mp_pipeline.py:41-69); an explicit
    `balance` list of per-stage cell counts overrides (must sum to n_cells,
    reference asserts mp_pipeline.py:55-58).
    """
    if balance is not None:
        assert sum(balance) == n_cells, (balance, n_cells)
        out, start = [], 0
        for b in balance:
            out.append((start, start + b))
            start += b
        return out
    base = n_cells // split_size
    rem = n_cells % split_size
    out, start = [], 0
    for s in range(split_size):
        size = base + (1 if s < rem else 0)
        out.append((start, start + size))
        start += size
    return out
