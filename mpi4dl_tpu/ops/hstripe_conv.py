"""H-striped convolution — bounding XLA's conv temporaries at huge spatial.

XLA's TPU lowering of a stride-1 conv on a TINY-channel HUGE-spatial input
materializes an im2col-style patch tensor of ~kh·kw·H·W·C elements
(measured ~3 GB per 3x3 conv at C=16, 2048² — the single reason
ResNet-110-v2 2048² bs1 did not fit a 16 GB chip, PERF_NOTES r3; the
reference sidesteps it only because cuDNN has native strided kernels and
its SP mode splits H/W across 5 GPUs, `/root/reference/src/torchgems/
spatial.py`).  The Pallas margin-consuming kernel cannot take these shapes
either: Mosaic refuses sub-128 lane DMA extents, and padding C=3..16 up to
128 lanes multiplies the whole input in HBM (8–42x, measured OOM).

So: run the conv as a ``lax.map`` (serial scan) over H stripes.  Each
stripe is a VALID conv on ``[N, sh + kh - 1, W', C]`` — the patch temp
shrinks by the stripe count and is freed before the next stripe runs.  The
backward (scan transpose) accumulates stripe input-grads with contiguous
``dynamic_update_slice``s — no scatter.  FLOPs are identical; only peak
memory changes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_DIMNUMS = ("NHWC", "HWIO", "NHWC")

# Per-stripe im2col budget (bytes).  Stripe count is the smallest divisor
# of the output height whose stripe patch tensor fits the budget.
_PATCH_BUDGET = 192 * 1024 * 1024


def _pick_stripes(h: int, wid: int, cin: int, kh: int, kw: int,
                  itemsize: int) -> int:
    patch = h * wid * cin * kh * kw * itemsize
    if patch <= _PATCH_BUDGET:
        return 1
    want = -(-patch // _PATCH_BUDGET)
    for s in range(want, h + 1):
        if h % s == 0:
            return s
    return h


def hstripe_conv2d(x: jax.Array, w: jax.Array,
                   pad_h=(0, 0), pad_w=(0, 0)) -> jax.Array:
    """Stride-1 conv with explicit padding, H stripe by H stripe.

    x: [N, H, W, Cin]; w: [kh, kw, Cin, Cout] →
    [N, H + Σpad_h − kh + 1, W + Σpad_w − kw + 1, Cout].

    Layout discipline (the actual ResNet-110 2048² OOM fix, PERF_NOTES r4):
    a full-size tiny-C 4-D tensor adjacent to a conv gets XLA's
    narrow-channel conv layouts — T(2,128) padded 4–16x at C=16..64 — so NO
    full-size 4-D tensor may exist here.  The input is flattened to
    [N, H, W·C] (fusible into its producer, so the producer's output buffer
    is the cleanly-tiled flat form), H padding happens on flat rows, W
    padding happens INSIDE the per-stripe conv, and each stripe reshapes to
    4-D only transiently.  The backward inherits all of it: the scan
    transpose accumulates dx into the flat buffer.

    Differentiable through the scan (dx = per-stripe conv-transposes
    assembled by dynamic_update_slice; dw = accumulated stripe filter
    grads).  Two variants were tried and measured WORSE on the ResNet-110
    2048² peak: a custom VJP saving (x, w) whole with explicitly re-striped
    dx/dw (+2 GB — the full-x residual and padded-cotangent buffer outlive
    the scan), and a fully-flat form that skipped the 4-D W-pad by padding
    W inside each stripe's conv (+2.8 GB — whatever fusion XLA lost there
    cost more than the pad copy).  Measured best: pad the 4-D input once,
    flatten, stripe."""
    n, h, wid, cin = x.shape
    kh, kw, wcin, cout = w.shape
    assert wcin == cin, (wcin, cin)
    (phl, phh), (pwl, pwh) = pad_h, pad_w
    oh = h + phl + phh - (kh - 1)
    ow = wid + pwl + pwh - (kw - 1)
    stripes = _pick_stripes(oh, wid + pwl + pwh, cin, kh, kw,
                            x.dtype.itemsize)
    if stripes == 1:
        return lax.conv_general_dilated(
            x, w, (1, 1), (pad_h, pad_w), dimension_numbers=_DIMNUMS
        )
    sh = oh // stripes

    # Pads happen on the 4-D form, THEN the tensor flattens.  A fully-flat
    # variant (W pad as pw·C elements on the flat last dim) was also tried
    # and measured +2.8 GB worse — XLA's fusion/layout choices around the
    # flat pad were worse than one 4-D pad copy.  Empirical, not modeled.
    if phl or phh or pwl or pwh:
        x = jnp.pad(x, ((0, 0), (phl, phh), (pwl, pwh), (0, 0)))
    hp, wp = h + phl + phh, wid + pwl + pwh
    xf = x.reshape(n, hp, wp * cin)

    def piece(i):
        xs = lax.dynamic_slice_in_dim(xf, i * sh, sh + kh - 1, axis=1)
        y = lax.conv_general_dilated(
            xs.reshape(n, sh + kh - 1, wp, cin), w, (1, 1), "VALID",
            dimension_numbers=_DIMNUMS,
        )
        return y.reshape(n, sh, ow * cout)

    ys = lax.map(piece, jnp.arange(stripes))        # [S, N, sh, OW·Cout]
    return ys.transpose(1, 0, 2, 3).reshape(n, oh, ow, cout)
