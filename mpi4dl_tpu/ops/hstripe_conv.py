"""H-striped convolution — bounding XLA's conv temporaries at huge spatial.

XLA's TPU lowering of a stride-1 conv on a TINY-channel HUGE-spatial input
materializes an im2col-style patch tensor of ~kh·kw·H·W·C elements
(measured ~3 GB per 3x3 conv at C=16, 2048² — the single reason
ResNet-110-v2 2048² bs1 did not fit a 16 GB chip, PERF_NOTES r3; the
reference sidesteps it only because cuDNN has native strided kernels and
its SP mode splits H/W across 5 GPUs, `/root/reference/src/torchgems/
spatial.py`).  The Pallas margin-consuming kernel cannot take these shapes
either: Mosaic refuses sub-128 lane DMA extents, and padding C=3..16 up to
128 lanes multiplies the whole input in HBM (8–42x, measured OOM).

So: run the conv as a ``lax.map`` (serial scan) over H stripes.  Each
stripe is a VALID conv on ``[N, sh + kh - 1, W', C]`` — the patch temp
shrinks by the stripe count and is freed before the next stripe runs.  The
backward (scan transpose) accumulates stripe input-grads with contiguous
``dynamic_update_slice``s — no scatter.  FLOPs are identical; only peak
memory changes.
"""

from __future__ import annotations

import logging
import os

import jax
import jax.numpy as jnp
from jax import lax
from mpi4dl_tpu.mesh import AXIS_SPH

_log = logging.getLogger("mpi4dl_tpu")

_DIMNUMS = ("NHWC", "HWIO", "NHWC")

# Per-stripe im2col budget (bytes).  Stripe count is the budget-derived
# value; a non-divisible output height gets a ragged (zero-padded) final
# stripe rather than degenerating to per-row scan steps (a near-prime
# oh=2039 would otherwise run as 2039 sequential 1-row convs).
_PATCH_BUDGET = 192 * 1024 * 1024


def _smallest_divisor_at_least(n: int, want: int) -> int:
    """Smallest divisor of ``n`` that is >= ``want`` (n itself worst-case)."""
    for s in range(max(1, want), n + 1):
        if n % s == 0:
            return s
    return n


def _pick_stripes(h: int, wid: int, cin: int, kh: int, kw: int,
                  itemsize: int) -> int:
    patch = h * wid * cin * kh * kw * itemsize
    if patch <= _PATCH_BUDGET:
        return 1
    return min(h, -(-patch // _PATCH_BUDGET))


def hstripe_conv2d(x: jax.Array, w: jax.Array,
                   pad_h=(0, 0), pad_w=(0, 0)) -> jax.Array:
    """Stride-1 conv with explicit padding, H stripe by H stripe.

    x: [N, H, W, Cin]; w: [kh, kw, Cin, Cout] →
    [N, H + Σpad_h − kh + 1, W + Σpad_w − kw + 1, Cout].

    Layout discipline (the actual ResNet-110 2048² OOM fix, PERF_NOTES r4):
    a full-size tiny-C 4-D tensor adjacent to a conv gets XLA's
    narrow-channel conv layouts — T(2,128) padded 4–16x at C=16..64 — so NO
    full-size 4-D tensor may exist here.  The input is flattened to
    [N, H, W·C] (fusible into its producer, so the producer's output buffer
    is the cleanly-tiled flat form), H padding happens on flat rows, W
    padding happens INSIDE the per-stripe conv, and each stripe reshapes to
    4-D only transiently.  The backward inherits all of it: the scan
    transpose accumulates dx into the flat buffer.

    Differentiable through the scan (dx = per-stripe conv-transposes
    assembled by dynamic_update_slice; dw = accumulated stripe filter
    grads).  Two variants were tried and measured WORSE on the ResNet-110
    2048² peak: a custom VJP saving (x, w) whole with explicitly re-striped
    dx/dw (+2 GB — the full-x residual and padded-cotangent buffer outlive
    the scan), and a fully-flat form that skipped the 4-D W-pad by padding
    W inside each stripe's conv (+2.8 GB — whatever fusion XLA lost there
    cost more than the pad copy).  Measured best: pad the 4-D input once,
    flatten, stripe."""
    n, h, wid, cin = x.shape
    kh, kw, wcin, cout = w.shape
    assert wcin == cin, (wcin, cin)
    (phl, phh), (pwl, pwh) = pad_h, pad_w
    oh = h + phl + phh - (kh - 1)
    ow = wid + pwl + pwh - (kw - 1)
    stripes = _pick_stripes(oh, wid + pwl + pwh, cin, kh, kw,
                            x.dtype.itemsize)
    if stripes == 1:
        return lax.conv_general_dilated(
            x, w, (1, 1), (pad_h, pad_w), dimension_numbers=_DIMNUMS
        )
    # Ragged final stripe: sh rows per stripe regardless of divisibility —
    # the input gets `extra` zero rows at the bottom so every scan step has
    # identical shapes, and the surplus output rows are dropped at the end.
    # (A conv over trailing zero rows is wasted FLOPs < one stripe's worth;
    # the alternative — the smallest DIVISOR of oh >= the budget count —
    # degenerates to per-row steps when oh is near-prime.)
    sh = -(-oh // stripes)
    stripes = -(-oh // sh)
    extra = stripes * sh - oh

    # Pads happen on the 4-D form, THEN the tensor flattens.  A fully-flat
    # variant (W pad as pw·C elements on the flat last dim) was also tried
    # and measured +2.8 GB worse — XLA's fusion/layout choices around the
    # flat pad were worse than one 4-D pad copy.  Empirical, not modeled.
    if phl or phh or pwl or pwh:
        x = jnp.pad(x, ((0, 0), (phl, phh), (pwl, pwh), (0, 0)))
    hp, wp = h + phl + phh, wid + pwl + pwh
    xf = x.reshape(n, hp, wp * cin)
    if extra:
        xf = jnp.pad(xf, ((0, 0), (0, extra), (0, 0)))

    def piece(i):
        xs = lax.dynamic_slice_in_dim(xf, i * sh, sh + kh - 1, axis=1)
        y = lax.conv_general_dilated(
            xs.reshape(n, sh + kh - 1, wp, cin), w, (1, 1), "VALID",
            dimension_numbers=_DIMNUMS,
        )
        return y.reshape(n, sh, ow * cout)

    ys = lax.map(piece, jnp.arange(stripes, dtype=jnp.int32))        # [S, N, sh, OW·Cout]
    out = ys.transpose(1, 0, 2, 3).reshape(n, stripes * sh, ow * cout)
    if extra:
        out = out[:, :oh]
    return out.reshape(n, oh, ow, cout)


# ---------------------------------------------------------------------------
# H-striped LAYER-RUN execution — the block-level form.
#
# Striping convs one by one (above) bounds conv temps, but a residual
# block's full-size INTERMEDIATE activations (BN/relu outputs between the
# convs) still materialize at every layer boundary — in XLA's padded
# narrow-channel layouts they were the last ~250 MB that kept ResNet-110
# 2048² bs1 off the chip (PERF_NOTES r4).  Running the whole branch stripe
# by stripe makes every intermediate a per-stripe transient.
#
# Semantics (both deviations are the REFERENCE'S OWN at high resolution,
# documented in ops/d2.py and layers.BatchNorm):
# - pad-once borders: the run's accumulated H margin is zero-padded once,
#   convs run VALID on H (exactly halo-D2's border semantics;
#   reference resnet_spatial_d2.py) — W keeps per-conv SAME padding;
# - train-mode BatchNorm uses PER-STRIPE batch statistics (the reference's
#   spatial ResNet uses per-TILE nn.BatchNorm2d the same way); margin rows
#   are excluded from the statistics via the pre_margin machinery.  Eval
#   mode uses running stats and has no statistics deviation.
# ---------------------------------------------------------------------------

# Per-stripe activation budget for the layer-run form (bytes of the
# stripe's widest intermediate), and the input-size gate below which the
# run is not worth striping.  The gate sits at 2048²: 1024²-class blocks
# fit and run fast on the plain path (hardware-validated 1.10 img/s rung),
# and the striped program's compile cost is only worth paying where the
# plain program cannot fit at all.
_RUN_STRIPE_BUDGET = 64 * 1024 * 1024
_RUN_MIN_PIXELS = 1 << 22

_RUN_WARNED = False


def _hstripe_run_mode() -> str:
    """Block-striping control, env ``MPI4DL_HSTRIPE_RUN`` (advisor r4):
    ``"0"`` = never; ``"1"`` = explicit opt-in (shape gates still apply —
    they are correctness/benefit conditions); unset = auto — the shape gate
    decides, and the FIRST engagement logs a warning, because the striped
    run changes train-mode semantics (per-stripe BN statistics, pad-once
    borders — the reference's own high-res behavior, but a deviation from
    the plain single-device path)."""
    return os.environ.get("MPI4DL_HSTRIPE_RUN", "auto")


def hstripe_run_eligible(layers, x_shape, ctx) -> bool:
    """Gate for the striped layer-run: single-device (no real spatial
    sharding), stride-1 run with a positive accumulated H halo, tiny-C
    huge-spatial input, all layers premargin-capable."""
    from mpi4dl_tpu.ops.d2 import accumulated_halo, layer_d2_geometry

    mode = _hstripe_run_mode()
    if mode == "0":
        return False
    if ctx.spatial is not None:
        return False
    n, h, w, c = x_shape
    if c > 64 or h * w < _RUN_MIN_PIXELS:
        return False
    acc = accumulated_halo(layers)
    if acc is None or acc[0] <= 0:
        return False
    for layer in layers:
        g = layer_d2_geometry(layer)
        if g is None or g[2] != 1 or g[3] != 1:
            return False
    return True


def _warn_engaged(pixels: int, exact_active: bool, train: bool) -> None:
    """One-time engagement warning — emitted from hstripe_layer_run only
    once striping is actually committed (an eligible run can still fall
    back when no reasonable stripe divisor exists, and warning there would
    both mislead and consume the single warning slot — advisor r5).
    ``exact_active`` is the REAL statistics mode of this run (the env flag
    alone can be overridden by the lane_pad fallback).  Eval-mode runs
    neither warn nor latch: they have no statistics deviation, and an
    eval-first job must not consume the slot with a message describing
    semantics its later TRAIN runs will not have."""
    global _RUN_WARNED
    if not train or _hstripe_run_mode() == "1" or _RUN_WARNED:
        return
    _RUN_WARNED = True
    bn_note = (
        "train-mode BN uses GLOBAL batch statistics (MPI4DL_HSTRIPE_EXACT)"
        if exact_active
        else "train-mode BN uses per-stripe statistics"
    )
    _log.warning(
        "H-striped block execution engaged for %s-pixel input (%s; conv "
        "borders are pad-once zeros — the halo-D2 semantics).  Set "
        "MPI4DL_HSTRIPE_RUN=0 to disable, =1 to silence this.",
        pixels, bn_note,
    )


class _FixedStatsBN:
    """BatchNorm with externally fixed batch statistics — the building
    block of the exact-stats striped run: every stripe normalizes with the
    same GLOBAL (mean, var), so striped train-mode output equals the
    unstriped pad-once run exactly."""

    _d2_identity = True  # consumes no margin (layer_d2_geometry)

    def __init__(self, bn, mean, var, cnt):
        self.bn, self.mean, self.var, self.cnt = bn, mean, var, cnt

    def apply(self, params, x, ctx):
        return self.bn.normalize_with_stats(
            params, x, self.mean, self.var, self.cnt, ctx
        )


def _margin_at(layers, upto: int, m: int) -> int:
    """Remaining H margin at the input of layers[upto] (stride-1 run)."""
    from mpi4dl_tpu.ops.d2 import layer_d2_geometry

    for layer in layers[:upto]:
        m -= layer_d2_geometry(layer)[0]
    return m


def _hstripe_exact_stats() -> bool:
    """MPI4DL_HSTRIPE_EXACT=1: train-mode BN inside a striped run uses
    GLOBAL batch statistics, computed by a cascade of stripewise stat
    passes (one per BN: run the prefix with earlier BNs fixed, reduce the
    BN's input over the true rows).  Costs ~one extra prefix-forward per
    BN; buys bit-parity with the unstriped pad-once run (the default
    per-stripe statistics are the reference's own high-res semantics but
    a documented deviation — advisor r4)."""
    return os.environ.get("MPI4DL_HSTRIPE_EXACT") == "1"


def hstripe_layer_run(layers, params_seq, x, ctx):
    """Run a stride-1 layer sequence stripe-by-stripe over H.

    x: [N, H, W, C] (unpadded).  The run's accumulated H margin is padded
    once with zeros; each stripe carries the margin and the layers consume
    it via :func:`mpi4dl_tpu.ops.d2.apply_layers_premargin` under a fake
    H-sharded SpatialCtx (no collectives: bn_cross_tile off, exchanges
    pre-consumed).  BN running-stat updates are averaged over stripes and
    re-deposited into the caller's sink (the microbatch momentum-rule
    equivalence, train.make_train_step docstring)."""
    import dataclasses

    from mpi4dl_tpu.layer_ctx import SpatialCtx
    from mpi4dl_tpu.ops.d2 import accumulated_halo, apply_layers_premargin

    n, h, w, c = x.shape
    m = accumulated_halo(layers)[0]
    # Stripe count sized to the run's WIDEST intermediate, not its input.
    cmax = c
    for layer in layers:
        cmax = max(
            cmax,
            getattr(layer, "out_channels", 0),
            getattr(layer, "num_features", 0),
        )
    per_row = w * cmax * x.dtype.itemsize * n
    want = max(1, -(-(h * per_row) // _RUN_STRIPE_BUDGET))
    stripes = _smallest_divisor_at_least(h, want)
    sh = h // stripes
    if stripes == 1 or sh < m + 1 or stripes > 4 * want:
        # stripes > 4*want: h has no reasonable divisor (near-prime) — a
        # ragged stripe is NOT an option here (zero-padded rows would enter
        # the per-stripe BN statistics), so fall back to the plain path
        # rather than degenerate into per-row scan steps (advisor r4).
        return None  # caller takes its normal path
    sp_fake = SpatialCtx(
        axis_h=AXIS_SPH, grid_h=stripes, bn_cross_tile=False, stat_local=True
    )
    sctx = ctx.with_spatial(sp_fake)
    leaves = jax.tree.leaves(params_seq)

    xp = jnp.pad(x, ((0, 0), (m, m), (0, 0), (0, 0)))
    xf = xp.reshape(n, h + 2 * m, w * c)

    # Exact-stats mode: fix every train-mode BN's batch statistics to the
    # GLOBAL values before the output pass, via one stripewise stat pass
    # per BN (prefix run with earlier BNs already fixed; the BN's input
    # reduced over the true rows of each stripe).  Striped output then
    # equals the unstriped pad-once run bit-for-bit (modulo reassociation).
    eff_layers = list(layers)
    has_lane_pad = any(
        getattr(l, "lane_pad", 0) or getattr(l, "lane_pad_in", 0)
        or getattr(l, "lane_pad_out", 0)
        for l in layers
    )
    # lane-padded runs keep per-stripe statistics: normalize_with_stats
    # does not support lane_pad and the padded width would mis-shape the
    # collected stats (unreachable via the shipped models, which never
    # combine lane_pad with hstripe shapes — defensive fallback).
    exact_active = _hstripe_exact_stats() and ctx.train and not has_lane_pad
    _warn_engaged(h * w, exact_active, ctx.train)
    if exact_active:
        from mpi4dl_tpu.layers import BatchNorm as _BN

        acc_dt = jnp.promote_types(jnp.float32, x.dtype)
        sctx_nostat = dataclasses.replace(sctx, bn_sink=None)
        for j, layer in enumerate(layers):
            if not isinstance(layer, _BN):
                continue
            if j == 0:
                s = jnp.sum(x, axis=(0, 1, 2), dtype=acc_dt)
                ss = jnp.sum(jnp.square(x.astype(acc_dt)), axis=(0, 1, 2))
            else:
                mh_j = _margin_at(eff_layers, j, m)

                def stat_piece(i, _j=j, _mh=mh_j):
                    xs = lax.dynamic_slice_in_dim(
                        xf, i * sh, sh + 2 * m, axis=1
                    )
                    xs = xs.reshape(n, sh + 2 * m, w, c)
                    y, mh_out, _ = apply_layers_premargin(
                        eff_layers[:_j], params_seq[:_j], xs,
                        sctx_nostat, m, 0,
                    )
                    assert mh_out == _mh, (mh_out, _mh)
                    t = y[:, _mh:_mh + sh]
                    return (
                        jnp.sum(t, axis=(0, 1, 2), dtype=acc_dt),
                        jnp.sum(jnp.square(t.astype(acc_dt)), axis=(0, 1, 2)),
                    )

                sA, ssA = lax.map(stat_piece, jnp.arange(stripes, dtype=jnp.int32))
                s, ss = jnp.sum(sA, axis=0), jnp.sum(ssA, axis=0)
            cnt = jnp.asarray(n * h * w, acc_dt)
            mean = s / cnt
            var = jnp.maximum(ss / cnt - mean * mean, 0.0)
            eff_layers[j] = _FixedStatsBN(layer, mean, var, cnt)

    def piece(i):
        xs = lax.dynamic_slice_in_dim(xf, i * sh, sh + 2 * m, axis=1)
        xs = xs.reshape(n, sh + 2 * m, w, c)
        if ctx.bn_sink is not None:
            inner: dict = {}
            cc = dataclasses.replace(sctx, bn_sink=inner)
        else:
            inner, cc = None, sctx
        y, mh, mw = apply_layers_premargin(eff_layers, params_seq, xs, cc, m, 0)
        assert mh == 0 and mw == 0, (mh, mw)
        # The reassembly below assumes every layer preserves W (SAME pads on
        # the unsharded dim) — a W-shrinking run would scramble the reshape.
        assert y.shape[2] == w, (y.shape, w)
        stats = (
            [inner.get(id(l)) for l in leaves] if inner is not None else []
        )
        return y.reshape(n, sh, y.shape[2] * y.shape[3]), stats

    ys, stats = lax.map(piece, jnp.arange(stripes, dtype=jnp.int32))
    oc = ys.shape[3] // w
    if ctx.bn_sink is not None:
        for leaf, s in zip(leaves, stats):
            if s is not None:
                ctx.bn_sink[id(leaf)] = jnp.mean(s, axis=0)
    return ys.transpose(1, 0, 2, 3).reshape(n, h, w, oc)
