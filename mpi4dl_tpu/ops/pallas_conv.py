"""Pallas halo-consuming convolution — the SURVEY §7 "D2 endgame" spike.

The D2 path amortizes halo exchange over fused layer runs (ops/d2.py); its
hot op is then a stride-1 conv that consumes a pre-exchanged margin: input
``[H + kh-1, W + kw-1, Cin]`` → VALID conv → ``[H, W, Cout]``.  This module
implements that op as a Pallas TPU kernel, formulated as implicit GEMM so
the FLOPs land on the MXU:

    out[y, x, :] = Σ_{dy, dx}  X[y+dy, x+dx, :] @ W[dy, dx, :, :]

Grid = (H tiles, W tiles, Cout tiles).  Each program DMAs its overlapping
input window HBM→VMEM (windows overlap by the margin, so the input stays
unblocked in ANY/HBM and the kernel slices with element-granular ``pl.ds``),
then accumulates the kh·kw shifted ``[TH·TW, Cin] @ [Cin, TCO]`` matmuls in
an fp32 VMEM scratch.

Scope (deliberate, per VERDICT r3 task 9 "measure, then decide"):
- forward only — adoption into Conv2d.apply is gated on the micro-benchmark
  (benchmarks/communication/halo/benchmark_pallas_conv.py) beating XLA's
  conv by >10% on real hardware; XLA's conv is the production path today.
- stride 1 (the fused-run hot case; strided convs stay on XLA).

Channel counts are zero-padded to the 128-lane width and H/W to the tile
grid by the wrapper; the un-padded result is sliced back out.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _kernel(x_any, w_any, o_ref, xwin, wbuf, acc, sem, wsem,
            *, kh, kw, th, tw, tww, tco, relu=False):
    """One (H-tile, W-tile, Cout-tile) program.

    The input window carries the FULL Cin depth — deep layers shrink the H
    tile (wrapper) instead of chunking Cin in-kernel.  An earlier revision
    chunked Cin through slot-reused DMA scratch; hardware runs showed that
    races: Mosaic does not fence a DMA write into VMEM against in-flight
    vector/MXU reads of the same buffer, so the chunk DMA landed while the
    previous chunk's matmuls were still reading (WAR hazard — wrong sums at
    n_ci >= 3, verified against a pure-DMA addressing probe that was exact).
    Keeping Cin whole means every scratch buffer is written by exactly one
    DMA per (i, j) visit, waited before first read — no reuse, no race.
    This is no longer only a comment: pallascheck's DMA-discipline pass
    (analysis/pallascheck/interp.py) walks this kernel's jaxpr over the
    full grid and fails the build on any read of a DMA destination before
    its wait or write to a DMA source while the copy is in flight — the
    exact hazard class the chunked revision hit on hardware.

    The window DMA is guarded on the first Cout tile: scratch persists
    across the (innermost) Cout grid dimension, so the same window serves
    every Cout tile without re-reading HBM.
    """
    i = pl.program_id(0)
    j = pl.program_id(1)
    c = pl.program_id(2)

    # Mosaic requires HBM slice extents on the sublane dim (W here) to be
    # multiples of the 8-row tiling — `tww` is tw+kw-1 rounded up to 8
    # (the wrapper pads the input so the over-read stays in bounds).
    win_copy = pltpu.make_async_copy(
        x_any.at[pl.ds(i * th, th + kh - 1), pl.ds(j * tw, tww), :],
        xwin,
        sem,
    )
    w_copy = pltpu.make_async_copy(
        w_any.at[:, :, :, pl.ds(c * tco, tco)],
        wbuf,
        wsem,
    )

    w_copy.start()

    @pl.when(c == 0)
    def _():
        win_copy.start()
        win_copy.wait()
        if relu:
            # Fused ReLU prologue: one VMEM-local pass over the window
            # (margins included — elementwise, identical to relu-then-conv).
            # A plain vector write AFTER the DMA wait: ordinary dataflow
            # ordering, not the DMA-vs-vector hazard documented above.
            xwin[:] = jnp.maximum(xwin[:], 0)

    w_copy.wait()
    acc[:] = jnp.zeros_like(acc)
    for dy in range(kh):
        for dx in range(kw):
            xs = xwin[dy : dy + th, dx : dx + tw, :].reshape(th * tw, -1)
            acc[:] += jnp.dot(
                xs, wbuf[dy, dx], preferred_element_type=jnp.float32
            )
    o_ref[:] = acc[:].reshape(th, tw, tco).astype(o_ref.dtype)


def _kernel_stats(x_any, w_any, o_ref, s_ref, sq_ref, xwin, wbuf, acc, sem,
                  wsem, *, kh, kw, th, tw, tww, tco, relu, win):
    """The fused-epilogue variant: conv (+ optional ReLU prologue) plus
    per-program partial BN statistics of the CAST output over the static
    stat window ``win`` = (h0, h1, w0, w1) in out coords (excludes padding
    and any not-yet-consumed D2 margin, mirroring BatchNorm's stat_x
    slicing).  Statistics are taken over the cast (compute-dtype) output
    with fp32 accumulation — the same numbers the unfused BatchNorm
    computes from the conv's output tensor."""
    _kernel(x_any, w_any, o_ref, xwin, wbuf, acc, sem, wsem,
            kh=kh, kw=kw, th=th, tw=tw, tww=tww, tco=tco, relu=relu)
    i = pl.program_id(0)
    j = pl.program_id(1)
    h0, h1, w0, w1 = win
    ri = jax.lax.broadcasted_iota(jnp.int32, (th, tw), 0) + i * th
    ci = jax.lax.broadcasted_iota(jnp.int32, (th, tw), 1) + j * tw
    valid = (ri >= h0) & (ri < h1) & (ci >= w0) & (ci < w1)
    yf = o_ref[:].astype(jnp.float32)
    yv = jnp.where(valid[:, :, None], yf, 0.0)
    s_ref[0, 0, :] = jnp.sum(yv, axis=(0, 1))
    sq_ref[0, 0, :] = jnp.sum(yv * yv, axis=(0, 1))


# Per-core VMEM pool the kernel budgets against (~16 MiB on current TPUs;
# see the Pallas guide).  The caps below are DERIVED splits of this pool —
# not hand-maintained constants — and the static verifier
# (analysis/pallascheck) re-derives the per-grid-point total from the traced
# specs and certifies it against this same number, so the splits cannot
# silently drift past what a core can hold.
_VMEM_BYTES = 16 * 1024 * 1024
# Input-window scratch share (3/8 = 6 MiB): the H tile halves until the
# full-Cin window fits, so deep layers (cin 1024-2048) run instead of dying
# in an opaque Mosaic allocation error.
_WINDOW_BUDGET = (3 * _VMEM_BYTES) // 8
# Weight-slab share (1/2 = 8 MiB) for the per-Cout-tile slab
# [kh, kw, Cin, tco] — beyond this the kernel would not fit VMEM alongside
# the window; callers should fall back to XLA's conv (Conv2d's dispatch
# checks pallas_conv_eligible).  The remaining 1/8 of the pool plus
# whatever the shrink loops free covers the fp32 accumulator and the
# double-buffered output block — bounded by _vmem_total_bytes below.
_WSLAB_CAP = _VMEM_BYTES // 2
# Default Cout tile — shared by halo_conv2d, the eligibility gate, and
# _bwd's fallback check so their slab math cannot drift apart.
_DEFAULT_TCO = 128


# Default W tile — shared with the eligibility gate's window math.
_DEFAULT_TW = 128


def _cpad(c: int) -> int:
    """Channel padding target: the 128-lane width, always.  A sub-128 pad
    was tried for the tiny-channel huge-spatial regime (ResNet C∈{3,16}) and
    REJECTED by Mosaic on hardware: a DMA window slice of a sub-128 channel
    extent lowers to a lane-dim memref_slice, which Mosaic refuses (both for
    the input window and the weight slab).  Tiny-channel shapes therefore
    must NOT take this kernel (the 128-pad multiplies the whole input in
    HBM — 42.7x for C=3); they use ops/hstripe_conv.py instead."""
    return _round_up(c, 128)


def _wslab_bytes(c: int, kh: int, kw: int, tco: int, itemsize: int) -> int:
    return kh * kw * _cpad(c) * tco * itemsize


def _win_bytes(c: int, kh: int, kw: int, th: int, tw: int, itemsize: int) -> int:
    """Bytes of the [th + kh-1, round8(tw + kw-1), Cin_pad] input-window
    scratch — the same formula the wrapper's H-tile shrink loop minimizes."""
    return (th + kh - 1) * _round_up(tw + kw - 1, 8) * _cpad(c) * itemsize


def _vmem_total_bytes(cin: int, kh: int, kw: int, th: int, tw: int,
                      tco: int, in_item: int, w_item: int,
                      out_item: int) -> int:
    """Per-grid-point VMEM of one program: input window + weight slab +
    fp32 accumulator scratch + the double-buffered output block (the Pallas
    pipeline keeps two output buffers in flight).  This is the model the
    wrapper's shrink loop bounds by ``_VMEM_BYTES`` and pallascheck's VMEM
    certification re-derives from the traced ``pallas_call`` specs — the
    first full verifier run flagged the fp32-at-default-tiles config at
    ~17.2 MiB (window 4.6 + slab 0.6 + acc 4 + 2x4 out), which the
    window-only budget could not see."""
    return (
        _win_bytes(cin, kh, kw, th, tw, in_item)
        + _wslab_bytes(cin, kh, kw, tco, w_item)
        + th * tw * tco * 4
        + 2 * th * tw * tco * out_item
    )


def pallas_conv_eligible(cin: int, cout: int | None = None, kh: int = 3,
                         kw: int = 3, tco: int = _DEFAULT_TCO,
                         itemsize: int = 2) -> bool:
    """True when the kernel's VMEM scratch fits its caps — the dispatch-time
    check mirroring the wrapper's trace-time errors.  Two bounds:

    - weight slab [kh, kw, Cin, tco] within ``_WSLAB_CAP``; when ``cout`` is
      given, the backward dx conv's io-swapped slab [kh, kw, Cout, tco] must
      fit too (``_bwd`` runs the same kernel with Cin/Cout exchanged);
    - input window within ``_WINDOW_BUDGET`` at the SMALLEST H tile (th=1) —
      tall-kernel deep-Cin shapes (e.g. 7x1 at Cin ~4k) can pass the slab cap
      yet have no fitting window, which previously surfaced as an opaque
      Mosaic allocation error instead of a clean lax.conv fallback;
    - the TOTAL per-grid-point model (window + slab + accumulator +
      double-buffered out block, ``_vmem_total_bytes``) within the VMEM
      pool at th=1 — two under-cap pieces can still sum past the core."""
    ok = (
        _wslab_bytes(cin, kh, kw, tco, itemsize) <= _WSLAB_CAP
        and _win_bytes(cin, kh, kw, 1, _DEFAULT_TW, itemsize) <= _WINDOW_BUDGET
        and _vmem_total_bytes(cin, kh, kw, 1, _DEFAULT_TW, tco, itemsize,
                              itemsize, itemsize) <= _VMEM_BYTES
    )
    if cout is not None:
        ok = ok and pallas_conv_eligible(cout, None, kh, kw, tco, itemsize)
    return ok


@functools.partial(
    jax.jit, static_argnames=(
        "th", "tw", "tco", "interpret", "out_dtype", "fuse_relu",
        "stat_window",
    )
)
def halo_conv2d(
    x: jax.Array,
    w: jax.Array,
    th: int = 64,
    tw: int = 128,
    tco: int = _DEFAULT_TCO,
    out_dtype=None,
    interpret: bool = False,
    fuse_relu: bool = False,
    stat_window=None,
):
    """VALID stride-1 conv consuming a pre-exchanged margin.

    x: [N, H + kh-1, W + kw-1, Cin] (margin already present — halo-exchanged
       under SP, or ``jnp.pad`` for the single-device case);
    w: [kh, kw, Cin, Cout].  Returns [N, H, W, Cout].

    ``th`` is an upper bound: it halves until the full-Cin input window fits
    the VMEM budget (Cin is never chunked — see the WAR-hazard note on
    ``_kernel``).

    ``fuse_relu`` applies ReLU to the input window in VMEM (one pass, no
    HBM round-trip for the pre-activation).  ``stat_window=(h0,h1,w0,w1)``
    (out coords) additionally returns fp32 partial BN statistics
    ``(y, sum, sumsq)`` of the cast output over that window, summed over
    batch/tiles to shape [Cout] — the epilogue that lets the kernel compete
    with XLA's conv+BN+ReLU fusion at step level (VERDICT r4 task 5).
    """
    n, hp, wp, cin = x.shape
    kh, kw, wcin, cout = w.shape
    assert wcin == cin, (wcin, cin)
    h, wid = hp - (kh - 1), wp - (kw - 1)
    assert h > 0 and wid > 0, (x.shape, w.shape)
    out_dtype = out_dtype or x.dtype

    cin_p = _cpad(cin)
    wslab = _wslab_bytes(cin, kh, kw, tco, w.dtype.itemsize)
    if wslab > _WSLAB_CAP:
        raise ValueError(
            f"pallas halo_conv2d: weight slab {wslab} B for cin={cin} "
            f"kh*kw={kh * kw} exceeds the VMEM cap {_WSLAB_CAP} B — use "
            f"lax.conv for this layer (pallas_conv_eligible gates dispatch)"
        )
    # Narrow images need no full-width W tile: clamping tw to the real width
    # keeps deep-Cin narrow shapes inside the window budget (the gate stays
    # conservative at tw=128 — it has no W — so dispatch merely declines
    # them; direct callers get the capability).
    tw = min(tw, max(wid, 8))
    while th > 1 and _win_bytes(cin, kh, kw, th, tw, x.dtype.itemsize) > _WINDOW_BUDGET:
        th //= 2
    if _win_bytes(cin, kh, kw, th, tw, x.dtype.itemsize) > _WINDOW_BUDGET:
        raise ValueError(
            f"pallas halo_conv2d: input window "
            f"{_win_bytes(cin, kh, kw, th, tw, x.dtype.itemsize)} B at the "
            f"minimum H tile (th={th}) for cin={cin} kh={kh} kw={kw} tw={tw} "
            f"exceeds the VMEM window budget {_WINDOW_BUDGET} B — use "
            f"lax.conv for this layer (pallas_conv_eligible gates dispatch)"
        )
    # Bound the TOTAL per-grid-point model, not just the window: the fp32
    # accumulator and the double-buffered output block scale with th too,
    # and at fp32 defaults (th=64, tw=tco=128) the sum exceeds the 16 MiB
    # pool even though window and slab are each under their caps — the
    # verifier's first full run surfaced exactly this (pallascheck
    # vmem-overbudget; see _vmem_total_bytes).
    out_item = jnp.dtype(out_dtype).itemsize
    while th > 1 and _vmem_total_bytes(
        cin, kh, kw, th, tw, tco, x.dtype.itemsize, w.dtype.itemsize,
        out_item,
    ) > _VMEM_BYTES:
        th //= 2
    if _vmem_total_bytes(cin, kh, kw, th, tw, tco, x.dtype.itemsize,
                         w.dtype.itemsize, out_item) > _VMEM_BYTES:
        raise ValueError(
            f"pallas halo_conv2d: per-grid-point VMEM total at the minimum "
            f"H tile (th={th}) for cin={cin} kh={kh} kw={kw} tw={tw} "
            f"tco={tco} exceeds the {_VMEM_BYTES} B pool — use lax.conv "
            f"for this layer (pallas_conv_eligible gates dispatch)"
        )
    cout_p = _round_up(cout, tco)
    h_p = _round_up(h, th)
    w_p = _round_up(wid, tw)
    # DMA window width rounded to the 8-row sublane tiling (Mosaic slice
    # alignment); the input's W is padded so the last tile's over-read of
    # (tww - tw - (kw-1)) columns stays in bounds.
    tww = _round_up(tw + kw - 1, 8)
    x_p = jnp.pad(
        x,
        ((0, 0), (0, h_p - h), (0, w_p + tww - tw - (kw - 1) - wid),
         (0, cin_p - cin)),
    )
    w_pd = jnp.pad(w, ((0, 0), (0, 0), (0, cin_p - cin), (0, cout_p - cout)))

    grid = (h_p // th, w_p // tw, cout_p // tco)
    # Under shard_map with vma checking, pallas_call must declare how its
    # output varies across mesh axes: the union of the inputs' vma.
    def _struct(shape, dtype):
        try:
            vma = frozenset(jax.typeof(x).vma) | frozenset(jax.typeof(w).vma)
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except (AttributeError, TypeError):
            return jax.ShapeDtypeStruct(shape, dtype)

    scratch = [
        pltpu.VMEM((th + kh - 1, tww, cin_p), x.dtype),
        pltpu.VMEM((kh, kw, cin_p, tco), w.dtype),
        pltpu.VMEM((th * tw, tco), jnp.float32),
        pltpu.SemaphoreType.DMA,
        pltpu.SemaphoreType.DMA,
    ]
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    o_spec = pl.BlockSpec(
        (th, tw, tco), lambda i, j, c: (i, j, c), memory_space=pltpu.VMEM
    )
    if stat_window is None:
        call = pl.pallas_call(
            functools.partial(
                _kernel, kh=kh, kw=kw, th=th, tw=tw, tww=tww, tco=tco,
                relu=fuse_relu,
            ),
            out_shape=_struct((h_p, w_p, cout_p), out_dtype),
            grid=grid,
            in_specs=in_specs,
            out_specs=o_spec,
            scratch_shapes=scratch,
            interpret=interpret,
        )
        y = jax.vmap(call, in_axes=(0, None))(x_p, w_pd)
        return y[:, :h, :wid, :cout]
    stat_shape = (grid[0], grid[1], cout_p)
    stat_spec = pl.BlockSpec(
        (1, 1, tco), lambda i, j, c: (i, j, c), memory_space=pltpu.VMEM
    )
    call = pl.pallas_call(
        functools.partial(
            _kernel_stats, kh=kh, kw=kw, th=th, tw=tw, tww=tww, tco=tco,
            relu=fuse_relu, win=tuple(stat_window),
        ),
        out_shape=(
            _struct((h_p, w_p, cout_p), out_dtype),
            _struct(stat_shape, jnp.float32),
            _struct(stat_shape, jnp.float32),
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=(o_spec, stat_spec, stat_spec),
        scratch_shapes=scratch,
        interpret=interpret,
    )
    y, s, ss = jax.vmap(call, in_axes=(0, None))(x_p, w_pd)
    return (
        y[:, :h, :wid, :cout],
        jnp.sum(s, axis=(0, 1, 2))[:cout],
        jnp.sum(ss, axis=(0, 1, 2))[:cout],
    )


def conv_flops(n: int, h: int, w: int, cin: int, cout: int, kh: int, kw: int) -> int:
    """MAC-based FLOPs of the VALID conv (2 flops per MAC)."""
    return 2 * n * h * w * cin * cout * kh * kw


# ---------------------------------------------------------------------------
# Differentiable wrapper: custom VJP so the kernel can train.
#
#   y[n,p,q,co] = Σ_{dy,dx,ci} x[n,p+dy,q+dx,ci] · w[dy,dx,ci,co]
#   dx[n,a,b,ci] = Σ ct[n,a-dy,b-dx,co] · w[dy,dx,ci,co]
#              = VALID conv of ct zero-padded by (kh-1, kw-1) with the
#                spatially-flipped, io-swapped kernel — the SAME primitive.
#   dw = XLA's conv-backprop-filter (via jax.vjp of the lax reference conv:
#        a full-spatial reduction that is not this kernel's shape).
# ---------------------------------------------------------------------------


def _auto_interpret(interpret: bool) -> bool:
    # Pallas TPU kernels need the interpreter on CPU hosts (tests / smoke).
    return interpret or jax.default_backend() == "cpu"


def _lax_valid_conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "VALID", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def halo_conv2d_t(x: jax.Array, w: jax.Array, interpret: bool = False) -> jax.Array:
    """Trainable (custom-VJP) form of :func:`halo_conv2d` with default tiles."""
    return halo_conv2d(x, w, interpret=_auto_interpret(interpret))


def _fwd(x, w, interpret):
    return halo_conv2d(x, w, interpret=_auto_interpret(interpret)), (x, w)


def _bwd(interpret, res, ct):
    x, w = res
    kh, kw = w.shape[0], w.shape[1]
    # dx: margin-consuming conv of the padded cotangent with flip+swap(w);
    # its output is exactly x's (padded-input) shape.
    ct_pad = jnp.pad(ct, ((0, 0), (kh - 1, kh - 1), (kw - 1, kw - 1), (0, 0)))
    w_t = jnp.flip(w, axis=(0, 1)).swapaxes(2, 3)
    if pallas_conv_eligible(w_t.shape[2], None, kh, kw, _DEFAULT_TCO,
                            ct.dtype.itemsize):
        dx = halo_conv2d(
            ct_pad, w_t.astype(ct.dtype), out_dtype=x.dtype,
            interpret=_auto_interpret(interpret),
        )
    else:
        # Swapped slab (Cin' = forward Cout) too big for VMEM: same math on
        # XLA's conv.  Reached only when halo_conv2d_t is called directly —
        # Conv2d's dispatch gate bounds both directions.
        dx = _lax_valid_conv(ct_pad, w_t.astype(ct.dtype)).astype(x.dtype)
    # dw: XLA's backprop-filter.  linear_transpose (the conv is linear in w)
    # avoids jax.vjp's throwaway primal forward on eager backward calls.
    w_t_fn = jax.linear_transpose(lambda w_: _lax_valid_conv(x, w_), w)
    (dw,) = w_t_fn(ct.astype(x.dtype))
    return dx, dw


halo_conv2d_t.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Fused relu→conv→BN-stats op (VERDICT r4 task 5: the kernel's one fair shot
# against XLA's conv+BN+ReLU fusion at step level).
#
#   (y, s, ss) = (conv(relu(x), w),
#                 Σ_win cast(y),  Σ_win cast(y)²)      win ⊂ out coords
#
# The ReLU rides the window DMA (no HBM pass for the pre-activation) and the
# statistics ride the accumulator cast (no re-read of y for BN's reduce).
# VJP (manual, no primal recompute):
#   dy_total = ct_y + 1_win·(ct_s + 2·y·ct_ss)
#   dx       = relu'(x) ⊙ conv(pad(dy_total), flip+swap(w))
#   dw       = conv-backprop-filter(relu(x), dy_total)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_relu_conv_bn_t(x: jax.Array, w: jax.Array, stat_window,
                         interpret: bool = False):
    """Trainable fused op: returns ``(y, sum, sumsq)`` with y = conv(relu(x),
    w) (VALID, margin-consuming) and fp32 statistics of the cast output over
    ``stat_window`` = (h0, h1, w0, w1) in out coords."""
    return halo_conv2d(
        x, w, interpret=_auto_interpret(interpret), fuse_relu=True,
        stat_window=tuple(stat_window),
    )


def _fused_fwd(x, w, stat_window, interpret):
    y, s, ss = fused_relu_conv_bn_t(x, w, stat_window, interpret)
    return (y, s, ss), (x, w, y)


def _fused_bwd(stat_window, interpret, res, cts):
    x, w, y = res
    ct_y, ct_s, ct_ss = cts
    h0, h1, w0, w1 = stat_window
    # Statistics backward: only the stat window receives the broadcast
    # ct_s and the 2·y·ct_ss term (fp32, then back to the compute dtype).
    y_win = y[:, h0:h1, w0:w1, :].astype(jnp.float32)
    dwin = ct_s[None, None, None, :] + 2.0 * y_win * ct_ss[None, None, None, :]
    dy = ct_y.astype(jnp.float32)
    dy = dy.at[:, h0:h1, w0:w1, :].add(dwin)
    dy = dy.astype(ct_y.dtype)
    # Conv backward — same structure as _bwd, plus the ReLU mask on dx and
    # relu(x) as the dw primal.
    kh, kw = w.shape[0], w.shape[1]
    ct_pad = jnp.pad(dy, ((0, 0), (kh - 1, kh - 1), (kw - 1, kw - 1), (0, 0)))
    w_t = jnp.flip(w, axis=(0, 1)).swapaxes(2, 3)
    if pallas_conv_eligible(w_t.shape[2], None, kh, kw, _DEFAULT_TCO,
                            dy.dtype.itemsize):
        dx_lin = halo_conv2d(
            ct_pad, w_t.astype(dy.dtype), out_dtype=x.dtype,
            interpret=_auto_interpret(interpret),
        )
    else:
        dx_lin = _lax_valid_conv(ct_pad, w_t.astype(dy.dtype)).astype(x.dtype)
    dx = jnp.where(x > 0, dx_lin, jnp.zeros((), dx_lin.dtype))
    xr = jax.nn.relu(x)
    w_t_fn = jax.linear_transpose(lambda w_: _lax_valid_conv(xr, w_), w)
    (dw,) = w_t_fn(dy.astype(xr.dtype))
    return dx, dw


fused_relu_conv_bn_t.defvjp(_fused_fwd, _fused_bwd)
