"""Sequence (context) parallelism: 1-D ghost-cell exchange + ring attention.

The reference is a CNN framework with no attention; its long-context analog
is spatial parallelism itself — partitioning the H/W "context" across devices
with ghost-region exchange (SURVEY §2a/§5: "the TPU build should implement
the halo/ghost primitive on a named mesh axis so that both 2-D image SP and
1-D sequence CP are instances of one mechanism").  This module is that 1-D
instance, built on the same ``halo_exchange_1d`` primitive:

- :func:`seq_ghost_exchange` — extend a [B, T_local, ...] sequence shard with
  neighbour tokens (ghost cells), the direct CP analog of the conv halo.
- :func:`ghost_conv1d` — "same"-padded 1-D convolution over a sharded
  sequence axis: exchange receptive-field overlap, then VALID conv — the
  sequence twin of layers.Conv2d's spatial mode.
- :func:`ring_attention` — exact blockwise attention over a sequence-sharded
  axis: K/V blocks circulate the ring via ``lax.ppermute`` while each device
  accumulates its queries' output with a numerically-stable online softmax
  (flash-attention style m/l/o running state).  One hop per step rides the
  ICI ring.  Per-device memory: O(T_local·H·D) on the default TPU path
  (``use_flash`` auto — the Pallas kernel in ops/pallas_attention.py keeps
  scores in VMEM tiles); the einsum fallback path materializes the per-hop
  O(T_local²·heads) score block and serves CPU + as the validation oracle.

All functions must be called inside shard_map with the named axis present.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import pcast

from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_1d


def seq_ghost_exchange(
    x: jax.Array,
    axis_name: str,
    n: int,
    lo: int,
    hi: int,
    dim: int = 1,
) -> jax.Array:
    """Extend the local sequence shard with `lo` trailing tokens of the
    previous shard and `hi` leading tokens of the next (zeros at the global
    sequence boundary — exactly the conv halo's zero-padding semantics)."""
    return halo_exchange_1d(x, dim, axis_name, n, HaloSpec(lo, hi))


def ghost_conv1d(
    x: jax.Array,
    kernel: jax.Array,
    axis_name: Optional[str],
    n: int,
    stride: int = 1,
) -> jax.Array:
    """1-D "same" convolution over a sequence-sharded [B, T, C] tensor.

    kernel: [K, C_in, C_out].  With `axis_name` None this is a plain padded
    conv; sharded, the (K-1)//2 overlap is ghost-exchanged and the conv runs
    VALID — bit-identical to the unsharded op (tests/test_ring.py)."""
    k = kernel.shape[0]
    lo, hi = (k - 1) // 2, k - 1 - (k - 1) // 2
    if axis_name is None:
        pad = ((lo, hi),)
    else:
        x = seq_ghost_exchange(x, axis_name, n, lo, hi)
        pad = ((0, 0),)
    return lax.conv_general_dilated(
        x, kernel.astype(x.dtype),
        window_strides=(stride,),
        padding=pad,
        dimension_numbers=("NHC", "HIO", "NHC"),
    )


def _resolve_flash(setting: Optional[bool]) -> bool:
    """None = auto: the Pallas block kernel (ops/pallas_attention.py) is a
    Mosaic program — on for TPU backends, einsum path elsewhere."""
    if setting is not None:
        return setting
    from mpi4dl_tpu.config import is_tpu_backend

    return is_tpu_backend()


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str],
    n: int,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
) -> jax.Array:
    """Exact attention over a sequence sharded on `axis_name` ([B, T_local,
    H, D] per device).  K/V blocks rotate around the ring; each device folds
    every block into its queries' output with the online-softmax update

        m' = max(m, rowmax(s));  c = exp(m - m')
        l' = l * c + rowsum(exp(s - m'));  o' = o * c + exp(s - m') @ v_blk

    which is invariant to block arrival order, so the result equals
    single-device softmax(QKᵀ)V exactly (up to fp accumulation).  `causal`
    masks by GLOBAL token position (block index from lax.axis_index).
    With `axis_name` None, computes plain (optionally causal) attention.
    """
    b, t, h, d = q.shape
    sc = scale if scale is not None else 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32) * sc

    def block_scores(kblk, q_pos, k_pos):
        # [B, H, Tq, Tk]
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kblk.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        return s

    flash = _resolve_flash(use_flash)

    if axis_name is None:
        if flash:
            from mpi4dl_tpu.ops.pallas_attention import flash_attention_local

            return flash_attention_local(
                q, k, v, causal=causal, scale=scale, interpret=interpret
            )
        s = block_scores(k, jnp.arange(t, dtype=jnp.int32), jnp.arange(t, dtype=jnp.int32))
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v.astype(jnp.float32)
        )
        return out.astype(q.dtype)

    if flash:
        return _ring_attention_flash(
            q, k, v, axis_name, n, causal,
            float(scale) if scale is not None else 1.0 / float(d) ** 0.5,
            interpret,
        )

    my = lax.axis_index(axis_name)
    q_pos = my * t + jnp.arange(t, dtype=jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]  # ring: block from prev device

    def body(carry, _):
        kblk, vblk, src, m, l, o = carry
        with scope("ring_step_compute"):
            k_pos = src * t + jnp.arange(t, dtype=jnp.int32)
            s = block_scores(kblk, q_pos, k_pos)  # [B, H, Tq, Tk]
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            # exp(-inf - -inf) guard: rows with no valid keys yet keep m=-inf.
            c = jnp.exp(jnp.where(jnp.isfinite(m), m - m_new, -jnp.inf))
            p = jnp.exp(s - m_new[..., None])
            p = jnp.where(jnp.isfinite(s), p, 0.0)
            l_new = l * c + jnp.sum(p, axis=-1)
            o_new = o * c[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p, vblk.astype(jnp.float32)
            )
        with scope("ring_step_hop"):
            kblk = lax.ppermute(kblk, axis_name, perm)
            vblk = lax.ppermute(vblk, axis_name, perm)
            src = lax.ppermute(src, axis_name, perm)
        return (kblk, vblk, src, m_new, l_new, o_new), None

    # Accumulators start device-uniform but become device-varying in the loop:
    # mark them varying up front (shard_map vma tracking requires carry types
    # to be loop-invariant; same pattern as the pipeline scans).
    vcast = lambda t_: pcast(t_, (axis_name,), to="varying")
    m0 = vcast(jnp.full((b, h, t), -jnp.inf, jnp.float32))
    l0 = vcast(jnp.zeros((b, h, t), jnp.float32))
    o0 = vcast(jnp.zeros((b, h, t, d), jnp.float32))
    (_, _, _, _, l, o), _ = lax.scan(body, (k, v, my, m0, l0, o0), None, length=n)
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name, n, causal, scale, interpret):
    """Ring attention with the Pallas block kernel as the local compute.

    Same schedule as the einsum path (K/V rotate via ppermute, one hop per
    scan step) but each hop's block state comes from
    :func:`mpi4dl_tpu.ops.pallas_attention.block_flash` — scores exist only
    as VMEM tiles, so per-hop HBM traffic drops from O(T_local²·H) to
    O(T_local·D·H), the long-context enabler.  Exact: block states fold via
    the associative :func:`mlo_merge` (same update the einsum path applies
    inline), so results match it to fp accumulation order.
    """
    from mpi4dl_tpu.ops.pallas_attention import block_flash, mlo_merge

    b, t, h, d = q.shape
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    qf = fold(q)
    q_off = my * t

    def body(carry, _):
        kblk, vblk, src, m, l, o = carry

        def compute(m, l, o):
            blk = block_flash(  # all-positional: custom_vjp + nondiff args
                qf, fold(kblk), fold(vblk), q_off, src * t, causal, scale,
                256, 512, interpret,
            )
            return mlo_merge((o, m, l), blk)

        with scope("ring_step_compute"):
            if causal:
                # A source block entirely in this device's future (src > my)
                # contributes exactly zero through the mask guard (blk =
                # (0, -inf, 0), an mlo_merge identity) — skip the kernel for
                # those ~n/2 hops instead of computing a fully-masked block
                # (ADVICE r3).  shard_map is per-device code, so the varying
                # predicate legitimately branches per device.
                o, m, l = lax.cond(
                    src <= my, compute, lambda m, l, o: (o, m, l), m, l, o
                )
            else:
                o, m, l = compute(m, l, o)
        with scope("ring_step_hop"):
            kblk = lax.ppermute(kblk, axis_name, perm)
            vblk = lax.ppermute(vblk, axis_name, perm)
            src = lax.ppermute(src, axis_name, perm)
        return (kblk, vblk, src, m, l, o), None

    vcast = lambda t_: pcast(t_, (axis_name,), to="varying")
    from mpi4dl_tpu.ops.pallas_attention import _NEG_INF

    m0 = vcast(jnp.full((b * h, t), _NEG_INF, jnp.float32))
    l0 = vcast(jnp.zeros((b * h, t), jnp.float32))
    o0 = vcast(jnp.zeros((b * h, t, d), jnp.float32))
    (_, _, _, _, l, o), _ = lax.scan(
        body, (k, v, my, m0, l0, o0), None, length=n
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3).astype(q.dtype)
