"""Pallas kernel registry — the enrollment point of the static verifier.

Every hand-written Pallas kernel in ``mpi4dl_tpu/ops`` registers its public
entry here as one or more :class:`KernelCase` rows: a representative trace
(shapes chosen so every grid dimension has interior AND edge points) for
each dtype/variant path the engines dispatch.  The verifier
(``mpi4dl_tpu/analysis/pallascheck``) traces each case on CPU, extracts the
``pallas_call`` specs from the jaxpr, and certifies grid/BlockSpec
soundness, the per-grid-point VMEM total, DMA/semaphore discipline and
accumulator-init coverage — see docs/analysis.md ("Pallas verifier").

Two things key off this module being the single registry:

- ``python -m mpi4dl_tpu.analysis pallascheck`` verifies exactly these
  cases, so a new kernel (ROADMAP item 2's halo-RDMA conv) is enrolled by
  adding a row — the gate covers it with no CI change;
- AST rule 12 ``unregistered-pallas-call`` statically parses THIS file's
  imports: a ``pl.pallas_call`` in any ``mpi4dl_tpu`` module not imported
  here is a violation, so a kernel cannot ship unverified.

Cases must trace with ``jax.make_jaxpr`` on a CPU host (no TPU compile, no
real mesh); keep shapes small — the verifier enumerates the full grid.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence, Tuple

# Imports below double as rule-12 registration: a module whose kernels are
# verified must be imported here (statically parsed, never executed by the
# analyzer).
from mpi4dl_tpu.ops.pallas_attention import block_flash
from mpi4dl_tpu.ops.pallas_conv import halo_conv2d


@dataclasses.dataclass(frozen=True)
class KernelCase:
    """One verified trace of a registered kernel.

    ``build()`` returns ``(fn, args)`` such that ``jax.make_jaxpr(fn)(*args)``
    contains at least one ``pallas_call`` equation.  ``ring_size``, when
    set, declares the remote-DMA neighbor topology the kernel's
    ``make_async_remote_copy`` ``device_id`` map must be bijective against
    (None = the kernel performs no remote copies; a remote copy in such a
    case is itself a finding).
    """

    name: str
    build: Callable[[], Tuple[Callable, tuple]]
    ring_size: Optional[int] = None


def _conv_case(dtype: str, fused: bool):
    def build():
        import jax.numpy as jnp

        dt = jnp.dtype(dtype)
        # Grid (th-tiles, 2, 3): every grid dim has an edge and the Cout
        # dim an interior point; cout=300 exercises the lane-pad tail.
        x = jnp.zeros((1, 130, 258, 8), dt)
        w = jnp.zeros((3, 3, 8, 300), dt)
        if fused:
            # Margin-excluding stat window, as the D2 dispatch passes it.
            fn = lambda x, w: halo_conv2d(  # noqa: E731
                x, w, fuse_relu=True, stat_window=(1, 127, 2, 254)
            )
        else:
            fn = halo_conv2d
        return fn, (x, w)

    variant = "fused_stats:" if fused else ""
    return KernelCase(name=f"halo_conv2d:{variant}{dtype}", build=build)


def _flash_case(dtype: str, causal: bool):
    def build():
        import jax.numpy as jnp

        dt = jnp.dtype(dtype)
        # Grid (2, 3, 3): batch·heads edge-only, q/k dims with interior
        # points; Tk=300 exercises the padded-key masking tail.
        q = jnp.zeros((2, 48, 64), dt)
        k = jnp.zeros((2, 300, 64), dt)
        v = jnp.zeros((2, 300, 64), dt)
        z = jnp.zeros((), jnp.int32)
        fn = lambda q, k, v: block_flash(  # noqa: E731
            q, k, v, z, z, causal, 0.125, 16, 128, False
        )
        return fn, (q, k, v)

    variant = "causal:" if causal else ""
    return KernelCase(name=f"block_flash:{variant}{dtype}", build=build)


# The raw (fp32) path and the bf16 compute path the mixed-precision/quant
# engines dispatch (quant/kernels.py itself is pure jnp — no pallas_call,
# which rule 12 verifies stays true).
REGISTRY: Tuple[KernelCase, ...] = (
    _conv_case("float32", fused=False),
    _conv_case("bfloat16", fused=False),
    _conv_case("float32", fused=True),
    _conv_case("bfloat16", fused=True),
    _flash_case("float32", causal=False),
    _flash_case("bfloat16", causal=True),
)


def registry_case(name: str) -> KernelCase:
    for case in REGISTRY:
        if case.name == name:
            return case
    raise KeyError(
        f"no registered kernel case {name!r}; have "
        f"{[c.name for c in REGISTRY]}"
    )


def case_names(kernels: Optional[Sequence[str]] = None) -> Tuple[str, ...]:
    """Registered case names, optionally filtered by kernel prefix (the
    part before the first ``:``) or exact case name."""
    names = tuple(c.name for c in REGISTRY)
    if kernels is None:
        return names
    wanted = set(kernels)
    out = tuple(
        n for n in names if n in wanted or n.split(":", 1)[0] in wanted
    )
    return out
