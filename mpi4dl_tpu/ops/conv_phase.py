"""Phase-decomposed input gradient for strided convolutions.

XLA computes the input grad of a stride-s conv as a conv with
``lhs_dilation=s`` — on TPU that materializes a zero-interleaved cotangent
(reshape/broadcast "data formatting" chains) and, at some shapes, chained
gather fusions.  Profiling the AmoebaNet-D 1024² bs1 step (PERF_NOTES r4)
attributed a large share of its 52.7 ms/step of backward-conv time plus
much of the 55.8 ms/step "data formatting" mass to exactly this machinery
(the reference framework never faces the issue: cuDNN has native strided
backward kernels, ``/root/reference/src/torchgems/mp_pipeline.py`` just
calls ``loss.backward()``).

Here dx is built WITHOUT zero-stuffing.  Writing padded input row
b = s·q + φ (phase φ ∈ [0, s)), the transpose of the forward

    y[p] = Σ_i x_pad[p·s + i] · w[i]

restricted to phase φ is

    dx_pad[s·q + φ] = Σ_m w[s·m + φ] · ct[q − m]

i.e. phase φ of dx_pad is the *correlation of the un-dilated cotangent
with the φ-subsampled kernel* — a plain stride-1 VALID conv of the
(Lφ−1)-padded cotangent with the flipped, io-swapped sub-kernel, exactly
the stride-1 transpose rule.  The s·s phase outputs interleave back with
ONE reshape.  FLOPs are identical to the dilated form (Σφ Lφ = k per dim);
what disappears is the gather/interleave traffic.

The weight gradient stays on XLA's conv-backprop-filter (measured
compute-bound at 36–52 TFLOPs in the same trace — not the problem).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_DIMNUMS = ("NHWC", "HWIO", "NHWC")


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _phase_dx(ct, w, strides, padding, x_shape, x_dtype):
    """dx for y = conv(x, w, strides, padding) given cotangent ct.

    ct: [N, OH, OW, Cout]; w: [KH, KW, Cin, Cout]; padding: ((phl, phh),
    (pwl, pwh)); x_shape: the forward input's [N, H, W, Cin].
    """
    n, oh, ow, cout = ct.shape
    kh, kw, cin, _ = w.shape
    sh, sw = strides
    (phl, phh), (pwl, pwh) = padding
    h, wid = x_shape[1], x_shape[2]
    hp, wp = h + phl + phh, wid + pwl + pwh
    hr, wr = _ceil_div(hp, sh), _ceil_div(wp, sw)

    wf = w.astype(ct.dtype)
    rows = []
    for fh in range(sh):
        cols = []
        lh = len(range(fh, kh, sh))
        # Valid q range for this phase: s·q + φ < hp.
        hq = _ceil_div(hp - fh, sh) if hp > fh else 0
        for fw in range(sw):
            lw = len(range(fw, kw, sw))
            wq = _ceil_div(wp - fw, sw) if wp > fw else 0
            if lh == 0 or lw == 0 or hq <= 0 or wq <= 0:
                cols.append(jnp.zeros((n, hr, wr, cin), ct.dtype))
                continue
            wsub = wf[fh::sh, fw::sw]                      # [lh, lw, cin, cout]
            wt = jnp.flip(wsub, axis=(0, 1)).swapaxes(2, 3)
            ctp = jnp.pad(ct, ((0, 0), (lh - 1, lh - 1), (lw - 1, lw - 1), (0, 0)))
            d = lax.conv_general_dilated(
                ctp, wt, (1, 1), "VALID", dimension_numbers=_DIMNUMS
            )                                              # [n, oh+lh-1, ow+lw-1, cin]
            # Crop to the phase's valid q range, then pad to the uniform
            # (hr, wr) grid.  hq can EXCEED the conv's extent when trailing
            # input rows are read by no window (h + 2p − k not divisible by
            # s) — those rows' grad is exactly zero, so the pad supplies it.
            d = d[:, : min(hq, d.shape[1]), : min(wq, d.shape[2]), :]
            d = jnp.pad(d, ((0, 0), (0, hr - d.shape[1]),
                            (0, wr - d.shape[2]), (0, 0)))
            cols.append(d)
        rows.append(jnp.stack(cols, axis=3))               # [n, hr, wr, sw, cin]
    dxp = jnp.stack(rows, axis=2)                          # [n, hr, sh, wr, sw, cin]
    dxp = dxp.reshape(n, hr * sh, wr * sw, cin)
    return dxp[:, phl : phl + h, pwl : pwl + wid, :].astype(x_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv2d_strided_t(x, w, strides, padding):
    """``lax.conv_general_dilated`` (NHWC/HWIO, groups=1) whose input grad
    uses the phase decomposition above.  ``strides``/``padding`` are static
    (tuple of ints / tuple of (lo, hi) pairs)."""
    return lax.conv_general_dilated(
        x, w, strides, padding, dimension_numbers=_DIMNUMS
    )


def _fwd(x, w, strides, padding):
    n, h, wid, c = x.shape
    # The residual is needed only by dw.  A tiny-channel x saved as-is is
    # stored in a channels-minor conv layout padded up to 42x (measured: the
    # C=3 stem input at 2048² held 2 GB across the whole backward,
    # PERF_NOTES r4); flattening (W, C) makes the saved buffer tile cleanly,
    # and the unflatten in _bwd is transient.
    xr = x.reshape(n, h, wid * c) if c < 128 else x
    return conv2d_strided_t(x, w, strides, padding), (xr, w)


def _bwd(strides, padding, res, ct):
    xr, w = res
    cin = w.shape[2]
    if xr.ndim == 3:
        n, h, wc = xr.shape
        x = xr.reshape(n, h, wc // cin, cin)
    else:
        x = xr
    dx = _phase_dx(ct, w, strides, padding, x.shape, x.dtype)
    # dw: XLA's backprop-filter (linear_transpose avoids a throwaway primal
    # forward on eager backward calls — same pattern as ops/pallas_conv).
    w_t_fn = jax.linear_transpose(
        lambda w_: lax.conv_general_dilated(
            x, w_, strides, padding, dimension_numbers=_DIMNUMS
        ),
        w,
    )
    (dw,) = w_t_fn(ct.astype(x.dtype))
    return dx, dw


conv2d_strided_t.defvjp(_fwd, _bwd)
