"""Pallas blockwise (flash) attention for the long-context path.

The 1-D sequence-parallel module (ops/ring.py) is exact ring attention:
K/V blocks circulate the ICI ring and each device folds blocks into its
queries' output with an online softmax.  Its local block compute, written
as einsums, materializes the [B, H, Tq, Tk] score tensor between the two
matmuls — O(T_local²) HBM traffic per hop, which becomes the long-context
ceiling (134 MB fp32 at T_local = 2048, B=1, H=8).  This module fuses that
block compute into a Pallas kernel in the flash-attention style: scores
live only as a [TQ, TK] VMEM tile between the QKᵀ and P·V matmuls.

Design (deliberately different from a monolithic flash attention):

- :func:`block_flash` returns the block's UNNORMALIZED partial state
  ``(o_hat, m, l)`` — the flash m/l/o triple — instead of a normalized
  output, because ring attention must keep folding further K/V blocks in.
- :func:`mlo_merge` is the associative combine of two partial states; the
  ring body merges each hop's block state into the running state (the same
  update ops/ring.py applies inline today, so results are bit-comparable).
- normalization (o / l) happens once, after the last block.

The kernel pipelines via BlockSpec index maps only (no manual DMA): grid =
(B·H, Tq tiles, Tk tiles), with the Tk dimension innermost so the fp32
accumulator scratch persists across it (zeroed at k==0, emitted at the
last k tile).  Causal masking is by GLOBAL token position: the q/k block
offsets arrive as scalar-prefetch arguments so one compiled kernel serves
every ring hop (the k offset is a traced, device-varying value).

Training: :func:`block_flash` carries a custom VJP whose backward is a
``lax.scan`` of einsum tiles over the Tk dimension — memory stays
O(TQ·TK) per step (never the full score matrix) while the matmuls stay on
the MXU.  Reference: the flash-attention backward recurrences; residuals
saved are (q, k, v, o_hat, m, l).

Used by :func:`mpi4dl_tpu.ops.ring.ring_attention` when ``use_flash``
resolves on (auto: TPU backends).  Interpret mode runs on CPU for tests.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi4dl_tpu.compat import pcast

_NEG_INF = -1e30  # large-negative instead of -inf: exp() of it is exactly 0
                  # and max() never produces nan from (-inf) - (-inf).
_LANES = 128


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _out_structs(operands, shapes_dtypes):
    """ShapeDtypeStructs carrying the operands' union vma — under shard_map
    with vma checking, pallas_call must declare how outputs vary across mesh
    axes (same pattern as ops/pallas_conv.py)."""
    try:
        vma = frozenset()
        for op in operands:
            vma = vma | frozenset(jax.typeof(op).vma)
        return [
            jax.ShapeDtypeStruct(s, d, vma=vma) for s, d in shapes_dtypes
        ]
    except (AttributeError, TypeError):
        return [jax.ShapeDtypeStruct(s, d) for s, d in shapes_dtypes]


def _kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
            acc, m_scr, l_scr, *, tq, tk, nk, causal, t_k_real):
    """One (bh, q-tile, k-tile) step.  Scratch (acc, m, l) persists across
    the innermost k dimension; outputs are written at the last k tile.
    ``t_k_real``: un-padded key count (static) — key slots past it are
    masked out so Tk padding contributes exactly nothing."""
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q = q_ref[0].astype(jnp.float32)            # [TQ, D] (pre-scaled)
    k = k_ref[0].astype(jnp.float32)            # [TK, D]
    s = jax.lax.dot_general(                    # [TQ, TK]
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    col = ki * tk + lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    if t_k_real % tk:
        s = jnp.where(col < t_k_real, s, _NEG_INF)
    if causal:
        qi = pl.program_id(1)
        q_pos = offs_ref[0] + qi * tq + lax.broadcasted_iota(
            jnp.int32, (tq, tk), 0
        )
        s = jnp.where(q_pos >= offs_ref[1] + col, s, _NEG_INF)

    m_prev = m_scr[:, 0]                        # [TQ]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    c = jnp.exp(m_prev - m_new)
    # Guard fully-masked rows: there m_new == _NEG_INF and the naive
    # exp(s - m_new) = exp(0) = 1 would count every masked key (the classic
    # flash pitfall — causal ring hops from later devices mask whole rows).
    p = jnp.where(
        s > _NEG_INF * 0.5, jnp.exp(s - m_new[:, None]), 0.0
    )                                           # [TQ, TK]
    l_new = l_scr[:, 0] * c + jnp.sum(p, axis=-1)
    acc[:] = acc[:] * c[:, None] + jax.lax.dot_general(
        p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(ki == nk - 1)
    def _():
        o_ref[0] = acc[:].astype(o_ref.dtype)
        m_ref[0] = m_scr[...].astype(m_ref.dtype)
        l_ref[0] = l_scr[...].astype(l_ref.dtype)


def _any_vma(*arrays) -> bool:
    try:
        return any(frozenset(jax.typeof(a).vma) for a in arrays)
    except (AttributeError, TypeError):
        return False


def _block_flash_fwd_impl(q, k, v, q_off, k_off, *, causal, scale,
                          tq, tk, interpret):
    """Pallas forward.  q: [BH, Tq, D]; k, v: [BH, Tk_total, D] (fp32/bf16).
    Returns (o_hat [BH, Tq, D] fp32, m [BH, Tq] fp32, l [BH, Tq] fp32)."""
    if interpret and _any_vma(q, k, v, q_off, k_off):
        # Interpret-mode pallas_call under shard_map trips the vma checker
        # (its BlockSpec emulation dynamic_slices varying operands with
        # uniform grid indices).  CPU tests of the SHARDED ring path run the
        # einsum reference instead — identical math; the kernel itself is
        # pinned by the uniform-context interpret tests and TPU validation.
        return _reference_mlo(q, k, v, q_off, k_off, causal, scale)
    bh, t_q, d = q.shape
    _, t_k, _ = k.shape
    tq = min(tq, _round_up(t_q, 8))
    tk = min(tk, _round_up(t_k, 128))
    tq_p = _round_up(t_q, tq)
    tk_p = _round_up(t_k, tk)
    d_p = _round_up(d, _LANES)
    qp = jnp.pad(q.astype(jnp.float32) * scale,
                 ((0, 0), (0, tq_p - t_q), (0, d_p - d)))
    kp = jnp.pad(k, ((0, 0), (0, tk_p - t_k), (0, d_p - d)))
    vp = jnp.pad(v, ((0, 0), (0, tk_p - t_k), (0, d_p - d)))
    # Padded key slots (a q·0 = 0 score would pollute m/l) are masked inside
    # the kernel by local column id against the static t_k.
    nq, nk = tq_p // tq, tk_p // tk
    offs = jnp.stack([q_off, k_off]).astype(jnp.int32)

    grid = (bh, nq, nk)
    kern = pl.pallas_call(
        functools.partial(_kernel, tq=tq, tk=tk, nk=nk, causal=causal,
                          t_k_real=t_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, tq, d_p), lambda b, i, j, offs: (b, i, 0)),
                pl.BlockSpec((1, tk, d_p), lambda b, i, j, offs: (b, j, 0)),
                pl.BlockSpec((1, tk, d_p), lambda b, i, j, offs: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, tq, d_p), lambda b, i, j, offs: (b, i, 0)),
                pl.BlockSpec((1, tq, _LANES), lambda b, i, j, offs: (b, i, 0)),
                pl.BlockSpec((1, tq, _LANES), lambda b, i, j, offs: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((tq, d_p), jnp.float32),
                pltpu.VMEM((tq, _LANES), jnp.float32),
                pltpu.VMEM((tq, _LANES), jnp.float32),
            ],
        ),
        out_shape=_out_structs(
            (qp, kp, vp, offs),
            [
                ((bh, tq_p, d_p), jnp.float32),
                ((bh, tq_p, _LANES), jnp.float32),
                ((bh, tq_p, _LANES), jnp.float32),
            ],
        ),
        interpret=interpret,
    )
    o, m, l = kern(offs, qp, kp, vp)
    d_out = q.shape[-1]
    return o[:, :t_q, :d_out], m[:, :t_q, 0], l[:, :t_q, 0]


def _reference_mlo(q, k, v, q_off, k_off, causal, scale):
    """Einsum reference of the block partial state (for VJP + tests)."""
    qf = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqd,bkd->bqk", qf, k.astype(jnp.float32))
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        q_pos = q_off + jnp.arange(t_q, dtype=jnp.int32)
        k_pos = k_off + jnp.arange(t_k, dtype=jnp.int32)
        s = jnp.where(q_pos[:, None] >= k_pos[None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))
    return o, m, l


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9)
)
def block_flash(q, k, v, q_off, k_off, causal=False, scale=1.0,
                tq=256, tk=512, interpret=False):
    """Unnormalized flash partial state of one attention block.

    q: [BH, Tq, D]; k, v: [BH, Tk, D]; ``q_off``/``k_off``: scalar GLOBAL
    position offsets (traced values allowed — they ride scalar prefetch).
    Returns ``(o_hat, m, l)`` with ``o_hat = exp(s - m) @ v`` and
    ``l = rowsum(exp(s - m))``; combine across blocks with
    :func:`mlo_merge`, finish with ``o_hat / l``.
    """
    return _block_flash_fwd_impl(
        q, k, v, q_off, k_off, causal=causal, scale=scale,
        tq=tq, tk=tk, interpret=interpret,
    )


def _block_flash_fwd(q, k, v, q_off, k_off, causal, scale, tq, tk, interpret):
    o, m, l = block_flash(q, k, v, q_off, k_off, causal, scale, tq, tk,
                          interpret)
    return (o, m, l), (q, k, v, q_off, k_off, o, m, l)


def _block_flash_bwd(causal, scale, tq, tk, interpret, res, cts):
    """Blockwise backward: a scan over Tk tiles of einsum blocks — never
    materializes the [Tq, Tk_total] score matrix.

    With ô = P·V, l = rowsum(P), P = exp(s - m) (m treated as a constant
    plateau — its cotangent is zero almost everywhere):
        dP = dô Vᵀ + dl·1ᵀ ;  ds = P ⊙ dP
        dq = ds K · scale ;  dk = dsᵀ Q · scale ;  dv = Pᵀ dô
    """
    q, k, v, q_off, k_off, o, m, l = res
    do, dm, dl = cts  # dm is zero a.e.; fold dl into dP
    del o, dm
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    do = do.astype(jnp.float32)
    dl = dl.astype(jnp.float32)
    nk = max(1, (t_k + tk - 1) // tk)
    tk_c = _round_up(t_k, nk) // nk if t_k else t_k
    # pad Tk to an even tile split for the scan
    tk_pad = nk * tk_c - t_k
    kf_p = jnp.pad(kf, ((0, 0), (0, tk_pad), (0, 0)))
    vf_p = jnp.pad(vf, ((0, 0), (0, tk_pad), (0, 0)))
    k_ids = jnp.arange(nk * tk_c, dtype=jnp.int32)
    q_pos = q_off + jnp.arange(t_q, dtype=jnp.int32)

    def tile(carry, inp):
        dq_acc, = carry
        kt, vt, ids = inp  # [BH, tk_c, D], [BH, tk_c, D], [tk_c]
        s = jnp.einsum("bqd,bkd->bqk", qf, kt)
        mask = (ids < t_k)[None, :]
        if causal:
            mask = mask & (q_pos[:, None] >= (k_off + ids)[None, :])
        s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - m[..., None]), 0.0)
        dp = jnp.einsum("bqd,bkd->bqk", do, vt) + dl[..., None]
        ds = p * dp
        dq_acc = dq_acc + jnp.einsum("bqk,bkd->bqd", ds, kt)
        dkt = jnp.einsum("bqk,bqd->bkd", ds, qf)
        dvt = jnp.einsum("bqk,bqd->bkd", p, do)
        return (dq_acc,), (dkt, dvt)

    kts = kf_p.reshape(bh, nk, tk_c, -1).transpose(1, 0, 2, 3)
    vts = vf_p.reshape(bh, nk, tk_c, -1).transpose(1, 0, 2, 3)
    idts = k_ids.reshape(nk, tk_c)
    dq0 = jnp.zeros((bh, t_q, d), jnp.float32)
    # Under shard_map the accumulator becomes device-varying inside the
    # scan; its initial value must be marked varying up front.
    try:
        vma = frozenset()
        for a in (q, k, v, do):
            vma = vma | frozenset(jax.typeof(a).vma)
        if vma:
            dq0 = pcast(dq0, tuple(vma), to="varying")
    except (AttributeError, TypeError):
        pass
    (dq,), (dks, dvs) = lax.scan(tile, (dq0,), (kts, vts, idts))
    dk = dks.transpose(1, 0, 2, 3).reshape(bh, nk * tk_c, -1)[:, :t_k]
    dv = dvs.transpose(1, 0, 2, 3).reshape(bh, nk * tk_c, -1)[:, :t_k]
    # Integer (position-offset) primals take float0 cotangents.
    import numpy as np

    f0 = np.zeros((), jax.dtypes.float0)
    return (
        (dq * scale).astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
        f0, f0,
    )


block_flash.defvjp(_block_flash_fwd, _block_flash_bwd)


def mlo_merge(state_a, state_b):
    """Associative combine of two flash partial states (o, m, l)."""
    o1, m1, l1 = state_a
    o2, m2, l2 = state_b
    m = jnp.maximum(m1, m2)
    c1 = jnp.exp(m1 - m)
    c2 = jnp.exp(m2 - m)
    return (
        o1 * c1[..., None] + o2 * c2[..., None],
        m,
        l1 * c1 + l2 * c2,
    )


def flash_attention_local(q, k, v, causal=False, scale=None,
                          interpret=False):
    """Single-device exact attention via the block kernel.

    q, k, v: [B, T, H, D] (the ring module's layout).  Returns [B, T, H, D]
    in q.dtype.  Memory: never materializes [T, T] scores.
    """
    b, t, h, d = q.shape
    sc = scale if scale is not None else float(1.0 / (d ** 0.5))
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    zero = jnp.zeros((), jnp.int32)
    o, m, l = block_flash(
        fold(q), fold(k), fold(v), zero, zero, causal, sc, 256, 512,
        interpret,
    )
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3).astype(q.dtype)
