"""Stripe-wise backward through spatial-region blocks.

The 8K flagship's O(parts) memory lives in the SPATIAL phase: every extra
micro-batch widens the per-device chunk that flows through the SP region,
and during the region's backward each block's recompute holds its full
working set — the r5-era measurement was ~19.5 GB/device per extra
pipeline part, capping the flagship at parts=2 and a 33% 1F1B bubble
(PERF_NOTES "8K readiness re-run"; re-measured at HEAD the su=17 slope
is 4.05 GB/part, and the trail is the parts=8 blocker at the deep su=22
placement: 120.1 GB plain vs 81.6 striped — PERF_NOTES "stripe-wise
backward").

This module is the buy-back.  A block's stride-1 bottleneck branch runs —
forward AND backward — one H-stripe at a time:

- the run's accumulated halo (``ops/d2.accumulated_halo``) is realized
  ONCE up front: a real :func:`halo_exchange_2d` pull on spatially sharded
  dims (zeros at the global border), a zero-pad on an unsharded H — the
  halo-D2 pad-once border semantics in both cases;
- the margined tile is then processed by a ``lax.map`` over H stripes
  whose body is wrapped in ``jax.checkpoint``: the scan's transpose
  re-executes each stripe's forward and transposes it in place, so the
  BACKWARD working set is one stripe's internals plus the input-cotangent
  accumulator — not the full-size intermediate trail the plain per-cell
  remat holds.  The margined input is a scan constant (saved once, never
  stacked), which is what makes the residual cost O(stripe) instead of
  O(H);
- the scan additionally *serializes* the stripe recomputes, denying XLA's
  scheduler the concurrent-recompute pile-up measured behind the
  ``MPI4DL_1F1B_CELL_REMAT`` pathology (docs/pipeline.md).

Semantics are exactly the H-striped layer-run's (ops/hstripe_conv.py),
generalized to active spatial sharding: pad-once borders (the reference's
own D2 trade) and per-stripe train-mode BatchNorm statistics, with
``MPI4DL_HSTRIPE_EXACT=1`` buying bit-parity global statistics via the
stripewise stat cascade — here extended with cross-tile psum over the real
mesh axes and W-margin exclusion.  Everything is opt-in behind
``MPI4DL_STRIPE_BWD=1`` (config.HATCHES); default-off engines are
bit-identical.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.layer_ctx import SpatialCtx
from mpi4dl_tpu.mesh import AXIS_SPH
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_2d

# Per-stripe working-set budget: the stripe count is sized so one stripe's
# widest intermediate stays under this many bytes (whole chunk, all batch
# rows).  MPI4DL_STRIPE_BUDGET overrides for tuning; the engagement gate is
# simply "more than one stripe would be needed", so small programs never
# change shape.
_STRIPE_BUDGET_DEFAULT = 64 * 1024 * 1024


def stripe_bwd_mode() -> str:
    """The ``MPI4DL_STRIPE_BWD`` hatch (config.HATCHES), read at dispatch
    (trace) time so A/B scripts can toggle it between step builds:

    - ``"0"``/unset — off (default; engines bit-identical);
    - ``"1"`` — stripe SPATIALLY SHARDED blocks only (the SP region — the
      production mode).  Pipeline-tail cells are deliberately excluded:
      striped scans inside the 1F1B backward branches inflate the fused
      stage-dispatch conditional's buffer union catastrophically (measured
      76.7 vs 8.3 GB/device on the 2048² flagship proxy — the same
      conditional-union pathology MPI4DL_1F1B_CELL_REMAT documents on deep
      stages), while the SP region runs OUTSIDE the tick loop and takes
      the full win;
    - ``"all"`` — stripe every eligible block including unsharded/tail
      cells (exactness testing and single-device capacity experiments).
    """
    return os.environ.get("MPI4DL_STRIPE_BWD", "0")


def stripe_bwd_enabled() -> bool:
    return stripe_bwd_mode() in ("1", "all")


def _stripe_budget() -> int:
    try:
        v = int(os.environ.get("MPI4DL_STRIPE_BUDGET", "0"))
    except ValueError:
        v = 0
    return v if v > 0 else _STRIPE_BUDGET_DEFAULT


def _exact_stats() -> bool:
    """Shared with the single-device striped run: MPI4DL_HSTRIPE_EXACT=1
    replaces per-stripe train-mode BN statistics with GLOBAL ones (stripe
    cascade + cross-tile psum) — bit-parity with the unstriped pad-once
    run at ~one extra prefix forward per BatchNorm."""
    return os.environ.get("MPI4DL_HSTRIPE_EXACT") == "1"


def _run_halo(layers) -> Optional[Tuple[int, int]]:
    """(hh, hw) accumulated halo of a stride-1 premargin-capable run, or
    None when any layer is unsupported or strided (striping needs the
    stripe grid to align with the global conv grid, which stride-1 runs
    guarantee for any stripe height).  Trivial runs — nothing but
    elementwise/identity layers — are rejected: their backward holds no
    intermediate trail worth bounding, so striping them is pure scan
    overhead."""
    from mpi4dl_tpu.layers import BatchNorm, Conv2d, Pool2d
    from mpi4dl_tpu.ops.d2 import accumulated_halo, layer_d2_geometry

    acc = accumulated_halo(layers)
    if acc is None:
        return None
    for layer in layers:
        g = layer_d2_geometry(layer)
        if g[2] != 1 or g[3] != 1:
            return None
    if not any(isinstance(l, (Conv2d, BatchNorm, Pool2d)) for l in layers):
        return None
    return acc


def _widest_row_bytes(layers, x_shape, itemsize: int) -> int:
    """Bytes of ONE H row of the run's widest intermediate (whole chunk):
    the unit the stripe budget divides."""
    n, h, w, c = x_shape
    cmax = c
    for layer in layers:
        cmax = max(
            cmax,
            getattr(layer, "out_channels", 0),
            getattr(layer, "num_features", 0),
            getattr(layer, "lane_pad_out", 0),
            getattr(layer, "lane_pad", 0),
        )
    return n * w * cmax * itemsize


def _pick_stripes(h: int, row_bytes: int) -> Optional[Tuple[int, int]]:
    """(stripes, stripe_height) for a local true H extent, or None when the
    run should stay on the plain path: one stripe suffices, or ``h`` has no
    reasonable divisor (a ragged stripe is not an option — zero rows would
    enter per-stripe BN statistics, the same constraint as
    hstripe_layer_run)."""
    from mpi4dl_tpu.ops.hstripe_conv import _smallest_divisor_at_least

    want = max(1, -(-(h * row_bytes) // _stripe_budget()))
    if want <= 1:
        return None
    stripes = _smallest_divisor_at_least(h, want)
    if stripes == 1 or stripes == h or stripes > 4 * want:
        return None
    return stripes, h // stripes


def _sharded(sp: Optional[SpatialCtx]) -> Tuple[bool, bool]:
    sharded_h = bool(sp and sp.active and sp.axis_h and sp.grid_h > 1)
    sharded_w = bool(sp and sp.active and sp.axis_w and sp.grid_w > 1)
    return sharded_h, sharded_w


def _has_lane_pad(layers) -> bool:
    return any(
        getattr(l, "lane_pad", 0) or getattr(l, "lane_pad_in", 0)
        or getattr(l, "lane_pad_out", 0)
        for l in layers
    )


def _stripe_plan(layers, x_shape, ctx, itemsize: int):
    """THE dispatch gate, shared by :func:`stripe_run_eligible` and
    :func:`maybe_stripe_run`: hatch on, a plain 4-D activation, a stride-1
    premargin-capable run, not already inside a margin-carrying or striped
    context, halo no wider than the tile, and a stripe plan that actually
    shrinks the working set.  Returns ``(acc_halo, (stripes, stripe_h))``
    or None."""
    if not stripe_bwd_enabled():
        return None
    sp = ctx.spatial
    if sp is not None and (sp.halo_pre_exchanged or sp.stat_local):
        return None
    if stripe_bwd_mode() != "all" and not (sp is not None and sp.active):
        return None
    if len(x_shape) != 4:
        return None
    acc = _run_halo(layers)
    if acc is None:
        return None
    sharded_h, sharded_w = _sharded(sp)
    if sharded_h and acc[0] > x_shape[1]:
        return None  # halo wider than the tile: single-neighbour limit
    if sharded_w and acc[1] > x_shape[2]:
        return None
    plan = _pick_stripes(
        x_shape[1], _widest_row_bytes(layers, x_shape, itemsize)
    )
    if plan is None:
        return None
    return acc, plan


def stripe_run_eligible(layers, x_shape, ctx, itemsize: int = 4) -> bool:
    """Shape-only predicate over :func:`_stripe_plan` (no activation in
    hand, so the caller supplies ``itemsize``; the real dispatch uses the
    activation's own dtype)."""
    return _stripe_plan(layers, x_shape, ctx, itemsize) is not None


def maybe_stripe_run(layers, params_seq, x, ctx):
    """Dispatch helper: run ``layers`` stripe-wise when eligible, else
    return None so the caller takes its normal path."""
    got = _stripe_plan(layers, x.shape, ctx, x.dtype.itemsize)
    if got is None:
        return None
    acc, plan = got
    return stripe_layer_run(layers, params_seq, x, ctx, acc, plan)


def _margins_at(layers, upto: int, mh: int, mw: int) -> Tuple[int, int]:
    """Remaining (H, W) margin at the input of ``layers[upto]`` for a
    stride-1 run.  W margin only decays when one was realized (mw > 0 —
    i.e. W is spatially sharded); an unsharded W carries no margin and the
    layers pad W themselves."""
    from mpi4dl_tpu.ops.d2 import layer_d2_geometry

    for layer in layers[:upto]:
        ph, pw, _, _ = layer_d2_geometry(layer)
        mh -= ph
        if mw:
            mw -= pw
    return mh, mw


def _deposit_axes(ctx) -> Tuple[str, ...]:
    """Mesh axes a striped run's BN running-stat deposits must pmean over so
    the written-back values are provably replicated: the caller's extra stat
    axes, the REAL tile axes (per-stripe statistics vary per tile; under the
    exact cascade the psum'd stats make this pmean an identity), and the
    data axis — the same set BatchNorm._deposit_running would use."""
    names = list(ctx.bn_stat_axes)
    sp = ctx.spatial
    if sp is not None and sp.active:
        names += [a for a in (sp.axis_h, sp.axis_w) if a]
    if ctx.data_axis:
        names.append(ctx.data_axis)
    return tuple(names)


def stripe_layer_run(layers, params_seq, x, ctx, acc=None, plan=None):
    """Run a stride-1 layer sequence stripe-by-stripe over H with a
    stripe-bounded backward.

    x: [N, H, W, C] — the LOCAL tile under spatial sharding (any of
    unsharded / H / W / HxW grids), unpadded.  The run's accumulated halo is
    realized once (exchange on sharded dims, zero-pad on an unsharded H),
    then ``lax.map`` over H stripes of a ``jax.checkpoint``-wrapped body
    computes the output; each stripe consumes the margin via
    :func:`mpi4dl_tpu.ops.d2.apply_layers_premargin`.  AD through the scan
    gives the stripe-wise backward: per stripe, re-execute + transpose.

    Train-mode BN uses per-stripe statistics (margins excluded), or GLOBAL
    statistics under ``MPI4DL_HSTRIPE_EXACT=1`` via one stripewise stat
    cascade per BN (cross-tile psum'd when the ctx says bn_cross_tile).
    Running-stat deposits are stripe-averaged and pmean'd over the real
    mesh axes before reaching the caller's sink."""
    from mpi4dl_tpu.layers import BatchNorm as _BN
    from mpi4dl_tpu.ops.d2 import apply_layers_premargin

    sp = ctx.spatial
    sharded_h, sharded_w = _sharded(sp)
    if acc is None:
        acc = _run_halo(layers)
    assert acc is not None, "stripe_layer_run on an unsupported run"
    mh = acc[0]
    mw = acc[1] if sharded_w else 0
    n, h, w, c = x.shape
    if plan is None:
        plan = _pick_stripes(
            h, _widest_row_bytes(layers, x.shape, x.dtype.itemsize)
        )
    if plan is None:
        return None
    stripes, sh = plan

    # --- margin realization (pad-once, the halo-D2 border semantics) -----
    # Every scope here is prefixed ``stripe_bwd``: turning the hatch on must
    # drift compiled-artifact contracts ONLY in stripe_bwd scopes
    # (tests/test_stripe_bwd.py asserts the locality).
    with scope("stripe_bwd_halo"):
        if sharded_h or sharded_w:
            xp = halo_exchange_2d(
                x,
                HaloSpec.symmetric(mh if sharded_h else 0),
                HaloSpec.symmetric(mw),
                sp.axis_h, sp.axis_w, sp.grid_h, sp.grid_w,
                rep_h=sp.rep_h, rep_w=sp.rep_w,
            )
            if not sharded_h and mh:
                xp = jnp.pad(xp, ((0, 0), (mh, mh), (0, 0), (0, 0)))
        elif mh:
            xp = jnp.pad(x, ((0, 0), (mh, mh), (0, 0), (0, 0)))
        else:
            xp = x

    # --- inner context: margins pre-realized, H consumed stripe-wise -----
    # The H "axis" exists only for margin-consuming geometry: when H is not
    # really sharded a fake axis name stands in (no collective ever fires
    # on it — exchanges are pre-consumed, BN runs bn_cross_tile=False with
    # local deposits; statistics are handled below over the REAL axes).
    base_sp = sp if sp is not None else SpatialCtx()
    inner_sp = dataclasses.replace(
        base_sp,
        axis_h=base_sp.axis_h if sharded_h else AXIS_SPH,
        grid_h=base_sp.grid_h if sharded_h else max(stripes, 2),
        rep_h=base_sp.rep_h if sharded_h else 1,
        bn_cross_tile=False,
        stat_local=True,
        d2_mode=False,
        use_pallas_conv=False,
    )
    # data_axis/bn_stat_axes feed ONLY the running-stat deposit pmean
    # (BatchNorm._deposit_running; normalization statistics never read
    # them) — cleared here so per-stripe deposits inside the serialized
    # scan fire no collectives; the stripe-averaged deposit is pmean'd
    # over the full axis set once, below.
    inner_ctx = dataclasses.replace(
        ctx, spatial=inner_sp, bn_sink=None, remat_ops=False,
        data_axis=None, bn_stat_axes=(),
    )
    idx = jnp.arange(stripes, dtype=jnp.int32)

    # --- exact-stats cascade: fix every train-mode BN to GLOBAL stats ----
    eff_layers = list(layers)
    exact = _exact_stats() and ctx.train and not _has_lane_pad(layers)
    if exact:
        acc_dt = jnp.promote_types(jnp.float32, x.dtype)
        real_axes = (
            tuple(a for a in (sp.axis_h, sp.axis_w) if a)
            if (sp is not None and sp.active and sp.bn_cross_tile)
            else ()
        )
        for j, layer in enumerate(layers):
            if not isinstance(layer, _BN):
                continue
            if j == 0:
                s = jnp.sum(x, axis=(0, 1, 2), dtype=acc_dt)
                ss = jnp.sum(jnp.square(x.astype(acc_dt)), axis=(0, 1, 2))
            else:
                mh_j, mw_j = _margins_at(eff_layers, j, mh, mw)

                def stat_piece(i, xbuf, ps, _j=j, _mh=mh_j, _mw=mw_j):
                    xs = lax.dynamic_slice_in_dim(
                        xbuf, i * sh, sh + 2 * mh, axis=1
                    )
                    y, mho, mwo = apply_layers_premargin(
                        eff_layers[:_j], ps[:_j], xs, inner_ctx, mh, mw
                    )
                    assert (mho, mwo) == (_mh, _mw), ((mho, mwo), (_mh, _mw))
                    t = y[:, _mh:_mh + sh, _mw:y.shape[2] - _mw or None]
                    return (
                        jnp.sum(t, axis=(0, 1, 2), dtype=acc_dt),
                        jnp.sum(jnp.square(t.astype(acc_dt)), axis=(0, 1, 2)),
                    )

                ck = jax.checkpoint(stat_piece)
                with scope("stripe_bwd_stats"):
                    sA, ssA = lax.map(lambda i: ck(i, xp, params_seq), idx)
                s, ss = jnp.sum(sA, axis=0), jnp.sum(ssA, axis=0)
            cnt = jnp.asarray(n * h * w, acc_dt)
            if real_axes:
                with scope("stripe_bwd_stats"):
                    # Count is a trace-time constant: static multiply, not a
                    # wire psum (psum(1, axes) folds to the axis-size
                    # product).
                    cnt = cnt * lax.psum(1, real_axes)
                    s = lax.psum(s, real_axes)
                    ss = lax.psum(ss, real_axes)
            mean = s / cnt
            var = jnp.maximum(ss / cnt - mean * mean, 0.0)
            from mpi4dl_tpu.ops.hstripe_conv import _FixedStatsBN

            eff_layers[j] = _FixedStatsBN(layer, mean, var, cnt)

    # --- output pass: checkpointed stripes under a serializing scan ------
    with_sink = ctx.bn_sink is not None

    def piece(i, xbuf, ps):
        xs = lax.dynamic_slice_in_dim(xbuf, i * sh, sh + 2 * mh, axis=1)
        if with_sink:
            inner: dict = {}
            cc = dataclasses.replace(inner_ctx, bn_sink=inner)
        else:
            inner, cc = None, inner_ctx
        y, mho, mwo = apply_layers_premargin(eff_layers, ps, xs, cc, mh, mw)
        assert mho == 0 and mwo == 0, (mho, mwo)
        # Reassembly below assumes W is preserved (stride-1 run).
        assert y.shape[1] == sh and y.shape[2] == w, (y.shape, sh, w)
        stats = (
            [inner.get(id(l)) for l in jax.tree.leaves(ps)]
            if inner is not None else []
        )
        return y, stats

    ck_piece = jax.checkpoint(piece)
    with scope("stripe_bwd_scan"):
        ys, stats = lax.map(lambda i: ck_piece(i, xp, params_seq), idx)
    if with_sink:
        names = _deposit_axes(ctx)
        for leaf, sarr in zip(jax.tree.leaves(params_seq), stats):
            if sarr is not None:
                v = jnp.mean(sarr, axis=0)
                if names:
                    with scope("stripe_bwd_stats"):
                        v = lax.pmean(v, names)
                ctx.bn_sink[id(leaf)] = v
    oc = ys.shape[-1]
    return ys.transpose(1, 0, 2, 3, 4).reshape(n, h, w, oc)
