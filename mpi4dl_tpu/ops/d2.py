"""D2 fused halo exchange: one accumulated exchange per layer run.

The reference's "Design-2" replaces per-conv halo exchange with one larger
exchange per block of ``fused_layers`` convs, the convs then running halo-free
and shrinking the tile (``src/models/resnet_spatial_d2.py:416-460``,
accumulated-halo formulas ``:651-697``); its charts show ~1.7-2x throughput
from this at 1024-2048 px (BASELINE.md).  The reference implements it as
separate model classes; here it is an apply-time mode (``SpatialCtx.d2_mode``)
of the SAME models:

- :func:`accumulated_halo` computes the input-space margin
  ``H = Σ_i p_i · Π_{j<i} s_j`` of a layer run (the receptive-field overlap of
  the whole run).
- :func:`run_layers_d2` exchanges that margin ONCE, then applies each layer
  with ``SpatialCtx.halo_pre_exchanged`` set and the layer's CURRENT margin in
  ``pre_margin_h/w``, so convs/pools run VALID on the sharded dims and consume
  ``p_i`` margin each; margins stay divisible by construction
  (``m_{i+1} = (m_i - p_i)/s_i`` with H built top-down).
- ``SpatialCtx.d2_max_fused`` caps the number of margin-consuming layers per
  exchange (the reference's ``--fused-layers`` knob); None fuses maximal runs.

Semantics notes (same trade as the reference's D2): the global image is
effectively zero-padded ONCE by H before the run instead of re-padded at
every conv, so border numerics of convs/pools differ from the per-conv D1
path (pools see pad-once zeros on the sharded dims).  BatchNorm inside a
fused run is EXACT, however: it excludes the not-yet-consumed margin rows
from its statistics (layers.py), so cross-tile BN equals single-device BN
whether or not a run is fused.  tests/test_d2.py pins these properties.
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Sequence, Tuple

import dataclasses

from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.layers import BatchNorm, Conv2d, Identity, Pool2d, ReLU, Softmax
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_2d


def layer_d2_geometry(layer) -> Optional[Tuple[int, int, int, int]]:
    """(ph, pw, sh, sw) of a layer inside a fused run, or None when the layer
    cannot participate (dense/flatten/head layers — those runs fall back to
    per-op D1)."""
    if isinstance(layer, Conv2d):
        kh, kw, sh, sw, ph, pw = layer._geometry()
        return (ph, pw, sh, sw)
    if isinstance(layer, Pool2d):
        kh, kw, sh, sw, ph, pw = layer._geometry()
        return (ph, pw, sh, sw)
    if isinstance(layer, (BatchNorm, ReLU, Identity, Softmax)):
        return (0, 0, 1, 1)
    if getattr(layer, "_d2_identity", False):
        # Wrapper layers that consume no margin (e.g. the exact-stats
        # striped run's fixed-statistics BN, ops/hstripe_conv.py).
        return (0, 0, 1, 1)
    return None


def accumulated_halo(layers: Sequence) -> Optional[Tuple[int, int]]:
    """Input-space halo (H_h, H_w) of a run, or None if any layer is
    unsupported.  H = Σ p_i · (product of strides before layer i) — the
    closed form of the reference's per-case tables
    (resnet_spatial_d2.py:651-697)."""
    hh = hw = 0
    fh = fw = 1
    for layer in layers:
        g = layer_d2_geometry(layer)
        if g is None:
            return None
        ph, pw, sh, sw = g
        hh += ph * fh
        hw += pw * fw
        fh *= sh
        fw *= sw
    return hh, hw


def can_fuse(layers: Sequence, sp) -> bool:
    """A run is fusable when every layer is supported and there is a halo to
    fuse on at least one sharded dim."""
    acc = accumulated_halo(layers)
    if acc is None:
        return False
    hh, hw = acc
    sharded_h = bool(sp.axis_h) and sp.grid_h > 1
    sharded_w = bool(sp.axis_w) and sp.grid_w > 1
    return (sharded_h and hh > 0) or (sharded_w and hw > 0)


def _fusable_triple(layers, i, x_dtype, train: bool,
                    x_shape=None) -> bool:
    """[ReLU, Conv2d, BatchNorm] starting at i, eligible for the fused
    Pallas relu→conv→BN-stats kernel: stride-1 non-1x1 ungrouped unbiased
    conv, no lane padding, train mode (eval normalizes with running stats —
    no stats to fuse), VMEM caps OK in both conv directions.  Tiny-channel
    huge-spatial inputs are excluded (``x_shape`` given): the kernel's
    128-lane pad multiplies such inputs 8-42x in HBM — that regime belongs
    to ops/hstripe_conv.py (see Conv2d.apply's dispatch order)."""
    if i + 2 >= len(layers) or not train:
        return False
    if (x_shape is not None and len(x_shape) == 4
            and x_shape[-1] <= 64 and x_shape[1] * x_shape[2] >= (1 << 20)):
        return False
    r, cv, bn = layers[i], layers[i + 1], layers[i + 2]
    if not (type(r) is ReLU and type(cv) is Conv2d and type(bn) is BatchNorm):
        return False
    kh, kw, sh, sw, _, _ = cv._geometry()
    if (sh, sw) != (1, 1) or (kh, kw) == (1, 1) or cv.feature_group_count != 1:
        return False
    if cv.bias or cv.lane_pad_in or cv.lane_pad_out or bn.lane_pad:
        return False
    if bn.num_features != cv.out_channels:
        return False
    from mpi4dl_tpu.ops.pallas_conv import pallas_conv_eligible

    return pallas_conv_eligible(
        cv.in_channels, cv.out_channels, kh, kw, itemsize=x_dtype.itemsize
    )


def _apply_fused_triple(cv: Conv2d, bn: BatchNorm, p_conv, p_bn, x, ctx,
                        sub, mh, mw, sharded_h, sharded_w):
    """One fused relu→conv→bn through the Pallas epilogue kernel.  Margins:
    relu consumes none; the conv consumes (ph, pw) on sharded dims (padding
    the unsharded dims explicitly — SAME semantics there); BN consumes none
    and its statistics exclude the remaining margin, exactly as the unfused
    BatchNorm.apply slices stat_x."""
    import jax.numpy as jnp
    from jax import lax

    from mpi4dl_tpu.ops.pallas_conv import fused_relu_conv_bn_t

    kh, kw, _, _, ph, pw = cv._geometry()
    w = p_conv["kernel"].astype(x.dtype)
    pad_h = (0, 0) if sharded_h else (ph, ph)
    pad_w = (0, 0) if sharded_w else (pw, pw)
    if pad_h != (0, 0) or pad_w != (0, 0):
        x = jnp.pad(x, ((0, 0), pad_h, pad_w, (0, 0)))
    h_out = x.shape[1] - (kh - 1)
    w_out = x.shape[2] - (kw - 1)
    mh2 = (mh - ph) if sharded_h else mh
    mw2 = (mw - pw) if sharded_w else mw
    win = (mh2, h_out - mh2, mw2, w_out - mw2)
    y, s, ss = fused_relu_conv_bn_t(x, w, win)
    cnt = jnp.asarray(
        y.shape[0] * (win[1] - win[0]) * (win[3] - win[2]), jnp.float32
    )
    if sub.active and sub.bn_cross_tile:
        ax_names = tuple(a for a in (sub.axis_h, sub.axis_w) if a)
        with scope("bn_cross_tile"):
            # Count is a trace-time constant: static multiply, not a wire
            # psum (psum(1, axes) folds to the axis-size product).
            cnt = cnt * lax.psum(1, ax_names)
            s = lax.psum(s, ax_names)
            ss = lax.psum(ss, ax_names)
    mean = s / cnt
    var = jnp.maximum(ss / cnt - mean * mean, 0.0)
    y = bn.normalize_with_stats(
        p_bn, y, mean, var, cnt, ctx.with_spatial(sub)
    )
    return y, mh2, mw2


def maybe_run_fused_unsharded(layers: Sequence, params_seq, x,
                              ctx: ApplyCtx):
    """Single-device fused relu→conv→bn dispatch for a plain layer cell.

    The unsharded case is the degenerate premargin run (no margins, SAME =
    explicit pad + margin-consuming VALID), so [ReLU, Conv2d, BatchNorm]
    windows can take the same fused Pallas kernel the D2 path uses —
    gated on the axis-free ``use_pallas_conv`` knob carrier
    (make_train_step(pallas_conv=True)); returns None (zero graph change)
    unless at least one fusable window exists and every layer in the cell
    is premargin-capable."""
    sp = ctx.spatial
    if (sp is None or not sp.use_pallas_conv or sp.active
            or sp.axis_h is not None or sp.axis_w is not None):
        return None
    if any(layer_d2_geometry(l) is None for l in layers):
        return None
    if not any(
        _fusable_triple(layers, i, x.dtype, ctx.train, x.shape)
        for i in range(len(layers))
    ):
        return None
    y, _, _ = apply_layers_premargin(layers, params_seq, x, ctx, 0, 0)
    return y


def apply_layers_premargin(layers: Sequence, params_seq, x, ctx: ApplyCtx,
                           mh: int, mw: int):
    """Apply `layers` to an activation already carrying margin (mh, mw) on the
    sharded dims, consuming it layer by layer.  Returns (y, mh_out, mw_out).

    When ``sp.use_pallas_conv`` is on, [ReLU, Conv2d, BatchNorm] windows
    take the fused Pallas relu→conv→BN-stats kernel (one VMEM pass for the
    pre-activation, statistics off the accumulator cast) — the step-level
    contender against XLA's conv+BN+ReLU fusion (VERDICT r4 task 5).

    Trace-time checks (ADVICE r1): each stride must divide both the remaining
    margin and the true local extent, otherwise tiles would silently de-phase
    relative to the pad-once global semantics."""
    sp = ctx.spatial
    sharded_h = bool(sp.axis_h) and sp.grid_h > 1
    sharded_w = bool(sp.axis_w) and sp.grid_w > 1
    idx = 0
    while idx < len(layers):
        if sp.use_pallas_conv and _fusable_triple(layers, idx, x.dtype,
                                                  ctx.train, x.shape):
            cv, bn = layers[idx + 1], layers[idx + 2]
            ph, pw, *_ = layer_d2_geometry(cv)
            # Stride is 1 by the gate, so the misalignment checks below are
            # trivially satisfied for this window.
            sub = dataclasses.replace(
                sp, halo_pre_exchanged=True,
                pre_margin_h=(mh - ph) if sharded_h else mh,
                pre_margin_w=(mw - pw) if sharded_w else mw,
            )
            x, mh, mw = _apply_fused_triple(
                cv, bn, params_seq[idx + 1], params_seq[idx + 2], x, ctx,
                sub, mh, mw, sharded_h, sharded_w,
            )
            idx += 3
            continue
        layer, p = layers[idx], params_seq[idx]
        ph, pw, sh, sw, *_ = layer_d2_geometry(layer)
        sub = dataclasses.replace(
            sp, halo_pre_exchanged=True, pre_margin_h=mh, pre_margin_w=mw
        )
        if sharded_h:
            if (mh - ph) % sh or (x.shape[1] - 2 * mh) % sh:
                raise ValueError(
                    f"D2 stride misalignment on H: margin {mh}, pad {ph}, "
                    f"stride {sh}, local extent {x.shape[1] - 2 * mh} — the "
                    "tile would de-phase from the global conv grid; adjust "
                    "tile grid / image size / fused run boundaries."
                )
        if sharded_w:
            if (mw - pw) % sw or (x.shape[2] - 2 * mw) % sw:
                raise ValueError(
                    f"D2 stride misalignment on W: margin {mw}, pad {pw}, "
                    f"stride {sw}, local extent {x.shape[2] - 2 * mw}."
                )
        x = layer.apply(p, x, ctx.with_spatial(sub))
        if sharded_h:
            mh = (mh - ph) // sh
        if sharded_w:
            mw = (mw - pw) // sw
        idx += 1
    return x, mh, mw


def premargin_out(layers: Sequence, ctx: ApplyCtx, mh: int, mw: int):
    """The (mh_out, mw_out) that :func:`apply_layers_premargin` would return
    — pure static margin arithmetic, no compute.  Lets callers wrap the
    compute in jax.checkpoint (whose outputs must be arrays, not the static
    margin ints) and recover the margins outside (ctx.remat_ops path)."""
    sp = ctx.spatial
    sharded_h = bool(sp.axis_h) and sp.grid_h > 1
    sharded_w = bool(sp.axis_w) and sp.grid_w > 1
    for layer in layers:
        ph, pw, sh, sw, *_ = layer_d2_geometry(layer)
        if sharded_h:
            mh = (mh - ph) // sh
        if sharded_w:
            mw = (mw - pw) // sw
    return mh, mw


def run_layers_d2(layers: Sequence, params_seq, x, ctx: ApplyCtx):
    """Apply a fused run: one accumulated halo exchange, then every layer in
    pre-exchanged (margin-consuming) mode."""
    sp = ctx.spatial
    assert sp is not None and sp.active
    sharded_h = bool(sp.axis_h) and sp.grid_h > 1
    sharded_w = bool(sp.axis_w) and sp.grid_w > 1
    for layer in layers:
        if isinstance(layer, Pool2d):
            ph, pw, *_ = layer_d2_geometry(layer)
            if (ph and sharded_h) or (pw and sharded_w):
                # VERDICT r2 weak-item 6: make the documented D2 trade VISIBLE
                # to users, not just readers of this module.
                warnings.warn(
                    "halo-D2 fused run contains a padded pooling layer: "
                    "image-border pooling windows see pad-once zeros instead "
                    "of the D1 path's exact mask/-inf semantics (numerics "
                    "differ at tile borders from a non-D2 run; see ops/d2.py)",
                    stacklevel=2,
                )
                break
    hh, hw = accumulated_halo(layers)
    mh = hh if sharded_h else 0
    mw = hw if sharded_w else 0
    with scope(f"halo_d2_fused_h{mh}w{mw}"):
        x = halo_exchange_2d(
            x,
            HaloSpec.symmetric(mh),
            HaloSpec.symmetric(mw),
            sp.axis_h,
            sp.axis_w,
            sp.grid_h,
            sp.grid_w,
            rep_h=sp.rep_h,
            rep_w=sp.rep_w,
        )
    with scope("d2_run"):
        y, mh_out, mw_out = apply_layers_premargin(layers, params_seq, x, ctx, mh, mw)
    assert mh_out == 0 and mw_out == 0, (mh_out, mw_out)
    return y


def _chunk_runs(layers: Sequence, max_fused: Optional[int]) -> List[Tuple[int, int]]:
    """Split [0, len) into runs each containing at most `max_fused`
    margin-consuming (padded) layers; None = one run."""
    n = len(layers)
    if max_fused is None or max_fused <= 0:
        return [(0, n)]
    runs, start, used = [], 0, 0
    for i, layer in enumerate(layers):
        ph, pw, *_ = layer_d2_geometry(layer)
        consumes = (ph > 0) or (pw > 0)
        if consumes and used >= max_fused:
            runs.append((start, i))
            start, used = i, 0
        used += 1 if consumes else 0
    runs.append((start, n))
    return [r for r in runs if r[0] < r[1]]


def maybe_run_d2(layers: Sequence, params_seq, x, ctx: ApplyCtx):
    """Fuse when D2 mode is on and the run qualifies; else return None so the
    caller takes its normal per-layer path."""
    sp = ctx.spatial
    if (
        sp is not None
        and sp.active
        and sp.d2_mode
        and not sp.halo_pre_exchanged
        and can_fuse(layers, sp)
    ):
        x_out = x
        for r0, r1 in _chunk_runs(layers, sp.d2_max_fused):
            sub_layers = layers[r0:r1]
            sub_params = params_seq[r0:r1]
            if can_fuse(sub_layers, sp):
                x_out = run_layers_d2(sub_layers, sub_params, x_out, ctx)
            else:
                for layer, p in zip(sub_layers, sub_params):
                    x_out = layer.apply(p, x_out, ctx)
        return x_out
    return None
