"""D2 fused halo exchange: one accumulated exchange per conv run.

The reference's "Design-2" replaces per-conv halo exchange with one larger
exchange per block of ``fused_layers`` convs, the convs then running halo-free
and shrinking the tile (``src/models/resnet_spatial_d2.py:416-460``,
accumulated-halo formulas ``:651-697``); its charts show ~1.7-2x throughput
from this at 1024-2048 px (BASELINE.md).  The reference implements it as
separate model classes; here it is an apply-time mode (``SpatialCtx.d2_mode``)
of the SAME models:

- :func:`accumulated_halo` computes the input-space margin
  ``H = Σ_i p_i · Π_{j<i} s_j`` of a layer run (the receptive-field overlap of
  the whole run).
- :func:`run_layers_d2` exchanges that margin ONCE, then applies each layer
  with ``SpatialCtx.halo_pre_exchanged`` set, so convs run VALID on the
  sharded dims and consume ``p_i`` margin each; margins stay divisible by
  construction (``m_{i+1} = (m_i - p_i)/s_i`` with H built top-down).

Semantics note (same as the reference's D2): border numerics differ from the
per-conv path — the global image is effectively zero-padded ONCE by H before
the run, instead of re-padded at every conv; and normalisation layers inside
a run see the not-yet-consumed margin rows.  A run whose first layers consume
the margin before any BatchNorm (conv-first blocks) is bit-identical to D1.
tests/test_d2.py pins both properties.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import dataclasses

from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.layers import BatchNorm, Conv2d, Identity, ReLU, Softmax
from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_2d


def layer_d2_geometry(layer) -> Optional[Tuple[int, int, int, int]]:
    """(ph, pw, sh, sw) of a layer inside a fused run, or None when the layer
    cannot participate (pools, dense — those runs fall back to per-op D1)."""
    if isinstance(layer, Conv2d):
        kh, kw, sh, sw, ph, pw = layer._geometry()
        return (ph, pw, sh, sw)
    if isinstance(layer, (BatchNorm, ReLU, Identity, Softmax)):
        return (0, 0, 1, 1)
    return None


def accumulated_halo(layers: Sequence) -> Optional[Tuple[int, int]]:
    """Input-space halo (H_h, H_w) of a run, or None if any layer is
    unsupported.  H = Σ p_i · (product of strides before layer i) — the
    closed form of the reference's per-case tables
    (resnet_spatial_d2.py:651-697)."""
    hh = hw = 0
    fh = fw = 1
    for layer in layers:
        g = layer_d2_geometry(layer)
        if g is None:
            return None
        ph, pw, sh, sw = g
        hh += ph * fh
        hw += pw * fw
        fh *= sh
        fw *= sw
    return hh, hw


def can_fuse(layers: Sequence, sp) -> bool:
    """A run is fusable when every layer is supported and there is a halo to
    fuse on at least one sharded dim."""
    acc = accumulated_halo(layers)
    if acc is None:
        return False
    hh, hw = acc
    sharded_h = bool(sp.axis_h) and sp.grid_h > 1
    sharded_w = bool(sp.axis_w) and sp.grid_w > 1
    return (sharded_h and hh > 0) or (sharded_w and hw > 0)


def run_layers_d2(layers: Sequence, params_seq, x, ctx: ApplyCtx):
    """Apply a fused run: one accumulated halo exchange, then every layer in
    pre-exchanged (margin-consuming) mode."""
    sp = ctx.spatial
    assert sp is not None and sp.active
    hh, hw = accumulated_halo(layers)
    sharded_h = bool(sp.axis_h) and sp.grid_h > 1
    sharded_w = bool(sp.axis_w) and sp.grid_w > 1
    x = halo_exchange_2d(
        x,
        HaloSpec.symmetric(hh if sharded_h else 0),
        HaloSpec.symmetric(hw if sharded_w else 0),
        sp.axis_h,
        sp.axis_w,
        sp.grid_h,
        sp.grid_w,
    )
    sub_ctx = ctx.with_spatial(dataclasses.replace(sp, halo_pre_exchanged=True))
    for layer, p in zip(layers, params_seq):
        x = layer.apply(p, x, sub_ctx)
    return x


def maybe_run_d2(layers: Sequence, params_seq, x, ctx: ApplyCtx):
    """Fuse when D2 mode is on and the run qualifies; else return None so the
    caller takes its normal per-layer path."""
    sp = ctx.spatial
    if (
        sp is not None
        and sp.active
        and sp.d2_mode
        and not sp.halo_pre_exchanged
        and can_fuse(layers, sp)
    ):
        return run_layers_d2(layers, params_seq, x, ctx)
    return None
