from mpi4dl_tpu.ops.halo import (
    halo_exchange_1d,
    halo_exchange_2d,
    halo_exchange_with_mask,
    HaloSpec,
)

__all__ = [
    "halo_exchange_1d",
    "halo_exchange_2d",
    "halo_exchange_with_mask",
    "HaloSpec",
]
