from mpi4dl_tpu.ops.halo import (
    halo_exchange_1d,
    halo_exchange_2d,
    halo_exchange_with_mask,
    HaloSpec,
)
from mpi4dl_tpu.ops.ring import (
    ghost_conv1d,
    ring_attention,
    seq_ghost_exchange,
)

__all__ = [
    "halo_exchange_1d",
    "halo_exchange_2d",
    "halo_exchange_with_mask",
    "HaloSpec",
    "ghost_conv1d",
    "ring_attention",
    "seq_ghost_exchange",
]
