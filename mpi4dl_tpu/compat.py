"""Version tolerance for the jax APIs the parallel schedules lean on.

The package targets vma-aware jax (``jax.shard_map`` with varying-manual-axes
tracking, ``lax.pcast``).  Older jax (0.4.x) still ships the experimental
``shard_map`` with the ``check_rep`` flag and no pcast; this module papers
over the difference so the package imports and the 8-device CPU test mesh
runs on both:

- :func:`shard_map` — ``jax.shard_map`` when present, else
  ``jax.experimental.shard_map.shard_map`` with ``check_rep=False``.
- :func:`pcast` — ``lax.pcast`` when present, else identity.
- :func:`ensure_host_device_count` — the ``jax_num_cpu_devices`` config
  option with the ``XLA_FLAGS`` fallback for older jax (shared by
  tests/conftest.py and benchmarks/common.py).

CAVEAT (legacy jax only): forward programs are identical, but the vma
varying-marks (``pcast``) that the pipeline/GEMS schedules document as
required for correct shard_map AD become no-ops, and the old
``check_rep=False`` AD has known cotangent-scaling differences — gradient
exactness of the scan-engine schedules is NOT guaranteed on jax 0.4.x
(their exact-match tests fail there; single-device/DP/SP paths are fine).
A one-line stderr note is emitted at import so training runs can't hit
this silently.

Import sites use ``from mpi4dl_tpu.compat import shard_map, pcast`` instead
of reaching into jax directly.
"""

from __future__ import annotations

from jax import lax

try:  # vma-aware shard_map (new jax)
    from jax import shard_map as _shard_map

    _LEGACY = False
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _LEGACY = True
    import warnings as _warnings

    _warnings.warn(
        "mpi4dl_tpu.compat: legacy jax (<jax.shard_map) — vma varying-marks "
        "are no-ops; pipeline/GEMS gradient exactness is not guaranteed on "
        "this jax version (see mpi4dl_tpu/compat.py)",
        stacklevel=2,
    )

# Public version guard: True on legacy jax (0.4.x line — no top-level
# jax.shard_map, check_rep=False AD, pcast no-op).  The engine exactness
# tests skipif on this (tests/*: the documented old-jax failures), so they
# auto-unskip on any vma-aware jax.
LEGACY_JAX = _LEGACY


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    # normalize the checker kwarg across the rename (check_rep -> check_vma)
    if _LEGACY:
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        kwargs.setdefault("check_rep", False)
    elif "check_rep" in kwargs and "check_vma" not in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


if hasattr(lax, "pcast"):
    pcast = lax.pcast
else:

    def pcast(x, axes, to="varying"):
        del axes, to  # no vma tracking on this jax — nothing to cast
        return x


def ensure_host_device_count(n: int) -> None:
    """Request an ``n``-device CPU platform.  New jax: the
    ``jax_num_cpu_devices`` config option (inert unless the CPU platform is
    actually selected).  Older jax: the equivalent ``XLA_FLAGS`` host-device
    flag, effective as long as no backend has initialized yet."""
    import os

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:  # noqa: BLE001 — option missing on this jax
        if "--xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""
        ):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n}"
            ).strip()
