"""Functional NHWC layer library.

Each layer is a lightweight frozen dataclass with

- ``init(key, in_shape) -> (params, out_shape)`` — params is a pytree of
  jnp arrays; shapes are *global* (unsharded) shapes including batch.
- ``apply(params, x, ctx) -> y`` — pure; `ctx` is an ApplyCtx.  When
  ``ctx.spatial`` is active (inside shard_map, H/W sharded), convs and pools
  exchange halos via ops/halo.py; otherwise they are plain XLA ops.

This replaces three parallel class hierarchies in the reference (sequential /
spatial "D1" / spatial "D2" copies of every model,
``src/models/{resnet,resnet_spatial,resnet_spatial_d2}.py`` etc.) with one
definition whose behaviour is chosen by sharding context at apply time.

Layout notes (TPU-first):
- NHWC activations, HWIO conv kernels: the channel dim lands on the TPU lane
  dimension (128) so convs map straight onto the MXU.
- Compute dtype is the incoming activation dtype; params are kept fp32 by
  default and cast at use (bf16 matmul/conv with fp32 master weights).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx
from mpi4dl_tpu.ops.halo import HaloSpec, halo_exchange_2d, halo_exchange_with_mask

# Escape hatches, read at DISPATCH time (trace), not import — so a script
# can toggle them between step builds for A/B runs (the pattern bench.py
# uses for MPI4DL_SQRT_GROUPS):
#  MPI4DL_NO_PHASE_DX=1  — strided convs keep XLA's lhs-dilation backward
#                          instead of ops/conv_phase.py.
#  MPI4DL_NO_HSTRIPE=1   — tiny-channel huge-spatial convs keep the plain
#                          XLA conv instead of ops/hstripe_conv.py.
# Both wins are scheduling/layout properties of XLA's TPU lowering, not of
# the math — hence the hatches.
def _phase_dx_enabled() -> bool:
    import os

    return os.environ.get("MPI4DL_NO_PHASE_DX") != "1"


def _hstripe_enabled() -> bool:
    import os

    return os.environ.get("MPI4DL_NO_HSTRIPE") != "1"


_HSTRIPE_MIN_PIXELS = 1 << 20
# Pools at or below this input size take the phase-view strided reduction
# (fast path); larger ones keep strided slices (see _window_reduce).
# 256 MB covers the 1024² headline (109 MB pools); a 512 MB setting that
# would cover the 2048² rung's 436 MB pools was tried and the rung's
# compile did not finish inside 25 min on the tunnel — kept conservative.
_PHASE_POOL_MAX_BYTES = 256 * 1024 * 1024

Params = Any
Shape = Tuple[int, ...]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _uniform(key, shape, bound, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


class Layer:
    """Base: subclasses implement init/apply."""

    def init(self, key, in_shape: Shape):
        raise NotImplementedError

    def apply(self, params, x, ctx: ApplyCtx):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Conv2d with spatial-parallel halo exchange
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Conv2d(Layer):
    """2-D convolution, NHWC/HWIO.

    Replicated mode: plain ``lax.conv_general_dilated`` with explicit
    symmetric padding.  Spatial mode (ctx.spatial active): halo-exchange the
    padding region from neighbour tiles, then VALID conv — the TPU-native
    equivalent of the reference's ``conv_spatial``
    (``src/torchgems/spatial.py:1019-1029``: pad → exchange → copy → conv).

    Requirements inherited from the reference's design (and checked):
    tile H/W divisible by stride so windows align across tiles.
    """

    in_channels: int
    out_channels: int
    kernel_size: Any = 3
    stride: Any = 1
    padding: Any = None  # None → (k-1)//2 per dim ("same"-style like reference)
    bias: bool = True
    feature_group_count: int = 1
    # Function-preserving lane padding (0 = off): the conv consumes/produces
    # activations padded to these channel widths, with the extra kernel
    # columns/rows ZERO — so padded input channels contribute exact zeros
    # and padded output channels are exact zeros.  Params keep their true
    # shapes (autodiff of the pad is a slice, so weight grads are exact).
    # Purpose: keep narrow mid-channel chains (AmoebaNet bottlenecks,
    # c/4 ∈ {52,104,156}) on one dense 128-lane layout through a whole op
    # chain instead of XLA flipping narrow padded tilings around each conv
    # (the r4 layout-copy mass, PERF_NOTES).
    lane_pad_in: int = 0
    lane_pad_out: int = 0

    def _geometry(self):
        kh, kw = _pair(self.kernel_size)
        sh, sw = _pair(self.stride)
        if self.padding is None:
            ph, pw = (kh - 1) // 2, (kw - 1) // 2
        else:
            ph, pw = _pair(self.padding)
        return kh, kw, sh, sw, ph, pw

    def init(self, key, in_shape: Shape):
        kh, kw, sh, sw, ph, pw = self._geometry()
        n, h, w, c = in_shape
        expect_c = self.lane_pad_in or self.in_channels
        assert c == expect_c, f"expected C={expect_c}, got {c} in {in_shape}"
        if self.lane_pad_in or self.lane_pad_out:
            assert self.feature_group_count == 1, "lane_pad: groups unsupported"
            assert not self.lane_pad_in or self.lane_pad_in >= self.in_channels, \
                (self.lane_pad_in, self.in_channels)
            assert not self.lane_pad_out or self.lane_pad_out >= self.out_channels, \
                (self.lane_pad_out, self.out_channels)
        fan_in = self.in_channels // self.feature_group_count * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        kkey, bkey = jax.random.split(key)
        params = {
            "kernel": _uniform(
                kkey,
                (kh, kw, self.in_channels // self.feature_group_count,
                 self.out_channels),
                bound,
            )
        }
        if self.bias:
            params["bias"] = _uniform(bkey, (self.out_channels,), bound)
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        return params, (n, oh, ow, self.lane_pad_out or self.out_channels)

    @staticmethod
    def _pallas_dispatchable(sp, kh, kw, sh, sw, groups, kernel) -> bool:
        """Route this conv through the Pallas margin-consuming kernel?
        Stride 1, no groups, not 1x1 (a pure matmul XLA already handles),
        and the kernel's VMEM scratch within its caps in both directions —
        the weight slab AND the th=1 input window (pallas_conv_eligible)."""
        if not (sp is not None and sp.use_pallas_conv):
            return False
        if (sh, sw) != (1, 1) or (kh, kw) == (1, 1) or groups != 1:
            return False
        from mpi4dl_tpu.ops.pallas_conv import pallas_conv_eligible

        return pallas_conv_eligible(
            kernel.shape[2], kernel.shape[3], kernel.shape[0],
            kernel.shape[1], itemsize=kernel.dtype.itemsize,
        )

    @staticmethod
    def _hstripe_shape(kh, kw, sh, sw, groups, x) -> bool:
        """Shape-based H-stripe dispatch for XLA-hostile convs: stride-1
        small-kernel convs on TINY-channel HUGE-spatial inputs, where XLA's
        TPU lowering materializes an im2col-style patch tensor (measured
        ~3 GB per 3x3 conv at C=16, 2048² — the ResNet-110 high-resolution
        OOM driver, PERF_NOTES r3/r4).  ops/hstripe_conv.py bounds the
        temp by scanning H stripes.  (The Pallas kernel cannot take these
        shapes: Mosaic refuses sub-128 lane DMA extents and a 128-lane
        channel pad multiplies the input 8–42x in HBM — measured OOM.)
        MPI4DL_NO_HSTRIPE=1 opts out."""
        if not _hstripe_enabled():
            return False
        n, h, w, c = x.shape
        # 1x1 convs are pure matmuls, but at huge spatial XLA still splits
        # them with ~2x-padded GB-scale temps — striping bounds those too.
        return (
            (sh, sw) == (1, 1) and groups == 1
            and c <= 64 and h * w >= _HSTRIPE_MIN_PIXELS
        )

    @staticmethod
    def _pallas_apply(bias, x, kernel, pads):
        from mpi4dl_tpu.ops.pallas_conv import halo_conv2d_t

        if any(p != (0, 0) for p in pads):
            x = jnp.pad(x, pads)
        y = halo_conv2d_t(x, kernel)
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y

    def apply(self, params, x, ctx: ApplyCtx):
        kh, kw, sh, sw, ph, pw = self._geometry()
        kernel = params["kernel"].astype(x.dtype)
        bias = params["bias"] if self.bias else None
        if self.lane_pad_in or self.lane_pad_out:
            pi = max(0, (self.lane_pad_in or self.in_channels) - self.in_channels)
            po = max(0, (self.lane_pad_out or self.out_channels) - self.out_channels)
            kernel = jnp.pad(kernel, ((0, 0), (0, 0), (0, pi), (0, po)))
            if bias is not None and po:
                bias = jnp.pad(bias, (0, po))
        sp = ctx.spatial
        if sp is not None and sp.active:
            sharded_h = bool(sp.axis_h) and sp.grid_h > 1
            sharded_w = bool(sp.axis_w) and sp.grid_w > 1
            halo_h = HaloSpec.symmetric(ph if sharded_h else 0)
            halo_w = HaloSpec.symmetric(pw if sharded_w else 0)
            # Per-conv ("D1") halo exchange of the receptive-field overlap —
            # skipped inside a D2 fused run (sp.halo_pre_exchanged: the
            # accumulated margin is already in x); either way the conv then
            # runs VALID on the sharded dims, consuming ph/pw of margin.
            if not sp.halo_pre_exchanged and (halo_h.lo or halo_w.lo):
                x = halo_exchange_2d(
                    x, halo_h, halo_w, sp.axis_h, sp.axis_w, sp.grid_h, sp.grid_w,
                    rep_h=sp.rep_h, rep_w=sp.rep_w,
                )
            # A dim whose margin came from exchange (or pre-exchange) needs no
            # padding; unsharded dims keep explicit symmetric padding.
            padding = (
                (0, 0) if halo_h.lo else (ph, ph),
                (0, 0) if halo_w.lo else (pw, pw),
            )
            # Sharded runs MAY use the Pallas margin-consuming kernel — but
            # only on explicit opt-in (sp.use_pallas_conv, checked by the
            # dispatch gate): the r4 step-level A/B measured XLA's fused
            # VALID conv equal-or-faster at every D2-representative shape
            # despite the kernel's op-level wins (PERF_NOTES r4).
            use_pallas = True
        else:
            padding = ((ph, ph), (pw, pw))
            # Unsharded dispatch only for an AXIS-FREE knob carrier (the
            # explicit make_train_step(pallas_conv=True) route) — NOT for
            # degenerate multi-level SP levels (grid 1, rep>1: inactive but
            # axis-bearing), whose full-image SAME convs measured 35% slower
            # on this path (PERF_NOTES.md).
            use_pallas = (
                sp is not None and sp.axis_h is None and sp.axis_w is None
            )
        # hstripe is checked BEFORE the Pallas opt-in: tiny-channel
        # huge-spatial convs (ResNet C<=16 at 2048²-class) are the regime
        # where the kernel's 128-lane channel pad multiplies the input
        # 8-42x in HBM (measured OOM) — a pallas_conv=True A/B run must
        # not route them away from the striped path built for them.
        if self._hstripe_shape(kh, kw, sh, sw, self.feature_group_count, x):
            from mpi4dl_tpu.ops.hstripe_conv import hstripe_conv2d

            y = hstripe_conv2d(x, kernel, padding[0], padding[1])
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y
        if use_pallas and self._pallas_dispatchable(
            sp, kh, kw, sh, sw, self.feature_group_count, kernel
        ):
            # The kernel wants the margin present on BOTH dims — pad any dim
            # whose margin wasn't realized by halo exchange (all of them in
            # the unsharded case: SAME = pad + margin-consuming VALID).
            return self._pallas_apply(
                bias, x, kernel,
                [(0, 0), padding[0], padding[1], (0, 0)],
            )
        if ((sh, sw) != (1, 1) and self.feature_group_count == 1
                and _phase_dx_enabled()):
            # Strided convs take the phase-decomposed-backward form: same
            # forward conv, but dx avoids XLA's lhs-dilation machinery
            # (ops/conv_phase.py; measured step-level win, PERF_NOTES r4).
            from mpi4dl_tpu.ops.conv_phase import conv2d_strided_t

            y = conv2d_strided_t(x, kernel, (sh, sw), padding)
        else:
            y = lax.conv_general_dilated(
                x,
                kernel,
                window_strides=(sh, sw),
                padding=padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=self.feature_group_count,
            )
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y


# ---------------------------------------------------------------------------
# BatchNorm
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BatchNorm(Layer):
    """BatchNorm2d over (N, H, W) per channel.

    Train mode uses batch statistics.  Under spatial sharding the stats are
    psum'd across the tile grid by default (``ctx.spatial.bn_cross_tile``),
    which makes sharded training numerically identical to single-device — the
    reference instead computes per-tile stats (plain nn.BatchNorm2d inside
    spatial layers, reference resnet_spatial.py:149-163); set
    ``bn_cross_tile=False`` on the SpatialCtx for that parity behaviour.

    Running stats (`mean`,`var`) live in params; they receive no gradient in
    train mode.  When ``ctx.bn_sink`` is set, train-mode apply() deposits the
    momentum-updated running values (torch semantics: unbiased variance for
    the running buffer) into the sink keyed by ``id()`` of the param leaves;
    step builders write them back post-optimizer-update.  Eval mode
    (``ctx.train=False``) normalizes with the running stats.
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    # Function-preserving lane padding (see Conv2d.lane_pad_*): the layer
    # normalizes an activation padded to this channel width.  Padded
    # channels get scale 0 / bias 0, so their output is exactly 0 (the
    # batch statistics of a zero channel never reach the output); params
    # and running stats keep the true num_features width.
    lane_pad: int = 0

    def init(self, key, in_shape: Shape):
        c = in_shape[-1]
        assert not self.lane_pad or self.lane_pad >= self.num_features, \
            (self.lane_pad, self.num_features)
        expect_c = self.lane_pad or self.num_features
        assert c == expect_c, f"expected C={expect_c}, got {in_shape}"
        nf = self.num_features
        params = {
            "scale": jnp.ones((nf,), jnp.float32),
            "bias": jnp.zeros((nf,), jnp.float32),
            "mean": jnp.zeros((nf,), jnp.float32),
            "var": jnp.ones((nf,), jnp.float32),
        }
        return params, in_shape

    def apply(self, params, x, ctx: ApplyCtx):
        # Memory discipline on the TRAIN path (the 2048px→beyond lever,
        # PERF_NOTES.md; eval below trades it back for fp32 precision):
        # never materialize an fp32 copy of the activation.  Statistics come from
        # ONE fused sum/sumsq pair with fp32 ACCUMULATION over the original
        # dtype (XLA fuses the upcast/square into the reductions), and
        # normalization is folded to y = x·a + b with per-channel fp32
        # (a, b) precomputed — a single fma in the compute dtype, so both
        # the forward temp and the backward cotangents stay bf16 under
        # bf16 compute.
        orig_dtype = x.dtype
        pad = (self.lane_pad - self.num_features) if self.lane_pad else 0
        scale = jnp.pad(params["scale"], (0, pad)) if pad else params["scale"]
        bias = jnp.pad(params["bias"], (0, pad)) if pad else params["bias"]
        if ctx.train:
            axes = tuple(range(x.ndim - 1))  # all but channel
            sp = ctx.spatial
            stat_x = x
            if sp is not None and sp.halo_pre_exchanged and (
                sp.pre_margin_h or sp.pre_margin_w
            ):
                # Inside a D2 fused run the tile still carries not-yet-consumed
                # margin rows (duplicated neighbour data / boundary zeros);
                # statistics come from the true tile region only, so fused-run
                # BN matches the unfused (and single-device) statistics
                # exactly.  Normalisation still covers the full extended tile.
                mh = sp.pre_margin_h if (sp.axis_h and sp.grid_h > 1) else 0
                mw = sp.pre_margin_w if (sp.axis_w and sp.grid_w > 1) else 0
                stat_x = x[:, mh : x.shape[1] - mh, mw : x.shape[2] - mw, :]
            # Accumulate in fp32 for bf16/fp32 activations; promote to f64
            # under x64 inputs (keeps f64 runs genuinely f64 end-to-end).
            acc_dt = jnp.promote_types(jnp.float32, x.dtype)
            cnt = jnp.asarray(
                math.prod([stat_x.shape[a] for a in axes]), acc_dt
            )
            s = jnp.sum(stat_x, axis=axes, dtype=acc_dt)
            ss = jnp.sum(
                jnp.square(stat_x.astype(acc_dt)), axis=axes
            )
            if sp is not None and sp.active and sp.bn_cross_tile:
                # Cross-tile statistics: psum local (sum, sumsq).  The count
                # is a trace-time constant (SPMD tiles share a shape), so its
                # "reduce" is a static multiply — psum(1, axes) constant-folds
                # to the axis-size product, no wire (ircheck: wasted-wire).
                ax_names = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
                cnt = cnt * lax.psum(1, ax_names)
                s = lax.psum(s, ax_names)
                ss = lax.psum(ss, ax_names)
            mean = s / cnt
            # E[x²]-E[x]² cancellation can go slightly negative in fp.
            var = jnp.maximum(ss / cnt - mean * mean, 0.0)
            if ctx.bn_sink is not None:
                nf = self.num_features
                self._deposit_running(
                    params, mean[:nf] if pad else mean,
                    var[:nf] if pad else var, cnt, ctx,
                )
        else:
            # Eval has no backward and therefore no activation-memory
            # pressure — keep the affine in fp32 (ADVICE r3: the folded
            # compute-dtype fma is a training-memory lever only; inference
            # outputs keep full precision).
            mean, var = params["mean"], params["var"]
            if pad:
                mean = jnp.pad(mean, (0, pad))
                var = jnp.pad(var, (0, pad), constant_values=1.0)
            inv = lax.rsqrt(var + self.eps) * scale
            y = x.astype(jnp.float32) * inv + (bias - mean * inv)
            return y.astype(orig_dtype)
        inv = lax.rsqrt(var + self.eps) * scale
        a = inv.astype(orig_dtype)
        b = (bias - mean * inv).astype(orig_dtype)
        return x * a + b

    def normalize_with_stats(self, params, x, mean, var, cnt, ctx: ApplyCtx):
        """Train-mode normalization with externally computed batch
        statistics — the fused Pallas relu-conv-bn epilogue path
        (ops/pallas_conv.fused_relu_conv_bn_t computes (sum, sumsq) in the
        conv kernel; the caller turns them into mean/var, cross-tile
        psum'd when required).  Running-stat deposit and the folded
        compute-dtype fma are identical to apply()'s train path.
        ``lane_pad`` is unsupported here (the fused dispatch gates it)."""
        assert not self.lane_pad, "fused-stats path does not support lane_pad"
        if ctx.bn_sink is not None:
            self._deposit_running(params, mean, var, cnt, ctx)
        inv = lax.rsqrt(var + self.eps) * params["scale"]
        a = inv.astype(x.dtype)
        b = (params["bias"] - mean * inv).astype(x.dtype)
        return x * a + b

    def _deposit_running(self, params, mean, var, cnt, ctx: ApplyCtx):
        """Put momentum-updated running stats into ctx.bn_sink.

        Stats must come out replicated (params are replicated), so axes over
        which the batch statistics still vary are pmean'd first: the data axis
        always; the tile axes only when per-tile stats are in use
        (bn_cross_tile=False — the psum'd cross-tile stats are already
        tile-invariant).  The variance stored in the running buffer is the
        unbiased one (torch nn.BatchNorm2d semantics)."""
        sp = ctx.spatial
        names = list(ctx.bn_stat_axes)
        if (sp is not None and sp.active and not sp.bn_cross_tile
                and not sp.stat_local):
            names += [a for a in (sp.axis_h, sp.axis_w) if a]
        if ctx.data_axis:
            names.append(ctx.data_axis)
        if names:
            mean = lax.pmean(mean, tuple(names))
            var = lax.pmean(var, tuple(names))
        unbiased = var * (cnt / jnp.maximum(cnt - 1.0, 1.0))
        m = self.momentum
        ctx.bn_sink[id(params["mean"])] = (1 - m) * params["mean"] + m * mean
        ctx.bn_sink[id(params["var"])] = (1 - m) * params["var"] + m * unbiased


# ---------------------------------------------------------------------------
# Activations / simple layers
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReLU(Layer):
    def init(self, key, in_shape):
        return {}, in_shape

    def apply(self, params, x, ctx):
        return jax.nn.relu(x)


@dataclasses.dataclass(frozen=True)
class Identity(Layer):
    def init(self, key, in_shape):
        return {}, in_shape

    def apply(self, params, x, ctx):
        return x


@dataclasses.dataclass(frozen=True)
class Softmax(Layer):
    """Channel softmax — exists to reproduce the reference's softmax-in-model
    head (resnet.py:140) behind cfg.softmax_in_model."""

    def init(self, key, in_shape):
        return {}, in_shape

    def apply(self, params, x, ctx):
        return jax.nn.softmax(x, axis=-1)


@dataclasses.dataclass(frozen=True)
class Dense(Layer):
    in_features: int
    out_features: int

    def init(self, key, in_shape):
        assert in_shape[-1] == self.in_features, (in_shape, self.in_features)
        bound = 1.0 / math.sqrt(self.in_features)
        k1, k2 = jax.random.split(key)
        params = {
            "kernel": _uniform(k1, (self.in_features, self.out_features), bound),
            "bias": _uniform(k2, (self.out_features,), bound),
        }
        return params, (*in_shape[:-1], self.out_features)

    def apply(self, params, x, ctx):
        y = x @ params["kernel"].astype(x.dtype)
        return y + params["bias"].astype(y.dtype)


@dataclasses.dataclass(frozen=True)
class Flatten(Layer):
    def init(self, key, in_shape):
        n = in_shape[0]
        return {}, (n, int(math.prod(in_shape[1:])))

    def apply(self, params, x, ctx):
        # Spatially sharded tensors must be gathered before flattening; model
        # builders place the SP→LP junction before any Flatten.
        return x.reshape(x.shape[0], -1)


# ---------------------------------------------------------------------------
# Pooling (with distributed-correct halo + divisor/mask handling)
# ---------------------------------------------------------------------------


def _window_reduce(x, kh, kw, sh, sw, ph, pw, op: str):
    """Differentiable window reduction (max/add) over NHWC.

    Non-overlapping unpadded windows use a reshape.  STRIDED overlapping
    windows use a phase decomposition: pad, reshape H→(H/s, s) W→(W/s, s),
    and read every tap as a UNIT-stride slice ``y[:, i//s : i//s + oh, i % s,
    ...]`` — on TPU a stride-s slice lowers to gathers in the forward and
    chained pad-scatter fusions in the backward (measured the single largest
    self-inflicted cost class of the AmoebaNet step at 1024²: ~9 ms of
    forward gathers + ~25 ms of scatter chains per 244 ms step, PERF_NOTES
    r4), while unit-stride slices of the phase view fuse into plain loop
    fusions with pad transposes.  Stride-1 windows keep the direct shifted
    slices (k ≤ 8 here, so ≤ 64 fused ops).
    """
    n, h, w, c = x.shape
    if ph == 0 and pw == 0 and kh == sh and kw == sw and h % kh == 0 and w % kw == 0:
        r = x.reshape(n, h // kh, kh, w // kw, kw, c)
        return jnp.max(r, axis=(2, 4)) if op == "max" else jnp.sum(r, axis=(2, 4))
    fill = jnp.asarray(-jnp.inf if op == "max" else 0, x.dtype)
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    # The phase view materializes a ~input-sized buffer that lives through
    # the pool's backward; at the memory FRONTIER (AmoebaNet ≥3328², where
    # pools at 1664-res × 208ch exceed a GB) that buffer costs trainable
    # resolution, so huge pools keep the strided-slice form (slower:
    # gathers + scatter chains — the throughput rungs never see it).
    phase_ok = (n * h * w * c * x.dtype.itemsize) <= _PHASE_POOL_MAX_BYTES
    if (sh > 1 or sw > 1) and phase_ok:
        # Phase view: padded row b = q·s + φ ↦ y[..., q, φ, ...].  Tap i of
        # output q reads padded row q·s + i = (q + i//s)·s + (i % s): a
        # unit-stride slice at phase i % s, offset i//s.  Rows/cols are
        # padded up to the phase grid; taps never read past (oh-1)·s + k-1,
        # so the grid crop below is safe for any h, s, k.
        hr = oh + (kh - 1) // sh
        wr = ow + (kw - 1) // sw
        xp = jnp.pad(
            x,
            ((0, 0), (ph, max(0, hr * sh - h - ph)),
             (pw, max(0, wr * sw - w - pw)), (0, 0)),
            constant_values=fill,
        )
        y = xp[:, : hr * sh, : wr * sw, :].reshape(n, hr, sh, wr, sw, c)
        acc = None
        for i in range(kh):
            for j in range(kw):
                piece = y[:, i // sh : i // sh + oh, i % sh,
                          j // sw : j // sw + ow, j % sw, :]
                if acc is None:
                    acc = piece
                elif op == "max":
                    acc = jnp.maximum(acc, piece)
                else:
                    acc = acc + piece
        return acc
    if ph or pw:
        x = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)), constant_values=fill)
        h, w = h + 2 * ph, w + 2 * pw
    acc = None
    for i in range(kh):
        for j in range(kw):
            piece = x[
                :, i : i + (oh - 1) * sh + 1 : sh,
                j : j + (ow - 1) * sw + 1 : sw, :,
            ]
            if acc is None:
                acc = piece
            elif op == "max":
                acc = jnp.maximum(acc, piece)
            else:
                acc = acc + piece
    return acc


@dataclasses.dataclass(frozen=True)
class Pool2d(Layer):
    """Max/Avg pooling with exact distributed semantics.

    Spatial mode exchanges a halo of the padding width (the reference's Pool,
    ``spatial.py:1416-1509``) and additionally exchanges a validity mask so

    - avg with count_include_pad=False divides by the number of *in-bounds*
      elements (global semantics), and
    - max treats out-of-bounds as -inf instead of 0 (fixing the reference's
      zero-halo leak at image borders).
    """

    op: str  # "max" | "avg"
    kernel_size: Any
    stride: Any = None
    padding: Any = 0
    count_include_pad: bool = True

    def _geometry(self):
        kh, kw = _pair(self.kernel_size)
        s = self.stride if self.stride is not None else self.kernel_size
        sh, sw = _pair(s)
        ph, pw = _pair(self.padding)
        return kh, kw, sh, sw, ph, pw

    def init(self, key, in_shape):
        kh, kw, sh, sw, ph, pw = self._geometry()
        n, h, w, c = in_shape
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        return {}, (n, oh, ow, c)

    def apply(self, params, x, ctx: ApplyCtx):
        kh, kw, sh, sw, ph, pw = self._geometry()
        sp = ctx.spatial
        sharded_h = sp is not None and sp.active and sp.axis_h and sp.grid_h > 1
        sharded_w = sp is not None and sp.active and sp.axis_w and sp.grid_w > 1

        need_mask = (self.op == "avg" and not self.count_include_pad) or (
            self.op == "max" and (ph or pw)
        )

        if sp is not None and sp.halo_pre_exchanged and (
            (sharded_h and ph) or (sharded_w and pw)
        ):
            # Inside a D2 fused run: the margin (incl. this pool's padding) is
            # already present, so run VALID on the sharded dims.  Pad-once D2
            # semantics apply: boundary margin rows are zeros (no -inf mask,
            # no in-bounds divisor on the sharded dims) — exactly what the
            # pad-global-once emulation computes; the D1 path below keeps the
            # exact global semantics.  Unsharded dims keep their own padding.
            rem_ph = 0 if sharded_h else ph
            rem_pw = 0 if sharded_w else pw
            if self.op == "max":
                return _window_reduce(x, kh, kw, sh, sw, rem_ph, rem_pw, "max")
            ysum = _window_reduce(x, kh, kw, sh, sw, rem_ph, rem_pw, "add")
            return ysum / jnp.asarray(kh * kw, x.dtype)

        if (sharded_h and ph) or (sharded_w and pw):
            halo_h = HaloSpec.symmetric(ph if sharded_h else 0)
            halo_w = HaloSpec.symmetric(pw if sharded_w else 0)
            mask = jnp.ones(x.shape[:-1] + (1,), x.dtype)
            x, mask = halo_exchange_with_mask(
                x, mask, halo_h, halo_w, sp.axis_h, sp.axis_w, sp.grid_h, sp.grid_w,
                rep_h=sp.rep_h, rep_w=sp.rep_w,
            )
            # Remaining explicit pad for unsharded dims
            rem_ph = 0 if sharded_h else ph
            rem_pw = 0 if sharded_w else pw
        else:
            # Unsharded max needs no mask: _window_reduce pads with -inf
            # itself, and a where() against an all-ones mask is a full
            # activation pass for nothing.  Avg keeps it for the in-bounds
            # divisor (a constant XLA folds away).
            mask = (
                jnp.ones(x.shape[:-1] + (1,), x.dtype)
                if (need_mask and self.op == "avg") else None
            )
            rem_ph, rem_pw = ph, pw

        # NOTE: implemented with shifted-slice reductions rather than
        # lax.reduce_window — reduce_window's reverse-mode AD is unsupported
        # inside shard_map (jax 0.9), and for the small kernels CNNs use the
        # unrolled form fuses just as well on TPU.
        if self.op == "max":
            neg = jnp.asarray(-jnp.inf, x.dtype)
            if mask is not None:
                x = jnp.where(mask > 0, x, neg)
            y = _window_reduce(x, kh, kw, sh, sw, rem_ph, rem_pw, "max")
            return y
        # avg
        ysum = _window_reduce(x, kh, kw, sh, sw, rem_ph, rem_pw, "add")
        if self.count_include_pad or (ph == 0 and pw == 0):
            return ysum / jnp.asarray(kh * kw, x.dtype)
        div = _window_reduce(mask, kh, kw, sh, sw, rem_ph, rem_pw, "add")
        return ysum / jnp.maximum(div, 1)


@dataclasses.dataclass(frozen=True)
class GlobalAvgPool(Layer):
    """AdaptiveAvgPool2d((1,1)) + flatten (reference Classify head,
    amoebanet.py:401-417).  Under spatial sharding this is a local mean plus a
    weighted psum over the tile grid — the natural SP→LP junction for heads."""

    def init(self, key, in_shape):
        n, h, w, c = in_shape
        return {}, (n, c)

    def apply(self, params, x, ctx: ApplyCtx):
        sp = ctx.spatial
        y = jnp.mean(x, axis=(1, 2))
        if sp is not None and sp.active:
            ax = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
            y = lax.pmean(y, ax)
        return y
