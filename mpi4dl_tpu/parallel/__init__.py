from mpi4dl_tpu.parallel.spatial import (
    gather_spatial,
    scatter_batch_over_tiles,
    apply_spatial_model,
)
from mpi4dl_tpu.parallel.partition import StagePartition, TreePack
from mpi4dl_tpu.parallel.pipeline import (
    PipelineState,
    init_pipeline_state,
    make_pipeline_train_step,
)
from mpi4dl_tpu.parallel.gems import make_gems_train_step
from mpi4dl_tpu.parallel.sp_pipeline import (
    SPPipeline,
    SPPipelineState,
    init_sp_pipeline_state,
    make_sp_gems_train_step,
    make_sp_pipeline_train_step,
)

__all__ = [
    "gather_spatial",
    "scatter_batch_over_tiles",
    "apply_spatial_model",
    "StagePartition",
    "TreePack",
    "PipelineState",
    "init_pipeline_state",
    "make_pipeline_train_step",
    "make_gems_train_step",
    "SPPipeline",
    "SPPipelineState",
    "init_sp_pipeline_state",
    "make_sp_gems_train_step",
    "make_sp_pipeline_train_step",
]
