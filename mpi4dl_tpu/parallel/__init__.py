from mpi4dl_tpu.parallel.spatial import (
    gather_spatial,
    scatter_batch_over_tiles,
    apply_spatial_model,
)

__all__ = [
    "gather_spatial",
    "scatter_batch_over_tiles",
    "apply_spatial_model",
]
