"""Spatial-parallel region handling and the SP→LP junction.

The reference moves data between its spatial region and the following
layer-parallel region with a rank-indexed gather/concat mosaic
(``train_spatial.py:690-721`` receive-from-all-tiles,
``:1083-1188`` merge_inputs_joint_cat) or a scatter/gather pair for
LOCAL_DP_LP (``:809-1028``).  On TPU both junctions are one collective:

- ``gather_spatial``: ``lax.all_gather(tiled=True)`` over the spatial axes —
  every device holds the full activation (replicated tail; fine for heads).
- ``scatter_batch_over_tiles``: gather + slice the batch by the device's tile
  linear index — the LOCAL_DP_LP junction (each former tile device trains the
  tail on its own micro-slice of the batch).

``apply_spatial_model`` runs a CellModel with the first ``spatial_until``
cells under spatial sharding and the rest replicated/batch-split — the analog
of the reference's spatial model variants that switch conv_spatial off past
``end_layer`` (amoebanet.py:618-710, resnet_spatial.py:272-296).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.cells import CellModel
from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx

Act = Union[jax.Array, Tuple[jax.Array, ...]]


def _map_act(fn, x: Act) -> Act:
    if isinstance(x, tuple):
        return tuple(fn(t) for t in x)
    return fn(x)


def gather_spatial(x: Act, sp: SpatialCtx, h_dim: int = 1, w_dim: int = 2) -> Act:
    """Reassemble the full (global-H/W) tensor from tiles on every device."""

    def g(t):
        if sp.axis_h and sp.grid_h > 1:
            t = lax.all_gather(t, sp.axis_h, axis=h_dim, tiled=True)
        if sp.axis_w and sp.grid_w > 1:
            t = lax.all_gather(t, sp.axis_w, axis=w_dim, tiled=True)
        return t

    return _map_act(g, x)


def tile_linear_index(sp: SpatialCtx) -> jax.Array:
    """This device's tile index in row-major (reference local_rank ordering,
    split_input train_spatial.py:241-290)."""
    idx = jnp.zeros((), jnp.int32)
    if sp.axis_h and sp.grid_h > 1:
        idx = idx + lax.axis_index(sp.axis_h) * sp.grid_w
    if sp.axis_w and sp.grid_w > 1:
        idx = idx + lax.axis_index(sp.axis_w)
    return idx


def scatter_batch_over_tiles(x: Act, sp: SpatialCtx) -> Act:
    """LOCAL_DP_LP junction: full tensor → per-device batch shard."""
    tiles = sp.grid_h * sp.grid_w
    t0 = x[0] if isinstance(x, tuple) else x
    n = t0.shape[0]
    assert n % tiles == 0, f"batch {n} not divisible by {tiles} tiles"
    shard = n // tiles
    start = tile_linear_index(sp) * shard

    def s(t):
        return lax.dynamic_slice_in_dim(t, start, shard, axis=0)

    return _map_act(s, x)


def apply_spatial_model(
    model: CellModel,
    params_list,
    x: Act,
    ctx: ApplyCtx,
    spatial_until: Optional[int] = None,
    junction: str = "gather",
) -> Act:
    """Run cells [0, spatial_until) spatially sharded, junction, then the tail
    replicated (junction='gather') or batch-split (junction='batch_split').

    Must be called inside shard_map with ctx.spatial set.  With
    spatial_until=None, all cells except the final head run spatially (safe
    because heads flatten/pool to per-image vectors).
    """
    sp = ctx.spatial
    assert sp is not None and sp.active, "apply_spatial_model needs an active SpatialCtx"
    if spatial_until is None:
        spatial_until = model.spatial_until or (len(model.cells) - 1)

    x = model.apply(params_list, x, ctx, start=0, stop=spatial_until)
    x = gather_spatial(x, sp)
    if junction == "batch_split":
        x = scatter_batch_over_tiles(x, sp)
    # BN running-stat deposits in the tail must pmean over the former tile
    # axes: under 'batch_split' the batch genuinely varies per tile device;
    # under 'gather' the all_gathered values are equal but shard_map's
    # varying-axes tracking cannot know that, so the (numerically no-op)
    # pmean re-establishes provable replication.
    import dataclasses

    tile_axes = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
    tail_ctx = dataclasses.replace(
        ctx.with_spatial(None), bn_stat_axes=ctx.bn_stat_axes + tile_axes
    )
    return model.apply(params_list, x, tail_ctx, start=spatial_until)
