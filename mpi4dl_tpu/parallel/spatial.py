"""Spatial-parallel region handling and the SP→LP junction.

The reference moves data between its spatial region and the following
layer-parallel region with a rank-indexed gather/concat mosaic
(``train_spatial.py:690-721`` receive-from-all-tiles,
``:1083-1188`` merge_inputs_joint_cat) or a scatter/gather pair for
LOCAL_DP_LP (``:809-1028``).  On TPU both junctions are one collective:

- ``gather_spatial``: ``lax.all_gather(tiled=True)`` over the spatial axes —
  every device holds the full activation (replicated tail; fine for heads).
- ``scatter_batch_over_tiles``: gather + slice the batch by the device's
  junction shard index — the LOCAL_DP_LP junction.  The DP ``degree`` is
  independent of the tile count (reference ``comm.py:278-294`` lets each LP
  stage run LOCAL_DP_LP-way data parallelism): with degree < device count the
  tail is computed redundantly within shard groups, with degree == device
  count every device trains a distinct batch shard.

Multi-level spatial parallelism (reference ``num_spatial_parts="4,2"``,
``train_spatial.py:453-504`` skewed spatial→spatial transitions): levels are
a list of ``(stop_cell, SpatialCtx)`` where later levels have coarser grids
on the SAME mesh axes with replication factor ``rep`` (layer_ctx.py).  The
transition is :func:`respatial` — one all_gather(+dedup) and a re-slice; its
AD transpose is the reverse re-shard, so the reference's hand-written skewed
recv-rank machinery has no analog here.

``apply_spatial_model`` runs a CellModel with the leading cells under spatial
sharding (one or more levels) and the rest replicated/batch-split — the
analog of the reference's spatial model variants that switch conv_spatial off
past ``end_layer`` (amoebanet.py:618-710, resnet_spatial.py:272-296).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.cells import CellModel
from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.quant.collectives import (
    quantized_all_gather,
    quantized_all_to_all,
    quantized_ppermute,
)
from mpi4dl_tpu.quant.policy import QuantPolicy

Act = Union[jax.Array, Tuple[jax.Array, ...]]
Levels = Sequence[Tuple[int, SpatialCtx]]


def _map_act(fn, x: Act) -> Act:
    if isinstance(x, tuple):
        return tuple(fn(t) for t in x)
    return fn(x)


def _mode_block(quant: Optional[QuantPolicy], cls: str):
    """(mode, block) of a policy class; (None, block) when exact."""
    if quant is None:
        return None, 0
    return quant.mode(cls), quant.block


def _gather_dedup(t: jax.Array, axis_name: str, dim: int, grid: int, rep: int,  # analysis: ok(unscoped-collective) — callers own the junction/respatial scopes
                  mode: Optional[str] = None, block: int = 0) -> jax.Array:
    """all_gather the full extent of `dim` from a (possibly rep-duplicated)
    tile layout: device order along the axis is grid blocks of rep identical
    tiles, so the tiled gather is viewed as (grid, rep, local) and the
    duplicates dropped.  ``mode`` routes the gather through the quantized
    wire (per-block int8/fp8/int4 payload, quant/collectives.py); the dedup
    reshape stays outside the quantized op so its AD transpose is shared."""
    if mode:
        t = quantized_all_gather(t, axis_name, dim, mode, block)
    else:
        t = lax.all_gather(t, axis_name, axis=dim, tiled=True)
    if rep > 1:
        lead = t.shape[:dim]
        local = t.shape[dim] // (grid * rep)
        t = t.reshape(*lead, grid, rep, local, *t.shape[dim + 1:])
        t = lax.index_in_dim(t, 0, axis=dim + 1, keepdims=False)
        t = t.reshape(*lead, grid * local, *t.shape[dim + 2:])
    return t


def gather_spatial(x: Act, sp: SpatialCtx, h_dim: int = 1, w_dim: int = 2,
                   quant: Optional[QuantPolicy] = None) -> Act:
    """Reassemble the full (global-H/W) tensor from tiles on every device.
    ``quant``: junction-class payload quantization (docs/quantization.md)."""
    mode, block = _mode_block(quant, "junction")

    def g(t):
        if sp.axis_h and sp.grid_h > 1:
            t = _gather_dedup(t, sp.axis_h, h_dim, sp.grid_h, sp.rep_h,
                              mode, block)
        if sp.axis_w and sp.grid_w > 1:
            t = _gather_dedup(t, sp.axis_w, w_dim, sp.grid_w, sp.rep_w,
                              mode, block)
        return t

    return _map_act(g, x)


def tile_device_count(sp: SpatialCtx) -> int:
    """Total devices on the tile axes (including replication groups)."""
    nh = sp.grid_h * sp.rep_h if sp.axis_h else 1
    nw = sp.grid_w * sp.rep_w if sp.axis_w else 1
    return nh * nw


def junction_shard_index(sp: SpatialCtx, degree: int) -> jax.Array:
    """This device's batch-shard index for a degree-way LOCAL_DP_LP junction:
    the tile-axes device grid is linearized row-major and chunked into
    `degree` contiguous groups (each group redundantly computes one shard)."""
    total = tile_device_count(sp)
    assert 1 <= degree <= total and total % degree == 0, (degree, total)
    lin = jnp.zeros((), jnp.int32)
    nw = sp.grid_w * sp.rep_w if sp.axis_w else 1
    if sp.axis_h:
        lin = lin + lax.axis_index(sp.axis_h) * nw
    if sp.axis_w:
        lin = lin + lax.axis_index(sp.axis_w)
    return lin // (total // degree)


def scatter_batch_over_tiles(x: Act, sp: SpatialCtx, degree: Optional[int] = None) -> Act:
    """LOCAL_DP_LP junction: full tensor → per-device batch shard.

    `degree` defaults to the tile count (the reference's implicit choice when
    LOCAL_DP_LP == num_spatial_parts); any degree dividing the tile-axes
    device count is legal (reference comm.py:278-294)."""
    if degree is None:
        degree = sp.grid_h * sp.grid_w
    t0 = x[0] if isinstance(x, tuple) else x
    n = t0.shape[0]
    assert n % degree == 0, f"batch {n} not divisible by junction degree {degree}"
    shard = n // degree
    start = junction_shard_index(sp, degree) * shard

    def s(t):
        return lax.dynamic_slice_in_dim(t, start, shard, axis=0)

    return _map_act(s, x)


def can_all_to_all_junction(sp: SpatialCtx, degree: int) -> bool:
    """The batch-split junction has an all_to_all fast path when every tile
    device takes a distinct batch shard (degree == device count) and no
    replication groups exist — the common LOCAL_DP_LP configuration."""
    return (
        sp.rep_h == 1 and sp.rep_w == 1
        and degree == sp.grid_h * sp.grid_w
    )


def batch_split_all_to_all(x: Act, sp: SpatialCtx,  # analysis: ok(unscoped-collective) — apply_junction wraps in scope("junction_batch_split_a2a")
                           h_dim: int = 1, w_dim: int = 2,
                           quant: Optional[QuantPolicy] = None) -> Act:
    """Tile layout → batch-shard layout in one collective per axis.

    Equivalent to ``gather_spatial`` + ``scatter_batch_over_tiles`` with
    degree == tile count, but moves 1/degree of the bytes and never
    materializes the full gathered activation on any device (the all_gather
    path costs degree× both in ICI traffic and junction memory).  Shard
    order matches :func:`junction_shard_index`: splitting over sph first
    (outer), then spw, puts batch shard ih*grid_w+iw on device (ih, iw).
    ``quant``: junction-class payload quantization (both transfer
    directions — a pure permutation, quantized once per crossing).
    """
    assert can_all_to_all_junction(sp, sp.grid_h * sp.grid_w)
    mode, block = _mode_block(quant, "junction")

    def a2a(t, axis, concat):
        if mode:
            return quantized_all_to_all(t, axis, 0, concat, mode, block)
        return lax.all_to_all(
            t, axis, split_axis=0, concat_axis=concat, tiled=True
        )

    def s(t):
        if sp.axis_h and sp.grid_h > 1:
            t = a2a(t, sp.axis_h, h_dim)
        if sp.axis_w and sp.grid_w > 1:
            t = a2a(t, sp.axis_w, w_dim)
        return t

    return _map_act(s, x)


def apply_junction(x: Act, sp_last: SpatialCtx, junction: str,
                   local_dp: Optional[int] = None,
                   quant: Optional[QuantPolicy] = None) -> Act:
    """The SP→LP junction, shared by the pure-SP and SPxPP engines.

    'gather': full activation everywhere.  'batch_split': per-device batch
    shard of degree ``local_dp`` (default: final level's tile count), via the
    all_to_all fast path when every tile device takes a distinct shard.
    ``quant``: opt-in junction-class payload quantization."""
    degree = local_dp if local_dp else sp_last.grid_h * sp_last.grid_w
    if junction == "batch_split":
        n = (x[0] if isinstance(x, tuple) else x).shape[0]
        assert n % degree == 0, (
            f"batch {n} not divisible by junction degree {degree}"
        )
        if can_all_to_all_junction(sp_last, degree):
            with scope("junction_batch_split_a2a"):
                return batch_split_all_to_all(x, sp_last, quant=quant)
        with scope("junction_batch_split"):
            x = gather_spatial(x, sp_last, quant=quant)
            return scatter_batch_over_tiles(x, sp_last, degree=degree)
    with scope("junction_gather"):
        return gather_spatial(x, sp_last, quant=quant)


def respatial_fast_enabled() -> bool:
    """The gather-free respatial fast paths (refine = local slice,
    coarsen = intra-group ring exchange) are on by default;
    ``MPI4DL_NO_RESPATIAL_FAST=1`` keeps the legacy gather+slice path for
    A/B comparison."""
    import os

    return os.environ.get("MPI4DL_NO_RESPATIAL_FAST", "0") != "1"


def _respatial_refine_slice(t, axis, dim, r_from, r_to, k):
    """Refinement (finer grid, ``k = g_to // g_from``): every device's new
    tile is a sub-slice of the source tile it already holds — ZERO
    collectives (memory-efficient redistribution, arxiv 2112.01075: a
    reshard whose target blocks nest in the source blocks is local)."""
    a = lax.axis_index(axis)
    off = a // r_to - (a // r_from) * k  # target's index inside the source
    local = t.shape[dim] // k
    return lax.dynamic_slice_in_dim(t, off * local, local, axis=dim)


def _respatial_coarsen_ring(t, axis, dim, k, n, mode, block):
    """Coarsening from an unreplicated level (``r_from == 1``,
    ``k = g_from // g_to``): the consumers of target tile ``T`` are exactly
    the holders of its ``k`` source tiles (the group ``[T*k, (T+1)*k)``),
    so the reshard is ``k-1`` intra-group cyclic ppermutes, each device
    accumulating received tiles into its target-tile buffer at the
    sender's position — wire and peak memory are one TARGET tile
    (``k/g_from`` of the full extent) instead of the gather path's full
    extent.  ``mode`` quantizes the ppermute payloads (each source tile
    encoded once, decoded once; the local copy is placed exact when raw).

    AD of the raw path transposes automatically (slice + reverse permute +
    sum); the quantized path's custom_vjp inside quantized_ppermute does
    the same with quantized cotangent slices."""
    pos0 = lax.axis_index(axis) % k  # my tile's index inside the group
    L = t.shape[dim]
    lead, tail = t.shape[:dim], t.shape[dim + 1:]
    out = jnp.zeros((*lead, k * L, *tail), t.dtype)

    def place(buf, tile, p):
        return lax.dynamic_update_slice_in_dim(buf, tile, p * L, axis=dim)

    out = place(out, t, pos0)
    for h in range(1, k):
        # Group-cyclic shift by h: device b receives the tile of b-h
        # (same group), whose position is (pos0 - h) mod k.
        perm = [(b, (b // k) * k + ((b % k) + h) % k) for b in range(n)]
        if mode:
            recv = quantized_ppermute(t, axis, perm, mode, block)
        else:
            recv = lax.ppermute(t, axis, perm)  # analysis: ok(unscoped-collective) — respatial() wraps the ring in scope("respatial_ring")
        out = place(out, recv, (pos0 - h) % k)
    return out


def respatial(x: Act, sp_from: SpatialCtx, sp_to: SpatialCtx,
              h_dim: int = 1, w_dim: int = 2,
              quant: Optional[QuantPolicy] = None) -> Act:
    """Re-shard an activation from one spatial level's tile layout to
    another's (the TPU form of the reference's skewed spatial→spatial
    transition, train_spatial.py:453-504).

    Per dim, in preference order (first two gated by
    :func:`respatial_fast_enabled`; both avoid ever materializing the full
    gathered extent on any device — arxiv 2112.01075):

    - refinement (``g_to`` a multiple of ``g_from``): pure local slice;
    - coarsening from an unreplicated level (``g_from`` a multiple of
      ``g_to > 1``, ``r_from == 1``): intra-group ring exchange building
      exactly the target tile (:func:`_respatial_coarsen_ring`);
    - otherwise: gather the full extent (deduplicating any replication)
      and slice this device's new tile — the legacy path, and the only
      one for a fully-degenerate target (``g_to == 1`` IS the full extent).

    Both levels must live on the same mesh axes (grid*rep equal per axis).
    AD gives the reverse re-shard in every case.  ``quant``: opt-in
    respatial-class payload quantization of whichever path runs."""
    mode, block = _mode_block(quant, "respatial")
    fast = respatial_fast_enabled()

    def dim_pass(t, axis, dim, g_from, r_from, g_to, r_to):
        if axis is None or g_from == g_to:
            assert g_from == g_to, (g_from, g_to)
            return t
        assert g_from * r_from == g_to * r_to, (
            f"levels disagree on axis size: {g_from}*{r_from} != {g_to}*{r_to}"
        )
        if fast and g_to > g_from and g_to % g_from == 0:
            with scope("respatial_refine"):
                return _respatial_refine_slice(
                    t, axis, dim, r_from, r_to, g_to // g_from
                )
        if (fast and g_to > 1 and r_from == 1 and g_from % g_to == 0):
            with scope("respatial_ring"):
                return _respatial_coarsen_ring(
                    t, axis, dim, g_from // g_to, g_from, mode, block
                )
        full = (
            _gather_dedup(t, axis, dim, g_from, r_from, mode, block)
            if g_from > 1 else t
        )
        if g_to == 1:
            return full
        local = full.shape[dim] // g_to
        idx = lax.axis_index(axis) // r_to
        return lax.dynamic_slice_in_dim(full, idx * local, local, axis=dim)

    def r(t):
        t = dim_pass(t, sp_from.axis_h, h_dim, sp_from.grid_h, sp_from.rep_h,
                     sp_to.grid_h, sp_to.rep_h)
        t = dim_pass(t, sp_from.axis_w, w_dim, sp_from.grid_w, sp_from.rep_w,
                     sp_to.grid_w, sp_to.rep_w)
        return t

    return _map_act(r, x)


def apply_spatial_region(
    model: CellModel,
    params_list,
    x: Act,
    ctx: ApplyCtx,
    levels: Levels,
    remat=False,
    quant: Optional[QuantPolicy] = None,
) -> Tuple[Act, SpatialCtx]:
    """Run the spatial region: cells [0, stop_i) per level with that level's
    SpatialCtx, respatial transitions between levels.  Returns the activation
    (still tiled per the LAST level's layout) and that last ctx.

    A fully-degenerate level (grid 1x1 — every device holds the whole image,
    e.g. the tail of a "4,2,1" chain) runs with ``spatial=None`` and the tile
    axes added to ``bn_stat_axes``: compute is replicated, and BN deposits
    pmean over the former tile axes so the written-back running stats are
    provably replicated (shard_map vma bookkeeping)."""
    import dataclasses

    tile_axes = tuple(a for a in (levels[0][1].axis_h, levels[0][1].axis_w) if a)
    start = 0
    prev: Optional[SpatialCtx] = None
    for li, (stop, sp_l) in enumerate(levels):
        assert stop > start, f"empty spatial level [{start}, {stop})"
        if prev is not None:
            with scope(f"respatial_l{li}"):
                x = respatial(x, prev, sp_l, quant=quant)
        if sp_l.active:
            c = ctx.with_spatial(sp_l)
        else:
            c = dataclasses.replace(
                ctx.with_spatial(None), bn_stat_axes=ctx.bn_stat_axes + tile_axes
            )
        # remat: per-cell checkpoints INSIDE the region — without this a
        # region-level checkpoint's backward holds every cell's internals
        # at once (measured 148 GB/device at the 8192² flagship; the
        # readiness artifact's discovery, PERF_NOTES r4).
        with scope(f"sp_level{li}"):
            x = model.apply(
                params_list, x, c, start=start, stop=stop, remat=remat
            )
        start, prev = stop, sp_l
    assert prev is not None
    return x, prev


def _cell_bytes(shape, itemsize: int) -> int:
    """Total activation bytes of one cell's (possibly tuple) output shape."""
    shapes = shape if isinstance(shape[0], (tuple, list)) else (shape,)
    total = 0
    for s in shapes:
        n = 1
        for d in s:
            n *= int(d)
        total += n * itemsize
    return total


def spatial_cost_ledger(shapes, tiles: int, itemsize: int = 2):
    """Per-placement analytical activation cost — the ``mem_probe
    --sweep-junction`` frontier's analytic half as a pure function.

    ``shapes``: per-cell global OUTPUT shapes (``CellModel.init``'s second
    return).  For each candidate junction placement ``su`` the per-device
    proxy is: cells before the junction carry 1/``tiles`` of their bytes
    (spatially sharded), cells at/after it carry full bytes (the
    junction='gather' tail is replicated per tile device — the flagship's
    configuration; batch_split divides both sides equally and preserves the
    argmin).  The head cell (global pool → per-image vectors) is excluded:
    it can never run tiled and its bytes are placement-independent.

    Returns ``{su: bytes}`` over every legal placement ``1 <= su <
    len(shapes) - 1``."""
    n_cells = len(shapes)
    b = [_cell_bytes(s, itemsize) for s in shapes]
    out = {}
    for su in range(1, n_cells - 1):
        spatial = sum(b[i] for i in range(su)) / tiles
        tail = sum(b[i] for i in range(su, n_cells - 1))
        out[su] = spatial + tail
    return out


def choose_spatial_until(shapes, tiles: int, itemsize: int = 2) -> int:
    """The ``--spatial-until auto`` chooser: resolve the SP→LP junction
    placement from the analytical frontier (ROADMAP item 1: the measured
    naive-vs-tuned gap at 8K was 370 vs 116.7 GB/device for placement
    alone — this makes the tuned choice the default instead of a report).

    Picks the placement minimizing :func:`spatial_cost_ledger`'s per-device
    activation proxy; ties go to the DEEPER placement (more cells tiled —
    at equal activation cost, later junctions move less wire because the
    gathered tensor is smaller).  Validated against the compiled frontier
    by ``mem_probe --sweep-junction`` (the artifact records both)."""
    ledger = spatial_cost_ledger(shapes, tiles, itemsize)
    best = min(sorted(ledger), key=lambda su: (ledger[su], -su))
    return best


def apply_spatial_model(
    model: CellModel,
    params_list,
    x: Act,
    ctx: ApplyCtx,
    spatial_until: Optional[int] = None,
    junction: str = "gather",
    levels: Optional[Levels] = None,
    local_dp: Optional[int] = None,
    remat=False,
    quant: Optional[QuantPolicy] = None,
) -> Act:
    """Run the spatial region (one or more levels), junction, then the tail
    replicated (junction='gather') or batch-split (junction='batch_split',
    degree `local_dp` or the final level's tile count).

    Must be called inside shard_map with ctx.spatial set (level-0 ctx).  With
    spatial_until=None and no levels, all cells except the final head run
    spatially (safe because heads flatten/pool to per-image vectors).
    """
    sp = ctx.spatial
    assert sp is not None and sp.active, "apply_spatial_model needs an active SpatialCtx"
    if levels is None:
        if spatial_until is None:
            spatial_until = model.spatial_until or (len(model.cells) - 1)
        levels = [(spatial_until, sp)]

    x, sp_last = apply_spatial_region(
        model, params_list, x, ctx, levels, remat=remat, quant=quant
    )
    x = apply_junction(x, sp_last, junction, local_dp, quant=quant)
    # BN running-stat deposits in the tail must pmean over the former tile
    # axes: under 'batch_split' the batch genuinely varies per tile device;
    # under 'gather' the all_gathered values are equal but shard_map's
    # varying-axes tracking cannot know that, so the (numerically no-op)
    # pmean re-establishes provable replication.
    import dataclasses

    tile_axes = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
    tail_ctx = dataclasses.replace(
        ctx.with_spatial(None), bn_stat_axes=ctx.bn_stat_axes + tile_axes
    )
    return model.apply(
        params_list, x, tail_ctx, start=levels[-1][0], remat=remat
    )
