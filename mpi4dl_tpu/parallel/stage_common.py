"""Machinery shared by the pipeline / GEMS / SP+PP engines.

Stage branches must be PURE COMPUTE: a collective (ppermute/psum) inside a
``lax.switch`` branch selected by ``axis_index`` deadlocks, because XLA lowers
a shard_map collective to ONE instruction whose rendezvous spans every device
on the axis — devices in other branches never arrive (verified empirically on
the CPU backend; the TPU lowering has the same cross-module semantics).  All
collectives — stage handoffs, halo exchanges, junction gathers — therefore
live at the schedule level, uniformly executed by every device.  This is the
structural reason the SP region runs as a separate uniform phase in
``sp_pipeline.py`` rather than inside stage-0's branch.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import pcast

from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.parallel.partition import StagePartition, lax_slice, pad_to
from mpi4dl_tpu.train import accuracy, cross_entropy
from mpi4dl_tpu.mesh import AXIS_STAGE


def make_stage_branches(
    part: StagePartition,
    ctx: ApplyCtx,
    compute_dtype,
    remat: bool,
    with_stats: bool = False,
    vary_axes: Tuple[str, ...] = (),
) -> List[Callable]:
    """One pure-compute branch per stage: unpack flat activation → run the
    stage's cells → pack/pad the output activation (reference per-rank
    sub-model forward, mp_pipeline.py:434-473).

    Every branch returns ``(act_out [act_max], stats [stat_max])`` — the
    second element carries the stage's UPDATED BN running stats (fp32, in the
    stage packing's slot order, zero-padded) when ``with_stats``; callers mask
    out bubble-tick garbage and scatter the average back into the stage's
    flat param row.  stat_max may be 0 (no BN / stats disabled).

    ``vary_axes``: mesh axes the engine's activations vary over.  A stage
    with NO stat leaves returns constant zeros for its stats slot, which
    lax.switch rejects against sibling branches whose (activation-derived)
    stats vary over those axes — the zeros are pcast to match."""
    stat_n = part.stat_max if with_stats else 0

    def stage_branch(s: int):
        pk_in = part.act_packs[s]
        out_pk = part.act_packs[s + 1] if s + 1 < part.num_stages else part.out_pack
        pkp = part.param_packs[s]
        r0, r1 = part.ranges[s]

        def fn(flat_params, buf):
            act = pk_in.unpack(lax_slice(buf, 0, pk_in.total), dtype=compute_dtype)
            params = pkp.unpack(lax_slice(flat_params, 0, pkp.total))
            if stat_n:
                sink: dict = {}
                c = dataclasses.replace(ctx, bn_sink=sink)
            else:
                sink, c = None, ctx
            y = act
            with scope(f"stage{s}"):
                for i in range(r0, r1):
                    with scope(f"cell{i:02d}"):
                        y = part.model.cells[i].apply(params[i - r0], y, c)
            out = pad_to(out_pk.pack(y, compute_dtype), part.act_max)
            if not stat_n:
                return out, jnp.zeros((0,), jnp.float32)
            leaves = jax.tree.leaves(params)
            vals = [
                sink.get(id(leaves[i]), leaves[i]) for i in part.stat_leaf_ids[s]
            ]
            if vals:
                svec = pad_to(
                    jnp.concatenate(
                        [jnp.ravel(v).astype(jnp.float32) for v in vals]
                    ),
                    stat_n,
                )
            else:
                svec = jnp.zeros((stat_n,), jnp.float32)
                if vary_axes:
                    svec = pcast(svec, tuple(vary_axes), to="varying")
            return out, svec

        return jax.checkpoint(fn) if remat else fn

    return [stage_branch(s) for s in range(part.num_stages)]


def gpipe_scan(
    part: StagePartition,
    branches: List[Callable],
    flat_params: jax.Array,
    x_parts: jax.Array,
    y_parts: jax.Array,
    *,
    vary_axes: Tuple[str, ...],
    from_probs: bool,
    compute_dtype,
):
    """The GPipe tick loop (reference run_step, mp_pipeline.py:509-534).

    x_parts: [Pn, mb, ...] micro-batch inputs of stage 0 (device-local);
    y_parts: [Pn, mb] labels.  Returns (loss_acc, acc_acc, stats_acc):
    loss/acc accumulated ONLY on the last stage's devices over the Pn drained
    parts — callers psum over 'stage' and normalise; stats_acc is the sum of
    the stage's BN running-stat updates over its Pn VALID compute ticks
    (bubble ticks masked out) — callers divide by Pn and scatter into the
    stage param row.  T = Pn + S - 1 ticks; activations advance one stage per
    tick via a non-wrapping ppermute; the backward pass is the AD transpose of
    this scan (all-forwards-then-all-backwards falls out).
    """
    S = part.num_stages
    lead = jax.tree.leaves(x_parts)[0]
    Pn, mb = lead.shape[0], lead.shape[1]
    T = Pn + S - 1
    s_idx = lax.axis_index(AXIS_STAGE)
    is_last = s_idx == S - 1
    in_pack0 = part.act_packs[0]
    logits_n = part.out_pack.total
    nclass = part.out_pack.shapes[0][-1]
    amax = part.act_max
    stat_n = branches_stat_n(branches, part)

    def tick(carry, t):
        buf, loss_acc, acc_acc, st_acc = carry
        with scope("mb_inject"):
            p_in = jnp.clip(t, 0, Pn - 1)
            xp = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, p_in, keepdims=False),
                x_parts,
            )
            inj = pad_to(in_pack0.pack(xp, compute_dtype), amax)
            buf = jnp.where(s_idx == 0, inj, buf)
        y, st = lax.switch(s_idx, branches, flat_params, buf)
        # Stage s computes part p = t - s; stats only count on valid ticks.
        st_valid = (t >= s_idx) & (t - s_idx < Pn)
        st_acc = st_acc + jnp.where(st_valid, st, 0.0)
        # Last stage: loss for part p = t - (S-1) when in range.
        p_out = t - (S - 1)
        valid = (p_out >= 0) & (p_out < Pn) & is_last
        logits = lax_slice(y, 0, logits_n).reshape(mb, nclass)
        lbl = lax.dynamic_index_in_dim(
            y_parts, jnp.clip(p_out, 0, Pn - 1), keepdims=False
        )
        l = cross_entropy(logits, lbl, from_probs)
        a = accuracy(logits, lbl)
        loss_acc = loss_acc + jnp.where(valid, l, 0.0)
        acc_acc = acc_acc + jnp.where(valid, a, 0.0)
        # Hand activations to the next stage (non-wrap: stage 0's stale recv
        # is overwritten by injection next tick).
        with scope("stage_handoff"):
            buf = lax.ppermute(
                y, AXIS_STAGE, [(i, i + 1) for i in range(S - 1)]
            )
        return (buf, loss_acc, acc_acc, st_acc), None

    # Initial carries must be marked varying over the axes the loop makes
    # them vary on, or shard_map's AD produces wrong collective transposes
    # (grads scaled by axis size).
    def v(t):
        return pcast(t, vary_axes, to="varying")

    buf0 = v(jnp.zeros((amax,), compute_dtype))
    st0 = v(jnp.zeros((stat_n,), jnp.float32))
    (_, loss_acc, acc_acc, stats_acc), _ = lax.scan(
        tick, (buf0, v(jnp.zeros((), jnp.float32)), v(jnp.zeros((), jnp.float32)), st0), jnp.arange(T, dtype=jnp.int32)
    )
    return loss_acc, acc_acc, stats_acc


def scatter_stage_stats(part: StagePartition, flat: jax.Array, stats: jax.Array):
    """Scatter averaged BN running-stat values into this device's stage param
    row.  ``stats`` is the [stat_max] vector in the stage's slot order (from
    gpipe_scan / gems_dual_scan, already divided by the part count); positions
    come from the -1-padded part.stat_idx table indexed by the device's stage.
    Padded entries resolve to a masked add of 0 at position 0, so the scatter
    is uniform across heterogeneous stages."""
    if part.stat_idx is None:
        return flat
    idx_all = jnp.asarray(part.stat_idx)  # [S, stat_max]
    row = lax.dynamic_index_in_dim(idx_all, lax.axis_index(AXIS_STAGE), keepdims=False)
    mask = row >= 0
    safe = jnp.where(mask, row, 0)
    cur = flat[safe]
    return flat.at[safe].add(jnp.where(mask, stats.astype(flat.dtype) - cur, 0.0))


def branches_stat_n(branches, part: StagePartition) -> int:
    """Static stats-vector length the branches were built with (0 or
    part.stat_max — probed abstractly so callers stay in sync)."""
    out = jax.eval_shape(
        branches[0],
        jax.ShapeDtypeStruct((part.param_max,), jnp.float32),
        jax.ShapeDtypeStruct((part.act_max,), jnp.float32),
    )
    return int(out[1].shape[0])


def gems_dual_scan(
    part: StagePartition,
    branches: List[Callable],
    flat_params: jax.Array,
    mirror_params: jax.Array,
    x_groups,
    y_groups: jax.Array,
    *,
    vary_axes: Tuple[str, ...],
    from_probs: bool,
    compute_dtype,
):
    """The GEMS bidirectional tick loop (reference gems_master.py:72-103).

    x_groups: pytree with leaves [times, 2, Pn, mb, ...]; y_groups
    [times, 2, Pn, mb].  Stream A of each pair flows stage 0→S-1 with the true
    params; stream B flows S-1→0 against ``mirror_params`` (device d holding
    stage S-1-d's row via the mirror ppermute) — the two switch branches per
    tick are what XLA interleaves into bidirectional bubble-filling.  Returns
    (loss_acc, acc_acc, statsA_acc, statsB_acc): loss/acc accumulated on the
    boundary stages over all 2·times·Pn drained parts (callers psum over
    'stage' and normalise); statsA_acc holds device d's stage-d BN stat
    updates from the forward stream, statsB_acc its stage-(S-1-d) updates from
    the reverse stream — callers mirror-ppermute B, average, and scatter.
    """
    S = part.num_stages
    lead = jax.tree.leaves(x_groups)[0]
    times, Pn, mb = lead.shape[0], lead.shape[2], lead.shape[3]
    T = Pn + S - 1
    d = lax.axis_index(AXIS_STAGE)
    in_pack0 = part.act_packs[0]
    logits_n = part.out_pack.total
    nclass = part.out_pack.shapes[0][-1]
    amax = part.act_max
    stat_n = branches_stat_n(branches, part)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]

    def v(t):
        return pcast(t, vary_axes, to="varying")

    def one_pair(carry, pair):
        loss_in, acc_in, stA_in, stB_in = carry
        xp, yp = pair  # leaves [2, Pn, mb, ...], [2, Pn, mb]

        def sel(tree, j, p):
            return jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(
                    a[j], p, keepdims=False
                ),
                tree,
            )

        def tick(c, t):
            bufA, bufB, l_acc, a_acc, stA, stB = c
            p_in = jnp.clip(t, 0, Pn - 1)
            injA = pad_to(in_pack0.pack(sel(xp, 0, p_in), compute_dtype), amax)
            injB = pad_to(in_pack0.pack(sel(xp, 1, p_in), compute_dtype), amax)
            bufA = jnp.where(d == 0, injA, bufA)
            bufB = jnp.where(d == S - 1, injB, bufB)
            yA, sA = lax.switch(d, branches, flat_params, bufA)
            yB, sB = lax.switch(S - 1 - d, branches, mirror_params, bufB)
            # Stream A: device d runs stage d on part t-d; stream B: device d
            # runs stage S-1-d, which part p enters at tick p+(S-1-d)... i.e.
            # processes part t-(S-1-d).
            vA = (t >= d) & (t - d < Pn)
            vB = (t >= (S - 1 - d)) & (t - (S - 1 - d) < Pn)
            stA = stA + jnp.where(vA, sA, 0.0)
            stB = stB + jnp.where(vB, sB, 0.0)
            p_out = t - (S - 1)
            in_range = (p_out >= 0) & (p_out < Pn)
            p_sel = jnp.clip(p_out, 0, Pn - 1)
            lblA = lax.dynamic_index_in_dim(yp[0], p_sel, keepdims=False)
            lblB = lax.dynamic_index_in_dim(yp[1], p_sel, keepdims=False)
            logitsA = lax_slice(yA, 0, logits_n).reshape(mb, nclass)
            logitsB = lax_slice(yB, 0, logits_n).reshape(mb, nclass)
            validA = in_range & (d == S - 1)
            validB = in_range & (d == 0)
            l_acc = (
                l_acc
                + jnp.where(validA, cross_entropy(logitsA, lblA, from_probs), 0.0)
                + jnp.where(validB, cross_entropy(logitsB, lblB, from_probs), 0.0)
            )
            a_acc = (
                a_acc
                + jnp.where(validA, accuracy(logitsA, lblA), 0.0)
                + jnp.where(validB, accuracy(logitsB, lblB), 0.0)
            )
            with scope("stage_handoff"):
                bufA = lax.ppermute(yA, AXIS_STAGE, fwd_perm)
                bufB = lax.ppermute(yB, AXIS_STAGE, bwd_perm)
            return (bufA, bufB, l_acc, a_acc, stA, stB), None

        init = (
            v(jnp.zeros((amax,), compute_dtype)),
            v(jnp.zeros((amax,), compute_dtype)),
            v(jnp.zeros((), jnp.float32)),
            v(jnp.zeros((), jnp.float32)),
            stA_in,
            stB_in,
        )
        (_, _, l_acc, a_acc, stA, stB), _ = lax.scan(tick, init, jnp.arange(T, dtype=jnp.int32))
        return (loss_in + l_acc, acc_in + a_acc, stA, stB), None

    st0 = v(jnp.zeros((stat_n,), jnp.float32))
    (loss_acc, acc_acc, stA_acc, stB_acc), _ = lax.scan(
        one_pair,
        (v(jnp.zeros((), jnp.float32)), v(jnp.zeros((), jnp.float32)), st0, v(jnp.zeros((stat_n,), jnp.float32))),
        (x_groups, y_groups),
    )
    return loss_acc, acc_acc, stA_acc, stB_acc
