"""Machinery shared by the pipeline / GEMS / SP+PP engines.

Stage branches must be PURE COMPUTE: a collective (ppermute/psum) inside a
``lax.switch`` branch selected by ``axis_index`` deadlocks, because XLA lowers
a shard_map collective to ONE instruction whose rendezvous spans every device
on the axis — devices in other branches never arrive (verified empirically on
the CPU backend; the TPU lowering has the same cross-module semantics).  All
collectives — stage handoffs, halo exchanges, junction gathers — therefore
live at the schedule level, uniformly executed by every device.  This is the
structural reason the SP region runs as a separate uniform phase in
``sp_pipeline.py`` rather than inside stage-0's branch.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import pcast

from mpi4dl_tpu.cells import checkpointed_apply
from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.parallel.partition import StagePartition, lax_slice, pad_to
from mpi4dl_tpu.quant.collectives import quantized_ppermute
from mpi4dl_tpu.quant.policy import QuantPolicy
from mpi4dl_tpu.train import accuracy, cross_entropy
from mpi4dl_tpu.mesh import AXIS_STAGE


def _handoff(y, perm, quant: Optional[QuantPolicy]):  # analysis: ok(unscoped-collective) — every caller wraps in scope("stage_handoff"/"cot_handoff")
    """One stage-handoff/cotangent ppermute, quantized when the policy's
    ``handoff`` class is on (per-block payload over the flat [act_max]
    buffer; quant/collectives.py).  The GEMS mirror ppermute must NOT go
    through here — it moves parameters, which are never quantized."""
    mode = quant.mode("handoff") if quant is not None else None
    if mode:
        return quantized_ppermute(y, AXIS_STAGE, perm, mode, quant.block)
    return lax.ppermute(y, AXIS_STAGE, perm)


def make_stage_branches(
    part: StagePartition,
    ctx: ApplyCtx,
    compute_dtype,
    remat: bool,
    with_stats: bool = False,
    vary_axes: Tuple[str, ...] = (),
    cell_remat: bool = False,
) -> List[Callable]:
    """One pure-compute branch per stage: unpack flat activation → run the
    stage's cells → pack/pad the output activation (reference per-rank
    sub-model forward, mp_pipeline.py:434-473).

    Every branch returns ``(act_out [act_max], stats [stat_max])`` — the
    second element carries the stage's UPDATED BN running stats (fp32, in the
    stage packing's slot order, zero-padded) when ``with_stats``; callers mask
    out bubble-tick garbage and scatter the average back into the stage's
    flat param row.  stat_max may be 0 (no BN / stats disabled).

    ``vary_axes``: mesh axes the engine's activations vary over.  A stage
    with NO stat leaves returns constant zeros for its stats slot, which
    lax.switch rejects against sibling branches whose (activation-derived)
    stats vary over those axes — the zeros are pcast to match.

    ``remat`` wraps the WHOLE branch in jax.checkpoint — what the GPipe
    grad-of-scan needs so AD saves only tick carries.  ``cell_remat``
    instead threads the stage body through per-cell ``checkpointed_apply``
    (CellModel.apply remat=True): a vjp of the branch then stores only cell
    boundaries and recomputes one cell at a time — the within-tick policy
    of the 1F1B manual backward, where a whole-branch checkpoint would be
    useless (its backward holds every stage-internal activation at once).
    The two are mutually exclusive by construction here."""
    stat_n = part.stat_max if with_stats else 0

    def stage_branch(s: int):
        pk_in = part.act_packs[s]
        out_pk = part.act_packs[s + 1] if s + 1 < part.num_stages else part.out_pack
        pkp = part.param_packs[s]
        r0, r1 = part.ranges[s]

        def fn(flat_params, buf):
            # The whole branch body rides the stage scope — the act/param
            # unpack and the output pack/pad allocate stage-owned buffers
            # (XLA hoists the loop-invariant parts of the tick switch out of
            # the scan; without the scope those hoisted temps show up
            # unattributed in the obs/hbm.py breakdown).
            with scope(f"stage{s}"):
                act = pk_in.unpack(
                    lax_slice(buf, 0, pk_in.total), dtype=compute_dtype
                )
                params = pkp.unpack(lax_slice(flat_params, 0, pkp.total))
                if stat_n:
                    sink: dict = {}
                    c = dataclasses.replace(ctx, bn_sink=sink)
                else:
                    sink, c = None, ctx
                y = act
                for i in range(r0, r1):
                    with scope(f"cell{i:02d}"):
                        if cell_remat:
                            y = checkpointed_apply(
                                part.model.cells[i].apply, params[i - r0], y, c
                            )
                        else:
                            y = part.model.cells[i].apply(params[i - r0], y, c)
                out = pad_to(out_pk.pack(y, compute_dtype), part.act_max)
                if not stat_n:
                    return out, jnp.zeros((0,), jnp.float32)
                leaves = jax.tree.leaves(params)
                vals = [
                    sink.get(id(leaves[i]), leaves[i])
                    for i in part.stat_leaf_ids[s]
                ]
                if vals:
                    svec = pad_to(
                        jnp.concatenate(
                            [jnp.ravel(v).astype(jnp.float32) for v in vals]
                        ),
                        stat_n,
                    )
                else:
                    svec = jnp.zeros((stat_n,), jnp.float32)
                    if vary_axes:
                        svec = pcast(svec, tuple(vary_axes), to="varying")
                return out, svec

        return jax.checkpoint(fn) if remat else fn

    return [stage_branch(s) for s in range(part.num_stages)]


def gpipe_scan(
    part: StagePartition,
    branches: List[Callable],
    flat_params: jax.Array,
    x_parts: jax.Array,
    y_parts: jax.Array,
    *,
    vary_axes: Tuple[str, ...],
    from_probs: bool,
    compute_dtype,
    quant: Optional[QuantPolicy] = None,
):
    """The GPipe tick loop (reference run_step, mp_pipeline.py:509-534).

    x_parts: [Pn, mb, ...] micro-batch inputs of stage 0 (device-local);
    y_parts: [Pn, mb] labels.  Returns (loss_acc, acc_acc, stats_acc):
    loss/acc accumulated ONLY on the last stage's devices over the Pn drained
    parts — callers psum over 'stage' and normalise; stats_acc is the sum of
    the stage's BN running-stat updates over its Pn VALID compute ticks
    (bubble ticks masked out) — callers divide by Pn and scatter into the
    stage param row.  T = Pn + S - 1 ticks; activations advance one stage per
    tick via a non-wrapping ppermute; the backward pass is the AD transpose of
    this scan (all-forwards-then-all-backwards falls out).
    """
    S = part.num_stages
    lead = jax.tree.leaves(x_parts)[0]
    Pn, mb = lead.shape[0], lead.shape[1]
    T = Pn + S - 1
    s_idx = lax.axis_index(AXIS_STAGE)
    is_last = s_idx == S - 1
    in_pack0 = part.act_packs[0]
    logits_n = part.out_pack.total
    nclass = part.out_pack.shapes[0][-1]
    amax = part.act_max
    stat_n = branches_stat_n(branches, part)

    def tick(carry, t):
        buf, loss_acc, acc_acc, st_acc = carry
        with scope("mb_inject"):
            p_in = jnp.clip(t, 0, Pn - 1)
            xp = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, p_in, keepdims=False),
                x_parts,
            )
            inj = pad_to(in_pack0.pack(xp, compute_dtype), amax)
            buf = jnp.where(s_idx == 0, inj, buf)
        y, st = lax.switch(s_idx, branches, flat_params, buf)
        # Stage s computes part p = t - s; stats only count on valid ticks.
        st_valid = (t >= s_idx) & (t - s_idx < Pn)
        st_acc = st_acc + jnp.where(st_valid, st, 0.0)
        # Last stage: loss for part p = t - (S-1) when in range.
        p_out = t - (S - 1)
        valid = (p_out >= 0) & (p_out < Pn) & is_last
        logits = lax_slice(y, 0, logits_n).reshape(mb, nclass)
        lbl = lax.dynamic_index_in_dim(
            y_parts, jnp.clip(p_out, 0, Pn - 1), keepdims=False
        )
        l = cross_entropy(logits, lbl, from_probs)
        a = accuracy(logits, lbl)
        loss_acc = loss_acc + jnp.where(valid, l, 0.0)
        acc_acc = acc_acc + jnp.where(valid, a, 0.0)
        # Hand activations to the next stage (non-wrap: stage 0's stale recv
        # is overwritten by injection next tick).
        with scope("stage_handoff"):
            buf = _handoff(y, [(i, i + 1) for i in range(S - 1)], quant)
        return (buf, loss_acc, acc_acc, st_acc), None

    # Initial carries must be marked varying over the axes the loop makes
    # them vary on, or shard_map's AD produces wrong collective transposes
    # (grads scaled by axis size).
    def v(t):
        return pcast(t, vary_axes, to="varying")

    buf0 = v(jnp.zeros((amax,), compute_dtype))
    st0 = v(jnp.zeros((stat_n,), jnp.float32))
    (_, loss_acc, acc_acc, stats_acc), _ = lax.scan(
        tick, (buf0, v(jnp.zeros((), jnp.float32)), v(jnp.zeros((), jnp.float32)), st0), jnp.arange(T, dtype=jnp.int32)
    )
    return loss_acc, acc_acc, stats_acc


def scatter_stage_stats(part: StagePartition, flat: jax.Array, stats: jax.Array):
    """Scatter averaged BN running-stat values into this device's stage param
    row.  ``stats`` is the [stat_max] vector in the stage's slot order (from
    gpipe_scan / gems_dual_scan, already divided by the part count); positions
    come from the -1-padded part.stat_idx table indexed by the device's stage.
    Padded entries resolve to a masked add of 0 at position 0, so the scatter
    is uniform across heterogeneous stages."""
    if part.stat_idx is None:
        return flat
    idx_all = jnp.asarray(part.stat_idx)  # [S, stat_max]
    row = lax.dynamic_index_in_dim(idx_all, lax.axis_index(AXIS_STAGE), keepdims=False)
    mask = row >= 0
    safe = jnp.where(mask, row, 0)
    cur = flat[safe]
    return flat.at[safe].add(jnp.where(mask, stats.astype(flat.dtype) - cur, 0.0))


def branches_stat_n(branches, part: StagePartition) -> int:
    """Static stats-vector length the branches were built with (0 or
    part.stat_max — probed abstractly so callers stay in sync)."""
    out = jax.eval_shape(
        branches[0],
        jax.ShapeDtypeStruct((part.param_max,), jnp.float32),
        jax.ShapeDtypeStruct((part.act_max,), jnp.float32),
    )
    return int(out[1].shape[0])


# ---------------------------------------------------------------------------
# 1F1B: one-forward-one-backward schedule, manual schedule-level backward
# ---------------------------------------------------------------------------


def stage_opt_specs(optimizer, part: StagePartition):
    """PartitionSpec pytree for an optimizer state over the [S, Pmax] stage
    buffer: moment buffers (rank >= 2, one row per stage) ride the stage
    sharding; scalar leaves (Adam's step counter) are replicated.  Derived
    from ``optimizer.init`` on a width-1 CONCRETE probe row buffer — the
    rule depends only on the state tree's structure and leaf ranks, and a
    concrete probe (unlike ``jax.eval_shape``) costs the engine build no
    counted trace, keeping it out of the contract gate's retrace budget —
    so the engines' shard_map in/out specs and the init-time device_put
    agree on a single rule."""
    from jax.sharding import PartitionSpec as P

    probe = optimizer.init(jnp.zeros((part.num_stages, 1), part.param_dtype))
    return jax.tree.map(
        lambda s: P(AXIS_STAGE, None) if s.ndim >= 2 else P(), probe
    )


def squeeze_opt_rows(opt_state):
    """Per-device view of a stage-sharded optimizer state: [1, Pmax] moment
    rows squeeze to [Pmax] (like the param row); replicated scalar leaves
    (Adam's step counter) pass through.  Stateful optimizers silently broke
    on the un-squeezed broadcast before this existed (caught by the donate
    exact-match test)."""
    return jax.tree.map(lambda z_: z_[0] if z_.ndim >= 2 else z_, opt_state)


def restore_opt_rows(new_opt, opt_in):
    """Inverse of :func:`squeeze_opt_rows` after the update (leaf-wise,
    keyed on the INPUT leaf's rank — the updated moment is rank 1)."""
    return jax.tree.map(
        lambda n_, o_: n_[None] if o_.ndim >= 2 else n_, new_opt, opt_in
    )


def put_stage_opt(opt_state, mesh):
    """Device-placement mirroring :func:`stage_opt_specs`: rank >= 2 leaves
    stage-sharded, scalars replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    row = NamedSharding(mesh, P(AXIS_STAGE, None))
    rep = NamedSharding(mesh, P())
    return jax.tree.map(
        lambda z_: jax.device_put(z_, row if z_.ndim >= 2 else rep), opt_state
    )


def use_1f1b_cell_remat(part: StagePartition) -> bool:
    """Auto policy for per-cell checkpoints inside the 1F1B backward
    branches (``MPI4DL_1F1B_CELL_REMAT`` overrides: 1/0 force on/off).

    Measured on the virtual mesh (docs/pipeline.md): for SHORT stages
    (<= 3 cells) inner cell checkpoints let the outer transpose free one
    cell's recompute scratch before the next is born — roughly a stage
    working set saved.  For longer stages the effect inverts
    catastrophically (several-fold peak regressions): XLA schedules the
    per-cell backward recomputes concurrently, so every cell's scratch is
    live at once ON TOP of the saved cell boundaries."""
    import os

    v = os.environ.get("MPI4DL_1F1B_CELL_REMAT", "")
    if v in ("0", "1"):
        return v == "1"
    return max(r1 - r0 for r0, r1 in part.ranges) <= 3


def resid_depth(num_stages: int) -> int:
    """Rotating residual-buffer depth of the 1F1B schedule.

    Stage s holds a part's stage-input activation from its forward tick
    (t = p + s) to its backward tick (t = p + 2(S-1) - s): 2(S-1-s) ring
    entries in flight, at most 2(S-1) at stage 0 (the current tick's part
    is NOT counted — every tick reads its backward slot before writing its
    forward slot, and the last stage — whose forward and backward share a
    tick — reads the live ``buf`` directly, never the ring).  One uniform
    depth keeps the buffer SPMD (every device carries the same shape); the
    key property is that it is O(stages), independent of the micro-batch
    count — GPipe as grad-of-scan keeps O(parts + stages) tick carries live
    instead."""
    return max(1, 2 * (num_stages - 1))


def ring_store(resid, valid, slot, row):
    """Masked write of ``row`` into slot ``slot`` of the rotating residual
    ring: a no-op on bubble ticks (``valid`` false) so drain-phase garbage
    never clobbers a live residual.  Shared by the single- and dual-stream
    1F1B builders — both rely on reads preceding this write (the ring depth
    is exactly the stage-0 round trip; see :func:`resid_depth`)."""
    old = lax.dynamic_index_in_dim(resid, slot, keepdims=False)
    return lax.dynamic_update_index_in_dim(
        resid, jnp.where(valid, row, old), slot, 0
    )


def scatter_part_row(G, g, slot, mask):
    """Masked write of one micro-batch part's cotangent ``g`` into row
    ``slot`` of the per-part buffer ``G`` (the grad_x injection transpose:
    each part's row is written exactly once, on its backward tick at the
    injecting stage)."""
    old = lax.dynamic_index_in_dim(G, slot, keepdims=False)
    new = jnp.where(mask, g.astype(G.dtype), old)
    return lax.dynamic_update_index_in_dim(G, new, slot, 0)


def _make_fb_branches(
    branches: List[Callable],
    *,
    logits_n: int,
    nclass: int,
    stat_n: int,
    from_probs: bool,
    seed_scale: float,
    compute_dtype,
) -> List[Callable]:
    """Per-stage combined forward+manual-transpose branches: pure compute,
    one uniform signature ``(flat_params, buf, a_in, cot_in, lbl, valid_out)
    -> (y, st, loss, acc, cot_a_in, grad_params)``.

    One tick = one switch: the forward micro-batch (``buf``) and the
    backward micro-batch (``jax.vjp`` of the same stage at the STORED input
    ``a_in`` — recompute-and-transpose, the same per-tick work GPipe's AD
    does under per-branch ``jax.checkpoint``) share a single branch body.
    Fusing them matters for memory, not just tidiness: two separate
    ``lax.switch`` calls per tick lower to two HLO conditionals whose
    internals get disjoint buffer regions, doubling the per-tick stage
    working set; one branch body lets buffer assignment reuse the forward's
    scratch for the transpose.  The stage index is STATIC inside each
    branch, so the last stage seeds its own backward from this tick's
    logits (1F1B: a part's last-stage forward and backward share a tick)
    while every other stage consumes the cotangent handed down by the
    reverse ppermute.  Callers must pass branches built with
    ``remat=False`` — the transpose half wraps its own ``jax.checkpoint``
    below, and a second wrapper would nest checkpoints for no benefit
    (``cell_remat`` is the supported inner policy, see
    ``use_1f1b_cell_remat``).  Stats get a zero cotangent (running-stat
    deposits are not differentiated, matching the GPipe engines' has_aux
    treatment).  Collectives stay at schedule level (lax.switch deadlock
    rule, module docstring)."""
    S = len(branches)

    def part_loss(yvec, lbl):
        logits = lax_slice(yvec, 0, logits_n).reshape(-1, nclass)
        return cross_entropy(logits, lbl, from_probs)

    def fb_branch(s: int) -> Callable:
        fwd = branches[s]
        seeds_self = s == S - 1

        def fn(flat_params, buf, a_in, cot_y, lbl, valid_out):
            y, st = fwd(flat_params, buf)
            l, ce_vjp = jax.vjp(lambda yv: part_loss(yv, lbl), y)
            logits = lax_slice(y, 0, logits_n).reshape(-1, nclass)
            a = accuracy(logits, lbl)
            if seeds_self:
                # 1F1B: a part's last-stage forward and backward share a
                # tick, so the self-seeding branch backwards THIS tick's
                # micro-batch — its stage input is the live ``buf``, not a
                # ring entry (statically selected: no where-materialised
                # extra activation buffer).
                (seed,) = ce_vjp(jnp.asarray(seed_scale, jnp.float32))
                cot_y = jnp.where(valid_out, seed, 0.0).astype(compute_dtype)
                a_in = buf
            # Sequence the backward after the forward: without the barrier
            # XLA's scheduler is free to interleave the two micro-batches'
            # stage bodies, which makes their scratch buffers live
            # simultaneously — the peak then carries TWO stage working sets
            # and the schedule's whole memory win evaporates.  The barrier
            # pins "forward scratch dies before transpose scratch is born".
            y, st, l, a, a_in, cot_y = lax.optimization_barrier(
                (y, st, l, a, a_in, cot_y)
            )
            # vjp through jax.checkpoint with the primal outputs UNUSED: the
            # primal pass is dead code, so what remains is exactly the
            # recompute-then-transpose body GPipe's AD emits per tick —
            # same structure, same per-tick working set, no stored
            # linearization residuals (a plain jax.vjp would materialize
            # every transpose operand during the forward sweep and hold it
            # across the whole stage body).
            _, vjp = jax.vjp(jax.checkpoint(fwd), flat_params, a_in)
            gp, ga = vjp(
                (cot_y, jnp.zeros((stat_n,), jnp.float32))
            )
            return y, st, l, a, ga, gp

        return fn

    return [fb_branch(s) for s in range(S)]


def _wrap_schedule_vjp(run, *, n_params: int, n_outs: int, seed_scale: float,
                       grad_x: bool):
    """Shared ``jax.custom_vjp`` scaffolding of the 1F1B scan builders.

    ``run(*params, x, y)`` is the interleaved tick loop: it returns
    ``n_outs`` metric outputs followed by ``n_params`` accumulated parameter
    gradients and the injection cotangent ``gx``.  The wrapper's forward
    stashes the gradients as residuals; its backward just scales them by the
    incoming loss cotangent (a replicated scalar, so scaling commutes with
    every collective already baked into the accumulation) and undoes
    ``seed_scale``.  Only the loss (first output) is transposed — the rest
    are aux metrics whose (zero) cotangents are ignored.

    Shapes of x/y are recorded at fwd-trace time (static), so the bwd rule
    can fabricate its zero cotangents without the fwd pass materialising
    (and the scan carrying) batch-sized zero residuals.  ``grad_x=False``
    therefore means "x is not a differentiation target": an engine that did
    differentiate x with it off would silently get zeros.  Labels are
    integers and get float0 cotangents."""
    import numpy as np

    structs: dict = {}

    @jax.custom_vjp
    def scan_sched(*args):
        return run(*args)[:n_outs]

    def scan_fwd(*args):
        out = run(*args)
        x, y = args[n_params], args[n_params + 1]
        structs["x"] = (
            [(l.shape, jnp.result_type(l)) for l in jax.tree.leaves(x)],
            jax.tree.structure(x),
        )
        structs["y"] = (
            [l.shape for l in jax.tree.leaves(y)],
            jax.tree.structure(y),
        )
        return out[:n_outs], out[n_outs:]

    def scan_bwd(res, cots):
        *gps, gx = res
        dloss = (cots[0] / seed_scale).astype(jnp.float32)

        def scale(g):
            return (g.astype(jnp.float32) * dloss).astype(g.dtype)

        if grad_x:
            gx_cot = jax.tree.map(scale, gx)
        else:
            xs, xdef = structs["x"]
            gx_cot = jax.tree.unflatten(
                xdef, [jnp.zeros(s, d) for s, d in xs]
            )
        ys, ydef = structs["y"]
        y_cot = jax.tree.unflatten(
            ydef, [np.zeros(s, jax.dtypes.float0) for s in ys]
        )
        return (*(scale(g) for g in gps), gx_cot, y_cot)

    scan_sched.defvjp(scan_fwd, scan_bwd)
    return scan_sched


def make_1f1b_scan(
    part: StagePartition,
    branches: List[Callable],
    *,
    vary_axes: Tuple[str, ...],
    from_probs: bool,
    compute_dtype,
    seed_scale: float = 1.0,
    grad_x: bool = False,
    quant: Optional[QuantPolicy] = None,
):
    """Build the 1F1B tick loop as a ``jax.custom_vjp`` drop-in for
    :func:`gpipe_scan`: ``f(flat_params, x_parts, y_parts) -> (loss_acc,
    acc_acc, st_acc)`` with the same output semantics (loss/acc accumulated
    on the last stage over the Pn drained parts, stats summed over valid
    forward ticks).

    Why this cannot be ``jax.grad`` of a scan: AD transposes the tick loop
    by replaying ticks in REVERSE — all-forwards-then-all-backwards, which
    *is* GPipe, and it must keep every tick's carry live for the replay
    (O(parts) stage-boundary activations).  Here the backward is part of the
    schedule itself: each tick runs one forward micro-batch AND one backward
    micro-batch (stage s forwards part t-s and backwards part t-2(S-1)+s),
    with the activation ppermute and the reverse cotangent ppermute in the
    same tick.  The scan carries a depth-``resid_depth(S)`` rotating
    residual buffer (stage INPUTS only; the stage body is recomputed inside
    the backward branch) plus one cotangent buffer — O(stages) live
    activations — and accumulates parameter gradients into the flat stage
    row in-scan.  T = Pn + 2(S-1) ticks fill and drain both directions.

    The ``custom_vjp`` wrapper is what lets the engines keep their
    ``jax.value_and_grad(loss_and_metrics)`` structure unchanged: the
    forward pass runs the interleaved loop and stashes the accumulated
    gradients as residuals; the backward rule just scales them by the
    incoming loss cotangent (a replicated scalar, so scaling commutes with
    every collective already baked into the accumulation — psum/pmean
    normalisation and loss-scale transposes stay in AD-land).  Only the
    loss output is transposed; acc/stats are aux metrics and their (zero)
    cotangents are ignored.

    ``seed_scale``: multiplies the in-scan loss-cotangent seed (and divides
    it back out in the vjp rule) so bf16 cotangents inside the scan enjoy
    the same underflow protection as the engines' ``loss_scale``.
    ``grad_x``: also accumulate the cotangent w.r.t. ``x_parts`` (stage-0
    backward, injection transpose) — required when the injections are
    produced by a differentiated phase (the SP region of sp_pipeline);
    engines whose inputs are raw batches leave it off and get a zeros
    cotangent.  Labels are integers and get float0 cotangents."""
    S = part.num_stages
    D = resid_depth(S)
    in_pack0 = part.act_packs[0]
    logits_n = part.out_pack.total
    nclass = part.out_pack.shapes[0][-1]
    amax = part.act_max
    stat_n = branches_stat_n(branches, part)
    fb_branches = _make_fb_branches(
        branches, logits_n=logits_n, nclass=nclass, stat_n=stat_n,
        from_probs=from_probs, seed_scale=seed_scale,
        compute_dtype=compute_dtype,
    )
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    rev_perm = [(i + 1, i) for i in range(S - 1)]

    def v(t):
        return pcast(t, tuple(vary_axes), to="varying")

    def run(flat_params, x_parts, y_parts):
        lead = jax.tree.leaves(x_parts)[0]
        Pn = lead.shape[0]
        T = Pn + 2 * (S - 1)
        s_idx = lax.axis_index(AXIS_STAGE)
        is_last = s_idx == S - 1
        is_first = s_idx == 0

        def tick(carry, t):
            buf, cot, resid, gacc, gx, loss_acc, acc_acc, st_acc = carry
            with scope("fwd_tick"):
                with scope("mb_inject"):
                    p_in = jnp.clip(t, 0, Pn - 1)
                    xp = jax.tree.map(
                        lambda a: lax.dynamic_index_in_dim(
                            a, p_in, keepdims=False
                        ),
                        x_parts,
                    )
                    inj = pad_to(in_pack0.pack(xp, compute_dtype), amax)
                    buf = jnp.where(is_first, inj, buf)
            # Backward operands FIRST: stage s backwards part t - 2(S-1) + s
            # (the seed enters at the last stage — same tick as that part's
            # forward there — and descends one stage per tick).  The read
            # precedes this tick's ring write, which is what lets the ring
            # be exactly 2(S-1) deep: stage 0's read and write land on the
            # SAME slot (its round trip equals the ring size) and the last
            # stage takes the live ``buf`` instead of touching the ring.
            p_b = t - 2 * (S - 1) + s_idx
            valid_b = (p_b >= 0) & (p_b < Pn)
            slot_r = jnp.clip(p_b, 0, Pn - 1) % D
            # The self-seeding branch statically ignores a_in (it backwards
            # the live buf); every other stage reads its ring slot.
            a_in = lax.dynamic_index_in_dim(resid, slot_r, keepdims=False)
            with scope("fwd_tick"):
                # Rotate this tick's stage input into the residual ring
                # (slot p mod D; the draining last stage backwards its live
                # buf instead and never touches the ring).
                p_f = t - s_idx
                valid_f = (p_f >= 0) & (p_f < Pn)
                resid = ring_store(
                    resid, valid_f & (~is_last),
                    jnp.clip(p_f, 0, Pn - 1) % D, buf,
                )
            p_out = t - (S - 1)
            valid_out = (p_out >= 0) & (p_out < Pn)
            lbl = lax.dynamic_index_in_dim(
                y_parts, jnp.clip(p_out, 0, Pn - 1), keepdims=False
            )
            # ONE switch runs this tick's forward AND backward micro-batch
            # (see _make_fb_branches for why the fusion matters).
            y, st, l, a, ga, gp = lax.switch(
                s_idx, fb_branches, flat_params, buf, a_in,
                cot.astype(compute_dtype), lbl, valid_out,
            )
            st_acc = st_acc + jnp.where(valid_f, st, 0.0)
            out_here = valid_out & is_last
            loss_acc = loss_acc + jnp.where(out_here, l, 0.0)
            acc_acc = acc_acc + jnp.where(out_here, a, 0.0)
            with scope("fwd_tick"), scope("stage_handoff"):
                nbuf = (
                    _handoff(y, fwd_perm, quant)
                    if fwd_perm
                    else jnp.zeros_like(y)
                )
            with scope("bwd_tick"):
                gacc = gacc + jnp.where(valid_b, gp, jnp.zeros_like(gp))
                if grad_x:
                    # Injection transpose: stage 0's input cotangent belongs
                    # to part p_b of x_parts (written exactly once per part).
                    gxa = in_pack0.unpack(
                        lax_slice(ga, 0, in_pack0.total), dtype=compute_dtype
                    )
                    slot_x = jnp.clip(p_b, 0, Pn - 1)
                    gx = jax.tree.map(
                        lambda G, g: scatter_part_row(
                            G, g, slot_x, valid_b & is_first
                        ),
                        gx, gxa,
                    )
                with scope("cot_handoff"):
                    cot = (
                        _handoff(ga, rev_perm, quant)
                        if rev_perm
                        else jnp.zeros_like(ga)
                    )
            return (nbuf, cot, resid, gacc, gx, loss_acc, acc_acc, st_acc), None

        z = jnp.zeros
        # scope: the zero ring/cotangent/accumulator inits get sunk into the
        # per-stage dispatch conditional by XLA — name them so the obs/hbm.py
        # breakdown attributes the ring slots instead of dropping them.
        with scope("schedule_init"):
            gx0 = (
                jax.tree.map(
                    lambda a_: v(z(a_.shape, compute_dtype)), x_parts
                )
                if grad_x
                else ()
            )
            init = (
                v(z((amax,), compute_dtype)),
                v(z((amax,), compute_dtype)),
                v(z((D, amax), compute_dtype)),
                v(z(flat_params.shape, flat_params.dtype)),
                gx0,
                v(z((), jnp.float32)),
                v(z((), jnp.float32)),
                v(z((stat_n,), jnp.float32)),
            )
        (_, _, _, gacc, gx, loss_acc, acc_acc, st_acc), _ = lax.scan(
            tick, init, jnp.arange(T, dtype=jnp.int32)
        )
        return loss_acc, acc_acc, st_acc, gacc, gx

    return _wrap_schedule_vjp(
        run, n_params=1, n_outs=3, seed_scale=seed_scale, grad_x=grad_x
    )


def gems_dual_scan(
    part: StagePartition,
    branches: List[Callable],
    flat_params: jax.Array,
    mirror_params: jax.Array,
    x_groups,
    y_groups: jax.Array,
    *,
    vary_axes: Tuple[str, ...],
    from_probs: bool,
    compute_dtype,
    quant: Optional[QuantPolicy] = None,
):
    """The GEMS bidirectional tick loop (reference gems_master.py:72-103).

    x_groups: pytree with leaves [times, 2, Pn, mb, ...]; y_groups
    [times, 2, Pn, mb].  Stream A of each pair flows stage 0→S-1 with the true
    params; stream B flows S-1→0 against ``mirror_params`` (device d holding
    stage S-1-d's row via the mirror ppermute) — the two switch branches per
    tick are what XLA interleaves into bidirectional bubble-filling.  Returns
    (loss_acc, acc_acc, statsA_acc, statsB_acc): loss/acc accumulated on the
    boundary stages over all 2·times·Pn drained parts (callers psum over
    'stage' and normalise); statsA_acc holds device d's stage-d BN stat
    updates from the forward stream, statsB_acc its stage-(S-1-d) updates from
    the reverse stream — callers mirror-ppermute B, average, and scatter.
    """
    S = part.num_stages
    lead = jax.tree.leaves(x_groups)[0]
    times, Pn, mb = lead.shape[0], lead.shape[2], lead.shape[3]
    T = Pn + S - 1
    d = lax.axis_index(AXIS_STAGE)
    in_pack0 = part.act_packs[0]
    logits_n = part.out_pack.total
    nclass = part.out_pack.shapes[0][-1]
    amax = part.act_max
    stat_n = branches_stat_n(branches, part)
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]

    def v(t):
        return pcast(t, vary_axes, to="varying")

    def one_pair(carry, pair):
        loss_in, acc_in, stA_in, stB_in = carry
        xp, yp = pair  # leaves [2, Pn, mb, ...], [2, Pn, mb]

        def sel(tree, j, p):
            return jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(
                    a[j], p, keepdims=False
                ),
                tree,
            )

        def tick(c, t):
            bufA, bufB, l_acc, a_acc, stA, stB = c
            p_in = jnp.clip(t, 0, Pn - 1)
            injA = pad_to(in_pack0.pack(sel(xp, 0, p_in), compute_dtype), amax)
            injB = pad_to(in_pack0.pack(sel(xp, 1, p_in), compute_dtype), amax)
            bufA = jnp.where(d == 0, injA, bufA)
            bufB = jnp.where(d == S - 1, injB, bufB)
            yA, sA = lax.switch(d, branches, flat_params, bufA)
            yB, sB = lax.switch(S - 1 - d, branches, mirror_params, bufB)
            # Stream A: device d runs stage d on part t-d; stream B: device d
            # runs stage S-1-d, which part p enters at tick p+(S-1-d)... i.e.
            # processes part t-(S-1-d).
            vA = (t >= d) & (t - d < Pn)
            vB = (t >= (S - 1 - d)) & (t - (S - 1 - d) < Pn)
            stA = stA + jnp.where(vA, sA, 0.0)
            stB = stB + jnp.where(vB, sB, 0.0)
            p_out = t - (S - 1)
            in_range = (p_out >= 0) & (p_out < Pn)
            p_sel = jnp.clip(p_out, 0, Pn - 1)
            lblA = lax.dynamic_index_in_dim(yp[0], p_sel, keepdims=False)
            lblB = lax.dynamic_index_in_dim(yp[1], p_sel, keepdims=False)
            logitsA = lax_slice(yA, 0, logits_n).reshape(mb, nclass)
            logitsB = lax_slice(yB, 0, logits_n).reshape(mb, nclass)
            validA = in_range & (d == S - 1)
            validB = in_range & (d == 0)
            l_acc = (
                l_acc
                + jnp.where(validA, cross_entropy(logitsA, lblA, from_probs), 0.0)
                + jnp.where(validB, cross_entropy(logitsB, lblB, from_probs), 0.0)
            )
            a_acc = (
                a_acc
                + jnp.where(validA, accuracy(logitsA, lblA), 0.0)
                + jnp.where(validB, accuracy(logitsB, lblB), 0.0)
            )
            with scope("stage_handoff"):
                bufA = _handoff(yA, fwd_perm, quant)
                bufB = _handoff(yB, bwd_perm, quant)
            return (bufA, bufB, l_acc, a_acc, stA, stB), None

        init = (
            v(jnp.zeros((amax,), compute_dtype)),
            v(jnp.zeros((amax,), compute_dtype)),
            v(jnp.zeros((), jnp.float32)),
            v(jnp.zeros((), jnp.float32)),
            stA_in,
            stB_in,
        )
        (_, _, l_acc, a_acc, stA, stB), _ = lax.scan(tick, init, jnp.arange(T, dtype=jnp.int32))
        return (loss_in + l_acc, acc_in + a_acc, stA, stB), None

    st0 = v(jnp.zeros((stat_n,), jnp.float32))
    (loss_acc, acc_acc, stA_acc, stB_acc), _ = lax.scan(
        one_pair,
        (v(jnp.zeros((), jnp.float32)), v(jnp.zeros((), jnp.float32)), st0, v(jnp.zeros((stat_n,), jnp.float32))),
        (x_groups, y_groups),
    )
    return loss_acc, acc_acc, stA_acc, stB_acc


def make_gems_1f1b_scan(
    part: StagePartition,
    branches: List[Callable],
    *,
    vary_axes: Tuple[str, ...],
    from_probs: bool,
    compute_dtype,
    seed_scale: float = 1.0,
    grad_x: bool = False,
    quant: Optional[QuantPolicy] = None,
):
    """1F1B counterpart of :func:`gems_dual_scan` (see :func:`make_1f1b_scan`
    for the schedule/custom_vjp design): ``f(flat_params, mirror_params,
    x_groups, y_groups) -> (loss_acc, acc_acc, statsA_acc, statsB_acc)``.

    Each tick runs one forward AND one backward micro-batch of BOTH streams:
    stream A's cotangents descend the stage chain (reverse ppermute) while
    stream B's — whose activations flow S-1→0 against the mirror rows —
    ascend it (forward ppermute), so the mirror streams keep interleaving
    under 1F1B exactly as they do under GPipe.  Stream B's accumulated
    gradients are returned as the MIRROR param cotangent; the engine-level
    ``mirror = ppermute(flat_params)`` transposes them home (the mirror
    permutation is an involution), identically to the GPipe AD path."""
    S = part.num_stages
    D = resid_depth(S)
    in_pack0 = part.act_packs[0]
    logits_n = part.out_pack.total
    nclass = part.out_pack.shapes[0][-1]
    amax = part.act_max
    stat_n = branches_stat_n(branches, part)
    # One combined forward+backward branch list serves BOTH streams: stream
    # B selects branch S-1-d, so the model's last stage (the self-seeding
    # branch) lands on device 0 — exactly where stream B drains.
    fb_branches = _make_fb_branches(
        branches, logits_n=logits_n, nclass=nclass, stat_n=stat_n,
        from_probs=from_probs, seed_scale=seed_scale,
        compute_dtype=compute_dtype,
    )
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    rev_perm = [(i + 1, i) for i in range(S - 1)]

    def v(t):
        return pcast(t, tuple(vary_axes), to="varying")

    def run(flat_params, mirror_params, x_groups, y_groups):
        lead = jax.tree.leaves(x_groups)[0]
        Pn = lead.shape[2]
        T = Pn + 2 * (S - 1)
        d = lax.axis_index(AXIS_STAGE)
        sB = S - 1 - d  # stream B's stage on this device
        is_lastA = d == S - 1
        is_lastB = d == 0
        z = jnp.zeros

        def sel(tree, j, p):
            return jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a[j], p, keepdims=False),
                tree,
            )

        def one_pair(carry, pair):
            gA, gB, loss_in, acc_in, stA_in, stB_in = carry
            xp, yp = pair  # leaves [2, Pn, mb, ...], [2, Pn, mb]

            def tick(c, t):
                (bufA, bufB, cotA, cotB, resA, resB,
                 gA, gB, gxA, gxB, l_acc, a_acc, stA, stB) = c
                with scope("fwd_tick"), scope("mb_inject"):
                    p_in = jnp.clip(t, 0, Pn - 1)
                    injA = pad_to(
                        in_pack0.pack(sel(xp, 0, p_in), compute_dtype), amax
                    )
                    injB = pad_to(
                        in_pack0.pack(sel(xp, 1, p_in), compute_dtype), amax
                    )
                    bufA = jnp.where(d == 0, injA, bufA)
                    bufB = jnp.where(d == S - 1, injB, bufB)
                # Reads precede writes (ring depth is exactly the round
                # trip; see make_1f1b_scan); each stream's draining device
                # takes its live buf directly — stream A drains at d=S-1,
                # stream B at d=0.
                p_fA = t - d
                p_fB = t - sB
                vA = (p_fA >= 0) & (p_fA < Pn)
                vB = (p_fB >= 0) & (p_fB < Pn)
                p_bA = t - 2 * (S - 1) + d
                p_bB = t - (S - 1) - d
                vbA = (p_bA >= 0) & (p_bA < Pn)
                vbB = (p_bB >= 0) & (p_bB < Pn)
                # The self-seeding branch (A: d=S-1, B: d=0) statically
                # ignores a_in and backwards its live buf.
                a_inA = lax.dynamic_index_in_dim(
                    resA, jnp.clip(p_bA, 0, Pn - 1) % D, keepdims=False
                )
                a_inB = lax.dynamic_index_in_dim(
                    resB, jnp.clip(p_bB, 0, Pn - 1) % D, keepdims=False
                )
                resA = ring_store(
                    resA, vA & (~is_lastA), jnp.clip(p_fA, 0, Pn - 1) % D, bufA
                )
                resB = ring_store(
                    resB, vB & (~is_lastB), jnp.clip(p_fB, 0, Pn - 1) % D, bufB
                )
                p_out = t - (S - 1)
                valid_out = (p_out >= 0) & (p_out < Pn)
                p_sel = jnp.clip(p_out, 0, Pn - 1)
                lblA = lax.dynamic_index_in_dim(yp[0], p_sel, keepdims=False)
                lblB = lax.dynamic_index_in_dim(yp[1], p_sel, keepdims=False)
                yA, sA_st, lA, aA, gaA, gpA = lax.switch(
                    d, fb_branches, flat_params, bufA, a_inA,
                    cotA.astype(compute_dtype), lblA, valid_out,
                )
                yB, sB_st, lB, aB, gaB, gpB = lax.switch(
                    sB, fb_branches, mirror_params, bufB, a_inB,
                    cotB.astype(compute_dtype), lblB, valid_out,
                )
                stA = stA + jnp.where(vA, sA_st, 0.0)
                stB = stB + jnp.where(vB, sB_st, 0.0)
                outA = valid_out & is_lastA
                outB = valid_out & is_lastB
                l_acc = (
                    l_acc + jnp.where(outA, lA, 0.0) + jnp.where(outB, lB, 0.0)
                )
                a_acc = (
                    a_acc + jnp.where(outA, aA, 0.0) + jnp.where(outB, aB, 0.0)
                )
                with scope("fwd_tick"), scope("stage_handoff"):
                    nbufA = (
                        _handoff(yA, fwd_perm, quant)
                        if fwd_perm else jnp.zeros_like(yA)
                    )
                    nbufB = (
                        _handoff(yB, rev_perm, quant)
                        if rev_perm else jnp.zeros_like(yB)
                    )
                with scope("bwd_tick"):
                    gA = gA + jnp.where(vbA, gpA, jnp.zeros_like(gpA))
                    gB = gB + jnp.where(vbB, gpB, jnp.zeros_like(gpB))
                    if grad_x:
                        gxa = in_pack0.unpack(
                            lax_slice(gaA, 0, in_pack0.total), dtype=compute_dtype
                        )
                        gxb = in_pack0.unpack(
                            lax_slice(gaB, 0, in_pack0.total), dtype=compute_dtype
                        )
                        slA, mA = jnp.clip(p_bA, 0, Pn - 1), vbA & (d == 0)
                        slB, mB = jnp.clip(p_bB, 0, Pn - 1), vbB & (d == S - 1)
                        gxA = jax.tree.map(
                            lambda G, g: scatter_part_row(G, g, slA, mA),
                            gxA, gxa,
                        )
                        gxB = jax.tree.map(
                            lambda G, g: scatter_part_row(G, g, slB, mB),
                            gxB, gxb,
                        )
                    with scope("cot_handoff"):
                        cotA = (
                            _handoff(gaA, rev_perm, quant)
                            if rev_perm else jnp.zeros_like(gaA)
                        )
                        cotB = (
                            _handoff(gaB, fwd_perm, quant)
                            if fwd_perm else jnp.zeros_like(gaB)
                        )
                return (nbufA, nbufB, cotA, cotB, resA, resB,
                        gA, gB, gxA, gxB, l_acc, a_acc, stA, stB), None

            # scope: see make_1f1b_scan — zero inits sunk into the stage
            # dispatch conditional need a name for HBM attribution.
            with scope("schedule_init"):
                gx0 = (
                    jax.tree.map(
                        lambda a_: v(z(a_.shape[1:], compute_dtype)), xp
                    )
                    if grad_x
                    else ()
                )
                init = (
                    v(z((amax,), compute_dtype)), v(z((amax,), compute_dtype)),
                    v(z((amax,), compute_dtype)), v(z((amax,), compute_dtype)),
                    v(z((D, amax), compute_dtype)),
                    v(z((D, amax), compute_dtype)),
                    gA, gB, gx0, gx0,
                    v(z((), jnp.float32)), v(z((), jnp.float32)),
                    stA_in, stB_in,
                )
            (_, _, _, _, _, _, gA, gB, gxA, gxB, l_acc, a_acc, stA, stB), _ = (
                lax.scan(tick, init, jnp.arange(T, dtype=jnp.int32))
            )
            gx_pair = (
                jax.tree.map(lambda a_, b_: jnp.stack([a_, b_]), gxA, gxB)
                if grad_x
                else ()
            )
            return (gA, gB, loss_in + l_acc, acc_in + a_acc, stA, stB), gx_pair

        st0 = v(z((stat_n,), jnp.float32))
        g0 = v(z(flat_params.shape, flat_params.dtype))
        (gA, gB, loss_acc, acc_acc, stA_acc, stB_acc), gx = lax.scan(
            one_pair,
            (g0, v(z(flat_params.shape, flat_params.dtype)),
             v(z((), jnp.float32)), v(z((), jnp.float32)),
             st0, v(z((stat_n,), jnp.float32))),
            (x_groups, y_groups),
        )
        return loss_acc, acc_acc, stA_acc, stB_acc, gA, gB, gx

    return _wrap_schedule_vjp(
        run, n_params=2, n_outs=4, seed_scale=seed_scale, grad_x=grad_x
    )
