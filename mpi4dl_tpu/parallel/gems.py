"""GEMS: bidirectional ("memory-aware") model parallelism, TPU-native.

Reference behaviour (``src/torchgems/gems_master.py``,
``train_spatial_master.py``): a second weight replica is laid out on the SAME
devices with stage order reversed (rank i hosts stage S-1-i), and each step
trains batch A through the forward chain and batch B through the reversed
chain, filling the pipeline bubbles in both directions; the two replicas'
gradients are combined by a mirrored-pair allreduce (``comm.py:460-504``) or
overlapped flat-buffer exchanges (MASTER-OPT,
``train_spatial_master.py:229-455``).

TPU-native re-design (this module):

- There is ONE set of weights: the [S, Pmax] stage-sharded flat buffer.  The
  reverse replica on device d is ``mirror = ppermute(buf, stage, i→S-1-i)`` —
  one ICI permute per step instead of a second resident optimizer state +
  param exchange protocol.  (SURVEY §7.6 flags this elimination as the thing
  to explore; it also makes MASTER-OPT moot: the replicas cannot diverge.)
- Both streams run in the SAME ``lax.scan``: buffer A rotates d→d+1, buffer B
  rotates d→d-1; device d applies stage d to A and stage S-1-d to B each tick
  (two switch branches back-to-back — XLA interleaves them, which is exactly
  the bidirectional bubble-filling).
- The mirrored-pair gradient combine is *free*: batch B's loss reaches the
  true weights through the mirror ppermute, so its adjoint routes the reverse
  replica's gradients back to their home stages automatically.
- ``times`` (reference ``--times`` replication, gems_master.py:87-102)
  processes `times` A/B pairs per step, accumulating gradients, then updates
  once — 2·times micro-batch groups per optimizer step.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.parallel.partition import StagePartition
from mpi4dl_tpu.parallel.pipeline import PipelineState, grad_pmean, metric_psum
from mpi4dl_tpu.quant.policy import QuantPolicy
from mpi4dl_tpu.parallel.stage_common import (
    gems_dual_scan,
    make_gems_1f1b_scan,
    make_stage_branches,
    restore_opt_rows,
    scatter_stage_stats,
    squeeze_opt_rows,
    stage_opt_specs,
    use_1f1b_cell_remat,
)
from mpi4dl_tpu.train import Optimizer
from mpi4dl_tpu.mesh import AXIS_DATA, AXIS_STAGE


def make_gems_train_step(
    part: StagePartition,
    optimizer: Optimizer,
    mesh: Mesh,
    parts: int,
    times: int = 1,
    compute_dtype=jnp.float32,
    remat: bool = True,
    from_probs: bool = False,
    with_data_axis: bool = False,
    bn_stats: bool = True,
    donate: bool = False,
    schedule: str = "gpipe",
    quant: Optional[QuantPolicy] = None,
):
    """Build the GEMS step: x is [2 * times * parts * mb, H, W, C]; the first
    half of each pair flows forward, the second backward.

    ``schedule="1f1b"`` swaps the dual tick loop for its manual-backward
    1F1B counterpart (stage_common.make_gems_1f1b_scan) — the mirror streams
    keep interleaving, with both streams' cotangent ppermutes riding the
    same ticks as the activations.

    ``quant``: opt-in quantized-collective policy (docs/quantization.md);
    both streams' activation/cotangent handoffs and the DP grad/stats
    pmeans quantize — the gems_mirror ppermute does NOT (it moves
    parameters)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}; use 'gpipe' or '1f1b'")
    S = part.num_stages
    Pn = parts
    ctx = ApplyCtx(train=True)
    mirror_perm = [(i, S - 1 - i) for i in range(S)]
    grad_axes: Tuple[str, ...] = (AXIS_DATA,) if with_data_axis else ()

    with_stats = bn_stats and part.stat_max > 0
    branches = make_stage_branches(
        part, ctx, compute_dtype, remat and schedule == "gpipe", with_stats,
        vary_axes=(AXIS_STAGE,) + grad_axes,
        cell_remat=schedule == "1f1b" and use_1f1b_cell_remat(part),
    )
    scan_1f1b = (
        make_gems_1f1b_scan(
            part, branches,
            vary_axes=(AXIS_STAGE,) + grad_axes,
            from_probs=from_probs, compute_dtype=compute_dtype,
            quant=quant,
        )
        if schedule == "1f1b"
        else None
    )

    def sharded_step(param_row, opt_state, x, labels):
        flat_params = param_row[0]
        # Stage-sharded opt rows squeeze like the param row; replicated
        # scalar leaves pass through (see pipeline.py).
        opt_local = squeeze_opt_rows(opt_state)
        groups = 2 * times
        mb = x.shape[0] // (groups * Pn)
        # [times, 2, parts, mb, ...]
        xs = x.reshape(times, 2, Pn, mb, *x.shape[1:]).astype(compute_dtype)
        ys = labels.reshape(times, 2, Pn, mb)

        def loss_and_metrics(flat_params):
            # The reverse replica's params: device d gets stage S-1-d's row.
            with scope("gems_mirror"):
                mirror_params = lax.ppermute(
                    flat_params, AXIS_STAGE, mirror_perm
                )
            if schedule == "1f1b":
                with scope("gems_1f1b_scan"):
                    loss_acc, acc_acc, stA, stB = scan_1f1b(
                        flat_params, mirror_params, xs, ys
                    )
            else:
                with scope("gems_dual_scan"):
                    loss_acc, acc_acc, stA, stB = gems_dual_scan(
                        part, branches, flat_params, mirror_params, xs, ys,
                        vary_axes=(AXIS_STAGE,) + grad_axes,
                        from_probs=from_probs,
                        compute_dtype=compute_dtype,
                        quant=quant,
                    )
            denom = 2 * times * Pn
            with scope("loss_reduce"):
                loss = metric_psum(loss_acc, (AXIS_STAGE,)) / denom
                acc = metric_psum(acc_acc, (AXIS_STAGE,)) / denom
                if grad_axes:
                    loss = lax.pmean(loss, grad_axes)
                    acc = lax.pmean(acc, grad_axes)
            # Stream B's stats belong to stage S-1-d: route them home via the
            # mirror permute, then average over all 2*times*Pn deposits (each
            # stream contributed times*Pn).
            with scope("stats_mirror"):
                stats = (stA + lax.ppermute(stB, AXIS_STAGE, mirror_perm)) / denom
            return loss, (acc, stats)

        (loss, (acc, stats)), grads = jax.value_and_grad(
            loss_and_metrics, has_aux=True
        )(flat_params)
        if grad_axes:
            with scope("grad_reduce"):
                grads = grad_pmean(grads, grad_axes, quant)
        with scope("optimizer_update"):
            new_flat, new_opt = optimizer.update(flat_params, grads, opt_local)
        if with_stats:
            if grad_axes:
                with scope("stats_reduce"):
                    stats = grad_pmean(stats, grad_axes, quant)
            new_flat = scatter_stage_stats(part, new_flat, stats)
        return (
            new_flat[None],
            restore_opt_rows(new_opt, opt_state),
            {"loss": loss, "accuracy": acc},
        )

    pspec = P(AXIS_STAGE, None)
    ospec = stage_opt_specs(optimizer, part)
    dspec = P(AXIS_DATA) if with_data_axis else P()
    smapped = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(pspec, ospec, dspec, dspec),
        out_specs=(pspec, ospec, P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state: PipelineState, x, labels):
        pb, opt, metrics = smapped(state.param_buf, state.opt_state, x, labels)
        return PipelineState(pb, opt, state.step + 1), metrics

    return step
