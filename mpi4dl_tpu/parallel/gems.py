"""GEMS: bidirectional ("memory-aware") model parallelism, TPU-native.

Reference behaviour (``src/torchgems/gems_master.py``,
``train_spatial_master.py``): a second weight replica is laid out on the SAME
devices with stage order reversed (rank i hosts stage S-1-i), and each step
trains batch A through the forward chain and batch B through the reversed
chain, filling the pipeline bubbles in both directions; the two replicas'
gradients are combined by a mirrored-pair allreduce (``comm.py:460-504``) or
overlapped flat-buffer exchanges (MASTER-OPT,
``train_spatial_master.py:229-455``).

TPU-native re-design (this module):

- There is ONE set of weights: the [S, Pmax] stage-sharded flat buffer.  The
  reverse replica on device d is ``mirror = ppermute(buf, stage, i→S-1-i)`` —
  one ICI permute per step instead of a second resident optimizer state +
  param exchange protocol.  (SURVEY §7.6 flags this elimination as the thing
  to explore; it also makes MASTER-OPT moot: the replicas cannot diverge.)
- Both streams run in the SAME ``lax.scan``: buffer A rotates d→d+1, buffer B
  rotates d→d-1; device d applies stage d to A and stage S-1-d to B each tick
  (two switch branches back-to-back — XLA interleaves them, which is exactly
  the bidirectional bubble-filling).
- The mirrored-pair gradient combine is *free*: batch B's loss reaches the
  true weights through the mirror ppermute, so its adjoint routes the reverse
  replica's gradients back to their home stages automatically.
- ``times`` (reference ``--times`` replication, gems_master.py:87-102)
  processes `times` A/B pairs per step, accumulating gradients, then updates
  once — 2·times micro-batch groups per optimizer step.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.parallel.partition import StagePartition, lax_slice, pad_to
from mpi4dl_tpu.parallel.pipeline import PipelineState
from mpi4dl_tpu.parallel.stage_common import make_stage_branches
from mpi4dl_tpu.train import Optimizer, accuracy, cross_entropy


def make_gems_train_step(
    part: StagePartition,
    optimizer: Optimizer,
    mesh: Mesh,
    parts: int,
    times: int = 1,
    compute_dtype=jnp.float32,
    remat: bool = True,
    from_probs: bool = False,
    with_data_axis: bool = False,
):
    """Build the GEMS step: x is [2 * times * parts * mb, H, W, C]; the first
    half of each pair flows forward, the second backward."""
    S = part.num_stages
    Pn = parts
    T = Pn + S - 1
    ctx = ApplyCtx(train=True)
    amax = part.act_max
    mirror_perm = [(i, S - 1 - i) for i in range(S)]
    fwd_perm = [(i, i + 1) for i in range(S - 1)]
    bwd_perm = [(i + 1, i) for i in range(S - 1)]
    grad_axes: Tuple[str, ...] = ("data",) if with_data_axis else ()

    branches = make_stage_branches(part, ctx, compute_dtype, remat)

    def sharded_step(param_row, opt_state, x, labels):
        flat_params = param_row[0]
        d = lax.axis_index("stage")
        groups = 2 * times
        mb = x.shape[0] // (groups * Pn)
        # [times, 2, parts, mb, ...]
        xs = x.reshape(times, 2, Pn, mb, *x.shape[1:]).astype(compute_dtype)
        ys = labels.reshape(times, 2, Pn, mb)
        in_pack0 = part.act_packs[0]
        logits_n = part.out_pack.total
        nclass = part.out_pack.shapes[0][-1]
        vary = ("stage",) + grad_axes
        v = lambda t: lax.pcast(t, vary, to="varying")

        def loss_and_metrics(flat_params):
            # The reverse replica's params: device d gets stage S-1-d's row.
            mirror_params = lax.ppermute(flat_params, "stage", mirror_perm)

            def one_pair(carry, pair):
                loss_in, acc_in = carry
                xa, ya_lbl = pair[0][0], pair[1][0]
                xb, yb_lbl = pair[0][1], pair[1][1]

                def tick(c, t):
                    bufA, bufB, l_acc, a_acc = c
                    p_in = jnp.clip(t, 0, Pn - 1)
                    injA = pad_to(
                        in_pack0.pack(
                            lax.dynamic_index_in_dim(xa, p_in, keepdims=False),
                            compute_dtype,
                        ),
                        amax,
                    )
                    injB = pad_to(
                        in_pack0.pack(
                            lax.dynamic_index_in_dim(xb, p_in, keepdims=False),
                            compute_dtype,
                        ),
                        amax,
                    )
                    bufA = jnp.where(d == 0, injA, bufA)
                    bufB = jnp.where(d == S - 1, injB, bufB)
                    yA = lax.switch(d, branches, flat_params, bufA)
                    yB = lax.switch(S - 1 - d, branches, mirror_params, bufB)
                    p_out = t - (S - 1)
                    in_range = (p_out >= 0) & (p_out < Pn)
                    lblA = lax.dynamic_index_in_dim(
                        ya_lbl, jnp.clip(p_out, 0, Pn - 1), keepdims=False
                    )
                    lblB = lax.dynamic_index_in_dim(
                        yb_lbl, jnp.clip(p_out, 0, Pn - 1), keepdims=False
                    )
                    logitsA = lax_slice(yA, 0, logits_n).reshape(mb, nclass)
                    logitsB = lax_slice(yB, 0, logits_n).reshape(mb, nclass)
                    validA = in_range & (d == S - 1)
                    validB = in_range & (d == 0)
                    l_acc = (
                        l_acc
                        + jnp.where(validA, cross_entropy(logitsA, lblA, from_probs), 0.0)
                        + jnp.where(validB, cross_entropy(logitsB, lblB, from_probs), 0.0)
                    )
                    a_acc = (
                        a_acc
                        + jnp.where(validA, accuracy(logitsA, lblA), 0.0)
                        + jnp.where(validB, accuracy(logitsB, lblB), 0.0)
                    )
                    bufA = lax.ppermute(yA, "stage", fwd_perm)
                    bufB = lax.ppermute(yB, "stage", bwd_perm)
                    return (bufA, bufB, l_acc, a_acc), None

                init = (
                    v(jnp.zeros((amax,), compute_dtype)),
                    v(jnp.zeros((amax,), compute_dtype)),
                    v(jnp.zeros(())),
                    v(jnp.zeros(())),
                )
                (_, _, l_acc, a_acc), _ = lax.scan(tick, init, jnp.arange(T))
                return (loss_in + l_acc, acc_in + a_acc), None

            (loss_acc, acc_acc), _ = lax.scan(
                one_pair, (v(jnp.zeros(())), v(jnp.zeros(()))), (xs, ys)
            )
            denom = 2 * times * Pn
            loss = lax.psum(loss_acc, "stage") / denom
            acc = lax.psum(acc_acc, "stage") / denom
            if grad_axes:
                loss = lax.pmean(loss, grad_axes)
                acc = lax.pmean(acc, grad_axes)
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_and_metrics, has_aux=True)(
            flat_params
        )
        if grad_axes:
            grads = lax.pmean(grads, grad_axes)
        new_flat, new_opt = optimizer.update(flat_params, grads, opt_state)
        return new_flat[None], new_opt, {"loss": loss, "accuracy": acc}

    pspec = P("stage", None)
    dspec = P("data") if with_data_axis else P()
    smapped = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(pspec, pspec, dspec, dspec),
        out_specs=(pspec, pspec, P()),
    )

    @jax.jit
    def step(state: PipelineState, x, labels):
        pb, opt, metrics = smapped(state.param_buf, state.opt_state, x, labels)
        return PipelineState(pb, opt, state.step + 1), metrics

    return step
