"""The SPMD pipeline engine (LP + GPipe PP), single jitted program.

Reference behaviour being re-expressed: ``train_model`` runs per-rank
processes exchanging activations/grads with tagged MPI send/recv and loops
micro-batch "parts" all-forward-then-all-backward
(``mp_pipeline.py:294-432``, ``:509-534``).  Here the whole schedule is ONE
``lax.scan`` inside ONE ``shard_map``:

- Each device holds its stage's flat parameter row ([S, Pmax] sharded over
  ``stage``) and runs its stage via ``lax.switch`` (stages are heterogeneous;
  branch s statically unpacks stage s's params/activations).
- The activation buffer rotates stage→stage+1 with one non-wrapping
  ``ppermute`` per tick; stage 0 overwrites its buffer with the next
  micro-batch injection.
- T = parts + S - 1 ticks fill and drain the pipe (GPipe).  Bubble ticks
  compute on don't-care data and are masked out of the loss — the same
  wall-clock the reference's idle bubbles cost, with no control-flow
  divergence in the compiled program.
- **The backward pass is jax.grad of the scan.**  AD transposes the forward
  ppermute into the reverse-direction cotangent ppermute (the reference's
  explicit grad send/recv chain, mp_pipeline.py:365-432) and replays ticks in
  reverse order — all-forward-then-all-backward falls out, with per-stage
  rematerialisation (jax.checkpoint) bounding activation memory exactly like
  GPipe.

No recv buffers, no tags, no GEMS_INVERSE rank mirroring — placement is the
mesh, ordering is dataflow.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.cells import CellModel
from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.parallel.partition import StagePartition, lax_slice, pad_to
from mpi4dl_tpu.train import Optimizer, accuracy, cross_entropy


@dataclasses.dataclass
class PipelineState:
    """Flat training state: [S, Pmax] param buffer + optimizer state."""

    param_buf: jax.Array
    opt_state: Any
    step: jax.Array


jax.tree_util.register_dataclass(
    PipelineState, data_fields=["param_buf", "opt_state", "step"], meta_fields=[]
)


def make_pipeline_train_step(
    part: StagePartition,
    optimizer: Optimizer,
    mesh: Mesh,
    parts: int,
    compute_dtype=jnp.float32,
    remat: bool = True,
    from_probs: bool = False,
    with_data_axis: bool = False,
    loss_scale: float = 1.0,
):
    """Build `(PipelineState, x, labels) -> (PipelineState, metrics)`.

    x: [B, H, W, C] global batch (B = parts * microbatch); labels: [B].
    """
    S = part.num_stages
    Pn = parts
    T = Pn + S - 1
    ctx = ApplyCtx(train=True)
    amax = part.act_max

    def stage_branch(s: int):
        pk_in = part.act_packs[s]
        out_pk = part.act_packs[s + 1] if s + 1 < S else part.out_pack

        def fn(flat_params, buf):
            act = pk_in.unpack(lax_slice(buf, 0, pk_in.total), dtype=compute_dtype)
            y = part.stage_apply(s, flat_params, act, ctx)
            return pad_to(out_pk.pack(y, compute_dtype), amax)

        return jax.checkpoint(fn) if remat else fn

    branches = [stage_branch(s) for s in range(S)]

    grad_axes: Tuple[str, ...] = ("data",) if with_data_axis else ()

    def sharded_step(param_row, opt_state, x, labels):
        # param_row: [1, Pmax] local stage block; squeeze to [Pmax].
        flat_params = param_row[0]
        s_idx = lax.axis_index("stage")
        mb = x.shape[0] // Pn
        x_parts = x.reshape(Pn, mb, *x.shape[1:]).astype(compute_dtype)
        y_parts = labels.reshape(Pn, mb)
        in_pack0 = part.act_packs[0]
        logits_n = part.out_pack.total
        nclass = part.out_pack.shapes[0][-1]
        is_last = s_idx == S - 1

        def loss_and_metrics(flat_params):
            def tick(carry, t):
                buf, loss_acc, acc_acc = carry
                p_in = jnp.clip(t, 0, Pn - 1)
                inj = pad_to(
                    in_pack0.pack(
                        lax.dynamic_index_in_dim(x_parts, p_in, keepdims=False),
                        compute_dtype,
                    ),
                    amax,
                )
                buf = jnp.where(s_idx == 0, inj, buf)
                y = lax.switch(s_idx, branches, flat_params, buf)
                # Last stage: loss for part p = t - (S-1) when in range.
                p_out = t - (S - 1)
                valid = (p_out >= 0) & (p_out < Pn) & is_last
                logits = lax_slice(y, 0, logits_n).reshape(mb, nclass)
                lbl = lax.dynamic_index_in_dim(
                    y_parts, jnp.clip(p_out, 0, Pn - 1), keepdims=False
                )
                l = cross_entropy(logits, lbl, from_probs)
                a = accuracy(logits, lbl)
                loss_acc = loss_acc + jnp.where(valid, l, 0.0)
                acc_acc = acc_acc + jnp.where(valid, a, 0.0)
                # Hand activations to the next stage (non-wrap: stage 0's
                # stale recv is overwritten by injection next tick).
                buf = lax.ppermute(y, "stage", [(i, i + 1) for i in range(S - 1)])
                return (buf, loss_acc, acc_acc), None

            # Initial carries must be marked varying over the axes the loop
            # makes them vary on, or shard_map's AD produces wrong collective
            # transposes (grads scaled by axis size).
            vary = ("stage",) + grad_axes

            def v(t):
                return lax.pcast(t, vary, to="varying")

            buf0 = v(jnp.zeros((amax,), compute_dtype))
            (buf, loss_acc, acc_acc), _ = lax.scan(
                tick, (buf0, v(jnp.zeros(())), v(jnp.zeros(()))), jnp.arange(T)
            )
            # Only the last stage accumulated; psum broadcasts to all stages
            # (and sums over data-parallel groups' mean below).
            loss = lax.psum(loss_acc, "stage") / Pn
            acc = lax.psum(acc_acc, "stage") / Pn
            if grad_axes:
                loss = lax.pmean(loss, grad_axes)
                acc = lax.pmean(acc, grad_axes)
            return loss * loss_scale, acc

        (loss, acc), grads = jax.value_and_grad(loss_and_metrics, has_aux=True)(
            flat_params
        )
        if loss_scale != 1.0:
            grads = grads / loss_scale
            loss = loss / loss_scale
        if grad_axes:
            grads = lax.pmean(grads, grad_axes)
        new_flat, new_opt = optimizer.update(flat_params, grads, opt_state)
        return new_flat[None], new_opt, {"loss": loss, "accuracy": acc}

    pspec = P("stage", None)
    dspec = P("data") if with_data_axis else P()
    smapped = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(pspec, pspec, dspec, dspec),
        out_specs=(pspec, pspec, P()),
    )

    @jax.jit
    def step(state: PipelineState, x, labels):
        pb, opt, metrics = smapped(state.param_buf, state.opt_state, x, labels)
        return PipelineState(pb, opt, state.step + 1), metrics

    return step


def init_pipeline_state(
    part: StagePartition, params_list, optimizer: Optimizer, mesh: Mesh
) -> PipelineState:
    """Pack params into the stage-sharded buffer and init the optimizer
    stage-locally (opt state shares the buffer's sharding)."""
    buf = part.pack_params(params_list)
    sharding = NamedSharding(mesh, P("stage", None))
    buf = jax.device_put(buf, sharding)
    opt_state = jax.tree.map(
        lambda z: jax.device_put(z, sharding), optimizer.init(buf)
    )
    return PipelineState(buf, opt_state, jnp.zeros((), jnp.int32))
