"""The SPMD pipeline engine (LP + GPipe PP), single jitted program.

Reference behaviour being re-expressed: ``train_model`` runs per-rank
processes exchanging activations/grads with tagged MPI send/recv and loops
micro-batch "parts" all-forward-then-all-backward
(``mp_pipeline.py:294-432``, ``:509-534``).  Here the whole schedule is ONE
``lax.scan`` inside ONE ``shard_map``:

- Each device holds its stage's flat parameter row ([S, Pmax] sharded over
  ``stage``) and runs its stage via ``lax.switch`` (stages are heterogeneous;
  branch s statically unpacks stage s's params/activations).
- The activation buffer rotates stage→stage+1 with one non-wrapping
  ``ppermute`` per tick; stage 0 overwrites its buffer with the next
  micro-batch injection.
- T = parts + S - 1 ticks fill and drain the pipe (GPipe).  Bubble ticks
  compute on don't-care data and are masked out of the loss — the same
  wall-clock the reference's idle bubbles cost, with no control-flow
  divergence in the compiled program.
- **The backward pass is jax.grad of the scan** (``schedule="gpipe"``, the
  default).  AD transposes the forward ppermute into the reverse-direction
  cotangent ppermute (the reference's explicit grad send/recv chain,
  mp_pipeline.py:365-432) and replays ticks in reverse order —
  all-forward-then-all-backward falls out, with per-stage rematerialisation
  (jax.checkpoint) bounding activation memory exactly like GPipe.
- ``schedule="1f1b"`` replaces the AD replay with a schedule-level manual
  backward (stage_common.make_1f1b_scan): each tick runs one forward AND
  one backward micro-batch, bounding live activations to O(stages) instead
  of the replay's O(parts) tick carries (docs/pipeline.md).

No recv buffers, no tags, no GEMS_INVERSE rank mirroring — placement is the
mesh, ordering is dataflow.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.layer_ctx import ApplyCtx
from mpi4dl_tpu.obs.scopes import scope
from mpi4dl_tpu.parallel.partition import StagePartition
from mpi4dl_tpu.parallel.stage_common import (
    gpipe_scan,
    make_1f1b_scan,
    make_stage_branches,
    put_stage_opt,
    restore_opt_rows,
    scatter_stage_stats,
    squeeze_opt_rows,
    stage_opt_specs,
    use_1f1b_cell_remat,
)
from mpi4dl_tpu.quant.collectives import quantized_pmean
from mpi4dl_tpu.quant.policy import QuantPolicy
from mpi4dl_tpu.train import Optimizer
from mpi4dl_tpu.mesh import AXIS_DATA, AXIS_STAGE


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def metric_psum(x, axes):  # analysis: ok(unscoped-collective) — callers own the loss_reduce scope
    """``lax.psum`` for the scalar loss/metric accumulators, with a wire-free
    transpose.  jax's psum is its own transpose, so differentiating
    ``psum(loss_acc, axes)`` re-reduces the cotangent over the wire — but the
    cotangent of a scalar loss is replicated (value_and_grad seeds 1.0), so
    that backward all-reduce only multiplies by the axis size.  The custom
    rule does the multiply statically (``psum(1, axes)`` constant-folds);
    bit-identical gradients, one collective fewer per step (ircheck:
    wasted-wire).  Only sound where the cotangent is axis-invariant — i.e.
    reductions feeding a scalar objective, not arbitrary psums."""
    return lax.psum(x, axes)


def _metric_psum_fwd(x, axes):
    return lax.psum(x, axes), None  # analysis: ok(unscoped-collective) — callers own the loss_reduce scope


def _metric_psum_bwd(axes, _, ct):
    # psum of a trace-time constant constant-folds: no wire, no scope owner.
    return (ct * lax.psum(1, axes),)  # analysis: ok(unscoped-collective)


metric_psum.defvjp(_metric_psum_fwd, _metric_psum_bwd)


def grad_pmean(x, axes, quant: Optional[QuantPolicy]):  # analysis: ok(unscoped-collective) — callers own the grad_reduce/stats_reduce scopes
    """The engines' gradient/BN-stats ``pmean``, EQuARX-style-quantized
    when the policy's ``grad`` class is on (quantized all_to_all → exact
    f32 dequant-accumulate per shard → quantized all_gather; see
    quant/collectives.quantized_pmean).  Runs OUTSIDE AD — the engines
    reduce value_and_grad outputs.  Shared by pipeline/gems/sp_pipeline."""
    mode = quant.mode("grad") if quant is not None else None
    if mode:
        return quantized_pmean(x, axes, mode, quant.block)
    return lax.pmean(x, axes)


@dataclasses.dataclass
class PipelineState:
    """Flat training state: [S, Pmax] param buffer + optimizer state."""

    param_buf: jax.Array
    opt_state: Any
    step: jax.Array


jax.tree_util.register_dataclass(
    PipelineState, data_fields=["param_buf", "opt_state", "step"], meta_fields=[]
)


def make_pipeline_train_step(
    part: StagePartition,
    optimizer: Optimizer,
    mesh: Mesh,
    parts: int,
    compute_dtype=jnp.float32,
    remat: bool = True,
    from_probs: bool = False,
    with_data_axis: bool = False,
    loss_scale: float = 1.0,
    bn_stats: bool = True,
    donate: bool = False,
    schedule: str = "gpipe",
    quant: Optional[QuantPolicy] = None,
):
    """Build `(PipelineState, x, labels) -> (PipelineState, metrics)`.

    x: [B, H, W, C] global batch (B = parts * microbatch); labels: [B].

    ``schedule``: ``"gpipe"`` (default — all-forward-then-all-backward as
    jax.grad of the tick scan, the exactness oracle) or ``"1f1b"`` (the
    one-forward-one-backward schedule with a schedule-level manual backward,
    stage_common.make_1f1b_scan: O(stages) live activations instead of
    O(parts)).  Both produce the same parameters after a step up to
    accumulation-order rounding; 1F1B always recomputes stage forwards
    inside its backward branches, so ``remat`` is moot there (branches are
    built unwrapped).  docs/pipeline.md covers when to pick which.

    ``quant``: opt-in quantized-collective policy (docs/quantization.md) —
    ``handoff`` quantizes the tick loop's stage/cotangent ppermutes,
    ``grad`` the DP gradient/stats pmeans; ``None`` is bit-identical to
    the unquantized engine.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}; use 'gpipe' or '1f1b'")
    S = part.num_stages
    Pn = parts
    ctx = ApplyCtx(train=True)

    grad_axes: Tuple[str, ...] = (AXIS_DATA,) if with_data_axis else ()
    with_stats = bn_stats and part.stat_max > 0
    branches = make_stage_branches(
        part, ctx, compute_dtype, remat and schedule == "gpipe", with_stats,
        vary_axes=(AXIS_STAGE,) + grad_axes,
        cell_remat=schedule == "1f1b" and use_1f1b_cell_remat(part),
    )
    scan_1f1b = (
        make_1f1b_scan(
            part, branches,
            vary_axes=(AXIS_STAGE,) + grad_axes,
            from_probs=from_probs, compute_dtype=compute_dtype,
            seed_scale=loss_scale, quant=quant,
        )
        if schedule == "1f1b"
        else None
    )

    def sharded_step(param_row, opt_state, x, labels):
        # param_row: [1, Pmax] local stage block; squeeze to [Pmax] (the
        # optimizer-state moment rows get the same treatment; Adam's
        # replicated scalar step counter passes through — stage_common.
        # squeeze_opt_rows).
        flat_params = param_row[0]
        opt_local = squeeze_opt_rows(opt_state)
        mb = x.shape[0] // Pn
        x_parts = x.reshape(Pn, mb, *x.shape[1:]).astype(compute_dtype)
        y_parts = labels.reshape(Pn, mb)

        def loss_and_metrics(flat_params):
            if schedule == "1f1b":
                with scope("pp_1f1b_scan"):
                    loss_acc, acc_acc, st_acc = scan_1f1b(
                        flat_params, x_parts, y_parts
                    )
            else:
                with scope("gpipe_scan"):
                    loss_acc, acc_acc, st_acc = gpipe_scan(
                        part, branches, flat_params, x_parts, y_parts,
                        vary_axes=(AXIS_STAGE,) + grad_axes,
                        from_probs=from_probs,
                        compute_dtype=compute_dtype,
                        quant=quant,
                    )
            # Only the last stage accumulated; psum broadcasts to all stages
            # (and sums over data-parallel groups' mean below).
            with scope("loss_reduce"):
                loss = metric_psum(loss_acc, (AXIS_STAGE,)) / Pn
                acc = metric_psum(acc_acc, (AXIS_STAGE,)) / Pn
                if grad_axes:
                    loss = lax.pmean(loss, grad_axes)
                    acc = lax.pmean(acc, grad_axes)
            return loss * loss_scale, (acc, st_acc / Pn)

        (loss, (acc, stats)), grads = jax.value_and_grad(
            loss_and_metrics, has_aux=True
        )(flat_params)
        if loss_scale != 1.0:
            grads = grads / loss_scale
            loss = loss / loss_scale
        if grad_axes:
            with scope("grad_reduce"):
                grads = grad_pmean(grads, grad_axes, quant)
        with scope("optimizer_update"):
            new_flat, new_opt = optimizer.update(flat_params, grads, opt_local)
        if with_stats:
            if grad_axes:
                with scope("stats_reduce"):
                    stats = grad_pmean(stats, grad_axes, quant)
            new_flat = scatter_stage_stats(part, new_flat, stats)
        return (
            new_flat[None],
            restore_opt_rows(new_opt, opt_state),
            {"loss": loss, "accuracy": acc},
        )

    pspec = P(AXIS_STAGE, None)
    ospec = stage_opt_specs(optimizer, part)
    dspec = P(AXIS_DATA) if with_data_axis else P()
    smapped = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(pspec, ospec, dspec, dspec),
        out_specs=(pspec, ospec, P()),
    )

    # donate=True: param/opt buffers update in place (one copy, not two, of
    # the stage buffers at peak).  Off by default: exact-match tests alias
    # param arrays across states.
    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state: PipelineState, x, labels):
        pb, opt, metrics = smapped(state.param_buf, state.opt_state, x, labels)
        return PipelineState(pb, opt, state.step + 1), metrics

    return step


def init_pipeline_state(
    part: StagePartition, params_list, optimizer: Optimizer, mesh: Mesh
) -> PipelineState:
    """Pack params into the stage-sharded buffer and init the optimizer
    stage-locally (opt state shares the buffer's sharding)."""
    buf = part.pack_params(params_list)
    sharding = NamedSharding(mesh, P(AXIS_STAGE, None))
    buf = jax.device_put(buf, sharding)
    # Moment buffers ride the stage sharding; scalar leaves (Adam's step
    # counter) are replicated — same rule as the engines' shard_map specs.
    opt_state = put_stage_opt(optimizer.init(buf), mesh)
    return PipelineState(buf, opt_state, jnp.zeros((), jnp.int32))
