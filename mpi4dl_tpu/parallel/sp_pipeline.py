"""SP x PP: spatial parallelism composed with the pipeline engine.

Reference behaviour being re-expressed: ``train_model_spatial``
(``src/torchgems/train_spatial.py:293-1458``) runs the first ``spatial_size``
pipeline split(s) spread over ``num_spatial_parts`` tile ranks (halo-exchange
convs), then hands tiles to the layer-parallel tail — via a joint-rank
gather + concat mosaic (``:690-721``, ``:1083-1188``) or the scatter/gather
LOCAL_DP_LP junction (``:809-1028``) — and pipelines micro-batch parts
through the tail ranks.

TPU-native re-design (one jitted SPMD program over mesh (data, stage, sph,
spw); every collective is uniform — see stage_common.py for why stage
branches must be pure compute):

- **SP phase**: the ``stage`` axis is data-parallel over the batch.  Every
  stage block takes its 1/S chunk of the batch and runs the spatial region
  tiled over (sph, spw) with halo exchanges.  Where the reference idles the
  tail GPUs during spatial compute (and the tile GPUs during tail compute),
  here every device computes the spatial region on distinct images —
  S x more spatial throughput from the same mesh.
- **Junction**: ``all_gather`` over the tile axes (the mosaic merge), then
  either replicate the tail per tile coordinate (junction='gather', the
  reference's plain SP→LP handoff) or batch-split over tile coordinates
  (junction='batch_split', the reference's LOCAL_DP_LP); finally an
  ``all_gather`` over ``stage`` lines junction activations up in micro-batch
  injection order.
- **PP phase**: the shared GPipe tick scan (stage_common.gpipe_scan) over the
  tail cells — or, under ``schedule="1f1b"``, the manual-backward 1F1B tick
  loop (stage_common.make_1f1b_scan; docs/pipeline.md).  The backward pass
  of BOTH phases is one jax.grad through the whole program: the junction
  gathers transpose into the tile/stage scatter of cotangents the reference
  implements by hand (the 1F1B scan's custom_vjp hands AD the tail-injection
  cotangents, so the same transposes fire either way).

Gradient combine — DERIVATION (validated exactly against single-device SGD
in tests/test_sp_pipeline.py for both junctions):

shard_map's AD reduces the cotangent of an axis-INVARIANT input itself: when
a replicated value (sp params, in_specs P(); tail rows, invariant over the
tile/data axes) feeds axis-varying compute, the transpose inserts the
cross-device psum so the returned cotangent is again invariant — including
the contributions routed home by the junction all_gather's adjoint
(reduce-scatter) and the ppermute transposes.  Each device's ``g_sp`` /
``g_tail`` therefore already IS the complete gradient of the
mean-over-devices loss.  The explicit ``pmean``s below are numerically the
identity on these already-reduced values — they exist to make the invariance
explicit (vma bookkeeping), not to combine anything; this is also why a
``psum`` over ``stage`` would multiply the gradient by exactly S.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.cells import CellModel
from mpi4dl_tpu.layer_ctx import ApplyCtx, SpatialCtx
from mpi4dl_tpu.obs.scopes import scope
import numpy as np

from mpi4dl_tpu.parallel.partition import (
    StagePartition,
    TreePack,
    pad_to,
    stat_leaf_info,
)
from mpi4dl_tpu.parallel.pipeline import grad_pmean, metric_psum
from mpi4dl_tpu.parallel.spatial import (
    apply_junction,
    apply_spatial_region,
    junction_shard_index,
)
from mpi4dl_tpu.quant.collectives import quantized_all_gather
from mpi4dl_tpu.quant.policy import QuantPolicy
from mpi4dl_tpu.parallel.stage_common import (
    gems_dual_scan,
    gpipe_scan,
    make_1f1b_scan,
    make_gems_1f1b_scan,
    make_stage_branches,
    put_stage_opt,
    restore_opt_rows,
    scatter_stage_stats,
    squeeze_opt_rows,
    stage_opt_specs,
    use_1f1b_cell_remat,
)
from mpi4dl_tpu.train import Optimizer, spatial_partition_spec
from mpi4dl_tpu.mesh import AXIS_DATA, AXIS_STAGE


@dataclasses.dataclass
class SPPipeline:
    """Static partition of a model into a spatial region + pipeline tail."""

    model: CellModel
    spatial_until: int
    sp: SpatialCtx
    sp_pack: TreePack  # spatial-region params, one flat vector
    tail_part: StagePartition  # pipeline partition of the tail cells
    junction: str  # 'gather' | 'batch_split'
    mb_tail: int  # per-device tail micro-batch
    # BN running-stat positions inside the spatial-region packing (the tail's
    # live in tail_part.stat_*): leaf indices into the unpacked tree + flat
    # positions in sp_buf for the write-back.
    sp_stat_leaf_ids: list = dataclasses.field(default_factory=list)
    sp_stat_idx: Optional[np.ndarray] = None
    # Multi-level spatial region: [(stop_cell, SpatialCtx)] — level 0 is `sp`;
    # None means the single level [(spatial_until, sp)].
    levels: Optional[list] = None
    # Junction batch-split degree (LOCAL_DP_LP, reference comm.py:278-294);
    # defaults to the final level's tile count.
    degree: int = 1
    # Storage dtype of sp_buf / tail_buf (bf_16_all — see StagePartition).
    param_dtype: Any = jnp.float32

    @classmethod
    def build(
        cls,
        model: CellModel,
        params_list,
        split_size: int,
        sp: SpatialCtx,
        microbatch: int,
        junction: str = "batch_split",
        balance=None,
        compute_dtype=jnp.float32,
        levels: Optional[list] = None,
        local_dp: Optional[int] = None,
        param_dtype=jnp.float32,
    ) -> "SPPipeline":
        su = model.spatial_until
        assert 0 < su < len(model.cells), f"spatial_until={su} must split the model"
        if levels is not None:
            assert levels[-1][0] == su, (levels, su)
            assert levels[0][1].rep_h == 1 and levels[0][1].rep_w == 1, (
                "level 0 must be the mesh-defining (rep=1) ctx"
            )
        sp_last = levels[-1][1] if levels else sp
        degree = local_dp if local_dp else sp_last.grid_h * sp_last.grid_w
        # Junction activation structure from abstract evaluation at GLOBAL
        # shapes (the reference's get_shapes_spatial tile math collapses into
        # eval_shape + one divide, train_spatial.py:61-238).
        ctx = ApplyCtx(train=True)
        jstruct = jax.eval_shape(
            lambda ps, xx: model.apply(ps, xx, ctx, start=0, stop=su),
            params_list[:su],
            jax.ShapeDtypeStruct((microbatch, *model.in_shape[1:]), compute_dtype),
        )
        if junction == "batch_split":
            assert microbatch % degree == 0, (microbatch, degree)
            mb_tail = microbatch // degree
        else:
            mb_tail = microbatch
        tail_in = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((mb_tail, *s.shape[1:]), compute_dtype),
            jstruct,
        )
        tail_model = CellModel(
            model.cells[su:],
            model.in_shape,
            model.num_classes,
            name=model.name + "_tail",
        )
        tail_part = StagePartition.build(
            tail_model, params_list[su:], split_size, tail_in,
            balance=balance, compute_dtype=compute_dtype, param_dtype=param_dtype,
        )
        sp_pack = TreePack.of(params_list[:su])
        sp_ids, sp_slots = stat_leaf_info(params_list[:su])
        sp_idx = (
            np.concatenate(
                [np.arange(o, o + s, dtype=np.int32) for o, s in sp_slots]
            )
            if sp_slots
            else None
        )
        return cls(
            model, su, sp, sp_pack, tail_part, junction, mb_tail, sp_ids, sp_idx,
            levels=levels, degree=degree, param_dtype=param_dtype,
        )

    def pack_spatial(self, params_list) -> jax.Array:
        return self.sp_pack.pack(params_list[: self.spatial_until], self.param_dtype)

    def unpack_all(self, sp_vec, tail_buf) -> list:
        """Reassemble the full params_list (host-side)."""
        return list(self.sp_pack.unpack(sp_vec)) + self.tail_part.unpack_params(tail_buf)


@dataclasses.dataclass
class SPPipelineState:
    sp_buf: jax.Array  # [sp_total] replicated
    tail_buf: jax.Array  # [S, Pmax] stage-sharded
    opt_sp: Any
    opt_tail: Any
    step: jax.Array


jax.tree_util.register_dataclass(
    SPPipelineState,
    data_fields=["sp_buf", "tail_buf", "opt_sp", "opt_tail", "step"],
    meta_fields=[],
)


def init_sp_pipeline_state(
    spp: SPPipeline, params_list, optimizer: Optimizer, mesh: Mesh
) -> SPPipelineState:
    sp_buf = jax.device_put(
        spp.pack_spatial(params_list), NamedSharding(mesh, P())
    )
    tail_sharding = NamedSharding(mesh, P(AXIS_STAGE, None))
    tail_buf = jax.device_put(spp.tail_part.pack_params(params_list[spp.spatial_until:]),
                              tail_sharding)
    opt_sp = optimizer.init(sp_buf)
    # Tail moment rows ride the stage sharding; scalar leaves (Adam's step
    # counter) are replicated — same rule as _make_sp_step's shard_map specs.
    opt_tail = put_stage_opt(optimizer.init(tail_buf), mesh)
    return SPPipelineState(sp_buf, tail_buf, opt_sp, opt_tail, jnp.zeros((), jnp.int32))


def _make_sp_step(
    spp: SPPipeline,
    optimizer: Optimizer,
    mesh: Mesh,
    lead_shape: Tuple[int, ...],
    scan_fn,
    denom: int,
    compute_dtype,
    remat: bool,
    with_data_axis: bool,
    bn_stats: bool = True,
    donate: bool = False,
    schedule: str = "gpipe",
    quant: Optional[QuantPolicy] = None,
):
    """Shared scaffolding of the SP(+GEMS) x PP steps: phase-1 spatial region,
    junction, tail scan (``scan_fn``), loss reduction, grad combine, update.

    ``schedule="1f1b"`` only affects how the tail branches are built here
    (unwrapped — the 1F1B scans recompute stage forwards in their own
    backward branches); the schedule itself lives in ``scan_fn``, whose
    custom_vjp hands the tail-injection cotangents back to this function's
    ``jax.value_and_grad``, which routes them through the junction/spatial
    transposes exactly as the GPipe AD path does.  The spatial region keeps
    its own remat setting either way.

    ``lead_shape`` shapes the injection pytree's leading dims —
    ``(Pn,)`` for GPipe, ``(times, 2, Pn)`` for the GEMS dual stream.
    ``scan_fn(branches, tail_flat, x_parts, y_parts, vary_axes)`` returns the
    boundary-stage (loss_acc, acc_acc, stats_avg); ``denom`` is the drained
    part count.

    BN running stats: the spatial region deposits once per step over the full
    per-device chunk (coarser batch-stat granularity than the per-micro-batch
    reference semantics — a documented, statistically stronger deviation); the
    tail deposits per valid tick via the scan, engine-normalized in scan_fn.
    """
    sp = spp.sp
    part = spp.tail_part
    S = part.num_stages
    su = spp.spatial_until
    levels = spp.levels if spp.levels is not None else [(su, sp)]
    sp_last = levels[-1][1]
    degree = spp.degree
    groups = 1
    for d in lead_shape:
        groups *= d
    tile_axes = tuple(a for a in (sp.axis_h, sp.axis_w) if a)
    grad_axes: Tuple[str, ...] = (AXIS_DATA,) if with_data_axis else ()
    sp_ctx = ApplyCtx(train=True, spatial=sp)
    tail_ctx = ApplyCtx(train=True)

    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}; use 'gpipe' or '1f1b'")
    with_stats_sp = bn_stats and bool(spp.sp_stat_leaf_ids)
    with_stats_tail = bn_stats and part.stat_max > 0
    branches = make_stage_branches(
        part, tail_ctx, compute_dtype, remat and schedule == "gpipe",
        with_stats_tail,
        vary_axes=(AXIS_STAGE,) + tile_axes + grad_axes,
        cell_remat=schedule == "1f1b" and use_1f1b_cell_remat(part),
    )

    def phase1(sp_flat, x_tile):
        """Spatial region on this device's (stage-chunk, tile): returns the
        tail injection pytree [*lead_shape, mb_tail, ...] in batch order,
        plus the spatial region's BN stat-update vector."""
        B = x_tile.shape[0]
        assert B % S == 0, f"batch {B} must divide over {S} stage blocks"
        chunk = B // S
        if spp.junction == "batch_split":
            assert chunk % degree == 0, (
                f"stage chunk {chunk} (= batch {B} / {S} stages) must divide "
                f"over junction degree {degree} for the batch_split junction; "
                f"choose batch = {groups} * microbatch with (B/S) % degree == 0"
            )
        s_idx = lax.axis_index(AXIS_STAGE)
        xs = lax.dynamic_slice_in_dim(x_tile, s_idx * chunk, chunk, axis=0)
        params_sp = spp.sp_pack.unpack(sp_flat)

        def region(ps, xx):
            if with_stats_sp:
                sink: dict = {}
                c = dataclasses.replace(sp_ctx, bn_sink=sink)
            else:
                sink, c = None, sp_ctx
            act, _ = apply_spatial_region(
                spp.model, ps, xx, c, levels, remat=remat, quant=quant
            )
            if not with_stats_sp:
                return act, jnp.zeros((0,), jnp.float32)
            leaves = jax.tree.leaves(ps)
            vals = [
                sink.get(id(leaves[i]), leaves[i]) for i in spp.sp_stat_leaf_ids
            ]
            svec = jnp.concatenate([jnp.ravel(v).astype(jnp.float32) for v in vals])
            return act, svec

        if remat:
            region = jax.checkpoint(region)
        with scope("sp_region"):
            act, sp_stats = region(params_sp, xs.astype(compute_dtype))
        # Junction: mosaic-merge tiles; batch-split for LOCAL_DP_LP (via the
        # all_to_all fast path when every tile device takes a distinct shard
        # — degree x less ICI traffic and junction memory than gather+slice).
        act = apply_junction(act, sp_last, spp.junction, degree, quant=quant)

        # Line all stage chunks up in batch order on every device (junction
        # wire class: the policy's junction mode quantizes the payload).
        j_mode = quant.mode("junction") if quant is not None else None

        def g(t):  # analysis: ok(unscoped-collective) — applied under scope("stage_lineup") below
            if j_mode:
                t = quantized_all_gather(t, AXIS_STAGE, 0, j_mode, quant.block)
            else:
                t = lax.all_gather(t, AXIS_STAGE, axis=0, tiled=True)
            return t.reshape(*lead_shape, spp.mb_tail, *t.shape[1:])

        with scope("stage_lineup"):
            return jax.tree.map(g, act), sp_stats

    def labels_to_parts(labels):
        """The same index transform phase1 applies to images (chunk by stage
        block, junction batch-split, gather) — applied to labels."""
        B = labels.shape[0]
        chunk = B // S
        if spp.junction == "batch_split":
            k = junction_shard_index(sp_last, degree)
            lab = labels.reshape(S, degree, chunk // degree)
            lab = lax.dynamic_index_in_dim(lab, k, axis=1, keepdims=False)
            lab = lab.reshape(-1)
        else:
            lab = labels
        return lab.reshape(*lead_shape, spp.mb_tail)

    def sharded_step(sp_buf, tail_row, opt_sp, opt_tail, x, labels):
        tail_flat = tail_row[0]
        # Stage-sharded tail opt moment rows squeeze like the param row;
        # scalar leaves pass through (see pipeline.py).  opt_sp is fully
        # replicated and passes through whole.
        opt_tail_local = squeeze_opt_rows(opt_tail)
        y_parts = labels_to_parts(labels)
        vary_axes = (AXIS_STAGE,) + tile_axes + grad_axes

        def loss_and_metrics(sp_flat, tail_flat):
            x_parts, sp_stats = phase1(sp_flat, x)
            with scope("tail_scan"):
                loss_acc, acc_acc, tail_stats = scan_fn(
                    branches, tail_flat, x_parts, y_parts, vary_axes
                )
            with scope("loss_reduce"):
                loss = metric_psum(loss_acc, (AXIS_STAGE,)) / denom
                acc = metric_psum(acc_acc, (AXIS_STAGE,)) / denom
                # Under 'gather' every tile device saw the full batch, so
                # loss/acc are already tile-invariant and the pmean would be
                # an identity over the wire (ircheck: wasted-wire); only the
                # batch_split junction leaves per-tile batch shards to merge.
                if tile_axes and spp.junction == "batch_split":
                    loss = lax.pmean(loss, tile_axes)
                    acc = lax.pmean(acc, tile_axes)
                if grad_axes:
                    loss = lax.pmean(loss, grad_axes)
                    acc = lax.pmean(acc, grad_axes)
            return loss, (acc, sp_stats, tail_stats)

        (loss, (acc, sp_stats, tail_stats)), (g_sp, g_tail) = jax.value_and_grad(
            loss_and_metrics, argnums=(0, 1), has_aux=True
        )(sp_buf, tail_flat)

        # Identity-on-value invariance bookkeeping (derivation in the module
        # docstring: AD already psum'd these cotangents home).  Identity on
        # the VALUE, not on the wire: these pmeans move the full flat param
        # buffers per axis, which is why the quant policy's grad class
        # routes them through the EQuARX-style quantized reduce
        # (pipeline.grad_pmean).
        with scope("grad_reduce"):
            g_sp = grad_pmean(g_sp, AXIS_STAGE, quant)
            if tile_axes:
                g_sp = grad_pmean(g_sp, tile_axes, quant)
                g_tail = grad_pmean(g_tail, tile_axes, quant)
            if grad_axes:
                g_sp = grad_pmean(g_sp, grad_axes, quant)
                g_tail = grad_pmean(g_tail, grad_axes, quant)

        with scope("optimizer_update"):
            new_sp, new_opt_sp = optimizer.update(sp_buf, g_sp, opt_sp)
            new_tail, new_opt_tail = optimizer.update(
                tail_flat, g_tail, opt_tail_local
            )
        if with_stats_sp:
            # Spatial stats vary over stage (distinct batch chunks) and data;
            # the tile axes are already reduced inside BN (cross-tile psum) or
            # the deposit (per-tile pmean).  sp_buf is fully replicated.
            with scope("stats_reduce"):
                st = grad_pmean(sp_stats, (AXIS_STAGE,) + grad_axes, quant)
            new_sp = new_sp.at[jnp.asarray(spp.sp_stat_idx)].set(
                st.astype(new_sp.dtype)
            )
        if with_stats_tail:
            # Tail stats vary over the tile axes under junction='batch_split'
            # (distinct batch shards) and over data; identical over tiles
            # under 'gather', where the pmean would move the whole stats
            # vector over the wire to reproduce it (ircheck: wasted-wire) —
            # skip it there.
            stt = tail_stats
            with scope("stats_reduce"):
                if tile_axes and spp.junction == "batch_split":
                    stt = grad_pmean(stt, tile_axes, quant)
                if grad_axes:
                    stt = grad_pmean(stt, grad_axes, quant)
            new_tail = scatter_stage_stats(part, new_tail, stt)
        return (
            new_sp,
            new_tail[None],
            new_opt_sp,
            restore_opt_rows(new_opt_tail, opt_tail),
            {"loss": loss, "accuracy": acc},
        )

    x_spec = spatial_partition_spec(sp, data=with_data_axis)
    y_spec = P(AXIS_DATA) if with_data_axis else P()
    tail_spec = P(AXIS_STAGE, None)
    tail_ospec = stage_opt_specs(optimizer, part)
    smapped = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(P(), tail_spec, P(), tail_ospec, x_spec, y_spec),
        out_specs=(P(), tail_spec, P(), tail_ospec, P()),
    )

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(state: SPPipelineState, x, labels):
        sp_buf, tail_buf, opt_sp, opt_tail, metrics = smapped(
            state.sp_buf, state.tail_buf, state.opt_sp, state.opt_tail, x, labels
        )
        return (
            SPPipelineState(sp_buf, tail_buf, opt_sp, opt_tail, state.step + 1),
            metrics,
        )

    return step


def make_sp_pipeline_train_step(
    spp: SPPipeline,
    optimizer: Optimizer,
    mesh: Mesh,
    parts: int,
    compute_dtype=jnp.float32,
    remat: bool = True,
    from_probs: bool = False,
    with_data_axis: bool = False,
    bn_stats: bool = True,
    donate: bool = False,
    schedule: str = "gpipe",
    quant: Optional[QuantPolicy] = None,
):
    """Build `(SPPipelineState, x, labels) -> (SPPipelineState, metrics)`.

    x: [B, H, W, C] global batch per data replica group; B = parts * microbatch.
    Constraints: B % S == 0 (stage blocks take equal chunks) and, for
    junction='batch_split', (B/S) % tiles == 0 (each stage chunk splits over
    the tile grid) — both checked at trace time.

    ``schedule="1f1b"`` runs the tail under the manual-backward 1F1B tick
    loop (grad_x=True: the scan's custom_vjp returns the tail-injection
    cotangents so AD can route them back through the junction into the
    spatial region).

    ``quant``: opt-in quantized-collective policy (docs/quantization.md):
    junction gathers/lineup, respatial reshards, grad/stats reduces, and
    tail handoffs per the policy's classes; ``None`` is bit-identical.
    """
    part = spp.tail_part
    cache: dict = {}

    def scan_fn(branches, tail_flat, x_parts, y_parts, vary_axes):
        if schedule == "1f1b":
            if "scan" not in cache:
                cache["scan"] = make_1f1b_scan(
                    part, branches,
                    vary_axes=vary_axes,
                    from_probs=from_probs,
                    compute_dtype=compute_dtype,
                    grad_x=True,
                    quant=quant,
                )
            loss_acc, acc_acc, st_acc = cache["scan"](
                tail_flat, x_parts, y_parts
            )
        else:
            loss_acc, acc_acc, st_acc = gpipe_scan(
                part, branches, tail_flat, x_parts, y_parts,
                vary_axes=vary_axes,
                from_probs=from_probs,
                compute_dtype=compute_dtype,
                quant=quant,
            )
        return loss_acc, acc_acc, st_acc / parts

    return _make_sp_step(
        spp, optimizer, mesh, (parts,), scan_fn, parts,
        compute_dtype, remat, with_data_axis, bn_stats, donate, schedule,
        quant=quant,
    )


def make_sp_gems_train_step(
    spp: SPPipeline,
    optimizer: Optimizer,
    mesh: Mesh,
    parts: int,
    times: int = 1,
    compute_dtype=jnp.float32,
    remat: bool = True,
    from_probs: bool = False,
    with_data_axis: bool = False,
    bn_stats: bool = True,
    donate: bool = False,
    schedule: str = "gpipe",
    quant: Optional[QuantPolicy] = None,
):
    """SP x GEMS x PP — the reference's flagship 5D composition
    (``train_spatial_master.py``: two spatial models over mirrored rank sets
    with flat param/grad exchange; here ONE weight set, the reverse stream
    reading mirror-ppermuted stage rows, see parallel/gems.py).

    x: [B, H, W, C] with B = 2 * times * parts * microbatch per data replica;
    pairs alternate direction through the tail stage chain.
    ``schedule="1f1b"``: both mirror streams run one-forward-one-backward
    (stage_common.make_gems_1f1b_scan, grad_x=True for the junction
    transpose); the mirror-ppermute here stays outside the scan so AD still
    routes stream B's gradients home.
    """
    part = spp.tail_part
    S = part.num_stages
    mirror_perm = [(i, S - 1 - i) for i in range(S)]
    cache: dict = {}

    def scan_fn(branches, tail_flat, x_parts, y_parts, vary_axes):
        with scope("gems_mirror"):
            mirror_params = lax.ppermute(tail_flat, AXIS_STAGE, mirror_perm)
        if schedule == "1f1b":
            if "scan" not in cache:
                cache["scan"] = make_gems_1f1b_scan(
                    part, branches,
                    vary_axes=vary_axes,
                    from_probs=from_probs,
                    compute_dtype=compute_dtype,
                    grad_x=True,
                    quant=quant,
                )
            loss_acc, acc_acc, stA, stB = cache["scan"](
                tail_flat, mirror_params, x_parts, y_parts
            )
        else:
            loss_acc, acc_acc, stA, stB = gems_dual_scan(
                part, branches, tail_flat, mirror_params, x_parts, y_parts,
                vary_axes=vary_axes,
                from_probs=from_probs,
                compute_dtype=compute_dtype,
                quant=quant,
            )
        with scope("stats_mirror"):
            st = (stA + lax.ppermute(stB, AXIS_STAGE, mirror_perm)) / (2 * times * parts)
        return loss_acc, acc_acc, st

    return _make_sp_step(
        spp, optimizer, mesh, (times, 2, parts), scan_fn, 2 * times * parts,
        compute_dtype, remat, with_data_axis, bn_stats, donate, schedule,
        quant=quant,
    )
