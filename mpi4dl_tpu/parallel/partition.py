"""Stage partitioning + flat parameter/activation packing.

The reference assigns contiguous cell ranges to ranks
(``mp_pipeline.py:41-83``) and keeps per-rank parameter objects.  The TPU
engine instead runs ONE SPMD program where every device holds its stage's
parameters as a single flat fp32 vector, padded to the max stage size and
sharded over the ``stage`` mesh axis.  Flat stage buffers are what make three
things trivial that cost the reference real machinery:

- heterogeneous stages under ``lax.switch`` (each branch statically unpacks
  its own tree; buffers all have one shape),
- the optimizer (elementwise over one vector; no per-layer loop),
- GEMS mirror exchange (one ppermute of the whole stage's weights — the
  reference builds contiguous flat views by re-pointing every torch parameter,
  train_spatial_master.py:114-138).

Activation boundaries likewise pack to flat vectors (tuple states — AmoebaNet
(x, skip) — flatten transparently) padded to the max boundary size so the
stage handoff is a single uniform ppermute.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mpi4dl_tpu.cells import CellModel, split_even
from mpi4dl_tpu.layer_ctx import ApplyCtx

Act = Any


# ---------------------------------------------------------------------------
# Generic pytree <-> flat vector packing (static metadata)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TreePack:
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]

    @property
    def total(self) -> int:
        return int(sum(self.sizes))

    @classmethod
    def of(cls, tree) -> "TreePack":
        leaves, treedef = jax.tree.flatten(tree)
        shapes = tuple(tuple(map(int, l.shape)) for l in leaves)
        dtypes = tuple(l.dtype for l in leaves)
        sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
        return cls(treedef, shapes, dtypes, sizes)

    def pack(self, tree, dtype=jnp.float32) -> jax.Array:
        leaves = jax.tree.leaves(tree)
        if not leaves:
            return jnp.zeros((0,), dtype)
        return jnp.concatenate([jnp.ravel(l).astype(dtype) for l in leaves])

    def unpack(self, vec: jax.Array, dtype=None):
        leaves, off = [], 0
        for shape, dt, size in zip(self.shapes, self.dtypes, self.sizes):
            chunk = lax_slice(vec, off, size)
            leaves.append(chunk.reshape(shape).astype(dtype or dt))
            off += size
        return jax.tree.unflatten(self.treedef, leaves)


def lax_slice(vec, off: int, size: int):
    return jax.lax.slice_in_dim(vec, off, off + size)


def stat_leaf_info(tree) -> Tuple[List[int], List[Tuple[int, int]]]:
    """Locate BN running-stat leaves in a params tree.

    Returns (leaf_ids, slots): ``leaf_ids`` are indices into the flattened
    leaf list for every 'mean'/'var' entry of a dict that also carries
    'scale' and 'bias' (the BatchNorm param signature — layers.py); ``slots``
    are the matching (offset, size) ranges in the TreePack flat vector (flatten
    order, offsets = cumulative leaf sizes).  This is what lets the flat-buffer
    engines deposit running-stat updates back into their stage rows."""
    from jax.tree_util import DictKey

    leaves_with_path, _ = jax.tree_util.tree_flatten_with_path(tree)
    parents: dict = {}
    for path, _leaf in leaves_with_path:
        if path and isinstance(path[-1], DictKey):
            parents.setdefault(path[:-1], set()).add(path[-1].key)
    bn_parents = {
        p for p, ks in parents.items() if {"scale", "bias", "mean", "var"} <= ks
    }
    leaf_ids: List[int] = []
    slots: List[Tuple[int, int]] = []
    off = 0
    for i, (path, leaf) in enumerate(leaves_with_path):
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        if (
            path
            and isinstance(path[-1], DictKey)
            and path[-1].key in ("mean", "var")
            and path[:-1] in bn_parents
        ):
            leaf_ids.append(i)
            slots.append((off, size))
        off += size
    return leaf_ids, slots


def stat_index_array(slots: Sequence[Tuple[int, int]], stat_max: int) -> np.ndarray:
    """[stat_max] int32 flat positions for the slots, padded with -1."""
    idx = np.full((stat_max,), -1, np.int32)
    o = 0
    for off, size in slots:
        idx[o : o + size] = np.arange(off, off + size, dtype=np.int32)
        o += size
    return idx


def pad_to(vec: jax.Array, n: int) -> jax.Array:
    if vec.shape[0] == n:
        return vec
    return jnp.pad(vec, (0, n - vec.shape[0]))


# ---------------------------------------------------------------------------
# Stage partition
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StagePartition:
    """Static description of a model split into S pipeline stages."""

    model: CellModel
    ranges: List[Tuple[int, int]]  # cell index ranges per stage
    param_packs: List[TreePack]  # per-stage parameter packing
    act_packs: List[TreePack]  # act_packs[s] = input structure of stage s
    out_pack: TreePack  # output of last stage (logits)
    param_max: int
    act_max: int
    # BN running-stat bookkeeping (see stat_leaf_info): per stage, the leaf
    # indices + (offset, size) slots of mean/var inside the stage packing, and
    # one [S, stat_max] -1-padded position table for the write-back scatter.
    stat_leaf_ids: List[List[int]] = dataclasses.field(default_factory=list)
    stat_slots: List[List[Tuple[int, int]]] = dataclasses.field(default_factory=list)
    stat_max: int = 0
    stat_idx: Optional[np.ndarray] = None  # [S, stat_max] int32
    # Storage dtype of the flat parameter buffers (reference --precision
    # bf_16_all: everything, params included, in bf16 — halves the stage
    # buffers, the GEMS mirror ppermute traffic, and the grad cotangents;
    # update arithmetic stays fp32 inside Optimizer).
    param_dtype: Any = jnp.float32

    @property
    def num_stages(self) -> int:
        return len(self.ranges)

    @classmethod
    def build(
        cls,
        model: CellModel,
        params_list: Sequence[Any],
        split_size: int,
        microbatch_shape: Any,
        balance: Optional[Sequence[int]] = None,
        compute_dtype=jnp.float32,
        param_dtype=jnp.float32,
    ) -> "StagePartition":
        """``microbatch_shape`` is either a plain shape tuple or a pytree of
        ``jax.ShapeDtypeStruct`` (tuple activations entering stage 0 — the
        SP→LP junction of sp_pipeline.py hands tail stages AmoebaNet's
        (x, skip) state)."""
        ranges = split_even(len(model.cells), split_size, balance)
        param_packs = [
            TreePack.of([params_list[i] for i in range(r0, r1)]) for r0, r1 in ranges
        ]
        # Boundary activation structures via eval_shape chain (the reference's
        # two-phase shape probe, mp_pipeline.py:126-168, for free).
        act_structs = []
        if isinstance(microbatch_shape, tuple) and all(
            isinstance(d, int) for d in microbatch_shape
        ):
            x = jax.ShapeDtypeStruct(microbatch_shape, compute_dtype)
        else:
            x = microbatch_shape
        ctx = ApplyCtx(train=True)
        for s, (r0, r1) in enumerate(ranges):
            act_structs.append(x)
            x = jax.eval_shape(
                lambda ps, xx, a=r0, b=r1: _apply_range(model, ps, xx, ctx, a, b),
                [params_list[i] for i in range(r0, r1)],
                x,
            )
        out_struct = x
        act_packs = [TreePack.of_struct(s, compute_dtype) for s in act_structs]
        out_pack = TreePack.of_struct(out_struct, compute_dtype)
        param_max = max(p.total for p in param_packs)
        act_max = max([p.total for p in act_packs] + [out_pack.total])
        stat_leaf_ids, stat_slots = [], []
        for r0, r1 in ranges:
            ids, slots = stat_leaf_info([params_list[i] for i in range(r0, r1)])
            stat_leaf_ids.append(ids)
            stat_slots.append(slots)
        stat_max = max((sum(sz for _, sz in s) for s in stat_slots), default=0)
        stat_idx = (
            np.stack([stat_index_array(s, stat_max) for s in stat_slots])
            if stat_max
            else None
        )
        return cls(
            model, ranges, param_packs, act_packs, out_pack, param_max, act_max,
            stat_leaf_ids, stat_slots, stat_max, stat_idx, param_dtype,
        )

    # ---- parameter buffers ----

    def pack_params(self, params_list) -> jax.Array:
        """[S, param_max] buffer in ``param_dtype`` (row s = stage s's flat
        params)."""
        rows = []
        for (r0, r1), pk in zip(self.ranges, self.param_packs):
            rows.append(
                pad_to(
                    pk.pack(
                        [params_list[i] for i in range(r0, r1)], self.param_dtype
                    ),
                    self.param_max,
                )
            )
        return jnp.stack(rows)

    def unpack_params(self, buf: jax.Array) -> List[Any]:
        """Inverse of pack_params (host-side, for checkpoint/eval)."""
        out: List[Any] = []
        for s, ((r0, r1), pk) in enumerate(zip(self.ranges, self.param_packs)):
            sub = pk.unpack(buf[s, : pk.total])
            out.extend(sub)
        return out

    def stage_apply(self, s: int, flat_params, act, ctx: ApplyCtx):
        """Apply stage s's cell range to an activation pytree."""
        r0, r1 = self.ranges[s]
        pk = self.param_packs[s]
        params = pk.unpack(lax_slice(flat_params, 0, pk.total))
        return _apply_range(self.model, params, act, ctx, r0, r1)


def _apply_range(model: CellModel, sub_params, x, ctx: ApplyCtx, r0: int, r1: int):
    """Run cells [r0, r1) with a stage-local (0-based) params list."""
    for i in range(r0, r1):
        x = model.cells[i].apply(sub_params[i - r0], x, ctx)
    return x


def _treepack_of_struct(struct, dtype) -> TreePack:
    leaves, treedef = jax.tree.flatten(struct)
    shapes = tuple(tuple(map(int, l.shape)) for l in leaves)
    dtypes = tuple(dtype for _ in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    return TreePack(treedef, shapes, dtypes, sizes)


TreePack.of_struct = staticmethod(_treepack_of_struct)
