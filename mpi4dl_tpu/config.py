"""Configuration / flags.

Mirrors the reference's single shared argparse parser
(``src/torchgems/parser.py:21-143``) so users of the reference find the same
vocabulary, plus TPU-specific knobs (mesh shape, dtype, D2 fusion, BN scope).
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Dict, List, Optional, Tuple


# ---------------------------------------------------------------------------
# Environment-hatch registry
#
# Every ``MPI4DL_*`` environment escape hatch the package (or its benches /
# tests) reads must be declared here.  The static analyzer
# (mpi4dl_tpu/analysis, rule ``env-hatch``) enforces both directions: an
# ``os.environ`` read of an undeclared ``MPI4DL_*`` name is a violation, and a
# declared hatch that is never read anywhere is a dead flag.  The README's
# "Environment hatches" section is generated from this table
# (:func:`hatches_markdown`).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Hatch:
    """One declared environment escape hatch."""

    name: str
    default: str  # the effective default when the variable is unset
    doc: str
    internal: bool = False  # process-internal plumbing, not a user knob


HATCHES: Dict[str, Hatch] = {
    h.name: h
    for h in (
        Hatch("MPI4DL_SQRT_GROUPS", "0",
              "Remat cell-group count for remat='sqrt'; 0 = auto (~sqrt(n); "
              "bench.py pins 16 for ResNet — PERF_NOTES r5)."),
        Hatch("MPI4DL_REMAT_OPS", "0",
              "1 = per-op checkpoints inside composite cells under ANY outer "
              "remat level (the ResNet-2048 memory frontier; bench auto-"
              "retries with it on OOM)."),
        Hatch("MPI4DL_1F1B_CELL_REMAT", "auto",
              "Per-cell checkpoints inside the 1F1B backward branches: "
              "1 = force on, 0 = force off, auto = on only for short stages "
              "(<= 3 cells — measured crossover, docs/pipeline.md; deep "
              "stages schedule the per-cell recomputes concurrently and "
              "regress peak HBM several-fold)."),
        Hatch("MPI4DL_NO_PHASE_DX", "0",
              "1 = strided convs keep XLA's lhs-dilation backward instead of "
              "the phase-decomposed dx path."),
        Hatch("MPI4DL_NO_HSTRIPE", "0",
              "1 = tiny-channel huge-spatial convs keep the plain XLA conv "
              "instead of H-striped patching."),
        Hatch("MPI4DL_HSTRIPE_RUN", "auto",
              "Block-level H-striping control: 0 = off, 1 = on (silences the "
              "train-mode BN stats warning), auto = on with warning."),
        Hatch("MPI4DL_HSTRIPE_EXACT", "0",
              "1 = striped train-mode BN uses GLOBAL batch statistics "
              "(exactness at ~1 extra prefix forward per BN; applies to "
              "both the single-device striped run and the stripe-wise "
              "backward)."),
        Hatch("MPI4DL_STRIPE_BWD", "0",
              "Stripe-wise forward+backward through eligible stride-1 "
              "blocks (ops/stripe_bwd.py): 1 = spatially sharded blocks "
              "only (the SP region — tail cells excluded: striped scans "
              "inside the 1F1B branch conditionals regress peak HBM "
              "several-fold), all = every eligible block (exactness "
              "testing).  The accumulated halo is realized once, then a "
              "jax.checkpoint'd scan over H stripes bounds the BACKWARD "
              "working set to one stripe — the SP-region O(parts) buy-back "
              "at the 8K flagship (docs/pipeline.md)."),
        Hatch("MPI4DL_STRIPE_BUDGET", str(64 * 1024 * 1024),
              "Per-stripe working-set budget in bytes for the stripe-wise "
              "backward (widest intermediate per stripe, whole chunk); "
              "the stripe count is derived from it."),
        Hatch("MPI4DL_NO_PACK", "0",
              "1 = disable boundary packing of D2 fused-run margins "
              "(A/B hatch; measured a no-op on v5e — PERF_NOTES r5)."),
        Hatch("MPI4DL_LANE_PAD", "0",
              "1 = pad AmoebaNet bottleneck mid-channels to 128 lanes "
              "(vector-lane utilization A/B)."),
        Hatch("MPI4DL_PALLAS_CONV", "0",
              "1 = route eligible spatial convs through the Pallas "
              "implicit-GEMM kernel in bench.py A/Bs (off: XLA wins at the "
              "step level — PERF_NOTES r4)."),
        Hatch("MPI4DL_NO_SCOPES", "0",
              "1 = disable obs trace scopes (jax.named_scope semantic names "
              "in traces/HLO) and host step annotations — pristine A/B "
              "compiles."),
        Hatch("MPI4DL_QUANT_COLLECTIVES", "<unset>",
              "Quantized-collective policy override (wins over --quant when "
              "set): `off`, one mode for every class (`int8`|`fp8`|`int4`), "
              "or per-class `junction=int4,respatial=int8,grad=int8,"
              "handoff=int8[,block=N]` — per-block-scaled payloads on the "
              "junction/respatial/grad/handoff wire classes "
              "(docs/quantization.md)."),
        Hatch("MPI4DL_NO_RESPATIAL_FAST", "0",
              "1 = disable the gather-free respatial fast paths (refine = "
              "local slice, coarsen = intra-group ring) and keep the legacy "
              "full-gather + slice reshard for A/B comparison."),
        Hatch("MPI4DL_FAULT", "<unset>",
              "Deterministic fault injection: `<kind>@<step>[:arg]` with "
              "kind in nan_loss|nan_batch|raise|sigterm|corrupt_ckpt|"
              "lost_shard_files|reshape|stall_data|oom_compile|oom_step|"
              "mesh_shrunk|slow_step|io_error — drives "
              "tests/test_resilience.py and the CI kill-and-resume + "
              "resilience-drill + supervisor-drill jobs "
              "(docs/resilience.md)."),
        Hatch("MPI4DL_CKPT_HOST_BYTES", str(1 << 30),
              "Byte budget for gathered-but-unwritten checkpoint shards in "
              "the async writer (sharded format): the training thread "
              "blocks instead of materializing more than this on the host, "
              "so peak save RSS is O(budget + largest shard), not O(full "
              "state) (docs/resilience.md)."),
        Hatch("MPI4DL_WATCHDOG_SECS", "0",
              "Step watchdog wall-clock budget in seconds (0 = off): a step "
              "(batch fetch + device step) exceeding it dumps live Python "
              "stacks + the last RunLog record to stderr "
              "(`--watchdog-secs` overrides)."),
        Hatch("MPI4DL_WATCHDOG_COMPILE_SECS", "10x step budget",
              "Watchdog budget for the FIRST step of a process (the one "
              "that pays the multi-minute XLA compile) — disarms after the "
              "first completed step, so realistic step budgets no longer "
              "false-trigger stall dumps during compile "
              "(`--watchdog-compile-secs` overrides; docs/resilience.md)."),
        Hatch("MPI4DL_WATCHDOG_ESCALATE", "0",
              "Watchdog escalation count (0 = dump forever): once one armed "
              "step has produced this many stall dumps, the watchdog writes "
              "a typed `hang` crash marker and exits the leg (status 82) so "
              "the supervisor can classify and relaunch instead of hanging "
              "until the scheduler kills it (docs/resilience.md)."),
        Hatch("MPI4DL_SUPERVISE_MAX_ATTEMPTS", "6",
              "Elastic supervisor: total training-leg launches before "
              "giving up (per-failure-class bounds apply on top — "
              "docs/resilience.md, policy matrix)."),
        Hatch("MPI4DL_SUPERVISE_BACKOFF", "1.0",
              "Elastic supervisor: base seconds of the exponential "
              "retry backoff (doubles per same-class recurrence, "
              "jittered +-25%)."),
        Hatch("MPI4DL_SUPERVISE_BACKOFF_CAP", "30",
              "Elastic supervisor: backoff ceiling in seconds (the "
              "exponential curve clamps here before jitter)."),
        Hatch("MPI4DL_QUARANTINE_STEPS", "<unset>",
              "Comma-list of global steps the supervised loop SKIPS "
              "outright (fetch nothing, train nothing, `quarantine` RunLog "
              "record) — the supervisor's poison-batch exclusion after a "
              "nan_cluster leg (docs/resilience.md)."),
        Hatch("MPI4DL_CRASH_MARKER", "<unset>",
              "Internal: where a supervised leg writes its structured "
              "crash marker (phase, step, error) on the way down — the "
              "supervisor points it at a per-attempt file.", internal=True),
        Hatch("MPI4DL_FLEET_DEVICES", "8",
              "Fleet scheduler: size of the shared device pool the "
              "bin-packer carves into per-job slices "
              "(docs/resilience.md, fleet scheduler)."),
        Hatch("MPI4DL_FLEET_POISON_ATTEMPTS", "2",
              "Fleet scheduler: failed supervisor RUNS (not leg attempts) "
              "before a job is quarantined as poison instead of requeued — "
              "the containment that keeps a doomed job from starving the "
              "queue."),
        Hatch("MPI4DL_FLEET_JOB", "<unset>",
              "Internal: the owning fleet job id, stamped into every leg "
              "subprocess so its result summary (and evidence artifacts) "
              "are attributable — the cross-contamination check verifies "
              "evidence stayed in its lane.", internal=True),
        Hatch("MPI4DL_FLEET_SLICE_DEVICES", "<unset>",
              "Internal: slice size the fleet scheduler pins a leg to; the "
              "leg self-provisions EXACTLY this many virtual-mesh devices "
              "instead of the 8-device default.", internal=True),
        Hatch("MPI4DL_NO_GUARD", "0",
              "1 = disable the anomaly guard (per-step finite-loss check "
              "with rollback to the last good checkpoint and poison-batch "
              "skip)."),
        Hatch("MPI4DL_GUARD_GRAD_NORM", "0",
              "Grad-norm guard limit (float; 0 = off): a step reporting "
              "metrics['grad_norm'] above it triggers the same rollback as "
              "a non-finite loss."),
        Hatch("MPI4DL_FLIGHT_STEPS", "64",
              "Flight-recorder ring capacity: the last N step records "
              "(per-device memory watermarks, jit-cache probe) plus "
              "checkpoint/anomaly/quarantine/preempt events kept in memory "
              "and dumped as `flight.json` on anomaly, watchdog "
              "escalation, preemption, and crash-marker writes "
              "(docs/observability.md)."),
        Hatch("MPI4DL_NO_FLIGHT", "0",
              "1 = disable the flight recorder (no in-memory ring, no "
              "`flight.json` dumps; the supervisor loses its fourth "
              "evidence source)."),
        Hatch("MPI4DL_METRICS_PORT", "<unset>",
              "Default port for `python -m mpi4dl_tpu.obs metrics --serve` "
              "(stdlib HTTP endpoint exposing the OpenMetrics text on "
              "/metrics); unset = file-sink only."),
        Hatch("MPI4DL_TPU_TESTS", "0",
              "1 = opt in to real-TPU subprocess tests (the tunnel is slow "
              "and intermittently down)."),
        Hatch("MPI4DL_TPU_NATIVE_DIR", "<alongside data_native.py>",
              "Directory holding the prebuilt native data-loader artifacts."),
        Hatch("MPI4DL_TPU_JAX_CACHE", "/tmp/mpi4dl_tpu_jax_cache",
              "Persistent XLA compilation-cache directory for the test "
              "suite."),
        Hatch("_MPI4DL_DRYRUN_INNER", "0",
              "Internal: marks the re-exec'd inner process of "
              "__graft_entry__.dryrun_multichip.", internal=True),
    )
}


def hatches_markdown(include_internal: bool = False) -> str:
    """Render the registry as the README's "Environment hatches" table."""
    lines = [
        "| Hatch | Default | Effect |",
        "| --- | --- | --- |",
    ]
    for h in HATCHES.values():
        if h.internal and not include_internal:
            continue
        lines.append(f"| `{h.name}` | `{h.default}` | {h.doc} |")
    return "\n".join(lines)


@dataclasses.dataclass
class ParallelConfig:
    # --- model / problem (reference parser.py) ---
    model: str = "resnet"  # resnet | amoebanet
    batch_size: int = 32
    parts: int = 1  # micro-batches per step (GPipe "parts")
    split_size: int = 1  # number of pipeline stages (LP splits)
    # Pipeline schedule: 'gpipe' (all-forward-then-all-backward, the
    # exactness oracle) or '1f1b' (one-forward-one-backward with a manual
    # schedule-level backward — O(stages) live activations instead of
    # O(parts); docs/pipeline.md).  Ignored by non-pipeline families.
    schedule: str = "gpipe"
    num_spatial_parts: Tuple[int, ...] = (4,)  # comma-list in the reference
    spatial_size: int = 1  # how many leading splits are spatial
    times: int = 1  # GEMS replication factor ("--times")
    image_size: int = 32
    num_epochs: int = 1
    num_layers: int = 18  # amoebanet cell count knob
    num_filters: int = 416
    num_classes: int = 10
    balance: Optional[Tuple[int, ...]] = None  # per-stage cell counts
    halo_d2: bool = False  # fused-halo "design 2"
    # Margin-consuming layers per fused halo block in D2 (reference
    # --fused-layers); 0 = fuse maximal runs (best: fewest exchanges).
    fused_layers: int = 0
    local_dp_lp: int = 1  # LOCAL_DP_LP: DP degree inside LP stages
    slice_method: str = "square"  # square | vertical | horizontal
    app: int = 3  # 1=image folder, 2=cifar-like, 3=synthetic (reference APP)
    datapath: str = "./train"
    enable_master_comm_opt: bool = False  # GEMS MASTER-OPT analog
    num_workers: int = 0
    precision: str = "fp_32"  # fp_32 | bf_16 | bf_16_all (reference vocabulary)

    # --- TPU-native knobs (new) ---
    data_parallel: int = 1  # outer DP degree
    bn_cross_tile: bool = True  # BN stats across spatial tiles (fix) or per-tile (parity)
    softmax_in_model: bool = False  # reproduce reference double-softmax quirk
    enable_gems: bool = False
    lr: float = 0.001  # reference benchmarks use SGD(lr=0.001)
    momentum: float = 0.0
    optimizer: str = "sgd"
    remat: bool = True  # jax.checkpoint each stage application
    # Route eligible SP convs through the Pallas kernel.  None = auto = OFF:
    # the op-level wins (1.2-2.3x at D2 shapes on v5e) did NOT survive the
    # step-level A/B — XLA's conv+BN+ReLU fusion beats the kernel in whole
    # programs (PERF_NOTES r4, benchmark_d2_step.py).  --pallas-conv is the
    # explicit opt-in; resolved by resolve_pallas_conv().
    pallas_conv: Optional[bool] = None
    # Quantized-collective policy spec ("off" | "int8" | "fp8" | "int4" |
    # per-class "junction=int4,grad=int8[,block=N]"); resolved by
    # mpi4dl_tpu.quant.QuantPolicy.resolve (the MPI4DL_QUANT_COLLECTIVES
    # hatch overrides).  Off is bit-identical to the unquantized engines.
    quant_collectives: str = "off"
    # Stripe-wise backward through eligible stride-1 blocks (sets the
    # MPI4DL_STRIPE_BWD hatch for this process at build time): the SP-region
    # O(parts) buy-back — docs/pipeline.md, ops/stripe_bwd.py.
    stripe_bwd: bool = False
    # SP→LP junction placement: None = derive from the pipeline splits (the
    # historical behaviour), an int = explicit junction cell, "auto" =
    # resolve from the analytical placement frontier
    # (parallel/spatial.choose_spatial_until — the mem_probe
    # --sweep-junction frontier promoted to the default config chooser).
    spatial_until: Optional[object] = None
    verbose: bool = False  # debug logging (reference parser.py --verbose)
    checkpoint_dir: Optional[str] = None
    seed: int = 0

    @property
    def spatial_part_size(self) -> int:
        return self.num_spatial_parts[0]

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.precision in ("bf_16", "bf_16_all") else jnp.float32

    @property
    def param_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self.precision == "bf_16_all" else jnp.float32

    def validate(self) -> None:
        from mpi4dl_tpu.utils import is_power_two

        # Reference verify_spatial_config (train_spatial.py:33-58): power-of-2
        # image size and per-tile sizes, legal slice method.
        assert self.slice_method in ("square", "vertical", "horizontal")
        if self.spatial_size > 0 and self.spatial_part_size > 1:
            assert is_power_two(self.image_size), "image_size must be a power of two"
            assert self.image_size % self.spatial_part_size == 0
            # Multi-level SP (reference num_spatial_parts="4,2"): later levels
            # must not grow and must embed in the level-0 grid (checked by
            # spatial_levels_for); LOCAL_DP_LP shards over the tile devices.
            for p in self.num_spatial_parts[1:]:
                assert p <= self.spatial_part_size, (
                    f"spatial levels must not grow: {self.num_spatial_parts}"
                )
                assert self.spatial_part_size % p == 0, (
                    f"level tile count {p} must divide {self.spatial_part_size}"
                )
            if self.local_dp_lp > 1:
                assert self.spatial_part_size % self.local_dp_lp == 0, (
                    f"--local-DP {self.local_dp_lp} must divide the "
                    f"{self.spatial_part_size} spatial-tile devices"
                )
        assert self.batch_size % self.parts == 0, "batch must divide into parts"
        if self.balance is not None:
            assert len(self.balance) == self.split_size
        if self.spatial_until is not None:
            assert self.spatial_until == "auto" or (
                isinstance(self.spatial_until, int) and self.spatial_until >= 1
            ), f"--spatial-until must be 'auto' or an int >= 1, got {self.spatial_until!r}"
        # Fail fast on a malformed quant spec (raises ValueError with the
        # offending token; the hatch override is resolved at build time).
        from mpi4dl_tpu.quant.policy import QuantPolicy

        QuantPolicy.parse(self.quant_collectives)


def is_tpu_backend() -> bool:
    """True on TPU backends (incl. the experimental axon plugin) — the
    shared auto-enable predicate for Pallas (Mosaic) kernels: the conv
    dispatch here and ring attention's flash path (ops/ring.py)."""
    import jax

    return jax.default_backend() in ("tpu", "axon")


def resolve_pallas_conv(setting: Optional[bool]) -> bool:
    """Resolve the tri-state ``pallas_conv`` config: ``None`` = auto = OFF.

    The kernel wins 1.1-2.3x at the OP level at D2 shapes, but the r4
    STEP-level A/B (benchmark_d2_step.py: full relu-conv-bn fused runs,
    forward+backward+update, real chip) measured 0.62-1.06x — XLA's
    conv+BN+ReLU fusion and layout propagation across the whole program
    beat the kernel's op-level margin at every representative shape except
    a statistical tie (PERF_NOTES r4; exactly the failure mode the r3
    single-device SAME-conv measurement warned about).  ``--pallas-conv``
    remains the explicit opt-in; CPU keeps XLA conv (interpret mode is for
    tests)."""
    if setting is not None:
        return setting
    return False


def get_parser() -> argparse.ArgumentParser:
    """Argparse mirroring reference parser.py flag names."""
    p = argparse.ArgumentParser(description="mpi4dl_tpu benchmarks")
    p.add_argument("--model", type=str, default="resnet")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--parts", type=int, default=1)
    p.add_argument("--split-size", type=int, default=1)
    p.add_argument("--schedule", choices=["gpipe", "1f1b"], default="gpipe",
                   help="pipeline schedule: gpipe (default) or 1f1b "
                        "(O(stages) live activations; docs/pipeline.md)")
    p.add_argument("--num-spatial-parts", type=str, default="4")
    p.add_argument("--spatial-size", type=int, default=1)
    p.add_argument("--times", type=int, default=1)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--num-epochs", type=int, default=1)
    p.add_argument("--num-layers", type=int, default=18)
    p.add_argument("--num-filters", type=int, default=416)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--balance", type=str, default=None)
    # the reference spells it --halo-D2 (parser.py); accept both
    p.add_argument("--halo-d2", "--halo-D2", dest="halo_d2", action="store_true")
    p.add_argument("--verbose", action="store_true",
                   help="enable debug logging (reference parser.py --verbose)")
    p.add_argument("--fused-layers", type=int, default=0,
                   help="padded layers per fused D2 exchange; 0 = maximal")
    p.add_argument("--local-DP", dest="local_dp_lp", type=int, default=1)
    p.add_argument(
        "--slice-method",
        type=str,
        default="square",
        help="square | vertical | horizontal",
    )
    p.add_argument("--app", type=int, default=3)
    p.add_argument("--datapath", type=str, default="./train")
    p.add_argument("--enable-master-comm-opt", action="store_true")
    p.add_argument("--num-workers", type=int, default=0)
    p.add_argument("--precision", type=str, default="fp_32")
    # TPU-native additions
    p.add_argument("--data-parallel", type=int, default=1)
    p.add_argument("--per-tile-bn", action="store_true", help="reference-parity per-tile BN stats")
    p.add_argument("--softmax-in-model", action="store_true")
    p.add_argument("--enable-gems", action="store_true")
    p.add_argument("--lr", type=float, default=0.001)
    p.add_argument("--no-remat", action="store_true")
    p.add_argument("--pallas-conv", action="store_const", const=True,
                   dest="pallas_conv", default=None,
                   help="force the Pallas margin-consuming conv kernel for "
                        "eligible spatial convs (default: auto — on for TPU "
                        "backends; see PERF_NOTES.md)")
    p.add_argument("--no-pallas-conv", action="store_const", const=False,
                   dest="pallas_conv",
                   help="keep all convs on XLA even on TPU")
    p.add_argument("--quant", dest="quant_collectives", type=str,
                   default="off", metavar="SPEC",
                   help="quantized-collective policy: off (default, "
                        "bit-identical), int8|fp8|int4 for every hot class, "
                        "or per-class junction=...,respatial=...,grad=...,"
                        "handoff=...[,block=N] (docs/quantization.md)")
    p.add_argument("--stripe-bwd", action="store_true",
                   help="stripe-wise forward+backward through eligible "
                        "stride-1 blocks (sets MPI4DL_STRIPE_BWD=1): bounds "
                        "the SP-region backward working set to one H-stripe "
                        "— the O(parts) buy-back (docs/pipeline.md)")
    p.add_argument("--spatial-until", default=None, metavar="N|auto",
                   type=_spatial_until_arg,
                   help="SP->LP junction placement: an explicit cell index, "
                        "or 'auto' to resolve it from the analytical "
                        "placement frontier (the mem_probe --sweep-junction "
                        "chooser); default: derive from the pipeline splits")
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--seed", type=int, default=0)
    return p


def _int_tuple(s: Optional[str]) -> Optional[Tuple[int, ...]]:
    if s is None or s == "":
        return None
    return tuple(int(x) for x in s.split(","))


def _spatial_until_arg(s):
    """Parse --spatial-until: None, 'auto', or an int."""
    if s is None or s == "":
        return None
    if s == "auto":
        return "auto"
    return int(s)


def config_from_args(args: argparse.Namespace) -> ParallelConfig:
    cfg = ParallelConfig(
        model=args.model,
        batch_size=args.batch_size,
        parts=args.parts,
        split_size=args.split_size,
        schedule=args.schedule,
        num_spatial_parts=_int_tuple(args.num_spatial_parts) or (4,),
        spatial_size=args.spatial_size,
        times=args.times,
        image_size=args.image_size,
        num_epochs=args.num_epochs,
        num_layers=args.num_layers,
        num_filters=args.num_filters,
        num_classes=args.num_classes,
        balance=_int_tuple(args.balance),
        halo_d2=args.halo_d2,
        fused_layers=args.fused_layers,
        local_dp_lp=args.local_dp_lp,
        slice_method=args.slice_method,
        app=args.app,
        datapath=args.datapath,
        enable_master_comm_opt=args.enable_master_comm_opt,
        num_workers=args.num_workers,
        precision=args.precision,
        data_parallel=args.data_parallel,
        bn_cross_tile=not args.per_tile_bn,
        softmax_in_model=args.softmax_in_model,
        enable_gems=args.enable_gems,
        lr=args.lr,
        remat=not args.no_remat,
        pallas_conv=args.pallas_conv,
        quant_collectives=getattr(args, "quant_collectives", "off"),
        stripe_bwd=getattr(args, "stripe_bwd", False),
        spatial_until=_spatial_until_arg(getattr(args, "spatial_until", None)),
        verbose=getattr(args, "verbose", False),
        checkpoint_dir=args.checkpoint_dir,
        seed=args.seed,
    )
    cfg.validate()
    return cfg
