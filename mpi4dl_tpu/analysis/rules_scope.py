"""Rule ``unscoped-collective`` (rule 10): collectives in the communication
layers must run under an ``obs.scope``.

The per-scope observability stack (obs/hbm.py HBM attribution, obs/timeline
collective-time estimates, the contract gate's per-scope collective ledger)
only works while every collective lowers inside a named scope — an
``lax.ppermute`` added without one lands in the ``(unattributed)`` bucket
and silently decays the coverage metric the CI gate asserts.  This rule
makes that decay a build failure at the source level, before any artifact
is extracted.

Scope: files under ``mpi4dl_tpu/parallel/`` and ``mpi4dl_tpu/ops/`` (the
communication layers; engines and kernels).  A collective call site must be
lexically inside a ``with obs.scope(...)``/``scope(...)``/
``jax.named_scope(...)`` block.  Helpers whose *callers* own the scope carry
the standard ``# analysis: ok(unscoped-collective)`` pragma with a comment
saying which scope covers them.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from mpi4dl_tpu.analysis.core import Project, Rule, Violation

# jax.lax collective callables (data-moving or reducing across mesh axes).
_COLLECTIVES = (
    "ppermute", "psum", "pmean", "pmax", "pmin", "all_gather",
    "psum_scatter", "all_to_all", "pbroadcast",
)

# Context-manager callees that establish a named scope.
_SCOPE_CALLEES = (
    "mpi4dl_tpu.obs.scopes.scope", "mpi4dl_tpu.obs.scope", "obs.scope",
    "jax.named_scope",
)


def _is_target(rel: str) -> bool:
    rel = f"/{rel}"
    return "mpi4dl_tpu/parallel/" in rel or "mpi4dl_tpu/ops/" in rel


class UnscopedCollectiveRule(Rule):
    name = "unscoped-collective"
    description = (
        "collective issued in mpi4dl_tpu/parallel|ops without an enclosing "
        "obs.scope — per-scope HBM/collective attribution would lose it; "
        "wrap it in `with scope(...)` or pragma a caller-scoped helper."
    )

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for src in project.files:
            if not _is_target(src.rel):
                continue
            scoped_spans: List[Tuple[int, int]] = []
            for w in src.nodes(ast.With):
                for item in w.items:
                    ctx = item.context_expr
                    if not isinstance(ctx, ast.Call):
                        continue
                    resolved = src.resolve(ctx.func) or ""
                    if resolved in _SCOPE_CALLEES or resolved.endswith(
                        ".named_scope"
                    ):
                        scoped_spans.append(
                            (w.lineno, getattr(w, "end_lineno", w.lineno))
                        )
                        break
            for node in src.nodes(ast.Call):
                resolved = src.resolve(node.func) or ""
                parts = resolved.split(".")
                if parts[-1] not in _COLLECTIVES:
                    continue
                # Only the jax.lax spellings (a local helper named `psum`
                # resolves to the bare name and is its own call site).
                if not (resolved.startswith("jax.lax.")
                        or resolved.startswith("lax.")):
                    continue
                if any(a <= node.lineno <= b for a, b in scoped_spans):
                    continue
                out.append(
                    Violation(
                        self.name,
                        src.rel,
                        node.lineno,
                        f"{parts[-1]} with no enclosing obs.scope — wrap in "
                        "`with scope(name):` so HBM/collective attribution "
                        "keeps its owner (docs/observability.md); helpers "
                        "covered by a caller's scope take "
                        "`# analysis: ok(unscoped-collective)`",
                    )
                )
        return out


RULE = UnscopedCollectiveRule()
