"""Rule ``retrace``: compile-time cache hazards.

Two checks:

1. Module-level ``jnp`` array construction.  A device array created at import
   time is closed over by every function that references it, baked into each
   trace as a constant: it pins device memory for the process lifetime,
   defeats donation, and a "small" table silently becomes a big XLA constant
   in every executable.  Build it with numpy (traced as a literal once) or
   inside the jitted function.
2. ``jit(f, static_argnums/static_argnames=...)`` where the corresponding
   parameter's default is a mutable literal (list/dict/set).  Static args are
   hashed for the compile cache; an unhashable default raises only on the
   first *defaulted* call — typically on the chip, hours after the CPU tests
   passed (they always passed the argument explicitly).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from mpi4dl_tpu.analysis.core import (
    Project,
    Rule,
    SourceFile,
    Violation,
    is_package_file,
)

_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                     ast.SetComp)


class RetraceRule(Rule):
    name = "retrace"
    description = (
        "No module-level jnp arrays (per-trace baked constants); static args "
        "must be hashable (no mutable-literal defaults)."
    )

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for src in project.files:
            if not is_package_file(src.rel):
                continue
            out.extend(self._check_module_arrays(src))
            out.extend(self._check_static_args(src))
        return out

    # -- module-level jnp arrays ------------------------------------------
    def _check_module_arrays(self, src: SourceFile) -> List[Violation]:
        out = []
        for node in src.tree.body:  # module level only, by construction
            value = None
            if isinstance(node, ast.Assign):
                value = node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value = node.value
            if value is None or not isinstance(value, ast.Call):
                continue
            resolved = src.resolve(value.func) or ""
            if resolved.startswith("jax.numpy."):
                out.append(
                    Violation(
                        self.name,
                        src.rel,
                        node.lineno,
                        f"module-level {resolved}() creates a device array "
                        "at import: baked into every trace as a constant "
                        "(use numpy here, or construct inside the function)",
                    )
                )
        return out

    # -- unhashable static args -------------------------------------------
    def _check_static_args(self, src: SourceFile) -> List[Violation]:
        out = []
        funcs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in src.nodes(ast.FunctionDef)
        }
        for node in src.nodes(ast.Call):
            resolved = src.resolve(node.func) or ""
            if resolved.split(".")[-1] != "jit":
                continue
            static_kw = {
                kw.arg: kw.value
                for kw in node.keywords
                if kw.arg in ("static_argnums", "static_argnames")
            }
            if not static_kw or not node.args:
                continue
            target = node.args[0]
            fdef = (
                funcs.get(target.id) if isinstance(target, ast.Name) else None
            )
            if fdef is None:
                continue
            params = fdef.args.args
            defaults = fdef.args.defaults
            # align defaults to trailing params
            default_of: Dict[str, ast.AST] = {}
            for p, d in zip(params[len(params) - len(defaults):], defaults):
                default_of[p.arg] = d
            flagged: List[str] = []
            nums = static_kw.get("static_argnums")
            if nums is not None:
                for idx in _int_literals(nums):
                    if 0 <= idx < len(params):
                        name = params[idx].arg
                        d = default_of.get(name)
                        if d is not None and isinstance(d, _MUTABLE_LITERALS):
                            flagged.append(name)
            names = static_kw.get("static_argnames")
            if names is not None:
                for name in _str_literals(names):
                    d = default_of.get(name)
                    if d is not None and isinstance(d, _MUTABLE_LITERALS):
                        flagged.append(name)
            for name in flagged:
                out.append(
                    Violation(
                        self.name,
                        src.rel,
                        node.lineno,
                        f"static arg {name!r} of {fdef.name!r} defaults to a "
                        "mutable literal — unhashable for the jit cache; "
                        "use a tuple/frozenset or require the argument",
                    )
                )
        return out


def _int_literals(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def _str_literals(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


RULE = RetraceRule()
