"""Rule ``unregistered-pallas-call`` (rule 12): every Pallas kernel must be
enrolled in the pallascheck gate.

The static kernel verifier (analysis/pallascheck, docs/analysis.md) only
certifies what ``mpi4dl_tpu/ops/kernel_registry.py`` enrolls: grid/
BlockSpec soundness, the per-grid-point VMEM budget, DMA/semaphore
discipline and accumulator-init coverage are all proved per registered
case.  A new ``pl.pallas_call`` in a module the registry never imports —
the exact shape of the future halo-RDMA conv landing as a fresh file —
would ship with none of those invariants checked and no test failing.
This rule fails the build at the source level: the fix is one
``KernelCase`` row (whose module import is itself the registration mark
this rule checks for).

Scope: ``mpi4dl_tpu`` package files and ``benchmarks/`` (a benchmark
throwaway kernel that is deliberately not worth a registry row carries
``# analysis: ok(unregistered-pallas-call)`` with a comment saying why).
Tests are exempt — pallascheck's own fixture lane defines
intentionally-broken kernels inline.  The registered-module set is parsed
statically from the registry's imports (never executed), falling back to
the installed module when the registry file is outside the scan scope.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from mpi4dl_tpu.analysis.core import (
    Project,
    Rule,
    SourceFile,
    Violation,
    _find_file,
    _parse_fallback,
    is_package_file,
)

_REGISTRY_SUFFIX = "mpi4dl_tpu/ops/kernel_registry.py"
_REGISTRY_MODULE = "mpi4dl_tpu.ops.kernel_registry"


def registered_modules(files) -> Set[str]:
    """Module names the kernel registry imports (statically parsed): the
    set whose kernels pallascheck discovers and certifies."""
    src = _find_file(files, _REGISTRY_SUFFIX) or _parse_fallback(
        _REGISTRY_MODULE
    )
    out: Set[str] = set()
    if src is None:
        return out
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module:
            out.add(node.module)
            # `from pkg import mod` also registers pkg.mod
            for a in node.names:
                out.add(f"{node.module}.{a.name}")
        elif isinstance(node, ast.Import):
            for a in node.names:
                out.add(a.name)
    return out


def _module_name(rel: str) -> Optional[str]:
    """Dotted module name of a scanned file, rooted at the package."""
    rel = rel.replace("\\", "/")
    if "mpi4dl_tpu/" in f"/{rel}":
        rel = rel[rel.index("mpi4dl_tpu/"):]
    elif not rel.startswith("mpi4dl_tpu"):
        return None
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    return is_package_file(rel) or "benchmarks/" in f"/{rel}"


class UnregisteredPallasCallRule(Rule):
    name = "unregistered-pallas-call"
    description = (
        "pl.pallas_call in a module the kernel registry (mpi4dl_tpu/ops/"
        "kernel_registry.py) never imports — the kernel ships outside the "
        "pallascheck VMEM/DMA/grid gate; add a KernelCase row, or pragma "
        "a benchmark throwaway"
    )

    def check(self, project: Project) -> List[Violation]:
        registered = registered_modules(project.files)
        out: List[Violation] = []
        for src in project.files:
            if not _in_scope(src.rel) or src.rel.endswith(_REGISTRY_SUFFIX):
                continue
            mod = _module_name(src.rel)
            if mod is not None and mod in registered:
                continue
            out.extend(self._file_violations(src, mod))
        return out

    def _file_violations(self, src: SourceFile,
                         mod: Optional[str]) -> List[Violation]:
        out: List[Violation] = []
        for node in src.nodes(ast.Call):
            resolved = src.resolve(node.func) or ""
            if not resolved.endswith("pallas_call"):
                continue
            where = mod or src.rel.replace("\\", "/")
            out.append(Violation(
                rule=self.name,
                path=src.rel,
                line=node.lineno,
                message=(
                    f"pallas_call in {where}, which "
                    "mpi4dl_tpu/ops/kernel_registry.py does not import — "
                    "the kernel is invisible to the pallascheck gate; "
                    "register a KernelCase (or pragma a benchmark "
                    "throwaway with a reason)"
                ),
            ))
        return out


RULE = UnregisteredPallasCallRule()
