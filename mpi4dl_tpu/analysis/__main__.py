"""CLI: ``python -m mpi4dl_tpu.analysis [--json] [--baseline F] [paths...]``.

With no paths, scans the repository tree the package sits in: the package
itself plus ``tests/``, ``benchmarks/``, ``bench.py`` and
``__graft_entry__.py`` (the env-hatch dead-flag check needs the whole tree —
several hatches are read only by the harness).  Exit status: 0 when no
violations remain after baseline filtering, 1 otherwise, 2 on usage errors.

``python -m mpi4dl_tpu.analysis contracts ...`` dispatches to the
compiled-artifact contract gate (analysis/contracts — lowers the engine
families and diffs their StableHLO/jaxpr contracts against checked-in
goldens; see its ``--help``).  ``python -m mpi4dl_tpu.analysis ircheck
...`` dispatches to the IR-level shard-flow verifier (analysis/ircheck —
replication flow, collective matching, donation safety, async
well-formedness over the same engine builds; see its ``--help``).
``python -m mpi4dl_tpu.analysis pallascheck ...`` dispatches to the static
Pallas kernel verifier (analysis/pallascheck — grid/BlockSpec soundness,
VMEM budget certification, DMA/semaphore discipline and accumulator-init
coverage over every kernel in ops/kernel_registry; see its ``--help``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

from mpi4dl_tpu.analysis import (
    RULE_TABLE,
    apply_baseline,
    build_project,
    load_baseline,
    run_rules,
)


def default_paths(root: str) -> List[str]:
    cand = ["mpi4dl_tpu", "tests", "benchmarks", "bench.py", "__graft_entry__.py"]
    return [os.path.join(root, c) for c in cand if os.path.exists(os.path.join(root, c))]


def repo_root() -> str:
    # the directory that holds the mpi4dl_tpu package
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def scope_filter(paths: List[str], scope: List[str]) -> List[str]:
    """Restrict absolute paths to those inside the gate's scan scope (a
    scope entry is a file to match exactly or a directory prefix)."""
    out = []
    for p in paths:
        for s in scope:
            if p == s or p.startswith(s.rstrip(os.sep) + os.sep):
                out.append(p)
                break
    return out


# Files whose declarations are the cross-file ground truth every other
# module is checked against (mesh axes; the env-hatch registry).  A change
# here invalidates --changed-only's file-local view: the evidence for a
# violation in an UNCHANGED module can live in these files.
CROSS_FILE_GROUND_TRUTH = ("mpi4dl_tpu/config.py", "mpi4dl_tpu/mesh.py")


def cross_file_ground_truth(paths: List[str]) -> List[str]:
    """The ground-truth files present in ``paths`` (normalized, relative
    suffix match — paths arrive absolute from git)."""
    hits = []
    for p in paths:
        norm = p.replace(os.sep, "/")
        for g in CROSS_FILE_GROUND_TRUTH:
            if norm.endswith("/" + g) or norm == g:
                hits.append(g)
    return sorted(set(hits))


def changed_python_files(root: str) -> Optional[List[str]]:
    """Repo-relative ``.py`` paths touched per git (worktree + index +
    untracked), for ``--changed-only`` pre-commit runs.  None when git is
    unavailable (caller falls back to a full scan)."""
    names: List[str] = []
    # git emits names relative to the TOPLEVEL, which may sit above `root`
    # (repo vendored inside an outer git repo) — resolve against it, not
    # root, or every changed file fails the exists check and the gate
    # silently passes.
    for cmd in (
        ["git", "-C", root, "rev-parse", "--show-toplevel"],
        ["git", "-C", root, "diff", "--name-only", "HEAD"],
        ["git", "-C", root, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=30, check=True
            )
        except (OSError, subprocess.SubprocessError):
            return None
        if cmd[3] == "rev-parse":
            toplevel = proc.stdout.strip() or root
        else:
            names.extend(proc.stdout.splitlines())
    out = []
    for name in dict.fromkeys(names):  # dedup, keep order
        path = os.path.join(toplevel, name)
        if name.endswith(".py") and os.path.exists(path):
            out.append(path)
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "contracts":
        from mpi4dl_tpu.analysis.contracts.__main__ import main as contracts_main

        return contracts_main(argv[1:])
    if argv and argv[0] == "ircheck":
        from mpi4dl_tpu.analysis.ircheck.__main__ import main as ircheck_main

        return ircheck_main(argv[1:])
    if argv and argv[0] == "pallascheck":
        from mpi4dl_tpu.analysis.pallascheck.__main__ import (
            main as pallascheck_main,
        )

        return pallascheck_main(argv[1:])

    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analysis",
        description="Shard-safety static analyzer (see docs/analysis.md). "
        "The `contracts` subcommand runs the compiled-artifact contract "
        "gate instead.",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to scan (default: repo tree)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--baseline", metavar="F", default=None,
                    help="JSON list of accepted violations to filter out")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="rewrite --baseline dropping stale entries "
                         "(entries that no longer match any violation)")
    ap.add_argument("--changed-only", action="store_true",
                    help="scan only files git reports as changed/untracked "
                         "(fast pre-commit mode; the dead-flag direction of "
                         "env-hatch and stale-baseline reporting are "
                         "disabled — both need a whole-tree scan)")
    ap.add_argument("--rule", action="append", default=None, metavar="NAME",
                    help="run only the named rule(s)")
    ap.add_argument("--sarif", metavar="F", default=None,
                    help="also write the (post-baseline) violations as a "
                         "SARIF 2.1.0 log for GitHub code scanning")
    ap.add_argument("--prune-pragmas", action="store_true",
                    help="list stale `# analysis: ok(...)` pragmas (those "
                         "that suppressed nothing on a whole-tree scan) "
                         "for removal, instead of the normal report")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--hatch-docs", action="store_true",
                    help="print the README env-hatch table from config.HATCHES")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULE_TABLE:
            print(f"{r.name}: {r.description}")
        return 0
    if args.hatch_docs:
        from mpi4dl_tpu.config import hatches_markdown

        print(hatches_markdown())
        return 0
    if args.prune_baseline and not args.baseline:
        print("analysis: --prune-baseline requires --baseline",
              file=sys.stderr)
        return 2
    if args.prune_baseline and args.changed_only:
        # staleness is judged against the FULL violation set; a partial scan
        # would mark every entry for an unscanned file stale and prune it
        print("analysis: --prune-baseline needs a whole-tree scan and "
              "cannot be combined with --changed-only", file=sys.stderr)
        return 2
    if args.prune_pragmas and (args.changed_only or args.paths or args.rule):
        # pragma staleness needs the FULL rule set over the FULL tree — a
        # subset scan trivially "never needs" every pragma outside it
        print("analysis: --prune-pragmas needs a whole-tree all-rules scan "
              "and cannot be combined with --changed-only, --rule or "
              "explicit paths", file=sys.stderr)
        return 2

    # Subcommands dispatch only as the FIRST token; a flag-first spelling
    # (`--json contracts`) would otherwise be treated as a scan path with
    # no .py files in it and exit 0 looking like a passed gate.
    for sub in ("contracts", "ircheck", "pallascheck"):
        if sub in args.paths:
            print(
                f"analysis: `{sub}` must come first: "
                f"python -m mpi4dl_tpu.analysis {sub} [flags]",
                file=sys.stderr,
            )
            return 2

    root = repo_root()
    partial_scan = False  # True only when actually scanning a subset
    if args.changed_only:
        if args.paths:
            print("analysis: --changed-only and explicit paths are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        changed = changed_python_files(root)
        if changed is None:
            print("analysis: git unavailable; --changed-only falling back "
                  "to a full scan", file=sys.stderr)
            paths = default_paths(root)
        else:
            # same scope as the full gate — a changed file OUTSIDE the
            # default tree must not fail here when the real gate and CI
            # would never scan it
            changed = scope_filter(changed, default_paths(root))
            if not changed:
                print("analysis: no changed python files in scope",
                      file=sys.stderr)
                return 0
            widen = cross_file_ground_truth(changed)
            if widen:
                # Cross-file rules judge every OTHER file against the
                # ground truth these files declare (mesh axes, env
                # hatches): an edit here changes what is a violation in
                # unchanged modules, so the scan must widen to the
                # dependency set — the whole tree.
                print(
                    "analysis: --changed-only: cross-file ground truth "
                    f"changed ({', '.join(widen)}); widening to a full "
                    "scan so dependent findings in unchanged files are "
                    "not missed", file=sys.stderr,
                )
                paths = default_paths(root)
            else:
                paths = changed
                partial_scan = True
    else:
        paths = args.paths or default_paths(root)
    if not paths:
        print("analysis: nothing to scan", file=sys.stderr)
        return 2

    rules = RULE_TABLE
    if args.rule:
        by_name = {r.name: r for r in RULE_TABLE}
        unknown = [n for n in args.rule if n not in by_name]
        if unknown:
            print(f"analysis: unknown rule(s) {unknown}; have "
                  f"{sorted(by_name)}", file=sys.stderr)
            return 2
        rules = [by_name[n] for n in args.rule]

    project = build_project(paths, root=root)
    if partial_scan:
        # The dead-flag direction needs every hatch reader in scope; a
        # partial scan that happens to include config.py would flag hatches
        # whose reads live in unscanned files.
        project.hatch_decl_in_scan = False
    # Pragma staleness mirrors the dead-flag gating: only a whole-tree
    # all-rules scan can say a pragma suppressed nothing.
    whole_tree = not partial_scan and not args.paths and rules is RULE_TABLE
    used_pragmas = set() if whole_tree else None
    violations = run_rules(project, rules, used_pragmas=used_pragmas)
    if used_pragmas is not None:
        from mpi4dl_tpu.analysis.core import stale_pragmas

        stale_p = stale_pragmas(project, used_pragmas)
        if args.prune_pragmas:
            for v in stale_p:
                text = ""
                src = next((f for f in project.files if f.rel == v.path),
                           None)
                if src is not None:
                    lines = src.text.splitlines()
                    if 0 < v.line <= len(lines):
                        text = lines[v.line - 1].strip()
                print(f"{v.path}:{v.line}: {text}")
            print(
                f"analysis: {len(stale_p)} stale pragma(s) listed for "
                "removal", file=sys.stderr,
            )
            return 1 if stale_p else 0
        violations = sorted(
            violations + stale_p, key=lambda v: (v.path, v.line, v.rule)
        )
    elif args.prune_pragmas:
        print("analysis: --prune-pragmas needs a whole-tree all-rules "
              "scan", file=sys.stderr)
        return 2

    stale: List[dict] = []
    if args.baseline:
        baseline = load_baseline(args.baseline)
        violations, stale = apply_baseline(violations, baseline)
        if partial_scan:
            stale = []  # staleness is meaningless on a partial scan
        if stale and args.prune_baseline:
            kept = [e for e in baseline if e not in stale]
            with open(args.baseline, "w", encoding="utf-8") as fh:
                json.dump(kept, fh, indent=1)
                fh.write("\n")
            print(
                f"analysis: pruned {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} from {args.baseline} "
                f"({len(kept)} kept)",
                file=sys.stderr,
            )

    if args.sarif:
        from mpi4dl_tpu.analysis.sarif import sarif_log, write_sarif

        descriptions = {r.name: r.description for r in RULE_TABLE}
        descriptions["stale-pragma"] = (
            "# analysis: ok(...) pragma that no longer suppresses anything"
        )
        write_sarif(args.sarif, sarif_log(
            violations=violations, rule_descriptions=descriptions,
        ))

    if args.json:
        print(json.dumps(
            {
                "violations": [
                    {
                        "rule": v.rule,
                        "path": v.path,
                        "line": v.line,
                        "message": v.message,
                    }
                    for v in violations
                ],
                "stale_baseline": stale,
            },
            indent=2,
        ))
    else:
        for v in violations:
            print(v.render())
        for e in stale:
            msg = (
                f"stale baseline entry (no longer fires): "
                f"{e.get('path')}: [{e.get('rule')}] {e.get('message')}"
            )
            print(f"warning: {msg}", file=sys.stderr)
            if os.environ.get("GITHUB_ACTIONS"):
                # Surfaced as an inline annotation on the CI run.
                print(f"::warning title=stale analyzer baseline::{msg}")
        if stale and not args.prune_baseline:
            print(
                f"warning: {len(stale)} stale baseline entr"
                f"{'y' if len(stale) == 1 else 'ies'} — rewrite with "
                "--prune-baseline",
                file=sys.stderr,
            )
        n_files = len(project.files)
        print(
            f"analysis: {len(violations)} violation(s) in {n_files} file(s) "
            f"[axes={','.join(project.axes) or '?'}; "
            f"hatches={len(project.hatches)}]",
            file=sys.stderr,
        )
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
