"""CLI: ``python -m mpi4dl_tpu.analysis [--json] [--baseline F] [paths...]``.

With no paths, scans the repository tree the package sits in: the package
itself plus ``tests/``, ``benchmarks/``, ``bench.py`` and
``__graft_entry__.py`` (the env-hatch dead-flag check needs the whole tree —
several hatches are read only by the harness).  Exit status: 0 when no
violations remain after baseline filtering, 1 otherwise, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from mpi4dl_tpu.analysis import (
    RULE_TABLE,
    apply_baseline,
    build_project,
    load_baseline,
    run_rules,
)


def default_paths(root: str) -> List[str]:
    cand = ["mpi4dl_tpu", "tests", "benchmarks", "bench.py", "__graft_entry__.py"]
    return [os.path.join(root, c) for c in cand if os.path.exists(os.path.join(root, c))]


def repo_root() -> str:
    # the directory that holds the mpi4dl_tpu package
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analysis",
        description="Shard-safety static analyzer (see docs/analysis.md).",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to scan (default: repo tree)")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--baseline", metavar="F", default=None,
                    help="JSON list of accepted violations to filter out")
    ap.add_argument("--rule", action="append", default=None, metavar="NAME",
                    help="run only the named rule(s)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--hatch-docs", action="store_true",
                    help="print the README env-hatch table from config.HATCHES")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULE_TABLE:
            print(f"{r.name}: {r.description}")
        return 0
    if args.hatch_docs:
        from mpi4dl_tpu.config import hatches_markdown

        print(hatches_markdown())
        return 0

    root = repo_root()
    paths = args.paths or default_paths(root)
    if not paths:
        print("analysis: nothing to scan", file=sys.stderr)
        return 2

    rules = RULE_TABLE
    if args.rule:
        by_name = {r.name: r for r in RULE_TABLE}
        unknown = [n for n in args.rule if n not in by_name]
        if unknown:
            print(f"analysis: unknown rule(s) {unknown}; have "
                  f"{sorted(by_name)}", file=sys.stderr)
            return 2
        rules = [by_name[n] for n in args.rule]

    project = build_project(paths, root=root)
    violations = run_rules(project, rules)

    stale: List[dict] = []
    if args.baseline:
        baseline = load_baseline(args.baseline)
        violations, stale = apply_baseline(violations, baseline)

    if args.json:
        print(json.dumps(
            {
                "violations": [
                    {
                        "rule": v.rule,
                        "path": v.path,
                        "line": v.line,
                        "message": v.message,
                    }
                    for v in violations
                ],
                "stale_baseline": stale,
            },
            indent=2,
        ))
    else:
        for v in violations:
            print(v.render())
        for e in stale:
            print(
                f"note: stale baseline entry (no longer fires): "
                f"{e.get('path')}: [{e.get('rule')}] {e.get('message')}",
                file=sys.stderr,
            )
        n_files = len(project.files)
        print(
            f"analysis: {len(violations)} violation(s) in {n_files} file(s) "
            f"[axes={','.join(project.axes) or '?'}; "
            f"hatches={len(project.hatches)}]",
            file=sys.stderr,
        )
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
