"""SARIF 2.1.0 output for analyzer violations and ircheck/pallascheck
findings.

One shared serializer so all gates render as GitHub code-scanning
annotations from a single uploaded log (the ``github/codeql-action/
upload-sarif`` step in CI): analyzer violations carry their real
``path:line``; ircheck findings are IR-level (no single source line), so
they anchor on the engine-family registry — the file whose builds produced
the verified artifacts — with the family/scope context in the message;
pallascheck findings likewise anchor on the kernel registry, the file
whose rows enrolled the traced kernels.

Kept dependency-free and minimal: tool driver + rule index + results, the
subset GitHub ingests.  Schema: https://json.schemastore.org/sarif-2.1.0.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"

# Where IR-level findings (which have no one source line) anchor.
IRCHECK_ANCHOR = "mpi4dl_tpu/analysis/contracts/engines.py"

# Where kernel-level pallascheck findings anchor: the registry row is the
# reviewable artifact that enrolled the kernel into the gate.
PALLASCHECK_ANCHOR = "mpi4dl_tpu/ops/kernel_registry.py"


def _result(rule_id: str, message: str, uri: str, line: int,
            rule_index: Dict[str, int]) -> dict:
    if rule_id not in rule_index:
        rule_index[rule_id] = len(rule_index)
    return {
        "ruleId": rule_id,
        "ruleIndex": rule_index[rule_id],
        "level": "error",
        "message": {"text": message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": uri,
                    "uriBaseId": "%SRCROOT%",
                },
                "region": {"startLine": max(1, line)},
            },
        }],
    }


def sarif_log(violations: Sequence = (), ircheck_findings: Sequence = (),
              pallas_findings: Sequence = (),
              rule_descriptions: Optional[Dict[str, str]] = None) -> dict:
    """One SARIF log dict from analyzer ``Violation``s, ircheck
    ``Finding``s and/or pallascheck ``Finding``s."""
    rule_index: Dict[str, int] = {}
    results: List[dict] = []
    for v in violations:
        results.append(_result(v.rule, v.message, v.path, v.line,
                               rule_index))
    for f in ircheck_findings:
        where = " / ".join(p for p in (f.family, f.scope) if p)
        msg = f"[{where}] {f.message}" if where else f.message
        results.append(_result(
            f"ircheck/{f.kind}", msg, IRCHECK_ANCHOR, 1, rule_index,
        ))
    for f in pallas_findings:
        where = " / ".join(p for p in (f.kernel, f.grid_class) if p)
        msg = f"[{where}] {f.message}" if where else f.message
        results.append(_result(
            f"pallascheck/{f.kind}", msg, PALLASCHECK_ANCHOR, 1,
            rule_index,
        ))
    descriptions = rule_descriptions or {}
    rules = [
        {
            "id": rid,
            **({"shortDescription": {"text": descriptions[rid]}}
               if rid in descriptions else {}),
        }
        for rid, _ in sorted(rule_index.items(), key=lambda kv: kv[1])
    ]
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "mpi4dl-tpu-analysis",
                    "informationUri":
                        "https://github.com/OSU-Nowlab/MPI4DL",
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, log: dict) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(log, fh, indent=2, sort_keys=True)
        fh.write("\n")
