"""Rule ``print-call`` (rule 7): no bare ``print()`` in library modules.

Library observability goes through the obs subsystem (``mpi4dl_tpu/obs``:
RunLog records, trace scopes) or stdlib ``logging`` — a ``print`` in library
code is output nobody can route, filter, or parse, which is exactly the
scattered-``print`` observability ISSUE 2 replaces.

Scope: files under ``mpi4dl_tpu/`` only.  Exempt:

- benchmarks/tests/harness files (not package files — out of scope by
  construction);
- ``__main__.py`` modules: CLI entry points whose *product* is stdout
  (``python -m mpi4dl_tpu.analysis``, ``python -m mpi4dl_tpu.obs``);
- lines/functions carrying the standard ``# analysis: ok(print-call)``
  pragma (applied by the shared runner).
"""

from __future__ import annotations

import ast
from typing import List

from mpi4dl_tpu.analysis.core import Project, Rule, Violation


class PrintCallRule(Rule):
    name = "print-call"
    description = (
        "bare print() in mpi4dl_tpu/ library modules — emit via obs "
        "(RunLog/logging) instead; __main__.py CLIs and benchmarks exempt."
    )

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for src in project.package_files():
            if src.rel.endswith("__main__.py"):
                continue
            for node in src.nodes(ast.Call):
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "print"
                    # a locally-bound `print` (alias/param) is not builtin
                    and src.aliases.get("print", "print") == "print"
                ):
                    out.append(
                        Violation(
                            self.name,
                            src.rel,
                            node.lineno,
                            "bare print() in library module — route output "
                            "through mpi4dl_tpu.obs (RunLog) or logging "
                            "(benchmarks and __main__ CLIs are exempt)",
                        )
                    )
        return out


RULE = PrintCallRule()
