"""Compiled-artifact contract gate (ISSUE 4 tentpole).

The shard-safety analyzer (rules 1-9) lints *source*; this package extends
static analysis to the *compiled artifact*: each engine family (lp / sp /
gems / gems_sp) is lowered — never executed — on the virtual mesh, and a
structured **contract** is extracted from the lowered StableHLO + jaxpr:

- collective op counts and bytes, keyed by the ``obs.scope`` name they
  appear under (an accidental extra all-gather in a halo exchange names the
  offending scope, not just a total);
- collective counts and bytes per mesh axis (from the jaxpr's collective
  equations — the semantic view before XLA fuses/reassociates);
- the scope-coverage set (instrumentation that silently disappears drifts);
- trace/lowering counts during build+lower (the retrace budget);
- GSPMD sharding annotations and entry shapes (a resharding inserted at a
  junction shows here before any benchmark regresses);
- the **overlap structure** of the compiled scheduled HLO (schema 2,
  obs/overlap.py): per-scope per-class async start/done-pair counts, sync
  (unsplit, structurally unhideable) counts, payload bytes and structurally
  exposed bytes — a collective that loses its async split fails the gate
  with the owning scope named (ISSUE 9, ROADMAP item 2).

Contracts are checked into ``contracts/<engine>.json`` as goldens;
``python -m mpi4dl_tpu.analysis contracts`` re-extracts and diffs, exiting
nonzero on drift with a human-readable report (``--update`` rewrites the
goldens, ``--json`` emits the machine-readable diff).  See docs/analysis.md.
"""

from __future__ import annotations

from mpi4dl_tpu.analysis.contracts.diff import (
    diff_contracts,
    render_drift_report,
)
from mpi4dl_tpu.analysis.contracts.engines import ENGINE_FAMILIES, build_engine
from mpi4dl_tpu.analysis.contracts.extract import (
    CONTRACT_SCHEMA,
    extract_contract,
    jaxpr_collective_stats,
)

__all__ = [
    "CONTRACT_SCHEMA",
    "ENGINE_FAMILIES",
    "build_engine",
    "diff_contracts",
    "extract_contract",
    "jaxpr_collective_stats",
    "render_drift_report",
]
