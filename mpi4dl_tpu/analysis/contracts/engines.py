"""Canonical per-family engine builds for contract extraction.

One fixed tiny configuration per engine family, mirroring the virtual-mesh
builds the obs scope tests lower (tests/test_obs.py) and the family dispatch
in benchmarks/common.build_train: ResNet-11 at 32px on the 8-device CPU
mesh, 2 pipeline stages, a 2-wide spatial tile grid where the family is
spatial.  Small enough to lower in seconds on any host; rich enough that
every structural collective of the family (halo ppermutes, junction
gather/reduce-scatter, stage handoffs, GEMS mirror, BN psums, gradient
all-reduces) appears in the artifact.

The contract is a *structural* invariant, so the exact numbers here are
arbitrary but FROZEN: changing a constant in this module is a contract
change and requires ``--update`` plus review of the golden diff.
"""

from __future__ import annotations

from typing import Tuple

# The ``*_1f1b`` variants build the SAME frozen configuration under
# ``schedule="1f1b"`` (the manual-backward one-forward-one-backward tick
# loop) — their goldens pin the schedule's collective structure (two
# ppermute handoffs per tick under fwd_tick/bwd_tick scopes) independently
# of the GPipe goldens, which must not drift when the flag is off.
ENGINE_FAMILIES: Tuple[str, ...] = (
    "lp", "sp", "gems", "gems_sp",
    "lp_1f1b", "sp_1f1b", "gems_1f1b", "gems_sp_1f1b",
)

# Frozen build constants (see module docstring before touching these).
_DEPTH = 11
_PX = 32
_BATCH = 4
_GEMS_SP_BATCH = 8
_CLASSES = 10
_STAGES = 2
_PARTS = 2  # microbatches
_SPW = 2
_SEED = 0


def base_family(family: str) -> str:
    """Strip the ``_1f1b`` schedule suffix off a contract family name."""
    return family[: -len("_1f1b")] if family.endswith("_1f1b") else family


def required_devices(family: str) -> int:
    """Virtual-mesh device count the family's canonical build needs."""
    return (
        _STAGES * _SPW
        if base_family(family) in ("sp", "gems_sp")
        else _STAGES
    )


def build_engine(family: str, quant=None):
    """Build the family's canonical train step on the virtual mesh.

    Returns ``(step, args)`` where ``step`` is the jitted train step and
    ``args`` the abstract-ready argument tuple — ``step.lower(*args)`` is
    the only thing callers do with it (contracts never execute).

    ``quant`` (Optional[QuantPolicy]): build the SAME frozen configuration
    with quantized collectives on — the ``--quant`` contract set
    (goldens under ``contracts/quant_<mode>/``) and the byte-ratio gate
    extract through this; the default ``None`` build must stay
    bit-identical to the raw goldens.
    """
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.layer_ctx import SpatialCtx
    from mpi4dl_tpu.mesh import AXIS_SPW, MeshSpec, build_mesh
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.train import Optimizer

    if family not in ENGINE_FAMILIES:
        raise ValueError(f"unknown engine family {family!r}; "
                         f"have {ENGINE_FAMILIES}")
    schedule = "1f1b" if family.endswith("_1f1b") else "gpipe"
    family = base_family(family)

    batch = _GEMS_SP_BATCH if family == "gems_sp" else _BATCH
    model = get_resnet_v2((batch, _PX, _PX, 3), depth=_DEPTH,
                          num_classes=_CLASSES)
    params, _ = model.init(jax.random.key(_SEED))
    opt = Optimizer("sgd", lr=0.01)
    x = jnp.zeros((batch, _PX, _PX, 3), jnp.float32)
    y = jnp.zeros((batch,), jnp.int32)

    if family in ("lp", "gems"):
        from mpi4dl_tpu.parallel.partition import StagePartition
        from mpi4dl_tpu.parallel.pipeline import init_pipeline_state

        mesh = build_mesh(MeshSpec(stage=_STAGES), jax.devices()[:_STAGES])
        micro = batch // (_PARTS if family == "lp" else 2 * _PARTS)
        part = StagePartition.build(
            model, params, _STAGES, (micro, _PX, _PX, 3)
        )
        if family == "lp":
            from mpi4dl_tpu.parallel.pipeline import make_pipeline_train_step

            step = make_pipeline_train_step(part, opt, mesh, parts=_PARTS,
                                            schedule=schedule, quant=quant)
        else:
            from mpi4dl_tpu.parallel.gems import make_gems_train_step

            step = make_gems_train_step(part, opt, mesh, parts=_PARTS,
                                        times=1, schedule=schedule,
                                        quant=quant)
        state = init_pipeline_state(part, params, opt, mesh)
        return step, (state, x, y)

    # Spatial families: SP x PP (sp) and GEMS x SP x PP (gems_sp).
    from mpi4dl_tpu.parallel.sp_pipeline import (
        SPPipeline,
        init_sp_pipeline_state,
        make_sp_gems_train_step,
        make_sp_pipeline_train_step,
    )

    model.spatial_until = 2
    sp = SpatialCtx(axis_w=AXIS_SPW, grid_w=_SPW)
    mesh = build_mesh(
        MeshSpec(stage=_STAGES, spw=_SPW), jax.devices()[:_STAGES * _SPW]
    )
    micro = batch // (_PARTS if family == "sp" else 2 * _PARTS)
    spp = SPPipeline.build(model, params, _STAGES, sp, micro,
                           junction="gather")
    if family == "sp":
        step = make_sp_pipeline_train_step(spp, opt, mesh, parts=_PARTS,
                                           schedule=schedule, quant=quant)
    else:
        step = make_sp_gems_train_step(spp, opt, mesh, parts=_PARTS, times=1,
                                       schedule=schedule, quant=quant)
    state = init_sp_pipeline_state(spp, params, opt, mesh)
    return step, (state, x, y)
