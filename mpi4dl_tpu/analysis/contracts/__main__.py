"""CLI: ``python -m mpi4dl_tpu.analysis contracts [--update] [--json]``
(also reachable as ``python -m mpi4dl_tpu.analysis.contracts``).

Checks the freshly-extracted per-engine contracts against the goldens in
``contracts/*.json`` at the repo root.  Exit status mirrors the analyzer:
0 = no drift, 1 = drift (or missing golden), 2 = usage/environment errors.
``--update`` rewrites the goldens instead of failing; ``--json`` prints the
machine-readable diff (the CI job uploads it as an artifact on failure);
``--out F`` additionally writes that JSON to a file in either mode.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List


def default_contracts_dir() -> str:
    from mpi4dl_tpu.analysis.__main__ import repo_root

    return os.path.join(repo_root(), "contracts")


def golden_path(directory: str, family: str) -> str:
    return os.path.join(directory, f"{family}.json")


def main(argv=None) -> int:
    from mpi4dl_tpu.analysis.contracts.diff import (
        diff_contracts,
        render_drift_report,
    )
    from mpi4dl_tpu.analysis.contracts.engines import ENGINE_FAMILIES
    from mpi4dl_tpu.analysis.contracts.extract import (
        ensure_virtual_mesh,
        extract_contract,
    )

    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analysis contracts",
        description="Compiled-artifact contract gate (docs/analysis.md): "
        "lower each engine family on the virtual mesh and diff its "
        "StableHLO/jaxpr contract against the checked-in golden.",
    )
    ap.add_argument("--update", action="store_true",
                    help="rewrite the goldens from the current artifacts")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable diff on stdout")
    ap.add_argument("--out", metavar="F", default=None,
                    help="also write the JSON diff to this file")
    ap.add_argument("--dir", metavar="D", default=None,
                    help="goldens directory (default: <repo>/contracts)")
    ap.add_argument("--engines", metavar="NAMES", default=None,
                    help="comma-separated subset of engine families "
                         f"(default: {','.join(ENGINE_FAMILIES)})")
    ap.add_argument("--section", choices=["overlap"], default=None,
                    help="restrict drift reporting to one contract section "
                         "(plus meta mismatches); the overlap-contract CI "
                         "job gates on --section overlap so overlap "
                         "regressions fail with a focused report")
    args = ap.parse_args(argv)

    families = list(ENGINE_FAMILIES)
    if args.engines:
        families = [f.strip() for f in args.engines.split(",") if f.strip()]
        unknown = [f for f in families if f not in ENGINE_FAMILIES]
        if unknown:
            print(f"contracts: unknown engine(s) {unknown}; "
                  f"have {list(ENGINE_FAMILIES)}", file=sys.stderr)
            return 2

    err = ensure_virtual_mesh(families)
    if err:
        print(f"contracts: {err}", file=sys.stderr)
        return 2

    directory = args.dir or default_contracts_dir()
    report: Dict[str, List[dict]] = {}
    rc = 0
    for family in families:
        current = extract_contract(family)
        path = golden_path(directory, family)
        if args.update:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(current, fh, indent=1, sort_keys=True)
                fh.write("\n")
            if not args.json:
                print(f"contract written: {path}")
            report[family] = []
            continue
        if not os.path.exists(path):
            report[family] = [{"kind": "meta", "field": "golden",
                               "golden": None, "current": path}]
            if not args.json:
                print(f"contract MISSING: no golden at {path} "
                      "(run with --update to create it)")
            rc = 1
            continue
        with open(path, "r", encoding="utf-8") as fh:
            golden = json.load(fh)
        drifts = diff_contracts(golden, current)
        if args.section:
            drifts = [d for d in drifts
                      if d["kind"] in ("meta", args.section)]
        report[family] = drifts
        if drifts:
            rc = 1
        if not args.json:
            print(render_drift_report(family, drifts))
            if drifts and golden.get("jax") != current.get("jax"):
                print(
                    f"  note: golden was extracted on jax "
                    f"{golden.get('jax')}, this run is jax "
                    f"{current.get('jax')} — lowering differences may be "
                    "version skew, not a code change"
                )

    payload = json.dumps({"drift": report}, indent=2, sort_keys=True)
    if args.json:
        print(payload)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    if rc == 0 and not args.json and not args.update:
        print(f"contracts: {len(families)} engine famil"
              f"{'y' if len(families) == 1 else 'ies'} clean")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
