"""CLI: ``python -m mpi4dl_tpu.analysis contracts [--update] [--json]``
(also reachable as ``python -m mpi4dl_tpu.analysis.contracts``).

Checks the freshly-extracted per-engine contracts against the goldens in
``contracts/*.json`` at the repo root.  Exit status mirrors the analyzer:
0 = no drift, 1 = drift (or missing golden), 2 = usage/environment errors.
``--update`` rewrites the goldens instead of failing; ``--json`` prints the
machine-readable diff (the CI job uploads it as an artifact on failure);
``--out F`` additionally writes that JSON to a file in either mode.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List


def default_contracts_dir() -> str:
    from mpi4dl_tpu.analysis.__main__ import repo_root

    return os.path.join(repo_root(), "contracts")


def golden_path(directory: str, family: str) -> str:
    return os.path.join(directory, f"{family}.json")


def main(argv=None) -> int:
    from mpi4dl_tpu.analysis.contracts.diff import (
        diff_contracts,
        render_drift_report,
    )
    from mpi4dl_tpu.analysis.contracts.engines import ENGINE_FAMILIES
    from mpi4dl_tpu.analysis.contracts.extract import (
        ensure_virtual_mesh,
        extract_contract,
    )

    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analysis contracts",
        description="Compiled-artifact contract gate (docs/analysis.md): "
        "lower each engine family on the virtual mesh and diff its "
        "StableHLO/jaxpr contract against the checked-in golden.",
    )
    ap.add_argument("--update", action="store_true",
                    help="rewrite the goldens from the current artifacts")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable diff on stdout")
    ap.add_argument("--out", metavar="F", default=None,
                    help="also write the JSON diff to this file")
    ap.add_argument("--dir", metavar="D", default=None,
                    help="goldens directory (default: <repo>/contracts)")
    ap.add_argument("--engines", metavar="NAMES", default=None,
                    help="comma-separated subset of engine families; the "
                         "pseudo-family `pallas` selects the Pallas kernel "
                         "contract alone "
                         f"(default: {','.join(ENGINE_FAMILIES)} + pallas)")
    ap.add_argument("--section", choices=["overlap", "pallas"],
                    default=None,
                    help="restrict drift reporting to one contract section "
                         "(plus meta mismatches); the overlap-contract CI "
                         "job gates on --section overlap so overlap "
                         "regressions fail with a focused report")
    ap.add_argument("--quant", metavar="SPEC", default=None,
                    help="extract with the quantized-collective policy on "
                         "(e.g. int8); goldens default to "
                         "<repo>/contracts/quant_<mode>/ and every family "
                         "additionally gets the byte-ratio gate against the "
                         "RAW goldens (--max-ratio)")
    ap.add_argument("--max-ratio", type=float, default=0.55,
                    help="with --quant: max quantized/raw contract-byte "
                         "ratio per gated wire class (junction/respatial/"
                         "grad); exceeded = exit 1 (default 0.55)")
    args = ap.parse_args(argv)

    families = list(ENGINE_FAMILIES)
    # The Pallas kernel contract rides as a pseudo-family: no engine build,
    # its "extraction" traces the kernel registry (skipped under --quant —
    # the registry already enrolls the quantized kernel variants as their
    # own cases, so there is no separate quant contract set).
    want_pallas = not args.quant
    if args.engines:
        families = [f.strip() for f in args.engines.split(",") if f.strip()]
        want_pallas = "pallas" in families and not args.quant
        families = [f for f in families if f != "pallas"]
        unknown = [f for f in families if f not in ENGINE_FAMILIES]
        if unknown:
            print(f"contracts: unknown engine(s) {unknown}; "
                  f"have {list(ENGINE_FAMILIES)} + pallas", file=sys.stderr)
            return 2

    err = ensure_virtual_mesh(families)
    if err:
        print(f"contracts: {err}", file=sys.stderr)
        return 2

    build = None
    policy = None
    if args.quant:
        from mpi4dl_tpu.analysis.contracts.engines import build_engine
        from mpi4dl_tpu.quant import QuantPolicy

        try:
            policy = QuantPolicy.parse(args.quant)
        except ValueError as e:
            print(f"contracts: {e}", file=sys.stderr)
            return 2
        if policy is None:
            print("contracts: --quant off is the default contract set; "
                  "drop the flag", file=sys.stderr)
            return 2
        build = lambda f: build_engine(f, quant=policy)  # noqa: E731

    raw_directory = default_contracts_dir()
    directory = args.dir or (
        os.path.join(raw_directory,
                     "quant_" + args.quant.replace(",", "_").replace("=", "-"))
        if args.quant else raw_directory
    )
    report: Dict[str, List[dict]] = {}
    ratio_report: Dict[str, dict] = {}
    rc = 0
    for family in families:
        current = extract_contract(family, build=build)
        if policy is not None:
            # Byte-ratio gate vs the RAW golden (the tentpole's acceptance
            # criterion: junction/respatial/grad contract bytes <=
            # max_ratio x raw on every family; vacuous where raw is 0).
            from mpi4dl_tpu.analysis.contracts.diff import (
                quant_byte_ratios,
                render_ratio_report,
            )

            raw_path = golden_path(raw_directory, family)
            if os.path.exists(raw_path):
                with open(raw_path, "r", encoding="utf-8") as fh:
                    raw_golden = json.load(fh)
                rows, breaches = quant_byte_ratios(
                    raw_golden, current, args.max_ratio
                )
                ratio_report[family] = {"rows": rows, "breaches": breaches}
                if not args.json:
                    print(render_ratio_report(family, rows, breaches,
                                              args.max_ratio))
                if breaches:
                    rc = 1
            else:
                # A missing raw golden must not pass the ratio gate
                # vacuously — the "<= max_ratio x raw on every family"
                # criterion would be unenforced with no signal.
                ratio_report[family] = {
                    "rows": [], "breaches": [f"no raw golden at {raw_path}"]
                }
                print(f"quant ratio gate FAILED for {family}: no raw "
                      f"golden at {raw_path} (regenerate the raw contract "
                      "set first)", file=sys.stderr)
                rc = 1
        path = golden_path(directory, family)
        if args.update:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(current, fh, indent=1, sort_keys=True)
                fh.write("\n")
            if not args.json:
                print(f"contract written: {path}")
            report[family] = []
            continue
        if not os.path.exists(path):
            report[family] = [{"kind": "meta", "field": "golden",
                               "golden": None, "current": path}]
            if not args.json:
                print(f"contract MISSING: no golden at {path} "
                      "(run with --update to create it)")
            rc = 1
            continue
        with open(path, "r", encoding="utf-8") as fh:
            golden = json.load(fh)
        drifts = diff_contracts(golden, current)
        if args.section:
            drifts = [d for d in drifts
                      if d["kind"] in ("meta", args.section)]
        report[family] = drifts
        if drifts:
            rc = 1
        if not args.json:
            print(render_drift_report(family, drifts))
            if drifts and golden.get("jax") != current.get("jax"):
                print(
                    f"  note: golden was extracted on jax "
                    f"{golden.get('jax')}, this run is jax "
                    f"{current.get('jax')} — lowering differences may be "
                    "version skew, not a code change"
                )

    if want_pallas:
        from mpi4dl_tpu.analysis.contracts.diff import diff_pallas_contract
        from mpi4dl_tpu.analysis.pallascheck import pallas_contract

        current = pallas_contract()
        path = golden_path(raw_directory, "pallas")
        if args.update:
            os.makedirs(raw_directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(current, fh, indent=1, sort_keys=True)
                fh.write("\n")
            if not args.json:
                print(f"contract written: {path}")
            report["pallas"] = []
        elif not os.path.exists(path):
            report["pallas"] = [{"kind": "meta", "field": "golden",
                                 "golden": None, "current": path}]
            if not args.json:
                print(f"contract MISSING: no golden at {path} "
                      "(run with --update to create it)")
            rc = 1
        else:
            with open(path, "r", encoding="utf-8") as fh:
                golden = json.load(fh)
            drifts = diff_pallas_contract(golden, current)
            if args.section:
                drifts = [d for d in drifts
                          if d["kind"] in ("meta", args.section)]
            report["pallas"] = drifts
            if drifts:
                rc = 1
            if not args.json:
                print(render_drift_report("pallas", drifts))
                if drifts and golden.get("jax") != current.get("jax"):
                    print(
                        f"  note: golden was extracted on jax "
                        f"{golden.get('jax')}, this run is jax "
                        f"{current.get('jax')} — tracing differences may "
                        "be version skew, not a code change"
                    )

    payload = json.dumps(
        {"drift": report, **({"quant_ratio": ratio_report}
                             if ratio_report else {})},
        indent=2, sort_keys=True,
    )
    if args.json:
        print(payload)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    if rc == 0 and not args.json and not args.update:
        n = len(families)
        print(f"contracts: {n} engine famil"
              f"{'y' if n == 1 else 'ies'}"
              + (" + pallas kernel contract" if want_pallas else "")
              + " clean")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
