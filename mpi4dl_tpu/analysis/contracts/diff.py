"""Contract diffing: golden vs current, with a drift report a human can
act on — which scope gained/lost which collective, byte deltas, coverage
and sharding changes.

Drift records are dicts with a ``kind`` discriminator so the JSON output is
machine-checkable (the CI job uploads it as an artifact on failure):

- ``collective``: per-(scope, op) count/byte delta (count_golden/_current,
  bytes_golden/_current);
- ``axis-collective``: per-(mesh axis, primitive) delta from the jaxpr view;
- ``scope-coverage``: a scope name appeared in / disappeared from the
  lowered artifact;
- ``lowerings``: trace/lowering count moved (retrace budget);
- ``sharding``: a GSPMD sharding annotation histogram entry or an entry
  shape changed;
- ``overlap``: the compiled schedule's overlap structure moved for a
  (scope, collective class) — async-pair/sync counts, payload bytes, or
  structurally exposed bytes (a collective that loses its start/done split
  becomes unhideable; ISSUE 9 / ROADMAP item 2);
- ``ircheck``: the IR verifier's per-kind finding count moved (a clean
  engine pins ``{}``; any growth names the regression class — wasted-wire,
  divergent-collective, read-after-donate, ... — ISSUE 16);
- ``pallas``: the static Pallas kernel verifier's ``pallas`` section moved
  for one registered kernel case — grid, a block shape, the re-derived
  per-grid-point VMEM total, the DMA-start count, or a finding count
  (clean kernels pin ``{}`` findings; ISSUE 19);
- ``meta``: schema/engine mismatch (golden unusable — regenerate).
"""

from __future__ import annotations

from typing import Dict, List, Tuple


def _counted(d: dict, *path) -> Dict[str, int]:
    for key in path:
        d = d.get(key, {}) if isinstance(d, dict) else {}
    if not isinstance(d, dict):
        return {}
    return d


def _diff_counted_tree(
    kind: str, golden: dict, current: dict, label: str
) -> List[dict]:
    """Diff two {outer: {op: {count, bytes}}} trees into drift records."""
    out: List[dict] = []
    for outer in sorted(set(golden) | set(current)):
        g_ops, c_ops = golden.get(outer, {}), current.get(outer, {})
        for op in sorted(set(g_ops) | set(c_ops)):
            g = g_ops.get(op, {"count": 0, "bytes": 0})
            c = c_ops.get(op, {"count": 0, "bytes": 0})
            if g == c:
                continue
            out.append({
                "kind": kind,
                label: outer,
                "op": op,
                "count_golden": g.get("count", 0),
                "count_current": c.get("count", 0),
                "bytes_golden": g.get("bytes", 0),
                "bytes_current": c.get("bytes", 0),
            })
    return out


def diff_contracts(golden: dict, current: dict) -> List[dict]:
    """All drift records between a golden and a freshly-extracted contract.
    Empty list = the artifact still honors the contract."""
    drifts: List[dict] = []
    for field in ("schema", "engine"):
        if golden.get(field) != current.get(field):
            drifts.append({
                "kind": "meta", "field": field,
                "golden": golden.get(field), "current": current.get(field),
            })
    if drifts:
        return drifts  # mismatched contracts — field diffs are meaningless

    drifts += _diff_counted_tree(
        "collective", _counted(golden, "collectives"),
        _counted(current, "collectives"), "scope",
    )
    drifts += _diff_counted_tree(
        "axis-collective", _counted(golden, "axis_collectives"),
        _counted(current, "axis_collectives"), "axis",
    )

    g_scopes = set(golden.get("scopes", ()))
    c_scopes = set(current.get("scopes", ()))
    for name in sorted(g_scopes - c_scopes):
        drifts.append({"kind": "scope-coverage", "scope": name,
                       "change": "lost"})
    for name in sorted(c_scopes - g_scopes):
        drifts.append({"kind": "scope-coverage", "scope": name,
                       "change": "gained"})

    g_low = golden.get("lowerings", {})
    c_low = current.get("lowerings", {})
    for field in sorted(set(g_low) | set(c_low)):
        if g_low.get(field) != c_low.get(field):
            drifts.append({
                "kind": "lowerings", "field": field,
                "golden": g_low.get(field), "current": c_low.get(field),
            })

    g_sh = _counted(golden, "shardings", "annotations")
    c_sh = _counted(current, "shardings", "annotations")
    for name in sorted(set(g_sh) | set(c_sh)):
        if g_sh.get(name, 0) != c_sh.get(name, 0):
            drifts.append({
                "kind": "sharding", "annotation": name,
                "count_golden": g_sh.get(name, 0),
                "count_current": c_sh.get(name, 0),
            })
    g_in = golden.get("shardings", {}).get("inputs", [])
    c_in = current.get("shardings", {}).get("inputs", [])
    if g_in != c_in:
        drifts.append({
            "kind": "sharding", "annotation": "<entry shapes>",
            "golden": g_in, "current": c_in,
        })

    drifts += _diff_overlap(
        _counted(golden, "overlap", "per_scope"),
        _counted(current, "overlap", "per_scope"),
    )

    g_irc = _counted(golden, "ircheck")
    c_irc = _counted(current, "ircheck")
    for name in sorted(set(g_irc) | set(c_irc)):
        if g_irc.get(name, 0) != c_irc.get(name, 0):
            drifts.append({
                "kind": "ircheck", "finding": name,
                "count_golden": g_irc.get(name, 0),
                "count_current": c_irc.get(name, 0),
            })
    return drifts


_OVERLAP_FIELDS = ("async_pairs", "sync", "bytes", "exposed_bytes")
_OVERLAP_ZERO = {f: 0 for f in _OVERLAP_FIELDS}


def _diff_overlap(golden: dict, current: dict) -> List[dict]:
    """Diff two overlap ``per_scope`` trees ({scope: {class: {async_pairs,
    sync, bytes, exposed_bytes}}}) into per-(scope, class) drift records."""
    out: List[dict] = []
    for scope in sorted(set(golden) | set(current)):
        g_ops, c_ops = golden.get(scope, {}), current.get(scope, {})
        for op in sorted(set(g_ops) | set(c_ops)):
            g = {**_OVERLAP_ZERO, **g_ops.get(op, {})}
            c = {**_OVERLAP_ZERO, **c_ops.get(op, {})}
            if g == c:
                continue
            rec = {"kind": "overlap", "scope": scope, "op": op}
            for f in _OVERLAP_FIELDS:
                rec[f"{f}_golden"] = g[f]
                rec[f"{f}_current"] = c[f]
            out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Pallas kernel contract (ISSUE 19)
# ---------------------------------------------------------------------------

_PALLAS_FIELDS = ("grid", "vmem_bytes", "dma_starts")


def diff_pallas_contract(golden: dict, current: dict) -> List[dict]:
    """Drift records between a golden and a freshly-extracted ``pallas``
    contract (:func:`mpi4dl_tpu.analysis.pallascheck.pallas_contract`).
    Record shape: ``{"kind": "pallas", "kernel": case, "field": ...,
    "golden": ..., "current": ...}`` (field ``presence`` when a registry
    case appeared or disappeared)."""
    drifts: List[dict] = []
    for field in ("schema", "vmem_frac"):
        if golden.get(field) != current.get(field):
            drifts.append({
                "kind": "meta", "field": field,
                "golden": golden.get(field), "current": current.get(field),
            })
    if drifts:
        return drifts
    g_k = golden.get("kernels", {})
    c_k = current.get("kernels", {})
    for name in sorted(set(g_k) | set(c_k)):
        if name not in c_k or name not in g_k:
            drifts.append({
                "kind": "pallas", "kernel": name, "field": "presence",
                "golden": name in g_k, "current": name in c_k,
            })
            continue
        g, c = g_k[name], c_k[name]
        for field in _PALLAS_FIELDS:
            if g.get(field) != c.get(field):
                drifts.append({
                    "kind": "pallas", "kernel": name, "field": field,
                    "golden": g.get(field), "current": c.get(field),
                })
        g_b, c_b = g.get("blocks", {}), c.get("blocks", {})
        for op in sorted(set(g_b) | set(c_b)):
            if g_b.get(op) != c_b.get(op):
                drifts.append({
                    "kind": "pallas", "kernel": name,
                    "field": f"blocks.{op}",
                    "golden": g_b.get(op), "current": c_b.get(op),
                })
        g_f, c_f = g.get("findings", {}), c.get("findings", {})
        for kind in sorted(set(g_f) | set(c_f)):
            if g_f.get(kind, 0) != c_f.get(kind, 0):
                drifts.append({
                    "kind": "pallas", "kernel": name,
                    "field": f"findings.{kind}",
                    "golden": g_f.get(kind, 0), "current": c_f.get(kind, 0),
                })
    return drifts


# ---------------------------------------------------------------------------
# Quantized-contract byte-ratio gate (ISSUE 10)
# ---------------------------------------------------------------------------

# The wire classes the --quant ratio gate enforces (the tentpole's
# "junction + respatial + grad-reduce contract bytes <= max_ratio x raw").
# handoff is reported but not gated — it is quantized opportunistically
# and absent from the acceptance criteria's class list.  NOTE: the frozen
# contract families run a single spatial level, so respatial is vacuous
# HERE; its non-vacuous enforcement is the lowered multilevel-engine
# ratio test (tests/test_quant.py::
# test_respatial_ratio_non_vacuous_on_multilevel_engine).
QUANT_GATED_CLASSES = ("junction", "respatial", "grad")


def quant_class_bytes(contract: dict) -> Dict[str, int]:
    """Per-quant-class byte sums over a contract's per-scope collective
    ledger (classes from mpi4dl_tpu.quant.policy.HOT_SCOPE_PATTERNS)."""
    from mpi4dl_tpu.quant.policy import scope_quant_class

    out: Dict[str, int] = {}
    for scope, ops in (contract.get("collectives") or {}).items():
        cls = scope_quant_class(scope)
        if cls is None:
            continue
        out[cls] = out.get(cls, 0) + sum(
            v.get("bytes", 0) for v in ops.values()
        )
    return out


def quant_byte_ratios(raw: dict, quant: dict, max_ratio: float
                      ) -> Tuple[List[dict], List[str]]:
    """Compare a quantized contract's hot-class bytes against the RAW
    golden's: returns ``(rows, breach_lines)``.  A gated class whose
    quantized bytes exceed ``max_ratio`` x the raw bytes breaches; classes
    the family doesn't exercise (raw == 0 — e.g. lp has no junction) are
    reported as n/a and pass vacuously."""
    rb, qb = quant_class_bytes(raw), quant_class_bytes(quant)
    rows: List[dict] = []
    breaches: List[str] = []
    for cls in sorted(set(rb) | set(qb)):
        r, q = rb.get(cls, 0), qb.get(cls, 0)
        ratio = (q / r) if r else None
        gated = cls in QUANT_GATED_CLASSES
        rows.append({"class": cls, "raw_bytes": r, "quant_bytes": q,
                     "ratio": None if ratio is None else round(ratio, 4),
                     "gated": gated})
        if gated and ratio is not None and ratio > max_ratio:
            breaches.append(
                f"class {cls}: quantized bytes {q} > {max_ratio:g} x raw "
                f"{r} (ratio {ratio:.3f})"
            )
    return rows, breaches


def render_ratio_report(engine: str, rows: List[dict],
                        breaches: List[str], max_ratio: float) -> str:
    lines = [f"quant byte ratio: engine {engine} (gate <= {max_ratio:g}x "
             f"on {'/'.join(QUANT_GATED_CLASSES)})"]
    for r in rows:
        ratio = "n/a" if r["ratio"] is None else f"{r['ratio']:.3f}x"
        mark = "" if r["gated"] else "  (reported, not gated)"
        lines.append(
            f"  {r['class']:<10} raw {r['raw_bytes']:>12} -> quant "
            f"{r['quant_bytes']:>12}  {ratio}{mark}"
        )
    for b in breaches:
        lines.append(f"  BREACH: {b}")
    return "\n".join(lines)


def _fmt_delta(golden: int, current: int) -> str:
    delta = current - golden
    return f"{golden} -> {current} ({'+' if delta >= 0 else ''}{delta})"


def render_drift_report(engine: str, drifts: List[dict]) -> str:
    """Human-readable drift report for one engine."""
    if not drifts:
        return f"contract ok: engine {engine}"
    lines = [f"contract DRIFT: engine {engine} ({len(drifts)} finding(s))"]
    for d in drifts:
        kind = d["kind"]
        if kind == "meta":
            lines.append(
                f"  {d['field']} mismatch: golden {d['golden']!r} vs "
                f"current {d['current']!r} — regenerate with --update"
            )
        elif kind in ("collective", "axis-collective"):
            where = ("scope " + d["scope"]) if kind == "collective" else (
                "mesh axis " + d["axis"])
            g_n, c_n = d["count_golden"], d["count_current"]
            if g_n == 0:
                verb = f"{d['op']} APPEARED (count {c_n}, " \
                       f"{d['bytes_current']} bytes)"
            elif c_n == 0:
                verb = f"{d['op']} DISAPPEARED (was count {g_n}, " \
                       f"{d['bytes_golden']} bytes)"
            else:
                verb = (
                    f"{d['op']} count {_fmt_delta(g_n, c_n)}, bytes "
                    f"{_fmt_delta(d['bytes_golden'], d['bytes_current'])}"
                )
            lines.append(f"  {where}: {verb}")
        elif kind == "scope-coverage":
            lines.append(f"  scope coverage {d['change']}: {d['scope']}")
        elif kind == "lowerings":
            lines.append(
                f"  lowerings.{d['field']}: "
                f"{_fmt_delta(d['golden'], d['current'])} (retrace budget)"
            )
        elif kind == "overlap":
            bits = []
            for f in _OVERLAP_FIELDS:
                g_v, c_v = d[f"{f}_golden"], d[f"{f}_current"]
                if g_v != c_v:
                    bits.append(f"{f} {_fmt_delta(g_v, c_v)}")
            extra = ""
            if (d["sync_golden"] == 0 and d["sync_current"] > 0
                    and d["async_pairs_current"] < d["async_pairs_golden"]):
                extra = " — collective LOST its start/done split " \
                        "(now structurally unhideable)"
            lines.append(
                f"  overlap scope {d['scope']}: {d['op']} "
                + ", ".join(bits) + extra
            )
        elif kind == "ircheck":
            lines.append(
                f"  ircheck finding {d['finding']}: count "
                f"{_fmt_delta(d['count_golden'], d['count_current'])} — "
                "run `python -m mpi4dl_tpu.analysis ircheck` for details"
            )
        elif kind == "pallas":
            extra = ""
            if d["field"].startswith("findings."):
                extra = (" — run `python -m mpi4dl_tpu.analysis "
                         "pallascheck` for details")
            elif d["field"] == "presence":
                extra = (" — registry case "
                         + ("REMOVED" if d["golden"] else "ADDED")
                         + "; regenerate with --update if intended")
            lines.append(
                f"  pallas kernel {d['kernel']}: {d['field']} "
                f"golden {d['golden']} vs current {d['current']}{extra}"
            )
        elif kind == "sharding":
            if "count_golden" in d:
                lines.append(
                    f"  sharding annotation {d['annotation']}: count "
                    f"{_fmt_delta(d['count_golden'], d['count_current'])}"
                )
            else:
                lines.append(
                    f"  sharding {d['annotation']}: golden {d['golden']} "
                    f"vs current {d['current']}"
                )
        else:
            lines.append(f"  {d}")
    return "\n".join(lines)
