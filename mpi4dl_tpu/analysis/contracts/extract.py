"""Contract extraction: lowered StableHLO + jaxpr + scheduled HLO ->
structured contract.

The engine's train step is built, ``.lower()``-ed and (since schema 2)
``compile()``-d on the virtual mesh — never executed — so the gate runs on
any CPU host in tens of seconds, the same property that makes the source
analyzer usable without a TPU tunnel window.  The compile feeds the
``overlap`` section: the *scheduled* compiled HLO is the only artifact that
says whether a collective was split into async start/done halves (hideable)
or compiled sync (structurally unhideable) — obs/overlap.py's structural
projection, pinned per scope (ISSUE 9, ROADMAP item 2's overlap-structure
gate).
"""

from __future__ import annotations

from typing import Dict, List, Optional

# Schema 2: adds the ``overlap`` section (per-scope per-class async-pair/
# sync counts, payload bytes, structurally exposed bytes from the compiled
# scheduled HLO).  Schema 3: adds the ``ircheck`` section (per-kind IR
# verifier finding counts over the jaxpr + compiled HLO — a clean engine
# pins ``{}``, so a refactor that introduces a wasted-wire reduction or an
# unpaired async op fails the gate).  Goldens with an older schema are
# unusable — regenerate.
CONTRACT_SCHEMA = 3

# jaxpr collective primitives -> the mesh-axis parameter that names them.
_JAXPR_COLLECTIVES = ("psum", "pmax", "pmin", "ppermute", "all_gather",
                      "psum_scatter", "all_to_all", "pbroadcast")

# /jax/core/compile duration events (jax._src.dispatch): one per jaxpr
# trace / per jaxpr->MLIR lowering.  Counted during build+lower as the
# retrace budget — a refactor that starts tracing an engine twice shows up
# here before it shows up as wall-clock.
_TRACE_EVENT_SUFFIXES = ("jaxpr_trace_duration", "jaxpr_to_mlir_module_duration")


def _aval_bytes(aval) -> int:
    try:
        import numpy as np

        n = 1
        for d in aval.shape:
            n *= int(d)
        return n * np.dtype(aval.dtype).itemsize
    except Exception:  # noqa: BLE001 — abstract tokens/effects have no shape
        return 0


def jaxpr_collective_stats(jaxpr) -> Dict[str, Dict[str, Dict[str, int]]]:
    """``{axis: {prim: {count, bytes}}}`` over every collective equation in
    a (closed) jaxpr, recursing into sub-jaxprs (scan/cond/pjit/remat/
    shard_map bodies).  Bytes are the equation's total output payload — the
    semantic per-invocation volume (a collective inside a scan body counts
    once; the contract is structural, not a per-step byte meter)."""
    out: Dict[str, Dict[str, Dict[str, int]]] = {}

    def record(axis: str, prim: str, nbytes: int) -> None:
        per_axis = out.setdefault(axis, {})
        entry = per_axis.setdefault(prim, {"count": 0, "bytes": 0})
        entry["count"] += 1
        entry["bytes"] += nbytes

    def walk(jx) -> None:
        jx = getattr(jx, "jaxpr", jx)  # unwrap ClosedJaxpr
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            if prim in _JAXPR_COLLECTIVES:
                axes = eqn.params.get("axes",
                                      eqn.params.get("axis_name", ()))
                if not isinstance(axes, (tuple, list)):
                    axes = (axes,)
                nbytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
                for ax in axes:
                    record(str(ax), prim, nbytes)
            for v in eqn.params.values():
                if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for item in v:
                        if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                            walk(item)

    walk(jaxpr)
    return out


class _LoweringCounter:
    """Counts jaxpr traces and MLIR lowerings via jax.monitoring duration
    events while active (the retrace budget)."""

    def __init__(self):
        self.counts = {suffix: 0 for suffix in _TRACE_EVENT_SUFFIXES}

    def __call__(self, event: str, duration_secs: float, **kw) -> None:
        for suffix in _TRACE_EVENT_SUFFIXES:
            if event.endswith(suffix):
                self.counts[suffix] += 1

    def __enter__(self) -> "_LoweringCounter":
        from jax._src import monitoring

        monitoring.register_event_duration_secs_listener(self)
        return self

    def __exit__(self, *exc) -> None:
        from jax._src import monitoring

        try:
            monitoring._unregister_event_duration_listener_by_callback(self)
        except Exception:  # analysis: ok(swallow-except) — jax internals moved; a leaked listener is benign
            pass


def _entry_shapes(avals) -> List[str]:
    return [f"{getattr(a, 'dtype', '?')}{list(getattr(a, 'shape', ()))}"
            for a in avals]


def extract_contract(family: str, build=None) -> dict:
    """Extract the contract dict for one engine family.

    ``build`` overrides the canonical builder (tests inject perturbed
    engines through it); it must return ``(step, args)`` like
    :func:`~mpi4dl_tpu.analysis.contracts.engines.build_engine`.
    """
    import jax

    from mpi4dl_tpu.analysis.contracts.engines import build_engine
    from mpi4dl_tpu.obs.hlo_stats import (
        scope_coverage,
        stablehlo_collectives,
        stablehlo_sharding_annotations,
    )

    # Build+lower TWICE; the counter watches only the second (warm) pass.
    # Cold trace counts depend on process history (jax's trace caches are
    # shared — whichever engine runs first pays for common machinery), but
    # the warm count is the engine's intrinsic per-build retrace cost and is
    # history-independent (verified across extraction orders), so it can be
    # a golden.  A broken cache key that starts re-tracing per build shows
    # up here as a jump.
    builder = build or build_engine
    step, args = builder(family)
    step.lower(*args)
    with _LoweringCounter() as counter:
        step, args = builder(family)
        lowered = step.lower(*args)

    # Per-scope collective accounting from the lowered StableHLO.  (No
    # separate totals field: it would duplicate what the per-scope tree
    # already pins, as un-diffed golden state.)
    collectives: Dict[str, Dict[str, Dict[str, int]]] = {}
    for op in stablehlo_collectives(lowered):
        scope = op["scope"] or "<unscoped>"
        entry = collectives.setdefault(scope, {}).setdefault(
            op["kind"], {"count": 0, "bytes": 0}
        )
        entry["count"] += 1
        entry["bytes"] += op["bytes"]

    # Per-mesh-axis accounting from the jaxpr (trace-cache hit: the step was
    # just traced by .lower(), so this re-derivation is nearly free).
    jaxpr = jax.make_jaxpr(step)(*args)

    compiled_text = compiled_text_of(lowered)

    return {
        "schema": CONTRACT_SCHEMA,
        "engine": family,
        "jax": jax.__version__,
        "collectives": _sorted_nested(collectives),
        "axis_collectives": _sorted_nested(jaxpr_collective_stats(jaxpr)),
        "scopes": scope_coverage(lowered),
        "lowerings": {
            "traces": counter.counts["jaxpr_trace_duration"],
            "modules": counter.counts["jaxpr_to_mlir_module_duration"],
        },
        "shardings": {
            "annotations": dict(sorted(
                stablehlo_sharding_annotations(lowered).items()
            )),
            # in_avals is a pytree ((args...), kwargs{}) — flatten to the
            # actual leaf avals or the shape channel records nothing
            "inputs": _entry_shapes(
                jax.tree_util.tree_leaves(lowered.in_avals)
            ),
        },
        "overlap": _overlap_section(compiled_text),
        "ircheck": _ircheck_section(jaxpr, compiled_text, family),
    }


def _ircheck_section(jaxpr, compiled_text: str, family: str) -> dict:
    """Per-kind IR-verifier finding counts (analysis/ircheck) over the
    jaxpr and the compiled scheduled HLO.  ``{}`` = the engine proves
    clean; any nonzero count names the regression class directly."""
    from mpi4dl_tpu.analysis.ircheck import (
        check_hlo,
        check_jaxpr,
        finding_counts,
    )

    findings = check_jaxpr(jaxpr, family=family)
    findings += check_hlo(compiled_text, family=family)
    return finding_counts(findings)


def compiled_text_of(lowered) -> str:
    """Compile a lowered computation and return the scheduled HLO text.
    The compile bypasses the persistent compilation cache — it keys on the
    program minus debug metadata, so a scope-less executable compiled
    elsewhere could alias this build and hand back HLO without op_name
    paths (the obs/hbm.py attribution caveat applies here verbatim).
    Shared by the ``overlap``/``ircheck`` contract sections and
    ``analysis.ircheck.check_family``."""
    import jax

    cache_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        compiled = lowered.compile()
    finally:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    return compiled.as_text()


def _overlap_section(compiled_text: str) -> dict:
    """The compiled scheduled HLO's structural overlap projection
    (obs/overlap.py): which collectives ride async start/done pairs vs
    sync ops, per scope, with payload and structurally-exposed bytes —
    a collective compiled *without* a start/done split can never hide
    under compute, so a sync count that grows is an overlap regression no
    benchmark has to measure first."""
    from mpi4dl_tpu.obs.overlap import structural_overlap

    return structural_overlap(compiled_text)


def _sorted_nested(d: dict) -> dict:
    """Recursively key-sort so golden JSON files diff cleanly."""
    return {
        k: _sorted_nested(v) if isinstance(v, dict) else v
        for k, v in sorted(d.items())
    }


def ensure_virtual_mesh(families=None) -> Optional[str]:
    """Provision the 8-device CPU platform the engine builds need (the
    conftest/benchmark-runner recipe, applied just in time for the CLI).
    ``families`` limits the requirement to the engines actually being
    extracted.  Returns an error string when the backend is already
    initialized with too few devices, else None."""
    import jax

    from mpi4dl_tpu.analysis.contracts.engines import (
        ENGINE_FAMILIES,
        required_devices,
    )
    from mpi4dl_tpu.compat import ensure_host_device_count

    need = max(required_devices(f) for f in (families or ENGINE_FAMILIES))
    ensure_host_device_count(max(need, 8))
    have = len(jax.devices())
    if have < need:
        return (
            f"contract extraction needs {need} devices, have {have}; run "
            "under JAX_PLATFORMS=cpu in a fresh process so the virtual CPU "
            "mesh can be provisioned"
        )
    return None
