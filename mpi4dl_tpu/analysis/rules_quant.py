"""Rule ``unquantized-collective`` (rule 11): collectives on the hot wire
list must offer the quantized path.

The quantized-collective layer (mpi4dl_tpu/quant, docs/quantization.md)
halves the 8K step's wire by encoding the payload of the junction /
respatial / grad-reduce / handoff collectives.  The win only holds while
every hot call site stays routed through the quant layer: a refactor that
re-introduces a bare ``lax.all_gather`` under ``scope("junction_gather")``
silently restores full-precision wire with no test failing — the contract
ratio gate would catch it one CI tier later, with a byte diff instead of a
source line.  This rule fails the build at the source level.

Scope: files under ``mpi4dl_tpu/parallel/`` (the engines — ops/ halo
kernels are latency-bound, 1.4% of bytes, deliberately not hot).  A
``jax.lax`` collective call lexically inside a ``with scope(...)`` block
whose literal name matches a hot pattern
(:data:`mpi4dl_tpu.quant.policy.HOT_SCOPE_PATTERNS`: junction*,
stage_lineup, respatial*, grad_reduce, stats_reduce, stage_handoff,
cot_handoff) must share that WITH-BLOCK with a reference to the quant
layer (a ``quant``-named guard or a ``quantized_*`` call) — i.e. the
exact collective must be the policy-off branch of a quant-aware site,
checked per block so a bare collective added to an already-quant-aware
engine function still trips the rule.
Exact-by-design sites (e.g. the loss_reduce scalar psums — not hot — or a
justified exact transpose) carry ``# analysis: ok(unquantized-collective)``
with a comment saying why.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from mpi4dl_tpu.analysis.core import Project, Rule, Violation
from mpi4dl_tpu.analysis.rules_scope import _COLLECTIVES, _SCOPE_CALLEES
from mpi4dl_tpu.quant.policy import scope_quant_class


def _is_target(rel: str) -> bool:
    return "mpi4dl_tpu/parallel/" in f"/{rel}"


def _literal_prefix(node: ast.expr) -> Optional[str]:
    """The literal text of a scope-name argument: a str constant, or the
    constant parts of an f-string (enough to match the hot patterns —
    every hot scope's class-determining token is literal)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = [v.value for v in node.values
                 if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return "".join(parts) if parts else None
    return None


class UnquantizedCollectiveRule(Rule):
    name = "unquantized-collective"
    description = (
        "bare jax.lax collective under a hot-wire obs.scope (junction/"
        "respatial/grad_reduce/stats_reduce/handoff) in a function with no "
        "quant-layer path — the quantized-collective win silently degrades; "
        "route through mpi4dl_tpu.quant or pragma a justified exact site."
    )

    def check(self, project: Project) -> List[Violation]:
        out: List[Violation] = []
        for src in project.files:
            if not _is_target(src.rel):
                continue
            # Spans of `with scope("<hot name>")` blocks.
            hot_spans: List[Tuple[int, int, str]] = []
            for w in src.nodes(ast.With):
                for item in w.items:
                    ctx = item.context_expr
                    if not isinstance(ctx, ast.Call) or not ctx.args:
                        continue
                    resolved = src.resolve(ctx.func) or ""
                    if not (resolved in _SCOPE_CALLEES
                            or resolved.endswith(".named_scope")):
                        continue
                    name = _literal_prefix(ctx.args[0])
                    cls = scope_quant_class(name or "")
                    if cls:
                        hot_spans.append(
                            (w.lineno, getattr(w, "end_lineno", w.lineno),
                             name)
                        )
            if not hot_spans:
                continue
            # Lines that reference the quant layer (a `quant` name/guard,
            # a quantized_* helper, a mpi4dl_tpu.quant.* attribute).  The
            # awareness check is PER HOT WITH-BLOCK, not per function: the
            # big engine functions all reference quant somewhere, so a
            # function-granular check would wave through a new bare
            # hot-wire collective added to them — exactly the regression
            # this rule exists to catch.
            quant_lines: List[int] = []
            for n in src.nodes(ast.Name):
                if "quant" in n.id.lower():
                    quant_lines.append(n.lineno)
            for n in src.nodes(ast.Attribute):
                if "quant" in (n.attr or "").lower():
                    quant_lines.append(n.lineno)
            for node in src.nodes(ast.Call):
                resolved = src.resolve(node.func) or ""
                parts = resolved.split(".")
                if parts[-1] not in _COLLECTIVES:
                    continue
                if not (resolved.startswith("jax.lax.")
                        or resolved.startswith("lax.")):
                    continue
                span = next(
                    ((a, b, name) for a, b, name in hot_spans
                     if a <= node.lineno <= b), None
                )
                if span is None:
                    continue
                a, b, hot = span
                if any(a <= ln <= b for ln in quant_lines):
                    continue
                out.append(
                    Violation(
                        self.name,
                        src.rel,
                        node.lineno,
                        f"{parts[-1]} under hot scope {hot!r} with no "
                        "quant-layer path in the scope block — the "
                        "quantized-collective wire win silently degrades "
                        "(docs/quantization.md); route through "
                        "mpi4dl_tpu.quant or pragma a justified exact "
                        "site with `# analysis: ok(unquantized-collective)`",
                    )
                )
        return out


RULE = UnquantizedCollectiveRule()
