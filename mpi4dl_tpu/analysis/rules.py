"""The rule table.  Each rule lives in its own module and exports ``RULE``;
adding a rule = adding a module here (docs/analysis.md walks through it)."""

from __future__ import annotations

from typing import Dict, List

from mpi4dl_tpu.analysis.core import Rule
from mpi4dl_tpu.analysis.rules_collective import RULE as _collective
from mpi4dl_tpu.analysis.rules_dtype import RULE as _dtype
from mpi4dl_tpu.analysis.rules_env import RULE as _env
from mpi4dl_tpu.analysis.rules_pallas import RULE as _pallas
from mpi4dl_tpu.analysis.rules_print import RULE as _print
from mpi4dl_tpu.analysis.rules_quant import RULE as _quant
from mpi4dl_tpu.analysis.rules_retrace import RULE as _retrace
from mpi4dl_tpu.analysis.rules_scope import RULE as _scope
from mpi4dl_tpu.analysis.rules_swallow import RULE as _swallow
from mpi4dl_tpu.analysis.rules_thread import RULE as _thread
from mpi4dl_tpu.analysis.rules_tracer import RULE as _tracer

RULE_TABLE: List[Rule] = [
    _collective,
    _tracer,
    _dtype,
    _env,
    _retrace,
    _print,
    _swallow,
    _thread,
    _scope,
    _quant,
    _pallas,
]

RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULE_TABLE}
