"""Static VMEM/DMA/grid certification for Pallas kernels (ISSUE 19).

``ircheck`` (ISSUE 16) verifies the engine wire but treats ``pallas_call``
as an opaque custom call; this package opens the box.  It traces every
kernel registered in :mod:`mpi4dl_tpu.ops.kernel_registry` on a CPU host
(``jax.make_jaxpr`` only — no TPU compile), enumerates the full grid, and
abstract-interprets the kernel jaxpr per grid point in the TPU's sequential
row-major order (last grid dimension innermost, scratch persisting across
steps).  It is the safety rail ROADMAP item 2's halo-RDMA conv is built
against: the invariants that were comments — the ``ops/pallas_conv.py``
WAR-hazard note, the hand-maintained VMEM caps — are now checked, and an
inter-chip ``make_async_remote_copy`` kernel will be enrolled into the same
gate by one registry row.

Finding taxonomy (every kind has an injected-violation fixture in
tests/test_pallascheck.py; keys are ``kernel:grid_point_class:kind`` with a
grid-point class like ``lo-mid-hi`` — one coordinate class per grid dim —
so baselines survive shape tweaks that keep the failure class):

grid/BlockSpec soundness (grid.py):

- ``oob-block`` — an index-map output places a block (partially) outside
  its operand array for some grid point;
- ``overlapping-output`` — an output block is revisited NON-consecutively:
  the pipeline emits it at the end of each visit run, so a later run
  silently clobbers data already written (consecutive revisits are the
  legal accumulation pattern and feed the ``uninit-accumulator`` check);
- ``untiled-output`` — grid-wide, the output blocks do not cover the
  output array (rows that no program ever writes reach HBM as garbage);
- ``misaligned-block`` — a block shape that violates the 128-lane /
  dtype-sublane tiling on its minor two dims (Mosaic would reject or pad);

VMEM budget certification (vmem.py):

- ``vmem-overbudget`` — scratch + double-buffered blocked operands exceed
  ``--require-vmem-frac`` x the 16 MiB per-core pool;

DMA/semaphore discipline (interp.py):

- ``unmatched-dma`` — a start with no wait on the same semaphore along
  some ``pl.when``/branch path (or still in flight at kernel end), a wait
  with no start, or a second start racing an in-flight copy;
- ``dma-race`` — a read of a DMA destination before its wait, or a write
  to a DMA source/destination while the copy is in flight (the
  ``pallas_conv.py`` WAR hazard, now an invariant);
- ``nonbijective-device-map`` — a remote copy whose resolved ``device_id``
  map repeats a target (or leaves the declared ring) across the grid, or
  any remote copy in a kernel whose registry case declares no topology;

accumulator-init coverage (interp.py):

- ``uninit-accumulator`` — a scratch/output ref read before any write, or
  scratch read at the start of a revisited-output run while still holding
  the previous block's values (an ``@pl.when(k == 0)`` guard that does not
  cover every revisit).

Entry points: :func:`check_spec`, :func:`check_case`,
:func:`check_registry`, :func:`pallas_contract` (the contract gate's
``pallas`` golden section), and the CLI
``python -m mpi4dl_tpu.analysis pallascheck``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

FINDING_KINDS = (
    "oob-block",
    "overlapping-output",
    "untiled-output",
    "misaligned-block",
    "vmem-overbudget",
    "unmatched-dma",
    "dma-race",
    "nonbijective-device-map",
    "uninit-accumulator",
)

#: per-core VMEM pool certified against (matches ops/pallas_conv._VMEM_BYTES)
VMEM_BYTES = 16 * 1024 * 1024


@dataclasses.dataclass(frozen=True)
class Finding:
    """One kernel-verification failure, keyed ``kernel:grid_class:kind``."""

    kind: str         # one of FINDING_KINDS
    kernel: str       # registry case name (fixture name for unit runs)
    grid_class: str   # per-dim lo/mid/hi class, "" for whole-kernel findings
    message: str

    @property
    def key(self) -> str:
        return f"{self.kernel}:{self.grid_class or '*'}:{self.kind}"

    @property
    def baseline_key(self) -> Tuple[str, str, str, str]:
        return (self.kind, self.kernel, self.grid_class, self.message)

    def render(self) -> str:
        return f"{self.key}: {self.message}"


def check_spec(spec, case=None,
               require_vmem_frac: float = 1.0) -> List[Finding]:
    """All findings for one traced :class:`~.trace.KernelSpec`."""
    from mpi4dl_tpu.analysis.pallascheck.grid import grid_findings
    from mpi4dl_tpu.analysis.pallascheck.interp import interp_findings
    from mpi4dl_tpu.analysis.pallascheck.vmem import vmem_findings

    out = grid_findings(spec)
    out += vmem_findings(spec, require_vmem_frac=require_vmem_frac)
    out += interp_findings(spec, case=case)
    return _sorted(out)


def check_case(case, require_vmem_frac: float = 1.0) -> List[Finding]:
    """Trace one registry case and check every ``pallas_call`` in it."""
    from mpi4dl_tpu.analysis.pallascheck.trace import trace_case

    out: List[Finding] = []
    for spec in trace_case(case):
        out += check_spec(spec, case=case,
                          require_vmem_frac=require_vmem_frac)
    return _sorted(out)


def check_registry(kernels: Optional[Sequence[str]] = None,
                   require_vmem_frac: float = 1.0) -> List[Finding]:
    """Check every registered kernel case (optionally a name subset)."""
    from mpi4dl_tpu.ops.kernel_registry import REGISTRY, case_names

    wanted = set(case_names(kernels))
    out: List[Finding] = []
    for case in REGISTRY:
        if case.name in wanted:
            out += check_case(case, require_vmem_frac=require_vmem_frac)
    return _sorted(out)


def finding_counts(findings) -> Dict[str, int]:
    """``{kind: count}`` — the ``pallas`` contract section's golden
    material (zero-count kinds omitted so a clean kernel pins ``{}``)."""
    out: Dict[str, int] = {}
    for f in findings:
        out[f.kind] = out.get(f.kind, 0) + 1
    return dict(sorted(out.items()))


PALLAS_CONTRACT_SCHEMA = 1


def pallas_contract(require_vmem_frac: float = 1.0) -> dict:
    """The contract gate's ``pallas`` section: per registered case, the
    reviewable kernel shape — grid, per-operand block shapes, the
    re-derived per-grid-point VMEM total, static DMA-start count, and the
    finding counts (all zero on a clean tree).  Golden:
    ``contracts/pallas.json``."""
    import jax

    from mpi4dl_tpu.analysis.pallascheck.trace import trace_case
    from mpi4dl_tpu.analysis.pallascheck.vmem import vmem_breakdown
    from mpi4dl_tpu.ops.kernel_registry import REGISTRY

    kernels: Dict[str, dict] = {}
    for case in REGISTRY:
        for spec in trace_case(case):
            findings = check_spec(spec, case=case,
                                  require_vmem_frac=require_vmem_frac)
            dma_starts = _count_prim(spec.jaxpr, "dma_start")
            kernels[spec.case] = {
                "grid": list(spec.grid),
                "blocks": {
                    op.name: list(op.shape)
                    for op in spec.operands if op.role != "index"
                },
                "vmem_bytes": vmem_breakdown(spec)["total"],
                "dma_starts": dma_starts,
                "findings": finding_counts(findings),
            }
    return {
        "schema": PALLAS_CONTRACT_SCHEMA,
        "jax": jax.__version__,
        "vmem_frac": require_vmem_frac,
        "kernels": kernels,
    }


def _count_prim(jaxpr, name: str) -> int:
    from mpi4dl_tpu.analysis.pallascheck.trace import _sub_jaxprs

    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == name:
            n += 1
        for sub in _sub_jaxprs(eqn.params):
            n += _count_prim(sub, name)
    return n


def _sorted(findings: List[Finding]) -> List[Finding]:
    return sorted(
        findings, key=lambda f: (f.kernel, f.kind, f.grid_class, f.message)
    )


def point_class(grid: Sequence[int], point: Sequence[int]) -> str:
    """Per-dim lo/mid/hi class of one grid point (size-1 dims are ``lo``):
    the ``grid_point_class`` segment of finding keys, chosen so a finding
    keyed at an edge/interior class survives shape tweaks."""
    parts = []
    for size, idx in zip(grid, point):
        if idx == 0:
            parts.append("lo")
        elif idx == int(size) - 1:
            parts.append("hi")
        else:
            parts.append("mid")
    return "-".join(parts)
