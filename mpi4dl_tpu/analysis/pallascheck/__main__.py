"""CLI: ``python -m mpi4dl_tpu.analysis pallascheck [--json] [--kernels ...]
[--baseline F] [--sarif F] [--require-vmem-frac X]``
(also reachable as ``python -m mpi4dl_tpu.analysis.pallascheck``).

Traces every kernel case registered in ``mpi4dl_tpu.ops.kernel_registry``
on the CPU host (no TPU compile), enumerates each kernel's full grid, and
runs every check (see the package docstring for the finding taxonomy).
Exit status mirrors the analyzer: 0 = no findings after baseline
filtering, 1 = findings, 2 = usage/environment errors.  The CI job runs
the full registry with ``--json --out`` + ``--sarif`` and uploads both as
artifacts on failure.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List


def main(argv=None) -> int:
    from mpi4dl_tpu.analysis.pallascheck import FINDING_KINDS, check_case
    from mpi4dl_tpu.ops.kernel_registry import REGISTRY, case_names

    ap = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analysis pallascheck",
        description="Static Pallas kernel verifier (docs/analysis.md): "
        "traces every registered kernel, enumerates the full grid, and "
        "abstract-interprets the kernel jaxpr per grid point, proving "
        "grid/BlockSpec soundness, the per-grid-point VMEM budget, "
        "DMA/semaphore discipline and accumulator-init coverage.  "
        "Finding kinds: " + ", ".join(FINDING_KINDS),
    )
    ap.add_argument("--kernels", metavar="NAMES", default=None,
                    help="comma-separated subset of registry cases; a bare "
                         "kernel name (e.g. halo_conv2d) selects every "
                         "variant of it "
                         f"(default: {','.join(c.name for c in REGISTRY)})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--out", metavar="F", default=None,
                    help="also write the JSON findings to this file")
    ap.add_argument("--baseline", metavar="F", default=None,
                    help="JSON list of accepted findings (keyed on "
                         "kind/kernel/grid_class/message) to filter out")
    ap.add_argument("--sarif", metavar="F", default=None,
                    help="write findings as a SARIF 2.1.0 log (GitHub "
                         "code-scanning annotations)")
    ap.add_argument("--require-vmem-frac", metavar="X", type=float,
                    default=1.0,
                    help="fail any kernel whose per-grid-point VMEM total "
                         "(double-buffered blocked operands + scratch) "
                         "exceeds X of the 16 MiB pool (default 1.0; CI "
                         "gates at 0.75 to keep compiler headroom)")
    args = ap.parse_args(argv)

    if not 0.0 < args.require_vmem_frac <= 1.0:
        print(f"pallascheck: --require-vmem-frac {args.require_vmem_frac} "
              "must be in (0, 1]", file=sys.stderr)
        return 2

    wanted = None
    if args.kernels:
        wanted = [k.strip() for k in args.kernels.split(",") if k.strip()]
        known = {c.name for c in REGISTRY}
        known |= {c.name.split(":", 1)[0] for c in REGISTRY}
        unknown = [k for k in wanted if k not in known]
        if unknown:
            print(f"pallascheck: unknown kernel(s) {unknown}; "
                  f"have {[c.name for c in REGISTRY]}", file=sys.stderr)
            return 2
    names = set(case_names(wanted))
    cases = [c for c in REGISTRY if c.name in names]

    findings = []
    for case in cases:
        try:
            findings.extend(check_case(
                case, require_vmem_frac=args.require_vmem_frac))
        except Exception as e:  # noqa: BLE001 — a case that cannot trace
            print(f"pallascheck: {case.name}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2

    if args.baseline:
        with open(args.baseline, "r", encoding="utf-8") as fh:
            baseline = json.load(fh)
        if not isinstance(baseline, list):
            print(f"pallascheck: baseline {args.baseline}: expected a "
                  "JSON list", file=sys.stderr)
            return 2
        keys = {
            (e.get("kind", ""), e.get("kernel", ""),
             e.get("grid_class", ""), e.get("message", ""))
            for e in baseline
        }
        findings = [f for f in findings if f.baseline_key not in keys]

    rows: List[dict] = [
        {"kind": f.kind, "kernel": f.kernel, "grid_class": f.grid_class,
         "message": f.message}
        for f in findings
    ]
    payload = json.dumps({"findings": rows}, indent=2, sort_keys=True)
    if args.json:
        print(payload)
    else:
        for f in findings:
            print(f.render())
        print(
            f"pallascheck: {len(findings)} finding(s) across "
            f"{len(cases)} kernel case(s) "
            f"[vmem frac {args.require_vmem_frac:g}]",
            file=sys.stderr,
        )
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(payload + "\n")
    if args.sarif:
        from mpi4dl_tpu.analysis.sarif import sarif_log, write_sarif

        write_sarif(args.sarif, sarif_log(pallas_findings=findings))
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
