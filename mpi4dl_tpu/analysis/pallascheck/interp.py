"""DMA/semaphore discipline and accumulator-init coverage: checks (c)+(d).

One sequential abstract interpretation of the kernel jaxpr over the full
grid, in the TPU's execution order (row-major, last dim innermost), with
scratch state persisting across grid points — exactly the machine model the
kernels are written against.  Scalar dataflow from ``program_id`` is
constant-folded so ``pl.when`` predicates like ``c == 0`` / ``ki == nk-1``
resolve concretely per grid point: the real kernels' guards take their
actual branches, and a *wrong* guard (the injected fixtures) walks the
wrong branch and trips a finding.  Unresolvable predicates walk BOTH
branches and merge conservatively: definite-written sets intersect,
maybe-written sets union, and a DMA started on one path but not the other
is an ``unmatched-dma`` finding by construction.

Tracked state:

- per-ref written/maybe-written (global across the grid — scratch
  persists) and per-output-visit-run written sets (grid.output_runs);
- in-flight DMAs keyed by semaphore ref, carrying src/dst refs: a read of
  a dst before its wait or a write to a src/dst while in flight is a
  ``dma-race`` (the ops/pallas_conv.py:48 WAR hazard as an invariant);
- resolved ``device_id`` values of remote copies, checked bijective
  against the registry case's declared ring topology.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax.tree_util as jtu

from mpi4dl_tpu.analysis.pallascheck import Finding, point_class
from mpi4dl_tpu.analysis.pallascheck.grid import grid_points, output_runs
from mpi4dl_tpu.analysis.pallascheck.trace import KernelSpec

UNKNOWN = object()


@dataclasses.dataclass(frozen=True)
class _Ref:
    pos: int


@dataclasses.dataclass
class _Dma:
    src: Optional[int]
    dst: Optional[int]
    remote: bool
    start_class: str


@dataclasses.dataclass
class _State:
    written: set
    maybe: set
    run_written: set
    run_maybe: set
    inflight: Dict[int, _Dma]

    @classmethod
    def fresh(cls) -> "_State":
        return cls(set(), set(), set(), set(), {})

    def copy(self) -> "_State":
        return _State(set(self.written), set(self.maybe),
                      set(self.run_written), set(self.run_maybe),
                      dict(self.inflight))


class _Ctx:
    """Per-kernel walk context shared across grid points."""

    def __init__(self, spec: KernelSpec, case) -> None:
        self.spec = spec
        self.case = case
        self.findings: List[Finding] = []
        self._seen: set = set()
        self.point: Tuple[int, ...] = ()
        self.cls: str = ""
        self.run_revisit = False
        self.remote_ids: List[Tuple[Tuple[int, ...], Any]] = []

    def emit(self, kind: str, message: str, cls: Optional[str] = None) -> None:
        cls = self.cls if cls is None else cls
        if (kind, cls) in self._seen:
            return
        self._seen.add((kind, cls))
        self.findings.append(Finding(
            kind=kind, kernel=self.spec.case, grid_class=cls,
            message=message,
        ))

    def name(self, pos: Optional[int]) -> str:
        return self.spec.by_pos(pos).name if pos is not None else "?"


# -- scalar constant folding -------------------------------------------------

_FOLD = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "rem": lambda a, b: a % b if b else UNKNOWN,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "and": lambda a, b: bool(a) and bool(b),
    "or": lambda a, b: bool(a) or bool(b),
    "xor": lambda a, b: bool(a) != bool(b),
    "not": lambda a: not a,
    "min": min,
    "max": max,
    "neg": lambda a: -a,
}


def _literal(v) -> Any:
    val = v.val
    try:
        if getattr(val, "shape", None) == ():
            return val.item()
    except (AttributeError, TypeError, ValueError):
        return UNKNOWN
    return val if isinstance(val, (int, float, bool)) else UNKNOWN


def _read(env: Dict, v) -> Any:
    if hasattr(v, "val"):  # Literal
        return _literal(v)
    return env.get(v, UNKNOWN)


def _scalar(x) -> bool:
    return isinstance(x, (bool, int, float)) and not isinstance(x, _Ref)


# -- DMA tree decoding -------------------------------------------------------

def _dma_parts(eqn, env):
    """(src_pos, dst_pos, sem_pos, src_sem_pos, device_id_value) of a
    dma_start/dma_wait equation via its flattening tree.  Layout on jax
    0.4.37: (src, src_transforms, dst, dst_transforms, dma_sem,
    sem_transforms, src_sem, src_sem_transforms, device_id).  ``src_sem``
    is non-None only for remote copies: the start signals it locally when
    the outbound data has left, so it carries the source-reuse (WAR)
    obligation while ``dma_sem`` (the recv semaphore) carries the
    destination-landing obligation."""
    tree = jtu.tree_unflatten(eqn.params["tree"], list(eqn.invars))
    if not isinstance(tree, (tuple, list)) or len(tree) < 5:
        return None, None, None, None, None

    def ref_pos(node):
        val = _read(env, node) if node is not None else None
        return val.pos if isinstance(val, _Ref) else None

    src, dst, sem = ref_pos(tree[0]), ref_pos(tree[2]), ref_pos(tree[4])
    src_sem = ref_pos(tree[6]) if len(tree) > 6 else None
    device_id = tree[8] if len(tree) > 8 else None
    if device_id is None:
        dev = None
    elif isinstance(device_id, (tuple, list)):
        dev = tuple(_read(env, d) if hasattr(d, "aval") or hasattr(d, "val")
                    else d for d in device_id)
    else:
        dev = _read(env, device_id)
    return src, dst, sem, src_sem, dev


# -- ref access checks -------------------------------------------------------

def _check_read(ctx: _Ctx, state: _State, pos: int) -> None:
    op = ctx.spec.by_pos(pos)
    for sem, dma in state.inflight.items():
        if dma.dst == pos:
            ctx.emit(
                "dma-race",
                f"{op.name} is read while the DMA into it (semaphore "
                f"{ctx.name(sem)}, started at class {dma.start_class}) is "
                "still in flight — Mosaic does not fence DMA writes "
                "against vector/MXU reads; wait first",
            )
    if op.role not in ("scratch", "out"):
        return
    if pos not in state.written and pos not in state.maybe:
        ctx.emit(
            "uninit-accumulator",
            f"{op.name} ({op.role}) is read at grid point {ctx.point} "
            "before anything ever wrote it",
        )
    elif (ctx.run_revisit and op.role == "scratch"
          and pos not in state.run_written and pos not in state.run_maybe
          and pos in state.written):
        ctx.emit(
            "uninit-accumulator",
            f"{op.name} (scratch) is read at the first grid point "
            f"{ctx.point} of a revisited-output run while still holding "
            "the previous output block's values — the init guard "
            "(pl.when(k == 0)-style) does not cover this revisit",
        )


def _check_write(ctx: _Ctx, state: _State, pos: int) -> None:
    for sem, dma in state.inflight.items():
        if dma.src == pos:
            ctx.emit(
                "dma-race",
                f"{ctx.name(pos)} is written while it is the SOURCE of an "
                f"in-flight DMA (semaphore {ctx.name(sem)}) — the "
                "write-after-read hazard ops/pallas_conv.py documents; "
                "wait before reusing the buffer",
            )
        if dma.dst == pos:
            ctx.emit(
                "dma-race",
                f"{ctx.name(pos)} is written while the DMA into it "
                f"(semaphore {ctx.name(sem)}) is still in flight — the "
                "store and the landing copy race",
            )
    state.written.add(pos)
    state.maybe.add(pos)
    state.run_written.add(pos)
    state.run_maybe.add(pos)


# -- the walk ----------------------------------------------------------------

def _merge(ctx: _Ctx, base: _State, branches: List[_State]) -> _State:
    """Conservative join after walking unknown-predicate branches."""
    written = set.intersection(*(b.written for b in branches))
    maybe = set.union(*(b.maybe for b in branches))
    run_written = set.intersection(*(b.run_written for b in branches))
    run_maybe = set.union(*(b.run_maybe for b in branches))
    keys = [set(b.inflight) for b in branches]
    if any(k != keys[0] for k in keys[1:]):
        diff = set.union(*keys) - set.intersection(*keys)
        ctx.emit(
            "unmatched-dma",
            "DMA in-flight set differs across a data-dependent branch "
            f"(semaphores {sorted(ctx.name(p) for p in diff)}): some path "
            "starts or waits a copy the other does not",
        )
    inflight: Dict[int, _Dma] = {}
    for b in branches:
        inflight.update(b.inflight)
    return _State(written, maybe, run_written, run_maybe, inflight)


def _walk(ctx: _Ctx, jaxpr, env: Dict, state: _State) -> _State:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "program_id":
            env[eqn.outvars[0]] = ctx.point[eqn.params["axis"]]
        elif prim == "num_programs":
            env[eqn.outvars[0]] = ctx.spec.grid[eqn.params["axis"]]
        elif prim in _FOLD:
            vals = [_read(env, v) for v in eqn.invars]
            if all(_scalar(v) for v in vals):
                env[eqn.outvars[0]] = _FOLD[prim](*vals)
        elif prim == "convert_element_type":
            val = _read(env, eqn.invars[0])
            if _scalar(val):
                env[eqn.outvars[0]] = int(val) if isinstance(val, bool) else val
        elif prim == "cond":
            state = _walk_cond(ctx, eqn, env, state)
        elif prim in ("pjit", "closed_call", "core_call", "custom_jvp_call",
                      "custom_vjp_call", "custom_vjp_call_jaxpr",
                      "remat_call", "checkpoint"):
            inner = (eqn.params.get("jaxpr")
                     or eqn.params.get("call_jaxpr")
                     or eqn.params.get("fun_jaxpr"))
            if inner is not None:
                consts = getattr(inner, "consts", ())
                ij = getattr(inner, "jaxpr", inner)
                for cv, c in zip(ij.constvars, consts):
                    env[cv] = c if _scalar(c) else UNKNOWN
                for bv, ov in zip(ij.invars, eqn.invars):
                    env[bv] = _read(env, ov)
                state = _walk(ctx, ij, env, state)
                for outer, innerv in zip(eqn.outvars, ij.outvars):
                    env[outer] = _read(env, innerv)
        elif prim in ("while", "scan"):
            state = _walk_loop(ctx, eqn, env, state)
        elif prim == "get":
            val = _read(env, eqn.invars[0])
            if isinstance(val, _Ref):
                _check_read(ctx, state, val.pos)
        elif prim == "swap":
            val = _read(env, eqn.invars[0])
            if isinstance(val, _Ref):
                _check_write(ctx, state, val.pos)
        elif prim == "addupdate":
            val = _read(env, eqn.invars[0])
            if isinstance(val, _Ref):
                _check_read(ctx, state, val.pos)
                _check_write(ctx, state, val.pos)
        elif prim == "dma_start":
            src, dst, sem, src_sem, dev = _dma_parts(eqn, env)
            remote = dev is not None or src_sem is not None
            for s, d in ((sem, _Dma(src=None if src_sem is not None else src,
                                    dst=dst, remote=remote,
                                    start_class=ctx.cls)),
                         (src_sem, _Dma(src=src, dst=None, remote=remote,
                                        start_class=ctx.cls))):
                if s is None:
                    continue
                if s in state.inflight:
                    ctx.emit(
                        "unmatched-dma",
                        f"second DMA start on semaphore {ctx.name(s)} "
                        f"while the copy started at class "
                        f"{state.inflight[s].start_class} has not been "
                        "waited — starts and waits must pair 1:1 per "
                        "semaphore",
                    )
                if d.dst is not None:
                    # the landing copy races any other in-flight copy's dst
                    for s2, dma in state.inflight.items():
                        if dma.dst == d.dst and s2 != s:
                            ctx.emit(
                                "dma-race",
                                f"two in-flight DMAs target "
                                f"{ctx.name(d.dst)} (semaphores "
                                f"{ctx.name(s2)}, {ctx.name(s)})",
                            )
                state.inflight[s] = d
            if dev is not None:
                ctx.remote_ids.append((ctx.point, dev))
        elif prim == "dma_wait":
            _, dst, sem, _, _ = _dma_parts(eqn, env)
            if sem is not None:
                dma = state.inflight.pop(sem, None)
                if dma is None:
                    ctx.emit(
                        "unmatched-dma",
                        f"DMA wait on semaphore {ctx.name(sem)} with no "
                        "copy in flight on it along this path",
                    )
                else:
                    landed = dma.dst if dma.dst is not None else dst
                    if landed is not None:
                        state.written.add(landed)
                        state.maybe.add(landed)
                        state.run_written.add(landed)
                        state.run_maybe.add(landed)
        # all other primitives: pure value flow, outvars stay UNKNOWN
    return state


def _walk_cond(ctx: _Ctx, eqn, env: Dict, state: _State) -> _State:
    branches = eqn.params["branches"]
    pred = _read(env, eqn.invars[0])
    operands = eqn.invars[1:]

    def enter(branch, st: _State) -> Tuple[_State, List]:
        ij = branch.jaxpr
        for cv, c in zip(ij.constvars, branch.consts):
            env[cv] = c if _scalar(c) else UNKNOWN
        for bv, ov in zip(ij.invars, operands):
            env[bv] = _read(env, ov)
        st = _walk(ctx, ij, env, st)
        return st, [_read(env, v) for v in ij.outvars]

    if _scalar(pred):
        idx = min(max(int(pred), 0), len(branches) - 1)
        state, outs = enter(branches[idx], state)
        for outer, val in zip(eqn.outvars, outs):
            env[outer] = val
        return state
    results, outs_per = [], []
    for branch in branches:
        st, outs = enter(branch, state.copy())
        results.append(st)
        outs_per.append(outs)
    for i, outer in enumerate(eqn.outvars):
        vals = [outs[i] for outs in outs_per]
        env[outer] = vals[0] if all(
            _scalar(v) and v == vals[0] for v in vals
        ) else UNKNOWN
    return _merge(ctx, state, results)


def _walk_loop(ctx: _Ctx, eqn, env: Dict, state: _State) -> _State:
    """One conservative body walk (the body may run 0..n times): writes
    inside become maybe-written only, and a body that changes the in-flight
    DMA set starts copies it cannot pair on every iteration count."""
    inner = (eqn.params.get("jaxpr") or eqn.params.get("body_jaxpr"))
    if inner is None:
        return state
    ij = getattr(inner, "jaxpr", inner)
    for cv, c in zip(ij.constvars, getattr(inner, "consts", ())):
        env[cv] = c if _scalar(c) else UNKNOWN
    for bv, ov in zip(ij.invars, eqn.invars[-len(ij.invars):]):
        env[bv] = _read(env, ov)
    after = _walk(ctx, ij, env, state.copy())
    if set(after.inflight) != set(state.inflight):
        ctx.emit(
            "unmatched-dma",
            "a loop body changes the set of in-flight DMAs "
            f"({sorted(ctx.name(p) for p in set(after.inflight) ^ set(state.inflight))})"
            " — starts and waits cannot pair for every trip count",
        )
    return _merge(ctx, state, [state.copy(), after])


def _device_map_findings(ctx: _Ctx) -> None:
    case = ctx.case
    ring = getattr(case, "ring_size", None) if case is not None else None
    if not ctx.remote_ids:
        return
    if ring is None:
        ctx.emit(
            "nonbijective-device-map",
            "kernel performs remote (inter-chip) copies but its registry "
            "case declares no ring/halo topology (KernelCase.ring_size) to "
            "check the device_id map against",
            cls="",
        )
        return
    resolved = [(pt, d) for pt, d in ctx.remote_ids if _scalar(d)]
    by_group: Dict[Tuple[int, ...], List[Tuple[Tuple[int, ...], int]]] = {}
    for pt, dev in resolved:
        if not 0 <= int(dev) < ring:
            ctx.emit(
                "nonbijective-device-map",
                f"remote copy at grid point {pt} targets device {dev}, "
                f"outside the declared ring of {ring}",
                cls=point_class(ctx.spec.grid, pt),
            )
        by_group.setdefault(tuple(pt[1:]), []).append((pt, int(dev)))
    for group, entries in by_group.items():
        seen: Dict[int, Tuple[int, ...]] = {}
        for pt, dev in entries:
            if dev in seen:
                ctx.emit(
                    "nonbijective-device-map",
                    f"device_id map is not injective over the ring grid "
                    f"dim: grid points {seen[dev]} and {pt} both target "
                    f"device {dev} (ring size {ring})",
                    cls=point_class(ctx.spec.grid, pt),
                )
                break
            seen[dev] = pt


def interp_findings(spec: KernelSpec, case=None) -> List[Finding]:
    ctx = _Ctx(spec, case)
    runs = output_runs(spec)
    run_sizes: Dict[int, int] = {}
    for r in runs:
        run_sizes[r] = run_sizes.get(r, 0) + 1
    points = grid_points(spec.grid)
    state = _State.fresh()
    prev_run = None
    for t, point in enumerate(points):
        ctx.point = point
        ctx.cls = point_class(spec.grid, point)
        ctx.run_revisit = run_sizes[runs[t]] > 1
        if runs[t] != prev_run:
            state.run_written = set()
            state.run_maybe = set()
            prev_run = runs[t]
        env: Dict = {}
        for op in spec.operands:
            env[spec.jaxpr.invars[op.pos]] = _Ref(op.pos)
        state = _walk(ctx, spec.jaxpr, env, state)
    for sem, dma in state.inflight.items():
        ctx.emit(
            "unmatched-dma",
            f"DMA on semaphore {ctx.name(sem)} (into "
            f"{ctx.name(dma.dst)}, started at class {dma.start_class}) is "
            "still in flight when the kernel ends — no wait ever pairs it",
            cls=dma.start_class,
        )
    _device_map_findings(ctx)
    return ctx.findings
