"""VMEM budget certification: the (b) check.

Re-derives the per-grid-point VMEM total from the traced specs — VMEM
scratch allocations at full size plus every blocked VMEM operand DOUBLE
(the Pallas pipeline keeps two buffers per blocked operand so the next
block's DMA overlaps compute) — and certifies it against
``--require-vmem-frac`` x the 16 MiB per-core pool.  This is the
derived-not-declared counterpart of ``ops/pallas_conv._vmem_total_bytes``:
the kernel's own budget model is an a-priori formula, this one is read back
from what was actually traced, so the two cannot drift apart silently.
"""

from __future__ import annotations

from typing import Dict, List

from mpi4dl_tpu.analysis.pallascheck import VMEM_BYTES, Finding
from mpi4dl_tpu.analysis.pallascheck.trace import VMEM, KernelSpec


def vmem_breakdown(spec: KernelSpec) -> Dict[str, int]:
    """Per-operand VMEM bytes (pipeline-doubled for blocked operands) plus
    the ``total`` — the contract section pins the total so a scratch-shape
    or tiling change is a reviewable drift, not a silent one."""
    out: Dict[str, int] = {}
    total = 0
    for op in spec.operands:
        if op.memory_space != VMEM:
            continue
        n = op.block_bytes() * (2 if op.blocked else 1)
        out[op.name] = n
        total += n
    out["total"] = total
    return out


def vmem_findings(spec: KernelSpec,
                  require_vmem_frac: float = 1.0) -> List[Finding]:
    breakdown = vmem_breakdown(spec)
    total = breakdown.pop("total")
    budget = int(VMEM_BYTES * require_vmem_frac)
    if total <= budget:
        return []
    parts = ", ".join(
        f"{name} {bytes_ / 1024 / 1024:.2f}"
        for name, bytes_ in sorted(breakdown.items(),
                                   key=lambda kv: -kv[1])
    )
    return [Finding(
        kind="vmem-overbudget",
        kernel=spec.case,
        grid_class="",
        message=(
            f"per-grid-point VMEM {total / 1024 / 1024:.2f} MiB exceeds "
            f"{require_vmem_frac:g} x {VMEM_BYTES // (1024 * 1024)} MiB "
            f"(blocked operands double-buffered; MiB by operand: {parts})"
        ),
    )]
