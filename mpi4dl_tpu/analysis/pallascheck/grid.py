"""Grid/BlockSpec soundness: the (a) checks.

Enumerates the FULL grid (registry shapes keep grids tiny) in the TPU's
sequential row-major order and evaluates every blocked operand's index map
at every point.  With Pallas ``Blocked`` indexing the element offset of a
block is ``index * block_shape``, so distinct indices can never partially
overlap — the output hazards are therefore *revisit structure* hazards:
identical consecutive indices are the legal accumulation pattern, identical
NON-consecutive indices clobber already-emitted data (``overlapping-
output``), and indices that never cover part of the array leave garbage in
HBM (``untiled-output``).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

from mpi4dl_tpu.analysis.pallascheck import Finding, point_class
from mpi4dl_tpu.analysis.pallascheck.trace import (
    KernelSpec, Operand, eval_index_map,
)

_LANES = 128


def grid_points(grid) -> List[Tuple[int, ...]]:
    """Every grid point in execution order (row-major, last dim innermost —
    the TPU's sequential grid semantics, which the scratch-persistence and
    revisit checks depend on)."""
    return list(itertools.product(*(range(int(g)) for g in grid)))


def block_offsets(spec: KernelSpec) -> Dict[int, List[Optional[Tuple[int, ...]]]]:
    """Per blocked operand (by kernel-invar pos), the block-index tuple at
    every grid point in execution order (None where the map is not
    statically evaluable)."""
    points = grid_points(spec.grid)
    out: Dict[int, List[Optional[Tuple[int, ...]]]] = {}
    for op in spec.operands:
        if not op.blocked:
            continue
        out[op.pos] = [eval_index_map(op.index_map, p) for p in points]
    return out


def _sublane_multiple(dtype) -> int:
    # 8 rows at 4-byte types, 16 at 2-byte, 32 at 1-byte (packed tiling).
    return max(1, 32 // max(1, np.dtype(dtype).itemsize))


def _alignment_findings(spec: KernelSpec, op: Operand) -> List[Finding]:
    """Lane/sublane tiling of the minor two block dims.  A dim of 1 (a
    squeezed leading block dim) and a dim equal to the full array extent
    are both fine — Mosaic handles whole-axis and singleton blocks; what it
    cannot tile is a PARTIAL block off the (sublane, lane) grid."""
    out: List[Finding] = []
    if len(op.shape) < 1:
        return out
    arr = op.array_shape or op.shape
    checks = [(-1, _LANES, "lane")]
    if len(op.shape) >= 2:
        checks.append((-2, _sublane_multiple(op.dtype), "sublane"))
    for axis, mult, label in checks:
        dim = int(op.shape[axis])
        full = int(arr[axis]) if len(arr) >= -axis else dim
        if dim != 1 and dim != full and dim % mult:
            out.append(Finding(
                kind="misaligned-block",
                kernel=spec.case,
                grid_class="",
                message=(
                    f"{op.name}: block dim {dim} on the {label} axis is "
                    f"neither the full array extent ({full}) nor a "
                    f"multiple of the {mult}-row {label} tiling for "
                    f"{np.dtype(op.dtype).name}"
                ),
            ))
    return out


def grid_findings(spec: KernelSpec) -> List[Finding]:
    points = grid_points(spec.grid)
    offsets = block_offsets(spec)
    out: List[Finding] = []
    seen: set = set()

    def emit(kind: str, cls: str, message: str) -> None:
        if (kind, cls) not in seen:
            seen.add((kind, cls))
            out.append(Finding(kind=kind, kernel=spec.case,
                               grid_class=cls, message=message))

    for op in spec.operands:
        if not op.blocked:
            continue
        out.extend(_alignment_findings(spec, op))
        offs = offsets[op.pos]
        arr = op.array_shape
        if arr is None:
            continue
        # (1) in-bounds at every grid point
        for point, off in zip(points, offs):
            if off is None:
                continue
            if len(off) != len(op.shape):
                continue
            for o, bs, ad in zip(off, op.shape, arr):
                if o < 0 or o * bs + bs > ad:
                    emit(
                        "oob-block", point_class(spec.grid, point),
                        f"{op.name}: block index {tuple(off)} places a "
                        f"{tuple(op.shape)} block outside the "
                        f"{tuple(arr)} array at grid point {point}",
                    )
                    break
        if op.role != "out":
            continue
        # (2) output revisit structure: non-consecutive revisit = clobber
        first_at: Dict[Tuple[int, ...], int] = {}
        last_at: Dict[Tuple[int, ...], int] = {}
        for t, off in enumerate(offs):
            if off is None:
                continue
            if off in last_at and last_at[off] != t - 1:
                emit(
                    "overlapping-output",
                    point_class(spec.grid, points[t]),
                    f"{op.name}: output block {off} is revisited "
                    f"non-consecutively (grid steps {last_at[off]} and "
                    f"{t}) — the block emitted after the first visit run "
                    "is clobbered by the second",
                )
            if off not in first_at:
                first_at[off] = t
            last_at[off] = t
        # (3) coverage: every block of the output array must be visited
        if all(o is not None for o in offs) and len(arr) == len(op.shape):
            want = itertools.product(
                *(range(-(-int(ad) // int(bs)))
                  for ad, bs in zip(arr, op.shape))
            )
            missing = [w for w in want if w not in first_at]
            if missing:
                emit(
                    "untiled-output", "",
                    f"{op.name}: {len(missing)} block(s) of the "
                    f"{tuple(arr)} output (first: {missing[0]}) are never "
                    "written by any grid point — uninitialized HBM reaches "
                    "the caller",
                )
    return out


def output_runs(spec: KernelSpec) -> List[int]:
    """For each grid point (execution order), the id of the output visit
    run it belongs to: a run is a maximal stretch of consecutive points
    whose EVERY output block index is unchanged.  Runs longer than one
    point are the accumulation pattern the ``uninit-accumulator`` check
    audits (interp.py)."""
    points = grid_points(spec.grid)
    offsets = block_offsets(spec)
    outs = [op.pos for op in spec.outputs if op.pos in offsets]
    runs: List[int] = []
    run = 0
    prev = None
    for t in range(len(points)):
        sig = tuple(offsets[p][t] for p in outs)
        if prev is not None and (sig != prev or None in sig):
            run += 1
        runs.append(run)
        prev = sig
    return runs
