"""Kernel tracing and spec extraction.

Traces each registered :class:`~mpi4dl_tpu.ops.kernel_registry.KernelCase`
with ``jax.make_jaxpr`` (CPU, no TPU compile), finds every ``pallas_call``
equation in the closed jaxpr (recursing through pjit/custom-vjp/control-flow
sub-jaxprs), and lifts the parts the checks consume into a stable
:class:`KernelSpec`:

- the grid and every operand's role/block shape/memory space/index-map
  jaxpr (from ``grid_mapping``; kernel-invar order is index operands,
  inputs, outputs, scratch);
- the kernel jaxpr itself, for the DMA/accumulator abstract interpreter.

Written against jax 0.4.37's pallas internals (``GridMapping``/
``BlockMapping``); everything reached here is exercised by
tests/test_pallascheck.py so a jax upgrade that moves a field fails loudly
in the fixture lane, not silently in the gate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

#: normalized memory-space tags
ANY, VMEM, SMEM, SEMAPHORE = "any", "vmem", "smem", "semaphore"


def _memory_space(aval) -> str:
    ms = getattr(aval, "memory_space", None)
    if ms is None:
        return VMEM  # pallas default for blocked operands
    name = getattr(ms, "value", None) or str(ms)
    name = str(name).lower()
    if "semaphore" in name:
        return SEMAPHORE
    if "smem" in name:
        return SMEM
    if "any" in name:
        return ANY
    return VMEM


def _inner_aval(aval):
    return getattr(aval, "inner_aval", aval)


@dataclasses.dataclass(frozen=True)
class Operand:
    """One kernel operand, at its kernel-invar position ``pos``."""

    pos: int
    role: str                 # "index" | "in" | "out" | "scratch"
    name: str                 # stable label, e.g. "in0" / "out1" / "scratch2"
    shape: Tuple[int, ...]    # block shape (scratch: allocation shape)
    dtype: Any
    memory_space: str         # "any" | "vmem" | "smem" | "semaphore"
    array_shape: Optional[Tuple[int, ...]] = None  # whole-array shape
    index_map: Any = None     # ClosedJaxpr (None for scratch/index/ANY)

    @property
    def blocked(self) -> bool:
        """True when the Pallas pipeline stages this operand block by block
        (a VMEM/SMEM block smaller than — or equal to — the array, driven
        by an index map).  ANY-space operands stay in HBM unbocked."""
        return (
            self.role in ("in", "out")
            and self.memory_space in (VMEM, SMEM)
            and self.index_map is not None
        )

    def block_bytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= int(d)
        return n * np.dtype(self.dtype).itemsize


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """Everything the checks need about one traced ``pallas_call``."""

    case: str                 # registry case name (the finding key's kernel)
    grid: Tuple[int, ...]
    operands: Tuple[Operand, ...]   # kernel-invar order
    jaxpr: Any                # the kernel body jaxpr

    @property
    def outputs(self) -> Tuple[Operand, ...]:
        return tuple(o for o in self.operands if o.role == "out")

    @property
    def scratch(self) -> Tuple[Operand, ...]:
        return tuple(o for o in self.operands if o.role == "scratch")

    def by_pos(self, pos: int) -> Operand:
        return self.operands[pos]


def _sub_jaxprs(params) -> List:
    out = []
    for v in params.values():
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            out.append(getattr(v, "jaxpr", v))
        elif isinstance(v, (list, tuple)):
            for item in v:
                if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                    out.append(getattr(item, "jaxpr", item))
    return out


def find_pallas_eqns(jaxpr) -> List:
    """Every ``pallas_call`` equation reachable from a (closed) jaxpr."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            out.append(eqn)
        for sub in _sub_jaxprs(eqn.params):
            out.extend(find_pallas_eqns(sub))
    return out


def spec_of_eqn(eqn, case_name: str) -> KernelSpec:
    """Lift one ``pallas_call`` equation into a :class:`KernelSpec`."""
    gm = eqn.params["grid_mapping"]
    kernel_jaxpr = eqn.params["jaxpr"]
    invars = kernel_jaxpr.invars
    n_idx = int(gm.num_index_operands)
    n_in = int(gm.num_inputs)
    n_out = int(gm.num_outputs)
    n_scr = int(gm.num_scratch_operands)
    if len(invars) != n_idx + n_in + n_out + n_scr:
        raise ValueError(
            f"{case_name}: kernel invar count {len(invars)} does not match "
            f"grid_mapping operand counts ({n_idx}+{n_in}+{n_out}+{n_scr})"
        )
    block_mappings = list(gm.block_mappings)  # inputs then outputs
    operands: List[Operand] = []
    for pos, var in enumerate(invars):
        aval = _inner_aval(var.aval)
        ms = _memory_space(var.aval)
        shape = tuple(int(d) for d in getattr(aval, "shape", ()))
        dtype = getattr(aval, "dtype", np.int32)
        if pos < n_idx:
            role, label = "index", f"index{pos}"
            arr_shape, imap = None, None
        elif pos < n_idx + n_in + n_out:
            io = pos - n_idx
            role = "in" if io < n_in else "out"
            label = f"in{io}" if io < n_in else f"out{io - n_in}"
            bm = block_mappings[io]
            sd = getattr(bm, "array_shape_dtype", None)
            arr_shape = tuple(int(d) for d in sd.shape) if sd is not None else None
            imap = None if ms == ANY else bm.index_map_jaxpr
            bs = tuple(
                1 if d is None else int(d)
                for d in (bm.block_shape or shape)
            )
            shape = bs or shape
        else:
            role = "scratch"
            label = f"scratch{pos - n_idx - n_in - n_out}"
            arr_shape, imap = None, None
        operands.append(Operand(
            pos=pos, role=role, name=label, shape=shape, dtype=dtype,
            memory_space=ms, array_shape=arr_shape, index_map=imap,
        ))
    return KernelSpec(
        case=case_name,
        grid=tuple(int(g) for g in gm.grid),
        operands=tuple(operands),
        jaxpr=kernel_jaxpr,
    )


def trace_case(case) -> List[KernelSpec]:
    """Trace one registry case and extract every ``pallas_call`` spec.
    Multiple calls in one trace get ``#<i>`` name suffixes."""
    import jax

    fn, args = case.build()
    closed = jax.make_jaxpr(fn)(*args)
    eqns = find_pallas_eqns(closed)
    if not eqns:
        raise ValueError(
            f"registry case {case.name!r} traced to a jaxpr with no "
            "pallas_call — the registered entry no longer dispatches the "
            "kernel (stale registry row?)"
        )
    specs = []
    for i, eqn in enumerate(eqns):
        suffix = f"#{i}" if len(eqns) > 1 else ""
        specs.append(spec_of_eqn(eqn, case.name + suffix))
    return specs


def eval_index_map(imap, point: Sequence[int]) -> Optional[Tuple[int, ...]]:
    """Evaluate one block index map at a concrete grid point.  Scalar int
    invars are fed the grid indices in order; ref invars (scalar-prefetch
    operands the map could read but our kernels do not) are fed zeros.
    Returns None when the map is not statically evaluable (e.g. it actually
    reads a prefetch ref in a data-dependent way)."""
    from jax.core import eval_jaxpr

    coords = list(point)
    args = []
    for var in imap.jaxpr.invars:
        aval = _inner_aval(var.aval)
        shape = tuple(getattr(aval, "shape", ()))
        if shape == () and np.issubdtype(
            np.dtype(getattr(aval, "dtype", np.int32)), np.integer
        ) and coords:
            args.append(np.int32(coords.pop(0)))
        else:
            args.append(np.zeros(shape, getattr(aval, "dtype", np.int32)))
    try:
        out = eval_jaxpr(imap.jaxpr, imap.consts, *args)
        return tuple(int(v) for v in out)
    except Exception:  # noqa: BLE001 — non-evaluable map = no offsets
        return None
